"""Resilience subsystem: async + preemption-aware checkpointing, fault
injection, supervised restarts, and goodput accounting.

The reference has no fault-tolerance story at all (SURVEY.md §5: rank-0
``{net, acc, epoch}`` saves gated on best accuracy; recovery is a manual
re-launch) — on preemptible TPU pods every interruption costs whole
epochs.  This package closes that gap in five orthogonal pieces, each
layered on machinery the repo already has:

  * ``manager``     — :class:`AsyncCheckpointManager`: step/wall-clock
    cadence saves layered on ``train/checkpoint.py``, keep-last-K
    retention, atomic commit markers, off-critical-path writes;
  * ``preemption``  — :class:`PreemptionHandler`: SIGTERM/SIGINT →
    cross-host-agreed emergency save (the agreement bit makes the
    collective save deadlock-proof);
  * ``supervisor``  — :class:`Supervisor`: bounded-retry exponential-
    backoff restarts from the newest *valid* checkpoint, refusing to
    loop on deterministic crashes;
  * ``faults``      — :class:`FaultPlan`: deterministic env-driven fault
    injection (die/SIGTERM at step N, data-iterator raise, checkpoint
    corruption) that the CPU test suite drives;
  * ``goodput``     — :class:`GoodputTracker`: productive time vs.
    checkpoint/restore/restart badput (and restart MTTR), surfaced per
    epoch through ``train/metrics.py`` and benched by the ``ckpt_*`` /
    ``restart_mttr_s`` bench.py arms;
  * ``coordinator`` — :class:`PodCoordinator` (r10): pod-coordinated
    restarts (shared-fs generation rendezvous so every host restarts
    into the same generation) + the cluster health watchdog (per-host
    heartbeats, peer-staleness detection, local step-hang escalation);
  * ``sentinel``    — :class:`Sentinel`: the SILENT-failure ladder —
    in-graph non-finite bad-step guard (train/steps.py), host-side
    loss-spike detection with durable batch quarantine +
    rollback-and-skip replay, and the data-integrity (CRC) verdict
    sink (``--sentinel guard|full``).

``Resilience`` bundles the pieces for the Trainer; ``build_resilience``
constructs the bundle from a TrainConfig (cli.run_training's path).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Callable, Optional


class Preempted(Exception):
    """Raised by the train loop after a cross-host-agreed preemption and
    a successful emergency save.  Carries the post-save train state so
    the caller can exit cleanly — this is a clean shutdown, NOT a
    failure: the supervisor re-raises it instead of retrying (the
    platform, not this process, owns the restart after a preemption)."""

    def __init__(self, message: str, state=None, step: Optional[int] = None):
        super().__init__(message)
        self.state = state
        self.step = step


# storage FIRST: it is dependency-free and both coordinator/manager and
# train/checkpoint.py import it — binding it on the package object
# before the manager->checkpoint->storage import cycle re-enters this
# partially-initialized module is what keeps that cycle resolvable
from faster_distributed_training_tpu.resilience import storage  # noqa: E402,F401,E501
from faster_distributed_training_tpu.resilience.storage import (  # noqa: E402,F401,E501
    FakeObjectStoreBackend, PosixBackend, StorageBackend, build_backend)
from faster_distributed_training_tpu.resilience.goodput import (  # noqa: E402,F401,E501
    GoodputTracker)
from faster_distributed_training_tpu.resilience.sentinel import (  # noqa: E402,F401,E501
    LossSpike, QuarantineLedger, Sentinel, SpikeDetector, host_finite)
from faster_distributed_training_tpu.resilience.coordinator import (  # noqa: E402,F401,E501
    PeerFailure, PodCoordinator, SeatTaken, StepTimeout, pod_identity,
    slice_identity, spare_identity)
from faster_distributed_training_tpu.resilience.executable_cache import (  # noqa: E402,F401,E501
    ExecutableCache, build_executable_cache)
from faster_distributed_training_tpu.resilience.manager import (  # noqa: E402,F401,E501
    AsyncCheckpointManager, RestoreDivergence)
from faster_distributed_training_tpu.resilience.preemption import (  # noqa: E402,F401,E501
    PreemptionHandler)
from faster_distributed_training_tpu.resilience.supervisor import (  # noqa: E402,F401,E501
    Supervisor)
from faster_distributed_training_tpu.resilience.faults import (  # noqa: E402,F401,E501
    FaultPlan, InjectedFault, corrupt_newest_checkpoint)


@dataclasses.dataclass
class Resilience:
    """The bundle the Trainer consumes (train/loop.py).  Any piece may be
    None; ``goodput`` always exists so accounting never needs guards.
    ``pod_index``/``pod_count``/``pod_simulated`` carry the pod identity
    the bundle was built for (the env seam or the real runtime) so the
    loop can gate per-pod-process behavior (e.g. only simulated-pod
    host 0 writes the shared epoch checkpoint — each simulated process
    computes the identical full state, and concurrent orbax writers on
    one path would race; a REAL pod's orbax save is collective and must
    be entered by every host)."""

    manager: Optional[AsyncCheckpointManager] = None
    preemption: Optional[PreemptionHandler] = None
    faults: Optional[FaultPlan] = None
    goodput: GoodputTracker = dataclasses.field(default_factory=GoodputTracker)
    coordinator: Optional[PodCoordinator] = None
    pod_index: int = 0
    pod_count: int = 1
    pod_simulated: bool = False
    slice_index: int = 0
    slice_count: int = 1
    backend: Optional[StorageBackend] = None
    spare_index: Optional[int] = None
    sentinel: Optional[Sentinel] = None

    def adopt_seat(self, seat: int) -> None:
        """r17 warm spares: after the coordinator claimed a failed pod
        seat (``PodCoordinator._adopt_seat``), re-key the rest of the
        bundle — the manager's shard ownership / commit-barrier role
        and the pod identity the train loop gates per-host behavior on
        (e.g. host-0-only epoch saves on fs-simulated pods)."""
        self.pod_index = int(seat)
        self.slice_index = (self.coordinator.si
                            if self.coordinator is not None else 0)
        if self.manager is not None:
            self.manager.adopt_identity(
                seat, shard_owner=(_sim_shard_owner(seat)
                                   if self.pod_simulated else None))

    def close(self) -> None:
        if self.manager is not None:
            self.manager.close()
        if self.preemption is not None:
            self.preemption.uninstall()
        if self.coordinator is not None:
            self.coordinator.close()


def _sim_shard_owner(pi: int):
    """The fs-SIMULATED pod's shard-ownership policy (one place, used
    at build time and again when a warm spare adopts a seat): host 0
    writes the full replica-0 cover, every other host writes an empty
    shard set whose DONE marker the commit barrier still requires."""
    if pi == 0:
        return lambda sh: sh.replica_id == 0
    return lambda sh: False


def build_resilience(cfg, log: Callable[[str], None] = print
                     ) -> Optional[Resilience]:
    """Resilience bundle for a TrainConfig, or None when every knob is
    off (the default — the Trainer's hot loop then has zero new work).

    Enabled by any of: --checkpoint_every / --checkpoint_every_secs
    (step-cadence manager + preemption handler), --supervise, an
    armed FDT_FAULT_* plan (fault injection needs the hooks even when
    checkpointing is off), or --sentinel guard|full (the anomaly
    sentinel's counters/ledger live on the bundle).

    Pod coordination (r10): with --supervise on a pod (real multi-host,
    or the FDT_POD_INDEX/FDT_POD_COUNT simulation seam) — or whenever
    --step_timeout_s arms the local hang watchdog — the bundle grows a
    :class:`PodCoordinator` under ``<checkpoint_dir>/_pod`` and the
    supervisor/loop drive the coordinated-restart protocol through it.
    In the fs-SIMULATED pod the manager also takes the simulated
    identity (host 0 owns the replica-0 shards, peers own none — every
    simulated process computes the identical full state) and the
    coordinator's marker-file allgather replaces the jax collective in
    the restore step-agreement.

    Storage + slices (r14): ``--storage_backend`` selects the durable
    medium every marker/sharded-checkpoint write rides
    (``resilience/storage.py`` — posix / fake_object_store / gs://);
    ``FDT_SLICE_INDEX``/``FDT_SLICE_COUNT`` partition the pod into
    slices and ``--readmit_timeout_s`` arms slice-granular elastic
    re-admission on the coordinator (surviving slices hold while a
    failed slice restarts and rejoins; whole-pod restart remains the
    fallback)."""
    pi, pc, simulated = pod_identity()
    spare = spare_identity()
    if spare is not None:
        # a warm spare is NOT one of the pod's pc members: park it under
        # a synthetic out-of-pod index (markers, shard files, telemetry
        # can never collide with a member's) until it claims a seat and
        # Resilience.adopt_seat re-keys the bundle
        pi = pc + spare
    si, sc, _slice_sim = slice_identity(process_index=pi, process_count=pc)
    faults = FaultPlan.from_env(process_index=pi)
    cadence = bool(cfg.checkpoint_every or cfg.checkpoint_every_secs)
    step_timeout = float(getattr(cfg, "step_timeout_s", 0.0) or 0.0)
    sentinel_mode = str(getattr(cfg, "sentinel", "none") or "none")
    if spare is not None and not cfg.supervise:
        log("[resilience] WARNING: FDT_SLICE_SPARE is set but --supervise "
            "is not — the warm-spare park lives on the pod coordinator, "
            "which only the supervised path builds; this process will "
            "train as an ordinary (out-of-pod!) run instead of parking")
    if step_timeout > 0 and not cfg.supervise:
        # BEFORE the enablement gate: --step_timeout_s as the ONLY
        # resilience flag must still warn, not silently no-op
        log("[resilience] WARNING: --step_timeout_s has no effect without "
            "--supervise — the hang watchdog lives on the pod coordinator, "
            "which only the supervised path builds; a wedged dispatch "
            "will block forever")
    if not (cadence or cfg.supervise or faults is not None
            or sentinel_mode != "none"):
        return None
    # the storage backend every resilience-critical durable write rides
    # (r14): markers, sharded checkpoint phases, retention.  posix =
    # today's shared-fs semantics, byte-compatible; fake_object_store /
    # gs:// = no-rename object semantics (multi-slice pods without a
    # shared filesystem)
    backend = storage.build_backend(
        getattr(cfg, "storage_backend", "posix"), cfg.checkpoint_dir,
        log=log)
    goodput = GoodputTracker()
    peer_timeout = float(getattr(cfg, "peer_timeout_s", 60.0))
    readmit_timeout = float(getattr(cfg, "readmit_timeout_s", 60.0))
    coordinator = None
    if cfg.supervise and (pc > 1 or step_timeout > 0 or spare is not None):
        coordinator = PodCoordinator(
            os.path.join(cfg.checkpoint_dir, "_pod"),
            process_index=pi, process_count=pc,
            sync_every=cfg.preempt_sync_every,
            peer_timeout_s=peer_timeout,
            step_timeout_s=step_timeout,
            slice_index=si, slice_count=sc,
            readmit_timeout_s=readmit_timeout,
            backend=backend, spare_index=spare,
            goodput=goodput, log=log)
    # commit-barrier timeout tied to the peer-detection timescale when
    # both are armed (r14 follow-on, now the default everywhere a
    # coordinator exists — not just simulated pods): the manager's old
    # 600 s default outlives both peer detection AND the re-admission
    # hold window, so a commit barrier stuck on a dead host burned the
    # whole hold into a pod_fallback_restart before anything timed out.
    # O(peer_timeout) keeps the ordering detection < barrier give-up.
    commit_timeout = float(getattr(cfg, "commit_timeout_s", 0.0) or 0.0)
    if commit_timeout <= 0:
        commit_timeout = (max(2.0 * peer_timeout, 10.0)
                          if coordinator is not None and pc > 1 else 600.0)
    elif coordinator is not None and pc > 1:
        if commit_timeout < peer_timeout:
            log(f"[resilience] WARNING: --commit_timeout_s "
                f"{commit_timeout:.0f} is below --peer_timeout_s "
                f"{peer_timeout:.0f} — the commit barrier gives up on a "
                f"slow-but-live peer before the watchdog could even call "
                f"it dead (inverted ordering: expect spurious counted "
                f"save_failures)")
        if readmit_timeout > 0 and sc > 1 \
                and commit_timeout > readmit_timeout:
            log(f"[resilience] WARNING: --commit_timeout_s "
                f"{commit_timeout:.0f} exceeds --readmit_timeout_s "
                f"{readmit_timeout:.0f} — a survivor draining a stuck "
                f"commit barrier can outlive the re-admission hold "
                f"window and degrade every slice recovery into a "
                f"pod_fallback_restart")
    manager = None
    if cadence:
        sim_kw = {"commit_timeout_s": commit_timeout}
        if simulated and pc > 1:
            # simulated pod: complementary shard owners (the r9 test
            # seam — host 0 writes the full replica-0 cover, peers write
            # empty shard sets whose DONE markers the commit barrier
            # still requires) + the fs-based restore step agreement
            sim_kw.update(
                process_index=pi, process_count=pc,
                shard_owner=_sim_shard_owner(pi))
        if coordinator is not None and (simulated or sc > 1) and pc > 1:
            # marker-transport restore agreement: fs-simulated pods (jax
            # single-process per host), and REAL multi-slice pods — a
            # jax collective across a pod with a dead/rejoining slice
            # is exactly the thing that cannot be relied on (the
            # slice-scoped barrier only exists on the marker transport)
            sim_kw["step_gather_fn"] = coordinator.gather_restored_step
        manager = AsyncCheckpointManager(
            cfg.checkpoint_dir,
            # mirror the epoch-checkpoint naming (loop.py ckpt_name) so
            # two workloads sharing a checkpoint_dir never restore each
            # other's step checkpoints
            prefix=("transformer" if cfg.model == "transformer"
                    else "resnet"),
            every_steps=cfg.checkpoint_every,
            every_secs=cfg.checkpoint_every_secs,
            keep=cfg.checkpoint_keep,
            async_save=cfg.checkpoint_async,
            backend=backend,
            goodput=goodput, log=log, **sim_kw)
    if coordinator is not None and manager is not None:
        # survivors drain their in-flight background save before
        # publishing a re-admission HOLD (freezes the commit frontier
        # the rejoining slice walks — coordinator._await_readmission)
        coordinator.drain_fn = manager.wait
    preemption = PreemptionHandler(sync_every=cfg.preempt_sync_every,
                                   log=log).install()
    sentinel = None
    if sentinel_mode != "none":
        if sentinel_mode == "full" and not (cfg.supervise and cadence):
            # BEFORE the Sentinel builds, same precedent as the
            # step_timeout warning above: the spike path still
            # quarantines durably, but with no supervisor + checkpoint
            # cadence there is nothing to roll back through in-process
            log("[resilience] WARNING: --sentinel full without --supervise "
                "+ --checkpoint_every: a detected loss spike quarantines "
                "its batches durably but the run then ABORTS instead of "
                "rolling back in-process (the next start replays with the "
                "quarantine applied); add --supervise and a checkpoint "
                "cadence for automatic rollback-and-skip")
        sentinel = Sentinel(sentinel_mode, backend=backend, goodput=goodput,
                            window=int(getattr(cfg, "spike_window", 32)),
                            threshold=float(
                                getattr(cfg, "spike_threshold", 8.0)),
                            log=log, root=cfg.checkpoint_dir)
    return Resilience(manager=manager, preemption=preemption,
                      faults=faults, goodput=goodput,
                      coordinator=coordinator, pod_index=pi, pod_count=pc,
                      pod_simulated=simulated, slice_index=si,
                      slice_count=sc, backend=backend, spare_index=spare,
                      sentinel=sentinel)
