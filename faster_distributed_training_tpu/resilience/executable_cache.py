"""Persistent EXECUTABLE cache: restart-time compiles become reads.

r14 made recovery slice-granular and r15 made every compile measurable;
this module closes the loop ROADMAP calls "instant restart": on real
hardware a restarted (or rejoining, or warm-spare) process spends its
MTTR almost entirely re-building XLA executables it has compiled many
times before.  The r15 compile observatory already owns the exact seam
— an explicit ``lower()``/``compile()`` per program — so this tier
slots in as a lookup-before-compile / store-after-compile hook
(:class:`~faster_distributed_training_tpu.telemetry.programs
.ProgramObservatory`): a fresh process deserializes its (train, eval,
epoch-reshard, serve-predict) programs instead of recompiling them and
records ``cache_source="deserialized"`` per program in the manifest
``compile`` table, where the A/B against ``cache_source="compiled"``
rounds is a committed number (bench ``restart_cached_mttr_s`` vs
``restart_mttr_s``).

Mechanics
---------

* Entries are whole objects through the r14
  :class:`~faster_distributed_training_tpu.resilience.storage
  .StorageBackend` (atomic put, ranged read) under
  ``<checkpoint_dir>/_exec_cache/`` by default — the same durable
  medium the pod's markers and sharded checkpoints ride, so a slice
  restarting on a DIFFERENT machine (the case that matters) still finds
  them.  The payload is ``jax.experimental.serialize_executable``'s
  serialized executable framed with a magic + length header; a torn or
  truncated object fails the frame check (or the deserializer) and the
  caller falls back to a plain compile — **a corrupt cache entry must
  never block recovery** (counted in :attr:`stats`, warned once).
* The *pytree* halves of ``serialize()``'s triple (``in_tree`` /
  ``out_tree``) are deliberately NOT stored: the train state's treedef
  embeds the optax transformation (unpicklable closures), and the
  observatory has a live ``Lowered`` in hand at lookup time anyway —
  ``lowered.in_tree``/``lowered.out_tree`` are bit-identical across
  processes for the same program, so the cache stores only the
  executable bytes and re-derives the trees locally.  (Lowering still
  runs on a cache hit; tracing is the cheap half — the measured CPU
  split for the tier-1 train step is ~0.2 s deserialize vs ~2.5 s
  compile.)
* Keys: sha256 over the r15 HLO fingerprint (sha of
  ``lowered.as_text()`` — shapes, shardings, donation policy context)
  PLUS the environment the executable is only valid in: jax + jaxlib
  versions, backend, device kind and count, mesh axes/shape, the
  donation flag, and the host ISA fingerprint (the MULTICHIP_r03
  lesson: a CPU AOT executable compiled with wider vector extensions
  SIGILLs elsewhere — ``cli._host_isa_fingerprint`` keys the persistent
  HLO cache for the same reason).  Any component moving (a jaxlib
  upgrade, a different slice topology) changes the key and the old
  entries are simply never read again.
* Where ``serialize_executable`` is unavailable or refuses a program
  (an exotic backend, a multi-controller executable an old runtime
  can't round-trip), the tier degrades to XLA's own persistent
  compilation cache directory: :func:`arm_persistent_cache` zeroes
  ``jax_persistent_cache_min_compile_time_secs`` so even sub-second
  programs (the CPU tier-1 suite, serve predict) populate and hit it —
  the r15 ``below_threshold`` verdict trap — and the observatory
  records ``cache_source="persistent_dir"`` when that tier served the
  compile.

Enablement: ``--executable_cache on`` (or an explicit directory/key
prefix), env ``FDT_EXEC_CACHE`` (``0`` kills it, ``on``/path arms it —
the bench/smoke seam).  The cache rides the observatory, so
``FDT_PROGRAM_OBS=0`` disables it too.
"""

from __future__ import annotations

import hashlib
import os
from typing import Callable, Dict, Optional

from faster_distributed_training_tpu.resilience import storage as storage_mod

ENV_CACHE = "FDT_EXEC_CACHE"

# retention GC bounds (r19 satellite; r17 caveat "no retention GC
# yet"): the _exec_cache/ prefix is bounded by entry count AND total
# payload bytes with LRU eviction by last_used — a long-lived
# checkpoint_dir no longer accretes one executable per (HLO x
# environment) key forever.  Env overrides for bench/tests.
ENV_MAX_ENTRIES = "FDT_EXEC_CACHE_MAX_ENTRIES"
ENV_MAX_BYTES = "FDT_EXEC_CACHE_MAX_BYTES"
DEFAULT_MAX_ENTRIES = 64
DEFAULT_MAX_BYTES = 512 * 1024 * 1024

# sidecar suffix recording an entry's last USE (hits don't rewrite the
# payload — a zero-byte touch file's mtime is the LRU clock instead)
_USED_SUFFIX = ".last_used"

# frame: magic + 8-byte big-endian payload length + payload.  Anything
# that fails the frame check is treated as corrupt and recompiled.
_MAGIC = b"FDTXEC01"


def environment_key(mesh=None, donate: Optional[bool] = None,
                    extra: str = "") -> str:
    """Fingerprint of everything OUTSIDE the HLO that an executable is
    only valid under: jax/jaxlib versions, backend + device kind/count,
    mesh axes/shape, donation flag, host ISA.  A restarted slice on an
    upgraded runtime gets a clean miss, never a poisoned load."""
    import jax

    bits = [f"jax={jax.__version__}"]
    try:
        import jaxlib
        bits.append(f"jaxlib={getattr(jaxlib, '__version__', '?')}")
    except ImportError:
        bits.append("jaxlib=")
    try:
        dev = jax.local_devices()[0]
        bits.append(f"backend={jax.default_backend()}")
        bits.append(f"device={getattr(dev, 'device_kind', str(dev))}")
        bits.append(f"devices={jax.device_count()}")
    except Exception:
        bits.append("backend=?")
    if mesh is not None:
        try:
            bits.append("mesh=" + ",".join(
                f"{k}={v}" for k, v in dict(mesh.shape).items()))
        except Exception:
            bits.append(f"mesh={mesh!r}")
    if donate is not None:
        bits.append(f"donate={bool(donate)}")
    if extra:
        bits.append(str(extra))
    try:
        from faster_distributed_training_tpu.cli import _host_isa_fingerprint
        bits.append(f"isa={_host_isa_fingerprint()}")
    except Exception:
        pass
    return hashlib.sha256("|".join(bits).encode()).hexdigest()[:16]


def serialize_available() -> bool:
    """Whether this jax ships the executable serialization API at all
    (the per-program round-trip can still fail; callers degrade)."""
    try:
        from jax.experimental import serialize_executable  # noqa: F401
        return True
    except ImportError:
        return False


def arm_persistent_cache() -> None:
    """Satellite fix for the r15 ``below_threshold`` verdict trap: with
    the executable cache armed, the persistent compilation cache is the
    DESIGNED fallback tier — but its default 1 s store floor
    (``jax_persistent_cache_min_compile_time_secs``, set by
    ``cli.enable_compilation_cache``) means every sub-second program
    (the whole CPU tier-1 suite, serve predict) neither populates nor
    hits it.  Zero the floor so the fallback tier actually serves the
    programs the executable tier exists for."""
    import jax

    try:
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception:
        pass  # an exotic jax without the knob keeps its default


class ExecutableCache:
    """Serialized-executable store keyed by (HLO fingerprint ×
    environment), read/written through a StorageBackend.

    All methods are best-effort by contract: :meth:`load` returns None
    on ANY failure (missing, torn, version-skewed, deserializer error)
    and :meth:`store` swallows its own; the observatory's compile path
    must be exactly as available with the cache as without it."""

    def __init__(self, directory: str,
                 backend: Optional[storage_mod.StorageBackend] = None,
                 mesh=None, donate: Optional[bool] = None,
                 log: Callable[[str], None] = print,
                 max_entries: Optional[int] = None,
                 max_bytes: Optional[int] = None):
        self.directory = os.path.abspath(directory)
        self.backend = backend if backend is not None \
            else storage_mod.posix_backend()
        self.env_key = environment_key(mesh=mesh, donate=donate)
        self._log = log
        self._warned: set = set()
        self.max_entries = int(
            os.environ.get(ENV_MAX_ENTRIES, "") or
            (DEFAULT_MAX_ENTRIES if max_entries is None else max_entries))
        self.max_bytes = int(
            os.environ.get(ENV_MAX_BYTES, "") or
            (DEFAULT_MAX_BYTES if max_bytes is None else max_bytes))
        self.stats: Dict[str, int] = {
            "hits": 0, "misses": 0, "stores": 0, "corrupt": 0,
            "store_failures": 0, "skipped_served": 0, "evicted": 0}
        self.backend.ensure_dir(self.directory)

    # -- keys --------------------------------------------------------------

    def key_for(self, name: str, fingerprint: str) -> str:
        """Object key for one program: the HLO fingerprint crossed with
        the environment key; the (sanitized) program name rides along
        for human-debuggable listings only."""
        digest = hashlib.sha256(
            f"{fingerprint}|{self.env_key}".encode()).hexdigest()[:24]
        safe = "".join(c if c.isalnum() else "-" for c in name)[:40]
        return os.path.join(self.directory, f"exec_{safe}_{digest}")

    # -- load / store ------------------------------------------------------

    def load(self, key: str, lowered):
        """Deserialize the executable at ``key`` for this ``lowered``
        program (whose in/out trees supply the pytree halves the store
        deliberately omits).  None on miss OR on any failure — recovery
        must degrade to a plain compile, never block on a bad entry."""
        try:
            raw = self.backend.read_bytes(key)
        except (OSError, ValueError):
            self.stats["misses"] += 1
            return None
        try:
            if len(raw) < 16 or raw[:8] != _MAGIC:
                raise ValueError("bad frame magic")
            n = int.from_bytes(raw[8:16], "big")
            if len(raw) != 16 + n:
                raise ValueError(f"truncated entry ({len(raw) - 16}/{n} "
                                 f"payload bytes)")
            from jax.experimental import serialize_executable as se
            compiled = se.deserialize_and_load(
                raw[16:], lowered.in_tree, lowered.out_tree)
        except Exception as e:
            self.stats["corrupt"] += 1
            self._warn_once(
                "corrupt", f"[exec_cache] entry {os.path.basename(key)} "
                f"failed to deserialize ({e!r}); recompiling (a corrupt "
                f"cache entry never blocks recovery)")
            return None
        self.stats["hits"] += 1
        self._touch(key)
        return compiled

    def store(self, key: str, compiled) -> bool:
        """Serialize + publish one executable (atomic whole-object put).
        Best-effort: a backend/serializer failure is counted + warned
        once, never raised into the compile path."""
        try:
            from jax.experimental import serialize_executable as se
            payload, _in_tree, _out_tree = se.serialize(compiled)
            self.backend.put_bytes(
                key, _MAGIC + len(payload).to_bytes(8, "big") + payload)
        except Exception as e:
            self.stats["store_failures"] += 1
            self._warn_once(
                "store", f"[exec_cache] could not store "
                f"{os.path.basename(key)} ({e!r}); this program recompiles "
                f"on the next restart")
            return False
        self.stats["stores"] += 1
        self.gc()
        return True

    # -- retention GC ------------------------------------------------------

    def _touch(self, key: str) -> None:
        """Best-effort LRU clock tick: a hit refreshes the entry's
        ``.last_used`` sidecar mtime instead of rewriting the payload."""
        try:
            self.backend.put_bytes(key + _USED_SUFFIX, b"")
        except Exception:
            pass

    def _last_used(self, key: str) -> float:
        """last_used for LRU ordering: the sidecar's mtime when present
        (a hit touched it), else the entry's own (its store time)."""
        try:
            if self.backend.exists(key + _USED_SUFFIX):
                return self.backend.mtime(key + _USED_SUFFIX)
        except Exception:
            pass
        try:
            return self.backend.mtime(key)
        except Exception:
            return 0.0

    def entries(self):
        """[(key, bytes, last_used)] for every cache entry under the
        directory (sidecars excluded)."""
        out = []
        try:
            keys = self.backend.list_prefix(
                self.backend.join(self.directory, "exec_"))
        except Exception:
            return out
        for k in keys:
            if k.endswith(_USED_SUFFIX):
                continue
            try:
                out.append((k, self.backend.size(k), self._last_used(k)))
            except Exception:
                continue
        return out

    def gc(self) -> int:
        """Retention GC (r19 satellite): keep the most-recently-used
        entries while count <= max_entries and total bytes <= max_bytes;
        evict the LRU tail (entry + sidecar).  Best-effort like every
        other method — a GC failure must never block the compile path.
        Returns the number of entries evicted."""
        ents = self.entries()
        if not ents:
            return 0
        ents.sort(key=lambda e: e[2], reverse=True)   # newest first
        evicted = 0
        kept = total = 0
        for key, nbytes, _ in ents:
            kept += 1
            total += nbytes
            # the MRU entry always survives, even past the byte bound:
            # evicting a single over-budget executable right after its
            # own store would permanently disable the cache for that
            # program (every restart recompiling while stats show
            # stores and evictions balancing)
            if kept == 1 or (kept <= self.max_entries
                             and total <= self.max_bytes):
                continue
            try:
                self.backend.delete(key)
                try:
                    self.backend.delete(key + _USED_SUFFIX)
                except Exception:
                    pass
                evicted += 1
            except Exception:
                continue
        if evicted:
            self.stats["evicted"] += evicted
            self._warn_once(
                "gc", f"[exec_cache] retention GC evicted {evicted} LRU "
                f"entr{'y' if evicted == 1 else 'ies'} (bounds: "
                f"{self.max_entries} entries / {self.max_bytes >> 20} "
                f"MiB; {ENV_MAX_ENTRIES}/{ENV_MAX_BYTES} override)")
        return evicted

    def note_skipped_served(self) -> None:
        """The observatory declined to store an executable because the
        compile was SERVED from XLA's persistent cache dir rather than
        compiled fresh (measured on this container's XLA:CPU: a
        cache-served executable serializes to a payload missing its
        compiled function symbols — ``Symbols not found`` at
        deserialize; only fresh compiles round-trip).  Not a failure:
        the persistent dir itself keeps serving such programs at
        restart (cache_source="persistent_dir"), and the executable
        tier populates the first time the program compiles against
        cold caches."""
        self.stats["skipped_served"] += 1

    def _warn_once(self, topic: str, msg: str) -> None:
        if topic not in self._warned:
            self._warned.add(topic)
            self._log(msg)


def build_executable_cache(cfg, backend=None, mesh=None,
                           log: Callable[[str], None] = print
                           ) -> Optional[ExecutableCache]:
    """ExecutableCache from a TrainConfig, or None when disabled.

    ``--executable_cache``: ``""``/``off`` = disabled (default), ``on``
    = ``<checkpoint_dir>/_exec_cache`` through the run's storage
    backend, anything else = an explicit directory.  ``FDT_EXEC_CACHE``
    overrides (``0`` = force off — the kill switch; ``on``/path = force
    on, the bench/smoke seam).  Arming the cache also zeroes the
    persistent-compilation-cache store floor (:func:`arm_persistent_
    cache`) so the fallback tier serves sub-second programs."""
    spec = (getattr(cfg, "executable_cache", "") or "").strip()
    env = os.environ.get(ENV_CACHE, "").strip()
    if env == "0":
        return None
    if env:
        spec = env
    if spec in ("", "off", "0"):
        return None
    if spec in ("on", "1"):
        directory = os.path.join(
            getattr(cfg, "checkpoint_dir", "."), "_exec_cache")
    else:
        directory = spec
    if not serialize_available():
        log("[exec_cache] jax.experimental.serialize_executable is "
            "unavailable in this environment — the executable tier is "
            "off; the persistent compilation cache (store floor zeroed) "
            "is the only restart-compile tier this run")
        arm_persistent_cache()
        return None
    arm_persistent_cache()
    cache = ExecutableCache(directory, backend=backend, mesh=mesh,
                            donate=bool(getattr(cfg, "donate", True)),
                            log=log)
    cache.gc()    # a long-lived prefix shrinks to bounds at arm time
    log(f"[exec_cache] persistent executable cache armed at {directory} "
        f"(env key {cache.env_key}; a restarted process deserializes "
        f"its programs instead of recompiling)")
    return cache
