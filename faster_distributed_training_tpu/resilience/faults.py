"""Deterministic fault injection — the resilience test harness.

Faults are armed through env vars (so the CPU test suite and the
preemption smoke script can inject into an unmodified training process)
and fire at exact host-side step/batch counters, never randomly:

  * ``FDT_FAULT_DIE_AT_STEP=N``      — raise :class:`InjectedFault` after
    global step N completes (a crash the supervisor should recover);
  * ``FDT_FAULT_SIGTERM_AT_STEP=N``  — deliver a real SIGTERM to this
    process after step N (exercises the preemption handler + emergency
    save end-to-end, signal delivery included);
  * ``FDT_FAULT_DATA_AT_BATCH=K``    — raise from inside the data
    iterator at batch index K of every epoch (exercises the prefetch
    pipeline's error propagation and the supervisor above it);
  * ``FDT_FAULT_HANG_AT_STEP=N``     — block forever at step N (a
    host-side stand-in for a wedged device program or a collective
    stuck on a dead peer): the r10 pod-scale arm that only the health
    watchdog can clear — nothing raises, nothing exits, the step clock
    just stops (resilience/coordinator.py escalates);
  * ``FDT_FAULT_HOST=P``             — scope EVERY armed fault above to
    the host with pod process index P (the other hosts of a simulated
    or real pod run fault-free); unset = every process.
  * ``FDT_FAULT_SLICE=S``            — scope EVERY armed fault above to
    the hosts of SLICE S (r14, mirrors FDT_FAULT_HOST at slice
    granularity: with FDT_SLICE_COUNT set, a die/hang/SIGTERM fault
    fires on every process of one slice of a simulated multi-slice pod
    — the arm the elastic re-admission tests kill a whole slice with);
    composes with FDT_FAULT_HOST (both must match when both are set).

Each fault fires ONCE per process: after a supervisor restart the
replayed step must succeed, otherwise every injected crash would look
deterministic (same step failing twice) and the supervisor would
correctly — but uselessly for testing — re-raise.

``corrupt_newest_checkpoint`` is the storage-fault arm: tests call it
directly to damage a committed checkpoint and assert the manager falls
back to the previous valid one."""

from __future__ import annotations

import os
import signal
import threading
from typing import Iterable, Iterator, Optional

ENV_DIE = "FDT_FAULT_DIE_AT_STEP"
ENV_SIGTERM = "FDT_FAULT_SIGTERM_AT_STEP"
ENV_DATA = "FDT_FAULT_DATA_AT_BATCH"
ENV_HANG = "FDT_FAULT_HANG_AT_STEP"
ENV_HOST = "FDT_FAULT_HOST"
ENV_SLICE = "FDT_FAULT_SLICE"


class InjectedFault(RuntimeError):
    """A deliberately injected failure — semantically a crash, so
    nothing catches it specially: it must flow through the exact
    recovery path a real fault would."""


def _env_int(env: dict, key: str) -> Optional[int]:
    raw = env.get(key)
    if raw in (None, ""):
        return None
    try:
        return int(raw)
    except ValueError:
        raise ValueError(f"malformed {key}={raw!r}: want an integer step")


class FaultPlan:
    def __init__(self, die_at: Optional[int] = None,
                 sigterm_at: Optional[int] = None,
                 data_at: Optional[int] = None,
                 hang_at: Optional[int] = None):
        self.die_at = die_at
        self.sigterm_at = sigterm_at
        self.data_at = data_at
        self.hang_at = hang_at
        self._die_fired = False
        self._sigterm_fired = False
        self._data_fired = False
        self._hang_fired = False
        # production never sets this — the hang "ends" when the watchdog
        # SIGKILLs the process; in-process tests set it from an injected
        # watchdog abort_fn so the pytest process survives the exercise
        self.hang_release = threading.Event()

    @classmethod
    def from_env(cls, env=os.environ,
                 process_index: Optional[int] = None
                 ) -> Optional["FaultPlan"]:
        """The armed plan, or None when no FDT_FAULT_* is set (the
        common case — callers skip every per-step hook).  With
        ``FDT_FAULT_HOST`` set, only the pod process with that index
        gets the plan; with ``FDT_FAULT_SLICE`` set, only the processes
        of that slice do (``process_index`` defaults to
        :func:`coordinator.pod_identity`, so the env seam and real
        multi-host runs both scope correctly)."""
        die = _env_int(env, ENV_DIE)
        sig = _env_int(env, ENV_SIGTERM)
        data = _env_int(env, ENV_DATA)
        hang = _env_int(env, ENV_HANG)
        if die is None and sig is None and data is None and hang is None:
            return None
        host = _env_int(env, ENV_HOST)
        slice_ = _env_int(env, ENV_SLICE)
        if host is not None or slice_ is not None:
            from faster_distributed_training_tpu.resilience.coordinator \
                import pod_identity, slice_identity
            if process_index is None:
                process_index = pod_identity(env)[0]
            if host is not None and int(process_index) != host:
                return None
            if slice_ is not None and slice_identity(
                    env, process_index=process_index)[0] != slice_:
                return None
        return cls(die_at=die, sigterm_at=sig, data_at=data, hang_at=hang)

    def on_step(self, step: int) -> None:
        """Called by the train loop after each completed global step."""
        if (self.sigterm_at is not None and step >= self.sigterm_at
                and not self._sigterm_fired):
            self._sigterm_fired = True
            # a REAL signal to this process: the preemption handler's
            # delivery path is part of what the harness exercises
            os.kill(os.getpid(), signal.SIGTERM)
        if (self.hang_at is not None and step >= self.hang_at
                and not self._hang_fired):
            self._hang_fired = True
            # block the main thread indefinitely — from the outside this
            # is indistinguishable from a wedged dispatch/collective,
            # which is the point: only the watchdog thread can act
            self.hang_release.wait()
        if (self.die_at is not None and step >= self.die_at
                and not self._die_fired):
            self._die_fired = True
            raise InjectedFault(f"injected crash at global step {step}")

    def wrap_data(self, iterable: Iterable) -> Iterator:
        """Data-iterator fault: yields batches until index `data_at`,
        then raises from INSIDE the iterator — through PrefetchIterator /
        ParallelBatchIterator this lands in the consumer thread exactly
        like a real loader failure."""
        if self.data_at is None:
            yield from iterable
            return
        for i, item in enumerate(iterable):
            if i >= self.data_at and not self._data_fired:
                self._data_fired = True
                raise InjectedFault(
                    f"injected data-iterator failure at batch {i}")
            yield item


def corrupt_newest_checkpoint(directory: str, prefix: str = "ckpt",
                              mode: str = "truncate") -> Optional[str]:
    """Damage the newest COMMITTED `<prefix>_step_*` checkpoint under
    `directory`; returns its path (None when there is none).

    mode="truncate": halve the largest data file — the commit marker
    stays intact, so validity checks pass but the restore fails
    (bit-rot / torn-block simulation; the manager must fall back).
    mode="unmark": delete BOTH completion markers (ours and orbax's) —
    the half-written-directory shape has_checkpoint() must reject (a
    directory from a non-atomic writer killed mid-save has neither)."""
    from faster_distributed_training_tpu.resilience.manager import (
        AsyncCheckpointManager)
    from faster_distributed_training_tpu.train import checkpoint as ckpt

    mgr = AsyncCheckpointManager(directory, prefix=prefix,
                                 log=lambda *_: None)
    newest = mgr.latest_valid()
    if newest is None:
        return None
    path = os.path.join(directory, newest[1])
    if mode == "unmark":
        for marker in (ckpt._COMMIT, ckpt._OCP_METADATA):
            p = os.path.join(path, marker)
            if os.path.exists(p):
                os.remove(p)
        return path
    if mode != "truncate":
        raise ValueError(f"unknown corruption mode {mode!r}")
    largest, size = None, -1
    for root, _dirs, files in os.walk(path):
        for f in files:
            p = os.path.join(root, f)
            s = os.path.getsize(p)
            if s > size:
                largest, size = p, s
    if largest is None:
        raise RuntimeError(f"no data files under {path}")
    with open(largest, "r+b") as f:
        f.truncate(max(size // 2, 1))
    return path
