"""Deterministic fault injection — the resilience test harness.

Faults are armed through env vars (so the CPU test suite and the
preemption smoke script can inject into an unmodified training process)
and fire at exact host-side step/batch counters, never randomly:

  * ``FDT_FAULT_DIE_AT_STEP=N``      — raise :class:`InjectedFault` after
    global step N completes (a crash the supervisor should recover);
  * ``FDT_FAULT_SIGTERM_AT_STEP=N``  — deliver a real SIGTERM to this
    process after step N (exercises the preemption handler + emergency
    save end-to-end, signal delivery included);
  * ``FDT_FAULT_DATA_AT_BATCH=K``    — raise from inside the data
    iterator at batch index K of every epoch (exercises the prefetch
    pipeline's error propagation and the supervisor above it);
  * ``FDT_FAULT_HANG_AT_STEP=N``     — block forever at step N (a
    host-side stand-in for a wedged device program or a collective
    stuck on a dead peer): the r10 pod-scale arm that only the health
    watchdog can clear — nothing raises, nothing exits, the step clock
    just stops (resilience/coordinator.py escalates);
  * ``FDT_FAULT_NAN_AT_STEP=N``      — poison the loss (and through it
    every gradient) with NaN at global step N, IN-GRAPH: the multiplier
    is baked into the jitted program at trace time
    (:func:`graph_nan_at` -> train/steps.py), so the fault exercises
    the sentinel's fused non-finite guard exactly where a real
    overflow/bad-batch NaN appears.  Deliberately NOT once-per-process:
    the program is pure, so a replay re-poisons step N identically —
    the guard's skip (which advances ``state.step`` past N) is what
    moves training forward, which is precisely the contract under test;
  * ``FDT_FAULT_LOSS_SPIKE_AT_STEP=N`` — multiply the HOST-OBSERVED
    dispatch loss by 1e4 once at step >= N (the device stream is
    untouched): exercises the sentinel's median/MAD spike detector,
    quarantine ledger, and rollback-and-skip replay
    (resilience/sentinel.py).  Fires once per process like die/hang;
  * ``FDT_FAULT_CORRUPT_SHARD=S``    — flip bytes inside stream shard S
    of the train split at startup (size unchanged, so only the CRC32C
    catches it — the byte-size cross-check at open passes): exercises
    the data-integrity quarantine (data/stream/reader.py).  Idempotent
    fixed-pattern overwrite, so restarts re-arm harmlessly;
  * ``FDT_FAULT_HOST=P``             — scope EVERY armed fault above to
    the host with pod process index P (the other hosts of a simulated
    or real pod run fault-free); unset = every process.
  * ``FDT_FAULT_SLICE=S``            — scope EVERY armed fault above to
    the hosts of SLICE S (r14, mirrors FDT_FAULT_HOST at slice
    granularity: with FDT_SLICE_COUNT set, a die/hang/SIGTERM fault
    fires on every process of one slice of a simulated multi-slice pod
    — the arm the elastic re-admission tests kill a whole slice with);
    composes with FDT_FAULT_HOST (both must match when both are set).

Each fault fires ONCE per process: after a supervisor restart the
replayed step must succeed, otherwise every injected crash would look
deterministic (same step failing twice) and the supervisor would
correctly — but uselessly for testing — re-raise.

``corrupt_newest_checkpoint`` is the storage-fault arm: tests call it
directly to damage a committed checkpoint and assert the manager falls
back to the previous valid one."""

from __future__ import annotations

import os
import signal
import threading
from typing import Iterable, Iterator, Optional

ENV_DIE = "FDT_FAULT_DIE_AT_STEP"
ENV_SIGTERM = "FDT_FAULT_SIGTERM_AT_STEP"
ENV_DATA = "FDT_FAULT_DATA_AT_BATCH"
ENV_HANG = "FDT_FAULT_HANG_AT_STEP"
ENV_NAN = "FDT_FAULT_NAN_AT_STEP"
ENV_SPIKE = "FDT_FAULT_LOSS_SPIKE_AT_STEP"
ENV_CORRUPT = "FDT_FAULT_CORRUPT_SHARD"
ENV_HOST = "FDT_FAULT_HOST"
ENV_SLICE = "FDT_FAULT_SLICE"


class InjectedFault(RuntimeError):
    """A deliberately injected failure — semantically a crash, so
    nothing catches it specially: it must flow through the exact
    recovery path a real fault would."""


def _env_int(env: dict, key: str) -> Optional[int]:
    raw = env.get(key)
    if raw in (None, ""):
        return None
    try:
        return int(raw)
    except ValueError:
        raise ValueError(f"malformed {key}={raw!r}: want an integer step")


class FaultPlan:
    def __init__(self, die_at: Optional[int] = None,
                 sigterm_at: Optional[int] = None,
                 data_at: Optional[int] = None,
                 hang_at: Optional[int] = None,
                 nan_at: Optional[int] = None,
                 spike_at: Optional[int] = None,
                 corrupt_shard: Optional[int] = None):
        self.die_at = die_at
        self.sigterm_at = sigterm_at
        self.data_at = data_at
        self.hang_at = hang_at
        self.nan_at = nan_at
        self.spike_at = spike_at
        self.corrupt_shard = corrupt_shard
        self._die_fired = False
        self._sigterm_fired = False
        self._data_fired = False
        self._hang_fired = False
        self._spike_fired = False
        # production never sets this — the hang "ends" when the watchdog
        # SIGKILLs the process; in-process tests set it from an injected
        # watchdog abort_fn so the pytest process survives the exercise
        self.hang_release = threading.Event()

    @classmethod
    def from_env(cls, env=os.environ,
                 process_index: Optional[int] = None
                 ) -> Optional["FaultPlan"]:
        """The armed plan, or None when no FDT_FAULT_* is set (the
        common case — callers skip every per-step hook).  With
        ``FDT_FAULT_HOST`` set, only the pod process with that index
        gets the plan; with ``FDT_FAULT_SLICE`` set, only the processes
        of that slice do (``process_index`` defaults to
        :func:`coordinator.pod_identity`, so the env seam and real
        multi-host runs both scope correctly)."""
        die = _env_int(env, ENV_DIE)
        sig = _env_int(env, ENV_SIGTERM)
        data = _env_int(env, ENV_DATA)
        hang = _env_int(env, ENV_HANG)
        nan = _env_int(env, ENV_NAN)
        spike = _env_int(env, ENV_SPIKE)
        corrupt = _env_int(env, ENV_CORRUPT)
        if (die is None and sig is None and data is None and hang is None
                and nan is None and spike is None and corrupt is None):
            return None
        host = _env_int(env, ENV_HOST)
        slice_ = _env_int(env, ENV_SLICE)
        if host is not None or slice_ is not None:
            from faster_distributed_training_tpu.resilience.coordinator \
                import pod_identity, slice_identity
            if process_index is None:
                process_index = pod_identity(env)[0]
            if host is not None and int(process_index) != host:
                return None
            if slice_ is not None and slice_identity(
                    env, process_index=process_index)[0] != slice_:
                return None
        return cls(die_at=die, sigterm_at=sig, data_at=data, hang_at=hang,
                   nan_at=nan, spike_at=spike, corrupt_shard=corrupt)

    def on_step(self, step: int) -> None:
        """Called by the train loop after each completed global step."""
        if (self.sigterm_at is not None and step >= self.sigterm_at
                and not self._sigterm_fired):
            self._sigterm_fired = True
            # a REAL signal to this process: the preemption handler's
            # delivery path is part of what the harness exercises
            os.kill(os.getpid(), signal.SIGTERM)
        if (self.hang_at is not None and step >= self.hang_at
                and not self._hang_fired):
            self._hang_fired = True
            # block the main thread indefinitely — from the outside this
            # is indistinguishable from a wedged dispatch/collective,
            # which is the point: only the watchdog thread can act
            self.hang_release.wait()
        if (self.die_at is not None and step >= self.die_at
                and not self._die_fired):
            self._die_fired = True
            raise InjectedFault(f"injected crash at global step {step}")

    def perturb_loss(self, step: int, loss: float) -> float:
        """The loss-spike arm: scale the HOST-OBSERVED dispatch loss
        once at step >= spike_at (resilience/sentinel.py feeds its
        detector through this).  The device metrics stream is never
        touched — the spike exists only in the sentinel's view, exactly
        like a bad batch whose gradients are finite but wrong."""
        if (self.spike_at is not None and step >= self.spike_at
                and not self._spike_fired):
            self._spike_fired = True
            return float(loss) * 1e4
        return loss

    def wrap_data(self, iterable: Iterable) -> Iterator:
        """Data-iterator fault: yields batches until index `data_at`,
        then raises from INSIDE the iterator — through PrefetchIterator /
        ParallelBatchIterator this lands in the consumer thread exactly
        like a real loader failure."""
        if self.data_at is None:
            yield from iterable
            return
        for i, item in enumerate(iterable):
            if i >= self.data_at and not self._data_fired:
                self._data_fired = True
                raise InjectedFault(
                    f"injected data-iterator failure at batch {i}")
            yield item


def graph_nan_at(env=os.environ) -> Optional[int]:
    """The ``FDT_FAULT_NAN_AT_STEP`` arm for train/steps.py: the step
    at which the jitted program should poison the loss, or None.  Read
    at TRACE time (the multiplier is baked into the lowered program),
    honoring the same FDT_FAULT_HOST/FDT_FAULT_SLICE scoping as every
    other arm."""
    plan = FaultPlan.from_env(env)
    return plan.nan_at if plan is not None else None


def corrupt_stream_shard(split_dir: str, index: int = 0) -> Optional[str]:
    """Flip bytes in the middle of stream shard ``index``'s largest
    leaf file under ``split_dir`` WITHOUT changing its size: the
    reader's byte-size cross-check at open still passes — only the
    per-shard CRC32C (data/stream format v1+) catches it, which is the
    exact silent bit-rot the checksum tier exists for.  Fixed-pattern
    overwrite (idempotent — a restart re-corrupting the same shard is a
    no-op).  Returns the damaged path, or None when the split has no
    manifest yet (nothing to corrupt)."""
    import json
    mpath = os.path.join(split_dir, "manifest.json")
    if not os.path.isfile(mpath):
        return None
    with open(mpath) as f:
        manifest = json.load(f)
    shards = manifest.get("shards") or []
    if not 0 <= int(index) < len(shards):
        raise ValueError(f"{ENV_CORRUPT}={index}: split {split_dir} has "
                         f"{len(shards)} shard(s)")
    files = shards[int(index)]["files"]
    leaf = max(files, key=lambda k: int(files[k]["bytes"]))
    path = os.path.join(split_dir, files[leaf]["file"])
    size = os.path.getsize(path)
    pattern = b"\xde\xad\xbe\xef" * 16
    # past the .npy header, short of EOF — data bytes, size untouched
    off = min(max(size // 2, 128), max(size - len(pattern), 0))
    with open(path, "r+b") as f:
        f.seek(off)
        f.write(pattern[:max(size - off, 1)])
        f.flush()
        os.fsync(f.fileno())
    return path


def apply_corrupt_shard_fault(stream_dir: str, env=os.environ,
                              log=print) -> Optional[str]:
    """Fire the ``FDT_FAULT_CORRUPT_SHARD`` arm (if armed and scoped to
    this process) against ``<stream_dir>/train`` — called by
    cli.run_training BEFORE the dataset opens, so the damage is on disk
    when the reader's background refill first touches the shard.
    Returns the damaged path or None."""
    plan = FaultPlan.from_env(env)
    if plan is None or plan.corrupt_shard is None:
        return None
    path = corrupt_stream_shard(os.path.join(stream_dir, "train"),
                                plan.corrupt_shard)
    if path is not None:
        log(f"[faults] corrupted stream shard {plan.corrupt_shard}: {path}")
    return path


def corrupt_newest_checkpoint(directory: str, prefix: str = "ckpt",
                              mode: str = "truncate") -> Optional[str]:
    """Damage the newest COMMITTED `<prefix>_step_*` checkpoint under
    `directory`; returns its path (None when there is none).

    mode="truncate": halve the largest data file — the commit marker
    stays intact, so validity checks pass but the restore fails
    (bit-rot / torn-block simulation; the manager must fall back).
    mode="unmark": delete BOTH completion markers (ours and orbax's) —
    the half-written-directory shape has_checkpoint() must reject (a
    directory from a non-atomic writer killed mid-save has neither)."""
    from faster_distributed_training_tpu.resilience.manager import (
        AsyncCheckpointManager)
    from faster_distributed_training_tpu.train import checkpoint as ckpt

    mgr = AsyncCheckpointManager(directory, prefix=prefix,
                                 log=lambda *_: None)
    newest = mgr.latest_valid()
    if newest is None:
        return None
    path = os.path.join(directory, newest[1])
    if mode == "unmark":
        for marker in (ckpt._COMMIT, ckpt._OCP_METADATA):
            p = os.path.join(path, marker)
            if os.path.exists(p):
                os.remove(p)
        return path
    if mode != "truncate":
        raise ValueError(f"unknown corruption mode {mode!r}")
    largest, size = None, -1
    for root, _dirs, files in os.walk(path):
        for f in files:
            p = os.path.join(root, f)
            s = os.path.getsize(p)
            if s > size:
                largest, size = p, s
    if largest is None:
        raise RuntimeError(f"no data files under {path}")
    with open(largest, "r+b") as f:
        f.truncate(max(size // 2, 1))
    return path
