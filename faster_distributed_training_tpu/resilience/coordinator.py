"""Pod-coordinated restart protocol + cluster health watchdog.

The r7 supervisor restarts *the process it lives in*.  On a multi-host
pod that is not enough: a crash on one host leaves its peers blocked
forever inside the next collective — the dominant badput source the
large-scale systems literature identifies (MegaScale's hang/partial-
failure taxonomy, Pathways' single-controller failure handling): no
process INSIDE a blocked collective can observe that a peer died.  Two
cooperating pieces close the gap, both living on the shared checkpoint
filesystem (the same marker-file idiom as the r9 two-phase commit — the
one medium every host can reach without a working collective):

  * **Restart coordination protocol** (:class:`PodCoordinator`): a
    monotonically increasing *generation* directory
    ``_pod/gen_<g>/``.  A host that fails locally writes ``FAIL_<pi>``
    into the current generation; every host polls the failure markers at
    the preemption-sync cadence, abandons the attempt
    (:class:`PeerFailure`) and re-enters ``Supervisor.run`` — whose next
    attempt computes the SAME next generation (1 + the newest generation
    carrying a FAIL marker) on every host, so the pod converges on one
    restart.  Each attempt then restores through ``restore_latest``'s
    cross-host step-agreement, so all hosts provably resume from the
    same checkpoint step; the (seed, epoch, step)-pure batch order means
    the data iterators re-agree on position for free (pinned by
    tests/test_pod_restart.py, not assumed).

  * **Health watchdog**: a per-host heartbeat thread touches
    ``HB_<pi>`` with the current step every ``hb_interval_s`` seconds;
    :meth:`check` flags a peer whose heartbeat is stale past
    ``peer_timeout_s`` (the host died without writing FAIL — SIGKILL,
    kernel panic, machine loss).  The same thread watches the LOCAL
    step clock: a dispatch exceeding ``step_timeout_s`` means this
    host's main thread is wedged (hung device program, a collective
    blocked on a dead peer) — the watchdog is the only thing still able
    to act, so it escalates by durably writing its own ``FAIL`` marker
    (kind="hang") and hard-aborting the process; the peers observe the
    marker (or the heartbeat going stale) and the pod converges on a
    restart instead of deadlocking.

Detection/restore latencies feed the goodput tracker (``detect_s``,
``restore_s``, ``restart_backoff_s`` → ``restart_mttr_s``) so MTTR is a
first-class metric beside goodput_pct.

Simulation seam (mirrors the r9 manager seam): ``process_index`` /
``process_count`` default to the real jax runtime but can be overridden
— two coordinators sharing one directory ARE a simulated two-host pod,
and :func:`pod_identity` reads ``FDT_POD_INDEX``/``FDT_POD_COUNT`` so
the pod_restart_smoke script can run a REAL two-process simulated pod
(coordination cross-process through the fs; jax stays single-process
per host, so each host computes the identical full state).  In that
fs-simulated mode :meth:`gather_restored_step` supplies the restore
step-agreement barrier that real pods get from the jax collective.

Clock caveat: marker timestamps are host wall clocks; the detect_s
latency derived from a PEER's marker is exact in the single-machine
simulations and subject to NTP skew across real hosts (seconds — noise
against multi-second detection cadences, documented rather than
hidden)."""

from __future__ import annotations

import os
import re
import shutil
import signal
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

ENV_POD_INDEX = "FDT_POD_INDEX"
ENV_POD_COUNT = "FDT_POD_COUNT"

_GEN_DIR = re.compile(r"^gen_(?P<gen>\d{6})$")
# strict: the atomic writer stages `FAIL_<pi>.tmp<pid>` beside the real
# marker — listing-based discovery must never parse those as markers
_FAIL = re.compile(r"^FAIL_(?P<pi>\d{5})$")


class PeerFailure(RuntimeError):
    """A peer host failed (FAIL marker observed) or went heartbeat-stale
    — this attempt is abandoned so the whole pod re-enters the
    supervisor together.  RESTARTABLE: the supervisor retries it like
    any crash (the next attempt converges on the same new generation on
    every host)."""


class StepTimeout(RuntimeError):
    """This host's own step made no progress for ``step_timeout_s`` and
    the watchdog escalated (its FAIL marker is already on the shared
    fs).  Raised by the main-thread poll when the hang RELEASES (test
    harnesses); in production the escalation hard-aborts the process
    before this can be raised — the platform's re-launch plays the
    supervisor's role."""


def pod_identity(env=os.environ) -> Tuple[int, int, bool]:
    """(process_index, process_count, simulated).

    ``FDT_POD_INDEX``/``FDT_POD_COUNT`` override the jax runtime — the
    simulation seam the pod_restart_smoke script and the tier-1 tests
    use (jax stays single-process; only the RESTART coordination and
    the checkpoint two-phase commit run cross-process).  Without them,
    the real runtime."""
    if env.get(ENV_POD_COUNT):
        return (int(env.get(ENV_POD_INDEX, "0")), int(env[ENV_POD_COUNT]),
                True)
    import jax
    return jax.process_index(), jax.process_count(), False


def _write_json_atomic(path: str, obj) -> None:
    # local copy of checkpoint._write_json_atomic (tmp + replace + fsync)
    # so the watchdog thread can write markers without importing the
    # orbax-heavy checkpoint module from a non-main thread mid-crash.
    # The tmp name carries the THREAD ident too: the heartbeat is
    # written from both the watchdog thread (every hb_interval_s) and
    # the main thread (begin_attempt) — a pid-only tmp path would let
    # one thread's os.replace consume the other's staged file and turn
    # a benign overlap into FileNotFoundError
    import json
    tmp = f"{path}.tmp{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "w") as f:
        json.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _read_json(path: str) -> Optional[dict]:
    import json
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


class PodCoordinator:
    """Owns ``<directory>/gen_<g>/`` and this host's markers in it.

    Lifecycle: the supervisor calls :meth:`begin_attempt` before every
    attempt (starts the heartbeat/watchdog thread on first use) and
    :meth:`record_failure` when one dies; the train loop calls
    :meth:`check` once per dispatch (cadence-gated internally) and wraps
    each epoch in :meth:`watch_steps` so the step watchdog only runs
    while dispatches are actually expected to complete (never during
    eval or restore — heartbeats continue regardless, proving liveness
    to the peers).  ``abort_fn`` is the escalation seam: the default
    SIGKILLs the process (the main thread may be wedged in C code where
    nothing softer is guaranteed to run); tests inject a releasing
    hook."""

    def __init__(self, directory: str, process_index: Optional[int] = None,
                 process_count: Optional[int] = None, sync_every: int = 8,
                 peer_timeout_s: float = 60.0, step_timeout_s: float = 0.0,
                 hb_interval_s: float = 2.0, gather_timeout_s: float = 120.0,
                 goodput=None, log: Callable[[str], None] = print,
                 abort_fn: Optional[Callable[[str], None]] = None):
        if process_index is None or process_count is None:
            pi, pc, _sim = pod_identity()
            process_index = pi if process_index is None else process_index
            process_count = pc if process_count is None else process_count
        self.directory = os.path.abspath(directory)
        self.pi = int(process_index)
        self.pc = int(process_count)
        self.sync_every = max(int(sync_every), 1)
        self.peer_timeout_s = float(peer_timeout_s)
        self.step_timeout_s = float(step_timeout_s)
        self.hb_interval_s = float(hb_interval_s)
        self.gather_timeout_s = float(gather_timeout_s)
        self._goodput = goodput
        self._log = log
        self._abort = abort_fn or self._default_abort
        # EXIT markers older than this coordinator are a PREVIOUS run's
        # completions (the same checkpoint_dir reused to train further)
        # and must not poison this run — see _exited_peers
        self._created_t = time.time()
        self._gen: Optional[int] = None
        self._gen_dir: Optional[str] = None
        self._attempt_wall_t = time.time()
        self._last_polled = -1
        # shared with the watchdog thread (plain attrs: CPython atomic
        # loads/stores; the thread only READS them)
        self._step = 0
        self._progress_t = time.monotonic()
        self._watching = False
        self._escalated = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- marker paths ------------------------------------------------------

    def _gen_path(self, gen: int) -> str:
        return os.path.join(self.directory, f"gen_{gen:06d}")

    def _marker(self, kind: str, pi: int, gen_dir: Optional[str] = None
                ) -> str:
        return os.path.join(gen_dir or self._require_gen(), f"{kind}_{pi:05d}")

    def _require_gen(self) -> str:
        if self._gen_dir is None:
            # a caller (direct restore, record_failure before any
            # attempt) outran begin_attempt: join the protocol at the
            # generation begin_attempt would compute
            self.begin_attempt()
        return self._gen_dir

    def _generations(self) -> List[Tuple[int, str]]:
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        out = []
        for n in names:
            m = _GEN_DIR.match(n)
            if m:
                out.append((int(m.group("gen")),
                            os.path.join(self.directory, n)))
        return sorted(out)

    def _failures(self, gen_dir: str) -> Dict[int, dict]:
        out = {}
        try:
            names = os.listdir(gen_dir)
        except OSError:
            return out
        for n in names:
            m = _FAIL.match(n)
            if m:
                out[int(m.group("pi"))] = _read_json(
                    os.path.join(gen_dir, n)) or {}
        return out

    # -- restart coordination protocol -------------------------------------

    def begin_attempt(self) -> int:
        """Enter the pod's current generation: 1 + the newest generation
        holding any FAIL marker (0 on a clean directory).  Every host
        computes this from the same shared-fs state, so hosts that
        restarted for DIFFERENT reasons (own crash vs observed peer
        failure) still converge on one generation — and a fresh process
        launched into an old incident's directory joins at the incident's
        next generation rather than rewinding the counter."""
        g = 0
        for gen, d in self._generations():
            if self._failures(d):
                g = gen + 1
        if self._gen is not None:
            if g > self._gen and self._goodput is not None:
                self._goodput.count("restart_generations", g - self._gen)
            g = max(g, self._gen)
        changed = g != self._gen
        self._gen = g
        self._gen_dir = self._gen_path(g)
        os.makedirs(self._gen_dir, exist_ok=True)
        try:
            # an attempting host is by definition not done: clear our own
            # completion marker (a previous run's residue when the same
            # checkpoint_dir is relaunched; peers also time-scope what
            # they honor — _exited_peers)
            os.remove(os.path.join(self.directory, f"EXIT_{self.pi:05d}"))
        except OSError:
            pass
        self._attempt_wall_t = time.time()
        self._last_polled = -1
        self._escalated = False
        self._progress_t = time.monotonic()
        self._write_heartbeat()
        if changed:
            self._log(f"[pod] host {self.pi}/{self.pc} entering "
                      f"generation {g}")
        self._ensure_thread()
        self._prune_generations()
        return g

    def record_failure(self, exc: BaseException,
                       step: Optional[int] = None) -> None:
        """Durably publish this host's failure to the pod (atomic marker
        write).  Best-effort: a failing shared fs must not mask the
        original exception."""
        kind = ("hang" if isinstance(exc, StepTimeout)
                else "peer" if isinstance(exc, PeerFailure) else "crash")
        try:
            self._write_fail(kind, f"{type(exc).__name__}: {exc}", step)
        except OSError as e:
            self._log(f"[pod] host {self.pi}: could not write FAIL marker "
                      f"({e!r}) — peers will detect via heartbeat staleness")

    def record_completion(self, step: Optional[int] = None) -> None:
        """Durably mark this host's run COMPLETE (``EXIT_<pi>`` at the
        coordination-directory ROOT, outside any generation, so it
        survives generation pruning).  Written by the supervisor on a
        successful run.  An exited peer is success, not failure: the
        staleness monitor ignores it (hosts finish at slightly
        different times — its heartbeat going quiet must not restart
        the stragglers), but the restore-agreement barrier fails FAST
        on it — a host restarting after a peer already finished can
        never rejoin the pod, and learning that immediately beats
        waiting out gather_timeout_s per attempt."""
        try:
            _write_json_atomic(
                os.path.join(self.directory, f"EXIT_{self.pi:05d}"),
                {"step": self._step if step is None else int(step),
                 "unix_time": round(time.time(), 3)})
        except OSError as e:
            self._log(f"[pod] host {self.pi}: could not write EXIT marker "
                      f"({e!r}) — a later-restarting peer will wait out "
                      f"its restore barrier instead of failing fast")

    def _exited_peers(self) -> List[int]:
        """Peers that completed THIS run: EXIT markers newer than this
        coordinator's creation.  An older marker is a PREVIOUS run's
        completion (the same checkpoint_dir relaunched to train
        further) — honoring it would permanently disable staleness
        detection for that peer and fail fresh restore barriers with
        "pod already finished", so it is ignored (and each host deletes
        its own stale marker in begin_attempt).  The in-process
        supervisor restart — the path the fail-fast exists for — keeps
        its coordinator across attempts, so a peer completing mid-run
        always postdates it.  Cross-host NTP skew (seconds) is noise
        against the run-length gap that separates the two cases."""
        out = []
        for pi in range(self.pc):
            if pi == self.pi:
                continue
            got = _read_json(os.path.join(self.directory, f"EXIT_{pi:05d}"))
            if got is not None and got.get("unix_time", 0.0) > self._created_t:
                out.append(pi)
        return out

    def _write_fail(self, kind: str, reason: str,
                    step: Optional[int] = None) -> None:
        _write_json_atomic(
            self._marker("FAIL", self.pi),
            {"kind": kind, "reason": reason[:500],
             "step": self._step if step is None else int(step),
             "unix_time": round(time.time(), 3)})

    def check(self, step: int) -> None:
        """Main-thread poll, called once per dispatch; raises
        :class:`PeerFailure` / :class:`StepTimeout` when the attempt
        must be abandoned.  Cadence-gated with the same boundary-
        crossing algebra as the preemption agreement bit (sync_every;
        robust to K-step dispatch boundaries), EXCEPT after a local
        watchdog escalation, which must surface on the very next poll."""
        self._step = int(step)
        self._progress_t = time.monotonic()
        prev, self._last_polled = self._last_polled, step
        if not self._escalated and prev >= 0 \
                and step // self.sync_every <= prev // self.sync_every:
            return
        self._raise_observed_failures()

    def _raise_observed_failures(self) -> None:
        gen_dir = self._require_gen()
        fails = self._failures(gen_dir)
        now = time.time()
        own = fails.pop(self.pi, None)
        if fails:
            peers = sorted(fails)
            newest = max((f.get("unix_time", now) for f in fails.values()),
                         default=now)
            detect = max(now - newest, 0.0)
            if self._goodput is not None:
                self._goodput.count("peer_failures")
                self._goodput.add("detect_s", detect)
            raise PeerFailure(
                f"host(s) {peers} failed in generation {self._gen} "
                f"({fails[peers[0]].get('kind', '?')}: "
                f"{fails[peers[0]].get('reason', '?')}); abandoning this "
                f"attempt so the pod restarts together "
                f"(observed {detect:.2f}s after the marker landed)")
        if own is not None:
            # our OWN marker with nobody else's: the watchdog escalated a
            # local hang and the abort was intercepted (test harness) —
            # surface it as the restartable fault it is
            raise StepTimeout(
                f"host {self.pi}: step watchdog escalated "
                f"({own.get('reason', 'no step progress')}); restarting")
        stale = self._stale_peers(now)
        if stale:
            pi0, age = stale[0]
            if self._goodput is not None:
                self._goodput.count("peer_failures")
                # detect_s = failure-to-observed latency.  The peer died
                # (silently — no FAIL marker) at roughly its last
                # heartbeat, so the full silence AGE is the latency
                # (over-estimates by at most hb_interval_s); it is
                # necessarily >= peer_timeout_s — a silent death cannot
                # be detected faster than the staleness threshold
                self._goodput.add("detect_s", age)
            raise PeerFailure(
                f"host(s) {[p for p, _ in stale]} heartbeat-stale "
                f"(oldest {age:.1f}s > peer_timeout_s="
                f"{self.peer_timeout_s:.0f}) in generation {self._gen} — "
                f"treating as dead and restarting the pod")

    def _stale_peers(self, now: float) -> List[Tuple[int, float]]:
        """[(peer index, silence age)] for peers silent past the
        timeout.  A missing heartbeat is aged from this attempt's start
        (peers that merely haven't launched yet get the same grace as
        slow first heartbeats)."""
        if self.pc <= 1 or self.peer_timeout_s <= 0:
            return []
        gen_dir = self._require_gen()
        exited = set(self._exited_peers())
        out = []
        for pi in range(self.pc):
            if pi == self.pi or pi in exited:
                # an exited peer FINISHED — its quiet heartbeat is
                # success, not death; stragglers keep running
                continue
            try:
                t = os.path.getmtime(self._marker("HB", pi, gen_dir))
            except OSError:
                t = self._attempt_wall_t
            age = now - t
            if age > self.peer_timeout_s:
                out.append((pi, age))
        return out

    # -- restore step agreement (fs-simulated pods) ------------------------

    def gather_restored_step(self, step: int,
                             phase: str = "agree") -> np.ndarray:
        """Span-wrapped ("rendezvous" — barrier waits are the pod
        restore's dominant cost and telemetry must attribute them):
        see :meth:`_gather_restored_step_impl`."""
        from faster_distributed_training_tpu.telemetry import spans
        with spans.span("rendezvous"):
            return self._gather_restored_step_impl(step, phase)

    def _gather_restored_step_impl(self, step: int,
                                   phase: str = "agree") -> np.ndarray:
        """Filesystem allgather of every host's restored checkpoint step
        (−1 = nothing restored) — the restore agreement barrier for
        fs-SIMULATED pods, where jax is single-process per host and the
        manager's real ``all_gather_across_processes`` would see only
        itself.  Same rendezvous property as the collective: every host
        blocks here until all have joined (so process 0's pre-agreement
        residue sweep stays race-free), and a FAIL marker or timeout
        raises :class:`PeerFailure` instead of deadlocking on a host
        that died mid-restore.  ``phase`` names the barrier — the
        manager enters twice per restore ("enter" = pre-walk
        rendezvous after draining in-flight writes, "agree" = the
        post-walk step agreement), and each phase needs its own marker
        file.  One restore per generation (the supervisor wiring
        guarantees it — each attempt enters a fresh generation after
        any failure)."""
        gen_dir = self._require_gen()
        kind = "RESTORE" if phase == "agree" else f"R{phase.upper()}"
        _write_json_atomic(self._marker(kind, self.pi),
                           {"step": int(step)})
        deadline = time.monotonic() + self.gather_timeout_s
        while True:
            vals = []
            for pi in range(self.pc):
                got = _read_json(self._marker(kind, pi, gen_dir))
                if got is None:
                    break
                vals.append(got["step"])
            else:
                return np.asarray(vals, np.int32)
            fails = {p: f for p, f in self._failures(gen_dir).items()
                     if p != self.pi}
            if fails:
                raise PeerFailure(
                    f"host(s) {sorted(fails)} failed while this host was "
                    f"waiting in the restore-agreement barrier "
                    f"(generation {self._gen})")
            done = [p for p in self._exited_peers()
                    if _read_json(self._marker(kind, p, gen_dir)) is None]
            if done:
                # a peer that already COMPLETED the run will never join
                # this barrier — fail fast (every retry will fail the
                # same way until the restart budget runs out, each in
                # milliseconds instead of a full gather timeout)
                raise PeerFailure(
                    f"host(s) {done} already completed the run (EXIT "
                    f"marker) and can never join the generation "
                    f"{self._gen} restore barrier — the pod finished "
                    f"without this host; restore the final checkpoint "
                    f"manually or rerun against a fresh directory")
            if time.monotonic() > deadline:
                raise PeerFailure(
                    f"restore-agreement barrier timed out after "
                    f"{self.gather_timeout_s:.0f}s in generation "
                    f"{self._gen}: {self.pc - len(vals)} host(s) never "
                    f"joined")
            time.sleep(0.05)

    # -- health watchdog ---------------------------------------------------

    def watch_steps(self):
        """Context manager arming the local step watchdog for an epoch's
        dispatch loop (heartbeats run regardless; only the no-progress
        escalation is scoped, so eval/restore phases can't false-
        trigger).  ``step_timeout_s`` must exceed the worst-case
        (re)compile of one dispatch — it defaults to 0 (off)."""
        from contextlib import contextmanager

        @contextmanager
        def _ctx():
            self._progress_t = time.monotonic()
            self._watching = True
            try:
                yield
            finally:
                self._watching = False
        return _ctx()

    def pause_watch(self):
        """Context manager suspending the LOCAL no-progress escalation
        around legitimate blocking work on the step thread — cadence
        saves that drain a prior write's commit barrier (up to
        commit_timeout_s, typically far beyond any sane
        step_timeout_s), the preemption emergency save — so a healthy
        host is never SIGKILLed mid-save.  Heartbeats keep running (the
        host IS alive, the peers must see that), and a genuinely
        wedged save stays bounded by its own timeout (TimeoutError →
        counted save failure) rather than needing the watchdog.  The
        step clock restarts fresh on resume."""
        from contextlib import contextmanager

        @contextmanager
        def _ctx():
            was = self._watching
            self._watching = False
            try:
                yield
            finally:
                self._progress_t = time.monotonic()
                self._watching = was
        return _ctx()

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._watchdog_body, name=f"fdt-pod-wd-{self.pi}",
                daemon=True)
            self._thread.start()

    def _watchdog_body(self) -> None:
        while not self._stop.wait(self.hb_interval_s):
            try:
                self._write_heartbeat()
            except OSError:
                pass  # a flaky shared fs must not kill the watchdog
            if (self._watching and not self._escalated
                    and self.step_timeout_s > 0
                    and time.monotonic() - self._progress_t
                    > self.step_timeout_s):
                self._escalate_hang()

    def _write_heartbeat(self) -> None:
        if self._gen_dir is None:
            return
        _write_json_atomic(self._marker("HB", self.pi),
                           {"step": self._step,
                            "unix_time": round(time.time(), 3)})

    def _escalate_hang(self) -> None:
        """Watchdog-thread escalation: the main thread has made no step
        progress for step_timeout_s — it is wedged in a dispatch or a
        collective and cannot raise for itself.  Publish the failure
        durably FIRST (so the peers restart even if the abort below is
        instant), then abort."""
        self._escalated = True
        stuck = time.monotonic() - self._progress_t
        reason = (f"no step progress for {stuck:.1f}s "
                  f"(> step_timeout_s={self.step_timeout_s:.0f}) "
                  f"at step {self._step}")
        try:
            self._write_fail("hang", reason)
        except OSError:
            pass  # peers fall back to heartbeat staleness
        if self._goodput is not None:
            self._goodput.count("step_timeouts")
        self._log(f"[pod] host {self.pi}: WATCHDOG: {reason}; FAIL marker "
                  f"written, aborting so the pod converges on a restart")
        self._abort(reason)

    @staticmethod
    def _default_abort(reason: str) -> None:
        # SIGKILL, not sys.exit/os._exit: the main thread may be wedged
        # inside a device runtime call holding locks that Python-level
        # teardown (atexit, GC finalizers, PJRT client destructors) would
        # deadlock on.  Nothing softer is guaranteed to terminate a
        # process whose main thread is stuck in C.
        os.kill(os.getpid(), signal.SIGKILL)

    # -- housekeeping ------------------------------------------------------

    def _prune_generations(self, keep: int = 3) -> None:
        """Old generation dirs are a few marker files each; process 0
        sweeps all but the newest ``keep`` so a long-lived flaky pod
        doesn't accumulate thousands of dirs.  Kept generations must
        include every one a lagging peer could still be reading (a peer
        is at most one incident behind — it restarts the moment it
        observes the newest FAIL markers)."""
        if self.pi != 0 or self._gen is None:
            return
        for gen, d in self._generations():
            if gen <= self._gen - keep:
                shutil.rmtree(d, ignore_errors=True)

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.hb_interval_s + 5.0)
            self._thread = None
