"""Pod-coordinated restart protocol + cluster health watchdog.

The r7 supervisor restarts *the process it lives in*.  On a multi-host
pod that is not enough: a crash on one host leaves its peers blocked
forever inside the next collective — the dominant badput source the
large-scale systems literature identifies (MegaScale's hang/partial-
failure taxonomy, Pathways' single-controller failure handling): no
process INSIDE a blocked collective can observe that a peer died.  Two
cooperating pieces close the gap, both living on the shared checkpoint
filesystem (the same marker-file idiom as the r9 two-phase commit — the
one medium every host can reach without a working collective):

  * **Restart coordination protocol** (:class:`PodCoordinator`): a
    monotonically increasing *generation* directory
    ``_pod/gen_<g>/``.  A host that fails locally writes ``FAIL_<pi>``
    into the current generation; every host polls the failure markers at
    the preemption-sync cadence, abandons the attempt
    (:class:`PeerFailure`) and re-enters ``Supervisor.run`` — whose next
    attempt computes the SAME next generation (1 + the newest generation
    carrying a FAIL marker) on every host, so the pod converges on one
    restart.  Each attempt then restores through ``restore_latest``'s
    cross-host step-agreement, so all hosts provably resume from the
    same checkpoint step; the (seed, epoch, step)-pure batch order means
    the data iterators re-agree on position for free (pinned by
    tests/test_pod_restart.py, not assumed).

  * **Health watchdog**: a per-host heartbeat thread touches
    ``HB_<pi>`` with the current step every ``hb_interval_s`` seconds;
    :meth:`check` flags a peer whose heartbeat is stale past
    ``peer_timeout_s`` (the host died without writing FAIL — SIGKILL,
    kernel panic, machine loss).  The same thread watches the LOCAL
    step clock: a dispatch exceeding ``step_timeout_s`` means this
    host's main thread is wedged (hung device program, a collective
    blocked on a dead peer) — the watchdog is the only thing still able
    to act, so it escalates by durably writing its own ``FAIL`` marker
    (kind="hang") and hard-aborting the process; the peers observe the
    marker (or the heartbeat going stale) and the pod converges on a
    restart instead of deadlocking.

Detection/restore latencies feed the goodput tracker (``detect_s``,
``restore_s``, ``restart_backoff_s`` → ``restart_mttr_s``) so MTTR is a
first-class metric beside goodput_pct.

Simulation seam (mirrors the r9 manager seam): ``process_index`` /
``process_count`` default to the real jax runtime but can be overridden
— two coordinators sharing one directory ARE a simulated two-host pod,
and :func:`pod_identity` reads ``FDT_POD_INDEX``/``FDT_POD_COUNT`` so
the pod_restart_smoke script can run a REAL two-process simulated pod
(coordination cross-process through the fs; jax stays single-process
per host, so each host computes the identical full state).  In that
fs-simulated mode :meth:`gather_restored_step` supplies the restore
step-agreement barrier that real pods get from the jax collective.

Clock caveat: marker timestamps are host wall clocks; the detect_s
latency derived from a PEER's marker is exact in the single-machine
simulations and subject to NTP skew across real hosts (seconds — noise
against multi-second detection cadences, documented rather than
hidden).

r14 — storage backend + slices: every marker read/write/list routes
through a :class:`~faster_distributed_training_tpu.resilience.storage.
StorageBackend`, so the ``_pod/gen_<g>/`` namespace can live on an
object store when the pod's slices do not share a filesystem (the
tier-1 fake object store proves the protocol needs no rename
primitive).  ``FDT_SLICE_INDEX``/``FDT_SLICE_COUNT``
(:func:`slice_identity`) partition the pod into slices with
slice-qualified marker names, and a failure confined to ONE foreign
slice no longer forces a whole-pod restart: the survivors park in a
bounded ``await_readmission`` hold (HOLD markers carrying their step),
the restarted slice REJOINS the incident's generation
(``begin_attempt`` detects own-slice-only FAILs), restores through a
slice-scoped barrier, catches up to the agreed target (max over
survivor holds — provably >= the restored checkpoint step) and joins
the ``RJREADY`` readiness barrier; every host then advances the
generation in place and resumes.  Whole-pod restart remains the
fallback for every ambiguous corner: hold/rejoin timeout, a second
failure outside the incident slice, or rejoin-retry residue (the
durable ``RJ_ABORT`` marker degrades everyone to the r10 protocol).

r17 — warm spares: a STANDBY process (``FDT_SLICE_SPARE=<id>`` /
``--warm_spares N``, :func:`spare_identity`) parks outside the pod —
mesh built, programs warmed through the persistent executable cache,
params restored to the last COMMIT and refreshed at each new one —
and, when an incident confined to one slice parks the survivors in
their hold, CLAIMS a failed seat with a durable first-writer-wins
``CLAIM`` marker (:meth:`PodCoordinator.spare_wait`) and swaps in
through the EXISTING rejoin machinery under the adopted member
identity: the survivors' ``_await_readmission`` never learns the
difference — it sees the seat's RJRENTER/RJRESTORE/RJREADY markers
as always.  A relaunch of the original host finds the CLAIM and
raises :class:`SeatTaken` (redundant by protocol, not restartable);
every post-claim ambiguity degrades through ``RJ_ABORT`` to the
whole-pod fallback like any rejoin."""

from __future__ import annotations

import os
import re
import signal
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from faster_distributed_training_tpu.resilience import storage as storage_mod

ENV_POD_INDEX = "FDT_POD_INDEX"
ENV_POD_COUNT = "FDT_POD_COUNT"
ENV_SLICE_INDEX = "FDT_SLICE_INDEX"
ENV_SLICE_COUNT = "FDT_SLICE_COUNT"
ENV_SLICE_SPARE = "FDT_SLICE_SPARE"

_GEN_DIR = re.compile(r"^gen_(?P<gen>\d{6})$")
# strict: the atomic writer stages `FAIL_<pi>.tmp<pid>` beside the real
# marker — listing-based discovery must never parse those as markers.
# Multi-slice pods qualify marker names with the slice (`FAIL_s001_00002`)
# so a per-slice observer can partition an incident without a reverse
# lookup; single-slice pods keep the r10 names byte-for-byte.
_FAIL = re.compile(r"^FAIL_(?:s(?P<si>\d{3})_)?(?P<pi>\d{5})$")
# one-per-generation rejoin-abort marker: a rejoining slice that cannot
# complete re-admission publishes it so the parked survivors fall back
# to a whole-pod restart immediately instead of waiting out their hold
_RJ_ABORT = "RJ_ABORT"


class PeerFailure(RuntimeError):
    """A peer host failed (FAIL marker observed) or went heartbeat-stale
    — this attempt is abandoned so the whole pod re-enters the
    supervisor together.  RESTARTABLE: the supervisor retries it like
    any crash (the next attempt converges on the same new generation on
    every host)."""


class SeatTaken(RuntimeError):
    """This host's pod seat was claimed by a warm spare while the host
    was down (durable first-writer-wins ``CLAIM`` marker, r17): the
    spare IS the seat now, so this relaunch is redundant by protocol.
    NOT restartable — retrying can never win the seat back; the
    supervisor re-raises it immediately (a platform that auto-relaunches
    should treat the exit as terminal for this incident, or re-launch
    the process as a fresh spare: FDT_SLICE_SPARE)."""


class StepTimeout(RuntimeError):
    """This host's own step made no progress for ``step_timeout_s`` and
    the watchdog escalated (its FAIL marker is already on the shared
    fs).  Raised by the main-thread poll when the hang RELEASES (test
    harnesses); in production the escalation hard-aborts the process
    before this can be raised — the platform's re-launch plays the
    supervisor's role."""


def pod_identity(env=os.environ) -> Tuple[int, int, bool]:
    """(process_index, process_count, simulated).

    ``FDT_POD_INDEX``/``FDT_POD_COUNT`` override the jax runtime — the
    simulation seam the pod_restart_smoke script and the tier-1 tests
    use (jax stays single-process; only the RESTART coordination and
    the checkpoint two-phase commit run cross-process).  Without them,
    the real runtime."""
    if env.get(ENV_POD_COUNT):
        return (int(env.get(ENV_POD_INDEX, "0")), int(env[ENV_POD_COUNT]),
                True)
    import jax
    return jax.process_index(), jax.process_count(), False


def slice_identity(env=os.environ, process_index: Optional[int] = None,
                   process_count: Optional[int] = None
                   ) -> Tuple[int, int, bool]:
    """(slice_index, slice_count, simulated) — the multi-SLICE seam
    beside :func:`pod_identity` (r14).

    ``FDT_SLICE_COUNT`` arms it: the pod's processes are partitioned
    into ``slice_count`` contiguous equal blocks (process ``pi`` lives
    on slice ``pi * slice_count // process_count`` — the layout real
    multislice launchers use, one process range per slice) and the
    coordinator scopes failure handling per slice: a dead slice can be
    restarted and RE-ADMITTED while the others hold, instead of forcing
    a whole-pod restart.  ``FDT_SLICE_INDEX`` overrides this host's own
    derived index for exotic layouts (the derived map still names the
    PEERS' slices, so overriding only one host inconsistently is
    unsupported — documented, not guessed around).  Without the env,
    (0, 1, False): single-slice, the r10 behavior byte-for-byte."""
    raw = env.get(ENV_SLICE_COUNT)
    if not raw:
        return 0, 1, False
    sc = int(raw)
    if sc <= 1:
        return 0, 1, False
    if process_index is None or process_count is None:
        pi, pc, _sim = pod_identity(env)
        process_index = pi if process_index is None else process_index
        process_count = pc if process_count is None else process_count
    raw_si = env.get(ENV_SLICE_INDEX)
    if raw_si not in (None, ""):
        return int(raw_si), sc, True
    return (int(process_index) * sc // max(int(process_count), 1), sc, True)


def spare_identity(env=os.environ) -> Optional[int]:
    """The warm-spare seam beside :func:`pod_identity` (r17):
    ``FDT_SLICE_SPARE=<id>`` marks this process a STANDBY spare — not
    one of the pod's ``process_count`` members, but a pre-admitted
    stand-in that parks (mesh built, programs warmed through the
    executable cache, params restored to the last COMMIT) and claims a
    failed slice's seat at re-admission time.  None = a normal member.
    ``--warm_spares N`` is the launcher-side contract: spawn N extra
    processes each carrying a distinct FDT_SLICE_SPARE id AND an
    out-of-pod ``FDT_POD_INDEX`` (``pod_count + id`` by convention —
    build_resilience derives that index regardless, but the telemetry
    recorder reads the env directly and its host JSONL file must not
    collide with a member's)."""
    raw = env.get(ENV_SLICE_SPARE)
    if raw in (None, ""):
        return None
    try:
        return int(raw)
    except ValueError:
        # fail FAST: two spares launched with malformed ids that both
        # silently mapped to 0 would share the synthetic pod index —
        # exactly the marker/shard/telemetry collision the out-of-pod
        # index exists to rule out
        raise ValueError(
            f"malformed {ENV_SLICE_SPARE}={raw!r}: want an integer "
            f"spare id (each spare process needs a DISTINCT one)")


def _write_json_atomic(path: str, obj) -> None:
    """Atomic marker write on the POSIX default backend — kept as a
    module-level helper for tests that plant markers directly; the
    coordinator itself routes every marker through its configured
    backend (r14).  The backend's staging name carries pid AND thread
    ident: heartbeats are written from both the watchdog thread and the
    main thread, and a shared staging path would let one thread's
    publish consume the other's."""
    storage_mod.posix_backend().put_json(path, obj)


def _read_json(path: str) -> Optional[dict]:
    return storage_mod.posix_backend().read_json(path)


class PodCoordinator:
    """Owns ``<directory>/gen_<g>/`` and this host's markers in it.

    Lifecycle: the supervisor calls :meth:`begin_attempt` before every
    attempt (starts the heartbeat/watchdog thread on first use) and
    :meth:`record_failure` when one dies; the train loop calls
    :meth:`check` once per dispatch (cadence-gated internally) and wraps
    each epoch in :meth:`watch_steps` so the step watchdog only runs
    while dispatches are actually expected to complete (never during
    eval or restore — heartbeats continue regardless, proving liveness
    to the peers).  ``abort_fn`` is the escalation seam: the default
    SIGKILLs the process (the main thread may be wedged in C code where
    nothing softer is guaranteed to run); tests inject a releasing
    hook."""

    def __init__(self, directory: str, process_index: Optional[int] = None,
                 process_count: Optional[int] = None, sync_every: int = 8,
                 peer_timeout_s: float = 60.0, step_timeout_s: float = 0.0,
                 hb_interval_s: float = 2.0, gather_timeout_s: float = 120.0,
                 goodput=None, log: Callable[[str], None] = print,
                 abort_fn: Optional[Callable[[str], None]] = None,
                 slice_index: Optional[int] = None,
                 slice_count: Optional[int] = None,
                 readmit_timeout_s: float = 0.0,
                 backend: Optional[storage_mod.StorageBackend] = None,
                 spare_index: Optional[int] = None):
        if process_index is None or process_count is None:
            pi, pc, _sim = pod_identity()
            process_index = pi if process_index is None else process_index
            process_count = pc if process_count is None else process_count
        self.directory = os.path.abspath(directory)
        self.pi = int(process_index)
        self.pc = int(process_count)
        # multi-slice identity (r14): slice_count>1 partitions the pod
        # into contiguous process blocks and arms slice-granular
        # re-admission (readmit_timeout_s>0); default = one slice, the
        # r10 whole-pod protocol byte-for-byte
        if slice_index is None or slice_count is None:
            si, sc, _ssim = slice_identity(
                process_index=self.pi, process_count=self.pc)
            slice_index = si if slice_index is None else slice_index
            slice_count = sc if slice_count is None else slice_count
        self.si = int(slice_index)
        self.sc = max(int(slice_count), 1)
        self.readmit_timeout_s = float(readmit_timeout_s)
        # warm-spare identity (r17): a spare is NOT one of the pod's pc
        # members — it parks under a synthetic out-of-pod index (pc +
        # spare id, so its markers can never collide with a member's)
        # until _spare_try_claim wins a failed seat and _adopt_seat
        # re-keys pi/si to the claimed member identity
        if spare_index is None:
            spare_index = spare_identity()
        self.spare_index = spare_index
        if spare_index is not None:
            self.pi = self.pc + int(spare_index)
        self._claimed: Optional[Tuple[int, int]] = None  # (gen, seat)
        self._spare_swap_t0: Optional[float] = None
        # every marker read/write/list routes through the storage
        # backend — with per-slice filesystems the backend (an object
        # store, or its tier-1 fake) IS what makes the `_pod/gen_<g>/`
        # namespace span slices
        self.backend = backend if backend is not None \
            else storage_mod.posix_backend()
        self.sync_every = max(int(sync_every), 1)
        self.peer_timeout_s = float(peer_timeout_s)
        self.step_timeout_s = float(step_timeout_s)
        self.hb_interval_s = float(hb_interval_s)
        self.gather_timeout_s = float(gather_timeout_s)
        self._goodput = goodput
        self._log = log
        self._abort = abort_fn or self._default_abort
        # slice re-admission state (all main-thread only)
        self._rejoining = False
        self._rejoin_target: Optional[int] = None
        self._release_target: Optional[int] = None
        self._align_target: Optional[int] = None
        # set by the resilience wiring to the checkpoint manager's
        # ``wait`` — a survivor drains its in-flight background save
        # before publishing HOLD (see _await_readmission)
        self.drain_fn: Optional[Callable[[], None]] = None
        # EXIT markers older than this coordinator are a PREVIOUS run's
        # completions (the same checkpoint_dir reused to train further)
        # and must not poison this run — see _exited_peers
        self._created_t = time.time()
        self._gen: Optional[int] = None
        self._gen_dir: Optional[str] = None
        self._attempt_wall_t = time.time()
        self._last_polled = -1
        # shared with the watchdog thread (plain attrs: CPython atomic
        # loads/stores; the thread only READS them)
        self._step = 0
        self._progress_t = time.monotonic()
        self._watching = False
        self._escalated = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- marker paths ------------------------------------------------------

    def _gen_path(self, gen: int) -> str:
        return os.path.join(self.directory, f"gen_{gen:06d}")

    def slice_of(self, pi: int) -> int:
        """The slice a pod process lives on: contiguous equal blocks
        (the :func:`slice_identity` layout).  Own index may be
        env-overridden; peers are always the derived map."""
        if pi == self.pi:
            return self.si
        if self.sc <= 1:
            return 0
        return int(pi) * self.sc // self.pc

    def _slice_members(self, si: int) -> List[int]:
        return [p for p in range(self.pc) if self.slice_of(p) == si]

    def _marker_name(self, kind: str, pi: int) -> str:
        """Slice-qualified on multi-slice pods (``FAIL_s001_00002``),
        the bare r10 form otherwise — byte-compatible with existing
        coordination directories."""
        if self.sc > 1:
            return f"{kind}_s{self.slice_of(pi):03d}_{pi:05d}"
        return f"{kind}_{pi:05d}"

    def _marker(self, kind: str, pi: int, gen_dir: Optional[str] = None
                ) -> str:
        return os.path.join(gen_dir or self._require_gen(),
                            self._marker_name(kind, pi))

    def _require_gen(self) -> str:
        if self._gen_dir is None:
            # a caller (direct restore, record_failure before any
            # attempt) outran begin_attempt: join the protocol at the
            # generation begin_attempt would compute
            self.begin_attempt()
        return self._gen_dir

    def _generations(self) -> List[Tuple[int, str]]:
        """Generation dirs discovered through the backend's one-level
        entry listing (an object store has no directories — a
        generation exists once any marker lands in it, which
        begin_attempt's immediate heartbeat guarantees)."""
        gens = set()
        for name in self.backend.list_entries(self.directory):
            m = _GEN_DIR.match(name)
            if m:
                gens.add(int(m.group("gen")))
        return [(g, self._gen_path(g)) for g in sorted(gens)]

    def _failures(self, gen_dir: str) -> Dict[int, dict]:
        out = {}
        for n in self.backend.list_entries(gen_dir):
            m = _FAIL.match(n)
            if m:
                out[int(m.group("pi"))] = self.backend.read_json(
                    os.path.join(gen_dir, n)) or {}
        return out

    # -- restart coordination protocol -------------------------------------

    def begin_attempt(self) -> int:
        """Enter the pod's current generation: 1 + the newest generation
        holding any FAIL marker (0 on a clean directory).  Every host
        computes this from the same shared-backend state, so hosts that
        restarted for DIFFERENT reasons (own crash vs observed peer
        failure) still converge on one generation — and a fresh process
        launched into an old incident's directory joins at the incident's
        next generation rather than rewinding the counter.

        Slice re-admission (r14): when the newest incident's FAIL
        markers are confined to THIS host's slice and re-admission is
        armed, the restarting slice does NOT advance the generation —
        it re-enters the incident's generation in rejoin mode
        (``rejoining`` True) while the surviving slices are parked in
        their ``await_readmission`` hold; :meth:`rejoin_sync` completes
        the handshake.  A second rejoin attempt in the same generation
        (own rejoin residue found) aborts to the whole-pod path via the
        durable ``RJ_ABORT`` marker, so retry ambiguity always degrades
        to the proven r10 protocol rather than a racy re-rejoin."""
        g, newest_fail = 0, None
        for gen, d in self._generations():
            if self._failures(d):
                newest_fail = (gen, d)
                g = gen + 1
        self._rejoining = False
        self._rejoin_target = None
        if (self._readmit_enabled() and newest_fail is not None
                and (self._gen is None or self._gen <= newest_fail[0])):
            gen, d = newest_fail
            fails = self._failures(d)
            if all(self.slice_of(p) == self.si for p in fails):
                mine = os.path.join(d, self._marker_name("RJRENTER", self.pi))
                # r17 warm spares: the seat is arbitrated through ONE
                # atomic point — the same first-writer-wins CLAIM
                # create_if_absent a spare uses.  A check-then-proceed
                # here would race a spare's claim in the gap between
                # this relaunch's begin_attempt and its first durable
                # rejoin marker (both processes would then drive the
                # seat's barriers under one identity), so the ORIGINAL
                # claims its own seat too; losing means a spare owns it.
                if not self._claim_own_seat(d, gen):
                    claim = os.path.join(
                        d, self._marker_name("CLAIM", self.pi))
                    got = self.backend.read_json(claim) or {}
                    raise SeatTaken(
                        f"pod seat {self.pi} (slice {self.si}) was "
                        f"claimed by warm spare "
                        f"{got.get('spare', '?')} in generation {gen} — "
                        f"the spare swapped in for this incident and "
                        f"this relaunch is redundant (re-launch with "
                        f"FDT_SLICE_SPARE to park as the new spare)")
                if self.backend.exists(os.path.join(d, _RJ_ABORT)):
                    pass          # a slice member already aborted rejoin
                elif self.backend.exists(mine):
                    # own rejoin residue: this slice already tried to
                    # rejoin this generation and died mid-handshake —
                    # publish the abort so survivors stop holding, then
                    # take the whole-pod path
                    self._rejoin_abort(d, "rejoin retry in generation "
                                          f"{gen} — falling back")
                else:
                    g = gen
                    self._rejoining = True
        if self._gen is not None:
            if (not self._rejoining and g > self._gen
                    and self._goodput is not None):
                self._goodput.count("restart_generations", g - self._gen)
            g = max(g, self._gen) if not self._rejoining else g
        changed = g != self._gen
        self._gen = g
        self._gen_dir = self._gen_path(g)
        self.backend.ensure_dir(self._gen_dir)
        # an attempting host is by definition not done: clear our own
        # completion marker (a previous run's residue when the same
        # checkpoint_dir is relaunched; peers also time-scope what
        # they honor — _exited_peers)
        self.backend.delete(
            os.path.join(self.directory, self._marker_name("EXIT", self.pi)))
        self._attempt_wall_t = time.time()
        self._last_polled = -1
        self._escalated = False
        self._progress_t = time.monotonic()
        self._write_heartbeat()
        if changed or self._rejoining:
            self._log(f"[pod] host {self.pi}/{self.pc} "
                      + (f"REJOINING generation {g} (slice {self.si} "
                         f"re-admission)" if self._rejoining
                         else f"entering generation {g}"))
        self._ensure_thread()
        self._prune_generations()
        return g

    def _claim_own_seat(self, gen_dir: str, gen: int) -> bool:
        """The relaunched ORIGINAL's side of seat arbitration (r17):
        claim our own seat through the same first-writer-wins
        ``create_if_absent`` a spare uses — winning (or finding our own
        previous claim: a rejoin retry, or the spare re-entering
        begin_attempt post-adoption) means the seat is ours; losing to
        a spare's claim means standing down (SeatTaken at the caller).
        An unreadable existing claim is treated as spare-owned: with
        the seat's ownership ambiguous, a redundant stand-down is safe
        and a double identity is not."""
        if self._claimed == (gen, self.pi):
            return True          # the adopted spare re-entering
        import json
        key = os.path.join(gen_dir, self._marker_name("CLAIM", self.pi))
        try:
            won = self.backend.create_if_absent(
                key, json.dumps({"pi": self.pi, "spare": None,
                                 "unix_time": round(time.time(), 3)}
                                ).encode("utf-8"))
        except OSError:
            return False         # can't arbitrate -> don't take the seat
        if won:
            self._claimed = (gen, self.pi)
            return True
        got = self.backend.read_json(key)
        if got is not None and got.get("spare") is None \
                and got.get("pi") == self.pi:
            # our OWN earlier claim (a previous rejoin attempt of this
            # same relaunched host) — the seat is still ours; the
            # RJRENTER-residue check below decides retry vs RJ_ABORT
            self._claimed = (gen, self.pi)
            return True
        return False

    def record_failure(self, exc: BaseException,
                       step: Optional[int] = None) -> None:
        """Durably publish this host's failure to the pod (atomic marker
        write).  Best-effort: a failing shared fs must not mask the
        original exception."""
        kind = ("hang" if isinstance(exc, StepTimeout)
                else "peer" if isinstance(exc, PeerFailure) else "crash")
        try:
            self._write_fail(kind, f"{type(exc).__name__}: {exc}", step)
        except OSError as e:
            self._log(f"[pod] host {self.pi}: could not write FAIL marker "
                      f"({e!r}) — peers will detect via heartbeat staleness")

    def record_completion(self, step: Optional[int] = None) -> None:
        """Durably mark this host's run COMPLETE (``EXIT_<pi>`` at the
        coordination-directory ROOT, outside any generation, so it
        survives generation pruning).  Written by the supervisor on a
        successful run.  An exited peer is success, not failure: the
        staleness monitor ignores it (hosts finish at slightly
        different times — its heartbeat going quiet must not restart
        the stragglers), but the restore-agreement barrier fails FAST
        on it — a host restarting after a peer already finished can
        never rejoin the pod, and learning that immediately beats
        waiting out gather_timeout_s per attempt."""
        try:
            self.backend.put_json(
                os.path.join(self.directory,
                             self._marker_name("EXIT", self.pi)),
                {"step": self._step if step is None else int(step),
                 "unix_time": round(time.time(), 3)})
        except OSError as e:
            self._log(f"[pod] host {self.pi}: could not write EXIT marker "
                      f"({e!r}) — a later-restarting peer will wait out "
                      f"its restore barrier instead of failing fast")

    def _exited_peers(self) -> List[int]:
        """Peers that completed THIS run: EXIT markers newer than this
        coordinator's creation.  An older marker is a PREVIOUS run's
        completion (the same checkpoint_dir relaunched to train
        further) — honoring it would permanently disable staleness
        detection for that peer and fail fresh restore barriers with
        "pod already finished", so it is ignored (and each host deletes
        its own stale marker in begin_attempt).  The in-process
        supervisor restart — the path the fail-fast exists for — keeps
        its coordinator across attempts, so a peer completing mid-run
        always postdates it.  Cross-host NTP skew (seconds) is noise
        against the run-length gap that separates the two cases."""
        out = []
        for pi in range(self.pc):
            if pi == self.pi:
                continue
            got = self.backend.read_json(
                os.path.join(self.directory, self._marker_name("EXIT", pi)))
            if got is not None and got.get("unix_time", 0.0) > self._created_t:
                out.append(pi)
        return out

    def _write_fail(self, kind: str, reason: str,
                    step: Optional[int] = None) -> None:
        self._write_fail_for(self.pi, kind, reason, step=step)

    def _write_fail_for(self, pi: int, kind: str, reason: str,
                        step: Optional[int] = None) -> None:
        """FAIL marker under a given identity.  Besides our own
        failures, a SURVIVOR writes a proxied marker on behalf of a
        heartbeat-stale peer slice (SIGKILL/machine loss wrote nothing)
        so the relaunched slice finds a durable incident record to key
        its re-admission on."""
        payload = {"kind": kind, "reason": reason[:500],
                   "step": self._step if step is None else int(step),
                   "unix_time": round(time.time(), 3)}
        if pi != self.pi:
            payload["proxied_by"] = self.pi
        self.backend.put_json(self._marker("FAIL", pi), payload)

    def check(self, step: int) -> None:
        """Main-thread poll, called once per dispatch; raises
        :class:`PeerFailure` / :class:`StepTimeout` when the attempt
        must be abandoned.  Cadence-gated with the same boundary-
        crossing algebra as the preemption agreement bit (sync_every;
        robust to K-step dispatch boundaries), EXCEPT after a local
        watchdog escalation, which must surface on the very next poll.

        Multi-slice (r14): a rejoining slice drives its re-admission
        handshake here instead of failure polling (the incident's own-
        slice FAIL markers are residue, not news), and a survivor that
        released from its hold below the agreed target finishes the
        release once it has caught up to it."""
        self._step = int(step)
        self._progress_t = time.monotonic()
        if self._rejoining:
            self.rejoin_sync(step)
            return
        if self._release_target is not None:
            if step >= self._release_target:
                self._finish_release(self._release_target)
            return
        prev, self._last_polled = self._last_polled, step
        if not self._escalated and prev >= 0 \
                and step // self.sync_every <= prev // self.sync_every:
            return
        self._raise_observed_failures()

    def _readmit_enabled(self) -> bool:
        return self.readmit_timeout_s > 0 and self.sc > 1

    def _raise_observed_failures(self) -> None:
        gen_dir = self._require_gen()
        fails = self._failures(gen_dir)
        now = time.time()
        own = fails.pop(self.pi, None)
        if fails:
            peers = sorted(fails)
            newest = max((f.get("unix_time", now) for f in fails.values()),
                         default=now)
            detect = max(now - newest, 0.0)
            failed_slices = {self.slice_of(p) for p in fails}
            if (self._readmit_enabled() and own is None
                    and self.si not in failed_slices
                    and len(failed_slices) == 1):
                # the incident is confined to ONE foreign slice: park in
                # a bounded hold and let the platform restart + re-admit
                # that slice, instead of burning a whole-pod restart
                if self._goodput is not None:
                    self._goodput.add("detect_s", detect)
                self._await_readmission(set(fails), failed_slices.pop())
                return
            if self._goodput is not None:
                self._goodput.count("peer_failures")
                self._goodput.add("detect_s", detect)
            raise PeerFailure(
                f"host(s) {peers} failed in generation {self._gen} "
                f"({fails[peers[0]].get('kind', '?')}: "
                f"{fails[peers[0]].get('reason', '?')}); abandoning this "
                f"attempt so the pod restarts together "
                f"(observed {detect:.2f}s after the marker landed)")
        if own is not None:
            # our OWN marker with nobody else's: the watchdog escalated a
            # local hang and the abort was intercepted (test harness) —
            # surface it as the restartable fault it is
            raise StepTimeout(
                f"host {self.pi}: step watchdog escalated "
                f"({own.get('reason', 'no step progress')}); restarting")
        stale = self._stale_peers(now)
        if stale:
            pi0, age = stale[0]
            stale_slices = {self.slice_of(p) for p, _a in stale}
            if (self._readmit_enabled() and self.si not in stale_slices
                    and len(stale_slices) == 1):
                # a silently-dead foreign slice (SIGKILL/machine loss —
                # nothing was written): publish proxied FAIL markers so
                # the relaunched slice finds the incident record it
                # keys its rejoin on, then hold for re-admission
                if self._goodput is not None:
                    self._goodput.add("detect_s", age)
                for p, a in stale:
                    try:
                        self._write_fail_for(
                            p, "stale",
                            f"heartbeat silent {a:.1f}s > peer_timeout_s="
                            f"{self.peer_timeout_s:.0f} (proxied)")
                    except OSError:
                        pass
                self._await_readmission({p for p, _a in stale},
                                        stale_slices.pop())
                return
            if self._goodput is not None:
                self._goodput.count("peer_failures")
                # detect_s = failure-to-observed latency.  The peer died
                # (silently — no FAIL marker) at roughly its last
                # heartbeat, so the full silence AGE is the latency
                # (over-estimates by at most hb_interval_s); it is
                # necessarily >= peer_timeout_s — a silent death cannot
                # be detected faster than the staleness threshold
                self._goodput.add("detect_s", age)
            raise PeerFailure(
                f"host(s) {[p for p, _ in stale]} heartbeat-stale "
                f"(oldest {age:.1f}s > peer_timeout_s="
                f"{self.peer_timeout_s:.0f}) in generation {self._gen} — "
                f"treating as dead and restarting the pod")

    def _stale_peers(self, now: float) -> List[Tuple[int, float]]:
        """[(peer index, silence age)] for peers silent past the
        timeout.  A missing heartbeat is aged from this attempt's start
        (peers that merely haven't launched yet get the same grace as
        slow first heartbeats)."""
        if self.pc <= 1 or self.peer_timeout_s <= 0:
            return []
        gen_dir = self._require_gen()
        exited = set(self._exited_peers())
        out = []
        for pi in range(self.pc):
            if pi == self.pi or pi in exited:
                # an exited peer FINISHED — its quiet heartbeat is
                # success, not death; stragglers keep running
                continue
            try:
                t = self.backend.mtime(self._marker("HB", pi, gen_dir))
            except OSError:
                t = self._attempt_wall_t
            age = now - t
            if age > self.peer_timeout_s:
                out.append((pi, age))
        return out

    # -- slice-granular elastic re-admission (r14) -------------------------

    @property
    def rejoining(self) -> bool:
        """True while this host's slice is re-entering the incident's
        generation: restore + catch-up to the survivors' agreed step,
        completed by :meth:`rejoin_sync`."""
        return self._rejoining

    @property
    def saves_suspended(self) -> bool:
        """True while this host must not take checkpoint-cadence ticks:
        a rejoining slice catching up, or a released survivor still
        below the agreed target.  A save tick taken here could never
        commit — the rest of the pod is not taking it — and would only
        burn the commit-barrier timeout into a counted save failure."""
        return self._rejoining or self._release_target is not None

    def consume_cadence_align(self) -> Optional[int]:
        """One-shot: the step every host re-anchors its checkpoint
        cadence to after a completed re-admission (the train loop feeds
        it to ``AsyncCheckpointManager.align_cadence``).  Hold and
        catch-up phases suppressed different ticks on different hosts;
        re-anchoring everyone at the agreed target restores the "pure
        function of the step sequence" property the pod's two-phase
        commit barrier depends on."""
        t, self._align_target = self._align_target, None
        return t

    def _await_readmission(self, fail_pis: set, failed_si: int) -> None:
        """Survivor side: the incident is confined to ONE foreign
        slice, so instead of raising :class:`PeerFailure` (whole-pod
        restart), park at this dispatch boundary in a bounded hold —
        publish a ``HOLD`` marker carrying our step (the rejoiner's
        catch-up target is the max over all survivors' holds), then
        poll for the restarted slice's ``RJREADY`` barrier.  Falls back
        to the whole-pod restart on timeout, on a rejoin abort, or on
        any additional failure outside the incident slice.  The local
        hang watchdog is paused for the duration (parked is not wedged;
        heartbeats keep proving liveness to the peers)."""
        gen_dir = self._require_gen()
        members = self._slice_members(failed_si)
        t0 = time.monotonic()
        deadline = t0 + self.readmit_timeout_s
        self._log(f"[pod] host {self.pi}: slice {failed_si} failed "
                  f"(host(s) {sorted(fail_pis)}); holding at step "
                  f"{self._step} for re-admission "
                  f"(timeout {self.readmit_timeout_s:.0f}s)")
        target = None
        try:
            with self.pause_watch():
                # drain this host's in-flight background save BEFORE
                # publishing HOLD: the rejoiners gate their restore
                # walk on the COMPLETE hold set, so "every HOLD
                # present" must imply "every survivor's durable writes
                # (including process 0's COMMIT) have landed or
                # terminally failed" — without this, a rejoiner can
                # walk mid-commit and its slice peers disagree on the
                # newest checkpoint (RestoreDivergence burns the whole
                # re-admission).  A drain stuck on a dead slice's DONE
                # barrier is bounded by the manager's commit timeout;
                # exceeding the rejoiners' hold window degrades to the
                # whole-pod fallback, never to divergence.
                if self.drain_fn is not None:
                    try:
                        self.drain_fn()
                    except Exception:
                        pass     # a failed save is already counted
                try:
                    self.backend.put_json(self._marker("HOLD", self.pi),
                                          {"step": self._step})
                except OSError as e:
                    self._readmit_fallback(
                        f"could not publish HOLD marker: {e!r}")
                while True:
                    if self.backend.exists(os.path.join(gen_dir, _RJ_ABORT)):
                        self._readmit_fallback(
                            "the restarting slice aborted its rejoin")
                    fails = self._failures(gen_dir)
                    fails.pop(self.pi, None)
                    extra = sorted(p for p in fails
                                   if self.slice_of(p) != failed_si)
                    if extra:
                        self._readmit_fallback(
                            f"additional failure on host(s) {extra}")
                    readys = [self.backend.read_json(
                        self._marker("RJREADY", p, gen_dir))
                        for p in members]
                    if readys and all(r is not None for r in readys):
                        target = max(int(r["step"]) for r in readys)
                        break
                    if time.monotonic() > deadline:
                        self._readmit_fallback(
                            f"re-admission timed out after "
                            f"{self.readmit_timeout_s:.0f}s")
                    time.sleep(0.05)
        finally:
            # parked time is badput either way (released or fallen
            # back) — the slice-MTTR hold component
            if self._goodput is not None:
                self._goodput.add("readmission_hold_s",
                                  time.monotonic() - t0)
        if self._step >= target:
            self._finish_release(target)
        else:
            # parked below the pod's agreed target (we observed the
            # failure earlier than a faster peer): resume stepping with
            # saves suspended and finish the release at the target
            self._release_target = int(target)
            self._log(f"[pod] host {self.pi}: released from hold at step "
                      f"{self._step}; catching up to the agreed step "
                      f"{target}")

    def _readmit_fallback(self, why: str) -> None:
        if self._goodput is not None:
            self._goodput.count("pod_fallback_restarts")
            self._goodput.count("peer_failures")
        raise PeerFailure(
            f"slice re-admission failed in generation {self._gen} ({why}) "
            f"— falling back to a whole-pod restart")

    def rejoin_sync(self, step: int) -> None:
        """Rejoining-slice side of re-admission, driven from the
        attempt path (right after restore — the target may already be
        reached) and from :meth:`check` during catch-up.  First call
        agrees the catch-up target (max over the survivors' HOLD
        steps — provably >= the restored checkpoint step, since a
        commit at step S implies every host passed S); once this
        host's step reaches it, the slice joins its ``RJREADY``
        readiness barrier and every pod host releases: the generation
        advances IN PLACE (fresh marker namespace, no restart) and
        training resumes from the agreed step."""
        if not self._rejoining:
            return
        self._step = int(step)
        if self._rejoin_target is None:
            self._rejoin_target = self._agree_rejoin_target()
        target = self._rejoin_target
        if step < target:
            return
        gen_dir = self._require_gen()
        members = self._slice_members(self.si)
        self.backend.put_json(self._marker("RJREADY", self.pi),
                              {"step": int(target)})
        deadline = time.monotonic() + self.readmit_timeout_s
        with self.pause_watch():
            while True:
                readys = [self.backend.read_json(
                    self._marker("RJREADY", p, gen_dir)) for p in members]
                if all(r is not None for r in readys):
                    break
                foreign = sorted(
                    p for p in self._failures(gen_dir)
                    if self.slice_of(p) != self.si)
                if foreign:
                    self._rejoin_fallback(
                        gen_dir, f"host(s) {foreign} failed during "
                                 f"re-admission")
                if time.monotonic() > deadline:
                    self._rejoin_fallback(
                        gen_dir, "slice readiness barrier timed out")
                time.sleep(0.05)
        self._rejoining = False
        self._rejoin_target = None
        self._finish_release(target)

    def _agree_rejoin_target(self) -> int:
        """The catch-up step: max over every survivor's HOLD marker
        (bounded wait for the complete set — survivors publish within
        one poll cadence of the incident)."""
        gen_dir = self._require_gen()
        survivors = [p for p in range(self.pc)
                     if self.slice_of(p) != self.si]
        deadline = time.monotonic() + self.readmit_timeout_s
        with self.pause_watch():
            while True:
                holds = [self.backend.read_json(
                    self._marker("HOLD", p, gen_dir)) for p in survivors]
                if holds and all(h is not None for h in holds):
                    return max(int(h["step"]) for h in holds)
                foreign = sorted(
                    p for p in self._failures(gen_dir)
                    if self.slice_of(p) != self.si)
                if foreign:
                    self._rejoin_fallback(
                        gen_dir, f"surviving host(s) {foreign} failed "
                                 f"while agreeing the catch-up target")
                if time.monotonic() > deadline:
                    self._rejoin_fallback(
                        gen_dir, "survivors never published their HOLD "
                                 "markers")
                time.sleep(0.05)

    def _rejoin_fallback(self, gen_dir: str, why: str) -> None:
        """Rejoiner-side fallback: durably abort (so parked survivors
        release into the whole-pod path immediately instead of waiting
        out their hold) and raise the restartable failure."""
        self._rejoining = False
        self._rejoin_target = None
        self._rejoin_abort(gen_dir, why)
        if self._goodput is not None:
            self._goodput.count("pod_fallback_restarts")
        raise PeerFailure(
            f"slice {self.si} re-admission failed in generation "
            f"{self._gen} ({why}) — falling back to a whole-pod restart")

    def _rejoin_abort(self, gen_dir: str, why: str) -> None:
        import json
        try:
            self.backend.create_if_absent(
                os.path.join(gen_dir, _RJ_ABORT),
                json.dumps({"pi": self.pi, "why": why[:300],
                            "unix_time": round(time.time(), 3)}
                           ).encode("utf-8"))
        except OSError:
            pass     # survivors still fall back via their hold timeout

    def _finish_release(self, target: int) -> None:
        """Completion of a re-admission, symmetric on every host:
        advance to the next generation IN PLACE (fresh marker
        namespace — the incident's FAIL/HOLD/RJREADY residue stays
        behind in the old one, which any later whole-pod restart
        computes past anyway), refresh the liveness clocks, and expose
        the cadence re-align target for the train loop."""
        self._release_target = None
        self._align_target = int(target)
        if self._goodput is not None:
            self._goodput.count("slice_readmissions")
        if self._spare_swap_t0 is not None:
            # r17: this host is a warm spare completing its first
            # release after claiming a seat — the claim→release wall
            # time IS the swap (restore + catch-up + readiness barrier;
            # programs were warmed while parked), the number the
            # warm_spare_swap_s bench arm commits.  Tracked beside the
            # badput segments, not among them: the window contains the
            # restore segment and productive catch-up steps.
            if self._goodput is not None:
                self._goodput.add_warm_spare_swap(
                    time.monotonic() - self._spare_swap_t0)
                self._goodput.count("warm_spare_swaps")
            self._spare_swap_t0 = None
        g = (self._gen or 0) + 1
        self._gen = g
        self._gen_dir = self._gen_path(g)
        self.backend.ensure_dir(self._gen_dir)
        # peers complete their release at their own pace: age their
        # missing heartbeats in the new generation from NOW, not from
        # the attempt start, or a slow releaser would look stale
        self._attempt_wall_t = time.time()
        self._last_polled = -1
        self._progress_t = time.monotonic()
        self._write_heartbeat()
        self._log(f"[pod] host {self.pi}: slice re-admission complete at "
                  f"step {target}; advancing to generation {g} in place")

    # -- warm spares (r17) -------------------------------------------------

    def spare_wait(self, refresh_fn: Optional[Callable[[], None]] = None,
                   stop_fn: Optional[Callable[[], bool]] = None,
                   poll_s: float = 0.1) -> Optional[dict]:
        """Park this STANDBY process (``spare_index`` armed) until a
        seat is claimable: heartbeat at the coordination-dir root
        (``SPAREHB_<id>`` — never parsed as a member heartbeat), call
        ``refresh_fn`` each poll (the caller's "re-restore params at
        each new COMMIT" hook — an optimization, never fatal), and scan
        the NEWEST generation for an incident confined to one slice
        whose survivors have all published HOLD.  Returns the claim
        dict after :meth:`_adopt_seat`, or None when the pod completed
        (every member's time-scoped EXIT marker present) or ``stop_fn``
        fired.

        Claiming waits for the COMPLETE survivor HOLD set first: holds
        prove the survivors drained their in-flight saves and committed
        to re-admission — claiming earlier would race the whole-pod
        restart path on an incident the survivors may classify
        differently.  Every ambiguous corner after the claim (missing
        co-spares for a multi-seat slice, survivor failure, timeout)
        rides the existing rejoin machinery and degrades to the durable
        ``RJ_ABORT`` whole-pod fallback."""
        if self.spare_index is None:
            raise RuntimeError("spare_wait on a non-spare coordinator")
        last_hb = 0.0
        while True:
            if stop_fn is not None and stop_fn():
                return None
            now = time.time()
            if now - last_hb >= self.hb_interval_s:
                try:
                    self.backend.put_json(
                        os.path.join(self.directory,
                                     f"SPAREHB_{self.spare_index:03d}"),
                        {"unix_time": round(now, 3)})
                except OSError:
                    pass
                last_hb = now
            if refresh_fn is not None:
                try:
                    refresh_fn()
                except Exception as e:
                    self._log(f"[spare] refresh failed ({e!r}); the swap "
                              f"will restore cold instead")
            done = 0
            for p in range(self.pc):
                got = self.backend.read_json(
                    os.path.join(self.directory,
                                 self._marker_name("EXIT", p)))
                # time-scoped like _exited_peers (previous-run residue in
                # a reused dir must not send a fresh spare home), with a
                # 10 ms tolerance: EXIT times are written rounded to the
                # millisecond, so a completion landing in the same
                # millisecond this coordinator was created could round
                # BELOW _created_t and park the spare forever — the
                # residue gap the scoping guards against is run-LENGTH,
                # not milliseconds
                if got is not None and got.get(
                        "unix_time", 0.0) > self._created_t - 0.01:
                    done += 1
            if done == self.pc:
                self._log(f"[spare] spare {self.spare_index}: pod "
                          f"completed without an incident; standing down")
                return None
            claim = self._spare_try_claim()
            if claim is not None:
                return claim
            time.sleep(poll_s)

    def _spare_try_claim(self) -> Optional[dict]:
        gens = self._generations()
        if not gens:
            return None
        gen, d = gens[-1]       # only the newest generation can hold a
        #                         live incident — a released or restarted
        #                         pod has already created a newer one
        fails = self._failures(d)
        if not fails or self.backend.exists(os.path.join(d, _RJ_ABORT)):
            return None
        failed_slices = {self.slice_of(p) for p in fails}
        if len(failed_slices) != 1:
            return None         # multi-slice incident: whole-pod territory
        si = failed_slices.pop()
        members = self._slice_members(si)
        survivors = [p for p in range(self.pc) if self.slice_of(p) != si]
        if not survivors:
            return None         # a whole-pod death has nothing to hold
        for p in survivors:
            if self.backend.read_json(self._marker("HOLD", p, d)) is None:
                return None     # survivors not (yet) parked for re-admission
        import json
        for p in members:
            if self.backend.exists(
                    os.path.join(d, self._marker_name("RJRENTER", p))):
                # the real slice is already rejoining this seat —
                # stand down rather than race it
                return None
            key = os.path.join(d, self._marker_name("CLAIM", p))
            try:
                won = self.backend.create_if_absent(
                    key, json.dumps({"pi": p, "spare": self.spare_index,
                                     "unix_time": round(time.time(), 3)}
                                    ).encode("utf-8"))
            except OSError:
                return None
            if won:
                self._adopt_seat(p, si, gen, d)
                return {"seat": p, "slice": si, "generation": gen}
        return None             # every seat already claimed by other spares

    def _adopt_seat(self, seat: int, si: int, gen: int,
                    gen_dir: str) -> None:
        """The spare becomes pod process ``seat``: pi/si re-key to the
        claimed member identity, the coordinator enters the incident's
        generation in REJOIN mode (the same machinery a relaunched
        slice uses — restore through the slice-scoped barrier, catch up
        to the survivors' agreed step, join RJREADY), and member
        heartbeats start under the adopted name so the pod sees the
        seat alive again."""
        self._log(f"[spare] spare {self.spare_index} CLAIMED seat {seat} "
                  f"(slice {si}, generation {gen}); swapping in")
        self.pi = int(seat)
        self.si = int(si)
        self._gen = int(gen)
        self._gen_dir = gen_dir
        self._claimed = (int(gen), int(seat))
        self._rejoining = True
        self._rejoin_target = None
        self._spare_swap_t0 = time.monotonic()
        if self._goodput is not None:
            self._goodput.count("warm_spare_claims")
        self._attempt_wall_t = time.time()
        self._last_polled = -1
        self._escalated = False
        self._progress_t = time.monotonic()
        self._write_heartbeat()
        self._ensure_thread()

    # -- restore step agreement (fs-simulated pods) ------------------------

    def gather_restored_step(self, step: int,
                             phase: str = "agree") -> np.ndarray:
        """Span-wrapped ("rendezvous" — barrier waits are the pod
        restore's dominant cost and telemetry must attribute them):
        see :meth:`_gather_restored_step_impl`."""
        from faster_distributed_training_tpu.telemetry import spans
        with spans.span("rendezvous"):
            return self._gather_restored_step_impl(step, phase)

    def _gather_restored_step_impl(self, step: int,
                                   phase: str = "agree") -> np.ndarray:
        """Filesystem allgather of every host's restored checkpoint step
        (−1 = nothing restored) — the restore agreement barrier for
        fs-SIMULATED pods, where jax is single-process per host and the
        manager's real ``all_gather_across_processes`` would see only
        itself.  Same rendezvous property as the collective: every host
        blocks here until all have joined (so process 0's pre-agreement
        residue sweep stays race-free), and a FAIL marker or timeout
        raises :class:`PeerFailure` instead of deadlocking on a host
        that died mid-restore.  ``phase`` names the barrier — the
        manager enters twice per restore ("enter" = pre-walk
        rendezvous after draining in-flight writes, "agree" = the
        post-walk step agreement), and each phase needs its own marker
        file.  One restore per generation (the supervisor wiring
        guarantees it — each attempt enters a fresh generation after
        any failure).

        Slice re-admission (r14): while rejoining, the barrier spans
        only THIS slice's hosts (the survivors are parked in their
        hold, not restoring) under ``RJ``-prefixed marker names — the
        original attempt's whole-pod RESTORE markers in the same
        generation are not re-read; the incident slice's own FAIL
        residue is expected and ignored, and any failure path aborts
        the rejoin durably so the survivors fall back fast."""
        gen_dir = self._require_gen()
        kind = "RESTORE" if phase == "agree" else f"R{phase.upper()}"
        members = list(range(self.pc))
        if self._rejoining:
            kind = "RJ" + kind
            members = self._slice_members(self.si)
            if phase == "enter" and self._rejoin_target is None:
                # BEFORE the restore walk: wait for the COMPLETE
                # survivor HOLD set.  Each survivor drains its in-flight
                # background save before publishing HOLD, so once all
                # holds exist the committed-checkpoint frontier is
                # frozen (survivors are parked, process 0's commit
                # either landed or terminally failed) and every member
                # of this slice walks the SAME newest checkpoint —
                # without the gate, a walk racing process 0's
                # background COMMIT splits the slice on
                # RestoreDivergence and burns the re-admission.
                self._rejoin_target = self._agree_rejoin_target()
        self.backend.put_json(self._marker(kind, self.pi),
                              {"step": int(step)})
        deadline = time.monotonic() + self.gather_timeout_s
        while True:
            vals = []
            for pi in members:
                got = self.backend.read_json(self._marker(kind, pi, gen_dir))
                if got is None:
                    break
                vals.append(got["step"])
            else:
                return np.asarray(vals, np.int32)
            fails = {p: f for p, f in self._failures(gen_dir).items()
                     if p != self.pi
                     and (not self._rejoining
                          or self.slice_of(p) != self.si)}
            if fails:
                if self._rejoining:
                    self._rejoin_fallback(
                        gen_dir, f"host(s) {sorted(fails)} failed while "
                                 f"this slice was restoring")
                raise PeerFailure(
                    f"host(s) {sorted(fails)} failed while this host was "
                    f"waiting in the restore-agreement barrier "
                    f"(generation {self._gen})")
            done = [p for p in self._exited_peers() if p in members
                    and self.backend.read_json(
                        self._marker(kind, p, gen_dir)) is None]
            if done:
                # a peer that already COMPLETED the run will never join
                # this barrier — fail fast (every retry will fail the
                # same way until the restart budget runs out, each in
                # milliseconds instead of a full gather timeout)
                raise PeerFailure(
                    f"host(s) {done} already completed the run (EXIT "
                    f"marker) and can never join the generation "
                    f"{self._gen} restore barrier — the pod finished "
                    f"without this host; restore the final checkpoint "
                    f"manually or rerun against a fresh directory")
            if time.monotonic() > deadline:
                if self._rejoining:
                    self._rejoin_fallback(
                        gen_dir, f"slice restore barrier timed out after "
                                 f"{self.gather_timeout_s:.0f}s")
                raise PeerFailure(
                    f"restore-agreement barrier timed out after "
                    f"{self.gather_timeout_s:.0f}s in generation "
                    f"{self._gen}: {len(members) - len(vals)} host(s) "
                    f"never joined")
            time.sleep(0.05)

    # -- health watchdog ---------------------------------------------------

    def watch_steps(self):
        """Context manager arming the local step watchdog for an epoch's
        dispatch loop (heartbeats run regardless; only the no-progress
        escalation is scoped, so eval/restore phases can't false-
        trigger).  ``step_timeout_s`` must exceed the worst-case
        (re)compile of one dispatch — it defaults to 0 (off)."""
        from contextlib import contextmanager

        @contextmanager
        def _ctx():
            self._progress_t = time.monotonic()
            self._watching = True
            try:
                yield
            finally:
                self._watching = False
        return _ctx()

    def pause_watch(self):
        """Context manager suspending the LOCAL no-progress escalation
        around legitimate blocking work on the step thread — cadence
        saves that drain a prior write's commit barrier (up to
        commit_timeout_s, typically far beyond any sane
        step_timeout_s), the preemption emergency save — so a healthy
        host is never SIGKILLed mid-save.  Heartbeats keep running (the
        host IS alive, the peers must see that), and a genuinely
        wedged save stays bounded by its own timeout (TimeoutError →
        counted save failure) rather than needing the watchdog.  The
        step clock restarts fresh on resume."""
        from contextlib import contextmanager

        @contextmanager
        def _ctx():
            was = self._watching
            self._watching = False
            try:
                yield
            finally:
                self._progress_t = time.monotonic()
                self._watching = was
        return _ctx()

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._watchdog_body, name=f"fdt-pod-wd-{self.pi}",
                daemon=True)
            self._thread.start()

    def _watchdog_body(self) -> None:
        while not self._stop.wait(self.hb_interval_s):
            try:
                self._write_heartbeat()
            except OSError:
                pass  # a flaky shared fs must not kill the watchdog
            if (self._watching and not self._escalated
                    and self.step_timeout_s > 0
                    and time.monotonic() - self._progress_t
                    > self.step_timeout_s):
                self._escalate_hang()

    def _write_heartbeat(self) -> None:
        if self._gen_dir is None:
            return
        self.backend.put_json(self._marker("HB", self.pi),
                              {"step": self._step,
                               "unix_time": round(time.time(), 3)})

    def _escalate_hang(self) -> None:
        """Watchdog-thread escalation: the main thread has made no step
        progress for step_timeout_s — it is wedged in a dispatch or a
        collective and cannot raise for itself.  Publish the failure
        durably FIRST (so the peers restart even if the abort below is
        instant), then abort."""
        self._escalated = True
        stuck = time.monotonic() - self._progress_t
        reason = (f"no step progress for {stuck:.1f}s "
                  f"(> step_timeout_s={self.step_timeout_s:.0f}) "
                  f"at step {self._step}")
        try:
            self._write_fail("hang", reason)
        except OSError:
            pass  # peers fall back to heartbeat staleness
        if self._goodput is not None:
            self._goodput.count("step_timeouts")
        self._log(f"[pod] host {self.pi}: WATCHDOG: {reason}; FAIL marker "
                  f"written, aborting so the pod converges on a restart")
        # crash flight recorder: the SIGKILL below destroys everything
        # this process knows — the unflushed telemetry ring, which span
        # the main thread is wedged inside, the program table.  Dump it
        # from a side thread with a BOUNDED join: a wedged shared fs
        # (plausibly the same one that hung the step) must not veto the
        # abort the peers are waiting on.
        try:
            from faster_distributed_training_tpu.telemetry import flight
            if flight.configured():
                t = threading.Thread(
                    target=flight.emergency_dump,
                    args=("watchdog_abort",),
                    kwargs={"step": self._step,
                            "extra": {"watchdog_reason": reason}},
                    daemon=True)
                t.start()
                t.join(timeout=2.0)
        except Exception:
            pass
        self._abort(reason)

    @staticmethod
    def _default_abort(reason: str) -> None:
        # SIGKILL, not sys.exit/os._exit: the main thread may be wedged
        # inside a device runtime call holding locks that Python-level
        # teardown (atexit, GC finalizers, PJRT client destructors) would
        # deadlock on.  Nothing softer is guaranteed to terminate a
        # process whose main thread is stuck in C.
        os.kill(os.getpid(), signal.SIGKILL)

    # -- housekeeping ------------------------------------------------------

    def _prune_generations(self, keep: int = 3) -> None:
        """Old generation dirs are a few marker files each; process 0
        sweeps all but the newest ``keep`` so a long-lived flaky pod
        doesn't accumulate thousands of dirs.  Kept generations must
        include every one a lagging peer could still be reading (a peer
        is at most one incident behind — it restarts the moment it
        observes the newest FAIL markers)."""
        if self.pi != 0 or self._gen is None:
            return
        for gen, d in self._generations():
            if gen <= self._gen - keep:
                self.backend.delete_prefix(d)

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.hb_interval_s + 5.0)
            self._thread = None
