"""Async, cadence-driven checkpoint manager.

Layered on ``train/checkpoint.py``'s save/restore (the ISSUE's
prescription — orbax arrays + meta.json + atomic COMMIT marker), adding
the four things a preemptible-pod run needs that the epoch-level
checkpoints don't give:

  * WHEN to save — step cadence (``every_steps``) and/or wall-clock
    cadence (``every_secs``), whichever fires first;
  * OFF the critical path — the save splits into a blocking snapshot
    (``jax.device_get`` of the state, unavoidable: the very next train
    step donates those buffers) and the orbax serialization + disk
    write, which run on a single background worker.  Only the snapshot
    time touches step latency; bench.py's ``ckpt_async_*`` arms measure
    it at <1% of median step time;
  * keep-last-K retention — committed checkpoints beyond ``keep`` are
    pruned after each successful commit, and uncommitted residue
    (half-written directories from a previous crash) is swept;
  * newest-VALID restore — :meth:`restore_latest` walks committed
    checkpoints newest-first and falls back past any that fail to
    restore (corrupt/truncated data with an intact marker), so one bad
    write can never wedge recovery.

Multi-host: ``device_get`` can only fetch addressable shards — so the
multi-host async path doesn't try to: each process snapshots ONLY its
addressable shards (``checkpoint.host_shard_snapshot``, replica-0-owned
for a globally disjoint exact cover) and a background writer per
process streams them to a per-host shard file; process 0 writes the
``COMMIT`` marker only after a cross-host completion barrier (every
host's ``DONE`` marker on the shared checkpoint filesystem) — the
two-phase commit that keeps a partially-written pod save invisible to
restore.  Pods therefore get off-critical-path saves exactly like
single hosts (the r7 sync-collective fallback is gone; ``sync=True``
emergency saves keep the collective orbax path, whose entry is already
cross-host-agreed by the preemption bit).  Restore reassembles from the
per-host shard files and still reads pre-existing single-file orbax
checkpoints.  Only the STEP cadence is honored multi-host — a pure
function of the step counter, identical on every host, so every host
enters the same save; the wall-clock cadence reads per-host clocks that
can disagree near a threshold (hosts would write shard sets nobody
commits) and is disabled multi-host (warned).
"""

from __future__ import annotations

import os
import re
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, List, Optional, Tuple

import jax
import numpy as np

from faster_distributed_training_tpu.resilience import storage as storage_mod
from faster_distributed_training_tpu.telemetry import spans
from faster_distributed_training_tpu.train import checkpoint as ckpt

_STEP_DIR = re.compile(r"^(?P<prefix>.+)_step_(?P<step>\d{9})$")


class RestoreDivergence(RuntimeError):
    """Pod hosts restored DIFFERENT checkpoint steps (one host's
    fallback walk diverged from its peers') — resuming would train on
    divergent state; see AsyncCheckpointManager._verify_restore_agreement."""


def _local_delete_tree(path: str) -> None:
    """Historic default retention deleter (local/NFS recursive tree
    delete), kept for callers that installed it as a ``delete_fn`` hook.
    Retention now routes through the storage backend's BATCHED
    ``delete_prefix`` (r14 — the rmtree-per-dir idiom did not map to
    GCS; list-prefix + batched object deletes is the portable shape),
    and on POSIX that is exactly this rmtree."""
    storage_mod.posix_backend().delete_prefix(path)


class AsyncCheckpointManager:
    """Owns `<directory>/<prefix>_step_<N>` checkpoints.

    Not thread-safe for concurrent maybe_save callers (the train loop is
    single-threaded); the background worker only touches the host
    snapshot handed to it.

    ``process_index``/``process_count`` default to the real runtime and
    exist as the simulation seam the tier-1 tests use (two managers in
    one process, complementary ``shard_owner`` functions, one shared
    directory = a simulated two-host pod save).  ``force_sharded``
    routes even a single-process manager down the per-host shard-
    streaming path (bench's ``ckpt_async_sharded`` arm)."""

    def __init__(self, directory: str, prefix: str = "ckpt",
                 every_steps: int = 0, every_secs: float = 0.0,
                 keep: int = 3, async_save: bool = True,
                 goodput=None, log: Callable[[str], None] = print,
                 delete_fn: Optional[Callable[[str], None]] = None,
                 force_sharded: bool = False,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None,
                 shard_owner: Optional[Callable] = None,
                 commit_timeout_s: float = 600.0,
                 step_gather_fn: Optional[Callable] = None,
                 backend: Optional[storage_mod.StorageBackend] = None):
        self.directory = os.path.abspath(directory)
        self.prefix = prefix
        self.every_steps = int(every_steps)
        self.every_secs = float(every_secs)
        self._pc = (jax.process_count() if process_count is None
                    else int(process_count))
        self._pi = (jax.process_index() if process_index is None
                    else int(process_index))
        # the storage backend every durable write/list/delete routes
        # through (r14): posix by default — byte-compatible with every
        # pre-r14 checkpoint dir.  A non-posix backend has no rename
        # primitive, so the orbax single-file path (which stages +
        # renames internally) is unusable: force the sharded two-phase
        # path, whose writes are all whole-object puts.
        self.backend = backend if backend is not None \
            else storage_mod.posix_backend()
        # per-host shard-streaming saves whenever >1 process (the r7
        # sync-collective fallback is gone), or forced for bench/tests,
        # or whenever the backend is not plain POSIX (see above)
        self._sharded = (bool(force_sharded) or self._pc > 1
                         or self.backend.kind != "posix")
        self._shard_owner = shard_owner
        self._commit_timeout_s = float(commit_timeout_s)
        # restore step-agreement transport override: fs-SIMULATED pods
        # (jax single-process per host) pass the pod coordinator's
        # marker-file allgather here; real pods keep the jax collective
        self._step_gather_fn = step_gather_fn
        self._delete = delete_fn or self.backend.delete_prefix
        if self.every_secs and self._pc > 1:
            # the wall-clock term reads each host's OWN monotonic clock,
            # so near a threshold hosts disagree: with the sharded path
            # a lone host writes a shard set nobody ever commits (and
            # the sync emergency path would deadlock its collective).
            # Only the step term is a pure function every host agrees
            # on.
            self.every_secs = 0.0
            if self._pi == 0:
                log("[ckpt] --checkpoint_every_secs is per-host-clock-"
                    "nondeterministic: hosts near a threshold would "
                    "disagree and write shard sets that never commit; "
                    "disabled — use the step cadence (--checkpoint_every)")
        self.keep = max(int(keep), 1)
        self.async_save = bool(async_save)
        self._goodput = goodput
        self._base_log = log
        self._log = log if self._pi == 0 else (lambda *_: None)
        self._last_save_t = time.monotonic()
        self._last_save_step: Optional[int] = None
        self._pool: Optional[ThreadPoolExecutor] = None
        self._inflight: Optional[Future] = None
        self._inflight_path: Optional[str] = None
        self._skip_logged = False
        self.backend.ensure_dir(self.directory)

    # -- cadence ----------------------------------------------------------

    def should_save(self, step: int) -> bool:
        """Multi-host, only the STEP term is live (a pure function of
        the observed step sequence, identical on every host — what keeps
        the collective save deadlock-free); the per-host wall-clock term
        is disabled at construction there.  Single-process runs use both.

        The step term fires when `step` has CROSSED an every_steps
        boundary since the last save — exact multiples for the classic
        per-step loop (identical behavior), and the first dispatch
        boundary at-or-past each multiple under a K-step fused dispatch,
        whose ticks only land at steps K, 2K, … (cli rounds
        checkpoint_every up to a multiple of K so the two coincide; the
        crossing form keeps cadence robust for epoch-tail dispatches of
        size < K, which shift every later boundary off the multiples)."""
        if step <= 0 or step == self._last_save_step:
            return False
        if self.every_steps:
            anchor = self._last_save_step or 0
            if anchor > step:
                # the step counter moved BACKWARD (auto-recover rolled
                # the state back to an epoch snapshot taken outside this
                # manager): a stale forward anchor would silence the
                # cadence for the whole replay window — reset so the
                # replay is checkpointable immediately
                anchor = 0
            if step // self.every_steps > anchor // self.every_steps:
                return True
        if self.every_secs:
            return time.monotonic() - self._last_save_t >= self.every_secs
        return False

    # -- saving -----------------------------------------------------------

    def maybe_save(self, state, step: int, epoch: int = 0,
                   step_in_epoch: int = 0, best_acc: float = 0.0) -> bool:
        if not self.should_save(step):
            return False
        return self.save(state, step, epoch=epoch,
                         step_in_epoch=step_in_epoch, best_acc=best_acc)

    def save(self, state, step: int, epoch: int = 0, step_in_epoch: int = 0,
             best_acc: float = 0.0, sync: bool = False,
             segment: str = "checkpoint_blocking_s") -> bool:
        """Checkpoint `state` at `step`.  Async (default): snapshot on
        the caller's thread, serialize + commit in the background; one
        save in flight at a time — a cadence tick that lands while the
        previous write is still running is SKIPPED (counted, never
        queued: a slow filesystem must not grow an unbounded backlog of
        full-state snapshots in host memory).  sync=True (emergency
        save path) waits for any in-flight write first and blocks until
        committed."""
        meta = {"step": int(step), "epoch": int(epoch),
                "step_in_epoch": int(step_in_epoch),
                "best_acc": float(best_acc)}
        # computed from the LIVE device state (the async snapshot below
        # is host numpy, where every leaf reads as tier "host")
        layout = ckpt.opt_state_layout(state)
        if layout:
            meta["opt_state_layout"] = layout
        name = self._name(step)
        if not (self.async_save or sync):
            sync = True      # async disabled: blocking collective path
        if sync and self.backend.kind != "posix":
            # the sync path is the single-file orbax save, which stages
            # + renames internally — impossible on an object store.  A
            # sharded save followed by a full drain gives the same
            # blocking "committed on return" contract on the backend.
            ok = self._save_sharded(state, step, meta, name, segment)
            self._drain_inflight()
            return ok
        if sync:
            self._drain_inflight()
            t0 = time.monotonic()
            with spans.span("ckpt_sync_save", step=step):
                ckpt.save_checkpoint(self.directory, name, state,
                                     epoch=epoch, best_acc=best_acc,
                                     extra_meta=meta)
            self._prune()
            self._record_save(step, time.monotonic() - t0, segment)
            if self._goodput:
                self._goodput.count("saves")   # committed — the sync
                # path only returns after the marker is on disk
            return True
        if self._sharded:
            return self._save_sharded(state, step, meta, name, segment)
        if self._inflight is not None and not self._inflight.done():
            if self._goodput:
                self._goodput.count("skipped_saves")
            # consume this cadence tick: without the anchor update the
            # crossing-based should_save would re-fire EVERY subsequent
            # step while the write runs, counting one skip per step
            # instead of one per missed tick
            self._last_save_step = step
            if not self._skip_logged:    # once per in-flight save, not per tick
                self._skip_logged = True
                self._log(f"[ckpt] step {step}: previous async save still "
                          f"in flight; skipping cadence ticks until it "
                          f"commits")
            return False
        self._finalize_inflight()
        t0 = time.monotonic()
        # the blocking part: the next train step will donate these
        # buffers, so the snapshot must complete before it dispatches
        with spans.span("ckpt_snapshot", step=step):
            snapshot = jax.device_get(ckpt._state_pytree(state))
        blocking = time.monotonic() - t0
        path = os.path.join(self.directory, name)
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="fdt-ckpt")
        self._inflight_path = path
        self._skip_logged = False
        self._inflight = self._pool.submit(
            self._write_pytree_bg, path, snapshot, meta, step)
        self._record_save(step, blocking, segment)
        return True

    @staticmethod
    def _write_pytree_bg(path: str, snapshot, meta: dict,
                         step: int) -> None:
        """Background worker body of the single-host async save —
        span-wrapped so the serialize+commit cost shows up in telemetry
        (recorded from the writer thread; the recorder is lock-safe)."""
        with spans.span("ckpt_commit", step=step):
            ckpt.save_pytree_checkpoint(path, snapshot, meta)

    def _save_sharded(self, state, step: int, meta: dict, name: str,
                      segment: str) -> bool:
        """The multi-host async path: per-host addressable-shard snapshot
        (the only blocking piece) + a background shard write per process,
        two-phase commit through ``checkpoint.write_host_shards`` /
        ``commit_sharded_checkpoint``.

        Unlike the single-host async path this DRAINS a still-running
        previous write instead of skipping the tick: the skip decision
        depends on per-host write timing (NOT a pure function of the
        step), so one host could skip a tick its peers take and the
        commit barrier would starve waiting for its shard.  Draining
        keeps every host's tick set identical; in steady state the
        previous write is long finished and the drain is free."""
        t0 = time.monotonic()   # before the drain: a slow-writer stall
        # is critical-path time and must land in the blocking segment
        if self._inflight is not None and not self._inflight.done():
            self._log(f"[ckpt] step {step}: waiting for the previous "
                      f"sharded save to finish (slow writer) — the tick "
                      f"is taken on every host to keep the pod's commit "
                      f"barrier aligned")
        self._drain_inflight()
        # blocking part: the drain above + fetching THIS process's owned
        # shards to host — the next train step donates those buffers
        with spans.span("ckpt_snapshot", step=step):
            blocks = ckpt.host_shard_snapshot(state, self._shard_owner)
        blocking = time.monotonic() - t0
        path = os.path.join(self.directory, name)
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="fdt-ckpt")
        self._inflight_path = path
        self._skip_logged = False
        self._inflight = self._pool.submit(
            self._write_shards_and_commit, path, blocks, meta)
        self._record_save(step, blocking, segment)
        return True

    def _write_shards_and_commit(self, path: str, blocks: list,
                                 meta: dict) -> None:
        """Background worker body: phase-1 shard write (every host),
        phase-2 barrier + COMMIT (process 0 only)."""
        with spans.span("ckpt_commit", step=meta.get("step")):
            ckpt.write_host_shards(path, self._pi, blocks,
                                   backend=self.backend)
            if self._pi == 0:
                ckpt.commit_sharded_checkpoint(
                    path, meta, n_hosts=self._pc,
                    timeout_s=self._commit_timeout_s,
                    backend=self.backend)

    def _record_save(self, step: int, blocking_s: float,
                     segment: str = "checkpoint_blocking_s") -> None:
        """Cadence anchors + blocking time (into `segment` — cadence
        saves bill checkpoint_blocking_s, the preemption path passes
        emergency_save_s so the seconds land in exactly ONE badput
        bucket), recorded at INITIATION (a failed write must not trigger
        an immediate save-retry storm); the 'saves' counter is only
        incremented once a save actually COMMITS — sync: on return,
        async: at _finalize_inflight."""
        self._last_save_t = time.monotonic()
        self._last_save_step = step
        if self._goodput:
            self._goodput.add(segment, blocking_s)

    def _name(self, step: int) -> str:
        return f"{self.prefix}_step_{step:09d}"

    def align_cadence(self, step: int) -> None:
        """Re-anchor the step cadence at `step` (idempotent, forward
        only).  Called after a completed slice re-admission
        (coordinator.consume_cadence_align): hold and catch-up phases
        suppressed different ticks on different hosts, and the pod's
        commit barrier needs every host's NEXT tick to be the same pure
        function of the shared step sequence again."""
        self._last_save_step = max(self._last_save_step or 0, int(step))

    def _finalize_inflight(self) -> None:
        """Reap a COMPLETED background save: surface its error (warn +
        count, never crash training over a failed save) and prune."""
        fut, self._inflight = self._inflight, None
        self._inflight_path = None
        if fut is None:
            return
        try:
            fut.result()
        except Exception as e:
            if self._goodput:
                self._goodput.count("save_failures")
            self._log(f"[ckpt] background save failed: {e!r} — training "
                      f"continues; the previous checkpoint remains newest")
            return
        if self._goodput:
            if self._sharded and self._pi != 0:
                # this host only knows its phase-1 shard write landed;
                # whether process 0's barrier COMMITTED the step is not
                # observable here — count the honest thing and leave
                # 'saves' (= committed checkpoints) to process 0
                self._goodput.count("shard_writes")
            else:
                self._goodput.count("saves")   # committed for real
        self._prune()

    def _drain_inflight(self) -> None:
        if self._inflight is not None:
            try:
                self._inflight.result()
            except Exception:
                pass
            self._finalize_inflight()

    def adopt_identity(self, process_index: int,
                       shard_owner: Optional[Callable] = None) -> None:
        """Re-key this manager to an adopted pod seat (r17 warm spares):
        a spare parks under a synthetic out-of-pod index (it must never
        commit, prune, or sweep while the real pod runs) and, after
        claiming a failed member's seat, takes over that member's shard
        ownership, commit-barrier role, and log gating."""
        self._pi = int(process_index)
        if shard_owner is not None:
            self._shard_owner = shard_owner
        self._log = self._base_log if self._pi == 0 else (lambda *_: None)

    def wait(self) -> None:
        """Block until no save is in flight (tests / epoch boundaries)."""
        self._drain_inflight()

    def close(self) -> None:
        self._drain_inflight()
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None

    # -- discovery / restore ----------------------------------------------

    def _entries(self) -> List[Tuple[int, str]]:
        """[(step, dirname)] of this prefix's step checkpoints, any
        state — discovered through the backend's one-level entry
        listing (an object store has no directories: the "entry" is the
        first key component under the manager's namespace; POSIX reads
        one directory, never walking the tree)."""
        out = []
        for name in self.backend.list_entries(self.directory):
            m = _STEP_DIR.match(name)
            if m and m.group("prefix") == self.prefix:
                out.append((int(m.group("step")), name))
        return sorted(out)

    def committed_steps(self) -> List[int]:
        return [s for s, n in self._entries()
                if ckpt.is_committed(os.path.join(self.directory, n),
                                     backend=self.backend)]

    def latest_valid(self) -> Optional[Tuple[int, str]]:
        """Newest COMMITTED (step, name); commit says "fully written",
        restore_latest additionally survives corrupted-but-committed."""
        for step, name in reversed(self._entries()):
            if ckpt.is_committed(os.path.join(self.directory, name),
                                 backend=self.backend):
                return step, name
        return None

    def restore_latest(self, state) -> Optional[Tuple[Any, dict]]:
        """Span-wrapped entry (telemetry "restore" — failed walks still
        record their cost; that time IS the MTTR restore component):
        see :meth:`_restore_latest_impl` for the semantics."""
        with spans.span("restore"):
            return self._restore_latest_impl(state)

    def peek_latest(self, state) -> Optional[Tuple[Any, dict]]:
        """Barrier-free READ-ONLY restore of the newest committed
        checkpoint — the warm-spare refresh path (r17).  A parked spare
        is OUTSIDE the pod's restore protocol: it must neither join the
        members' rendezvous/agreement barriers (it would wedge them)
        nor sweep uncommitted residue (restore_latest's deletion point
        is only race-free because the peers are blocked in the
        agreement collective — a spare has no such guarantee).  Walks
        newest-first past corrupt-but-committed entries exactly like
        restore_latest; returns (state, meta) or None.  Does NOT touch
        cadence anchors or goodput (a refresh is not recovery)."""
        for step, name in reversed(self._entries()):
            path = os.path.join(self.directory, name)
            if not ckpt.is_committed(path, backend=self.backend):
                continue
            try:
                if ckpt.is_sharded_checkpoint(path, backend=self.backend):
                    restored, _e, _b = ckpt.restore_sharded_checkpoint(
                        self.directory, name, state, backend=self.backend)
                else:
                    restored, _e, _b = ckpt.restore_checkpoint(
                        self.directory, name, state)
                meta = ckpt.read_checkpoint_meta(self.directory, name,
                                                 backend=self.backend)
                return restored, meta
            except Exception as e:
                self._base_log(f"[ckpt] peek: checkpoint {name} is "
                               f"committed but failed to restore ({e!r}); "
                               f"trying the previous one")
        return None

    def _restore_latest_impl(self, state) -> Optional[Tuple[Any, dict]]:
        """(restored_state, meta) from the newest checkpoint that BOTH
        carries a commit marker and actually restores — a committed-but-
        corrupt newest (bit rot, torn block device) falls back to the
        previous valid one with a warning.  None when nothing restores.
        Sharded (per-host shard-file) and single-file orbax checkpoints
        interoperate: each entry restores through whichever format it
        was written in, so a pod run resumes from a pre-sharding
        checkpoint (and vice versa) transparently."""
        self._drain_inflight()
        # Pre-walk rendezvous (r10): no host may WALK until every host
        # has drained its in-flight background write.  Without it, a
        # host that restarts quickly after a pod failure walks the
        # directory before a slower peer's two-phase COMMIT lands,
        # restores an older step (or nothing), and the agreement below
        # kills the attempt with RestoreDivergence — burning a whole
        # restart generation on a transient that draining fixes.  One
        # extra allgather per restore; restores are rare.
        self._rendezvous()
        result, restored_step, t0 = None, -1, time.monotonic()
        for step, name in reversed(self._entries()):
            path = os.path.join(self.directory, name)
            if not ckpt.is_committed(path, backend=self.backend):
                continue
            try:
                if ckpt.is_sharded_checkpoint(path, backend=self.backend):
                    restored, _epoch, _best = ckpt.restore_sharded_checkpoint(
                        self.directory, name, state, backend=self.backend)
                else:
                    restored, _epoch, _best = ckpt.restore_checkpoint(
                        self.directory, name, state)
                meta = ckpt.read_checkpoint_meta(self.directory, name,
                                                 backend=self.backend)
                saved_layout = meta.get("opt_state_layout")
                live_layout = ckpt.opt_state_layout(restored)
                if saved_layout and live_layout \
                        and saved_layout != live_layout:
                    self._log(f"[ckpt] restore {name}: opt-state layout "
                              f"changed {saved_layout} -> {live_layout} "
                              f"(ZeRO<->replicated interchange; values "
                              f"re-placed by the restore template)")
                result, restored_step = (restored, meta), step
                break
            except Exception as e:
                self._log(f"[ckpt] checkpoint {name} is committed but "
                          f"failed to restore ({e!r}); falling back to "
                          f"the previous one")
        # Sweep ALL uncommitted residue now, BEFORE the agreement
        # collective: a crashed sharded save leaves a dir with every
        # host's DONE marker but no COMMIT, and if it survived to the
        # re-reached save step the commit barrier would see the stale
        # markers and COMMIT a mix of two attempts' shard files.
        # Restore is the one point where deletion is race-free — the
        # peers are blocked in _gather_restored_steps below until
        # process 0 (the only deleter) joins, so no host can be
        # writing.  Uncommitted dirs are never restorable, so this
        # deletes only disk (and the stale-marker trap).
        if self._pi == 0:
            for _s, n in self._entries():
                p = os.path.join(self.directory, n)
                if not ckpt.is_committed(p, backend=self.backend):
                    self._delete(p)
        # cross-host agreement AFTER the walk, joined by EVERY host
        # regardless of its outcome (None restores gather -1): a host
        # whose walk fell back — or exhausted every entry — must still
        # meet its peers in the collective, or they would block forever
        # waiting for it instead of raising
        gathered = (self._step_gather_fn(restored_step, phase="agree")
                    if self._step_gather_fn is not None
                    else self._gather_restored_steps(restored_step))
        self._verify_restore_agreement(gathered)
        if result is None:
            return None
        if self._goodput:
            self._goodput.count("restores")
            self._goodput.add("restore_s", time.monotonic() - t0)
        self._last_save_step = restored_step
        return result

    def _rendezvous(self) -> None:
        """The pre-walk barrier of restore_latest: joined by every host
        AFTER draining its in-flight write, so the newest checkpoint's
        COMMIT (or its absence) is identical in every host's subsequent
        walk.  The gathered values are ignored — only the rendezvous
        matters."""
        if self._step_gather_fn is not None:
            self._step_gather_fn(0, phase="enter")
        else:
            self._gather_restored_steps(0)

    @staticmethod
    def _gather_restored_steps(step: int) -> np.ndarray:
        """Every REAL host's restored step (−1 = nothing restored),
        stacked — the collective piece, split from the pure decision
        below so the tier-1 simulated-pod tests can exercise the
        decision without multi-process collectives."""
        if jax.process_count() == 1:
            return np.asarray([step], np.int32)
        from faster_distributed_training_tpu.parallel.collectives import (
            all_gather_across_processes)
        return all_gather_across_processes(np.asarray(step, np.int32))

    @staticmethod
    def _verify_restore_agreement(steps: np.ndarray) -> None:
        """Multi-host: the restore walk runs independently per host, so a
        host whose shard-file read failed (torn page, transient IO) would
        silently fall back to an OLDER checkpoint while its peers resume
        the newest — divergent state with no error.  Fail LOUDLY on
        disagreement (every host sees the same gathered vector, so all
        raise together); the r7 collective restore failed loudly too,
        this keeps that property."""
        if int(steps.min()) != int(steps.max()):
            raise RestoreDivergence(
                f"hosts restored different checkpoint steps "
                f"{sorted(set(int(s) for s in steps))} (−1 = none) — a "
                f"per-host shard-read failure made one host fall back "
                f"while its peers took the newest; refusing to resume "
                f"divergent (clear or repair the newest checkpoint dir "
                f"and rerun)")

    # -- retention --------------------------------------------------------

    def _prune(self) -> None:
        """Keep the newest `keep` COMMITTED checkpoints; also sweep
        uncommitted residue older than the newest committed one (a
        half-written dir from a crash — never restorable, only disk).
        Process 0 only; other hosts see the shared result.  Deletion is
        the backend's BATCHED ``delete_prefix`` (r14 — rmtree on POSIX,
        list+batched object deletes on GCS/fake; the ``delete_fn`` hook
        still overrides for custom retention policies)."""
        if self._pi != 0:
            return
        entries = self._entries()
        committed = [(s, n) for s, n in entries if ckpt.is_committed(
            os.path.join(self.directory, n), backend=self.backend)]
        doomed = [n for _s, n in committed[:-self.keep]]
        if committed:
            newest_committed = committed[-1][0]
            doomed += [n for s, n in entries
                       if s < newest_committed
                       and not ckpt.is_committed(
                           os.path.join(self.directory, n),
                           backend=self.backend)
                       and os.path.join(self.directory, n)
                       != self._inflight_path]
        for n in doomed:
            self._delete(os.path.join(self.directory, n))
