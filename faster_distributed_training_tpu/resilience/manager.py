"""Async, cadence-driven checkpoint manager.

Layered on ``train/checkpoint.py``'s save/restore (the ISSUE's
prescription — orbax arrays + meta.json + atomic COMMIT marker), adding
the four things a preemptible-pod run needs that the epoch-level
checkpoints don't give:

  * WHEN to save — step cadence (``every_steps``) and/or wall-clock
    cadence (``every_secs``), whichever fires first;
  * OFF the critical path — the save splits into a blocking snapshot
    (``jax.device_get`` of the state, unavoidable: the very next train
    step donates those buffers) and the orbax serialization + disk
    write, which run on a single background worker.  Only the snapshot
    time touches step latency; bench.py's ``ckpt_async_*`` arms measure
    it at <1% of median step time;
  * keep-last-K retention — committed checkpoints beyond ``keep`` are
    pruned after each successful commit, and uncommitted residue
    (half-written directories from a previous crash) is swept;
  * newest-VALID restore — :meth:`restore_latest` walks committed
    checkpoints newest-first and falls back past any that fail to
    restore (corrupt/truncated data with an intact marker), so one bad
    write can never wedge recovery.

Multi-host: ``device_get`` can only fetch addressable shards, so with
``jax.process_count() > 1`` the manager saves SYNCHRONOUSLY through the
collective orbax path (async multi-host save is a ROADMAP open item),
and only the STEP cadence is honored — a pure function of the step
counter, identical on every host, so the collective save can't
deadlock.  The wall-clock cadence reads per-host clocks that can
disagree near a threshold and is disabled multi-host (warned).
"""

from __future__ import annotations

import os
import re
import shutil
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, List, Optional, Tuple

import jax

from faster_distributed_training_tpu.train import checkpoint as ckpt

_STEP_DIR = re.compile(r"^(?P<prefix>.+)_step_(?P<step>\d{9})$")


class AsyncCheckpointManager:
    """Owns `<directory>/<prefix>_step_<N>` checkpoints.

    Not thread-safe for concurrent maybe_save callers (the train loop is
    single-threaded); the background worker only touches the host
    snapshot handed to it."""

    def __init__(self, directory: str, prefix: str = "ckpt",
                 every_steps: int = 0, every_secs: float = 0.0,
                 keep: int = 3, async_save: bool = True,
                 goodput=None, log: Callable[[str], None] = print):
        self.directory = os.path.abspath(directory)
        self.prefix = prefix
        self.every_steps = int(every_steps)
        self.every_secs = float(every_secs)
        if self.every_secs and jax.process_count() > 1:
            # the wall-clock term reads each host's OWN monotonic clock,
            # so near a threshold hosts can disagree and one would enter
            # the COLLECTIVE multi-host save alone — a deadlock.  Only
            # the step term is a pure function every host agrees on.
            self.every_secs = 0.0
            if jax.process_index() == 0:
                log("[ckpt] --checkpoint_every_secs is per-host-clock-"
                    "nondeterministic and cannot drive the multi-host "
                    "collective save (hosts could disagree and deadlock); "
                    "disabled — use the step cadence (--checkpoint_every)")
        self.keep = max(int(keep), 1)
        # async needs a host snapshot; multi-host arrays aren't fully
        # addressable from one process, so the collective sync path wins
        self.async_save = bool(async_save) and jax.process_count() == 1
        self._goodput = goodput
        self._log = log if jax.process_index() == 0 else (lambda *_: None)
        self._last_save_t = time.monotonic()
        self._last_save_step: Optional[int] = None
        self._pool: Optional[ThreadPoolExecutor] = None
        self._inflight: Optional[Future] = None
        self._inflight_path: Optional[str] = None
        self._skip_logged = False
        os.makedirs(self.directory, exist_ok=True)

    # -- cadence ----------------------------------------------------------

    def should_save(self, step: int) -> bool:
        """Multi-host, only the STEP term is live (a pure function of
        the observed step sequence, identical on every host — what keeps
        the collective save deadlock-free); the per-host wall-clock term
        is disabled at construction there.  Single-process runs use both.

        The step term fires when `step` has CROSSED an every_steps
        boundary since the last save — exact multiples for the classic
        per-step loop (identical behavior), and the first dispatch
        boundary at-or-past each multiple under a K-step fused dispatch,
        whose ticks only land at steps K, 2K, … (cli rounds
        checkpoint_every up to a multiple of K so the two coincide; the
        crossing form keeps cadence robust for epoch-tail dispatches of
        size < K, which shift every later boundary off the multiples)."""
        if step <= 0 or step == self._last_save_step:
            return False
        if self.every_steps:
            anchor = self._last_save_step or 0
            if anchor > step:
                # the step counter moved BACKWARD (auto-recover rolled
                # the state back to an epoch snapshot taken outside this
                # manager): a stale forward anchor would silence the
                # cadence for the whole replay window — reset so the
                # replay is checkpointable immediately
                anchor = 0
            if step // self.every_steps > anchor // self.every_steps:
                return True
        if self.every_secs:
            return time.monotonic() - self._last_save_t >= self.every_secs
        return False

    # -- saving -----------------------------------------------------------

    def maybe_save(self, state, step: int, epoch: int = 0,
                   step_in_epoch: int = 0, best_acc: float = 0.0) -> bool:
        if not self.should_save(step):
            return False
        return self.save(state, step, epoch=epoch,
                         step_in_epoch=step_in_epoch, best_acc=best_acc)

    def save(self, state, step: int, epoch: int = 0, step_in_epoch: int = 0,
             best_acc: float = 0.0, sync: bool = False,
             segment: str = "checkpoint_blocking_s") -> bool:
        """Checkpoint `state` at `step`.  Async (default): snapshot on
        the caller's thread, serialize + commit in the background; one
        save in flight at a time — a cadence tick that lands while the
        previous write is still running is SKIPPED (counted, never
        queued: a slow filesystem must not grow an unbounded backlog of
        full-state snapshots in host memory).  sync=True (emergency
        save path) waits for any in-flight write first and blocks until
        committed."""
        meta = {"step": int(step), "epoch": int(epoch),
                "step_in_epoch": int(step_in_epoch),
                "best_acc": float(best_acc)}
        name = self._name(step)
        if not (self.async_save or sync):
            sync = True      # multi-host / async disabled: collective path
        if sync:
            self._drain_inflight()
            t0 = time.monotonic()
            ckpt.save_checkpoint(self.directory, name, state,
                                 epoch=epoch, best_acc=best_acc,
                                 extra_meta=meta)
            self._prune()
            self._record_save(step, time.monotonic() - t0, segment)
            if self._goodput:
                self._goodput.count("saves")   # committed — the sync
                # path only returns after the marker is on disk
            return True
        if self._inflight is not None and not self._inflight.done():
            if self._goodput:
                self._goodput.count("skipped_saves")
            # consume this cadence tick: without the anchor update the
            # crossing-based should_save would re-fire EVERY subsequent
            # step while the write runs, counting one skip per step
            # instead of one per missed tick
            self._last_save_step = step
            if not self._skip_logged:    # once per in-flight save, not per tick
                self._skip_logged = True
                self._log(f"[ckpt] step {step}: previous async save still "
                          f"in flight; skipping cadence ticks until it "
                          f"commits")
            return False
        self._finalize_inflight()
        t0 = time.monotonic()
        # the blocking part: the next train step will donate these
        # buffers, so the snapshot must complete before it dispatches
        snapshot = jax.device_get(ckpt._state_pytree(state))
        blocking = time.monotonic() - t0
        path = os.path.join(self.directory, name)
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="fdt-ckpt")
        self._inflight_path = path
        self._skip_logged = False
        self._inflight = self._pool.submit(
            ckpt.save_pytree_checkpoint, path, snapshot, meta)
        self._record_save(step, blocking, segment)
        return True

    def _record_save(self, step: int, blocking_s: float,
                     segment: str = "checkpoint_blocking_s") -> None:
        """Cadence anchors + blocking time (into `segment` — cadence
        saves bill checkpoint_blocking_s, the preemption path passes
        emergency_save_s so the seconds land in exactly ONE badput
        bucket), recorded at INITIATION (a failed write must not trigger
        an immediate save-retry storm); the 'saves' counter is only
        incremented once a save actually COMMITS — sync: on return,
        async: at _finalize_inflight."""
        self._last_save_t = time.monotonic()
        self._last_save_step = step
        if self._goodput:
            self._goodput.add(segment, blocking_s)

    def _name(self, step: int) -> str:
        return f"{self.prefix}_step_{step:09d}"

    def _finalize_inflight(self) -> None:
        """Reap a COMPLETED background save: surface its error (warn +
        count, never crash training over a failed save) and prune."""
        fut, self._inflight = self._inflight, None
        self._inflight_path = None
        if fut is None:
            return
        try:
            fut.result()
        except Exception as e:
            if self._goodput:
                self._goodput.count("save_failures")
            self._log(f"[ckpt] background save failed: {e!r} — training "
                      f"continues; the previous checkpoint remains newest")
            return
        if self._goodput:
            self._goodput.count("saves")   # committed for real
        self._prune()

    def _drain_inflight(self) -> None:
        if self._inflight is not None:
            try:
                self._inflight.result()
            except Exception:
                pass
            self._finalize_inflight()

    def wait(self) -> None:
        """Block until no save is in flight (tests / epoch boundaries)."""
        self._drain_inflight()

    def close(self) -> None:
        self._drain_inflight()
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None

    # -- discovery / restore ----------------------------------------------

    def _entries(self) -> List[Tuple[int, str]]:
        """[(step, dirname)] of this prefix's step directories, any state."""
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for n in names:
            m = _STEP_DIR.match(n)
            if m and m.group("prefix") == self.prefix:
                out.append((int(m.group("step")), n))
        return sorted(out)

    def committed_steps(self) -> List[int]:
        return [s for s, n in self._entries()
                if ckpt.is_committed(os.path.join(self.directory, n))]

    def latest_valid(self) -> Optional[Tuple[int, str]]:
        """Newest COMMITTED (step, name); commit says "fully written",
        restore_latest additionally survives corrupted-but-committed."""
        for step, name in reversed(self._entries()):
            if ckpt.is_committed(os.path.join(self.directory, name)):
                return step, name
        return None

    def restore_latest(self, state) -> Optional[Tuple[Any, dict]]:
        """(restored_state, meta) from the newest checkpoint that BOTH
        carries a commit marker and actually restores — a committed-but-
        corrupt newest (bit rot, torn block device) falls back to the
        previous valid one with a warning.  None when nothing restores."""
        self._drain_inflight()
        for step, name in reversed(self._entries()):
            path = os.path.join(self.directory, name)
            if not ckpt.is_committed(path):
                continue
            try:
                t0 = time.monotonic()
                restored, _epoch, _best = ckpt.restore_checkpoint(
                    self.directory, name, state)
                meta = ckpt.read_checkpoint_meta(self.directory, name)
                if self._goodput:
                    self._goodput.count("restores")
                    self._goodput.add("restore_s", time.monotonic() - t0)
                self._last_save_step = step
                return restored, meta
            except Exception as e:
                self._log(f"[ckpt] checkpoint {name} is committed but "
                          f"failed to restore ({e!r}); falling back to "
                          f"the previous one")
        return None

    # -- retention --------------------------------------------------------

    def _prune(self) -> None:
        """Keep the newest `keep` COMMITTED checkpoints; also sweep
        uncommitted residue older than the newest committed one (a
        half-written dir from a crash — never restorable, only disk).
        Process 0 only; other hosts see the shared-fs result."""
        if jax.process_index() != 0:
            return
        entries = self._entries()
        committed = [(s, n) for s, n in entries if ckpt.is_committed(
            os.path.join(self.directory, n))]
        doomed = [n for _s, n in committed[:-self.keep]]
        if committed:
            newest_committed = committed[-1][0]
            doomed += [n for s, n in entries
                       if s < newest_committed
                       and not ckpt.is_committed(
                           os.path.join(self.directory, n))
                       and os.path.join(self.directory, n)
                       != self._inflight_path]
        for n in doomed:
            shutil.rmtree(os.path.join(self.directory, n),
                          ignore_errors=True)
