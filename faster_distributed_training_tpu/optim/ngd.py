"""Online natural-gradient descent, fully on device.

TPU-native re-design of the reference's ``OnlineNaturalGradient`` /
``NGD`` (``ngd_optimizer.py``, itself a Python port of Kaldi's
natural-gradient-online.cc).  The algorithm: per parameter tensor and per
tensor axis, maintain a rank-R-plus-identity approximation of that
axis's Fisher matrix,

    F_t ≈ W_t^T diag(d_t) W_t + rho_t I          (dim x dim, R << dim)

and precondition each incoming gradient by (approximately) F_t^{-1},
then rescale so the preconditioned gradient keeps the Euclidean norm of
the raw gradient (``ngd_optimizer.py:151-168``).  Every
``update_period`` steps (and always in the first 10) the factorization
is refreshed from the current minibatch of directions via a rank-sized
symmetric eigendecomposition (``ngd_optimizer.py:205-328``).

What is deliberately different from the reference (SURVEY.md §7 hard
part 1 — this is the point of the TPU build):

  * **No host round-trips.**  The reference calls ``.item()`` on five
    scalars per update and runs ``eigh`` on CPU
    (``ngd_optimizer.py:225,240,265,285-289``), forcing a device sync
    per parameter-axis per step.  Here the entire update — including the
    (R,R) ``eigh`` with R <= 80 — is traced into the jitted train step.
  * **State is an optax pytree** (one ``OnlineNaturalGradientState`` per
    preconditioned axis), so it is shardable under pjit, checkpointable
    by orbax (the reference never serializes Fisher state — SURVEY §5),
    and donate-able.
  * **Update gating via ``lax.cond``** on the step counter, so the
    expensive refresh is only *executed* every ``update_period`` steps
    even inside one compiled graph.
  * **NaN fallback preserves state**: on a non-finite result the
    reference returns the raw gradient but keeps possibly-poisoned
    factors (``ngd_optimizer.py:158-165``); we also roll back W/d/rho.

Hyperparameters match ``ngd_optimizer.py:9-15``: alpha=4.0,
rank=min((dim+1)//2, 80), update_period=4, eta=0.1, epsilon=1e-10,
delta=5e-4; preconditioning is a no-op for axes of dim 1
(``ngd_optimizer.py:110-111``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, NamedTuple, Optional, Tuple

import chex
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax

EPSILON = 1.0e-10
DELTA = 5.0e-4
NUM_INITIAL_ITERS = 10  # always update during the first 10 steps


@dataclasses.dataclass(frozen=True)
class NGDHyperParams:
    alpha: float = 4.0
    rank: int = -1          # -1 → min((dim+1)//2, 80) per axis
    update_period: int = 4
    eta: float = 0.1
    # Axes larger than max_dim are left unpreconditioned (identity).
    # Kaldi's online NGD estimates a dim x dim inverse-Fisher from rank-N
    # outer products of DENSE gradients; a vocab-sized embedding axis
    # (30522) violates both assumptions — its per-step gradient touches
    # only the ~batch.seq tokens present, and empirically preconditioning
    # that axis STALLS transformer training entirely (loss flat at
    # chance; measured: adamw learns the same task to 96% in 5 epochs,
    # NGD with the vocab axis preconditioned stays at 25-32%, NGD with it
    # skipped learns — see ACCURACY.md).  The reference never validated
    # its NGD on the transformer (its published accuracy results are
    # CNN-only, README.md:63 "mainly CNN"), so this policy has no
    # reference analog to match; 8192 clears every dense layer axis
    # (d_ff=1024, conv 2048) while excluding vocab-sized tables.
    max_dim: int = 8192


class OnlineNaturalGradientState(NamedTuple):
    """Fisher factor state for ONE tensor axis (all on device)."""
    w: jax.Array     # (rank, dim) — inverse-Fisher factor W_t
    d: jax.Array     # (rank,)     — eigenvalue diagonal D_t
    rho: jax.Array   # ()          — identity scale rho_t
    t: jax.Array     # () int32    — number of precondition calls


def _default_rank(dim: int, rank: int) -> int:
    if rank > 0:
        # The reference asserts 0 < rank < dim per axis (ngd_optimizer.py:25)
        # which would make one global rank setting crash on small axes; we
        # clamp instead so e.g. rank=40 still works on a dim-3 kernel axis.
        return min(rank, dim - 1)
    return min((dim + 1) // 2, 80)


def _orthonormal_special(rank: int, dim: int) -> np.ndarray:
    """Deterministic near-orthonormal (rank, dim) matrix
    (ngd_optimizer.py:397-420), built host-side — it depends only on the
    static shapes, so under jit it is a compile-time constant."""
    first_elem = 1.1
    num_cols = dim // rank
    remainder = dim % rank
    k = np.full((rank,), 1.0 / np.sqrt(first_elem * first_elem + num_cols - 1))
    k[:remainder] = 1.0 / np.sqrt(first_elem * first_elem + num_cols)
    diag = np.diag(k)
    ans = np.concatenate([np.diag(k * first_elem)]
                         + [diag] * (num_cols + 1), axis=1)[:, :dim]
    return ans


def init_ng_state(dim: int, hp: NGDHyperParams,
                  dtype=jnp.float32) -> OnlineNaturalGradientState:
    """Default-initialized state (ngd_optimizer.py:378-395); the data-dependent
    power-iteration warmup happens lazily at the first precondition call."""
    rank = _default_rank(dim, hp.rank)
    e_tii = 1.0 / (2.0 + (dim + rank) * hp.alpha / dim)
    w0 = np.sqrt(e_tii) * _orthonormal_special(rank, dim)
    return OnlineNaturalGradientState(
        w=jnp.asarray(w0, dtype),
        d=jnp.full((rank,), EPSILON, dtype),
        rho=jnp.asarray(EPSILON, dtype),
        t=jnp.asarray(0, jnp.int32),
    )


def _core_step(w, d, rho, x, tr_xxt, updating, hp: NGDHyperParams):
    """One preconditioning step on a (N, dim) matrix of directions; returns
    ((w', d', rho'), x_hat).  Mirrors _precondition_directions3
    (ngd_optimizer.py:170-328) with every scalar kept on device."""
    n_rows, dim = x.shape
    rank = w.shape[0]
    alpha, eta = hp.alpha, hp.eta
    eta_n = eta / n_rows

    h = x @ w.T                       # H_t = X_t W_t^T           (N, rank)
    x_hat = x - h @ w                 # X_hat_t = X_t - H_t W_t

    def no_update(_):
        return w, d, rho

    def do_update(_):
        j = h.T @ x                   # J_t = H_t^T X_t          (rank, dim)
        if n_rows > dim:              # static shape choice (ngd:214-217)
            l_mat = j @ w.T
        else:
            l_mat = h.T @ h
        k_mat = j @ j.T

        d_sum = jnp.sum(d)
        beta = rho * (1.0 + alpha) + alpha * d_sum / dim
        e = 1.0 / (beta / d + 1.0)
        inv_sqrt_e = 1.0 / jnp.sqrt(e)
        # z_t_scale keeps Z_t (4th-power-of-gradients) in range (ngd:240)
        z_scale = jnp.maximum(1.0, jnp.trace(k_mat))
        d_plus_rho = d + rho
        inv_sqrt_e_outer = ((eta_n ** 2) / z_scale) * jnp.outer(inv_sqrt_e,
                                                                inv_sqrt_e)
        op1 = (eta_n * (1.0 - eta) / z_scale) * jnp.outer(
            inv_sqrt_e, inv_sqrt_e * d_plus_rho)
        z = (k_mat * inv_sqrt_e_outer + l_mat * (op1 + op1.T)
             + jnp.diag(((1.0 - eta) ** 2 / z_scale)
                        * d_plus_rho * d_plus_rho))

        # (rank, rank) symmetric eigendecomposition ON DEVICE — the
        # reference ships Z_t to the CPU here (ngd_optimizer.py:265).
        # Symmetrize first: K/L are symmetric only up to rounding, and eigh
        # reads a single triangle.
        z = 0.5 * (z + z.T)
        c, u = jnp.linalg.eigh(z)
        c = c[::-1]                    # descending
        u = u[:, ::-1]
        c_floor = ((rho * (1.0 - eta)) ** 2) / z_scale
        c = jnp.maximum(c, c_floor)
        sqrt_c = jnp.sqrt(c) * jnp.sqrt(z_scale)
        inv_sqrt_c = 1.0 / sqrt_c

        rho_new = (1.0 / (dim - rank)) * (
            eta_n * tr_xxt + (1.0 - eta) * (dim * rho + d_sum)
            - jnp.sum(sqrt_c))
        floor_val = jnp.maximum(EPSILON, DELTA * jnp.max(sqrt_c))
        d_new = jnp.maximum(sqrt_c - rho_new, floor_val)
        rho_new = jnp.maximum(rho_new, floor_val)

        beta_new = rho_new * (1.0 + alpha) + alpha * jnp.sum(d_new) / dim
        e_new = 1.0 / (beta_new / d_new + 1.0)
        sqrt_e_new = jnp.sqrt(e_new)

        # B_t = J_t + (1-eta)/(eta/N) (D_t + rho_t I) W_t   (ngd:308-311)
        w_coeff = ((1.0 - eta) / eta_n) * d_plus_rho
        b = j + w_coeff[:, None] * w
        # A_t = (eta/N) E_{t+1}^{1/2} C_t^{-1/2} U_t^T E_t^{-1/2}
        a = u.T * jnp.outer(eta_n * sqrt_e_new * inv_sqrt_c, inv_sqrt_e)
        return a @ b, d_new, rho_new

    w1, d1, rho1 = lax.cond(updating, do_update, no_update, operand=None)
    return (w1, d1, rho1), x_hat


def _member_init(x, tr_xxt, rank: int, hp: NGDHyperParams):
    """Lazy init (ngd_optimizer.py:356-376): reset to the default factors
    then run 3 discarded updates on this same minibatch — a cheap
    power-iteration approximation of an SVD init."""
    dim = x.shape[1]
    fresh = init_ng_state(dim, dataclasses.replace(hp, rank=rank), x.dtype)

    def body(_, wdr):
        (w, d, rho), _x = _core_step(*wdr, x, tr_xxt, True, hp)
        return (w, d, rho)

    return lax.fori_loop(0, 3, body, (fresh.w, fresh.d, fresh.rho))


def _member_finalize(w, d, rho, w1, d1, rho1, x, x_hat, tr_xxt):
    """Norm-preserving rescale (ngd:168); on NaN return raw grads AND roll
    back the factors (improvement over ngd:158-165 which keeps them)."""
    final = jnp.sum(x_hat * x_hat)
    good = jnp.isfinite(final)
    out = jnp.where(good, x_hat * jnp.sqrt(tr_xxt / (final + 1.0e-30)), x)
    w1 = jnp.where(good, w1, w)
    d1 = jnp.where(good, d1, d)
    rho1 = jnp.where(good, rho1, rho)
    return w1, d1, rho1, out


def _precondition_2d(state: OnlineNaturalGradientState, x: jax.Array,
                     hp: NGDHyperParams
                     ) -> Tuple[OnlineNaturalGradientState, jax.Array]:
    """Precondition a (N, dim) matrix; full semantics of
    _precondition_directions2 (ngd_optimizer.py:138-168) including lazy
    power-iteration init, norm-preserving rescale and NaN fallback."""
    rank = state.w.shape[0]
    tr_xxt = jnp.sum(x * x)
    w, d, rho = lax.cond(
        state.t == 0,
        lambda carry: _member_init(x, tr_xxt, rank, hp),
        lambda carry: carry,
        (state.w, state.d, state.rho))

    updating = jnp.logical_or(state.t < NUM_INITIAL_ITERS,
                              state.t % hp.update_period == 0)
    (w1, d1, rho1), x_hat = _core_step(w, d, rho, x, tr_xxt, updating, hp)
    w1, d1, rho1, out = _member_finalize(w, d, rho, w1, d1, rho1, x, x_hat,
                                         tr_xxt)
    return OnlineNaturalGradientState(w1, d1, rho1, state.t + 1), out


def _group_precondition(gw, gd, grho, t, xs, hp: NGDHyperParams):
    """Vmapped precondition for a GROUP of same-shaped axis-states.

    gw: (G, rank, dim), gd: (G, rank), grho: (G,), xs: (G, N, dim); `t` is
    the SHARED scalar step counter — every state in a training run is
    preconditioned every step, so the counters are always in lockstep
    (the reference keeps one `t` per OnlineNaturalGradient but they all
    advance identically, ngd_optimizer.py:186).  Keeping `t` scalar keeps
    the lax.cond predicates unbatched, so under vmap the update stays a
    real branch (executed every update_period steps) instead of being
    flattened into always-executed selects."""
    rank = gw.shape[1]
    trs = jnp.sum(xs * xs, axis=(1, 2))

    init_all = jax.vmap(lambda x, tr: _member_init(x, tr, rank, hp))
    gw, gd, grho = lax.cond(
        t == 0,
        lambda carry: init_all(xs, trs),
        lambda carry: carry,
        (gw, gd, grho))

    updating = jnp.logical_or(t < NUM_INITIAL_ITERS,
                              t % hp.update_period == 0)

    def member(w, d, rho, x, tr):
        (w1, d1, rho1), x_hat = _core_step(w, d, rho, x, tr, updating, hp)
        return _member_finalize(w, d, rho, w1, d1, rho1, x, x_hat, tr)

    gw1, gd1, grho1, outs = jax.vmap(member)(gw, gd, grho, xs, trs)
    return gw1, gd1, grho1, outs


def precondition(state: OnlineNaturalGradientState, grad: jax.Array,
                 axis: int, hp: NGDHyperParams
                 ) -> Tuple[OnlineNaturalGradientState, jax.Array]:
    """Precondition `grad` along `axis` (ngd_optimizer.py:102-118): move the
    axis last, flatten the rest, run the 2-D core, restore the layout."""
    dim = grad.shape[axis]
    if dim == 1:
        return state, grad
    moved = jnp.moveaxis(grad, axis, -1)
    flat = moved.reshape(-1, dim)
    state, out = _precondition_2d(state, flat, hp)
    return state, jnp.moveaxis(out.reshape(moved.shape), -1, axis)


def self_test(w: jax.Array, d: jax.Array, rho: jax.Array,
              hp: NGDHyperParams) -> Dict[str, jax.Array]:
    """Jittable invariant check on one axis-state — the reference's
    ``_self_test`` (``ngd_optimizer.py:330-345``) with asserts replaced by
    a dict of on-device booleans (usable inside jit / under vmap):

      * ``rho_floor``:   rho >= epsilon,
      * ``d_floor``:     min(d) >= epsilon and min(d) > 0.9*delta*max(d),
      * ``rho_vs_d``:    rho > 0.9*delta*max(d),
      * ``orthonormal``: max|W W^T ∘ (e^-1/2 e^-1/2ᵀ) − I| < 0.1, where
        e = 1/(beta/d + 1) — i.e. W's rows are orthogonal with squared
        norms e_i (the factorization the update maintains).

    ``ok`` is the conjunction.  The reference runs this only when
    ``debug`` is set and on NaN detection; here it also backs
    tests/test_optim.py's invariant checks after real update steps."""
    dim = w.shape[1]
    rank = w.shape[0]
    d_max, d_min = jnp.max(d), jnp.min(d)
    rho_floor = rho >= EPSILON
    d_floor = jnp.logical_and(d_min >= EPSILON, d_min > DELTA * d_max * 0.9)
    rho_vs_d = rho > DELTA * d_max * 0.9
    beta = rho * (1.0 + hp.alpha) + hp.alpha * jnp.sum(d) / dim
    e = 1.0 / (beta / d + 1.0)
    inv_sqrt_e = 1.0 / jnp.sqrt(e)
    should_be_zero = (w @ w.T) * jnp.outer(inv_sqrt_e, inv_sqrt_e) \
        - jnp.eye(rank, dtype=w.dtype)
    orthonormal = jnp.max(jnp.abs(should_be_zero)) < 0.1
    ok = rho_floor & d_floor & rho_vs_d & orthonormal
    return {"ok": ok, "rho_floor": rho_floor, "d_floor": d_floor,
            "rho_vs_d": rho_vs_d, "orthonormal": orthonormal}


def self_test_all(opt_state,
                  hp: Optional[NGDHyperParams] = None) -> Dict[str, Any]:
    """Validate every Fisher factor inside an optimizer state tree.

    Walks `opt_state` (e.g. the whole optax chain state) for
    ScaleByNGDState leaves and runs `self_test` on each grouped /
    ungrouped axis-state that has been initialized (t > 0).  Returns
    {"ok": bool, "failures": [(name, check_dict), ...]} with everything
    pulled to host — this is a debugging/validation surface, not a step
    -time path (cf. ngd_optimizer.py:46 `debug` flag).

    Groups whose direction count n is below the factor rank are SKIPPED
    (reported in "skipped"): with fewer than `rank` rows per step the
    rank-R factorization is under-determined and the orthonormality
    invariant legitimately does not hold — verified against the torch
    reference, whose own `_self_test` fails on e.g. a bias vector
    (N=1, dim=8, rank=4); it goes unnoticed there only because `debug`
    defaults to False.

    Pass the run's actual `hp` when alpha differs from the default — the
    orthonormality target e = 1/(beta/d + 1) depends on it.

    Ungrouped axis-states (scale_by_ngd(grouped=False)) carry no record
    of their direction count, so the under-determined case cannot be
    detected there; for them only the floor invariants gate `ok` and the
    orthonormality result is reported per-state without failing the
    check."""
    failures = []
    skipped = []
    checked = 0

    def check(name, w, d, rho, hp, gate_orthonormal=True):
        nonlocal checked
        checked += 1
        res = jax.device_get(self_test(w, d, rho, hp))
        ok = bool(res["ok"]) if gate_orthonormal else bool(
            res["rho_floor"] & res["d_floor"] & res["rho_vs_d"])
        if not ok:
            failures.append((name, {k: bool(v) for k, v in res.items()}))

    hp = hp or NGDHyperParams()  # invariants depend on alpha
    for s in jax.tree.leaves(
            opt_state, is_leaf=lambda x: isinstance(x, ScaleByNGDState)):
        if not isinstance(s, ScaleByNGDState):
            continue
        if int(jax.device_get(s.t)) == 0:
            continue  # never preconditioned — factors still at defaults
        for key, g in s.groups.items():
            # key format: "r{axis}:n{rows}:d{dim}:k{rank}" (_group_key)
            parts = {p[0]: int(p[1:]) for p in key.split(":")}
            if parts.get("n", 0) < parts.get("k", 0):
                skipped.append(key)
                continue
            for i in range(g.w.shape[0]):
                check(f"group[{key}][{i}]", g.w[i], g.d[i], g.rho[i], hp)
        for leaf_states in jax.tree.leaves(
                s.axes, is_leaf=lambda x: isinstance(
                    x, OnlineNaturalGradientState)):
            if isinstance(leaf_states, OnlineNaturalGradientState):
                check("axis_state", leaf_states.w, leaf_states.d,
                      leaf_states.rho, hp, gate_orthonormal=False)
    return {"ok": not failures, "checked": checked, "failures": failures,
            "skipped": skipped}


# ---------------------------------------------------------------------------
# optax wiring
# ---------------------------------------------------------------------------


class GroupState(NamedTuple):
    """Stacked factors for a group of same-shaped axis-states."""
    w: jax.Array     # (G, rank, dim)
    d: jax.Array     # (G, rank)
    rho: jax.Array   # (G,)


class ScaleByNGDState(NamedTuple):
    t: jax.Array                   # () int32 — shared step counter
    axes: Any                      # ungrouped mode: per-leaf tuples of
                                   # OnlineNaturalGradientState (or None)
    groups: Any                    # grouped mode: {key: GroupState}


def _param_axis_states(p: jax.Array, hp: NGDHyperParams, dtype
                       ) -> Tuple[Optional[OnlineNaturalGradientState], ...]:
    states = []
    for axis in range(p.ndim):
        dim = p.shape[axis]
        if 1 < dim <= hp.max_dim:
            states.append(init_ng_state(dim, hp, dtype))
        else:
            states.append(None)
    return tuple(states)


def _group_key(r: int, n: int, dim: int, rank: int) -> str:
    return f"r{r}:n{n}:d{dim}:k{rank}"


def _build_plan(shapes, hp: NGDHyperParams):
    """Static grouping plan: rounds[r] maps (n, dim, rank) -> leaf indices.
    Round r preconditions axis r of every leaf with >r axes (sequential
    dependency between rounds, parallel within — the reference's axis loop,
    ngd_optimizer.py:489-491)."""
    max_nd = max((len(s) for s in shapes), default=0)
    rounds = []
    for r in range(max_nd):
        groups: Dict[Tuple[int, int, int], list] = {}
        for i, shp in enumerate(shapes):
            if len(shp) > r and 1 < shp[r] <= hp.max_dim:
                dim = int(shp[r])
                n = int(np.prod(shp)) // dim
                rank_ = _default_rank(dim, hp.rank)
                groups.setdefault((n, dim, rank_), []).append(i)
        rounds.append(groups)
    return rounds


def scale_by_ngd(alpha: float = 4.0, rank: int = -1, update_period: int = 4,
                 eta: float = 0.1, precond_dtype=jnp.float32,
                 grouped: bool = True,
                 max_dim: int = 8192) -> optax.GradientTransformation:
    """The preconditioning stage of the reference's NGD.step
    (ngd_optimizer.py:481-491): per param, per axis with dim>1, apply the
    online natural gradient sequentially (axis 0, then 1, ...).

    grouped=True (default) batches all same-shaped axis-states per round
    into stacked arrays and vmaps the core — turning ~600 tiny eigh/matmul
    sites in a ResNet-50 graph into ~30 batched ones.  This is a pure
    program-structure change: the math per state is identical (covered by
    an equivalence test against the ungrouped path)."""
    hp = NGDHyperParams(alpha=alpha, rank=rank, update_period=update_period,
                        eta=eta, max_dim=max_dim)

    # -------------------- grouped (default) --------------------
    def grouped_init(params):
        shapes = [tuple(np.shape(p)) for p in jax.tree.leaves(params)]
        plan = _build_plan(shapes, hp)
        groups = {}
        for r, round_groups in enumerate(plan):
            for (n, dim, rank_), members in round_groups.items():
                proto = init_ng_state(
                    dim, dataclasses.replace(hp, rank=rank_), precond_dtype)
                g = len(members)
                groups[_group_key(r, n, dim, rank_)] = GroupState(
                    w=jnp.broadcast_to(proto.w, (g,) + proto.w.shape),
                    d=jnp.broadcast_to(proto.d, (g,) + proto.d.shape),
                    rho=jnp.broadcast_to(proto.rho, (g,)),
                )
        return ScaleByNGDState(t=jnp.asarray(0, jnp.int32), axes=(),
                               groups=groups)

    def grouped_update(updates, state, params=None):
        del params
        flat, treedef = jax.tree.flatten(updates)
        orig_dtypes = [g.dtype for g in flat]
        work = [g.astype(precond_dtype) for g in flat]
        shapes = [tuple(np.shape(g)) for g in flat]
        plan = _build_plan(shapes, hp)
        new_groups = dict(state.groups)
        for r, round_groups in enumerate(plan):
            for (n, dim, rank_), members in round_groups.items():
                key = _group_key(r, n, dim, rank_)
                moved = [jnp.moveaxis(work[i], r, -1) for i in members]
                xs = jnp.stack([m.reshape(n, dim) for m in moved])
                gs = new_groups[key]
                gw, gd, grho, outs = _group_precondition(
                    gs.w, gs.d, gs.rho, state.t, xs, hp)
                new_groups[key] = GroupState(gw, gd, grho)
                for slot, i in enumerate(members):
                    out = outs[slot].reshape(moved[slot].shape)
                    work[i] = jnp.moveaxis(out, -1, r)
        out_flat = [g.astype(dt) for g, dt in zip(work, orig_dtypes)]
        return (treedef.unflatten(out_flat),
                ScaleByNGDState(t=state.t + 1, axes=(), groups=new_groups))

    # -------------------- ungrouped (reference-shaped) --------------------
    def ungrouped_init(params):
        axes = jax.tree.map(
            lambda p: _param_axis_states(p, hp, precond_dtype), params)
        return ScaleByNGDState(t=jnp.asarray(0, jnp.int32), axes=axes,
                               groups={})

    def ungrouped_update(updates, state, params=None):
        del params

        def per_leaf(g, ax_states):
            orig_dtype = g.dtype
            g = g.astype(precond_dtype)
            new_states = []
            for axis, st in enumerate(ax_states):
                if st is None:
                    new_states.append(None)
                    continue
                st, g = precondition(st, g, axis, hp)
                new_states.append(st)
            return g.astype(orig_dtype), tuple(new_states)

        flat_updates, treedef = jax.tree.flatten(updates)
        flat_axes = treedef.flatten_up_to(state.axes)
        out = [per_leaf(g, ax) for g, ax in zip(flat_updates, flat_axes)]
        new_updates = treedef.unflatten([o[0] for o in out])
        new_axes = treedef.unflatten([o[1] for o in out])
        return new_updates, ScaleByNGDState(t=state.t + 1, axes=new_axes,
                                            groups={})

    if grouped:
        return optax.GradientTransformation(grouped_init, grouped_update)
    return optax.GradientTransformation(ungrouped_init, ungrouped_update)


def ngd(learning_rate, momentum: float = 0.0, dampening: float = 0.0,
        weight_decay: float = 0.0, nesterov: bool = False,
        use_ngd: bool = True, alpha: float = 4.0, rank: int = -1,
        update_period: int = 4, eta: float = 0.1,
        precond_dtype=jnp.float32,
        grouped: bool = True,
        max_dim: int = 8192) -> optax.GradientTransformation:
    """Full NGD optimizer, matching NGD.step order (ngd_optimizer.py:452-508):
    weight decay → per-axis preconditioning → momentum/nesterov → -lr."""
    if nesterov and (momentum <= 0 or dampening != 0):
        raise ValueError("Nesterov momentum requires a momentum and zero "
                         "dampening")
    chain = []
    if weight_decay:
        chain.append(optax.add_decayed_weights(weight_decay))
    if use_ngd:
        chain.append(scale_by_ngd(alpha, rank, update_period, eta,
                                  precond_dtype, grouped=grouped,
                                  max_dim=max_dim))
    if momentum:
        # torch SGD momentum: buf = momentum*buf + (1-dampening)*g;
        # nesterov: d_p = g + momentum*buf — optax.trace matches.
        chain.append(optax.trace(decay=momentum, nesterov=nesterov))
        if dampening:
            # optax.trace has no dampening; emulate by scaling the update in.
            raise NotImplementedError("dampening != 0 is not supported")
    chain.append(optax.scale_by_learning_rate(learning_rate))
    return optax.chain(*chain)
