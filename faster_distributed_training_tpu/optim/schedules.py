"""LR schedules matching the reference pairings, as optax schedules.

The reference steps its torch schedulers once per *epoch*
(resnet50_test.py:628, transformer_test.py:291); optax schedules are
functions of the *update step*, so every constructor here takes
``steps_per_epoch`` and quantizes internally — same trajectory, no
per-epoch host intervention.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
import optax


def multistep(base_lr: float, milestones: Sequence[int] = (10, 20),
              gamma: float = 0.2, steps_per_epoch: int = 1) -> optax.Schedule:
    """MultiStepLR([10,20], 0.2) — resnet50_test.py:489."""
    boundaries = {int(m) * steps_per_epoch: gamma for m in milestones}
    return optax.piecewise_constant_schedule(base_lr, boundaries)


def cosine_annealing(base_lr: float, t_max: int = 200,
                     steps_per_epoch: int = 1,
                     eta_min: float = 0.0) -> optax.Schedule:
    """CosineAnnealingLR(T_max=200) — resnet50_test.py:494."""
    def schedule(step):
        epoch = step / steps_per_epoch
        return eta_min + (base_lr - eta_min) * 0.5 * (
            1.0 + jnp.cos(jnp.pi * jnp.minimum(epoch, t_max) / t_max))
    return schedule


def one_cycle(base_lr: float, epochs: int, steps_per_epoch: int,
              max_lr_factor: float = 5.0, pct_start: float = 0.3,
              div_factor: float = 25.0,
              final_div_factor: float = 1e4) -> optax.Schedule:
    """OneCycleLR(max_lr=5*lr) — transformer_test.py:224-226.  The reference
    (incorrectly) steps OneCycle per epoch with total_steps=epochs; we spread
    the same cycle over all update steps, which is the scheduler's intent."""
    total = max(1, epochs * steps_per_epoch)
    return optax.cosine_onecycle_schedule(
        transition_steps=total, peak_value=base_lr * max_lr_factor,
        pct_start=pct_start, div_factor=div_factor,
        final_div_factor=final_div_factor)


def step_decay(base_lr: float, step_size: int = 2, gamma: float = 0.2,
               steps_per_epoch: int = 1) -> optax.Schedule:
    """StepLR(2, gamma) — tuning/resnet50_tuning.py:435."""
    def schedule(step):
        epoch = step // steps_per_epoch
        return base_lr * gamma ** (epoch // step_size)
    return schedule
