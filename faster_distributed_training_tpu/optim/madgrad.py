"""MADGRAD and MirrorMADGRAD as optax transformations.

The reference consumes these from the external ``madgrad`` CUDA-ready
package (``resnet50_test.py:493``, ``transformer_test.py:220``).  Here
they are pure JAX, following Defazio & Jelassi, *Adaptivity without
Compromise* (MADGRAD), and the mirror-descent variant from the same
repository.

Per step k (0-based), with lr λ, momentum c_m, eps:
    lamb_k = λ * sqrt(k+1)
    s_{k+1} = s_k + lamb_k * g            (dual average of gradients)
    v_{k+1} = v_k + lamb_k * g^2          (dual average of squares)
    z_{k+1} = x_0 - s_{k+1} / (v_{k+1}^{1/3} + eps)
    x_{k+1} = (1 - c) x_k + c z_{k+1},    c = 1 - c_m

MirrorMADGRAD replaces the dual-averaging point x_0 with a mirror-descent
step on z itself:
    z_{k+1} = z_k - lamb_k * g / (v_{k+1}^{1/3} + eps)
    x_{k+1} = (1 - c) x_k + c z_{k+1}

Weight decay is L2 (added to the gradient), matching the package default.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax


class MadgradState(NamedTuple):
    step: jax.Array   # () int32
    s: optax.Updates  # gradient dual average (MADGRAD) — unused by mirror
    v: optax.Updates  # squared-gradient dual average
    z: optax.Updates  # x_0 copy (MADGRAD) or mirror point (MirrorMADGRAD)


def _tree_zeros_like(params):
    return jax.tree.map(jnp.zeros_like, params)


def _make(learning_rate, momentum, weight_decay, eps, mirror: bool
          ) -> optax.GradientTransformation:
    if not 0.0 <= momentum < 1.0:
        raise ValueError(f"momentum {momentum} must be in [0, 1)")

    def init_fn(params):
        return MadgradState(
            step=jnp.asarray(0, jnp.int32),
            s=_tree_zeros_like(params),
            v=_tree_zeros_like(params),
            z=jax.tree.map(jnp.copy, params),
        )

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError("madgrad requires params")
        lr = (learning_rate(state.step) if callable(learning_rate)
              else learning_rate)
        # int + 1.0 promotes to the ambient float width: f32 in training,
        # f64 under enable_x64 — so the fp64 oracle test pins full precision
        lamb = lr * jnp.sqrt(state.step + 1.0)
        ck = 1.0 - momentum

        if weight_decay:
            updates = jax.tree.map(lambda g, p: g + weight_decay * p,
                                   updates, params)

        v_new = jax.tree.map(lambda v, g: v + lamb * g * g, state.v, updates)
        if mirror:
            z_new = jax.tree.map(
                lambda z, g, v: z - lamb * g / (jnp.cbrt(v) + eps),
                state.z, updates, v_new)
            s_new = state.s
        else:
            s_new = jax.tree.map(lambda s, g: s + lamb * g, state.s, updates)
            z_new = state.z  # x_0, never changes
        # x_{k+1} = (1-c) x_k + c z_{k+1}; emit the delta for optax
        if mirror:
            def delta(p, z):
                return ck * (z - p)
            new_updates = jax.tree.map(delta, params, z_new)
        else:
            def delta(p, z0, s, v):
                z = z0 - s / (jnp.cbrt(v) + eps)
                return ck * (z - p)
            new_updates = jax.tree.map(delta, params, z_new, s_new, v_new)
        return new_updates, MadgradState(state.step + 1, s_new, v_new, z_new)

    return optax.GradientTransformation(init_fn, update_fn)


def madgrad(learning_rate, momentum: float = 0.9, weight_decay: float = 0.0,
            eps: float = 1e-6) -> optax.GradientTransformation:
    return _make(learning_rate, momentum, weight_decay, eps, mirror=False)


def mirror_madgrad(learning_rate, momentum: float = 0.9,
                   weight_decay: float = 0.0,
                   eps: float = 1e-6) -> optax.GradientTransformation:
    return _make(learning_rate, momentum, weight_decay, eps, mirror=True)
