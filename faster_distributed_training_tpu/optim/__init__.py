"""Optimizers and schedules.

The reference pairs (resnet50_test.py:486-494, transformer_test.py:216-226,
tuning/resnet50_tuning.py:431-440):
  * --ngd        → NGD(momentum .9, wd 1e-4) + MultiStepLR([10,20], 0.2)
  * resnet else  → MADGRAD + CosineAnnealingLR(T_max=200)
  * transformer  → NGD or MirrorMADGRAD + OneCycleLR(max_lr=5*lr)
  * tuning       → NGD + StepLR(2, gamma) or SGD + CosineAnnealing

Everything here is a pure optax GradientTransformation whose state lives
on device (the reference's NGD round-trips to host for every Fisher
update, ngd_optimizer.py:225,240,265,285-289 — the #1 perf hazard
SURVEY.md §7 flags).
"""

from faster_distributed_training_tpu.optim.ngd import (  # noqa: F401
    NGDHyperParams, OnlineNaturalGradientState, init_ng_state, ngd,
    precondition, scale_by_ngd, self_test, self_test_all)
from faster_distributed_training_tpu.optim.madgrad import (  # noqa: F401
    madgrad, mirror_madgrad)
from faster_distributed_training_tpu.optim.schedules import (  # noqa: F401
    cosine_annealing, multistep, one_cycle, step_decay)
from faster_distributed_training_tpu.optim.builder import (  # noqa: F401
    build_optimizer)
