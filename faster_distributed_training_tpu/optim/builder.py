"""Optimizer + schedule pairing, mirroring the reference's get_optimizer
selection logic (resnet50_test.py:486-494, transformer_test.py:216-226,
tuning/resnet50_tuning.py:431-440) behind one function."""

from __future__ import annotations

from typing import Optional, Tuple

import optax

from faster_distributed_training_tpu.config import TrainConfig
from faster_distributed_training_tpu.optim import schedules
from faster_distributed_training_tpu.optim.madgrad import (madgrad,
                                                           mirror_madgrad)
from faster_distributed_training_tpu.optim.ngd import ngd as _ngd


def build_optimizer(cfg: TrainConfig, steps_per_epoch: int,
                    lr_scale: float = 1.0
                    ) -> Tuple[optax.GradientTransformation, optax.Schedule]:
    """Returns (optimizer, schedule).  `lr_scale` is the xN-devices LR
    scaling the reference hard-codes as x4 (resnet50_test.py:482-483) —
    here it is the actual data-parallel world size."""
    base_lr = cfg.lr * lr_scale
    name = cfg.optimizer or ("ngd" if cfg.use_ngd else
                             ("mirror_madgrad" if cfg.model == "transformer"
                              else "madgrad"))
    sched_name = cfg.schedule or _default_schedule(name, cfg)

    if sched_name == "multistep":
        schedule = schedules.multistep(base_lr, (10, 20), cfg.gamma,
                                       steps_per_epoch)
    elif sched_name == "cosine":
        schedule = schedules.cosine_annealing(base_lr, 200, steps_per_epoch)
    elif sched_name == "onecycle":
        schedule = schedules.one_cycle(base_lr, cfg.epochs, steps_per_epoch)
    elif sched_name == "step":
        schedule = schedules.step_decay(base_lr, 2, cfg.gamma, steps_per_epoch)
    elif sched_name == "constant":
        schedule = optax.constant_schedule(base_lr)
    else:
        raise ValueError(f"unknown schedule {sched_name!r}")

    if name == "ngd":
        tx = _ngd(schedule, momentum=cfg.momentum,
                      weight_decay=cfg.weight_decay, use_ngd=True,
                      alpha=cfg.ngd_alpha, rank=cfg.ngd_rank,
                      update_period=cfg.ngd_update_period, eta=cfg.ngd_eta,
                      max_dim=cfg.ngd_max_dim)
    elif name == "sgd":
        tx = _ngd(schedule, momentum=cfg.momentum,
                      weight_decay=cfg.weight_decay, use_ngd=False)
    elif name == "madgrad":
        tx = madgrad(schedule, momentum=cfg.momentum,
                              weight_decay=cfg.weight_decay)
    elif name == "mirror_madgrad":
        tx = mirror_madgrad(schedule, momentum=cfg.momentum,
                                     weight_decay=cfg.weight_decay)
    elif name == "adamw":
        tx = optax.adamw(schedule, weight_decay=cfg.weight_decay)
    else:
        raise ValueError(f"unknown optimizer {name!r}")

    if cfg.clip_norm:
        # unscale -> clip_grad_norm_(10) -> step (resnet50_test.py:544-547)
        tx = optax.chain(optax.clip_by_global_norm(cfg.clip_norm), tx)
    return tx, schedule


def _default_schedule(optimizer: str, cfg: TrainConfig) -> str:
    if cfg.model == "transformer":
        return "onecycle"                       # transformer_test.py:224
    if cfg.subset_stride > 1 and optimizer == "ngd":
        return "step"                           # tuning/resnet50_tuning.py:435
    if optimizer == "ngd":
        return "multistep"                      # resnet50_test.py:489
    return "cosine"                             # resnet50_test.py:494
