"""Synthetic datasets, shape- and dtype-compatible with the real ones.

Used by tests and benchmarks in zero-egress environments (no CIFAR/AG
News download possible) — the data *pipeline* code paths (sharding,
prefetch, augmentation, bucketing) are identical; only the bytes are
random.  Labels are derived from the data so models can overfit them
(useful for convergence smoke tests)."""

from __future__ import annotations

from typing import Dict, Sequence, Tuple

import numpy as np


def synthetic_cifar(n: int = 1024, seed: int = 0, num_classes: int = 10,
                    signal: float = 0.6, noise_std: float = 40.0
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """(NHWC uint8 images, int32 labels) with learnable class structure:
    class k images are noise biased by a per-class mean pattern.

    The prototypes come from a FIXED rng, independent of `seed` — `seed`
    only varies labels/noise.  Different splits (train seed 0, test seed
    1) therefore share the class structure, so generalization is
    measurable; deriving prototypes from `seed` would give every split
    its own classes and pin test accuracy at chance.

    signal/noise_std tune difficulty: the defaults make an easy task
    (tests overfit it in a few steps); the accuracy-evidence convergence
    runs lower the signal so the learning curve has a real shape instead
    of saturating in epoch 1 (FDT_SYNTH_SIGNAL/FDT_SYNTH_NOISE env
    overrides, read by cli.load_dataset)."""
    rng = np.random.default_rng(seed)
    proto_rng = np.random.default_rng(20260101)
    labels = rng.integers(0, num_classes, size=n).astype(np.int32)
    prototypes = proto_rng.integers(0, 256, size=(num_classes, 32, 32, 3))
    noise = rng.normal(0, noise_std, size=(n, 32, 32, 3))
    x = np.clip(prototypes[labels] * signal + noise + 50,
                0, 255).astype(np.uint8)
    return x, labels


def synthetic_agnews(n: int = 512, seed: int = 0, vocab: int = 30522,
                     num_classes: int = 4, max_len: int = 128):
    """An AGNewsDataset-compatible object with random token sequences."""
    rng = np.random.default_rng(seed)

    class _Synthetic:
        buckets = (64, 128, 256, 512)

        def __init__(self):
            self._labels = rng.integers(0, num_classes, n).astype(np.int32)
            self._lens = rng.integers(8, max_len, n)
            # class-dependent token distribution, consistent across
            # splits: every token is congruent to the label modulo
            # num_classes (uniform noise + a shared constant would stay
            # uniform — not learnable)
            self._tokens = [
                1000 + (rng.integers(0, (vocab - 1000) // num_classes,
                                     size=ln) * num_classes
                        + self._labels[i])
                for i, ln in enumerate(self._lens)]

        def __len__(self):
            return n

        def num_classes(self):
            return num_classes

        def vocab_size(self):
            return vocab

        def encode_batch(self, indices: Sequence[int], max_len: int = 512
                         ) -> Dict[str, np.ndarray]:
            from faster_distributed_training_tpu.data.loader import (
                select_bucket)
            seqs = [self._tokens[i][:max_len - 2] for i in indices]
            longest = max(len(s) + 2 for s in seqs)
            L = select_bucket(longest, self.buckets, max_len)
            tokens = np.zeros((len(seqs), L), np.int32)
            mask = np.zeros((len(seqs), L), np.int32)
            for i, s in enumerate(seqs):
                row = [101] + list(s) + [102]
                tokens[i, :len(row)] = row
                mask[i, :len(row)] = 1
            return {"tokens": tokens,
                    "token_types": np.zeros_like(tokens),
                    "mask": mask,
                    "label": self._labels[np.asarray(indices)]}

    return _Synthetic()
