"""CIFAR-10: pickled-batch loading with MD5 verification, NHWC numpy.

Re-expression of the reference's vendored CIFAR10 dataset
(resnet50_test.py:161-292): same download URL and per-file MD5 table
semantics, but decoded once into contiguous NHWC uint8 arrays instead of
per-sample __getitem__ (TPU pipelines want whole-epoch tensors the
augmentation can vmap over).  The reference's one behavioral change over
torchvision — returning normalized float tensors instead of PIL
(resnet50_test.py:264) — is inherited: `load_cifar10(normalize=True)`
hands back float32 arrays already normalized."""

from __future__ import annotations

import os
import pickle
from typing import Dict, Tuple

import numpy as np

from faster_distributed_training_tpu.data import download as dl

URL = "https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz"
TGZ_MD5 = "c58f30108f718f92721af3b95e74349a"
BASE = "cifar-10-batches-py"
TRAIN_FILES = {
    "data_batch_1": "c99cafc152244af753f735de768cd75f",
    "data_batch_2": "d4bba439e000b95fd0a9bffe97cbabec",
    "data_batch_3": "54ebc095f3ab1f0389bbae665268c751",
    "data_batch_4": "634d18415352ddfa80567beed471001a",
    "data_batch_5": "482c414d41f54cd18b22e5b47cb7c3cb",
}
TEST_FILES = {"test_batch": "40351d587109b95175f43aff81a1287e"}

# the reference's normalize constants (resnet50_test.py:306,315)
CIFAR10_MEAN = np.asarray([0.4914, 0.4822, 0.4465], np.float32)
CIFAR10_STD = np.asarray([0.2023, 0.1994, 0.2010], np.float32)


def _load_batches(root: str, files: Dict[str, str], verify: bool
                  ) -> Tuple[np.ndarray, np.ndarray]:
    images, labels = [], []
    for name, md5 in files.items():
        path = os.path.join(root, BASE, name)
        if verify and not dl.check_integrity(path, md5):
            raise RuntimeError(f"corrupt or missing CIFAR batch: {path}")
        with open(path, "rb") as f:
            entry = pickle.load(f, encoding="latin1")
        images.append(entry["data"])
        labels.extend(entry.get("labels", entry.get("fine_labels")))
    # (N, 3072) row-major CHW -> NHWC uint8
    x = np.vstack(images).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    return np.ascontiguousarray(x), np.asarray(labels, np.int32)


def load_cifar10(data_dir: str, train: bool = True, download: bool = True,
                 verify: bool = True, normalize: bool = False
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """Returns (images NHWC uint8 [or normalized float32], labels int32)."""
    files = TRAIN_FILES if train else TEST_FILES
    present = all(os.path.isfile(os.path.join(data_dir, BASE, n))
                  for n in files)
    if not present and download:
        dl.download_and_extract_archive(URL, data_dir, md5=TGZ_MD5)
    x, y = _load_batches(data_dir, files, verify)
    if normalize:
        x = (x.astype(np.float32) / 255.0 - CIFAR10_MEAN) / CIFAR10_STD
    return x, y
