"""Batch loading: per-host sharding, background prefetch, device staging.

Replaces the reference's DataLoaderX/BackgroundGenerator + pin_memory +
non_blocking H2D stack (resnet50_test.py:41-43,321-352) and
DistributedSampler (:331):

  * ``shard_for_host`` — every process loads only its slice of the
    global batch, reshuffled per epoch (the reference's ResNet loop
    forgets ``set_epoch``, SURVEY.md §5 — fixed here);
  * ``PrefetchIterator`` — a daemon thread keeps a bounded queue of
    ready batches (BackgroundGenerator equivalent);
  * ``device_prefetch`` — stages the next batch onto device while the
    current one computes (the pin_memory+non_blocking double-buffer,
    TPU style);
  * ``drop_last`` is always on for static shapes (resnet50_test.py:330).
"""

from __future__ import annotations

import queue
import threading
from typing import (Any, Callable, Dict, Iterable, Iterator, List, Optional,
                    Sequence, Tuple)

import jax
import numpy as np


def dataset_len(data) -> int:
    """Sample count of either dataset kind: text datasets expose
    ``encode_batch``/__len__, array datasets are (x, y) tuples."""
    return len(data) if hasattr(data, "encode_batch") else len(data[0])


def eligible_buckets(buckets: Sequence[int],
                     max_len: Optional[int] = None) -> Tuple[int, ...]:
    """The bucket lengths actually in play at ``max_len``: the
    configured set capped at max_len, falling back to [max_len] when
    none fit (a 16-token seq_len on the default (64,...,512) buckets
    serves one L=16 bucket).  ONE implementation site — encode_batch's
    filter, the serving queue's bins and run_serving's engine warmup
    must agree on this set or a request could land in a length no
    program compiled for."""
    out = tuple(sorted({int(b) for b in buckets
                        if max_len is None or b <= max_len}))
    return out or (int(max_len),)


def select_bucket(n: int, buckets: Sequence[int],
                  max_len: Optional[int] = None) -> int:
    """The padded length a sequence of ``n`` real tokens runs at: the
    smallest eligible bucket >= n (the last eligible bucket truncates —
    data/agnews.py's ``bucket_length`` rule).  This is the ONE
    bucket-selection rule shared by the training text pipeline
    (encode_batch) and the serving request queue (serve/queue.py): a
    serving request lands in a length the training programs already
    compiled for, so no request mix can retrace."""
    from faster_distributed_training_tpu.data.agnews import bucket_length
    return bucket_length(int(n), list(eligible_buckets(buckets, max_len)))


def shard_for_host(n: int, epoch: int, seed: int = 0, shuffle: bool = True,
                   process_index: Optional[int] = None,
                   process_count: Optional[int] = None, pad: bool = False):
    """Global permutation (identical on every host — seeded by (seed, epoch))
    sliced to this host's contiguous shard.

    pad=False (training): truncate to ``(n // pc) * pc`` — global
    drop-last, matching the static-shape training semantics.
    pad=True (eval): ceil-div shard — the global list is padded to
    ``ceil(n/pc) * pc`` with repeated samples marked INVALID, so every
    one of the n samples lands on exactly one host and test accuracy is
    exact at any process count (VERDICT r2 weak #4: the truncating
    shard dropped up to pc-1 samples from the reported full-split
    metric).  Returns ``(indices, valid)`` instead of ``indices``."""
    pi = jax.process_index() if process_index is None else process_index
    pc = jax.process_count() if process_count is None else process_count
    if shuffle:
        order = np.random.default_rng((seed, epoch)).permutation(n)
    else:
        order = np.arange(n)
    if pad:
        per = -(-n // pc)
        extra = per * pc - n
        # modulo-tile the pad region: extra can exceed n when the split
        # is smaller than the process count (n < pc), and every host
        # must still get a full-length shard for lockstep eval
        padded = np.concatenate(
            [order, order[np.arange(extra, dtype=np.intp) % max(n, 1)]])
        valid = np.concatenate(
            [np.ones(n, np.bool_), np.zeros(extra, np.bool_)])
        sl = slice(pi * per, (pi + 1) * per)
        return padded[sl], valid[sl]
    per = n // pc
    return order[pi * per:(pi + 1) * per]


def pod_epoch_order(n: int, epoch: int, seed: int = 0, shuffle: bool = True,
                    process_count: Optional[int] = None,
                    local_batch_size: int = 1) -> np.ndarray:
    """The GLOBAL per-epoch batch stream of a ``process_count``-host pod
    as one flat int32 index array — the pure function the sharded
    device-resident path gathers from in-graph.

    The host path's contract: host ``pi`` iterates
    ``shard_for_host(n, epoch, seed)[pi]`` in ``local_batch_size``
    chunks and ``make_array_from_process_local_data`` concatenates the
    per-host chunks (process-major) into each global batch.  This
    function emits exactly that sequence: entry
    ``b * (pc * lbs) + pi * lbs + j`` is host ``pi``'s ``j``-th sample
    of global batch ``b`` — so slicing ``[b * bs : (b + 1) * bs]`` off
    the result reproduces global batch ``b`` bitwise
    (tests/test_pod_scale.py pins this against ``BatchLoader.plan()``
    for simulated 2- and 4-process layouts).

    ``process_count=1`` degenerates to the single-host
    ``shard_for_host(...)[: steps * bs]`` order the r8 resident path
    uploads — the two paths share one batch-order algebra."""
    pc = jax.process_count() if process_count is None else int(process_count)
    lbs = int(local_batch_size)
    per = n // pc
    steps = per // lbs
    if shuffle:
        order = np.random.default_rng((seed, epoch)).permutation(n)
    else:
        order = np.arange(n)
    # per-host contiguous shards (shard_for_host's slicing), each
    # truncated to whole local batches, interleaved batch-major
    shards = order[: per * pc].reshape(pc, per)[:, : steps * lbs]
    return np.ascontiguousarray(
        shards.reshape(pc, steps, lbs).transpose(1, 0, 2).reshape(-1)
        .astype(np.int32))


def verify_host_shards(n: int, epoch: int, seed: int = 0,
                       shuffle: bool = True,
                       process_count: Optional[int] = None) -> None:
    """LOCAL validation of the sharding algebra: simulating every process
    with THIS host's (n, seed, epoch) config, the shards must be pairwise
    disjoint and tile exactly the first (n // pc) * pc entries of one
    global permutation.  This checks the partition logic and this host's
    config; it cannot see another host's actual state — for that, use
    ``verify_host_shards_global``.  O(n) host-side; run under ``--debug``
    or in tests, not per step."""
    pc = jax.process_count() if process_count is None else process_count
    shards = [shard_for_host(n, epoch, seed, shuffle, pi, pc)
              for pi in range(pc)]
    allidx = np.concatenate(shards)
    if len(np.unique(allidx)) != len(allidx):
        raise AssertionError(
            f"host shards overlap (epoch {epoch}, {pc} processes): "
            f"{len(allidx) - len(np.unique(allidx))} duplicated samples")
    per = n // pc
    if len(allidx) != per * pc:
        raise AssertionError(
            f"host shards mis-sized: {len(allidx)} != {per * pc}")
    full = np.random.default_rng((seed, epoch)).permutation(n) if shuffle \
        else np.arange(n)
    if not np.array_equal(np.sort(allidx), np.sort(full[:per * pc])):
        raise AssertionError("host shards do not tile the global permutation")


def _check_shard_digests(digests: np.ndarray) -> None:
    """Pure cross-host consistency check on stacked per-host digests
    (rows: [n, process_count, seed, epoch, shard_hash]).  Raises when hosts
    disagree on the sharding inputs (different dataset size / world size /
    seed / epoch — i.e. different global permutations: the set_epoch-style
    desync, SURVEY.md §5) or when two hosts hold byte-identical shards
    (every rank reading the same data: the forgotten-DistributedSampler
    failure mode, resnet50_test.py:331)."""
    digests = np.asarray(digests)
    for col, what in ((0, "dataset size n"), (1, "process_count"),
                      (2, "seed"), (3, "epoch")):
        if not (digests[:, col] == digests[0, col]).all():
            raise AssertionError(
                f"hosts disagree on {what}: {digests[:, col].tolist()} — "
                f"each host is drawing from a different permutation")
    per = int(digests[0, 0]) // max(int(digests[0, 1]), 1)
    if digests.shape[0] > 1 and per > 0:
        # empty shards (n < pc, smoke-sized subsets) all hash alike —
        # only non-empty byte-equal shards indicate duplication
        hashes = digests[:, 4]
        if len(np.unique(hashes)) != len(hashes):
            raise AssertionError(
                "two hosts hold identical data shards — every rank is "
                "loading the same slice (DistributedSampler-forgotten bug)")


def verify_host_shards_global(n: int, epoch: int, seed: int = 0,
                              shuffle: bool = True) -> None:
    """CROSS-HOST validation: allgathers each host's actual sharding inputs
    + a 64-bit hash of its real index shard and checks agreement/disjointness
    (see _check_shard_digests).  Agreement on (n, pc, seed, epoch) plus the
    locally-verified algebra implies globally disjoint shards.  No-op
    guarantees on a single process.  Collective — every process must call
    it at the same point."""
    import hashlib

    shard = shard_for_host(n, epoch, seed, shuffle)
    # 64-bit sha1 prefix, not crc32: a 1-in-2^32 collision between two
    # healthy (distinct) shards would abort a multi-host run with a false
    # "identical shards" error; 2^64 makes that practically impossible.
    shard_hash = int.from_bytes(
        hashlib.sha1(np.ascontiguousarray(shard).tobytes()).digest()[:8],
        "little", signed=True)
    digest = np.asarray([n, jax.process_count(), seed, epoch, shard_hash],
                        dtype=np.int64)
    if jax.process_count() == 1:
        _check_shard_digests(digest[None])
        return
    from jax.experimental import multihost_utils
    _check_shard_digests(multihost_utils.process_allgather(digest))


class BatchLoader:
    """Iterates dict batches from an array dataset (images) or an
    ``encode_batch``-style text dataset, host-sharded.

    drop_last semantics are split by purpose:
      * training (``pad_last=False``): the trailing partial batch is
        dropped for static shapes (resnet50_test.py:330);
      * eval (``pad_last=True``): ceil-div host sharding (every sample
        lands on exactly one host, pad entries marked invalid) plus a
        final partial batch padded to ``batch_size``; EVERY batch
        carries a float ``valid`` mask (1 real / 0 pad) — one compiled
        eval program covers the whole split and no sample is excluded
        from test accuracy at ANY batch size or process count (the
        reference evaluates the full 10k split,
        resnet50_test.py:631-659; r2's truncating shard dropped up to
        pc-1 samples multi-host — fixed).
    """

    def __init__(self, data, batch_size: int, epoch: int = 0, seed: int = 0,
                 shuffle: bool = True, max_len: int = 512,
                 pad_last: bool = False,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None):
        self.data = data
        self.batch_size = batch_size
        self.epoch = epoch
        self.seed = seed
        self.shuffle = shuffle
        self.max_len = max_len
        self.pad_last = pad_last
        self._pi, self._pc = process_index, process_count
        self.is_text = hasattr(data, "encode_batch")
        self._n = dataset_len(data)

    def __len__(self) -> int:
        pc = self._pc if self._pc is not None else jax.process_count()
        if self.pad_last:
            per = -(-self._n // pc)          # ceil-div shard (exact eval)
            return -(-per // self.batch_size)
        return (self._n // pc) // self.batch_size

    def _load(self, batch_idx: np.ndarray) -> Dict[str, np.ndarray]:
        if self.is_text:
            return dict(self.data.encode_batch(batch_idx, self.max_len))
        x, y = self.data
        from faster_distributed_training_tpu.runtime import native_lib
        xb = (native_lib.gather_u8(x, batch_idx)
              if isinstance(x, np.ndarray) and x.dtype == np.uint8
              else None)
        return {"image": xb if xb is not None else x[batch_idx],
                "label": y[batch_idx]}

    def plan(self) -> List[Tuple[np.ndarray, Optional[np.ndarray]]]:
        """The epoch's batch schedule: [(indices[bs], valid_mask|None)].
        Separated from materialization so worker threads
        (ParallelBatchIterator) can load batches concurrently in order."""
        bs = self.batch_size
        out: List[Tuple[np.ndarray, Optional[np.ndarray]]] = []
        if self.pad_last:
            idx, validity = shard_for_host(
                self._n, self.epoch, self.seed, self.shuffle,
                self._pi, self._pc, pad=True)
            validity = validity.astype(np.float32)
            full = (len(idx) // bs) * bs
            for start in range(0, full, bs):
                out.append((idx[start:start + bs],
                            validity[start:start + bs]))
            tail = len(idx) - full
            if tail:
                pad = idx[np.zeros(bs - tail, np.intp)]  # any real sample
                valid = np.concatenate(
                    [validity[full:], np.zeros(bs - tail, np.float32)])
                out.append((np.concatenate([idx[full:], pad]), valid))
            return out
        idx = shard_for_host(self._n, self.epoch, self.seed, self.shuffle,
                             self._pi, self._pc)
        full = (len(idx) // bs) * bs
        for start in range(0, full, bs):
            out.append((idx[start:start + bs], None))
        return out

    def materialize(self, entry: Tuple[np.ndarray, Optional[np.ndarray]]
                    ) -> Dict[str, np.ndarray]:
        batch_idx, valid = entry
        batch = self._load(batch_idx)
        if valid is not None:
            batch["valid"] = valid
        return batch

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        for entry in self.plan():
            yield self.materialize(entry)


class PrefetchIterator:
    """Background-thread prefetch with a bounded queue — the
    BackgroundGenerator role (resnet50_test.py:41-43).

    An abandoned iterator (consumer stops early — preemption mid-epoch,
    an injected fault, a crashed train step) must not leave the worker
    blocked forever on a full queue: every ``put`` polls a cancel event,
    and :meth:`close` sets it, drains the queue so a blocked producer
    wakes immediately, and joins the thread.  The Trainer closes its
    epoch loader on any abnormal loop exit (train/loop.py)."""

    _DONE = object()
    _PUT_POLL_S = 0.2

    def __init__(self, iterable: Iterable, depth: int = 2):
        self._q: "queue.Queue" = queue.Queue(maxsize=depth)
        self._err: Optional[BaseException] = None
        self._cancel = threading.Event()

        def worker():
            try:
                for item in iterable:
                    if not self._put(item):
                        return      # cancelled: drop everything, no _DONE
                                    # (close() owns the shutdown)
            except BaseException as e:  # propagate into the consumer
                self._err = e
            finally:
                self._put(self._DONE)

        self._t = threading.Thread(target=worker, daemon=True)
        self._t.start()
        self._done = False

    def _put(self, item) -> bool:
        """Bounded put that gives up when the iterator is closed; returns
        False iff cancelled (the item is dropped)."""
        while not self._cancel.is_set():
            try:
                self._q.put(item, timeout=self._PUT_POLL_S)
                return True
            except queue.Full:
                continue
        return False

    def close(self) -> None:
        """Cancel the worker and reclaim its thread.  Idempotent; safe
        from the consumer at any point (including mid-iteration).  After
        close() the iterator behaves as exhausted."""
        self._cancel.set()
        # drain so a producer blocked in put() frees up within one poll
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._t.join(timeout=5.0)
        self._done = True

    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            # the worker is gone and the queue is empty — a second get()
            # would block forever (unlike a generator, which raises
            # StopIteration on every call after exhaustion).  A worker
            # failure stays sticky: every subsequent call re-raises it, so
            # an outer retry/drain loop can't mistake a crashed pipeline
            # for a cleanly exhausted one.
            if self._err is not None:
                raise self._err
            raise StopIteration
        item = self._q.get()
        if item is self._DONE:
            self._done = True
            if self._err is not None:
                raise self._err
            raise StopIteration
        return item


class ParallelBatchIterator:
    """Multi-worker batch loading — the reference's `--workers` DataLoader
    processes (resnet50_test.py:52,321-352), thread-flavored for TPU
    hosts: N threads materialize batches concurrently (the C++ core's
    tokenize/gather calls release the GIL, so threads genuinely overlap)
    and results are yielded strictly IN ORDER with a bounded number in
    flight.  Threads, not processes: the hot work is in native code, and
    device arrays/put_fn stay in one process."""

    def __init__(self, loader: BatchLoader, workers: int, depth: int = 4):
        self._loader = loader
        self._workers = max(int(workers), 1)
        self._depth = max(depth, self._workers)

    def __len__(self) -> int:
        return len(self._loader)

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        from concurrent.futures import ThreadPoolExecutor

        plan = self._loader.plan()
        with ThreadPoolExecutor(max_workers=self._workers) as ex:
            pending = []
            nxt = 0
            while nxt < len(plan) or pending:
                while nxt < len(plan) and len(pending) < self._depth:
                    pending.append(ex.submit(self._loader.materialize,
                                             plan[nxt]))
                    nxt += 1
                fut = pending.pop(0)
                yield fut.result()   # in-order; re-raises worker errors


def device_prefetch(iterator: Iterable, put_fn: Callable[[Any], Any],
                    depth: int = 2) -> Iterator:
    """Keep `depth` batches already transferred to device ahead of the
    consumer — overlaps H2D with compute like pin_memory+non_blocking
    (resnet50_test.py:522).  depth <= 0 = fully synchronous transfer
    per batch (the bag-of-tricks OFF arm: no double buffering)."""
    if depth <= 0:
        for item in iterator:
            yield put_fn(item)
        return
    staged = []
    it = iter(iterator)
    exhausted = False
    try:
        for _ in range(depth):
            staged.append(put_fn(next(it)))
    except StopIteration:
        exhausted = True
    while staged:
        if not exhausted:
            # stage the NEXT batch before yielding the current one so its
            # transfer overlaps the consumer's compute; once exhausted,
            # never call next() again — not every iterator keeps raising
            # StopIteration (PrefetchIterator's queue would block)
            try:
                staged.append(put_fn(next(it)))
            except StopIteration:
                exhausted = True
        yield staged.pop(0)
