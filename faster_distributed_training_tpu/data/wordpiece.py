"""WordPiece tokenization (BERT-style), zero-egress capable.

The reference tokenizes AG News with HuggingFace ``bert-base-uncased``
(transformer_test.py:96-104).  This module reproduces that tokenizer's
*algorithm* exactly — BasicTokenizer (lowercase, accent strip,
punctuation split, CJK isolation) + greedy longest-match WordPiece with
``##`` continuations — so that given the same ``vocab.txt`` the token
streams are identical to HF's.  Byte-parity with HF's own
``BasicTokenizer``/``WordpieceTokenizer`` classes (which are pure Python
and importable without any download) is enforced by
tests/test_wordpiece.py.

Vocabulary resolution is environment-aware:
  * a real BERT ``vocab.txt`` (data_dir or HF cache) → exact
    bert-base-uncased ids;
  * otherwise ``build_wordpiece_vocab`` trains a deterministic
    vocabulary from the corpus itself (whole-word frequency with
    character backoff — every word segments without [UNK]), laid out
    with BERT's special-token ids ([PAD]=0, [UNK]=100, [CLS]=101,
    [SEP]=102, [MASK]=103) so downstream code is vocab-source-agnostic.

The ASCII hot path (text already cleaned by data/agnews.clean_text)
runs in the native C++ core (fdt_wp_encode_batch) with this module as
the semantic reference and fallback.
"""

from __future__ import annotations

import os
import threading
import unicodedata
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# BERT special-token layout (bert-base-uncased vocab.txt:1-1000)
PAD, UNK, CLS, SEP, MASK = "[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"
_SPECIAL_IDS = {PAD: 0, UNK: 100, CLS: 101, SEP: 102, MASK: 103}


# --------------------------------------------------------------- basic text
# Character classes must match transformers.models.bert.tokenization_bert
# (_is_whitespace/_is_control/_is_punctuation) exactly.

def _is_whitespace(ch: str) -> bool:
    if ch in (" ", "\t", "\n", "\r"):
        return True
    return unicodedata.category(ch) == "Zs"


def _is_control(ch: str) -> bool:
    if ch in ("\t", "\n", "\r"):
        return False
    return unicodedata.category(ch).startswith("C")


def _is_punctuation(ch: str) -> bool:
    cp = ord(ch)
    if (33 <= cp <= 47 or 58 <= cp <= 64 or 91 <= cp <= 96
            or 123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


def _is_cjk(cp: int) -> bool:
    return (0x4E00 <= cp <= 0x9FFF or 0x3400 <= cp <= 0x4DBF
            or 0x20000 <= cp <= 0x2A6DF or 0x2A700 <= cp <= 0x2B73F
            or 0x2B740 <= cp <= 0x2B81F or 0x2B820 <= cp <= 0x2CEAF
            or 0xF900 <= cp <= 0xFAFF or 0x2F800 <= cp <= 0x2FA1F)


def basic_tokenize(text: str, do_lower_case: bool = True) -> List[str]:
    """HF BasicTokenizer(do_lower_case, strip_accents=None): clean control
    chars, isolate CJK, lowercase (+NFD accent strip), split punctuation."""
    cleaned = []
    for ch in text:
        cp = ord(ch)
        if cp == 0 or cp == 0xFFFD or _is_control(ch):
            continue
        cleaned.append(" " if _is_whitespace(ch) else ch)
    out = []
    for ch in "".join(cleaned):
        if _is_cjk(ord(ch)):
            out.append(" ")
            out.append(ch)
            out.append(" ")
        else:
            out.append(ch)
    # HF normalizes to NFC before whitespace-splitting (equivalent
    # codepoint sequences must tokenize identically)
    text = unicodedata.normalize("NFC", "".join(out))
    tokens = []
    for tok in text.split():
        if do_lower_case:
            tok = tok.lower()
            # strip_accents=None + do_lower_case => strip accents (HF)
            tok = "".join(c for c in unicodedata.normalize("NFD", tok)
                          if unicodedata.category(c) != "Mn")
        # split on punctuation, keeping each punctuation char as a token
        word: List[str] = []
        for ch in tok:
            if _is_punctuation(ch):
                if word:
                    tokens.append("".join(word))
                    word = []
                tokens.append(ch)
            else:
                word.append(ch)
        if word:
            tokens.append("".join(word))
    return tokens


def wordpiece_word(word: str, vocab: Dict[str, int],
                   max_chars: int = 100) -> List[str]:
    """Greedy longest-match-first segmentation of one basic token
    (HF WordpieceTokenizer.tokenize, single-word case)."""
    if len(word) > max_chars:
        return [UNK]
    pieces: List[str] = []
    start = 0
    while start < len(word):
        end = len(word)
        cur = None
        while start < end:
            piece = word[start:end]
            if start > 0:
                piece = "##" + piece
            if piece in vocab:
                cur = piece
                break
            end -= 1
        if cur is None:
            return [UNK]
        pieces.append(cur)
        start = end
    return pieces


class WordPieceTokenizer:
    """bert-base-uncased-compatible tokenizer over an explicit vocab.

    Exposes the interface subset the data pipeline uses from HF
    tokenizers: ``encode(text, truncation=..., max_length=...)``,
    ``vocab_size``, ``pad_token_id``."""

    def __init__(self, vocab: Dict[str, int], do_lower_case: bool = True):
        self.vocab = vocab
        self.do_lower_case = do_lower_case
        self.pad_token_id = vocab[PAD]
        self.unk_id = vocab[UNK]
        self.cls_id = vocab[CLS]
        self.sep_id = vocab[SEP]
        self._native_handle = -1          # -1 unset, None unavailable
        self._native_lock = threading.Lock()

    def vocab_lines(self) -> List[str]:
        by_id = {i: t for t, i in self.vocab.items()}
        return [by_id.get(i, f"[unused{i}]") for i in range(self.vocab_size)]

    def native_handle(self) -> Optional[int]:
        """Handle into the C++ core's vocab registry (fdt_wp_load), or
        None when the native library is unavailable.  Registered once;
        the lock matters because ParallelBatchIterator workers
        (--workers N) hit the first batches concurrently and the C++
        registry push_back is not synchronized."""
        with self._native_lock:
            if self._native_handle == -1:
                from faster_distributed_training_tpu.runtime import native_lib
                self._native_handle = native_lib.wp_load(self.vocab_lines())
            return self._native_handle

    @property
    def vocab_size(self) -> int:
        # model embedding size: one past the largest id (gap-tolerant)
        return max(self.vocab.values()) + 1

    @classmethod
    def from_vocab_file(cls, path: str, **kw) -> "WordPieceTokenizer":
        vocab: Dict[str, int] = {}
        with open(path, encoding="utf-8") as f:
            for i, line in enumerate(f):
                vocab[line.rstrip("\n")] = i
        return cls(vocab, **kw)

    def save_vocab(self, path: str) -> None:
        """HF vocab.txt format (line index = id); id gaps become
        [unusedN] fillers so the file round-trips exactly."""
        by_id = {i: t for t, i in self.vocab.items()}
        with open(path, "w", encoding="utf-8") as f:
            for i in range(max(self.vocab.values()) + 1):
                f.write(by_id.get(i, f"[unused{i}]") + "\n")

    def tokenize(self, text: str) -> List[str]:
        pieces: List[str] = []
        for word in basic_tokenize(text, self.do_lower_case):
            pieces.extend(wordpiece_word(word, self.vocab))
        return pieces

    def encode(self, text: str, truncation: bool = True,
               max_length: int = 512) -> List[int]:
        ids = [self.vocab.get(p, self.unk_id) for p in self.tokenize(text)]
        if truncation and len(ids) > max_length - 2:
            ids = ids[:max_length - 2]
        return [self.cls_id] + ids + [self.sep_id]


# ----------------------------------------------------------- vocab sources

def build_wordpiece_vocab(texts: Iterable[str], size: int = 30522,
                          do_lower_case: bool = True) -> Dict[str, int]:
    """Deterministic corpus-trained WordPiece vocabulary.

    Whole-word frequency with full character backoff: every character
    seen in the corpus enters the vocab both bare and as a ``##``
    continuation, then the most frequent whole words fill the remaining
    budget (count desc, token asc — fully deterministic).  Greedy
    longest-match over this vocab segments any corpus word without
    [UNK], and common words stay single tokens — the behavior that
    matters for classification accuracy when the real learned
    bert-base-uncased vocab file is unreachable (zero egress)."""
    counts: Dict[str, int] = {}
    chars: set = set()
    for text in texts:
        for word in basic_tokenize(text, do_lower_case):
            counts[word] = counts.get(word, 0) + 1
            chars.update(word)
    vocab: Dict[str, int] = dict(_SPECIAL_IDS)
    # [unused] fillers keep BERT's id layout (specials at 0/100-103)
    next_id = 0

    def alloc() -> int:
        nonlocal next_id
        while next_id in _SPECIAL_IDS.values():
            next_id += 1
        i = next_id
        next_id += 1
        return i

    for ch in sorted(chars):
        vocab[ch] = alloc()
        vocab["##" + ch] = alloc()
    budget = size - len(vocab)
    ranked = sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))
    for word, _ in ranked:
        if budget <= 0:
            break
        if word not in vocab:
            vocab[word] = alloc()
            budget -= 1
    return vocab


def find_bert_vocab(data_dir: str) -> Optional[str]:
    """Locate a real bert-base-uncased vocab.txt without network access:
    explicit data_dir copies first, then the HF hub cache layout."""
    candidates = [
        os.path.join(data_dir, "bert-base-uncased-vocab.txt"),
        os.path.join(data_dir, "vocab.txt"),
    ]
    hf_home = os.environ.get("HF_HOME",
                             os.path.expanduser("~/.cache/huggingface"))
    hub = os.path.join(hf_home, "hub", "models--bert-base-uncased",
                       "snapshots")
    if os.path.isdir(hub):
        for snap in sorted(os.listdir(hub)):
            candidates.append(os.path.join(hub, snap, "vocab.txt"))
    for path in candidates:
        if os.path.isfile(path):
            return path
    return None
