"""Device-side batched augmentation.

The reference's pipeline (resnet50_test.py:301-318): train =
RandomCrop(32, pad 4) + RandomHorizontalFlip + Normalize compiled with
TorchScript; eval = Normalize.  Quirk: the reference samples a random
*choice of 3* of those transforms ONCE at startup — possibly dropping
Normalize for the whole run (SURVEY.md §2).  We fix that (all three,
every step) and note the divergence.

TPU-first design: augmentation is a jittable function of (batch, key)
running on device — a few gathers and a flip fused into the step's
prologue, instead of per-sample host workers.  The crop is expressed as
a dynamic_slice via per-sample offsets gathered from a padded batch."""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from faster_distributed_training_tpu.data.cifar10 import (CIFAR10_MEAN,
                                                          CIFAR10_STD)


def normalize(x: jax.Array, mean=CIFAR10_MEAN, std=CIFAR10_STD) -> jax.Array:
    """uint8 NHWC -> normalized float32."""
    x = x.astype(jnp.float32) / 255.0
    return (x - jnp.asarray(mean)) / jnp.asarray(std)


def random_crop(key: jax.Array, x: jax.Array, padding: int = 4) -> jax.Array:
    """RandomCrop(H, padding=4) for the whole batch via vmapped
    dynamic_slice (static output shape — XLA-friendly)."""
    n, h, w, c = x.shape
    pad = ((0, 0), (padding, padding), (padding, padding), (0, 0))
    xp = jnp.pad(x, pad)
    off = jax.random.randint(key, (n, 2), 0, 2 * padding + 1)

    def crop_one(img, o):
        return jax.lax.dynamic_slice(img, (o[0], o[1], 0), (h, w, c))

    return jax.vmap(crop_one)(xp, off)


def random_flip(key: jax.Array, x: jax.Array) -> jax.Array:
    """Per-sample horizontal flip with p=0.5."""
    flip = jax.random.bernoulli(key, 0.5, (x.shape[0], 1, 1, 1))
    return jnp.where(flip, x[:, :, ::-1, :], x)


def augment_batch(key: jax.Array, x: jax.Array, train: bool = True,
                  padding: int = 4, mean=CIFAR10_MEAN, std=CIFAR10_STD
                  ) -> jax.Array:
    """Full train pipeline (crop+flip+normalize) or eval (normalize)."""
    if not train:
        return normalize(x, mean, std)
    k_crop, k_flip = jax.random.split(key)
    x = normalize(x, mean, std)
    x = random_crop(k_crop, x, padding)
    x = random_flip(k_flip, x)
    return x
