"""Memory-mapped reader over the sharded stream format (format.py).

``ShardedStreamDataset`` opens every shard leaf with
``np.load(mmap_mode="r")`` — rows are gathered straight out of the OS
page cache, so the refill thread's per-window read is bounded by disk
bandwidth on a cold cache and near-free on a warm one, with no
decompression and no whole-shard materialization.

The TEXT flavor (a ``content: "lm"`` manifest with a ``tokens`` leaf) also
exposes the ``encode_batch`` interface of the host text pipeline, so the
SAME on-disk dataset can run through every data path — host BatchLoader,
device-resident, streamed — which is what lets tests pin the streamed
batch stream bitwise against the resident reference."""

from __future__ import annotations

import json
import os
import warnings
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from faster_distributed_training_tpu.data.stream.format import (
    FORMAT, MANIFEST, checksum_file)


class ShardedStreamDataset:
    """Random row access over a committed stream-format directory."""

    def __init__(self, directory: str):
        self.directory = str(directory)
        mpath = os.path.join(self.directory, MANIFEST)
        if not os.path.isfile(mpath):
            raise FileNotFoundError(
                f"no {MANIFEST} in {self.directory} — not a committed "
                f"stream dataset (the manifest is written LAST: a missing "
                f"one means the writer never finished; re-run the shard "
                f"writer, e.g. scripts/shard_dataset.py)")
        with open(mpath) as f:
            self.manifest = json.load(f)
        if self.manifest.get("format") != FORMAT:
            raise ValueError(f"{mpath}: format "
                             f"{self.manifest.get('format')!r} != {FORMAT!r}")
        self.n = int(self.manifest["n"])
        self.leaf_spec: Dict[str, dict] = dict(self.manifest["leaves"])
        shards = self.manifest["shards"]
        rows = np.asarray([int(s["rows"]) for s in shards], np.int64)
        if int(rows.sum()) != self.n:
            raise ValueError(f"{mpath}: shard rows sum {int(rows.sum())} "
                             f"!= n {self.n} (torn manifest?)")
        # shard s covers global rows [starts[s], starts[s] + rows[s])
        self._starts = np.concatenate([[0], np.cumsum(rows)[:-1]])
        self._rows = rows
        # end-to-end integrity (format v1+ manifests carry per-file
        # CRCs): expected (path, alg, crc) per shard, verified LAZILY on
        # the first gather that touches the shard — which in the
        # streamed data path is the background window-refill thread, so
        # verification never blocks the dispatch loop.  A failed shard
        # is quarantined and its rows deterministically remapped to a
        # healthy shard (same remap on every process: pure function of
        # the manifest + CRC verdicts) — the run continues, never
        # crashes.  on_quarantine is the sentinel's wire-in
        # (cli.run_training -> Sentinel.quarantine_shard).
        self._crc: Dict[int, List[tuple]] = {}
        self._crc_checked = [False] * len(shards)
        self._bad_shards: set = set()
        self.on_quarantine: Optional[Callable[[int, str], None]] = None
        for si, s in enumerate(shards):
            for leaf, info in s["files"].items():
                if "crc32c" in info:
                    self._crc.setdefault(si, []).append(
                        (os.path.join(self.directory, info["file"]),
                         info.get("crc_alg", "crc32c"),
                         int(info["crc32c"])))
        self._mmaps: Dict[str, List[np.ndarray]] = {}
        for leaf, spec in self.leaf_spec.items():
            maps = []
            for s in shards:
                info = s["files"][leaf]
                path = os.path.join(self.directory, info["file"])
                size = os.path.getsize(path) if os.path.isfile(path) else -1
                if size != int(info["bytes"]):
                    raise ValueError(
                        f"{path}: {size} bytes on disk != {info['bytes']} "
                        f"in the manifest — truncated/torn shard file")
                m = np.load(path, mmap_mode="r")
                # EVERY shard's header vs the manifest spec (a same-size
                # file with a reinterpreted dtype/shape must fail at
                # open, not gather as silent garbage mid-epoch)
                want = (int(s["rows"]),) + tuple(spec["shape"])
                if m.shape != want or m.dtype.str != spec["dtype"]:
                    raise ValueError(
                        f"{path}: leaf {leaf!r} shard is "
                        f"{m.dtype}{m.shape}, manifest says "
                        f"{spec['dtype']}{want}")
                maps.append(m)
            self._mmaps[leaf] = maps
        self.is_text = "tokens" in self.leaf_spec
        self.seq_len = int(self.manifest.get("seq_len") or 0)
        self.nbytes_on_disk = sum(int(f["bytes"]) for s in shards
                                  for f in s["files"].values())

    def __len__(self) -> int:
        return self.n

    def vocab_size(self) -> int:
        return int(self.manifest.get("vocab_size") or 30522)

    def num_classes(self) -> int:
        return int(self.manifest.get("num_classes") or 0)

    def row_bytes(self) -> int:
        """Bytes of one sample across all leaves (window sizing)."""
        total = 0
        for leaf, spec in self.leaf_spec.items():
            total += int(np.dtype(spec["dtype"]).itemsize
                         * int(np.prod(spec["shape"] or [1])))
        return total

    def _verify_shard(self, s: int) -> None:
        """First-touch CRC verification of shard ``s`` (all leaves);
        a mismatch quarantines the shard (sentinel callback when wired,
        loud warning regardless) — it never raises."""
        if self._crc_checked[s]:
            return
        self._crc_checked[s] = True
        for path, alg, want in self._crc.get(s, ()):
            got = checksum_file(path, alg)
            if got is None or got == want:
                # None: alg not computable here (e.g. a crc32c-signed
                # manifest read where google_crc32c is absent) — cannot
                # verify, must not false-alarm
                continue
            self._bad_shards.add(s)
            msg = (f"stream shard {s} CRC mismatch ({path}: {alg} "
                   f"{got:#010x} != manifest {want:#010x}) — shard "
                   f"quarantined, rows remapped to a healthy shard")
            warnings.warn(msg, stacklevel=3)
            if self.on_quarantine is not None:
                try:
                    self.on_quarantine(s, path)
                except Exception:
                    pass  # integrity reporting must not kill the refill
            break

    def _screen(self, idx: np.ndarray, shard_of: np.ndarray):
        """Verify every shard ``idx`` touches; remap rows of
        quarantined shards onto the first healthy shard (position
        preserved modulo its row count — deterministic on every
        process).  Loops because a remap target needs verifying too;
        bounded by the shard count."""
        while True:
            for s in np.unique(shard_of):
                self._verify_shard(int(s))
            if not self._bad_shards:
                return idx, shard_of
            bad = np.isin(shard_of, sorted(self._bad_shards))
            if not bad.any():
                return idx, shard_of
            good = next((g for g in range(len(self._rows))
                         if g not in self._bad_shards), None)
            if good is None:
                raise RuntimeError(
                    f"{self.directory}: every stream shard failed its "
                    f"CRC check — nothing left to serve (restore the "
                    f"dataset or re-run the shard writer)")
            off = idx[bad] - self._starts[shard_of[bad]]
            idx = idx.copy()
            idx[bad] = self._starts[good] + off % self._rows[good]
            shard_of = np.searchsorted(self._starts, idx,
                                       side="right") - 1

    def gather(self, indices: Sequence[int]) -> Dict[str, np.ndarray]:
        """Rows at global ``indices`` (any order, repeats allowed) as
        compact host arrays — one vectorized fancy-index per touched
        shard per leaf, against the mmap (page-cache reads only)."""
        idx = np.asarray(indices, np.int64).reshape(-1)
        if idx.size and (idx.min() < 0 or idx.max() >= self.n):
            raise IndexError(f"stream gather index out of range [0, {self.n})")
        shard_of = np.searchsorted(self._starts, idx, side="right") - 1
        if self._crc and idx.size:
            idx, shard_of = self._screen(idx, shard_of)
        out: Dict[str, np.ndarray] = {}
        for leaf, spec in self.leaf_spec.items():
            dst = np.empty((idx.size,) + tuple(spec["shape"]),
                           np.dtype(spec["dtype"]))
            for s in np.unique(shard_of):
                sel = shard_of == s
                dst[sel] = self._mmaps[leaf][int(s)][idx[sel]
                                                     - self._starts[int(s)]]
            out[leaf] = dst
        return out

    # -- host text-pipeline compatibility (text flavor only) --------------

    def encode_batch(self, indices: Sequence[int], max_len: int = 512
                     ) -> Dict[str, np.ndarray]:
        """The host text pipeline's batch interface over PRE-TOKENIZED
        packed rows: a plain gather, truncated to ``max_len`` columns.
        Rows are packed (no padding), so the mask is all-ones and
        token_types/label are the zero constants the classification
        pipeline shapes expect — byte-identical leaves whichever data
        path (host / resident / streamed) serves the batch."""
        if not self.is_text:
            raise ValueError("encode_batch is only meaningful on the text "
                             "flavor (a 'tokens' leaf); image stream "
                             "datasets are consumed as (image, label) "
                             "arrays")
        rows = self.gather(indices)
        tokens = rows["tokens"]
        if max_len and max_len < tokens.shape[1]:
            tokens = np.ascontiguousarray(tokens[:, :max_len])
        out = {"tokens": tokens,
               "token_types": np.zeros_like(tokens),
               "mask": np.ones_like(tokens)}
        out["label"] = (rows["label"] if "label" in rows
                        else np.zeros(len(tokens), np.int32))
        return out


class _LazyShardRows:
    """Zero-copy concatenation view over one leaf's per-shard mmaps —
    the image flavor's host/resident adapter.  Behaves like the single
    ndarray the array pipelines consume: ``len()``, fancy row indexing
    (BatchLoader's ``x[batch_idx]`` becomes a per-shard mmap gather),
    strided slicing (``apply_subset``'s ``x[::stride]``), and
    ``np.asarray`` (the resident path's whole-split upload, which
    materializes by design) — WITHOUT concatenating the shards in host
    RAM, so a beyond-RAM split opened for the host path reads only the
    rows each batch asks for."""

    def __init__(self, ds: "ShardedStreamDataset", leaf: str):
        self._ds = ds
        self._leaf = leaf
        spec = ds.leaf_spec[leaf]
        self.dtype = np.dtype(spec["dtype"])
        self.shape = (ds.n,) + tuple(spec["shape"])

    def __len__(self) -> int:
        return self._ds.n

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            idx = np.arange(*idx.indices(self._ds.n))
        idx = np.asarray(idx)
        if idx.ndim == 0:
            return self._ds.gather(idx.reshape(1))[self._leaf][0]
        return self._ds.gather(idx)[self._leaf]

    def __array__(self, dtype=None):
        out = self._ds.gather(np.arange(self._ds.n))[self._leaf]
        return out.astype(dtype) if dtype is not None else out


def open_stream_split(stream_dir: str, train: bool):
    """The ``cli.load_dataset`` adapter for ``--dataset stream``: the
    text flavor returns the reader itself (it speaks ``encode_batch``),
    the image flavor returns an ``(image, label)`` pair the array
    pipelines consume — the shards' mmaps directly when there is one,
    a lazy row view (:class:`_LazyShardRows`) when there are many.
    ``<stream_dir>/{train,test}`` layout, as the writers produce."""
    ds = ShardedStreamDataset(
        os.path.join(stream_dir, "train" if train else "test"))
    if ds.is_text:
        return ds
    if "image" not in ds.leaf_spec or "label" not in ds.leaf_spec:
        raise ValueError(f"{ds.directory}: non-text stream dataset needs "
                         f"'image'+'label' leaves, has "
                         f"{sorted(ds.leaf_spec)}")
    img = ds._mmaps["image"]
    lab = ds._mmaps["label"]
    if len(img) == 1:
        # a memmap IS an ndarray: the pipelines (incl. gather_u8) use it
        return (img[0], lab[0])
    return (_LazyShardRows(ds, "image"), _LazyShardRows(ds, "label"))
