"""Beyond-HBM streaming data plane (r18).

The third rung of the input-pipeline ladder:

  host loader  ->  device-resident (replicated / pod-sharded)  ->  STREAM

``--data_path stream`` keeps the train split ON DISK in the sharded
stream format (format.py: raw per-leaf ``.npy`` shards + a manifest
committed last) and trains through a fixed device-resident window
refilled by a background double-buffered H2D stream (window.py, riding
PrefetchIterator's cancel/drain lifecycle).  Batch order is the same
``pod_epoch_order`` pure ``(seed, epoch, step)`` algebra as every other
path, so mid-epoch resume is a pure seek and kill-at-N resumes land
bitwise on the uninterrupted reference.

Produced by ``scripts/shard_dataset.py`` (LM text corpora via
``write_lm_corpus``; image splits via ``write_array_dataset``); proven
on the ``--task lm`` next-token workload through the transformer."""

from faster_distributed_training_tpu.data.stream.format import (  # noqa: F401,E501
    FORMAT, MANIFEST, pack_lm_rows, synthetic_corpus, write_array_dataset,
    write_lm_corpus, write_stream_dataset)
from faster_distributed_training_tpu.data.stream.reader import (  # noqa: F401,E501
    ShardedStreamDataset, open_stream_split)
from faster_distributed_training_tpu.data.stream.window import (  # noqa: F401,E501
    DiskStreamSource)


def build_stream(cfg, mesh=None, dataset=None):
    """cfg-gated constructor (the build_device_resident sibling): None
    unless ``cfg.data_path == "stream"``; else a DiskStreamSource over
    ``<cfg.stream_dir>/train``.  Pass the already-open reader as
    ``dataset`` to reuse its mmaps — at production shard counts a second
    open re-stats and re-maps every shard file."""
    import os

    if getattr(cfg, "data_path", "host") != "stream":
        return None
    stream_dir = getattr(cfg, "stream_dir", "") or ""
    if not stream_dir:
        raise ValueError("--data_path stream requires --stream_dir (a "
                         "sharded dataset root with train/ + test/ — "
                         "scripts/shard_dataset.py writes one)")
    if isinstance(dataset, ShardedStreamDataset):
        ds = dataset
    else:
        ds = ShardedStreamDataset(os.path.join(stream_dir, "train"))
    return DiskStreamSource(
        ds, cfg.batch_size, seed=cfg.seed, mesh=mesh,
        window_batches=getattr(cfg, "stream_window", 8),
        steps_per_dispatch=getattr(cfg, "steps_per_dispatch", 1),
        max_len=cfg.seq_len)
