"""Fixed-size device-resident window over an on-disk stream dataset.

The beyond-HBM tier between the host loader and full device residency:
the train split lives on disk (reader.py mmaps), and only a fixed
WINDOW of upcoming batches is resident on device at a time.  The window
is double-buffered — while the dispatch loop trains through buffer i
(``window`` batches, gathered in-graph by ``dynamic_index`` exactly like
the sharded-resident batch-major view), a background producer thread is
already disk-gathering AND ``device_put``-ing buffer i+1, so the H2D
stream hides under compute.  The producer rides
:class:`~faster_distributed_training_tpu.data.loader.PrefetchIterator`
(depth 1), inheriting its cancel/drain lifecycle: an abnormal epoch exit
(injected fault, preemption, crash) closes the window and the producer
thread is cancelled, drained and joined — never left blocked on a full
queue (the r8 contract, re-used rather than re-invented).

Batch order is ``loader.pod_epoch_order``'s pure ``(seed, epoch, step)``
algebra — identical to both resident layouts and the host loader — and
host ``pi`` materializes ONLY its own ``local_bs`` rows of each global
batch (per-host file reads; the device buffer is assembled with
``make_array_from_process_local_data`` on real pods).  Mid-epoch resume
is therefore a pure SEEK: ``epoch_window(epoch, start_step)`` begins the
refill stream at ``start_step`` and batch contents are a function of the
batch index alone, so a killed-at-N streamed run resumes bitwise on the
uninterrupted reference (tests/test_stream.py pins this against the
resident path).

Telemetry: each refill emits a ``stream_refill`` event (+ a
``stream_refill`` span from the producer thread, so the cost also lands
in the span breakdown / XLA trace vocabulary), and each buffer swap the
consumer had to WAIT for emits a ``stream_stall`` event — the numerator
of bench's ``stream_stall_pct`` (<1% steady-state target, the input-
pipeline sibling of ``ckpt_async_overhead_pct``)."""

from __future__ import annotations

import time
import warnings
from typing import Dict, Optional, Tuple

import jax
import numpy as np

from faster_distributed_training_tpu.data.loader import (PrefetchIterator,
                                                         pod_epoch_order)
from faster_distributed_training_tpu.telemetry import spans


class DiskStreamSource:
    """Run-scoped streaming source: owns the reader + window geometry.

    Duck-types the fused-dispatch ``resident`` interface with
    ``batch_major=True`` (train/steps.py gathers by ``dynamic_index`` on
    the unsharded leading axis), so the stream path reuses the resident
    scan program shape — only the leading axis is ``window`` batches
    deep instead of a whole epoch.

    ``process_index``/``process_count`` default to the real runtime and
    are the simulation seam the tier-1 tests use (a single process
    materializes any simulated host's buffer and checks it byte-equal
    to ``pod_epoch_order``'s slice)."""

    batch_major = True
    program_key = "stream"

    def __init__(self, dataset, batch_size: int, seed: int = 0,
                 mesh=None, shuffle: bool = True, window_batches: int = 8,
                 steps_per_dispatch: int = 1, max_len: int = 512,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None):
        self.dataset = dataset
        self.pc = (jax.process_count() if process_count is None
                   else int(process_count))
        self.pi = (jax.process_index() if process_index is None
                   else int(process_index))
        self.batch_size = int(batch_size)          # GLOBAL batch
        if self.batch_size % self.pc:
            raise ValueError(f"global batch {self.batch_size} not divisible "
                             f"by {self.pc} processes")
        self.local_bs = self.batch_size // self.pc
        self.seed = int(seed)
        self.shuffle = bool(shuffle)
        self.mesh = mesh
        self.max_len = int(max_len)
        self.n = len(dataset)
        self.is_text = bool(getattr(dataset, "is_text", False))
        self.seq_len = (min(int(getattr(dataset, "seq_len", 0) or 0),
                            self.max_len) if self.is_text else 0)
        self.steps_per_epoch = (self.n // self.pc) // self.local_bs
        if self.steps_per_epoch < 1:
            raise ValueError(
                f"stream dataset ({self.n} samples / {self.pc} hosts) "
                f"smaller than one local batch ({self.local_bs}) — "
                f"nothing to train on")
        k = max(int(steps_per_dispatch or 1), 1)
        w = max(int(window_batches or 1), 1)
        if w % k:
            rounded = -(-w // k) * k
            warnings.warn(
                f"stream window of {w} batches is not a multiple of "
                f"steps_per_dispatch={k}; rounding up to {rounded} so "
                f"buffer boundaries stay dispatch-aligned (a mid-group "
                f"boundary would change the K-grouping between a resumed "
                f"and an uninterrupted run)", stacklevel=2)
            w = rounded
        self.window = w            # batches per buffer (x2 double-buffered)
        # per-sample DEVICE bytes: the text flavor materializes
        # tokens + token_types + mask (int32, seq_len wide each) + label
        # into every buffer — 3x the on-disk tokens row plus 4 — so the
        # HBM-budget log line reflects what actually lands on device
        row_dev = (3 * self.seq_len * 4 + 4 if self.is_text
                   else int(dataset.row_bytes()))
        # PEAK device bytes: up to 3 buffers alive at once — one being
        # trained, one staged in the queue, one transiently in flight in
        # the producer's device_put (_EpochWindow docstring)
        self.nbytes = 3 * self.window * self.local_bs * row_dev
        self._sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            from faster_distributed_training_tpu.parallel.sharding import (
                batch_spec)
            self._sharding = NamedSharding(mesh, P(None, *batch_spec(mesh)))
        # signature-uniformity placeholder for the fused step's `order`
        # arg (batch_major dispatches never index through it)
        self._dummy_order = None

    @property
    def dummy_order(self):
        if self._dummy_order is None:
            self._dummy_order = jax.device_put(np.zeros(1, np.int32))
        return self._dummy_order

    def epoch_order(self, epoch: int) -> np.ndarray:
        """The epoch's GLOBAL batch stream (host-side): pod_epoch_order's
        flat index array, entry ``b*bs + pi*lbs + j`` = host pi's j-th
        sample of global batch b — the ONE algebra all data paths share."""
        return pod_epoch_order(self.n, epoch, self.seed, self.shuffle,
                               self.pc, self.local_bs)

    def host_buffer(self, order: np.ndarray, base: int, hi: int
                    ) -> Dict[str, np.ndarray]:
        """THIS host's rows of global batches [base, hi) as stacked host
        arrays ``[window, local_bs, ...]`` — the pure (order, range) ->
        bytes function the refill thread runs and the byte-equality
        tests pin directly.  A tail range shorter than the window leaves
        the unused trailing slots zeroed (never consumed: the dispatch
        loop caps at steps_per_epoch)."""
        nb = hi - base
        # order.reshape(steps, pc, lbs)[b, pi] = host pi's rows of batch b
        idx = order.reshape(-1, self.pc, self.local_bs)[base:hi, self.pi]
        rows = self._rows(idx.reshape(-1))
        out = {}
        for k, v in rows.items():
            v = v.reshape((nb, self.local_bs) + v.shape[1:])
            if nb < self.window:
                v = np.concatenate(
                    [v, np.zeros((self.window - nb,) + v.shape[1:],
                                 v.dtype)])
            out[k] = np.ascontiguousarray(v)
        return out

    def _rows(self, flat_idx: np.ndarray) -> Dict[str, np.ndarray]:
        # text goes through encode_batch so the leaf set (tokens/
        # token_types/mask/label) is byte-identical to what the host and
        # resident paths feed the same program — the cross-path bitwise
        # contract; images gather the stored leaves directly
        if self.is_text:
            return dict(self.dataset.encode_batch(flat_idx, self.max_len))
        return self.dataset.gather(flat_idx)

    def _put(self, host: Dict[str, np.ndarray]) -> Dict[str, jax.Array]:
        if self._sharding is not None:
            if jax.process_count() > 1:
                return {k: jax.make_array_from_process_local_data(
                            self._sharding, v) for k, v in host.items()}
            return {k: jax.device_put(v, self._sharding)
                    for k, v in host.items()}
        return {k: jax.device_put(v) for k, v in host.items()}

    def epoch_window(self, epoch: int, start_step: int = 0
                     ) -> "_EpochWindow":
        return _EpochWindow(self, epoch, start_step)


class _EpochWindow:
    """One epoch's double-buffered refill stream (see module docstring).

    The producer generator disk-gathers + device_puts one buffer per
    iteration; PrefetchIterator(depth=1) runs it on a background thread
    with the r8 cancel/drain/join lifecycle.  At any moment at most one
    buffer is being trained on, one is staged ready, and one is in
    flight in the producer — the device window is bounded by
    ~3 x window x local_bs rows per host."""

    def __init__(self, src: DiskStreamSource, epoch: int,
                 start_step: int = 0):
        self.src = src
        self.epoch = int(epoch)
        self.start_step = int(start_step)
        self.stall_s = 0.0
        self.stalls = 0
        self.refills = 0
        self.closed = False
        self._cur: Optional[Tuple[int, int, Dict[str, jax.Array]]] = None
        order = src.epoch_order(epoch)
        steps, w = src.steps_per_epoch, src.window

        def produce():
            for base in range(self.start_step, steps, w):
                hi = min(base + w, steps)
                t0 = time.monotonic()
                with spans.span("stream_refill"):
                    host = src.host_buffer(order, base, hi)
                    t1 = time.monotonic()
                    dev = src._put(host)
                t2 = time.monotonic()
                self.refills += 1
                rec = spans.get_recorder()
                if rec is not None:
                    rec.record_event(
                        "stream_refill", epoch=self.epoch, base=base,
                        batches=hi - base,
                        bytes=int(sum(v.nbytes for v in host.values())),
                        read_ms=round((t1 - t0) * 1e3, 3),
                        h2d_ms=round((t2 - t1) * 1e3, 3))
                yield (base, hi, dev)

        self._it = PrefetchIterator(produce(), depth=1)

    def buffer_for(self, n: int) -> Tuple[int, int, Dict[str, jax.Array]]:
        """The device buffer covering batch ``n`` as ``(base, hi, data)``.
        Advancing past the current buffer blocks until the background
        refill has it staged — that wait IS the stream stall the <1%
        target bounds, recorded per swap as a ``stream_stall`` event."""
        cur = self._cur
        if cur is not None and cur[0] <= n < cur[1]:
            return cur
        t0 = time.monotonic()
        try:
            cur = next(self._it)
        except StopIteration:
            raise RuntimeError(
                f"stream window exhausted at batch {n} (epoch "
                f"{self.epoch}: {self.src.steps_per_epoch} steps from "
                f"{self.start_step}) — consumer/producer ranges disagree")
        wait = time.monotonic() - t0
        if cur[0] > n or n >= cur[1]:
            raise RuntimeError(
                f"stream window skew: batch {n} requested, buffer "
                f"[{cur[0]}, {cur[1]}) arrived — the consumer must "
                f"advance monotonically from start_step")
        self.stall_s += wait
        self.stalls += 1
        rec = spans.get_recorder()
        if rec is not None:
            rec.record_event("stream_stall", epoch=self.epoch, step=n,
                             wait_ms=round(wait * 1e3, 3))
        self._cur = cur
        return cur

    def close(self) -> None:
        """Cancel + drain + join the producer (idempotent; safe at any
        point — the Trainer calls it on EVERY epoch exit, normal or
        abnormal, so an injected fault or preemption can never strand
        the refill thread blocked on a full queue)."""
        if self.closed:
            return
        self.closed = True
        self._cur = None
        self._it.close()
