"""On-disk sharded dataset format for the beyond-HBM streaming tier.

The two resident layouts (data/device_resident.py) assume the train
split fits in HBM (replicated) or in the pod's aggregate HBM (sharded).
Production datasets fit in neither — they live on disk/object storage
and stream through a fixed device window (data/stream/window.py).  This
module owns the at-rest format:

  * a dataset directory holds ``shard_<i>.<leaf>.npy`` files — one raw
    ``.npy`` per leaf per shard, each covering a contiguous row range —
    plus ``manifest.json``, written LAST as the commit marker (a torn
    writer run leaves no manifest and the reader refuses the directory
    loudly instead of serving a partial split);
  * raw ``.npy`` (never ``.npz``): ``np.load(..., mmap_mode="r")`` gives
    zero-copy random row access, so the refill thread's gather is an OS
    page-cache read, not a per-shard decompress;
  * the manifest records n, per-leaf dtype/shape, the shard row table,
    per-file byte sizes (the reader cross-checks them, so a truncated
    shard file fails at open, not as silent garbage mid-epoch), and a
    per-file CRC32C (``crc32c`` + ``crc_alg``) — the size check cannot
    see a same-size byte flip, so the reader re-derives the CRC on
    first touch of each shard (riding the background window-refill
    thread) and quarantines-and-continues on mismatch
    (reader.py / resilience/sentinel.py).

Rows are addressed by GLOBAL sample index; which rows a host reads for
global batch ``b`` comes from ``loader.pod_epoch_order``'s pure
``(seed, epoch, step)`` algebra — the same function the resident layouts
gather by, which is what keeps mid-epoch resume a pure seek and the
bitwise kill-at-N pins valid across data paths (tests/test_stream.py).

``write_lm_corpus`` is the first producer: it tokenizes a text corpus
(the agnews tokenizer-resolution ladder — HF when cached, WordPiece,
hash fallback), PACKS the token stream into fixed ``[n, seq_len]`` rows
(no padding: every position is a real next-token target), and writes a
train/test doc-level split — the next-token LM workload's at-rest form.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

MANIFEST = "manifest.json"
FORMAT = "fdt-stream-v1"


def _checksum_impl():
    """(algorithm name, whole-buffer fn): CRC32C via google_crc32c when
    the wheel is present (hardware-accelerated, the GCS/TPU-fleet
    convention), else zlib's CRC32 — always available, same 32-bit
    detection strength for random bit-rot.  The manifest records which
    one signed each file (``crc_alg``), so a reader environment with a
    different library set verifies with the RIGHT polynomial or skips
    loudly instead of false-alarming."""
    try:
        import google_crc32c

        return "crc32c", lambda b: int(google_crc32c.value(bytes(b)))
    except Exception:
        import zlib

        return "crc32", lambda b: zlib.crc32(bytes(b)) & 0xFFFFFFFF


CRC_ALG, _crc_bytes = _checksum_impl()


def checksum_file(path: str, alg: str = CRC_ALG) -> Optional[int]:
    """Streaming file checksum under ``alg`` (chunked — shard files can
    exceed comfortable one-read sizes).  None when ``alg`` isn't
    computable in this environment (the reader then SKIPS verification
    for that file rather than inventing a mismatch)."""
    if alg == "crc32c":
        try:
            import google_crc32c
        except Exception:
            return None
        crc = 0
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                crc = google_crc32c.extend(crc, chunk)
        return int(crc)
    if alg == "crc32":
        import zlib
        crc = 0
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                crc = zlib.crc32(chunk, crc)
        return crc & 0xFFFFFFFF
    return None


def checksum_bytes(data) -> int:
    """One-shot checksum of an in-memory buffer under this
    environment's :data:`CRC_ALG` — the resident-upload integrity tag
    (data/device_resident.py) shares the shard files' definition."""
    return _crc_bytes(data)


def _write_npy_atomic(path: str, arr: np.ndarray) -> Tuple[int, int]:
    """np.save via tmp + os.replace so a crashed writer never leaves a
    half-written shard under its final name.  Returns (byte size,
    checksum) — the checksum re-reads what the filesystem actually
    durably holds (straight from page cache), not the array in memory,
    so a write-path corruption is signed as-is and caught at first
    verify instead of laundered into a 'valid' manifest entry."""
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "wb") as f:
        np.save(f, arr)
        f.flush()
        os.fsync(f.fileno())
    crc = checksum_file(tmp)
    os.replace(tmp, path)
    return os.path.getsize(path), int(crc or 0)


def write_stream_dataset(directory: str,
                         chunks: Iterable[Dict[str, np.ndarray]],
                         rows_per_shard: int = 4096,
                         meta: Optional[dict] = None) -> dict:
    """Write ``chunks`` (dicts of equal-leading-dim arrays) as a sharded
    stream dataset under ``directory``.  The manifest is written LAST —
    its presence is the commit marker.  Returns the manifest dict.

    Leaf dtypes/shapes must be identical across chunks (the reader mmaps
    fixed-stride rows); a mismatch raises before anything durable is
    half-written beyond shard files a re-run will overwrite."""
    rows_per_shard = int(rows_per_shard)
    if rows_per_shard < 1:
        raise ValueError(f"rows_per_shard must be >= 1, got {rows_per_shard}")
    os.makedirs(directory, exist_ok=True)
    spec: Optional[Dict[str, dict]] = None
    pending: Dict[str, List[np.ndarray]] = {}
    pending_rows = 0
    shards: List[dict] = []
    n = 0

    def flush(final: bool) -> None:
        nonlocal pending, pending_rows
        while pending_rows and (pending_rows >= rows_per_shard or final):
            take = min(pending_rows, rows_per_shard)
            idx = len(shards)
            files = {}
            rest: Dict[str, List[np.ndarray]] = {}
            for leaf, parts in pending.items():
                arr = np.concatenate(parts) if len(parts) > 1 else parts[0]
                cut, remainder = arr[:take], arr[take:]
                fname = f"shard_{idx:05d}.{leaf}.npy"
                size, crc = _write_npy_atomic(
                    os.path.join(directory, fname),
                    np.ascontiguousarray(cut))
                # end-to-end integrity: the reader re-derives this on
                # first touch of the shard (background window refill) —
                # a byte-flip keeps the size, only the CRC catches it
                files[leaf] = {"file": fname, "bytes": size,
                               "crc32c": crc, "crc_alg": CRC_ALG}
                rest[leaf] = [remainder] if len(remainder) else []
            shards.append({"rows": take, "files": files})
            pending = rest
            pending_rows -= take

    for chunk in chunks:
        if not chunk:
            continue
        got = {k: {"dtype": np.asarray(v).dtype.str,
                   "shape": list(np.asarray(v).shape[1:])}
               for k, v in chunk.items()}
        if spec is None:
            spec = got
        elif got != spec:
            raise ValueError(f"stream writer: chunk leaf spec {got} != "
                             f"first chunk's {spec} — every chunk must "
                             f"carry the same leaves/dtypes/shapes")
        rows = {len(np.asarray(v)) for v in chunk.values()}
        if len(rows) != 1:
            raise ValueError(f"stream writer: chunk leaves disagree on row "
                             f"count: { {k: len(np.asarray(v)) for k, v in chunk.items()} }")
        r = rows.pop()
        for k, v in chunk.items():
            pending.setdefault(k, []).append(np.asarray(v))
        pending_rows += r
        n += r
        flush(final=False)
    flush(final=True)
    if spec is None or n == 0:
        raise ValueError("stream writer: no rows written — empty chunk "
                         "iterable")
    manifest = {"format": FORMAT, "n": int(n), "leaves": spec,
                "shards": shards, "rows_per_shard": rows_per_shard}
    if meta:
        manifest.update(meta)
    tmp = os.path.join(directory, f"{MANIFEST}.tmp{os.getpid()}")
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(directory, MANIFEST))
    return manifest


def write_array_dataset(directory: str, arrays: Dict[str, np.ndarray],
                        rows_per_shard: int = 4096,
                        meta: Optional[dict] = None) -> dict:
    """Convenience wrapper: one in-memory dict of full arrays -> shards.
    Used by the image data-path bench arm and the tests; real corpora
    stream through ``write_stream_dataset``'s chunk iterable."""
    return write_stream_dataset(directory, [arrays],
                                rows_per_shard=rows_per_shard, meta=meta)


def _encode_doc(tokenizer, text: str) -> List[int]:
    """Whole-document token ids through either tokenizer interface: the
    HF/WordPiece ``encode(text, truncation=, max_length=)`` surface, or
    the HashTokenizer's positional ``encode(text, max_len)``."""
    try:
        return list(tokenizer.encode(text, truncation=True,
                                     max_length=1_000_000))
    except TypeError:
        return list(tokenizer.encode(text, 1_000_000))


def pack_lm_rows(texts: Sequence[str], tokenizer, seq_len: int,
                 chunk_docs: int = 512) -> Iterable[Dict[str, np.ndarray]]:
    """Tokenize ``texts`` doc by doc, concatenate the id streams (each
    doc keeps its CLS/SEP boundaries from the tokenizer), and cut the
    stream into PACKED ``[*, seq_len]`` int32 rows — no padding, so every
    position of every row is a real next-token target (the shifted-loss
    mask is all-ones).  The trailing partial row is dropped (static
    shapes, the drop-last training semantic).  Yields chunk dicts for
    ``write_stream_dataset``."""
    seq_len = int(seq_len)
    if seq_len < 2:
        raise ValueError(f"seq_len must be >= 2 for next-token prediction, "
                         f"got {seq_len}")
    carry: List[int] = []
    buf: List[np.ndarray] = []
    for i, text in enumerate(texts):
        carry.extend(_encode_doc(tokenizer, text))
        full = len(carry) // seq_len
        if full:
            rows = np.asarray(carry[: full * seq_len],
                              np.int32).reshape(full, seq_len)
            buf.append(rows)
            carry = carry[full * seq_len:]
        if buf and (i + 1) % chunk_docs == 0:
            yield {"tokens": np.concatenate(buf)}
            buf = []
    if buf:
        yield {"tokens": np.concatenate(buf)}


def write_lm_corpus(out_dir: str, texts: Sequence[str], seq_len: int,
                    tokenizer=None, data_dir: str = "",
                    val_fraction: float = 0.1, rows_per_shard: int = 2048,
                    seed: int = 0, clean: bool = True) -> dict:
    """Shard a text corpus for the next-token LM workload: clean (the
    agnews pipeline's cleaner, so a cached WordPiece vocab matches),
    resolve a tokenizer (HF -> WordPiece -> hash, data/agnews.py ladder),
    split DOCUMENTS train/test (deterministic in ``seed`` — packing
    after the split keeps held-out text genuinely unseen), pack each
    split into ``[n, seq_len]`` rows and write ``<out_dir>/train`` +
    ``<out_dir>/test``.  Returns {"train": manifest, "test": manifest,
    "vocab_size": V}."""
    from faster_distributed_training_tpu.data.agnews import (
        _resolve_tokenizer, clean_text)

    docs = [clean_text(t) if clean else str(t) for t in texts]
    docs = [d for d in docs if d.strip()]
    if len(docs) < 2:
        raise ValueError(f"LM corpus needs >= 2 non-empty documents, got "
                         f"{len(docs)}")
    if tokenizer is None:
        tokenizer = _resolve_tokenizer(data_dir, docs)
    order = np.random.default_rng(seed).permutation(len(docs))
    n_test = max(1, int(round(len(docs) * float(val_fraction))))
    test_docs = [docs[i] for i in order[:n_test]]
    train_docs = [docs[i] for i in order[n_test:]]
    vocab = int(getattr(tokenizer, "vocab_size", 30522))
    # "content" (not "kind"): the telemetry schema lint reserves literal
    # "kind" dict keys for JSONL event dicts (scripts/
    # check_telemetry_schema.py scans every dict literal in the package)
    meta = {"content": "lm", "seq_len": int(seq_len), "vocab_size": vocab,
            "tokenizer": type(tokenizer).__name__}
    out = {"vocab_size": vocab}
    for split, split_docs in (("train", train_docs), ("test", test_docs)):
        out[split] = write_stream_dataset(
            os.path.join(out_dir, split),
            pack_lm_rows(split_docs, tokenizer, seq_len),
            rows_per_shard=rows_per_shard,
            meta={**meta, "split": split, "docs": len(split_docs)})
    return out


def synthetic_corpus(n_docs: int = 256, seed: int = 0,
                     words_per_doc: Tuple[int, int] = (30, 120),
                     vocab_words: int = 600) -> List[str]:
    """Deterministic pseudo-text corpus for zero-egress environments:
    word-like strings drawn zipf-ish from a fixed fake vocabulary, so
    the WordPiece/hash tokenizers produce a learnable (skewed, repeated)
    token distribution rather than uniform noise."""
    rng = np.random.default_rng(seed)
    syll = ["ka", "ro", "mi", "ten", "lu", "za", "por", "eni", "sta", "vel",
            "dor", "ashi", "qu", "ber", "on", "tra", "ix", "mel", "gra", "un"]
    words = ["".join(syll[j % len(syll)]
                     for j in rng.integers(0, len(syll), size=ln))
             for ln in rng.integers(2, 5, size=vocab_words)]
    ranks = np.arange(1, vocab_words + 1, dtype=np.float64)
    p = (1.0 / ranks) / np.sum(1.0 / ranks)
    docs = []
    for _ in range(int(n_docs)):
        k = int(rng.integers(*words_per_doc))
        docs.append(" ".join(words[i]
                             for i in rng.choice(vocab_words, size=k, p=p)))
    return docs
