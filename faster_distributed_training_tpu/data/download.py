"""Dataset download / integrity / extraction infrastructure.

Re-expression of the *capabilities* of the vendored torchvision utils
(torchvision_utils.py:82-91 MD5 verify, :123-171 download with redirect
handling, :220-285 Google-Drive fetch, :391-442 archive extraction,
:480-512 .pfm reader) in ~1/4 the code: stdlib + numpy only.

In zero-egress environments download attempts fail fast with a clear
message pointing at the synthetic fallback."""

from __future__ import annotations

import gzip
import hashlib
import os
import tarfile
import urllib.error
import urllib.request
import zipfile
from typing import Optional


def check_md5(path: str, md5: str, chunk: int = 1 << 20) -> bool:
    """torchvision_utils.py:82-91 equivalent."""
    h = hashlib.md5()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest() == md5


def check_integrity(path: str, md5: Optional[str] = None) -> bool:
    if not os.path.isfile(path):
        return False
    return True if md5 is None else check_md5(path, md5)


def download_url(url: str, root: str, filename: Optional[str] = None,
                 md5: Optional[str] = None) -> str:
    os.makedirs(root, exist_ok=True)
    filename = filename or os.path.basename(url)
    path = os.path.join(root, filename)
    if check_integrity(path, md5):
        return path
    try:
        req = urllib.request.Request(url, headers={"User-Agent": "fdt-tpu"})
        with urllib.request.urlopen(req, timeout=30) as r, \
                open(path, "wb") as f:
            while True:
                block = r.read(1 << 20)
                if not block:
                    break
                f.write(block)
    except (urllib.error.URLError, OSError) as e:
        # never leave a partial file behind: check_integrity(md5=None)
        # would return it as the dataset on the next call
        if os.path.exists(path):
            os.remove(path)
        raise RuntimeError(
            f"could not download {url} ({e}); in offline environments "
            f"place the file at {path} manually or use the synthetic "
            f"dataset (data.synthetic)") from e
    if md5 and not check_md5(path, md5):
        raise RuntimeError(f"MD5 mismatch for {path}")
    return path


def extract_archive(path: str, dest: Optional[str] = None) -> str:
    """tar(.gz/.bz2/.xz) / zip / lone .gz — torchvision_utils.py:391-421."""
    dest = dest or os.path.dirname(path)
    if tarfile.is_tarfile(path):
        with tarfile.open(path) as t:
            t.extractall(dest, filter="data")
    elif zipfile.is_zipfile(path):
        with zipfile.ZipFile(path) as z:
            z.extractall(dest)
    elif path.endswith(".gz"):
        out = os.path.join(dest, os.path.basename(path)[:-3])
        with gzip.open(path, "rb") as f, open(out, "wb") as o:
            o.write(f.read())
    else:
        raise ValueError(f"unknown archive type: {path}")
    return dest


def download_and_extract_archive(url: str, root: str,
                                 md5: Optional[str] = None) -> str:
    """torchvision_utils.py:424-442 equivalent."""
    path = download_url(url, root, md5=md5)
    return extract_archive(path, root)


def download_file_from_google_drive(file_id: str, root: str,
                                    filename: Optional[str] = None,
                                    md5: Optional[str] = None) -> str:
    """Google-Drive fetch incl. the large-file virus-scan confirm hop
    (torchvision_utils.py:220-285 capability, stdlib only)."""
    import http.cookiejar

    os.makedirs(root, exist_ok=True)
    path = os.path.join(root, filename or file_id)
    if check_integrity(path, md5):
        return path
    base = "https://docs.google.com/uc?export=download&id=" + file_id
    jar = http.cookiejar.CookieJar()
    opener = urllib.request.build_opener(
        urllib.request.HTTPCookieProcessor(jar))

    def stream_to(resp, dest) -> bytes:
        """Stream response to dest in chunks; returns the first bytes so
        callers can sniff HTML without buffering multi-GB files in RAM."""
        head = b""
        with open(dest, "wb") as f:
            while True:
                block = resp.read(1 << 20)
                if not block:
                    break
                head = head or block[:64]
                f.write(block)
        return head

    try:
        with opener.open(base, timeout=30) as r:
            head = stream_to(r, path)
        token = next((c.value for c in jar
                      if c.name.startswith("download_warning")), None)
        if token is None and head[:1] == b"<":
            # confirm token embedded in the interstitial HTML page
            import re
            with open(path, "rb") as f:
                m = re.search(rb"confirm=([0-9A-Za-z_\-]+)", f.read())
            token = m.group(1).decode() if m else "t"
        if token:
            with opener.open(f"{base}&confirm={token}", timeout=30) as r:
                head = stream_to(r, path)
        if head[:1] == b"<":
            # still HTML after the confirm hop: quota-exceeded page etc.
            # Delete it so a broken file is never cached as the dataset.
            os.remove(path)
            raise RuntimeError(
                f"Google Drive id={file_id} returned an HTML page instead "
                f"of the file (quota exceeded / permission denied?)")
    except (urllib.error.URLError, OSError) as e:
        # a failed confirm hop leaves the interstitial HTML / partial
        # payload at `path`; delete it or the next call caches it as data
        if os.path.exists(path):
            os.remove(path)
        raise RuntimeError(
            f"could not fetch Google Drive id={file_id} ({e}); place the "
            f"file at {path} manually") from e
    if md5 and not check_md5(path, md5):
        raise RuntimeError(f"MD5 mismatch for {path}")
    return path


def read_pfm(path: str):
    """Portable FloatMap reader (torchvision_utils.py:480-512 capability):
    returns a float32 numpy array, flipped to top-down row order."""
    import numpy as np

    with open(path, "rb") as f:
        header = f.readline().strip()
        if header not in (b"PF", b"Pf"):
            raise ValueError(f"{path}: not a PFM file (header {header!r})")
        color = header == b"PF"
        line = f.readline().strip()
        while line.startswith(b"#"):  # comment lines
            line = f.readline().strip()
        w, h = map(int, line.split())
        scale = float(f.readline().strip())
        endian = "<" if scale < 0 else ">"
        count = h * w * (3 if color else 1)
        # exact count: writers commonly append a trailing newline after the
        # raster, which would break a whole-file frombuffer+reshape
        raw = f.read(4 * count)
        if len(raw) != 4 * count:
            raise ValueError(f"{path}: truncated PFM (got {len(raw)} of "
                             f"{4 * count} raster bytes)")
        data = np.frombuffer(raw, dtype=endian + "f4")
        shape = (h, w, 3) if color else (h, w)
        return data.reshape(shape)[::-1].astype(np.float32)
