"""Dataset download / integrity / extraction infrastructure.

Re-expression of the *capabilities* of the vendored torchvision utils
(torchvision_utils.py:82-91 MD5 verify, :123-171 download with redirect
handling, :220-285 Google-Drive fetch, :391-442 archive extraction,
:480-512 .pfm reader) in ~1/4 the code: stdlib + numpy only.

In zero-egress environments download attempts fail fast with a clear
message pointing at the synthetic fallback."""

from __future__ import annotations

import gzip
import hashlib
import http.client
import os
import tarfile
import time
import urllib.error
import urllib.request
import zipfile
from typing import Callable, Optional


class ChecksumError(RuntimeError):
    """A fetched file failed md5/sha256 verification.  RETRYABLE: the
    dominant real-world cause is a truncated/corrupted transfer, which a
    re-fetch fixes — a genuinely wrong upstream file exhausts the retry
    budget and surfaces with the mismatch in the message."""


def _hash_file(path: str, algo: str, chunk: int = 1 << 20) -> str:
    h = hashlib.new(algo)
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def check_md5(path: str, md5: str, chunk: int = 1 << 20) -> bool:
    """torchvision_utils.py:82-91 equivalent."""
    return _hash_file(path, "md5", chunk) == md5


def check_sha256(path: str, sha256: str, chunk: int = 1 << 20) -> bool:
    return _hash_file(path, "sha256", chunk) == sha256


def check_integrity(path: str, md5: Optional[str] = None,
                    sha256: Optional[str] = None) -> bool:
    if not os.path.isfile(path):
        return False
    if md5 is not None and not check_md5(path, md5):
        return False
    if sha256 is not None and not check_sha256(path, sha256):
        return False
    return True


def _verify_checksums(path: str, md5: Optional[str],
                      sha256: Optional[str]) -> None:
    for algo, want in (("md5", md5), ("sha256", sha256)):
        if want is None:
            continue
        got = _hash_file(path, algo)
        if got != want:
            raise ChecksumError(
                f"{algo} mismatch for {path}: got {got}, expected {want} "
                f"(truncated/corrupt transfer, or the upstream file "
                f"changed)")


def _urlopen_fetch(url: str, path: str, timeout: float = 30.0) -> None:
    """Default fetcher: stream the URL to ``path`` in 1 MB blocks.  The
    injectable seam retry tests (and alternative transports) replace."""
    req = urllib.request.Request(url, headers={"User-Agent": "fdt-tpu"})
    with urllib.request.urlopen(req, timeout=timeout) as r, \
            open(path, "wb") as f:
        while True:
            block = r.read(1 << 20)
            if not block:
                break
            f.write(block)


def download_url(url: str, root: str, filename: Optional[str] = None,
                 md5: Optional[str] = None, sha256: Optional[str] = None,
                 attempts: int = 3, backoff_s: float = 1.0,
                 fetch: Optional[Callable[[str, str], None]] = None,
                 sleep: Callable[[float], None] = time.sleep) -> str:
    """Fetch ``url`` into ``root`` with BOUNDED retry/backoff and
    checksum verification (r18 hardening — a single flaky connection
    used to fail the whole run outright).

      * up to ``attempts`` tries; exponential backoff between them
        (``backoff_s * 2^(attempt-1)``, injected ``sleep`` for tests);
      * every failed/torn attempt deletes the partial file — a truncated
        archive can never be cached as the dataset;
      * ``md5``/``sha256`` verify EACH attempt's payload; a mismatch is
        retried like a network error (truncation is the common cause)
        and only exhausts the budget if persistent;
      * ``fetch(url, path)`` is the injectable transport seam.

    Returns the verified path; raises RuntimeError (chained to the last
    underlying error) when the budget is exhausted."""
    os.makedirs(root, exist_ok=True)
    filename = filename or os.path.basename(url)
    path = os.path.join(root, filename)
    if check_integrity(path, md5, sha256):
        return path
    attempts = max(int(attempts), 1)
    fetch = fetch or _urlopen_fetch
    last: Optional[BaseException] = None
    for attempt in range(attempts):
        if attempt:
            sleep(backoff_s * (2 ** (attempt - 1)))
        try:
            fetch(url, path)
            _verify_checksums(path, md5, sha256)
            return path
        except (urllib.error.URLError, OSError, ChecksumError,
                http.client.HTTPException) as e:
            # HTTPException covers mid-body disconnects (IncompleteRead,
            # BadStatusLine) that are neither URLError nor OSError — they
            # must hit the same delete-partial + retry path or a torn
            # file survives for the next check_integrity(md5=None) call
            # never leave a partial/corrupt file behind:
            # check_integrity(md5=None) would return it as the dataset
            # on the next call
            if os.path.exists(path):
                os.remove(path)
            last = e
    raise RuntimeError(
        f"could not download {url} after {attempts} attempt(s) ({last}); "
        f"in offline environments place the file at {path} manually or "
        f"use the synthetic dataset (data.synthetic)") from last


def extract_archive(path: str, dest: Optional[str] = None) -> str:
    """tar(.gz/.bz2/.xz) / zip / lone .gz — torchvision_utils.py:391-421."""
    dest = dest or os.path.dirname(path)
    if tarfile.is_tarfile(path):
        with tarfile.open(path) as t:
            t.extractall(dest, filter="data")
    elif zipfile.is_zipfile(path):
        with zipfile.ZipFile(path) as z:
            z.extractall(dest)
    elif path.endswith(".gz"):
        out = os.path.join(dest, os.path.basename(path)[:-3])
        with gzip.open(path, "rb") as f, open(out, "wb") as o:
            o.write(f.read())
    else:
        raise ValueError(f"unknown archive type: {path}")
    return dest


def download_and_extract_archive(url: str, root: str,
                                 md5: Optional[str] = None,
                                 sha256: Optional[str] = None,
                                 attempts: int = 3,
                                 backoff_s: float = 1.0) -> str:
    """torchvision_utils.py:424-442 equivalent (retry/checksum args
    pass through to the hardened download_url)."""
    path = download_url(url, root, md5=md5, sha256=sha256,
                        attempts=attempts, backoff_s=backoff_s)
    return extract_archive(path, root)


def download_file_from_google_drive(file_id: str, root: str,
                                    filename: Optional[str] = None,
                                    md5: Optional[str] = None) -> str:
    """Google-Drive fetch incl. the large-file virus-scan confirm hop
    (torchvision_utils.py:220-285 capability, stdlib only)."""
    import http.cookiejar

    os.makedirs(root, exist_ok=True)
    path = os.path.join(root, filename or file_id)
    if check_integrity(path, md5):
        return path
    base = "https://docs.google.com/uc?export=download&id=" + file_id
    jar = http.cookiejar.CookieJar()
    opener = urllib.request.build_opener(
        urllib.request.HTTPCookieProcessor(jar))

    def stream_to(resp, dest) -> bytes:
        """Stream response to dest in chunks; returns the first bytes so
        callers can sniff HTML without buffering multi-GB files in RAM."""
        head = b""
        with open(dest, "wb") as f:
            while True:
                block = resp.read(1 << 20)
                if not block:
                    break
                head = head or block[:64]
                f.write(block)
        return head

    try:
        with opener.open(base, timeout=30) as r:
            head = stream_to(r, path)
        token = next((c.value for c in jar
                      if c.name.startswith("download_warning")), None)
        if token is None and head[:1] == b"<":
            # confirm token embedded in the interstitial HTML page
            import re
            with open(path, "rb") as f:
                m = re.search(rb"confirm=([0-9A-Za-z_\-]+)", f.read())
            token = m.group(1).decode() if m else "t"
        if token:
            with opener.open(f"{base}&confirm={token}", timeout=30) as r:
                head = stream_to(r, path)
        if head[:1] == b"<":
            # still HTML after the confirm hop: quota-exceeded page etc.
            # Delete it so a broken file is never cached as the dataset.
            os.remove(path)
            raise RuntimeError(
                f"Google Drive id={file_id} returned an HTML page instead "
                f"of the file (quota exceeded / permission denied?)")
    except (urllib.error.URLError, OSError) as e:
        # a failed confirm hop leaves the interstitial HTML / partial
        # payload at `path`; delete it or the next call caches it as data
        if os.path.exists(path):
            os.remove(path)
        raise RuntimeError(
            f"could not fetch Google Drive id={file_id} ({e}); place the "
            f"file at {path} manually") from e
    if md5 and not check_md5(path, md5):
        raise RuntimeError(f"MD5 mismatch for {path}")
    return path


def read_pfm(path: str):
    """Portable FloatMap reader (torchvision_utils.py:480-512 capability):
    returns a float32 numpy array, flipped to top-down row order."""
    import numpy as np

    with open(path, "rb") as f:
        header = f.readline().strip()
        if header not in (b"PF", b"Pf"):
            raise ValueError(f"{path}: not a PFM file (header {header!r})")
        color = header == b"PF"
        line = f.readline().strip()
        while line.startswith(b"#"):  # comment lines
            line = f.readline().strip()
        w, h = map(int, line.split())
        scale = float(f.readline().strip())
        endian = "<" if scale < 0 else ">"
        count = h * w * (3 if color else 1)
        # exact count: writers commonly append a trailing newline after the
        # raster, which would break a whole-file frombuffer+reshape
        raw = f.read(4 * count)
        if len(raw) != 4 * count:
            raise ValueError(f"{path}: truncated PFM (got {len(raw)} of "
                             f"{4 * count} raster bytes)")
        data = np.frombuffer(raw, dtype=endian + "f4")
        shape = (h, w, 3) if color else (h, w)
        return data.reshape(shape)[::-1].astype(np.float32)
