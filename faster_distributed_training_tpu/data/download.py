"""Dataset download / integrity / extraction infrastructure.

Re-expression of the *capabilities* of the vendored torchvision utils
(torchvision_utils.py:82-91 MD5 verify, :123-171 download with redirect
handling, :391-442 archive extraction) in ~1/5 the code: stdlib only,
no Google-Drive special cases (CIFAR/AG News don't need them).

In zero-egress environments download attempts fail fast with a clear
message pointing at the synthetic fallback."""

from __future__ import annotations

import gzip
import hashlib
import os
import tarfile
import urllib.error
import urllib.request
import zipfile
from typing import Optional


def check_md5(path: str, md5: str, chunk: int = 1 << 20) -> bool:
    """torchvision_utils.py:82-91 equivalent."""
    h = hashlib.md5()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest() == md5


def check_integrity(path: str, md5: Optional[str] = None) -> bool:
    if not os.path.isfile(path):
        return False
    return True if md5 is None else check_md5(path, md5)


def download_url(url: str, root: str, filename: Optional[str] = None,
                 md5: Optional[str] = None) -> str:
    os.makedirs(root, exist_ok=True)
    filename = filename or os.path.basename(url)
    path = os.path.join(root, filename)
    if check_integrity(path, md5):
        return path
    try:
        req = urllib.request.Request(url, headers={"User-Agent": "fdt-tpu"})
        with urllib.request.urlopen(req, timeout=30) as r, \
                open(path, "wb") as f:
            while True:
                block = r.read(1 << 20)
                if not block:
                    break
                f.write(block)
    except (urllib.error.URLError, OSError) as e:
        raise RuntimeError(
            f"could not download {url} ({e}); in offline environments "
            f"place the file at {path} manually or use the synthetic "
            f"dataset (data.synthetic)") from e
    if md5 and not check_md5(path, md5):
        raise RuntimeError(f"MD5 mismatch for {path}")
    return path


def extract_archive(path: str, dest: Optional[str] = None) -> str:
    """tar(.gz/.bz2/.xz) / zip / lone .gz — torchvision_utils.py:391-421."""
    dest = dest or os.path.dirname(path)
    if tarfile.is_tarfile(path):
        with tarfile.open(path) as t:
            t.extractall(dest, filter="data")
    elif zipfile.is_zipfile(path):
        with zipfile.ZipFile(path) as z:
            z.extractall(dest)
    elif path.endswith(".gz"):
        out = os.path.join(dest, os.path.basename(path)[:-3])
        with gzip.open(path, "rb") as f, open(out, "wb") as o:
            o.write(f.read())
    else:
        raise ValueError(f"unknown archive type: {path}")
    return dest


def download_and_extract_archive(url: str, root: str,
                                 md5: Optional[str] = None) -> str:
    """torchvision_utils.py:424-442 equivalent."""
    path = download_url(url, root, md5=md5)
    return extract_archive(path, root)
