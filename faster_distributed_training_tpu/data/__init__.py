"""Input pipelines: CIFAR-10, AG News, synthetic fallbacks, prefetching
loaders, device-side augmentation — the reference's L1 layer
(torchvision_utils.py, dataset classes in resnet50_test.py:87-292 and
transformer_test.py:82-138, DataLoaderX) rebuilt for TPU: static shapes,
host->device double buffering, per-host sharding."""

from faster_distributed_training_tpu.data.cifar10 import (  # noqa: F401
    CIFAR10_MEAN, CIFAR10_STD, load_cifar10)
from faster_distributed_training_tpu.data.synthetic import (  # noqa: F401
    synthetic_cifar, synthetic_agnews)
from faster_distributed_training_tpu.data.loader import (  # noqa: F401
    BatchLoader, PrefetchIterator, pod_epoch_order, shard_for_host,
    verify_host_shards, verify_host_shards_global)
from faster_distributed_training_tpu.data.augment import (  # noqa: F401
    augment_batch, normalize)
from faster_distributed_training_tpu.data.device_resident import (  # noqa: F401,E501
    DeviceResidentData, ShardedDeviceResidentData, build_device_resident)
from faster_distributed_training_tpu.data.agnews import (  # noqa: F401
    AGNewsDataset, clean_text)
