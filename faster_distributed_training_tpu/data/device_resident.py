"""Device-resident train datasets: upload once, batch in-graph.

Both reference workloads are small enough to live in HBM whole —
CIFAR-10 train is 50000·32·32·3 uint8 ≈ 147 MB raw but ~37 MB for the
subset-strided configs the tuning harness runs, AG News at seq≤256 is
~50 MB of int32 token ids — so the steady-state input pipeline does not
need a host at all: the split is uploaded ONCE per run as compact
dtypes (uint8 images, int32 token ids) and every batch is assembled
*inside* the jitted train dispatch by an index gather
(``train.steps.make_fused_train_step``).  This removes the per-step
host work that bounds small-model step time (Murray et al., *tf.data*,
2021): the ``BatchLoader`` gather, the per-batch ``device_put``, and
the Python dispatch itself (amortized K× further by
``--steps_per_dispatch``).

Epoch semantics are the host loader's EXACTLY: the per-epoch order is
the same ``shard_for_host(n, epoch, seed)`` permutation ``BatchLoader.
plan()`` draws — a pure function of ``(seed, epoch)``, which is the
determinism contract the resilience bitwise-resume tests pin.  The
order is computed host-side once per EPOCH (an O(n) permutation and a
~4·n-byte upload — noise against an epoch of steps) rather than by an
in-graph ``jax.random.permutation``: threefry cannot reproduce numpy's
``default_rng((seed, epoch))`` stream, and bit-identical batch order
between the host and resident paths is a pinned test contract
(tests/test_fused_dispatch.py).

Text: the whole split is pre-encoded at ONE fixed bucket length (the
smallest ``seq_buckets`` entry covering the longest sequence, ≤
``max_len``) instead of the host path's per-batch bucketing — a single
compiled program over the epoch, trading pad FLOPs for zero host work.

Two layouts (``--resident_layout``):

  * :class:`DeviceResidentData` (``replicated`` — the r8 layout,
    default single-host): the split replicated over the mesh, every
    chip gathering its batch shard from a full local copy.  Single-host
    only by construction.
  * :class:`ShardedDeviceResidentData` (``sharded`` — default on pods):
    the ZeRO move applied to data (Rajbhandari et al., 2020): each
    process uploads ONLY its addressable row-shard of the split (per-
    host HBM = n/process_count, not n), and once per epoch ONE jitted
    collective re-shards the split into that epoch's batch-major layout
    ``[steps, batch, ...]`` — the same ``shard_for_host`` permutation
    the host ``BatchLoader`` draws, sliced per host and interleaved
    process-major (``loader.pod_epoch_order``).  After the re-shard the
    steady-state in-graph "gather" is a ``dynamic_index`` on the
    UNsharded leading step axis: every device reads only its own HBM,
    and no batch bytes cross hosts or the PCIe — the Pathways-style
    off-critical-path property (Barham et al., 2022), paid once per
    epoch instead of per step."""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import numpy as np

from faster_distributed_training_tpu.data.loader import (dataset_len,
                                                         pod_epoch_order,
                                                         shard_for_host)
from faster_distributed_training_tpu.telemetry import spans


def _encode_split(data, max_len: int) -> Tuple[Dict[str, np.ndarray],
                                               bool, int, int]:
    """(host_arrays, is_text, seq_len, n): the whole split as compact
    host numpy — uint8 NHWC images + int32 labels, or the text split
    pre-encoded at ONE fixed bucket length (the bucket covering the
    split's longest sequence, so every host-path batch embeds into it —
    content equality modulo trailing padding, pinned by test)."""
    is_text = hasattr(data, "encode_batch")
    n = dataset_len(data)
    if is_text:
        host = {k: np.asarray(v) for k, v in
                data.encode_batch(np.arange(n), max_len).items()}
        return host, True, int(host["tokens"].shape[1]), n
    x, y = data
    return {"image": np.asarray(x), "label": np.asarray(y)}, False, 0, n


def _host_checksums(host: Dict[str, np.ndarray]) -> Dict[str, int]:
    """Per-leaf checksum of the encoded split at upload time (same
    CRC32C definition as the stream shard files) — the resident path's
    end of the data-integrity chain: a stream shard is CRC-verified at
    gather, this tags what actually left the host, and
    ``verify_upload()`` closes the loop against what HBM holds."""
    from faster_distributed_training_tpu.data.stream.format import (
        checksum_bytes)
    return {k: checksum_bytes(np.ascontiguousarray(v))
            for k, v in host.items()}


def _verify_resident_upload(arrays: Dict[str, jax.Array], n: int,
                            checksums: Dict[str, int]) -> bool:
    """Fetch the resident arrays back from device and compare their
    first ``n`` rows against the encode-time checksums; raises on
    mismatch (an upload/DMA corruption — there is no sane way to
    continue on poisoned training data already in HBM).  Multi-process
    runs skip (each host holds only its row shard; the per-shard CRC at
    gather already covered the bytes it contributed): returns False for
    'not verified', True for verified."""
    if not checksums:
        return False
    if jax.process_count() > 1:
        return False
    from faster_distributed_training_tpu.data.stream.format import (
        checksum_bytes)
    for k, want in checksums.items():
        got = checksum_bytes(np.ascontiguousarray(
            np.asarray(jax.device_get(arrays[k]))[:n]))
        if got != want:
            raise RuntimeError(
                f"device-resident upload integrity failure: leaf {k!r} "
                f"read back from HBM with checksum {got:#010x} != "
                f"{want:#010x} computed at encode time — the uploaded "
                f"split is corrupt; refusing to train on it")
    return True


class DeviceResidentData:
    """The train split as device arrays + per-epoch order uploads
    (the REPLICATED r8 layout — see module docstring).

    ``arrays`` is a dict of device arrays with a leading sample axis
    (images: ``image`` uint8 NHWC + ``label`` int32; text: ``tokens``/
    ``token_types``/``mask``/``label`` int32), replicated over the mesh
    (every chip gathers its own batch shard from the full split).
    ``epoch_order(epoch)`` returns the epoch's device-resident index
    array — ``steps_per_epoch * batch_size`` int32 entries in exactly
    ``BatchLoader.plan()``'s order."""

    batch_major = False

    def __init__(self, data, batch_size: int, seed: int = 0,
                 max_len: int = 512, mesh=None, shuffle: bool = True,
                 checksum: bool = False):
        if jax.process_count() > 1:
            raise ValueError(
                "replicated device residency is single-host only; "
                "multi-host runs use ShardedDeviceResidentData "
                "(--resident_layout sharded / auto)")
        self.batch_size = int(batch_size)
        self.seed = int(seed)
        self.shuffle = bool(shuffle)
        self.n = dataset_len(data)
        self.steps_per_epoch = self.n // self.batch_size
        if self.steps_per_epoch < 1:
            raise ValueError(
                f"dataset ({self.n} samples) smaller than one batch "
                f"({self.batch_size}) — nothing to train on")
        host, self.is_text, self.seq_len, _n = _encode_split(data, max_len)
        self.upload_checksums = _host_checksums(host) if checksum else {}
        self.mesh = mesh
        self._replicated = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            self._replicated = NamedSharding(mesh, PartitionSpec())
        self.nbytes = sum(a.nbytes for a in host.values())
        with spans.span("h2d_upload"):
            self.arrays: Dict[str, jax.Array] = {
                k: self._put(v) for k, v in host.items()}

    def _put(self, arr: np.ndarray) -> jax.Array:
        if self._replicated is not None:
            return jax.device_put(arr, self._replicated)
        return jax.device_put(arr)

    def epoch_arrays(self, epoch: int) -> Dict[str, jax.Array]:
        """The arrays the fused dispatch gathers from this epoch — the
        static replicated split (the order indirection happens in-graph
        via ``epoch_order``)."""
        return self.arrays

    def verify_upload(self) -> bool:
        """Compare HBM contents against the encode-time checksums
        (no-op False unless built with ``checksum=True``)."""
        return _verify_resident_upload(self.arrays, self.n,
                                       self.upload_checksums)

    def epoch_order(self, epoch: int) -> jax.Array:
        """The epoch's sample order as a device int32 array, truncated to
        whole batches — elementwise equal to concatenating
        ``BatchLoader.plan()``'s index entries for the same
        ``(seed, epoch)`` (single-process; drop-last)."""
        idx = shard_for_host(self.n, epoch, self.seed, self.shuffle,
                             process_index=0, process_count=1)
        idx = idx[: self.steps_per_epoch * self.batch_size]
        # the replicated layout's only per-epoch device work — spanned
        # under the same name as the sharded re-shard so the telemetry
        # breakdown compares the two layouts' epoch-boundary cost
        with spans.span("epoch_reshard"):
            return self._put(np.ascontiguousarray(idx.astype(np.int32)))


class ShardedDeviceResidentData:
    """Per-host sharded residency + per-epoch batch-major re-shard
    (see module docstring for the design).

    Storage: every leaf is ONE global array whose sample axis is
    sharded over the mesh's data axes — each process contributes only
    its contiguous row range (``make_array_from_process_local_data``),
    so per-host HBM is ``n / process_count`` (+ the epoch view below).
    Rows are zero-padded up to a multiple of the data-axis device count;
    pad rows are never referenced (permutation values are < n).

    ``epoch_arrays(epoch)`` runs one jitted re-shard — gather by the
    epoch's ``pod_epoch_order`` permutation, reshape to
    ``[steps_per_epoch, batch_size, ...]``, output-sharded
    ``P(None, data_axes)`` — so batch ``b`` of the view IS global batch
    ``b`` of the pod's host loaders (bitwise; tests/test_pod_scale.py),
    already laid out so each device owns exactly its rows of every
    batch.  The fused dispatch then just ``dynamic_index``es the
    unsharded leading axis: fully local HBM reads, zero steady-state
    host or cross-host traffic.  The view is cached per epoch and
    replaced (freed) at the next epoch boundary — steady-state HBM is
    ~2·n/process_count per host (canonical shards + current epoch
    view), vs n per host for the replicated layout.

    ``process_index``/``process_count`` default to the real runtime and
    exist as the simulation seam the tier-1 tests use (a single process
    with a multi-device CPU mesh exercises the full storage + re-shard
    + gather machinery for simulated pod layouts)."""

    batch_major = True

    def __init__(self, data, batch_size: int, seed: int = 0,
                 max_len: int = 512, mesh=None, shuffle: bool = True,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None,
                 checksum: bool = False):
        if mesh is None:
            raise ValueError("sharded device residency requires the mesh "
                             "(its data axes define the row sharding)")
        from jax.sharding import NamedSharding, PartitionSpec as P
        from faster_distributed_training_tpu.parallel.sharding import (
            batch_spec)

        self.mesh = mesh
        self.pc = (jax.process_count() if process_count is None
                   else int(process_count))
        self.pi = (jax.process_index() if process_index is None
                   else int(process_index))
        self.batch_size = int(batch_size)          # GLOBAL batch
        if self.batch_size % self.pc:
            raise ValueError(f"global batch {self.batch_size} not divisible "
                             f"by {self.pc} processes")
        self.local_bs = self.batch_size // self.pc
        self.seed = int(seed)
        self.shuffle = bool(shuffle)
        host, self.is_text, self.seq_len, self.n = _encode_split(data,
                                                                 max_len)
        self.upload_checksums = _host_checksums(host) if checksum else {}
        # the host loader's algebra: per-host shard of n // pc samples,
        # truncated to whole local batches
        self.steps_per_epoch = (self.n // self.pc) // self.local_bs
        if self.steps_per_epoch < 1:
            raise ValueError(
                f"dataset ({self.n} samples / {self.pc} hosts) smaller "
                f"than one local batch ({self.local_bs}) — nothing to "
                f"train on")
        from faster_distributed_training_tpu.parallel.placement import (
            dp_size)
        d = max(dp_size(mesh), 1)
        if self.batch_size % d:
            raise ValueError(f"global batch {self.batch_size} not divisible "
                             f"by the mesh's {d} data-axis devices")
        real_pc = jax.process_count()
        # row shards come from the DP SUBMESH only — batch_spec(mesh)
        # shards the sample axis over dp/fsdp and REPLICATES across
        # tp/sp (a tp group shares its rows), so a 2D (data, model)
        # mesh keeps the n/pc per-host HBM win wherever the dp devices
        # spread evenly over processes.  Only when dp genuinely doesn't
        # divide the process count (a tp-heavy mesh, e.g. dp=1,tp=8 on
        # 2 hosts — the contiguous per-process row slice can't line up
        # with the dp sharding) do rows fall back to replicated storage,
        # loudly: the re-shard/gather machinery is unchanged, only the
        # per-host HBM saving is lost (r9's hard reject, relaxed r11).
        self._rows_replicated = bool(d % real_pc)
        if self._rows_replicated:
            import warnings
            warnings.warn(
                f"sharded device residency: the mesh's data-axis device "
                f"count ({d}) is not a multiple of the process count "
                f"({real_pc}) — a tp-heavy mesh; row storage falls back "
                f"to REPLICATED (per-host HBM = full split, not "
                f"n/process_count).  Give the mesh a dp axis that "
                f"spreads over processes to regain sharded residency",
                stacklevel=2)
        self._n_pad = -(-self.n // d) * d
        self._replicated = NamedSharding(mesh, P())
        self._row_sharding = (self._replicated if self._rows_replicated
                              else NamedSharding(mesh, batch_spec(mesh)))
        self._batch_sharding = NamedSharding(mesh,
                                             P(None, *batch_spec(mesh)))
        self.nbytes = 0          # HOST-LOCAL bytes resident in this
        self.arrays: Dict[str, jax.Array] = {}   # process's HBM shard
        # _encode_split's full-split host arrays are an O(n) transient
        # per host (the text bucket length is a GLOBAL property of the
        # split, so every host tokenizes everything; n is bounded by
        # fits-in-one-host's-HBM anyway) — but the padding below is
        # applied to the LOCAL slice only, so no host ever materializes
        # a second full-split copy; everything here is freed on return.
        real_pi = jax.process_index()
        with spans.span("h2d_upload"):
            for k, v in host.items():
                if self._rows_replicated:
                    if self._n_pad != self.n:
                        v = np.concatenate(
                            [v, np.zeros((self._n_pad - self.n,)
                                         + v.shape[1:], v.dtype)])
                    self.arrays[k] = self._put_replicated(
                        np.ascontiguousarray(v))
                    self.nbytes += v.nbytes
                elif real_pc > 1:
                    rows = self._n_pad // real_pc
                    lo, hi = real_pi * rows, (real_pi + 1) * rows
                    local = v[min(lo, self.n):min(hi, self.n)]
                    if hi > self.n:   # this host's slice covers pad rows
                        local = np.concatenate(
                            [local, np.zeros((hi - max(lo, self.n),)
                                             + v.shape[1:], v.dtype)])
                    self.arrays[k] = \
                        jax.make_array_from_process_local_data(
                            self._row_sharding,
                            np.ascontiguousarray(local))
                    self.nbytes += local.nbytes
                else:
                    if self._n_pad != self.n:
                        v = np.concatenate(
                            [v, np.zeros((self._n_pad - self.n,)
                                         + v.shape[1:], v.dtype)])
                    self.arrays[k] = jax.device_put(
                        np.ascontiguousarray(v), self._row_sharding)
                    self.nbytes += v.nbytes
        self._reshard = None
        self._epoch_cache: Tuple[Optional[int], Optional[dict],
                                 Optional[jax.Array]] = (None, None, None)

    def _put_replicated(self, arr: np.ndarray) -> jax.Array:
        # make_array_from_callback is the multi-host-safe "same host
        # value everywhere -> one replicated global array" path (plain
        # device_put cannot target a process-spanning sharding)
        return jax.make_array_from_callback(
            arr.shape, self._replicated, lambda idx: arr[idx])

    def epoch_order(self, epoch: int) -> jax.Array:
        """The epoch's GLOBAL batch stream (pod_epoch_order) as a device
        int32 array — slicing ``[b*bs:(b+1)*bs]`` is global batch b,
        bitwise the pod's host-loader batch (pinned by test).  Kept for
        bookkeeping/step-signature uniformity: after the batch-major
        re-shard the dispatch itself never gathers through it."""
        cached_epoch, _view, order = self._epoch_cache
        if cached_epoch == epoch and order is not None:
            return order
        idx = pod_epoch_order(self.n, epoch, self.seed, self.shuffle,
                              self.pc, self.local_bs)
        return self._put_replicated(idx)

    def epoch_arrays(self, epoch: int) -> Dict[str, jax.Array]:
        """This epoch's batch-major view ``[steps, batch, ...]`` — ONE
        jitted collective re-shard per epoch (the only cross-device
        data movement of the epoch), cached until the next epoch."""
        cached_epoch, view, _order = self._epoch_cache
        if cached_epoch == epoch and view is not None:
            return view
        order = self.epoch_order(epoch)
        # drop the previous epoch's view BEFORE building the new one
        # (both the cache and the unpacked local): the cache is the only
        # reference that survives between epochs, so releasing it first
        # keeps the boundary peak at shards + ONE view (~2·n/pc per
        # host) instead of shards + old + new (~3×) — on a pod sharded
        # precisely because n/pc is near the HBM budget, the 3×
        # transient would OOM at the first epoch turn
        view = None
        self._epoch_cache = (None, None, None)
        if self._reshard is None:
            steps, bs = self.steps_per_epoch, self.batch_size

            def fn(data, idx):
                return {k: v[idx].reshape((steps, bs) + v.shape[1:])
                        for k, v in data.items()}

            # the per-epoch collective is a real compiled program: route
            # it through the compile observatory (identity when no
            # observatory is active) so its compile ms / fingerprint /
            # memory bytes land beside the train programs'
            from faster_distributed_training_tpu.telemetry.programs import (
                wrap_jit)
            self._reshard = wrap_jit(
                "epoch_reshard",
                jax.jit(fn, out_shardings={k: self._batch_sharding
                                           for k in self.arrays}),
                sig_argnums=(0, 1))
        with spans.span("epoch_reshard"):
            view = self._reshard(self.arrays, order)
        self._epoch_cache = (epoch, view, order)
        return view

    def verify_upload(self) -> bool:
        """Compare HBM contents (canonical row shards, pad trimmed)
        against the encode-time checksums — single-process only; see
        :func:`_verify_resident_upload`."""
        return _verify_resident_upload(self.arrays, self.n,
                                       self.upload_checksums)


def build_device_resident(cfg, train_ds, mesh=None):
    """cfg-gated constructor: None (host path) unless
    ``cfg.data_path == "resident"``.

    Layout resolution (``cfg.resident_layout``):
      * ``auto``       — replicated single-host (the unchanged r8 path),
                         per-host sharded on pods;
      * ``replicated`` — force the r8 layout; multi-host falls back to
                         the HOST path with a warning (a replicated
                         multi-host upload would put the whole split in
                         every host's HBM);
      * ``sharded``    — force per-host sharding (also usable single-
                         host to spread the split over local chips).
    """
    if getattr(cfg, "data_path", "host") != "resident":
        return None
    layout = getattr(cfg, "resident_layout", "auto") or "auto"
    pc = jax.process_count()
    if layout == "replicated" and pc > 1:
        import warnings
        warnings.warn(
            "--resident_layout replicated is single-host only (it would "
            "replicate the whole split into every host's HBM); falling "
            "back to the host data path — use --resident_layout auto or "
            "sharded for per-host sharded residency", stacklevel=2)
        return None
    if layout == "sharded" or (layout == "auto" and pc > 1):
        if mesh is None:
            import warnings
            warnings.warn(
                "sharded device residency needs a mesh; falling back to "
                "the host data path", stacklevel=2)
            return None
        return ShardedDeviceResidentData(
            train_ds, cfg.batch_size, seed=cfg.seed, max_len=cfg.seq_len,
            mesh=mesh,
            checksum=getattr(cfg, "sentinel", "none") not in ("none", None))
    return DeviceResidentData(
        train_ds, cfg.batch_size, seed=cfg.seed, max_len=cfg.seq_len,
        mesh=mesh,
        checksum=getattr(cfg, "sentinel", "none") not in ("none", None))
