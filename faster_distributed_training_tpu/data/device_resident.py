"""Device-resident train datasets: upload once, batch in-graph.

Both reference workloads are small enough to live in HBM whole —
CIFAR-10 train is 50000·32·32·3 uint8 ≈ 147 MB raw but ~37 MB for the
subset-strided configs the tuning harness runs, AG News at seq≤256 is
~50 MB of int32 token ids — so the steady-state input pipeline does not
need a host at all: the split is uploaded ONCE per run as compact
dtypes (uint8 images, int32 token ids) and every batch is assembled
*inside* the jitted train dispatch by an index gather
(``train.steps.make_fused_train_step``).  This removes the per-step
host work that bounds small-model step time (Murray et al., *tf.data*,
2021): the ``BatchLoader`` gather, the per-batch ``device_put``, and
the Python dispatch itself (amortized K× further by
``--steps_per_dispatch``).

Epoch semantics are the host loader's EXACTLY: the per-epoch order is
the same ``shard_for_host(n, epoch, seed)`` permutation ``BatchLoader.
plan()`` draws — a pure function of ``(seed, epoch)``, which is the
determinism contract the resilience bitwise-resume tests pin.  The
order is computed host-side once per EPOCH (an O(n) permutation and a
~4·n-byte upload — noise against an epoch of steps) rather than by an
in-graph ``jax.random.permutation``: threefry cannot reproduce numpy's
``default_rng((seed, epoch))`` stream, and bit-identical batch order
between the host and resident paths is a pinned test contract
(tests/test_fused_dispatch.py).

Text: the whole split is pre-encoded at ONE fixed bucket length (the
smallest ``seq_buckets`` entry covering the longest sequence, ≤
``max_len``) instead of the host path's per-batch bucketing — a single
compiled program over the epoch, trading pad FLOPs for zero host work.

Multi-host is deliberately unsupported (cli falls back to the host
path with a warning): residency would have to be per-host sharded —
each process holding only its shard — before ``process_count > 1``
runs could use it without replicating the split into every host's HBM
and re-deriving the per-host slice in-graph (README "Host-free inner
loop" records this as the open item)."""

from __future__ import annotations

from typing import Dict, Optional

import jax
import numpy as np

from faster_distributed_training_tpu.data.loader import (dataset_len,
                                                         shard_for_host)


class DeviceResidentData:
    """The train split as device arrays + per-epoch order uploads.

    ``arrays`` is a dict of device arrays with a leading sample axis
    (images: ``image`` uint8 NHWC + ``label`` int32; text: ``tokens``/
    ``token_types``/``mask``/``label`` int32), replicated over the mesh
    (every chip gathers its own batch shard from the full split).
    ``epoch_order(epoch)`` returns the epoch's device-resident index
    array — ``steps_per_epoch * batch_size`` int32 entries in exactly
    ``BatchLoader.plan()``'s order."""

    def __init__(self, data, batch_size: int, seed: int = 0,
                 max_len: int = 512, mesh=None, shuffle: bool = True):
        if jax.process_count() > 1:
            raise ValueError(
                "device-resident datasets are single-host only (per-host "
                "sharded residency is an open item); use the host data "
                "path for multi-host runs")
        self.batch_size = int(batch_size)
        self.seed = int(seed)
        self.shuffle = bool(shuffle)
        self.n = dataset_len(data)
        self.steps_per_epoch = self.n // self.batch_size
        if self.steps_per_epoch < 1:
            raise ValueError(
                f"dataset ({self.n} samples) smaller than one batch "
                f"({self.batch_size}) — nothing to train on")
        self.is_text = hasattr(data, "encode_batch")
        if self.is_text:
            # one fixed-length encoding of the whole split: the largest
            # batch-bucketed length any (seed, epoch) schedule could draw
            # is the bucket covering the split's longest sequence, so
            # every host-path batch embeds into this shape (content
            # equality modulo trailing padding — pinned by test)
            host = {k: np.asarray(v) for k, v in
                    data.encode_batch(np.arange(self.n), max_len).items()}
            self.seq_len = int(host["tokens"].shape[1])
        else:
            x, y = data
            host = {"image": np.asarray(x), "label": np.asarray(y)}
            self.seq_len = 0
        self.mesh = mesh
        self._replicated = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            self._replicated = NamedSharding(mesh, PartitionSpec())
        self.nbytes = sum(a.nbytes for a in host.values())
        self.arrays: Dict[str, jax.Array] = {
            k: self._put(v) for k, v in host.items()}

    def _put(self, arr: np.ndarray) -> jax.Array:
        if self._replicated is not None:
            return jax.device_put(arr, self._replicated)
        return jax.device_put(arr)

    def epoch_order(self, epoch: int) -> jax.Array:
        """The epoch's sample order as a device int32 array, truncated to
        whole batches — elementwise equal to concatenating
        ``BatchLoader.plan()``'s index entries for the same
        ``(seed, epoch)`` (single-process; drop-last)."""
        idx = shard_for_host(self.n, epoch, self.seed, self.shuffle,
                             process_index=0, process_count=1)
        idx = idx[: self.steps_per_epoch * self.batch_size]
        return self._put(np.ascontiguousarray(idx.astype(np.int32)))


def build_device_resident(cfg, train_ds, mesh=None
                          ) -> Optional[DeviceResidentData]:
    """cfg-gated constructor: None (host path) unless
    ``cfg.data_path == "resident"`` and the run is single-host."""
    if getattr(cfg, "data_path", "host") != "resident":
        return None
    if jax.process_count() > 1:
        import warnings
        warnings.warn(
            "--data_path resident is single-host only (per-host sharded "
            "residency is an open item, see README); falling back to the "
            "host data path", stacklevel=2)
        return None
    return DeviceResidentData(train_ds, cfg.batch_size, seed=cfg.seed,
                              max_len=cfg.seq_len, mesh=mesh)
