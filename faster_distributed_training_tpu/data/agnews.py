"""AG News text-classification pipeline.

Re-expression of the reference's AG_NEWS_DATASET + collate
(transformer_test.py:82-138): CSV loading, HTML tag stripping
(:73-75), URL stripping (:78-79), stopword removal (gensim's list in the
reference; a built-in English list here — gensim is not a dependency),
then tokenization.

Tokenizer: HuggingFace ``bert-base-uncased`` when cached locally (the
reference downloads it, transformer_test.py:96); otherwise our own
WordPiece (data/wordpiece.py — HF-algorithm-parity-tested) over a real
``vocab.txt`` if one exists on disk, else over a deterministic
corpus-trained vocab; the crc32 hash tokenizer remains only as the
no-corpus last resort.  Labels arrive 1-indexed in the CSV and are
shifted to 0-based (transformer_test.py:242).

TPU-critical change: the reference pads each batch to its longest
sequence (``padding='longest'``, transformer_test.py:97) — dynamic
shapes that would retrigger XLA compilation every step.  Here sequences
are padded into a fixed set of bucket lengths (cfg.seq_buckets), one
compiled program per bucket (SURVEY.md §7 hard part 3)."""

from __future__ import annotations

import csv
import html
import os
import re
import zlib
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

_TAG_RE = re.compile(r"<[^>]+>")
_URL_RE = re.compile(r"https?://\S+|www\.\S+")
_TOKEN_RE = re.compile(r"[a-z0-9']+")

# gensim's 337-word STOPWORDS list, vendored verbatim (the reference
# filters with gensim.parsing.remove_stopwords, transformer_test.py:95;
# gensim itself is not a dependency here).  The list is sklearn's
# 318-word ENGLISH_STOP_WORDS plus gensim's 19 documented additions —
# tests/test_data.py re-derives it from sklearn to pin exactness.
# Must equal kStopwords in runtime/native/fdt_native.cc (parity test in
# tests/test_runtime.py).
STOPWORDS = frozenset("""
a about above across after afterwards again against all almost alone
along already also although always am among amongst amoungst amount an
and another any anyhow anyone anything anyway anywhere are around as at
back be became because become becomes becoming been before beforehand
behind being below beside besides between beyond bill both bottom but
by call can cannot cant co computer con could couldnt cry de describe
detail did didn do does doesn doing don done down due during each eg
eight either eleven else elsewhere empty enough etc even ever every
everyone everything everywhere except few fifteen fifty fill find fire
first five for former formerly forty found four from front full further
get give go had has hasnt have he hence her here hereafter hereby
herein hereupon hers herself him himself his how however hundred i ie
if in inc indeed interest into is it its itself just keep kg km last
latter latterly least less ltd made make many may me meanwhile might
mill mine more moreover most mostly move much must my myself name
namely neither never nevertheless next nine no nobody none noone nor
not nothing now nowhere of off often on once one only onto or other
others otherwise our ours ourselves out over own part per perhaps
please put quite rather re really regarding same say see seem seemed
seeming seems serious several she should show side since sincere six
sixty so some somehow someone something sometime sometimes somewhere
still such system take ten than that the their them themselves then
thence there thereafter thereby therefore therein thereupon these they
thick thin third this those though three through throughout thru thus
to together too top toward towards twelve twenty two un under unless
until up upon us used using various very via was we well were what
whatever when whence whenever where whereafter whereas whereby wherein
whereupon wherever whether which while whither who whoever whole whom
whose why will with within without would yet you your yours yourself
yourselves
""".split())


def cleaner_fingerprint() -> str:
    """Hash of the cleaning configuration (today: the stopword list).
    The corpus-trained WordPiece vocab is built from clean_text output,
    so a vocab cached under one cleaner version must not be reused by
    another — the cache filename embeds this fingerprint."""
    return format(zlib.crc32(" ".join(sorted(STOPWORDS)).encode()), "08x")


def clean_text_py(text: str) -> str:
    """Pure-Python reference cleaner (transformer_test.py:73-79,95)."""
    text = html.unescape(text)
    text = _TAG_RE.sub(" ", text)
    text = _URL_RE.sub(" ", text)
    words = _TOKEN_RE.findall(text.lower())
    return " ".join(w for w in words if w not in STOPWORDS)


def clean_text(text: str) -> str:
    """strip HTML + URLs + stopwords (transformer_test.py:73-79,95).
    Entity unescaping runs in Python (html.unescape's full HTML5 table);
    the regex-heavy remainder uses the native C++ core when available —
    byte-equality with clean_text_py is enforced by tests/test_runtime.py."""
    from faster_distributed_training_tpu.runtime import native_lib
    out = native_lib.clean_text(html.unescape(text))
    return out if out is not None else clean_text_py(text)


class HashTokenizer:
    """Deterministic fallback tokenizer: crc32 hash buckets + specials.
    Same interface subset as the HF tokenizer the pipeline needs."""

    def __init__(self, vocab_size: int = 30522):
        self.vocab_size = vocab_size
        self.pad_id, self.cls_id, self.sep_id, self.unk_id = 0, 101, 102, 100
        self._reserved = 999  # ids below this are never produced by hashing

    def encode(self, text: str, max_len: int) -> List[int]:
        ids = [self.cls_id]
        for w in text.split()[:max_len - 2]:
            h = zlib.crc32(w.encode()) % (self.vocab_size - self._reserved)
            ids.append(h + self._reserved)
        ids.append(self.sep_id)
        return ids


def _load_hf_tokenizer():
    try:
        from transformers import AutoTokenizer
        return AutoTokenizer.from_pretrained("bert-base-uncased",
                                             local_files_only=True)
    except Exception:
        return None


# corpus-trained tokenizers memoized per data_dir: the TRAIN split builds
# the vocab, and the TEST split in the same process must reuse the same
# object even when the on-disk cache can't be written (read-only
# data_dir) — otherwise train and eval ids silently disagree
_corpus_tokenizers: Dict[str, object] = {}


def _resolve_tokenizer(data_dir: str, corpus_texts: Sequence[str]):
    """Tokenizer priority (transformer_test.py:96 wants bert-base-uncased):
      1. the HF tokenizer itself, when cached locally;
      2. our WordPiece over a real bert vocab.txt found on disk — same
         token ids as HF (algorithm parity: tests/test_wordpiece.py);
      3. our WordPiece over a deterministic corpus-trained vocab, cached
         beside the dataset (and memoized in-process) so train/test
         share one vocab (zero-egress);
      4. crc32 HashTokenizer (no corpus and no vocab — last resort).
    """
    from faster_distributed_training_tpu.data.wordpiece import (
        WordPieceTokenizer, build_wordpiece_vocab, find_bert_vocab)

    hf = _load_hf_tokenizer()
    if hf is not None:
        return hf
    if data_dir:
        vocab_path = find_bert_vocab(data_dir)
        if vocab_path:
            return WordPieceTokenizer.from_vocab_file(vocab_path)
    # the disk cache + in-process memo exist to make the CSV train and
    # test splits (same data_dir, same corpus family) share ONE vocab;
    # in-memory datasets (from_samples with no data_dir: tests,
    # benchmarks, ad-hoc corpora) must NOT read or write it — a vocab
    # trained on one corpus silently cripples tokenization of another
    if data_dir:
        cache = os.path.join(data_dir, "ag_news",
                             f"wordpiece_vocab_{cleaner_fingerprint()}.txt")
        if os.path.isfile(cache):
            return WordPieceTokenizer.from_vocab_file(cache)
        memo = _corpus_tokenizers.get(os.path.abspath(data_dir))
        if memo is not None:
            return memo
    if corpus_texts:
        tk = WordPieceTokenizer(build_wordpiece_vocab(corpus_texts))
        if data_dir:
            _corpus_tokenizers[os.path.abspath(data_dir)] = tk
            try:
                os.makedirs(os.path.dirname(cache), exist_ok=True)
                tk.save_vocab(cache)
            except OSError:
                print(f"[data] warning: could not write {cache}; later "
                      f"processes will rebuild the vocab from their own "
                      f"split — keep data_dir writable for cross-process "
                      f"train/eval vocab agreement")
        return tk
    return HashTokenizer()


def bucket_length(n: int, buckets: Sequence[int]) -> int:
    """Smallest bucket >= n (last bucket truncates)."""
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


class AGNewsDataset:
    """Map-style dataset over AG News CSV (class,title,description rows)."""

    def __init__(self, data_dir: str, train: bool = True,
                 buckets: Sequence[int] = (64, 128, 256, 512),
                 tokenizer=None, subset_stride: int = 1):
        path = os.path.join(data_dir, "ag_news",
                            "train.csv" if train else "test.csv")
        self.buckets = tuple(buckets)
        self.samples: List[Tuple[str, int]] = []
        # isfile, not exists: a failed download can leave a stray empty
        # DIRECTORY at the CSV path (observed round 5 — IsADirectoryError
        # instead of the clean FileNotFoundError fallback)
        if os.path.isfile(path):
            with open(path, newline="", encoding="utf-8") as f:
                for i, row in enumerate(csv.reader(f)):
                    if subset_stride > 1 and i % subset_stride:
                        continue
                    label = int(row[0]) - 1          # 1-indexed -> 0-based
                    text = " ".join(row[1:])
                    self.samples.append((clean_text(text), label))
        else:
            raise FileNotFoundError(
                f"AG News CSV not found at {path}; use data.synthetic."
                f"synthetic_agnews for offline runs")
        self.tokenizer = tokenizer
        if self.tokenizer is None:
            self.tokenizer = _resolve_tokenizer(
                data_dir, [t for t, _ in self.samples])

    @classmethod
    def from_samples(cls, samples: Sequence[Tuple[str, int]],
                     buckets: Sequence[int] = (64, 128, 256, 512),
                     tokenizer=None, data_dir: str = "",
                     clean: bool = True) -> "AGNewsDataset":
        """Build a dataset from in-memory (text, label) pairs — the same
        pipeline (clean -> tokenize -> bucket) without a CSV on disk;
        used by tests and the input-pipeline benchmark.  data_dir="" (the
        default) keeps the corpus-trained vocab in-memory only — an
        ad-hoc corpus must never poison the on-disk vocab cache a real
        dataset in that directory would load."""
        self = cls.__new__(cls)
        self.buckets = tuple(buckets)
        self.samples = [((clean_text(t) if clean else t), int(l))
                        for t, l in samples]
        self.tokenizer = tokenizer
        if self.tokenizer is None:
            self.tokenizer = _resolve_tokenizer(
                data_dir, [t for t, _ in self.samples])
        return self

    def __len__(self) -> int:
        return len(self.samples)

    def num_classes(self) -> int:
        return 4

    def vocab_size(self) -> int:
        tk = self.tokenizer
        return getattr(tk, "vocab_size", 30522)

    def _bucketed_native(self, tokens_full: np.ndarray, lens: np.ndarray,
                         labels: np.ndarray, max_len: int
                         ) -> Dict[str, np.ndarray]:
        """Shared tail of both native encode paths: bucket the padded
        [n, max_len] token matrix to the smallest fitting length and
        derive the attention mask from the true lengths."""
        from faster_distributed_training_tpu.data.loader import (
            select_bucket)
        L = select_bucket(int(lens.max()), self.buckets, max_len)
        tokens = tokens_full[:, :L]
        mask = (np.arange(L)[None, :] < lens[:, None]).astype(np.int32)
        return {"tokens": tokens, "token_types": np.zeros_like(tokens),
                "mask": mask, "label": labels}

    def encode_batch(self, indices: Sequence[int], max_len: int = 512
                     ) -> Dict[str, np.ndarray]:
        """Tokenize + pad to the bucketed length (static shapes)."""
        texts = [self.samples[i][0] for i in indices]
        labels = np.asarray([self.samples[i][1] for i in indices], np.int32)
        from faster_distributed_training_tpu.data.wordpiece import (
            WordPieceTokenizer)
        if isinstance(self.tokenizer, WordPieceTokenizer):
            tk = self.tokenizer
            handle = tk.native_handle()
            native = None
            if handle is not None:
                from faster_distributed_training_tpu.runtime import native_lib
                native = native_lib.wp_encode_batch(
                    handle, texts, max_len, tk.cls_id, tk.sep_id,
                    tk.unk_id, tk.pad_token_id)
            if native is not None:
                return self._bucketed_native(*native, labels, max_len)
            # non-ASCII text or no native lib: the generic Python path
            # below handles it (WordPieceTokenizer has the HF encode
            # signature)
        if isinstance(self.tokenizer, HashTokenizer):
            from faster_distributed_training_tpu.runtime import native_lib
            tk = self.tokenizer
            native = native_lib.encode_batch(
                texts, max_len, tk.vocab_size, tk.pad_id, tk.cls_id,
                tk.sep_id, tk._reserved)
            if native is not None:
                return self._bucketed_native(*native, labels, max_len)
            encoded = [self.tokenizer.encode(t, max_len) for t in texts]
            pad_id = self.tokenizer.pad_id
        else:
            encoded = [self.tokenizer.encode(t, truncation=True,
                                             max_length=max_len)
                       for t in texts]
            pad_id = self.tokenizer.pad_token_id
        longest = max(len(e) for e in encoded)
        from faster_distributed_training_tpu.data.loader import select_bucket
        L = select_bucket(longest, self.buckets, max_len)
        tokens = np.full((len(encoded), L), pad_id, np.int32)
        mask = np.zeros((len(encoded), L), np.int32)
        for i, e in enumerate(encoded):
            e = e[:L]
            tokens[i, :len(e)] = e
            mask[i, :len(e)] = 1
        return {"tokens": tokens, "token_types": np.zeros_like(tokens),
                "mask": mask, "label": labels}
