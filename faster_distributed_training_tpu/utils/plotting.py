"""Accuracy/time curves as PNGs — utils.py:54-69 re-expressed."""

from __future__ import annotations

from typing import Sequence


def draw_graph(data: Sequence[float], ylabel: str, title: str,
               path: str) -> str:
    """Save a single-curve PNG (epoch on x).  Matches the reference's
    draw_graph (utils.py:54-69) minus the global pyplot state."""
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(6, 4))
    ax.plot(range(len(data)), data)
    ax.set_xlabel("epoch")
    ax.set_ylabel(ylabel)
    ax.set_title(title)
    fig.tight_layout()
    fig.savefig(path, dpi=120)
    plt.close(fig)
    return path
