"""Profiling hooks: jax.profiler traces + device memory, replacing the
reference's cuda.max_memory_allocated prints (resnet50_test.py:623-625)."""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional

import jax


@contextlib.contextmanager
def trace_profile(log_dir: Optional[str]) -> Iterator[None]:
    """`with trace_profile('/tmp/trace'):` captures a TensorBoard-viewable
    profiler trace when log_dir is set; no-op otherwise."""
    if not log_dir:
        yield
        return
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def peak_memory_bytes(device: Optional[jax.Device] = None) -> Optional[int]:
    """Peak device memory if the backend exposes runtime stats (plain TPU
    does; the axon tunnel and CPU do not and get None — the Trainer then
    omits peak_mem from its epoch log; bench.py reports the static
    compiled_memory_bytes estimate instead)."""
    device = device or jax.local_devices()[0]
    stats = getattr(device, "memory_stats", lambda: None)()
    if not stats:
        return None
    return stats.get("peak_bytes_in_use")


def compiled_memory_bytes(compiled) -> Optional[int]:
    """Static peak estimate from a compiled executable's memory analysis:
    temp + argument + output − aliased (donated buffers are BOTH an
    argument and an output — counting them twice would overstate a
    donating train step by roughly the whole train state).  Available on
    every backend, including ones without runtime memory_stats."""
    try:
        ma = compiled.memory_analysis()
        if ma is None:
            return None
        total = 0
        for field in ("temp_size_in_bytes", "argument_size_in_bytes",
                      "output_size_in_bytes"):
            total += int(getattr(ma, field, 0) or 0)
        total -= int(getattr(ma, "alias_size_in_bytes", 0) or 0)
        return total if total > 0 else None
    except Exception:
        return None
