"""Profiling hooks: jax.profiler traces + device memory, replacing the
reference's cuda.max_memory_allocated prints (resnet50_test.py:623-625)."""

from __future__ import annotations

import contextlib
from typing import Callable, Iterator, Optional, Tuple

import jax


def parse_profile_steps(spec: str) -> Optional[Tuple[int, int]]:
    """``--profile_steps A:B`` -> (A, B), None for "".  Steps are
    1-indexed GLOBAL train steps (the checkpointed step counter), A <= B
    inclusive; malformed specs raise ValueError at config time, not at
    step A mid-run."""
    if not spec:
        return None
    a, sep, b = str(spec).partition(":")
    try:
        lo, hi = int(a), int(b)
    except ValueError:
        lo = hi = 0
    if not sep or lo < 1 or hi < lo:
        raise ValueError(
            f"bad --profile_steps {spec!r}; want 'A:B' with 1 <= A <= B "
            f"(1-indexed global train steps, inclusive)")
    return lo, hi


class StepWindowProfiler:
    """Windowed profiler capture: start/stop ``jax.profiler`` around a
    global-step range MID-RUN (``--profile_steps A:B``), instead of
    ``--profile``'s whole-run trace — which past toy scale is unusable
    (gigabytes of timeline for minutes of steady state that all looks
    the same).  The window quantizes to dispatch boundaries: under a
    K-step fused dispatch the trace covers the dispatches that contain
    steps A..B (there is no narrower host-observable boundary).  A run
    resumed past B never starts; resumed inside the window, it captures
    the remainder.

    ``start_fn``/``stop_fn`` are the test seam (default
    ``jax.profiler.start_trace``/``stop_trace``); a profiler failure
    logs and disables itself — observability must never kill training.
    """

    def __init__(self, log_dir: str, start_step: int, stop_step: int,
                 start_fn: Optional[Callable[[str], None]] = None,
                 stop_fn: Optional[Callable[[], None]] = None,
                 log: Callable[[str], None] = print):
        self.log_dir = log_dir
        self.a = int(start_step)
        self.b = int(stop_step)
        self._start = start_fn or (lambda d: jax.profiler.start_trace(d))
        self._stop = stop_fn or jax.profiler.stop_trace
        self._log = log
        self.active = False
        self.done = False
        self.started_at: Optional[int] = None
        self.stopped_at: Optional[int] = None

    def before_dispatch(self, completed_steps: int, n_steps: int = 1
                        ) -> None:
        """Called with the global steps completed so far, before a
        dispatch that will run steps ``completed+1 .. completed+n``."""
        if self.done or self.active:
            return
        if completed_steps >= self.b:
            self.done = True       # resumed past the window: never start
            return
        if completed_steps + n_steps >= self.a:
            try:
                self._start(self.log_dir)
            except Exception as e:
                self._log(f"[profile] could not start the step-window "
                          f"trace ({e!r}); --profile_steps disabled for "
                          f"this run")
                self.done = True
                return
            self.active = True
            self.started_at = completed_steps
            self._log(f"[profile] trace started before step "
                      f"{completed_steps + 1} (window {self.a}:{self.b}) "
                      f"-> {self.log_dir}")

    def after_dispatch(self, completed_steps: int,
                       fence: Optional[Callable[[], None]] = None) -> None:
        """Called after a dispatch with the new completed-step count;
        ``fence`` (e.g. a metrics readback) runs before stop so the
        trace includes the device work of the window's last dispatch."""
        if not self.active or completed_steps < self.b:
            return
        if fence is not None:
            try:
                fence()
            except Exception:
                pass
        self._finish(completed_steps)

    def close(self) -> None:
        """End-of-run/epoch-exhaustion: stop a still-open trace (the run
        ended before step B) so the capture is never lost."""
        if self.active:
            self._finish(None)

    def _finish(self, completed_steps: Optional[int]) -> None:
        try:
            self._stop()
        except Exception as e:
            self._log(f"[profile] stop_trace failed: {e!r}")
        self.active = False
        self.done = True
        self.stopped_at = completed_steps
        at = (f"after step {completed_steps}" if completed_steps is not None
              else "at run end (window unfinished)")
        self._log(f"[profile] trace stopped {at}; view with "
                  f"tensorboard --logdir {self.log_dir}")


@contextlib.contextmanager
def trace_profile(log_dir: Optional[str]) -> Iterator[None]:
    """`with trace_profile('/tmp/trace'):` captures a TensorBoard-viewable
    profiler trace when log_dir is set; no-op otherwise."""
    if not log_dir:
        yield
        return
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def peak_memory_bytes(device: Optional[jax.Device] = None) -> Optional[int]:
    """Peak device memory if the backend exposes runtime stats (plain TPU
    does; the axon tunnel and CPU do not and get None — the Trainer then
    omits peak_mem from its epoch log; bench.py reports the static
    compiled_memory_bytes estimate instead)."""
    device = device or jax.local_devices()[0]
    stats = getattr(device, "memory_stats", lambda: None)()
    if not stats:
        return None
    return stats.get("peak_bytes_in_use")


def memory_watermarks(device: Optional[jax.Device] = None
                      ) -> Optional[dict]:
    """{"peak_bytes", "bytes_in_use"} from the backend's runtime memory
    stats, or None where they don't exist (CPU, the axon tunnel) — the
    per-epoch device memory watermark the telemetry ``memory`` events
    carry (train/loop.py)."""
    device = device or jax.local_devices()[0]
    stats = getattr(device, "memory_stats", lambda: None)()
    if not stats:
        return None
    return {"peak_bytes": int(stats.get("peak_bytes_in_use", 0) or 0),
            "bytes_in_use": int(stats.get("bytes_in_use", 0) or 0)}


def compiled_memory_bytes(compiled) -> Optional[int]:
    """Static peak estimate from a compiled executable's memory analysis:
    temp + argument + output − aliased (donated buffers are BOTH an
    argument and an output — counting them twice would overstate a
    donating train step by roughly the whole train state).  Available on
    every backend, including ones without runtime memory_stats."""
    try:
        ma = compiled.memory_analysis()
        if ma is None:
            return None
        total = 0
        for field in ("temp_size_in_bytes", "argument_size_in_bytes",
                      "output_size_in_bytes"):
            total += int(getattr(ma, field, 0) or 0)
        total -= int(getattr(ma, "alias_size_in_bytes", 0) or 0)
        return total if total > 0 else None
    except Exception:
        return None
