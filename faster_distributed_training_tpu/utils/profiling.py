"""Profiling hooks: jax.profiler traces + device memory, replacing the
reference's cuda.max_memory_allocated prints (resnet50_test.py:623-625)."""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional

import jax


@contextlib.contextmanager
def trace_profile(log_dir: Optional[str]) -> Iterator[None]:
    """`with trace_profile('/tmp/trace'):` captures a TensorBoard-viewable
    profiler trace when log_dir is set; no-op otherwise."""
    if not log_dir:
        yield
        return
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def peak_memory_bytes(device: Optional[jax.Device] = None) -> Optional[int]:
    """Peak device memory if the backend exposes it (TPU does)."""
    device = device or jax.local_devices()[0]
    stats = getattr(device, "memory_stats", lambda: None)()
    if not stats:
        return None
    return stats.get("peak_bytes_in_use")
