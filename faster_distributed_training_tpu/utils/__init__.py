"""Observability: plotting, profiling, device memory, logging."""

from faster_distributed_training_tpu.utils.plotting import draw_graph  # noqa: F401
from faster_distributed_training_tpu.utils.profiling import (  # noqa: F401
    peak_memory_bytes, trace_profile)
