"""faster_distributed_training_tpu — a TPU-native distributed training framework.

A ground-up JAX/XLA/Pallas/pjit re-design of the capabilities of
SuperbTUM/Faster-Distributed-Training (reference surveyed in SURVEY.md):

- ResNet family + Transformer encoder workloads (``models/``)
- Online natural-gradient descent (Kaldi-style low-rank inverse-Fisher
  preconditioning) as a fully on-device optax transformation, plus
  MADGRAD / MirrorMADGRAD and LR schedules (``optim/``)
- mixup / learnable meta-mixup / intra-class mixup (``train/mixup.py``)
- fused Conv+BN and MLP kernels via ``jax.custom_vjp`` with backward
  recomputation, and Pallas TPU kernels for the hot ops (``ops/``)
- data-parallel, fully-sharded (FSDP/ZeRO-style), tensor-parallel and
  sequence-parallel (ring attention) execution over a ``jax.sharding.Mesh``
  with XLA collectives over ICI/DCN (``parallel/``)
- host input pipelines with background prefetch + device double-buffering,
  with a native C++ decode/augment core (``data/``, ``runtime/``)
- checkpoint/resume of full training state (params, optimizer incl. Fisher
  factors, RNG, step), profiling, metrics, plotting (``train/``, ``utils/``)
- fault tolerance: async + preemption-aware step-cadence checkpointing,
  a self-restarting supervisor, deterministic fault injection and
  goodput accounting (``resilience/``)

Import alias convention used throughout docs and tests::

    import faster_distributed_training_tpu as fdt
"""

__version__ = "0.1.0"

from faster_distributed_training_tpu import config as config  # noqa: F401
from faster_distributed_training_tpu import prng as prng  # noqa: F401
