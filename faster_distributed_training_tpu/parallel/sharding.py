"""Partition-spec builders: DP batch sharding, FSDP/ZeRO-3 param sharding, TP rules.

The reference's three data-parallel strategies (DataParallel
resnet50_test.py:466; DDP :716; FSDP+CPUOffload transformer_test.py:387-392)
all collapse to sharding choices on one mesh:

  DP    — batch sharded over ("dp","fsdp"), params replicated.
  FSDP  — batch sharded AND every large param sharded on its largest
          divisible axis over "fsdp" (ZeRO-3); XLA compiles the gradient
          psum into reduce_scatter + all_gather automatically.
  TP    — regex rules mapping transformer param names to head/hidden axes.

Host offload (CPUOffload(offload_params=True), transformer_test.py:46-48)
maps to `memory_kind="pinned_host"` shardings with explicit device_put.
"""

from __future__ import annotations

import re
from typing import Any, Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def batch_spec(mesh: Mesh, *extra_axes: Optional[str]) -> P:
    """PartitionSpec for a [batch, ...] array: batch over every data-ish mesh axis."""
    data_axes = mesh_data_axes(mesh)
    if not data_axes:
        data_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)[:1]
    lead = data_axes if len(data_axes) != 1 else data_axes[0]
    return P(lead, *extra_axes)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def mesh_data_axes(mesh: Optional[Mesh]) -> tuple:
    """The mesh's data axes with size > 1 (batch-sharding candidates)."""
    if mesh is None:
        return ()
    return tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names
                 and mesh.shape[a] > 1)


def shard_activation(x, mesh: Optional[Mesh], dims: Sequence) -> Any:
    """`with_sharding_constraint(x, P(*dims))`, defensively filtered.

    `dims` has one entry per array dim: None, an axis name, or a tuple
    of axis names.  Axes the mesh doesn't have (or has at size 1) are
    dropped, as is any dim annotation whose axis sizes don't divide the
    dim — so the SAME model code is a no-op on a 1D dp mesh and a real
    constraint on a (data, model) mesh (SNIPPETS [3]'s `with_sharding`
    pattern).  Semantically always the identity: it only constrains
    XLA's partitioner, never the values."""
    if mesh is None:
        return x
    spec, any_axis = [], False
    for i, d in enumerate(dims):
        if d is None:
            spec.append(None)
            continue
        names = (d,) if isinstance(d, str) else tuple(d)
        names = tuple(a for a in names if a in mesh.axis_names
                      and mesh.shape[a] > 1)
        total = int(np.prod([mesh.shape[a] for a in names])) if names else 1
        if not names or x.shape[i] % total:
            spec.append(None)
            continue
        spec.append(names if len(names) > 1 else names[0])
        any_axis = True
    if not any_axis:
        return x
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(*spec)))


def _largest_divisible_axis(shape: Sequence[int], n: int) -> Optional[int]:
    best, best_dim = None, 0
    for i, d in enumerate(shape):
        if d % n == 0 and d > best_dim:
            best, best_dim = i, d
    return best


def fsdp_partition_params(params: Any, mesh: Mesh, axis: str = "fsdp",
                          min_size: int = 1024) -> Any:
    """ZeRO-3-style spec pytree: shard each tensor's largest divisible dim.

    Tensors with fewer than `min_size` total elements stay replicated —
    sharding a 64-element BN scale just adds collective latency.
    Returns a pytree of PartitionSpec matching `params`.
    """
    if axis not in mesh.axis_names:
        return jax.tree.map(lambda _: P(), params)
    n = mesh.shape[axis]

    def spec_for(x):
        shape = np.shape(x)
        if n <= 1 or not shape or int(np.prod(shape)) < min_size:
            return P()
        i = _largest_divisible_axis(shape, n)
        if i is None:
            return P()
        spec = [None] * len(shape)
        spec[i] = axis
        return P(*spec)

    return jax.tree.map(spec_for, params)


def shard_pytree(tree: Any, specs: Any, mesh: Mesh) -> Any:
    """device_put a pytree according to a matching pytree of PartitionSpec."""
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), tree, specs)


# ---------------------------------------------------------------------------
# Tensor parallelism — name-based rules for the transformer (models/transformer.py)
# ---------------------------------------------------------------------------

_TP_RULES = (
    # attention projections: shard the head (output-feature) dim.
    # Patterns match models/transformer.py param paths plus common
    # hf/flax spellings.  The fused QKV kernel is (d_model, 3, h, d_k),
    # its bias (3, h, d_k) — the head axis is the shardable one.
    (r".*(attn|attention).*/qkv/kernel", P(None, None, "tp", None)),
    (r".*(attn|attention).*/qkv/bias", P(None, "tp", None)),
    (r".*(attn|attention).*/(query|key|value)/kernel", P(None, "tp")),
    (r".*(attn|attention).*/(query|key|value)/bias", P("tp")),
    (r".*(attn|attention).*/out/kernel", P("tp", None)),
    # MLP: first linear shards hidden out (+bias), second shards hidden in
    (r".*(ffn|mlp).*/(dense_0|fc1|wi)/kernel", P(None, "tp")),
    (r".*(ffn|mlp).*/(dense_0|fc1|wi)/bias", P("tp")),
    (r".*(ffn|mlp).*/(dense_1|fc2|wo)/kernel", P("tp", None)),
    # embeddings: shard the vocab dim of the token table only
    (r".*token_embedding", P("tp", None)),
)


def param_path_name(path) -> str:
    """'/'-joined name for a tree_map_with_path key path — THE framework
    convention for matching param names against sharding rules."""
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                    for k in path)


def tensor_parallel_rules(flat_name: str) -> P:
    """Map a '/'-joined param path to a TP PartitionSpec (P() if no rule hits)."""
    low = flat_name.lower()
    for pat, spec in _TP_RULES:
        if re.match(pat, low):
            return spec
    return P()


def apply_tp_rules(params: Any, mesh: Mesh) -> Any:
    """Spec pytree from _TP_RULES; falls back to replication."""
    if "tp" not in mesh.axis_names or mesh.shape["tp"] <= 1:
        return jax.tree.map(lambda _: P(), params)

    def lookup(path, _):
        return tensor_parallel_rules(param_path_name(path))

    return jax.tree_util.tree_map_with_path(lookup, params)
