"""Partition-spec builders: DP batch sharding, FSDP/ZeRO-3 param sharding, TP rules.

The reference's three data-parallel strategies (DataParallel
resnet50_test.py:466; DDP :716; FSDP+CPUOffload transformer_test.py:387-392)
all collapse to sharding choices on one mesh:

  DP    — batch sharded over ("dp","fsdp"), params replicated.
  FSDP  — batch sharded AND every large param sharded on its largest
          divisible axis over "fsdp" (ZeRO-3); XLA compiles the gradient
          psum into reduce_scatter + all_gather automatically.
  TP    — regex rules mapping transformer param names to head/hidden axes.

Host offload (CPUOffload(offload_params=True), transformer_test.py:46-48)
maps to `memory_kind="pinned_host"` shardings with explicit device_put.
"""

from __future__ import annotations

import re
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def batch_spec(mesh: Mesh, *extra_axes: Optional[str]) -> P:
    """PartitionSpec for a [batch, ...] array: batch over every data-ish mesh axis."""
    data_axes = mesh_data_axes(mesh)
    if not data_axes:
        data_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)[:1]
    lead = data_axes if len(data_axes) != 1 else data_axes[0]
    return P(lead, *extra_axes)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def mesh_data_axes(mesh: Optional[Mesh]) -> tuple:
    """The mesh's data axes with size > 1 (batch-sharding candidates)."""
    if mesh is None:
        return ()
    return tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names
                 and mesh.shape[a] > 1)


def shard_activation(x, mesh: Optional[Mesh], dims: Sequence) -> Any:
    """`with_sharding_constraint(x, P(*dims))`, defensively filtered.

    `dims` has one entry per array dim: None, an axis name, or a tuple
    of axis names.  Axes the mesh doesn't have (or has at size 1) are
    dropped, as is any dim annotation whose axis sizes don't divide the
    dim — so the SAME model code is a no-op on a 1D dp mesh and a real
    constraint on a (data, model) mesh (SNIPPETS [3]'s `with_sharding`
    pattern).  Semantically always the identity: it only constrains
    XLA's partitioner, never the values."""
    if mesh is None:
        return x
    spec, any_axis = [], False
    for i, d in enumerate(dims):
        if d is None:
            spec.append(None)
            continue
        names = (d,) if isinstance(d, str) else tuple(d)
        names = tuple(a for a in names if a in mesh.axis_names
                      and mesh.shape[a] > 1)
        total = int(np.prod([mesh.shape[a] for a in names])) if names else 1
        if not names or x.shape[i] % total:
            spec.append(None)
            continue
        spec.append(names if len(names) > 1 else names[0])
        any_axis = True
    if not any_axis:
        return x
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(*spec)))


def _largest_divisible_axis(shape: Sequence[int], n: int) -> Optional[int]:
    best, best_dim = None, 0
    for i, d in enumerate(shape):
        if d % n == 0 and d > best_dim:
            best, best_dim = i, d
    return best


def fsdp_partition_params(params: Any, mesh: Mesh, axis: str = "fsdp",
                          min_size: int = 1024) -> Any:
    """ZeRO-3-style spec pytree: shard each tensor's largest divisible dim.

    Tensors with fewer than `min_size` total elements stay replicated —
    sharding a 64-element BN scale just adds collective latency.
    Returns a pytree of PartitionSpec matching `params`.
    """
    if axis not in mesh.axis_names:
        return jax.tree.map(lambda _: P(), params)
    n = mesh.shape[axis]

    def spec_for(x):
        shape = np.shape(x)
        if n <= 1 or not shape or int(np.prod(shape)) < min_size:
            return P()
        i = _largest_divisible_axis(shape, n)
        if i is None:
            return P()
        spec = [None] * len(shape)
        spec[i] = axis
        return P(*spec)

    return jax.tree.map(spec_for, params)


def shard_pytree(tree: Any, specs: Any, mesh: Mesh) -> Any:
    """device_put a pytree according to a matching pytree of PartitionSpec."""
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), tree, specs)


# ---------------------------------------------------------------------------
# Tensor parallelism — name-based rules for the transformer (models/transformer.py)
# ---------------------------------------------------------------------------

_TP_RULES = (
    # attention projections: shard the head (output-feature) dim.
    # Patterns match models/transformer.py param paths plus common
    # hf/flax spellings.  The fused QKV kernel is (d_model, 3, h, d_k),
    # its bias (3, h, d_k) — the head axis is the shardable one.
    (r".*(attn|attention).*/qkv/kernel", P(None, None, "tp", None)),
    (r".*(attn|attention).*/qkv/bias", P(None, "tp", None)),
    (r".*(attn|attention).*/(query|key|value)/kernel", P(None, "tp")),
    (r".*(attn|attention).*/(query|key|value)/bias", P("tp")),
    (r".*(attn|attention).*/out/kernel", P("tp", None)),
    # MLP: first linear shards hidden out (+bias), second shards hidden in
    (r".*(ffn|mlp).*/(dense_0|fc1|wi)/kernel", P(None, "tp")),
    (r".*(ffn|mlp).*/(dense_0|fc1|wi)/bias", P("tp")),
    (r".*(ffn|mlp).*/(dense_1|fc2|wo)/kernel", P("tp", None)),
    # embeddings: shard the vocab dim of the token table only
    (r".*token_embedding", P("tp", None)),
)


def param_path_name(path) -> str:
    """'/'-joined name for a tree_map_with_path key path — THE framework
    convention for matching param names against sharding rules."""
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                    for k in path)


def tensor_parallel_rules(flat_name: str) -> P:
    """Map a '/'-joined param path to a TP PartitionSpec (P() if no rule hits)."""
    low = flat_name.lower()
    for pat, spec in _TP_RULES:
        if re.match(pat, low):
            return spec
    return P()


def apply_tp_rules(params: Any, mesh: Mesh) -> Any:
    """Spec pytree from _TP_RULES; falls back to replication."""
    if "tp" not in mesh.axis_names or mesh.shape["tp"] <= 1:
        return jax.tree.map(lambda _: P(), params)

    def lookup(path, _):
        return tensor_parallel_rules(param_path_name(path))

    return jax.tree_util.tree_map_with_path(lookup, params)


# ---------------------------------------------------------------------------
# ZeRO optimizer-state sharding — shape-aware rules (ISSUE 16 tentpole)
# ---------------------------------------------------------------------------
#
# The params overlay above cannot cover the optimizer state: NGD's
# grouped factor states (optim/ngd.py GroupState) do NOT mirror param
# shapes — w is (G, rank, dim) stacked over group members — so rules
# here match by leaf ROLE + SHAPE, not by param-tree position.  The two
# registries below are THE inspectable spec (SNIPPETS [2] idiom): every
# opt-state leaf any of our optimizers produce must classify into one
# OPT_STATE_RULES entry or one REPLICATED_OPT_STATE entry, enforced by
# scripts/check_sharding_rules.py (a new optimizer leaf cannot silently
# regress to replicated).

ZERO_MIN_SIZE = 1024

# rule name -> how the leaf is recognized and sharded (documentation
# table; classify_opt_state_leaf is the executable form).
OPT_STATE_RULES: Dict[str, str] = {
    "param_mirror":
        "leaf path ends with a param path and shapes agree (optax trace/"
        "adam mu,nu/madgrad s,v,z embed the param tree whole) — inherit "
        "the param's tp spec, else shard the largest divisible axis",
    "ngd_group_factor":
        "path contains .groups[ (GroupState w (G,rank,dim), d (G,rank),"
        " rho (G,)) — shard the leading group axis; per-member math is "
        "vmapped over G so splitting it is pure batching",
    "ngd_axis_factor":
        "path contains .axes[ (ungrouped OnlineNaturalGradientState "
        "w (rank,dim), d (rank,)) — shard the largest divisible axis",
}

# leaf classes that stay replicated ON PURPOSE, with the reason the
# lint requires.  Keyed by class name; classify returns these names.
REPLICATED_OPT_STATE: Dict[str, str] = {
    "scalar":
        "rank-0 counters and scales (t/step/count/rho/loss-scale) — "
        "nothing to shard, and every chip needs them each step",
    "small":
        f"fewer than {ZERO_MIN_SIZE} elements — sharding a bias-sized "
        "slot just adds collective latency (same floor as FSDP params)",
    "indivisible":
        "no axis divisible by the zero-axis size — padding slots would "
        "break the bitwise checkpoint-interchange contract",
    "unmatched":
        "no rule recognized the leaf role — conservatively replicated; "
        "scripts/check_sharding_rules.py fails until a rule (or an "
        "explicit entry here) covers the new optimizer's leaf class",
}


def _param_suffix_table(params: Any, param_specs: Any) -> Dict[str, tuple]:
    """keystr -> (shape, spec) for every param leaf; opt-state mirror
    leaves are recognized because optax embeds the param tree whole, so
    their keystr ENDS WITH the param's keystr."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    spec_flat = jax.tree_util.tree_flatten_with_path(
        param_specs, is_leaf=lambda x: isinstance(x, P))[0]
    table = {}
    for (path, leaf), (_, spec) in zip(flat, spec_flat):
        table[jax.tree_util.keystr(path)] = (np.shape(leaf), spec)
    return table


def classify_opt_state_leaf(key: str, shape, suffixes: Dict[str, tuple],
                            n: int, axis: str = "tp",
                            min_size: int = ZERO_MIN_SIZE
                            ) -> Tuple[str, P]:
    """(rule-or-replicate-class name, PartitionSpec) for one opt-state leaf.

    `key` is the jax.tree_util.keystr of the leaf inside the opt_state
    pytree, `suffixes` the _param_suffix_table of the (tp-overlaid)
    params.  Shape-aware on purpose: the same field name means different
    things in different optimizers, but role + shape is unambiguous.
    """
    shape = tuple(shape)
    if not shape:
        return "scalar", P()
    numel = int(np.prod(shape))

    def largest_axis_spec(rule: str) -> Tuple[str, P]:
        if numel < min_size:
            return "small", P()
        i = _largest_divisible_axis(shape, n)
        if i is None:
            return "indivisible", P()
        spec = [None] * len(shape)
        spec[i] = axis
        return rule, P(*spec)

    # NGD factor states first: their trees also contain param-named
    # fragments nowhere (groups are keyed "r2:n128:d64:k16"), but check
    # role markers before the mirror suffix test for clarity.  keystr
    # renders NamedTuple fields as attribute access (".groups[…]").
    if ".groups[" in key:
        # GroupState: leading axis is the stacked group-member axis G;
        # _group_precondition is vmapped over it, so sharding G is pure
        # batching.  Fall back to any divisible axis (w's dim often
        # divides when G does not).
        if shape[0] % n == 0 and numel >= min_size:
            spec = [None] * len(shape)
            spec[0] = axis
            return "ngd_group_factor", P(*spec)
        return largest_axis_spec("ngd_group_factor")
    if ".axes[" in key:
        return largest_axis_spec("ngd_axis_factor")

    for pkey, (pshape, pspec) in suffixes.items():
        if key.endswith(pkey) and shape == tuple(pshape):
            if pspec != P():
                return "param_mirror", pspec
            return largest_axis_spec("param_mirror")

    return "unmatched", P()


def zero_opt_state_specs(opt_state: Any, params: Any, param_specs: Any,
                         mesh: Mesh, axis: str = "tp",
                         min_size: int = ZERO_MIN_SIZE) -> Any:
    """Spec pytree for the optimizer state, ZeRO-sharded over `axis`.

    Momentum/adam/madgrad slots inherit the matching param's (possibly
    tp-overlaid) spec; NGD factor states shard by role + shape (they do
    not mirror params); scalars and sub-floor leaves replicate with a
    registered reason.  Returns all-P() when the axis is absent/size 1.
    """
    if axis not in mesh.axis_names or mesh.shape[axis] <= 1:
        return jax.tree.map(lambda _: P(), opt_state)
    n = mesh.shape[axis]
    suffixes = _param_suffix_table(params, param_specs)

    def per_leaf(path, leaf):
        key = jax.tree_util.keystr(path)
        _, spec = classify_opt_state_leaf(
            key, np.shape(leaf), suffixes, n, axis=axis,
            min_size=min_size)
        return spec

    return jax.tree_util.tree_map_with_path(per_leaf, opt_state)


# ---------------------------------------------------------------------------
# Per-stage parameter residency over pp (ISSUE 19 tentpole)
# ---------------------------------------------------------------------------
#
# r22 left every param replicated over pp, so a 4-stage model still had
# to fit one slice's HBM.  The overlay below gives stage-owned leaves —
# params under a ``layer_{i}`` subtree, whose stage home
# pipeline.param_stage_home reads off the ONE rule table — a 'pp' entry
# on a free axis of their (tp/fsdp-overlaid) spec, so each stage's
# chips hold 1/pp of the layer weights and (through the param_mirror
# inheritance in classify_opt_state_leaf) 1/pp of their optimizer
# mirrors.  Values are untouched: GSPMD materializes a leaf at use from
# its shards, so pp=2 ≡ pp=1 parity and the bitwise checkpoint
# interchange (specs live in the restore template, never the arrays)
# both survive.  The registries are the inspectable spec, enforced by
# scripts/check_sharding_rules.py exactly like OPT_STATE_RULES: a new
# param leaf class cannot silently re-replicate over pp.
#
# Honest scope note (the CPU-measurable claim): this is RESIDENCY —
# bytes at rest per chip scale with 1/pp, which is what the
# pp_param_bytes_per_chip bench arms measure.  On the steady path the
# unrolled tick loop applies each layer once per tick, and GSPMD
# gathers a stage's shard set at first use and CSEs the gather across
# ticks (ZeRO-3-class traffic, one gather per layer per step); the
# real-HBM/real-DCN traffic read is the live-TPU carryover item in
# ROADMAP.md.

PP_RESIDENCY_RULES: Dict[str, str] = {
    "stage_owned":
        "param under layer_{i} (pipeline.param_stage_home maps i to its "
        "stage) — 'pp' added on the largest free axis (one not already "
        "carrying fsdp/tp) divisible by the pp size; optimizer mirrors "
        "inherit the spec via classify_opt_state_leaf's param_mirror "
        "rule, multiplying the ZeRO reduction on dp x tp x pp meshes",
}

# param leaf classes that stay replicated over pp ON PURPOSE, with the
# registered reason the lint requires (the REPLICATED_OPT_STATE idiom).
REPLICATED_PP_PARAMS: Dict[str, str] = {
    "shared_embed":
        "embedding tables (token/pos/segment) — consumed by stage 0's "
        "input assembly and (tied LM head) the last stage's logits, so "
        "no single stage owns them; logical home stage 0",
    "shared_head":
        "ln_final / pooler / classifier / lm_head — applied after the "
        "staged encoder on the reassembled full batch; logical home is "
        "the last stage",
    "pp_small":
        f"stage-owned but fewer than {ZERO_MIN_SIZE} elements (LN "
        "scales/biases) — sharding a bias-sized leaf just adds "
        "collective latency (same floor as FSDP/ZeRO)",
    "pp_indivisible":
        "stage-owned but no free axis divisible by the pp size — "
        "padding would break the bitwise checkpoint interchange",
    "pp_unmatched":
        "param_stage_home recognized neither a layer home nor a shared "
        "role — conservatively replicated; "
        "scripts/check_sharding_rules.py fails until a rule (or an "
        "explicit entry here) covers the new leaf class",
}


def classify_pp_param_leaf(role: str, shape, base_spec: P, n: int,
                           axis: str = "pp",
                           min_size: int = ZERO_MIN_SIZE
                           ) -> Tuple[str, P]:
    """(class name, PartitionSpec) for one param leaf under per-stage
    residency.  ``role`` is pipeline.param_stage_home's verdict
    ('stage_owned' / 'shared_embed' / 'shared_head' / 'unknown');
    ``base_spec`` the leaf's existing (fsdp/tp-overlaid) spec, whose
    occupied axes are off-limits.  Only stage-owned leaves shard: the
    'pp' entry lands on the largest FREE axis divisible by ``n``."""
    shape = tuple(shape)
    if role in ("shared_embed", "shared_head"):
        return role, base_spec
    if role != "stage_owned":
        return "pp_unmatched", base_spec
    if not shape or int(np.prod(shape)) < min_size:
        return "pp_small", base_spec
    entries = tuple(base_spec) + (None,) * (len(shape) - len(base_spec))
    best, best_dim = None, 0
    for i, d in enumerate(shape):
        if entries[i] is None and d % n == 0 and d > best_dim:
            best, best_dim = i, d
    if best is None:
        return "pp_indivisible", base_spec
    out = list(entries)
    out[best] = axis
    return "stage_owned", P(*out)


def pp_residency_specs(params: Any, base_specs: Any, pipeline,
                       mesh: Mesh, min_size: int = ZERO_MIN_SIZE) -> Any:
    """Overlay per-stage residency onto the model-param spec tree:
    stage-owned leaves (per ``pipeline``'s rule table) gain a 'pp'
    entry per classify_pp_param_leaf; everything else keeps its base
    spec.  Identity when the mesh has no pp axis of size > 1."""
    if "pp" not in mesh.axis_names or mesh.shape["pp"] <= 1:
        return base_specs
    from faster_distributed_training_tpu.parallel.pipeline import (
        param_stage_home)
    n = mesh.shape["pp"]

    def per_leaf(path, leaf, base):
        role, _ = param_stage_home(pipeline, param_path_name(path))
        _, spec = classify_pp_param_leaf(role, np.shape(leaf), base, n,
                                         min_size=min_size)
        return spec

    return jax.tree_util.tree_map_with_path(
        per_leaf, params, base_specs)


def mirror_param_specs(opt_state: Any, params: Any,
                       param_specs: Any) -> Any:
    """Spec pytree placing each opt-state PARAM-MIRROR leaf (optax
    trace/adam mu,nu/madgrad s,v,z — recognized exactly like
    classify_opt_state_leaf: keystr suffix match + shape agreement) on
    its param's spec; P() everywhere else.

    This is the residency slice of the ZeRO overlay factored out so
    placement can apply it on pp meshes even under --no_zero_opt: a
    stage-owned param whose adam moments stay replicated would cap HBM
    at one slice's optimizer state, silently undoing the r23 tentpole
    for the (much larger) opt-state fraction.  When the full ZeRO
    overlay also runs it agrees on every mirror leaf (same suffix
    table, same inheritance), so applying both is idempotent."""
    suffixes = _param_suffix_table(params, param_specs)

    def per_leaf(path, leaf):
        key = jax.tree_util.keystr(path)
        shape = tuple(np.shape(leaf))
        for pkey, (pshape, pspec) in suffixes.items():
            if key.endswith(pkey) and shape == tuple(pshape):
                return pspec
        return P()

    return jax.tree_util.tree_map_with_path(per_leaf, opt_state)


# elements below this stay on device even under --offload_opt_state:
# streaming a bias-sized slot over PCIe costs more latency than the
# HBM it frees.  64Ki elements ~= 256 KB fp32.
OFFLOAD_MIN_ELEMENTS = 65536


def offload_opt_leaf(shape) -> bool:
    """Whether an opt-state leaf joins the host tier under
    --offload_opt_state.  Size-based: the big factor/momentum slots
    dominate HBM and amortize the PCIe round-trip; small slots stay
    resident (see README's offload cost model)."""
    shape = tuple(shape)
    return bool(shape) and int(np.prod(shape)) >= OFFLOAD_MIN_ELEMENTS


# ---------------------------------------------------------------------------
# Overlapped gradient reduce-scatter (ISSUE 16 tentpole, part C)
# ---------------------------------------------------------------------------

def bucketed_grad_reduce(grads: Any, mesh: Optional[Mesh],
                         axis: Optional[str] = None,
                         bucket_bytes: int = 4 << 20) -> Any:
    """Value-identity resharding pass that makes XLA lower the gradient
    reduction as bucketed reduce-scatter instead of one giant all-reduce.

    Flattens same-dtype gradient leaves into ~`bucket_bytes` 1-D buckets,
    constrains each bucket to P(axis), and splits back.  Because the
    constraint is on an intermediate, GSPMD materializes the scattered
    form right after the backward produces each bucket and defers the
    matching all-gather to first use — inside the K-dispatch `lax.scan`
    that means the collective for microbatch i overlaps microbatch
    i+1's compute.  Pure reshard: never changes values (reduce ORDER may
    shift float bits, which is why --overlap_grad_reduce defaults off
    and the K-twin pins compare the flag-off path).
    """
    if mesh is None:
        return grads
    if axis is None:
        axis = next((a for a in ("tp", "fsdp", "dp")
                     if a in mesh.axis_names and mesh.shape[a] > 1), None)
    if axis is None or axis not in mesh.axis_names or mesh.shape[axis] <= 1:
        return grads
    n = mesh.shape[axis]
    scattered = NamedSharding(mesh, P(axis))

    flat, treedef = jax.tree.flatten(grads)
    out = list(flat)
    by_dtype: Dict[Any, list] = {}
    for i, g in enumerate(flat):
        if not hasattr(g, "dtype") or g.ndim is None:
            continue
        by_dtype.setdefault(jnp.result_type(g), []).append(i)

    def flush(idxs):
        if not idxs:
            return
        vec = jnp.concatenate([flat[i].reshape(-1) for i in idxs])
        # materialize the logical (fully dp-reduced) gradient BEFORE the
        # scatter constraint: straight off the backward pass these leaves
        # are pending partial-sums over the data axes, and GSPMD resharding
        # a partial-sum value to P(axis) double-reduces it (measured:
        # exactly dp× gradients on a dp4 mesh, CPU and TPU partitioners
        # alike).  The P() pin forces the one true all-reduce here; XLA's
        # collective optimizer then fuses it with the adjacent
        # dynamic-slice into the reduce-scatter this pass exists for.
        vec = jax.lax.with_sharding_constraint(
            vec, NamedSharding(mesh, P()))
        pad = (-vec.size) % n
        if pad:
            vec = jnp.pad(vec, (0, pad))
        vec = jax.lax.with_sharding_constraint(vec, scattered)
        off = 0
        for i in idxs:
            size = flat[i].size
            out[i] = vec[off:off + size].reshape(flat[i].shape)
            off += size

    for dtype, idxs in by_dtype.items():
        itemsize = jnp.dtype(dtype).itemsize
        bucket, bucket_bytes_used = [], 0
        for i in idxs:
            bucket.append(i)
            bucket_bytes_used += flat[i].size * itemsize
            if bucket_bytes_used >= bucket_bytes:
                flush(bucket)
                bucket, bucket_bytes_used = [], 0
        flush(bucket)

    return jax.tree.unflatten(treedef, out)
