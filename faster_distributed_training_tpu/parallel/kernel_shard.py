"""ONE shard_map wrapper layer: every Pallas kernel partitions over tp.

The repo's recurring measured caveat (recorded three times: flash
attention r11, the monolithic fused-FFN kernel r11, the quant-matmul
kernel r13) was that Pallas custom calls don't partition over the tp
axis, so every 2D ``(dp, tp)`` mesh silently rerouted the hand-written
kernels — the paper's "faster" lever — to slower XLA/flax fallbacks.
This module is the single layer that closes the gap: each kernel runs
PER SHARD under ``shard_map`` on operands that are already tp-sharded
the way the r11 TP param rules lay them out, so the kernel wins and
the 2D-mesh wins compose instead of excluding each other.

Decompositions (one per recovered kernel):

* **flash attention — head-sharded** (``flash_attention_sharded``):
  heads divide tp, so each device runs the monolithic/K-blocked flash
  kernel on its local ``H/tp`` heads with batch over the data axes.
  Zero collectives inside the sublayer (attention is embarrassingly
  parallel over heads); the in-kernel hash dropout addresses GLOBAL
  ``(b, h)`` stream indices via the kernels' ``bh0``/``h_glob``
  plumbing, so masks stay placement-invariant.
* **fused FFN — Megatron column-then-row** (``fused_ffn_sublayer_tp``):
  w1 arrives column-sharded ``[d, d_ff/tp]``, w2 row-sharded
  ``[d_ff/tp, d]`` (exactly the r11 ``_TP_RULES`` layout — NO per-step
  weight gather, the exact failure the old fallback existed to avoid).
  Each shard runs the generalized kernel in PARTIAL mode (LN -> GEMM1
  -> GELU -> hidden dropout on global d_ff columns -> GEMM2, stopping
  before b2), then ONE ``psum`` over tp inside the shard_map boundary
  recombines the row-parallel products; b2 + connection dropout +
  residual apply on each shard's OWN sequence slice, so the output
  leaves the boundary sequence-sharded over tp (Megatron-SP: the psum
  + slice is a reduce-scatter in XLA's hands) and — critically for
  ``check_vma=False`` autodiff — every mesh axis appears in the out
  spec, keeping the transpose's cotangent psums correct.
* **quant matmul — column/row per TP rule** (``quant_dense_sharded``):
  each QuantDense site names the kernel dim its TP rule shards
  (``tp_dim``); column-parallel sites contract locally and emit
  tp-sharded output columns, row-parallel sites contract their local
  K rows and ``psum`` once — the Pallas quant kernel (or the XLA
  reference off-TPU, same math) runs per-shard either way, and the
  delayed per-tensor scales stay GLOBAL scalars (amax reductions
  happen outside the boundary on the logical arrays, unchanged).

Enablement: the layer is ON by default; ``FDT_KERNEL_SHARD=0`` kills
it, restoring the r11/r13 warned capability fallbacks — which also
makes the kill switch the bench A/B arm (kernel-via-shard_map vs
forced fallback, ``transformer_tp2_*`` arms).  Non-dividing shapes
(heads/d_ff/seq not divisible by tp) take the same registered warned
fallbacks; ``scripts/check_kernel_routing.py`` (tier-1) lints that no
NEW call site reaches a Pallas kernel entry point outside this layer
or those registered fallbacks.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from faster_distributed_training_tpu.compat import shard_map
from faster_distributed_training_tpu.parallel.mesh import axis_size, tp_size

ENV_KILL = "FDT_KERNEL_SHARD"


def enabled() -> bool:
    """FDT_KERNEL_SHARD=0 kill switch (read per call so bench children
    and tests can flip it): False restores the pre-r19 warned
    capability fallbacks on tp meshes."""
    return os.environ.get(ENV_KILL, "1") != "0"


def _batch_axes(mesh: Mesh) -> Tuple[str, ...]:
    return tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names
                 and mesh.shape[a] > 1)


def _lead(batch: Tuple[str, ...]):
    if not batch:
        return None
    return batch if len(batch) != 1 else batch[0]


def _batch_index(mesh: Mesh, batch: Tuple[str, ...]) -> jax.Array:
    """Row-major flat index of this device's batch-shard — the same
    convention fused_ffn_sublayer_sharded uses, so the two layers'
    global-row addressing can never disagree."""
    bi = jnp.uint32(0)
    for ax in batch:
        bi = bi * jnp.uint32(mesh.shape[ax]) \
            + lax.axis_index(ax).astype(jnp.uint32)
    return bi


# ---------------------------------------------------------------------------
# flash attention: head-sharded over tp
# ---------------------------------------------------------------------------

def flash_serviceable(mesh: Optional[Mesh], n_heads: int) -> bool:
    """True when the head-sharded flash wrapper can serve this mesh:
    the layer is enabled and the heads divide tp.  (Sequence length is
    untouched — each shard sees full rows.)"""
    tp = tp_size(mesh)
    return enabled() and tp > 1 and n_heads % tp == 0


def flash_attention_sharded(q: jax.Array, k: jax.Array, v: jax.Array,
                            mask: Optional[jax.Array], mesh: Mesh,
                            dropout_rate: float = 0.0,
                            dropout_seed: Optional[jax.Array] = None,
                            save_stats: Optional[bool] = None
                            ) -> jax.Array:
    """[B,H,L,D] flash attention with H sharded over tp and B over the
    data axes — each device runs the flash Pallas kernel (or its
    off-TPU blockwise twin, same routing as the unsharded call) on its
    local heads.  Dropout masks address GLOBAL (b, h) stream indices
    (ops/flash_attention._pack_seed), so the SAME seed draws the SAME
    pattern at any tp/dp layout — the placement-invariance contract
    every sharded dropout consumer in this repo keeps."""
    from faster_distributed_training_tpu.ops.flash_attention import (
        flash_attention)

    B, H, L, D = q.shape
    tp = tp_size(mesh)
    if tp <= 1 or H % tp:
        raise ValueError(
            f"flash_attention_sharded needs a tp axis whose size divides "
            f"the head count (H={H}, mesh={dict(mesh.shape) if mesh else None}"
            f") — build_model routes non-dividing shapes to the warned "
            f"fallback instead")
    batch = _batch_axes(mesh)
    lead = _lead(batch)
    qkv_spec = P(lead, "tp", None, None)
    b_shards = 1
    for a in batch:
        b_shards *= mesh.shape[a]
    b_loc, h_loc = B // b_shards, H // tp

    key_mask = None
    if mask is not None:
        m = jnp.asarray(mask)
        if m.ndim == 4:                      # [B,1,1,L] -> [B,L]
            m = m.reshape(B, m.shape[-1])
        key_mask = jnp.broadcast_to(m, (B, k.shape[2]))

    has_mask = key_mask is not None
    has_drop = dropout_rate > 0.0

    args, specs = [q, k, v], [qkv_spec] * 3
    if has_mask:
        args.append(key_mask)
        specs.append(P(lead, None))
    if has_drop:
        args.append(jnp.asarray(dropout_seed if dropout_seed is not None
                                else 0, jnp.uint32))
        specs.append(P())

    def call(q_, k_, v_, *rest):
        rest = list(rest)
        mask_ = rest.pop(0) if has_mask else None
        seed_ = rest.pop(0) if has_drop else None
        b0 = _batch_index(mesh, batch) * jnp.uint32(b_loc)
        h0 = lax.axis_index("tp").astype(jnp.uint32) * jnp.uint32(h_loc)
        return flash_attention(q_, k_, v_, mask=mask_,
                               dropout_rate=dropout_rate,
                               dropout_seed=seed_,
                               save_stats=save_stats,
                               bh0=(b0, h0), h_glob=H)

    return shard_map(call, mesh=mesh, in_specs=tuple(specs),
                     out_specs=qkv_spec,
                     # the pallas_call's out_shape carries no
                     # varying-mesh-axes info (the fused_ffn precedent)
                     check_vma=False)(*args)


# ---------------------------------------------------------------------------
# fused FFN: Megatron column-then-row over tp
# ---------------------------------------------------------------------------

def ffn_tp_serviceable(mesh: Optional[Mesh], d_ff: int,
                       seq_len: int) -> bool:
    """True when the column/row-sharded fused-FFN wrapper can serve:
    layer enabled, d_ff divides tp (the column/row split) and the
    sequence divides sp*tp (the output leaves sequence-sharded over tp
    inside any dedicated-sp sharding)."""
    tp = tp_size(mesh)
    if not (enabled() and tp > 1 and d_ff % tp == 0):
        return False
    sp = axis_size(mesh, "sp")
    return seq_len % (sp * tp) == 0


def fused_ffn_sublayer_tp(h, ln_scale, ln_bias, w1, b1, w2, b2,
                          hid_seed, out_seed, mesh: Mesh,
                          rate_hidden: float = 0.0,
                          rate_conn: float = 0.0, eps: float = 1e-6,
                          quant_fmt: Optional[str] = None,
                          quant_scales=None,
                          grad_fmt: Optional[str] = None):
    """The Megatron column-then-row fused-FFN sublayer on a tp mesh
    (module docstring).  h: GLOBAL (B, L, d); weights GLOBAL logical
    shapes, tp-sharded per the r11 rules (w1 on d_ff columns, w2 on
    d_ff rows — the shard_map in_specs consume those shards in place).
    Returns ``out`` — or ``(out, amax2)`` when quant_fmt is set, with
    amax2 the global (2,) [amax_f, amax_a] for the delayed-scaling
    history roll."""
    from faster_distributed_training_tpu.ops.dropout import (
        guard_index_ceiling, keep_factor_rows)
    from faster_distributed_training_tpu.ops.fused_ffn import (
        ffn_core_generalized, pack_scales)

    if h.ndim != 3:
        raise ValueError("fused_ffn_sublayer_tp expects (B, L, d) "
                         f"activations, got shape {h.shape}")
    B, L, d = h.shape
    d_ff = w1.shape[1]
    tp = tp_size(mesh)
    if not ffn_tp_serviceable(mesh, d_ff, L):
        raise ValueError(
            f"fused_ffn_sublayer_tp cannot serve d_ff={d_ff}, seq={L} on "
            f"mesh {dict(mesh.shape)} — build_model routes such shapes "
            f"to the warned flax fallback instead")
    if rate_hidden > 0.0 or rate_conn > 0.0:
        width = max(d_ff if rate_hidden > 0.0 else 0,
                    d if rate_conn > 0.0 else 0)
        guard_index_ceiling(B * L * width,
                            site="fused FFN dropout (tp-sharded)")
    batch = _batch_axes(mesh)
    lead = _lead(batch)
    sp = axis_size(mesh, "sp")
    seq_in = "sp" if sp > 1 else None
    seq_out = ("sp", "tp") if sp > 1 else "tp"
    b_shards = 1
    for a in batch:
        b_shards *= mesh.shape[a]
    b_loc = B // b_shards
    l_in = L // sp                # rows per shard entering the kernel
    l_out = l_in // tp            # rows per shard leaving (seq over tp)
    dff_loc = d_ff // tp

    rep = P(None)
    h_spec = P(lead, seq_in, None)
    out_spec = P(lead, seq_out, None)

    def per_shard(h_, lns_, lnb_, w1_, b1_, w2_, b2_, s1_, s2_, scales_):
        b0 = _batch_index(mesh, batch) * jnp.uint32(b_loc)
        t = lax.axis_index("tp").astype(jnp.uint32)
        s0_in = (lax.axis_index("sp").astype(jnp.uint32)
                 * jnp.uint32(l_in) if seq_in else jnp.uint32(0))
        c0 = t * jnp.uint32(dff_loc)
        qscales = (tuple(scales_[i] for i in range(4))
                   if quant_fmt is not None else None)
        partial, amax2 = ffn_core_generalized(
            h_, lns_, lnb_, w1_, b1_, w2_, b2_, s1_, s2_, b0, s0_in, c0,
            rate_hidden, 0.0, eps, l_in, l_in * sp, dff_glob=d_ff,
            quant_fmt=quant_fmt, quant_scales=qscales, grad_fmt=grad_fmt,
            grad_axes=(batch + (("sp",) if seq_in else ()) + ("tp",)
                       if quant_fmt is not None else ()),
            partial=True)
        # the ONE tp collective of the sublayer: recombine the
        # row-parallel GEMM2 products (fp32, psum-of-dequantized is
        # exact-in-structure since descale is linear)
        tot = lax.psum(partial, "tp")
        # b2 + connection dropout + residual on this shard's OWN
        # sequence slice — the output leaves sequence-sharded over tp
        # (psum+slice == reduce-scatter), and every mesh axis appears
        # in the out spec so check_vma=False transposes stay correct
        ti = lax.axis_index("tp")
        f2 = lax.dynamic_slice_in_dim(tot, ti * l_out, l_out, axis=1)
        x_sl = lax.dynamic_slice_in_dim(h_, ti * l_out, l_out, axis=1
                                        ).astype(jnp.float32)
        f2 = f2 + b2_.astype(jnp.float32)
        if rate_conn > 0.0:
            s0_out = s0_in + t * jnp.uint32(l_out)
            grows = ((b0 + lax.iota(jnp.uint32, b_loc))[:, None]
                     * jnp.uint32(L) + s0_out
                     + lax.iota(jnp.uint32, l_out)[None, :]).reshape(-1)
            keep = keep_factor_rows(s2_, grows, d, rate_conn)
            f2 = f2 * keep.reshape(b_loc, l_out, d)
        out = (x_sl + f2).astype(h.dtype)
        if quant_fmt is None:
            return out, amax2
        # per-tensor amaxes globalize here: amax_f is tp-replicated
        # already (every tp shard sees the same LN rows), amax_a is
        # column-sharded — pmax over every sharded axis so the (2,)
        # output is genuinely replicated (its out_spec says so).
        # stop_gradient first: amaxes feed the scale-history roll, not
        # the loss, and pmax has no differentiation rule
        amax2 = lax.stop_gradient(amax2)
        for ax in batch + (("sp",) if seq_in else ()):
            amax2 = lax.pmax(amax2, ax)
        amax2 = lax.pmax(amax2, "tp")
        return out, amax2

    out, amax2 = shard_map(
        per_shard, mesh=mesh,
        in_specs=(h_spec, rep, rep, P(None, "tp"), P("tp"),
                  P("tp", None), rep, P(), P(), P()),
        out_specs=(out_spec, P()),
        check_vma=False,
    )(h, ln_scale, ln_bias, w1, b1, w2, b2,
      jnp.asarray(hid_seed, jnp.uint32), jnp.asarray(out_seed, jnp.uint32),
      pack_scales(quant_scales if quant_fmt is not None else None))
    if quant_fmt is None:
        return out
    return out, amax2


# ---------------------------------------------------------------------------
# quant matmul: column/row-parallel per the site's TP rule
# ---------------------------------------------------------------------------

def quant_tp_serviceable(mesh: Optional[Mesh], tp_dim: Optional[int],
                         kernel_shape) -> bool:
    """True when a QuantDense site's GEMM can run per-shard: layer
    enabled, the mesh has tp > 1, the site declared its Megatron role
    (tp_dim), and tp divides the sharded kernel dim."""
    tp = tp_size(mesh)
    if not (enabled() and tp > 1 and tp_dim is not None):
        return False
    if tp_dim >= len(kernel_shape):
        return False
    return int(kernel_shape[tp_dim]) % tp == 0


def quant_tp_routed(mesh: Optional[Mesh], tp_dim: Optional[int],
                    kernel_shape, use_pallas) -> bool:
    """The QuantDense routing predicate: shard_map when the site is
    serviceable AND the policy didn't force the registered fallback
    (use_pallas=False — the FDT_KERNEL_SHARD=0 / non-dividing-shape
    path cli.build_model sets)."""
    return (use_pallas is not False
            and quant_tp_serviceable(mesh, tp_dim, kernel_shape))


def quant_dense_sharded(x2d: jax.Array, kernel: jax.Array,
                        sx: jax.Array, sw: jax.Array, fmt: str,
                        mesh: Mesh, tp_dim: int,
                        grad_fmt: Optional[str] = None) -> jax.Array:
    """One QuantDense GEMM per-shard over tp.  x2d: [M, K] (rows
    batch-sharded over the data axes); kernel: (K, *feats) with feats
    dim ``tp_dim`` tp-sharded (column-parallel) or ``tp_dim == 0``
    (K tp-sharded, row-parallel — x2d's columns arrive tp-sharded the
    way the model's activation annotations lay them out, and ONE psum
    recombines the partial products).  Scales are GLOBAL per-tensor
    scalars (replicated).  Returns the flat [M, prod(feats)] result."""
    from faster_distributed_training_tpu.ops.quant import quant_dot

    tp = tp_size(mesh)
    batch = _batch_axes(mesh)
    lead = _lead(batch)
    ndim = kernel.ndim
    feats = kernel.shape[1:]
    row = tp_dim == 0
    w_spec = P(*[("tp" if i == tp_dim else None) for i in range(ndim)])
    if row:
        x_spec = P(lead, "tp")
        out_spec = P(lead, *([None] * len(feats)))
        g_axes = batch
    else:
        x_spec = P(lead, None)
        out_spec = P(lead, *[("tp" if i == tp_dim else None)
                             for i in range(1, ndim)])
        g_axes = batch + ("tp",)

    def per_shard(x_, w_, scales_):
        w2d = w_.reshape(w_.shape[0], -1)
        # (1,)-shaped scale slices, NOT scalars: rank-0 custom_vjp
        # residuals break this jax's shard_map linearization (the
        # inferred residual out-names can't attach to a rank-0 aval)
        y = quant_dot(x_, w2d, scales_[0:1], scales_[1:2], fmt,
                      grad_fmt=grad_fmt, grad_axes=g_axes)
        if row:
            # row-parallel: partial products over the local K rows —
            # the site's single tp collective (descale is linear, so
            # psum-of-dequantized equals dequantize-of-psum up to fp32
            # summation order)
            y = lax.psum(y, "tp")
        return y.reshape((x_.shape[0],) + w_.shape[1:])

    # scales travel as ONE (2,) vector: rank-0 replicated operands trip
    # this jax's shard_map transpose spec check on the cotangent side
    scales = jnp.stack([jnp.asarray(sx, jnp.float32).reshape(()),
                        jnp.asarray(sw, jnp.float32).reshape(())])
    out = shard_map(per_shard, mesh=mesh,
                    in_specs=(x_spec, w_spec, P(None)),
                    out_specs=out_spec,
                    check_vma=False)(x2d, kernel, scales)
    return out.reshape(x2d.shape[0], int(np.prod(feats)))
