"""Placing the training state and batches onto a mesh.

This is where the reference's three distribution strategies become
sharding decisions (SURVEY.md §2 checklist):

  * DP/DDP   — batch sharded over data axes, state replicated; XLA
               compiles the gradient psum (DDP's bucketed all-reduce).
  * FSDP     — additionally shard every large param/optimizer leaf over
               the ``fsdp`` axis (ZeRO-3); XLA lowers the gradient psum
               to reduce_scatter + all_gather exactly like FSDP's
               C++ hooks (transformer_test.py:387-392).
  * ZeRO-1   — params replicated, only optimizer state sharded over a
               data axis (the commented ZeroRedundancyOptimizer wrap,
               transformer_test.py:4,221-222).
  * offload  — params/opt state pinned to host memory
               (``memory_kind='pinned_host'``), the CPUOffload analog
               (transformer_test.py:46-48).

Batches are assembled from per-host shards with
``jax.make_array_from_process_local_data`` — the DistributedSampler
equivalent at the array level."""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from faster_distributed_training_tpu.config import TrainConfig
from faster_distributed_training_tpu.parallel.sharding import (
    batch_spec, fsdp_partition_params)


def data_axes(mesh: Mesh):
    return tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)


def dp_size(mesh: Mesh) -> int:
    n = 1
    for a in data_axes(mesh):
        n *= mesh.shape[a]
    return max(n, 1)


def train_state_shardings(state, mesh: Mesh, cfg: TrainConfig,
                          pipeline=None):
    """A TrainState-shaped pytree of NamedSharding.

    Model params additionally get tensor-parallel specs wherever a
    sharding._TP_RULES name rule matches, when the mesh has a tp axis of
    size > 1 (TP wins over the FSDP spec on matched tensors).

    With ``pipeline`` (a parallel.pipeline.PipelineSpec) and
    cfg.pp_residency, stage-owned leaves additionally land on their pp
    coordinate (sharding.pp_residency_specs — ISSUE 19): each stage's
    chips hold 1/pp of the layer params, composing with the tp overlay
    (the pp entry only takes a FREE axis).  The ZeRO opt-state overlay
    then runs over tp when present, else over pp — and its param_mirror
    rule inherits the pp'd param specs either way, so a dp x tp x pp
    mesh multiplies both reductions."""
    if cfg.fsdp and "fsdp" in mesh.axis_names:
        specs = fsdp_partition_params(state, mesh, axis="fsdp")
    elif cfg.zero1:
        # ZeRO-1 (ZeroRedundancyOptimizer analog, transformer_test.py:4,
        # 221-222): params stay replicated, only the optimizer state —
        # momentum buffers, Fisher factors, MADGRAD accumulators — is
        # sharded over a data axis.  XLA inserts the gather at tx.update.
        ax = next((a for a in ("fsdp", "dp") if a in mesh.axis_names
                   and mesh.shape[a] > 1), None)
        specs = jax.tree.map(lambda _: P(), state)
        if ax is not None:
            specs = specs.replace(
                opt_state=fsdp_partition_params(state.opt_state, mesh,
                                                axis=ax))
    else:
        specs = jax.tree.map(lambda _: P(), state)
    tp_live = "tp" in mesh.axis_names and mesh.shape["tp"] > 1
    pp_live = (pipeline is not None
               and getattr(cfg, "pp_residency", True)
               and "pp" in mesh.axis_names and mesh.shape["pp"] > 1)
    if tp_live:
        from faster_distributed_training_tpu.parallel.sharding import (
            param_path_name, tensor_parallel_rules)

        def overlay(path, spec):
            tp_spec = tensor_parallel_rules(param_path_name(path))
            return tp_spec if tp_spec != P() else spec

        model_specs = jax.tree_util.tree_map_with_path(
            overlay, specs.params["model"],
            is_leaf=lambda x: isinstance(x, P))
        specs = specs.replace(params={**specs.params, "model": model_specs})
    if pp_live:
        # per-stage residency (ISSUE 19): runs AFTER the tp overlay so
        # tp-occupied axes are off-limits; the fsdp/zero1 base specs'
        # axes are respected the same way
        from faster_distributed_training_tpu.parallel.sharding import (
            pp_residency_specs)
        model_specs = pp_residency_specs(
            state.params["model"], specs.params["model"], pipeline, mesh)
        specs = specs.replace(params={**specs.params, "model": model_specs})
        # the opt-state mirrors of stage-owned params must follow them
        # onto their pp coordinate even with --no_zero_opt — otherwise
        # the (2-3x larger) optimizer fraction silently stays replicated
        from faster_distributed_training_tpu.parallel.sharding import (
            mirror_param_specs)
        mspecs = mirror_param_specs(
            state.opt_state, state.params, specs.params)
        specs = specs.replace(opt_state=jax.tree.map(
            lambda m, base: m if m != P() else base,
            mspecs, specs.opt_state,
            is_leaf=lambda x: isinstance(x, P)))
    zero_axis = "tp" if tp_live else ("pp" if pp_live else None)
    if zero_axis is not None and getattr(cfg, "zero_opt", True):
        # ZeRO over the model axis (ISSUE 16; extended to pp-only
        # meshes by ISSUE 19): the FULL optimizer state joins the
        # overlay — shape-aware rules, because NGD factor states don't
        # mirror param shapes.  param_mirror leaves inherit the
        # (tp+pp-overlaid) param specs, so stage-owned mirrors land on
        # their pp coordinate even when the roll axis is tp.  The zero
        # spec wins over the base fsdp/zero1 spec wherever a rule
        # matched.
        from faster_distributed_training_tpu.parallel.sharding import (
            zero_opt_state_specs)
        zspecs = zero_opt_state_specs(
            state.opt_state, state.params, specs.params, mesh,
            axis=zero_axis)
        merged = jax.tree.map(
            lambda z, base: z if z != P() else base,
            zspecs, specs.opt_state,
            is_leaf=lambda x: isinstance(x, P))
        specs = specs.replace(opt_state=merged)
    shardings = jax.tree.map(lambda spec: NamedSharding(mesh, spec), specs,
                             is_leaf=lambda x: isinstance(x, P))
    offloadable = _supports_memory_kind(mesh)
    pin = lambda s: NamedSharding(mesh, s.spec,                # noqa: E731
                                  memory_kind="pinned_host")
    if cfg.host_offload and offloadable:
        # CPUOffload(offload_params=True) analog: only the big leaves —
        # params and optimizer state — live in host memory.
        shardings = shardings.replace(
            params=jax.tree.map(pin, shardings.params),
            opt_state=jax.tree.map(pin, shardings.opt_state))
    elif getattr(cfg, "offload_opt_state", False) and offloadable:
        # The narrower host tier (--offload_opt_state): only the big,
        # cold opt-state slots park in host memory; params and the small
        # hot counters stay resident.  Selection is sharding.offload_opt_leaf
        # (size floor) so telemetry can attribute the tier per leaf.
        from faster_distributed_training_tpu.parallel.sharding import (
            offload_opt_leaf)
        shardings = shardings.replace(
            opt_state=jax.tree.map(
                lambda x, s: pin(s) if offload_opt_leaf(np.shape(x)) else s,
                state.opt_state, shardings.opt_state))
    return shardings


def _supports_memory_kind(mesh: Mesh) -> bool:
    try:
        dev = np.ravel(mesh.devices)[0]
        return "pinned_host" in {m.kind for m in dev.addressable_memories()}
    except Exception:
        return False


def place_on_shardings(state, shardings):
    """Re-place a (possibly host-numpy, e.g. checkpoint-restored) state
    onto an explicit sharding tree — identity when `shardings` is None.
    The ONE re-placement policy every restore path shares (run_training
    resume/attempt, the auto-recover rollback), so a 2D mesh's
    tp-sharded params always land back on their shards instead of
    wherever jit's default placement puts uncommitted arrays."""
    if shardings is None:
        return state
    return jax.tree.map(jax.device_put, state, shardings)


def shard_train_state(state, mesh: Mesh, cfg: TrainConfig, shardings=None):
    """device_put the full state per the DP/FSDP/offload policy.  Offload
    applies only to params/opt_state (the big leaves).  Pass `shardings`
    (from train_state_shardings) to reuse an already-computed tree."""
    if shardings is None:
        shardings = train_state_shardings(state, mesh, cfg)
    return jax.tree.map(jax.device_put, state, shardings)


def make_put_batch(mesh: Optional[Mesh],
                   augment_fn: Optional[Callable] = None,
                   stacked: bool = False
                   ) -> Callable[[Dict[str, Any]], Dict[str, Any]]:
    """Returns put_batch: host numpy dict -> global device arrays with the
    batch dim sharded over the data axes.  Each process contributes its
    local shard (multi-host DistributedSampler semantics).

    stacked=True stages K-step fused-dispatch super-batches: every leaf
    carries a leading K (steps-per-dispatch) axis that stays UNsharded —
    the lax.scan consumes it — and the batch axis below it shards over
    the data axes as usual."""
    if mesh is None:
        if augment_fn is None:
            return lambda b: b
        return lambda b: augment_fn(b)

    def put(batch: Dict[str, Any]) -> Dict[str, Any]:
        out = {}
        for k, v in batch.items():
            v = np.asarray(v)
            if stacked:
                spec = (P(None, *batch_spec(mesh)) if v.ndim >= 2
                        else P())
            else:
                spec = batch_spec(mesh) if v.ndim >= 1 else P()
            sharding = NamedSharding(mesh, spec)
            out[k] = jax.make_array_from_process_local_data(sharding, v)
        if augment_fn is not None:
            out = augment_fn(out)
        return out

    return put
