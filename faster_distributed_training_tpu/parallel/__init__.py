"""Distributed execution: device meshes, sharding rules, collectives.

Replaces the reference's NCCL/DDP/FSDP/torchrun stack (utils.py:13-51,
resnet50_test.py:716, transformer_test.py:387-392, run_distributed.sh) with
XLA collectives compiled over ICI/DCN: a `jax.sharding.Mesh` plus
NamedSharding partition specs; gradient synchronization is inserted by the
compiler from the shardings rather than hooked into backward like DDP.
"""

from faster_distributed_training_tpu.parallel.mesh import (  # noqa: F401
    AXIS_ALIASES,
    MeshSpec,
    axis_size,
    canonical_axes,
    canonical_axis,
    make_mesh,
    initialize_distributed,
    local_batch_slice,
    seq_parallel_axis,
    sp_size,
    tp_size,
)
from faster_distributed_training_tpu.parallel.sharding import (  # noqa: F401
    batch_spec,
    replicated,
    fsdp_partition_params,
    mesh_data_axes,
    shard_activation,
    shard_pytree,
    tensor_parallel_rules,
)
from faster_distributed_training_tpu.parallel.placement import (  # noqa: F401
    dp_size,
    make_put_batch,
    shard_train_state,
    train_state_shardings,
)
from faster_distributed_training_tpu.parallel.collectives import (  # noqa: F401
    all_reduce_metrics,
    all_sum_across_processes,
)
