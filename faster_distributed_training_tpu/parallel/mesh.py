"""Device-mesh bootstrap.

The reference boots NCCL process groups three ways (utils.py:13-30:
env:// rendezvous, torchrun-provided rank, shared-file rendezvous).  The TPU
equivalent is `jax.distributed.initialize(coordinator, num_processes,
process_id)` once per host, then ONE `Mesh` over all global devices; data /
fully-sharded / tensor / sequence parallelism are just axes of that mesh.

Axis naming convention used framework-wide:
  "dp"   — data parallel (batch sharded, grads psum'd by XLA)
  "fsdp" — fully-sharded data parallel (batch AND params/opt-state sharded;
           ZeRO-3; XLA turns grad psum into reduce_scatter + all_gather)
  "tp"   — tensor parallel (attention heads / MLP hidden sharded)
  "sp"   — sequence/context parallel (ring attention, ops/ring_attention.py)
  "pp"   — pipeline parallel (encoder LAYERS staged across slices; the
           stage-boundary activation rotation is the only per-step
           collective, so pp tolerates the slowest links and is the
           PREFERRED axis to span DCN on multi-slice pods —
           parallel/pipeline.py.  Since r23 pp is also a RESIDENCY
           axis: stage-owned params and optimizer state are physically
           sharded over pp (parallel/sharding.py pp-residency rules),
           so per-chip HBM for those tiers scales ~1/S with pipeline
           depth and pp composes multiplicatively with tp/ZeRO.)

AXIS_ALIASES is the ONE canonical alias table (r11 satellite): every
surface that names a mesh axis — ``--mesh`` parsing, ``resolve_attention``
auto-routing, ``apply_tp_rules``, the shard_map fallbacks in
``build_model`` — goes through ``canonical_axis`` so ``--mesh
dp=4,model=2`` and ``--mesh dp=4,tp=2`` are the same mesh and no layer
can disagree about what the model axis is called.
"""

from __future__ import annotations

import dataclasses
import os
import warnings
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

# canonical-name ← accepted spellings.  Unknown names pass through
# unchanged (exotic axes stay usable), but the four canonical roles each
# accept the common alternative spellings, so the TP rules (which match
# the LITERAL string "tp") and the sequence-parallel ops (literal "sp")
# always see the canonical name regardless of what the CLI was given.
AXIS_ALIASES = {
    "dp": "dp", "data": "dp", "batch": "dp",
    "fsdp": "fsdp", "zero": "fsdp", "zero3": "fsdp",
    "tp": "tp", "model": "tp", "mp": "tp", "tensor": "tp",
    "sp": "sp", "seq": "sp", "sequence": "sp", "context": "sp",
    "pp": "pp", "pipe": "pp", "pipeline": "pp", "stage": "pp",
}

# ICI speed rank for the auto device-assignment policy: higher = placed
# on a faster (more-minor) mesh axis.  Model/sequence axes carry the
# per-layer collectives (psum at every FFN/projection boundary, the
# ring's per-step ppermute), data axes one grad psum per step — so tp
# gets the fastest links, dp the slowest.  pp ranks BELOW dp: a pipeline
# stage boundary moves one [microbatch, L, d_model] activation per tick
# point-to-point (collective-permute), the cheapest per-step traffic of
# any axis, so pp is placed outermost and is the preferred axis to span
# DCN between slices on multi-slice pods (_ici_device_mesh).
_AXIS_SPEED = {"pp": -1, "dp": 0, "fsdp": 1, "sp": 2, "tp": 3}


def canonical_axis(name: str) -> str:
    """Canonical spelling of a mesh-axis name (AXIS_ALIASES)."""
    return AXIS_ALIASES.get(str(name).strip().lower(), str(name).strip())


def canonical_axes(axes: Sequence[str]) -> Tuple[str, ...]:
    out = tuple(canonical_axis(a) for a in axes)
    if len(set(out)) != len(out):
        raise ValueError(f"mesh axes {tuple(axes)} collapse to duplicate "
                         f"canonical names {out} (see AXIS_ALIASES)")
    return out


def axis_size(mesh: Optional[Mesh], name: str) -> int:
    """Size of canonical axis `name` in `mesh` (1 when absent/None)."""
    if mesh is None:
        return 1
    name = canonical_axis(name)
    return int(mesh.shape[name]) if name in mesh.axis_names else 1


def tp_size(mesh: Optional[Mesh]) -> int:
    return axis_size(mesh, "tp")


def sp_size(mesh: Optional[Mesh]) -> int:
    return axis_size(mesh, "sp")


def pp_size(mesh: Optional[Mesh]) -> int:
    return axis_size(mesh, "pp")


def seq_parallel_axis(mesh: Optional[Mesh]) -> Tuple[Optional[str], int]:
    """(axis_name, size) the sequence-parallel ops (ring/ulysses) and the
    sequence-sharded activation regions should use: a dedicated "sp"
    axis when present at size > 1, else the "tp" axis (Megatron-style
    sequence parallelism rides the tensor-parallel group), else
    (None, 1).  The ONE policy resolve_attention, build_model and the
    model's activation annotations all share."""
    for name in ("sp", "tp"):
        n = axis_size(mesh, name)
        if n > 1:
            return name, n
    return None, 1


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    axes: Tuple[str, ...]
    shape: Tuple[int, ...]

    @property
    def size(self) -> int:
        return int(np.prod(self.shape)) if self.shape else 1


def initialize_distributed(coordinator: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Multi-host bootstrap; replaces MASTER_ADDR/MASTER_PORT + init_process_group.

    No-op for single-process runs.  Arguments default from the environment
    (FDT_COORDINATOR, FDT_NUM_PROCESSES, FDT_PROCESS_ID), mirroring how
    torchrun feeds rank/world-size via env vars (utils.py:20-23) — but with
    no fixed hard-coded port (reference pins 12355, utils.py:15).
    """
    coordinator = coordinator or os.environ.get("FDT_COORDINATOR")
    if num_processes is None:
        num_processes = int(os.environ.get("FDT_NUM_PROCESSES", "1"))
    if process_id is None:
        process_id = int(os.environ.get("FDT_PROCESS_ID", "0"))
    if num_processes > 1:
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=num_processes,
                                   process_id=process_id)


def _ici_device_mesh(shape: Tuple[int, ...],
                     axes: Tuple[str, ...]) -> Optional[np.ndarray]:
    """ICI-aware device assignment for a TPU mesh (SNIPPETS [1]).

    `mesh_utils.create_device_mesh` assigns later mesh dims to
    physically nearer chips, so the axes are permuted SLOWEST-first by
    `_AXIS_SPEED` (dp outermost, tp innermost = fastest links) before
    construction and transposed back to the caller's order after — the
    "tp on the fastest axis" auto policy.  Multi-process pods factor the
    slowest data axis over DCN via `create_hybrid_device_mesh`.  Returns
    None when the topology tools can't serve the request (caller falls
    back to the plain reshape)."""
    try:
        from jax.experimental import mesh_utils
    except ImportError:        # pragma: no cover - jax always ships it
        return None
    perm = sorted(range(len(axes)),
                  key=lambda i: (_AXIS_SPEED.get(axes[i], -1), i))
    pshape = tuple(shape[i] for i in perm)
    try:
        pc = jax.process_count()
        if pc > 1:
            # factor the process count out of the slowest eligible axis
            # that divides it — that axis spans slices over DCN,
            # everything else stays inside a slice's ICI.  Eligible:
            # pp FIRST (it sorts outermost at speed -1 — a stage
            # boundary moves one point-to-point activation per tick, the
            # cheapest traffic to put on the slow links), then dp/fsdp
            # (one grad reduction per step).  tp/sp stay ineligible:
            # letting them span DCN would put the per-layer
            # model-parallel collectives on the slowest links, inverting
            # the _AXIS_SPEED policy — a mesh whose pp/data axes can't
            # absorb the process count falls back to the plain reshape.
            paxes = [axes[i] for i in perm]
            dcn = [1] * len(pshape)
            for j, d in enumerate(pshape):
                if (paxes[j] in ("pp", "dp", "fsdp")
                        and d % pc == 0 and d >= pc):
                    dcn[j] = pc
                    break
            else:
                return None
            ici = list(pshape)
            ici[j] //= pc
            dev = mesh_utils.create_hybrid_device_mesh(
                tuple(ici), tuple(dcn))
        else:
            dev = mesh_utils.create_device_mesh(pshape)
    except Exception:
        return None
    return np.transpose(dev, np.argsort(perm))


def make_mesh(axes: Sequence[str] = ("dp",),
              shape: Sequence[int] = (),
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a Mesh. Empty `shape` auto-sizes: one unsized axis absorbs all devices.

    Axis names are canonicalized through AXIS_ALIASES (``--mesh
    dp=4,model=2`` == ``dp=4,tp=2``).  On TPU with default devices the
    device assignment is ICI-aware (`_ici_device_mesh`: tp on the
    fastest links, hybrid ICI×DCN on pods — SNIPPETS [1]); everywhere
    else (CPU simulation, explicit device lists) it is the plain
    row-major reshape, whose LAST axis is still the fastest-varying —
    so ``dp=4,tp=2`` groups tp pairs on adjacent devices either way.

    Single-process only: a shape smaller than the visible device count
    uses the FIRST prod(shape) devices — the CUDA_VISIBLE_DEVICES-
    narrowing analog (run_distributed.sh:2), e.g. `--mesh dp=1` on an
    8-chip host.  Multi-host runs keep the exact-count requirement: a
    mesh built from a subset would exclude some processes' addressable
    devices and fail far later inside batch assembly.

    Examples:
      make_mesh()                          -> all devices on "dp"
      make_mesh(("dp","tp"), (4, 2))       -> 4x2 (data, model) mesh
      make_mesh(("fsdp",))                 -> all devices fully-sharded
    """
    explicit_devices = devices is not None
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    axes = canonical_axes(axes)
    if not shape:
        shape = (n,) + (1,) * (len(axes) - 1)
    shape = tuple(shape)
    if len(shape) != len(axes):
        raise ValueError(f"mesh axes {axes} vs shape {shape} rank mismatch")
    want = int(np.prod(shape))
    if want > n or (want < n and jax.process_count() > 1):
        raise ValueError(f"mesh shape {shape} needs {want} devices, "
                         f"have {n}"
                         + (" across all hosts — per-host narrowing is "
                            "not supported in multi-process runs"
                            if jax.process_count() > 1 else ""))
    if want < n:
        warnings.warn(f"mesh shape {shape} uses {want} of {n} visible "
                      f"devices; the remaining {n - want} idle",
                      stacklevel=2)
    dev_array = None
    if (not explicit_devices and want == n
            and devices[0].platform == "tpu"):
        dev_array = _ici_device_mesh(shape, axes)
    if dev_array is None:
        dev_array = np.asarray(devices[:want]).reshape(shape)
    return Mesh(dev_array, axes)


def local_batch_slice(global_batch: int, mesh: Mesh) -> Tuple[int, int]:
    """(per-host batch, host offset) for building per-host sharded loaders.

    Replaces torch's DistributedSampler (resnet50_test.py:331): each host
    loads only its slice of the global batch; `jax.make_array_from_process_local_data`
    assembles the global array.
    """
    n_proc = jax.process_count()
    if global_batch % n_proc:
        raise ValueError(f"global batch {global_batch} not divisible by "
                         f"{n_proc} processes")
    per = global_batch // n_proc
    return per, per * jax.process_index()
