"""Device-mesh bootstrap.

The reference boots NCCL process groups three ways (utils.py:13-30:
env:// rendezvous, torchrun-provided rank, shared-file rendezvous).  The TPU
equivalent is `jax.distributed.initialize(coordinator, num_processes,
process_id)` once per host, then ONE `Mesh` over all global devices; data /
fully-sharded / tensor / sequence parallelism are just axes of that mesh.

Axis naming convention used framework-wide:
  "dp"   — data parallel (batch sharded, grads psum'd by XLA)
  "fsdp" — fully-sharded data parallel (batch AND params/opt-state sharded;
           ZeRO-3; XLA turns grad psum into reduce_scatter + all_gather)
  "tp"   — tensor parallel (attention heads / MLP hidden sharded)
  "sp"   — sequence/context parallel (ring attention, ops/ring_attention.py)
"""

from __future__ import annotations

import dataclasses
import os
import warnings
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    axes: Tuple[str, ...]
    shape: Tuple[int, ...]

    @property
    def size(self) -> int:
        return int(np.prod(self.shape)) if self.shape else 1


def initialize_distributed(coordinator: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Multi-host bootstrap; replaces MASTER_ADDR/MASTER_PORT + init_process_group.

    No-op for single-process runs.  Arguments default from the environment
    (FDT_COORDINATOR, FDT_NUM_PROCESSES, FDT_PROCESS_ID), mirroring how
    torchrun feeds rank/world-size via env vars (utils.py:20-23) — but with
    no fixed hard-coded port (reference pins 12355, utils.py:15).
    """
    coordinator = coordinator or os.environ.get("FDT_COORDINATOR")
    if num_processes is None:
        num_processes = int(os.environ.get("FDT_NUM_PROCESSES", "1"))
    if process_id is None:
        process_id = int(os.environ.get("FDT_PROCESS_ID", "0"))
    if num_processes > 1:
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=num_processes,
                                   process_id=process_id)


def make_mesh(axes: Sequence[str] = ("dp",),
              shape: Sequence[int] = (),
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a Mesh. Empty `shape` auto-sizes: one unsized axis absorbs all devices.

    Single-process only: a shape smaller than the visible device count
    uses the FIRST prod(shape) devices — the CUDA_VISIBLE_DEVICES-
    narrowing analog (run_distributed.sh:2), e.g. `--mesh dp=1` on an
    8-chip host.  Multi-host runs keep the exact-count requirement: a
    mesh built from a subset would exclude some processes' addressable
    devices and fail far later inside batch assembly.

    Examples:
      make_mesh()                          -> all devices on "dp"
      make_mesh(("dp","tp"), (2, 4))       -> 2x4 mesh
      make_mesh(("fsdp",))                 -> all devices fully-sharded
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    axes = tuple(axes)
    if not shape:
        shape = (n,) + (1,) * (len(axes) - 1)
    shape = tuple(shape)
    if len(shape) != len(axes):
        raise ValueError(f"mesh axes {axes} vs shape {shape} rank mismatch")
    want = int(np.prod(shape))
    if want > n or (want < n and jax.process_count() > 1):
        raise ValueError(f"mesh shape {shape} needs {want} devices, "
                         f"have {n}"
                         + (" across all hosts — per-host narrowing is "
                            "not supported in multi-process runs"
                            if jax.process_count() > 1 else ""))
    if want < n:
        warnings.warn(f"mesh shape {shape} uses {want} of {n} visible "
                      f"devices; the remaining {n - want} idle",
                      stacklevel=2)
    dev_array = np.asarray(devices[:want]).reshape(shape)
    return Mesh(dev_array, axes)


def local_batch_slice(global_batch: int, mesh: Mesh) -> Tuple[int, int]:
    """(per-host batch, host offset) for building per-host sharded loaders.

    Replaces torch's DistributedSampler (resnet50_test.py:331): each host
    loads only its slice of the global batch; `jax.make_array_from_process_local_data`
    assembles the global array.
    """
    n_proc = jax.process_count()
    if global_batch % n_proc:
        raise ValueError(f"global batch {global_batch} not divisible by "
                         f"{n_proc} processes")
    per = global_batch // n_proc
    return per, per * jax.process_index()
