"""Pipeline parallelism over the pp mesh axis: the ONE rule table.

The third parallelism axis (after dp/fsdp batch sharding and tp/sp
tensor/sequence sharding): the transformer's L encoder layers are
partitioned into ``pp`` contiguous STAGES, the batch is split into M
MICROBATCHES, and the stages process microbatches in a rotating
schedule — stage s works on microbatch ``t - s`` at tick ``t``, so the
activation leaving stage s-1 at tick t-1 is exactly what stage s
consumes at tick t.  The stage-boundary hop is the only per-tick
communication (a [microbatch, L, d_model] collective-permute over pp),
which is why pp is the axis that spans DCN between slices
(parallel/mesh.py::_AXIS_SPEED — pp ranks slowest, placed outermost,
preferred by the hybrid DCN factoring).

Every routing decision the pipeline makes — stage assignment,
microbatch count, collective placement, bubble accounting — is decided
HERE and dumped as one inspectable table (``pipeline_rules``) into the
run's ``manifest.json`` beside the r15 compile table (cli.run_training),
in the spirit of SNIPPETS [2]'s ``compile_step_with_plan``: no scattered
call sites, one place to read what the pipeline did.

Execution model (models/transformer.py, gated on a ``pp_spec`` call
argument so ``pp=1`` traces stay byte-identical to r21):

  * the [B, L, d] encoder input is reshaped to M microbatches of B/M;
  * a stage buffer [S, B/M, L, d], sharded ``P("pp", data_axes, ...)``
    over dim 0, holds each stage's current input;
  * each of T = M + S - 1 ticks rotates the buffer down one stage
    (the collective-permute), inserts the next microbatch at stage 0,
    and applies every stage's layer block to its slot;
  * the last stage's outputs are collected in microbatch order and
    reassembled into [B, L, d] — bitwise the same VALUES as running the
    microbatches sequentially, so the pp=2 ≡ pp=1 comparison sits in
    the documented cross-program-family allclose class (batch-dim
    tiling + microbatch reduction order), while within a pp program
    family everything stays bitwise (the r8 scan-rounding precedent).
    The parity contract holds with DROPOUT DISABLED only: under the
    staged encoder each layer is invoked once per tick (bubble slots
    included), so Flax's make_rng fold count differs from the unstaged
    forward and bubble slots consume dropout draws — still valid
    dropout (an independent mask stream), but a different stream than
    pp=1, so pp=2 vs pp=1 is not comparable beyond distribution.
    build_pipeline_spec warns when pp>1 meets a live dropout impl.

The schedule is 1F1B in the combined fwd+bwd sense: jax.grad
differentiates through the rotation, so the backward pipeline replays
the ticks in reverse — stage s's backward for microbatch m runs as soon
as stage s+1's has (the reversed rotation), one-forward-one-backward
per stage per tick with no GPipe-style full-forward buffer beyond the
[S, ...] stage buffer itself.  ``schedule="interleaved"`` (the
Megatron v=2 assignment) deals round-robin layer chunks to the stages
and the tick loop traverses the resulting VIRTUAL stages in depth
order: the buffer grows to V = 2S slots, slot j applies depth-chunk j
(``virtual_chunks`` is the contract), and physical stage j % S hosts
slot j — so every microbatch still applies layer 0..L-1 in order and
the pp=2 ≡ pp=1 parity class is schedule-independent.  In this
rotate-all formulation each tick computes ALL of a stage's chunks, so
interleaving buys placement fidelity (two non-adjacent depth regions
per stage, twice the boundary hops), NOT the Megatron bubble win:
fill/drain lengthens to V-1 ticks and the rule table records the
honest (V-1)/(M+V-1).  The chunk-granularity staggered schedule that
realizes the v× bubble reduction is a named live-TPU ROADMAP
follow-on.

Fill/drain ticks (the bubble) compute on recycled microbatch data
(never zeros — an all-zero constant block invites XLA constant-folding
the slot's backward into 0*inf NaN constants at x64): the garbage
outputs are never selected into the loss, so their cotangents are zero
and the extra work is exactly the analytic bubble fraction
(V - 1) / (M + V - 1) over the V virtual-stage slots (V = S for 1f1b)
— the executed program genuinely pays the bubble it reports
(``pipeline_bubble_pct``).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

from faster_distributed_training_tpu.parallel.mesh import pp_size

SCHEDULES = ("1f1b", "interleaved")


@dataclasses.dataclass(frozen=True)
class PipelineSpec:
    """Static description of one pipelined encoder — everything the
    traced program and the rule table need.  ``mesh`` rides along (not
    part of equality-relevant identity: specs are rebuilt per Trainer,
    never hashed into jit keys — the pp program is selected by python
    branching before trace)."""
    n_layers: int
    n_stages: int
    n_microbatches: int
    stage_layers: Tuple[Tuple[int, ...], ...]   # layer indices per stage
    schedule: str = "1f1b"
    mesh: Optional[object] = None

    @property
    def n_virtual(self) -> int:
        """Virtual-stage count V: the number of depth-ordered chunks
        the tick loop traverses (== n_stages for contiguous 1F1B
        assignment, 2 * n_stages under v=2 interleaving)."""
        return len(virtual_chunks(self))

    @property
    def n_ticks(self) -> int:
        return self.n_microbatches + self.n_virtual - 1

    @property
    def bubble_pct(self) -> float:
        return 100.0 * bubble_fraction(self.n_virtual,
                                       self.n_microbatches)


def partition_stages(n_layers: int, n_stages: int,
                     schedule: str = "1f1b"
                     ) -> Tuple[Tuple[int, ...], ...]:
    """Layer indices per stage.

    "1f1b": contiguous balanced blocks — earlier stages take the extra
    layer when n_layers % n_stages != 0 (they also host the un-staged
    embedding, but the tie-break is mostly cosmetic: the schedule's
    critical path is the max per-stage block either way).

    "interleaved": layers dealt round-robin in contiguous CHUNKS of
    L / (S * v) with v=2 virtual stages per physical stage (the
    Megatron v-interleave ASSIGNMENT) — each stage touches two
    non-adjacent regions of the depth at the price of twice the
    boundary hops.  Requires L % (2S) == 0 so the V = 2S depth-ordered
    chunks are equal-sized and slot j lands on stage j % S exactly
    (the placement rule constrain_stage_buffer encodes); falls back to
    the contiguous split otherwise.  Execution stays depth-ordered
    either way: the tick loop runs virtual_chunks, never a stage's
    concatenated layer list."""
    if not 1 <= n_stages <= n_layers:
        raise ValueError(f"cannot split {n_layers} layers into "
                         f"{n_stages} pipeline stages")
    if schedule not in SCHEDULES:
        raise ValueError(f"unknown pipeline schedule {schedule!r} "
                         f"(one of {SCHEDULES})")
    if schedule == "interleaved" and n_layers % (2 * n_stages) == 0:
        v = 2
        chunk = n_layers // (n_stages * v)
        chunks = [tuple(range(i, i + chunk))
                  for i in range(0, n_layers, chunk)]
        out = [[] for _ in range(n_stages)]
        for idx, ch in enumerate(chunks):
            out[idx % n_stages].extend(ch)
        return tuple(tuple(s) for s in out)
    base, extra = divmod(n_layers, n_stages)
    bounds, lo = [], 0
    for s in range(n_stages):
        hi = lo + base + (1 if s < extra else 0)
        bounds.append(tuple(range(lo, hi)))
        lo = hi
    return tuple(bounds)


def virtual_chunks(spec: PipelineSpec) -> Tuple[Tuple[int, ...], ...]:
    """The depth-ordered virtual-stage chunks the tick loop executes:
    each chunk is a maximal run of consecutive layers from one stage's
    assignment, and the chunks are ordered by first layer — so slot j
    applying chunk j walks every microbatch through layer 0..L-1 in
    depth order REGARDLESS of schedule (the property the pp ≡ pp=1
    parity pins).  Contiguous 1F1B assignment yields one run per stage
    (chunks == stage_layers, V == S); v=2 interleaving yields V == 2S
    equal runs with chunk j owned by stage j % S — the mapping
    constrain_stage_buffer's [v, S] placement view relies on."""
    runs = []
    for layers in spec.stage_layers:
        start = 0
        for k in range(1, len(layers) + 1):
            if k == len(layers) or layers[k] != layers[k - 1] + 1:
                runs.append(tuple(layers[start:k]))
                start = k
    runs.sort(key=lambda r: r[0])
    flat = [i for r in runs for i in r]
    if flat != sorted(flat):
        raise ValueError(f"stage assignment {spec.stage_layers} has "
                         f"overlapping depth runs — no depth-ordered "
                         f"traversal exists")
    return tuple(runs)


def bubble_fraction(n_slots: int, n_microbatches: int) -> float:
    """Idle fraction of the pipelined dispatch: (V-1)/(M+V-1) over the
    V virtual-stage slots (V == S for 1f1b).  Each slot is active for
    exactly M of the T = M+V-1 ticks (fill for the early slots' tail,
    drain for the late slots' head)."""
    if n_slots <= 1:
        return 0.0
    return (n_slots - 1) / float(n_microbatches + n_slots - 1)


def schedule_ticks(n_stages: int, n_microbatches: int
                   ) -> Tuple[Tuple[Tuple[int, int], ...], ...]:
    """The schedule as data, for tests/telemetry: per tick, the active
    (stage, microbatch) pairs.  Stage s processes microbatch t-s when
    0 <= t-s < M; everything else is a bubble slot."""
    out = []
    for t in range(n_microbatches + n_stages - 1):
        out.append(tuple((s, t - s) for s in range(n_stages)
                         if 0 <= t - s < n_microbatches))
    return tuple(out)


def stage_idle_ticks(spec: PipelineSpec) -> Tuple[int, ...]:
    """Bubble slot-ticks per stage — the per-stage accounting the
    ``pp_stage`` telemetry records and the ``pp_stage_idle_ms`` bench
    arm scales by the measured tick time.  Each of a stage's V/S slots
    idles exactly V-1 = T-M of the T ticks under the rotation
    schedule, so a stage's idle total is (V/S)(V-1): S-1 for 1f1b,
    2(2S-1) under v=2 interleaving (the lengthened fill/drain the
    module docstring owns up to)."""
    slots_per_stage = spec.n_virtual // spec.n_stages
    return tuple(slots_per_stage * (spec.n_ticks - spec.n_microbatches)
                 for _ in range(spec.n_stages))


def resolve_microbatches(batch_size: int, n_stages: int,
                         requested: int = 0) -> int:
    """Microbatch count M for a global batch: the requested value when
    given (must divide the batch), else the largest divisor of
    batch_size in [S, 2S] — 2S halves the bubble vs M=S, and staying a
    divisor keeps every microbatch the same shape (one compiled stage
    program, no ragged tail).  Falls back toward S, then to the largest
    divisor <= batch_size."""
    if requested:
        # validate the range BEFORE the divisibility check: python's
        # `8 % -2 == 0`, so a negative count would sail through and
        # surface as an obscure reshape/trace failure far from the flag
        if not 1 <= requested <= batch_size:
            raise ValueError(
                f"--pp_microbatches {requested} must be in "
                f"[1, batch_size={batch_size}]")
        if batch_size % requested:
            raise ValueError(
                f"--pp_microbatches {requested} does not divide the "
                f"global batch {batch_size}")
        return requested
    for m in range(2 * n_stages, n_stages - 1, -1):
        if m and batch_size % m == 0:
            return m
    for m in range(min(n_stages, batch_size), 0, -1):
        if batch_size % m == 0:
            return m
    return 1


def build_pipeline_spec(cfg, mesh) -> Optional[PipelineSpec]:
    """The spec for this (cfg, mesh), or None when the mesh has no pp
    axis of size > 1 — the None path is what keeps pp=1 programs
    byte-identical (callers select today's unstaged code path on None,
    they never trace a degenerate 1-stage pipeline)."""
    stages = pp_size(mesh)
    if stages <= 1:
        return None
    if cfg.model != "transformer":
        raise ValueError(
            f"--mesh with pp={stages}: pipeline parallelism stages the "
            f"transformer encoder; model {cfg.model!r} has no staged "
            f"form")
    if (getattr(cfg, "quant", "none") or "none") != "none":
        # each layer's QuantDense amax history would roll once per TICK
        # instead of once per step under the staged encoder, silently
        # changing the delayed-scaling semantics vs pp=1 — refuse
        # loudly; named ROADMAP follow-on next to the decode
        # unquantized-checkpoint caveat.
        raise ValueError(
            f"--quant {cfg.quant} does not compose with pipeline "
            f"parallelism yet (per-tick amax updates would diverge from "
            f"the pp=1 delayed-scaling schedule); train unquantized on "
            f"pp meshes")
    if (getattr(cfg, "dropout_impl", "none") or "none") != "none":
        # dropout stays VALID on a pp mesh (an independent mask
        # stream), but the staged encoder's make_rng fold count differs
        # from pp=1 and bubble slots consume draws — so pp>1 vs pp=1
        # runs are only comparable in distribution, not the documented
        # allclose class (module docstring).  Warn, don't refuse.
        import warnings
        warnings.warn(
            f"pp={stages} with dropout_impl={cfg.dropout_impl!r}: the "
            f"staged encoder draws a different dropout stream than "
            f"pp=1 (per-tick make_rng folds, bubble-slot draws) — the "
            f"pp ≡ pp=1 parity contract holds only with dropout "
            f"disabled (--dropout_impl none)",
            stacklevel=2)
    schedule = getattr(cfg, "pp_schedule", "1f1b") or "1f1b"
    m = resolve_microbatches(cfg.batch_size, stages,
                             int(getattr(cfg, "pp_microbatches", 0) or 0))
    return PipelineSpec(
        n_layers=cfg.n_layers, n_stages=stages, n_microbatches=m,
        stage_layers=partition_stages(cfg.n_layers, stages, schedule),
        schedule=schedule, mesh=mesh)


def constrain_stage_buffer(buf, spec: PipelineSpec):
    """The pipeline's single placement rule, applied to the [V, mb, L,
    d] stage buffer: the slot dim over pp (each stage's slots live on
    its slice — the rotation becomes the DCN collective-permute), the
    microbatch dim over the data axes (microbatches stay batch-sharded
    within a slice).  tp/sp activation constraints keep applying
    INSIDE the layers unchanged.

    With V == S (1f1b) dim 0 shards over pp directly.  Under v=2
    interleaving (V == 2S, depth-ordered slots, chunk j owned by stage
    j % S) a contiguous dim-0 shard would pile adjacent chunks onto
    one stage, so the buffer is viewed as [v, S, mb, ...] — the STAGE
    dim shards over pp, placing slot j = p*S + s on stage s = j % S,
    exactly the round-robin assignment the rule table records."""
    from faster_distributed_training_tpu.parallel.sharding import (
        shard_activation)
    V, S = buf.shape[0], spec.n_stages
    if V == S:
        return shard_activation(
            buf, spec.mesh,
            ("pp", ("dp", "fsdp")) + (None,) * (buf.ndim - 2))
    grouped = buf.reshape((V // S, S) + buf.shape[1:])
    grouped = shard_activation(
        grouped, spec.mesh,
        (None, "pp", ("dp", "fsdp")) + (None,) * (buf.ndim - 2))
    return grouped.reshape(buf.shape)


def pipeline_rules(spec: Optional[PipelineSpec], cfg=None) -> dict:
    """The inspectable routing/rule table dumped into manifest.json
    beside the compile table (cli.run_training) — stage assignment,
    microbatch count, collective placement and bubble accounting in one
    place, so "what did the pipeline decide" is a file read, not a
    code trace."""
    if spec is None:
        return {"enabled": False, "n_stages": 1}
    return {
        "enabled": True,
        "schedule": spec.schedule,
        "n_stages": spec.n_stages,
        "n_layers": spec.n_layers,
        "n_microbatches": spec.n_microbatches,
        "n_virtual_stages": spec.n_virtual,
        "n_ticks": spec.n_ticks,
        "bubble_pct": round(spec.bubble_pct, 3),
        "stage_idle_ticks": list(stage_idle_ticks(spec)),
        # the EXECUTION order (slot j applies chunk j): depth order by
        # construction whatever the assignment — the record that makes
        # "interleaved ran the layers in order" a file read
        "depth_order": [[f"layer_{i}" for i in ch]
                        for ch in virtual_chunks(spec)],
        "stages": [
            {"stage": s,
             "layers": [f"layer_{i}" for i in layers],
             # embedding/head are un-staged (replicated over pp, like
             # every param — see param_placement below); the table
             # records their logical home for the memory follow-on
             "extra": (["embeddings"] if s == 0 else [])
             + (["ln_final", "head"] if s == spec.n_stages - 1 else [])}
            for s, layers in enumerate(spec.stage_layers)],
        # placement rules, verbatim what the traced program constrains:
        "activation_placement":
            "stage buffer [S, B/M, L, d] = P('pp', ('dp','fsdp'))",
        "boundary_collective":
            "collective-permute over pp (the DCN hop), one "
            "[B/M, L, d] activation per tick",
        "param_placement":
            "replicated over pp (dp/fsdp/tp/zero specs unchanged per "
            "stage — physical per-stage residency is the named "
            "live-TPU ROADMAP follow-on)",
        "batch_axes": "dp/fsdp only (pp never shards the batch)",
    }
