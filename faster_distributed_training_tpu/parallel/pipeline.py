"""Pipeline parallelism over the pp mesh axis: the ONE rule table.

The third parallelism axis (after dp/fsdp batch sharding and tp/sp
tensor/sequence sharding): the transformer's L encoder layers are
partitioned into ``pp`` contiguous STAGES, the batch is split into M
MICROBATCHES, and the stages process microbatches in a rotating
schedule — stage s works on microbatch ``t - s`` at tick ``t``, so the
activation leaving stage s-1 at tick t-1 is exactly what stage s
consumes at tick t.  The stage-boundary hop is the only per-tick
communication (a [microbatch, L, d_model] collective-permute over pp),
which is why pp is the axis that spans DCN between slices
(parallel/mesh.py::_AXIS_SPEED — pp ranks slowest, placed outermost,
preferred by the hybrid DCN factoring).

Every routing decision the pipeline makes — stage assignment,
microbatch count, collective placement, bubble accounting — is decided
HERE and dumped as one inspectable table (``pipeline_rules``) into the
run's ``manifest.json`` beside the r15 compile table (cli.run_training),
in the spirit of SNIPPETS [2]'s ``compile_step_with_plan``: no scattered
call sites, one place to read what the pipeline did.

Execution model (models/transformer.py, gated on a ``pp_spec`` call
argument so ``pp=1`` traces stay byte-identical to r21):

  * the [B, L, d] encoder input is reshaped to M microbatches of B/M;
  * a stage buffer [S, B/M, L, d], sharded ``P("pp", data_axes, ...)``
    over dim 0, holds each stage's current input;
  * each of T = M + S - 1 ticks rotates the buffer down one stage
    (the collective-permute), inserts the next microbatch at stage 0,
    and applies every stage's layer block to its slot;
  * the last stage's outputs are collected in microbatch order and
    reassembled into [B, L, d] — bitwise the same VALUES as running the
    microbatches sequentially, so the pp=2 ≡ pp=1 comparison sits in
    the documented cross-program-family allclose class (batch-dim
    tiling + microbatch reduction order), while within a pp program
    family everything stays bitwise (the r8 scan-rounding precedent).
    Since r23 the parity contract also holds with dropout LIVE on the
    hash engine (dense attention, flax FFN): the tick loop threads a
    PipelineTickCtx through the layers — per-site seeds stashed at the
    first (fold-count-0) make_rng draw so later ticks and bubble slots
    never consume draws, and every dropout site offsets its hash
    stream by the microbatch's GLOBAL row0, so each microbatch sees
    exactly its slice of pp=1's mask.  The same ctx carries the
    delayed-scaling amax cadence that lets --quant compose (one
    history roll per optimizer step; see PipelineTickCtx).
    build_pipeline_spec warns for the remaining non-parity dropout
    combos (xla engine, pallas FFN, flash/ring/ulysses attention).

The schedule is 1F1B in the combined fwd+bwd sense: jax.grad
differentiates through the rotation, so the backward pipeline replays
the ticks in reverse — stage s's backward for microbatch m runs as soon
as stage s+1's has (the reversed rotation), one-forward-one-backward
per stage per tick with no GPipe-style full-forward buffer beyond the
[S, ...] stage buffer itself.  ``schedule="interleaved"`` (the
Megatron v=2 assignment) deals round-robin layer chunks to the stages
and the tick loop traverses the resulting VIRTUAL stages in depth
order: the buffer grows to V = 2S slots, slot j applies depth-chunk j
(``virtual_chunks`` is the contract), and physical stage j % S hosts
slot j — so every microbatch still applies layer 0..L-1 in order and
the pp=2 ≡ pp=1 parity class is schedule-independent.  In this
rotate-all formulation each tick computes ALL of a stage's chunks, so
interleaving buys placement fidelity (two non-adjacent depth regions
per stage, twice the boundary hops), NOT the Megatron bubble win:
fill/drain lengthens to V-1 ticks and the rule table records the
honest (V-1)/(M+V-1).  The chunk-granularity staggered schedule that
realizes the v× bubble reduction is a named live-TPU ROADMAP
follow-on.

Fill/drain ticks (the bubble) compute on recycled microbatch data
(never zeros — an all-zero constant block invites XLA constant-folding
the slot's backward into 0*inf NaN constants at x64): the garbage
outputs are never selected into the loss, so their cotangents are zero
and the extra work is exactly the analytic bubble fraction
(V - 1) / (M + V - 1) over the V virtual-stage slots (V = S for 1f1b)
— the executed program genuinely pays the bubble it reports
(``pipeline_bubble_pct``).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Optional, Sequence, Tuple

from faster_distributed_training_tpu.parallel.mesh import pp_size

SCHEDULES = ("1f1b", "interleaved")

_LAYER_RE = re.compile(r"(?:^|/)layer_(\d+)(?:/|$)")

# markers for the post-encoder shared leaves (param_stage_home): params
# applied AFTER the staged region on the reassembled batch, logically
# homed on the last stage.  Anything matching none of the tables below
# classifies "unknown" and the sharding lint fails until it is covered
# (sharding.REPLICATED_PP_PARAMS "pp_unmatched").
_HEAD_MARKERS = ("ln_final", "pooler", "cls_", "lm_head")


def param_stage_home(spec: "PipelineSpec", flat_name: str
                     ) -> Tuple[str, Optional[int]]:
    """(role, stage) for a '/'-joined param/batch_stats path — THE
    stage-home rule every residency surface shares (the sharding
    overlay, the rule table, the lint):

      ('stage_owned',  s)    — leaf under layer_{i}, i in stage s's
                               assignment;
      ('shared_embed', 0)    — embedding tables (consumed by stage 0's
                               input assembly; the tied LM head also
                               reads the token table on the LAST stage,
                               which is why they replicate over pp);
      ('shared_head',  S-1)  — ln_final/pooler/classifier/lm_head,
                               applied after the staged region;
      ('unknown',      None) — nothing matched; the lint fails until a
                               rule covers the new leaf class.
    """
    low = flat_name.lower()
    m = _LAYER_RE.search(low)
    if m:
        li = int(m.group(1))
        for s, layers in enumerate(spec.stage_layers):
            if li in layers:
                return "stage_owned", s
        return "unknown", None
    if "embedding" in low:
        return "shared_embed", 0
    if any(mk in low for mk in _HEAD_MARKERS):
        return "shared_head", spec.n_stages - 1
    return "unknown", None


class PipelineTickCtx:
    """Trace-time context the staged tick loop threads through the
    layer modules (models/transformer.py staged branch) so the
    per-TICK invocation pattern reproduces pp=1's per-STEP semantics
    for the two stateful per-site mechanisms:

      * dropout seeds (``site_seed``): pp=1 draws each site's seed
        once per step; the staged encoder invokes every layer once per
        tick, so repeated make_rng calls would fold a different count
        per tick and bubble slots would consume draws.  The ctx stashes
        the FIRST invocation's draw (Flax fold count 0 — the same key
        pp=1's single call derives) and replays it every later tick;
        combined with the global row offset (``row0`` — the microbatch
        id times the microbatch size, NOT the tick or slot index) each
        microbatch addresses exactly its slice of pp=1's hash-dropout
        index stream.
      * delayed-scaling amax cadence (``amax_pre``/``amax_push``):
        one history roll per optimizer step instead of one per tick —
        every tick quantizes at the PRE-step scale (pp=1's scale), the
        first REAL (non-bubble) invocation rolls the history, later
        real invocations max their microbatch amax into slot 0, and
        bubble invocations never touch it (their recycled fill/drain
        data could exceed the true batch max).  max-of-microbatch-
        maxes == the full-batch amax bitwise, so the post-step scale
        state matches pp=1 exactly (tests/test_pp_residency.py pins
        it).

    The object is created fresh inside the staged branch at every
    trace (including the once-per-dispatch trace of the K-step scan
    body), so nothing leaks across traces; the tick loop sets
    ``microbatch``/``bubble`` before each slot invocation (the loop is
    unrolled python, so module calls observe the current values at
    trace time)."""

    def __init__(self, n_microbatches: int, microbatch_rows: int):
        self.n_microbatches = int(n_microbatches)
        self.microbatch_rows = int(microbatch_rows)
        self.microbatch = 0      # clamped microbatch id of the current slot
        self.bubble = False      # fill/drain invocation (output discarded)
        self._seeds: dict = {}
        self._amax_rolled: set = set()
        self._amax_pre: dict = {}

    @property
    def row0(self) -> int:
        """Global batch-row offset of the current microbatch — the
        static offset dropout sites add to address pp=1's index
        stream."""
        return self.microbatch * self.microbatch_rows

    def site_seed(self, site: str, draw):
        """The site's per-step dropout seed: ``draw()`` (a make_rng
        bits draw) on the first invocation, the stashed tracer after —
        later ticks and bubble slots never consume rng draws."""
        if site not in self._seeds:
            self._seeds[site] = draw()
        return self._seeds[site]

    def amax_pre(self, site: str, hist):
        """The site's PRE-step amax history (stashed at first touch):
        every tick's scale comes from it, exactly like pp=1's single
        scale_from_history read."""
        if site not in self._amax_pre:
            self._amax_pre[site] = hist
        return self._amax_pre[site]

    def amax_push(self, site: str, hist, amax):
        """One-roll-per-step history update; returns the new history
        value.  Bubble invocations return ``hist`` untouched."""
        if self.bubble:
            return hist
        import jax.numpy as jnp
        from faster_distributed_training_tpu.ops.quant import (
            update_amax_history)
        if site in self._amax_rolled:
            return hist.at[0].set(jnp.maximum(hist[0], amax))
        self._amax_rolled.add(site)
        return update_amax_history(self.amax_pre(site, hist), amax)


@dataclasses.dataclass(frozen=True)
class PipelineSpec:
    """Static description of one pipelined encoder — everything the
    traced program and the rule table need.  ``mesh`` rides along (not
    part of equality-relevant identity: specs are rebuilt per Trainer,
    never hashed into jit keys — the pp program is selected by python
    branching before trace)."""
    n_layers: int
    n_stages: int
    n_microbatches: int
    stage_layers: Tuple[Tuple[int, ...], ...]   # layer indices per stage
    schedule: str = "1f1b"
    mesh: Optional[object] = None

    @property
    def n_virtual(self) -> int:
        """Virtual-stage count V: the number of depth-ordered chunks
        the tick loop traverses (== n_stages for contiguous 1F1B
        assignment, 2 * n_stages under v=2 interleaving)."""
        return len(virtual_chunks(self))

    @property
    def n_ticks(self) -> int:
        return self.n_microbatches + self.n_virtual - 1

    @property
    def bubble_pct(self) -> float:
        return 100.0 * bubble_fraction(self.n_virtual,
                                       self.n_microbatches)


def partition_stages(n_layers: int, n_stages: int,
                     schedule: str = "1f1b"
                     ) -> Tuple[Tuple[int, ...], ...]:
    """Layer indices per stage.

    "1f1b": contiguous balanced blocks — earlier stages take the extra
    layer when n_layers % n_stages != 0 (they also host the un-staged
    embedding, but the tie-break is mostly cosmetic: the schedule's
    critical path is the max per-stage block either way).

    "interleaved": layers dealt round-robin in contiguous CHUNKS of
    L / (S * v) with v=2 virtual stages per physical stage (the
    Megatron v-interleave ASSIGNMENT) — each stage touches two
    non-adjacent regions of the depth at the price of twice the
    boundary hops.  Requires L % (2S) == 0 so the V = 2S depth-ordered
    chunks are equal-sized and slot j lands on stage j % S exactly
    (the placement rule constrain_stage_buffer encodes); falls back to
    the contiguous split otherwise.  Execution stays depth-ordered
    either way: the tick loop runs virtual_chunks, never a stage's
    concatenated layer list."""
    if not 1 <= n_stages <= n_layers:
        raise ValueError(f"cannot split {n_layers} layers into "
                         f"{n_stages} pipeline stages")
    if schedule not in SCHEDULES:
        raise ValueError(f"unknown pipeline schedule {schedule!r} "
                         f"(one of {SCHEDULES})")
    if schedule == "interleaved" and n_layers % (2 * n_stages) == 0:
        v = 2
        chunk = n_layers // (n_stages * v)
        chunks = [tuple(range(i, i + chunk))
                  for i in range(0, n_layers, chunk)]
        out = [[] for _ in range(n_stages)]
        for idx, ch in enumerate(chunks):
            out[idx % n_stages].extend(ch)
        return tuple(tuple(s) for s in out)
    base, extra = divmod(n_layers, n_stages)
    bounds, lo = [], 0
    for s in range(n_stages):
        hi = lo + base + (1 if s < extra else 0)
        bounds.append(tuple(range(lo, hi)))
        lo = hi
    return tuple(bounds)


def virtual_chunks(spec: PipelineSpec) -> Tuple[Tuple[int, ...], ...]:
    """The depth-ordered virtual-stage chunks the tick loop executes:
    each chunk is a maximal run of consecutive layers from one stage's
    assignment, and the chunks are ordered by first layer — so slot j
    applying chunk j walks every microbatch through layer 0..L-1 in
    depth order REGARDLESS of schedule (the property the pp ≡ pp=1
    parity pins).  Contiguous 1F1B assignment yields one run per stage
    (chunks == stage_layers, V == S); v=2 interleaving yields V == 2S
    equal runs with chunk j owned by stage j % S — the mapping
    constrain_stage_buffer's [v, S] placement view relies on."""
    runs = []
    for layers in spec.stage_layers:
        start = 0
        for k in range(1, len(layers) + 1):
            if k == len(layers) or layers[k] != layers[k - 1] + 1:
                runs.append(tuple(layers[start:k]))
                start = k
    runs.sort(key=lambda r: r[0])
    flat = [i for r in runs for i in r]
    if flat != sorted(flat):
        raise ValueError(f"stage assignment {spec.stage_layers} has "
                         f"overlapping depth runs — no depth-ordered "
                         f"traversal exists")
    return tuple(runs)


def bubble_fraction(n_slots: int, n_microbatches: int) -> float:
    """Idle fraction of the pipelined dispatch: (V-1)/(M+V-1) over the
    V virtual-stage slots (V == S for 1f1b).  Each slot is active for
    exactly M of the T = M+V-1 ticks (fill for the early slots' tail,
    drain for the late slots' head)."""
    if n_slots <= 1:
        return 0.0
    return (n_slots - 1) / float(n_microbatches + n_slots - 1)


def schedule_ticks(n_stages: int, n_microbatches: int
                   ) -> Tuple[Tuple[Tuple[int, int], ...], ...]:
    """The schedule as data, for tests/telemetry: per tick, the active
    (stage, microbatch) pairs.  Stage s processes microbatch t-s when
    0 <= t-s < M; everything else is a bubble slot."""
    out = []
    for t in range(n_microbatches + n_stages - 1):
        out.append(tuple((s, t - s) for s in range(n_stages)
                         if 0 <= t - s < n_microbatches))
    return tuple(out)


def stage_idle_ticks(spec: PipelineSpec) -> Tuple[int, ...]:
    """Bubble slot-ticks per stage — the per-stage accounting the
    ``pp_stage`` telemetry records and the ``pp_stage_idle_ms`` bench
    arm scales by the measured tick time.  Each of a stage's V/S slots
    idles exactly V-1 = T-M of the T ticks under the rotation
    schedule, so a stage's idle total is (V/S)(V-1): S-1 for 1f1b,
    2(2S-1) under v=2 interleaving (the lengthened fill/drain the
    module docstring owns up to)."""
    slots_per_stage = spec.n_virtual // spec.n_stages
    return tuple(slots_per_stage * (spec.n_ticks - spec.n_microbatches)
                 for _ in range(spec.n_stages))


def resolve_microbatches(batch_size: int, n_stages: int,
                         requested: int = 0) -> int:
    """Microbatch count M for a global batch: the requested value when
    given (must divide the batch), else the largest divisor of
    batch_size in [S, 2S] — 2S halves the bubble vs M=S, and staying a
    divisor keeps every microbatch the same shape (one compiled stage
    program, no ragged tail).  Falls back toward S, then to the largest
    divisor <= batch_size."""
    if requested:
        # validate the range BEFORE the divisibility check: python's
        # `8 % -2 == 0`, so a negative count would sail through and
        # surface as an obscure reshape/trace failure far from the flag
        if not 1 <= requested <= batch_size:
            raise ValueError(
                f"--pp_microbatches {requested} must be in "
                f"[1, batch_size={batch_size}]")
        if batch_size % requested:
            raise ValueError(
                f"--pp_microbatches {requested} does not divide the "
                f"global batch {batch_size}")
        return requested
    for m in range(2 * n_stages, n_stages - 1, -1):
        if m and batch_size % m == 0:
            return m
    for m in range(min(n_stages, batch_size), 0, -1):
        if batch_size % m == 0:
            return m
    return 1


def build_pipeline_spec(cfg, mesh,
                        attention_impl: Optional[str] = None
                        ) -> Optional[PipelineSpec]:
    """The spec for this (cfg, mesh), or None when the mesh has no pp
    axis of size > 1 — the None path is what keeps pp=1 programs
    byte-identical (callers select today's unstaged code path on None,
    they never trace a degenerate 1-stage pipeline).

    ``attention_impl``: the RESOLVED attention implementation when the
    caller knows it (cli passes build_model's choice); None falls back
    to cfg.attention, where "" (auto) is treated conservatively for
    the dropout-parity predicate below."""
    stages = pp_size(mesh)
    if stages <= 1:
        return None
    if cfg.model != "transformer":
        raise ValueError(
            f"--mesh with pp={stages}: pipeline parallelism stages the "
            f"transformer encoder; model {cfg.model!r} has no staged "
            f"form")
    # quant composes since r23: the staged encoder threads a
    # PipelineTickCtx amax cadence through QuantDense so each site's
    # history rolls once per optimizer STEP (quantizing every tick at
    # the pre-step scale and folding the per-microbatch amaxes into one
    # max — bitwise the full-batch amax), instead of the per-tick rolls
    # that made r22 refuse.  The cadence is schedule-independent (every
    # chunk invocation per tick is either real or bubble under 1f1b and
    # interleaved alike), so the old refusal is gone entirely; scale-
    # state parity vs pp=1 is pinned by tests/test_pp_residency.py.
    # The ONE remaining refusal: --remat.  nn.remat makes every tick's
    # layer call its own checkpoint trace, so the cadence's cross-tick
    # history stash would leak tracers between traces — the staged
    # branch disables the ctx under remat, which would silently restore
    # the per-tick rolls r22 refused.  Refuse loudly instead.
    remat = bool(getattr(cfg, "remat", False))
    if getattr(cfg, "quant", "none") not in (None, "", "none") and remat:
        raise ValueError(
            f"--quant with pp={stages} and --remat: the per-step amax "
            f"cadence that makes delayed scaling match pp=1 cannot "
            f"cross nn.remat's per-tick checkpoint traces; drop --remat "
            f"on pp meshes with quant, or train unquantized")
    impl = (getattr(cfg, "dropout_impl", "none") or "none")
    if impl != "none":
        attn = (attention_impl if attention_impl is not None
                else (getattr(cfg, "attention", "") or ""))
        # hash-engine dropout composes since r23: the staged encoder
        # threads PipelineTickCtx through the FastDropout sites and the
        # dense attention path — per-site seeds stashed at the first
        # (fold-count-0) make_rng draw, each microbatch offset to its
        # GLOBAL rows of the hash index stream — so pp ≡ pp=1 holds
        # with dropout LIVE for the hash engine on dense attention with
        # the flax FFN.  The remaining non-parity combos keep a warning:
        # "xla" (threefry masks fold per invocation), the pallas fused
        # FFN (in-kernel rows address the microbatch-local index
        # space), and the flash/ring/ulysses kernels (dropout streams
        # keyed on local (b,h) inside their scan/shard_map).
        parity = (impl == "hash"
                  and (getattr(cfg, "ffn_impl", "flax") or "flax")
                  != "pallas"
                  and attn == "dense"
                  and not remat)
        if not parity:
            import warnings
            warnings.warn(
                f"pp={stages} with dropout_impl={impl!r}, "
                f"attention={attn or 'auto'!r}, "
                f"ffn_impl={getattr(cfg, 'ffn_impl', 'flax')!r}, "
                f"remat={remat}: this "
                f"combination draws a different dropout stream than "
                f"pp=1 — still valid dropout, but the pp ≡ pp=1 parity "
                f"class needs the hash engine on dense attention with "
                f"the flax FFN, no remat (or --dropout_impl none)",
                stacklevel=2)
    schedule = getattr(cfg, "pp_schedule", "1f1b") or "1f1b"
    m = resolve_microbatches(cfg.batch_size, stages,
                             int(getattr(cfg, "pp_microbatches", 0) or 0))
    return PipelineSpec(
        n_layers=cfg.n_layers, n_stages=stages, n_microbatches=m,
        stage_layers=partition_stages(cfg.n_layers, stages, schedule),
        schedule=schedule, mesh=mesh)


def constrain_stage_buffer(buf, spec: PipelineSpec):
    """The pipeline's single placement rule, applied to the [V, mb, L,
    d] stage buffer: the slot dim over pp (each stage's slots live on
    its slice — the rotation becomes the DCN collective-permute), the
    microbatch dim over the data axes (microbatches stay batch-sharded
    within a slice).  tp/sp activation constraints keep applying
    INSIDE the layers unchanged.

    With V == S (1f1b) dim 0 shards over pp directly.  Under v=2
    interleaving (V == 2S, depth-ordered slots, chunk j owned by stage
    j % S) a contiguous dim-0 shard would pile adjacent chunks onto
    one stage, so the buffer is viewed as [v, S, mb, ...] — the STAGE
    dim shards over pp, placing slot j = p*S + s on stage s = j % S,
    exactly the round-robin assignment the rule table records."""
    from faster_distributed_training_tpu.parallel.sharding import (
        shard_activation)
    V, S = buf.shape[0], spec.n_stages
    if V == S:
        return shard_activation(
            buf, spec.mesh,
            ("pp", ("dp", "fsdp")) + (None,) * (buf.ndim - 2))
    grouped = buf.reshape((V // S, S) + buf.shape[1:])
    grouped = shard_activation(
        grouped, spec.mesh,
        (None, "pp", ("dp", "fsdp")) + (None,) * (buf.ndim - 2))
    return grouped.reshape(buf.shape)


def pipeline_rules(spec: Optional[PipelineSpec], cfg=None) -> dict:
    """The inspectable routing/rule table dumped into manifest.json
    beside the compile table (cli.run_training) — stage assignment,
    microbatch count, collective placement and bubble accounting in one
    place, so "what did the pipeline decide" is a file read, not a
    code trace."""
    if spec is None:
        return {"enabled": False, "n_stages": 1}
    return {
        "enabled": True,
        "schedule": spec.schedule,
        "n_stages": spec.n_stages,
        "n_layers": spec.n_layers,
        "n_microbatches": spec.n_microbatches,
        "n_virtual_stages": spec.n_virtual,
        "n_ticks": spec.n_ticks,
        "bubble_pct": round(spec.bubble_pct, 3),
        "stage_idle_ticks": list(stage_idle_ticks(spec)),
        # the EXECUTION order (slot j applies chunk j): depth order by
        # construction whatever the assignment — the record that makes
        # "interleaved ran the layers in order" a file read
        "depth_order": [[f"layer_{i}" for i in ch]
                        for ch in virtual_chunks(spec)],
        "stages": [
            {"stage": s,
             "layers": [f"layer_{i}" for i in layers],
             # embedding/head are un-staged (replicated over pp — see
             # param_residency below); the table records their logical
             # home so per-stage accounting can attribute them
             "extra": (["embeddings"] if s == 0 else [])
             + (["ln_final", "head"] if s == spec.n_stages - 1 else [])}
            for s, layers in enumerate(spec.stage_layers)],
        # placement rules, verbatim what the traced program constrains:
        "activation_placement":
            "stage buffer [S, B/M, L, d] = P('pp', ('dp','fsdp'))",
        "boundary_collective":
            "collective-permute over pp (the DCN hop), one "
            "[B/M, L, d] activation per tick",
        "param_residency": _param_residency_rules(spec, cfg),
        "batch_axes": "dp/fsdp only (pp never shards the batch)",
    }


def _param_residency_rules(spec: PipelineSpec, cfg=None) -> dict:
    """The per-stage residency block of the rule table (ISSUE 19
    tentpole): which leaf classes live on their pp coordinate, which
    replicate and why — sharding.py's PP registries plus the stage-home
    assignment, in one inspectable record.  ``enabled`` reflects
    cfg.pp_residency (--no_pp_residency restores the r22 replicated-
    over-pp layout, e.g. for pp on a single slice where HBM is shared
    anyway — see README's decision table)."""
    from faster_distributed_training_tpu.parallel.sharding import (
        PP_RESIDENCY_RULES, REPLICATED_PP_PARAMS, ZERO_MIN_SIZE)
    enabled = bool(getattr(cfg, "pp_residency", True)) if cfg is not None \
        else True
    return {
        "enabled": enabled,
        "axis": "pp",
        "min_size": ZERO_MIN_SIZE,
        # every param/opt-state/batch_stats leaf resolves its stage
        # home through param_stage_home; stage-owned leaves shard over
        # pp (optimizer mirrors inherit via the param_mirror rule,
        # multiplying with ZeRO-within-a-stage), the rest replicate
        # with a registered reason:
        "sharded": dict(PP_RESIDENCY_RULES) if enabled else {},
        "replicated": (dict(REPLICATED_PP_PARAMS) if enabled else {
            "all": "pp_residency disabled (--no_pp_residency): params "
                   "and optimizer state keep the r22 replicated-over-pp "
                   "layout"}),
        "stage_home": {
            **{f"layer_{i}": s
               for s, layers in enumerate(spec.stage_layers)
               for i in layers},
            "embeddings": 0,
            "head": spec.n_stages - 1,
        },
    }
