"""Cross-host collectives for host-side values.

Inside jit, collectives are implicit (shardings) or explicit
(lax.psum/all_gather under shard_map — see ops/ring_attention.py).
This module covers the remaining case: host-side Python values that
must agree across processes — the reference's epoch-end
``dist.all_reduce`` on loss/correct/total (resnet50_test.py:616-619,
transformer_test.py:286-287)."""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np


def all_sum_across_processes(value) -> np.ndarray:
    """SUM all-reduce of a host scalar/array across processes.  No-op for
    single-process runs (the common single-controller TPU case)."""
    if jax.process_count() == 1:
        return np.asarray(value)
    from jax.experimental import multihost_utils
    gathered = multihost_utils.process_allgather(jnp.asarray(value))
    return np.asarray(gathered).sum(axis=0)


def all_gather_across_processes(value) -> np.ndarray:
    """Stack a host scalar/array from every process along a new leading
    axis (shape ``[process_count, ...]``), dtype-preserving.  Single-
    process: the value with the leading axis added — so callers can
    reason about host agreement (min == max, set size) without a
    process_count branch.

    Transport is raw uint8: ``jnp.asarray`` would silently downcast
    float64→float32 / int64→int32 with x64 off (the default), so a
    counter past 2^31 or a float64 timestamp would corrupt on the
    multi-host path only — the one the tests can't reach."""
    arr = np.asarray(value)
    if jax.process_count() == 1:
        return arr[None]
    from jax.experimental import multihost_utils
    flat = np.ascontiguousarray(arr).reshape(-1).view(np.uint8)
    g = np.asarray(multihost_utils.process_allgather(jnp.asarray(flat)))
    return g.view(arr.dtype).reshape((g.shape[0],) + arr.shape)


def _pack_values(metrics: Dict[str, float]):
    """(sizes, packed): the dict's values raveled into ONE flat float64
    vector, in key-insertion order (identical on every host — the dict
    is built by the same code path everywhere).  float64, not float32:
    counters like bytes-loaded exceed float32's 2^24 exact-integer
    ceiling routinely; float64 is exact to 2^53."""
    parts = [np.ravel(np.asarray(metrics[k], np.float64)) for k in metrics]
    sizes = [p.size for p in parts]
    packed = (np.concatenate(parts) if parts
              else np.zeros(0, np.float64))
    return sizes, packed


def _unpack_values(keys, sizes, summed: np.ndarray) -> Dict[str, float]:
    out, off = {}, 0
    for k, s in zip(keys, sizes):
        v = np.asarray(summed[off:off + s])
        off += s
        out[k] = float(v[0]) if s == 1 else v
    return out


def all_reduce_metrics(metrics: Dict[str, float]) -> Dict[str, float]:
    """SUM a dict of per-process-LOCAL counters across hosts.

    The reference's epoch-end ``dist.all_reduce`` (resnet50_test.py:616-619)
    sums per-rank local loss/correct/total.  In this framework the jitted
    train/eval steps already produce GLOBAL metrics (the jit program spans
    every process's devices and psums over the sharded batch) — do NOT feed
    those here or multi-host runs inflate every metric by process_count.
    Use only for values each process computes independently on host
    (e.g. per-host input-pipeline counters, files read, bytes loaded).

    One collective for the whole dict: the values are packed into a
    single float vector, allgathered ONCE, and unpacked — a D-key dict
    used to issue D ``process_allgather`` rounds, each a full cross-host
    rendezvous (the packing is what DDP's bucketed all-reduce does to
    gradients, applied to host counters).  Scalars come back as floats —
    the same contract as before (counters are float-valued)."""
    if jax.process_count() == 1:
        return dict(metrics)
    if not metrics:
        return {}
    sizes, packed = _pack_values(metrics)
    # the gather's uint8 transport keeps the float64 payload exact
    # (counters above 2^24 would round through a float32 collective);
    # the sum happens on host after decoding
    summed = all_gather_across_processes(packed).sum(axis=0)
    return _unpack_values(list(metrics), sizes, summed)
