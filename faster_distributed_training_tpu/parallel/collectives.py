"""Cross-host collectives for host-side values.

Inside jit, collectives are implicit (shardings) or explicit
(lax.psum/all_gather under shard_map — see ops/ring_attention.py).
This module covers the remaining case: host-side Python values that
must agree across processes — the reference's epoch-end
``dist.all_reduce`` on loss/correct/total (resnet50_test.py:616-619,
transformer_test.py:286-287)."""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np


def all_sum_across_processes(value) -> np.ndarray:
    """SUM all-reduce of a host scalar/array across processes.  No-op for
    single-process runs (the common single-controller TPU case)."""
    if jax.process_count() == 1:
        return np.asarray(value)
    from jax.experimental import multihost_utils
    gathered = multihost_utils.process_allgather(jnp.asarray(value))
    return np.asarray(gathered).sum(axis=0)


def all_reduce_metrics(metrics: Dict[str, float]) -> Dict[str, float]:
    """SUM a dict of per-process-LOCAL counters across hosts.

    The reference's epoch-end ``dist.all_reduce`` (resnet50_test.py:616-619)
    sums per-rank local loss/correct/total.  In this framework the jitted
    train/eval steps already produce GLOBAL metrics (the jit program spans
    every process's devices and psums over the sharded batch) — do NOT feed
    those here or multi-host runs inflate every metric by process_count.
    Use only for values each process computes independently on host
    (e.g. per-host input-pipeline counters, files read, bytes loaded)."""
    if jax.process_count() == 1:
        return dict(metrics)
    return {k: float(all_sum_across_processes(v)) for k, v in metrics.items()}
