"""Explicit PRNG handling.

The reference relies on global torch RNG state (torch.manual_seed(123456),
resnet50_test.py:728) plus host-side numpy/Beta sampling per step.  Here
randomness is explicit and reproducible across hosts and devices: one root
key per run, folded by purpose and step so every consumer gets an
independent stream and the same key sequence regardless of device count.
"""

from __future__ import annotations

import zlib
from typing import Dict

import jax
import jax.numpy as jnp

# Stable fold constants so streams can't collide across purposes.
_STREAMS = ("params", "dropout", "mixup", "data", "init", "eval")


def root_key(seed: int) -> jax.Array:
    return jax.random.PRNGKey(seed)


def stream(key: jax.Array, name: str) -> jax.Array:
    """Fold a named purpose into a key. Unknown names hash by position-independent fold."""
    try:
        idx = _STREAMS.index(name)
    except ValueError:
        # crc32, not hash(): str hash is salted per process, which would
        # derive different keys on different hosts of the same run.
        idx = (zlib.crc32(name.encode()) & 0x3FFFFFFF) | 0x40000000
    return jax.random.fold_in(key, idx)


def at_step(key: jax.Array, step) -> jax.Array:
    """Fold a (possibly traced) step counter into a key — jit-safe."""
    return jax.random.fold_in(key, jnp.asarray(step, dtype=jnp.uint32))


def split_streams(key: jax.Array, *names: str) -> Dict[str, jax.Array]:
    return {n: stream(key, n) for n in names}
