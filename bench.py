"""Benchmark: every BASELINE.md tracked metric in ONE JSON line.

  {"metric": "resnet50_cifar10_train_images_per_sec_per_chip_bs1024",
   "value": N, "unit": "images/sec/chip", "vs_baseline": N,
   "ngd_overhead_pct": N,
   "transformer_agnews_ex_per_sec_bs256_seq256": N,
   "transformer_agnews_ex_per_sec_bs64_seq512": N, ...}

The primary metric stays the flagship ResNet-50/CIFAR-10 NGD+mixup
throughput @ bs=1024 (resnet50_test.py's headline workload); the same
line now always carries the other tracked numbers (VERDICT r1 weak #3):
NGD's step-time overhead vs SGD and both reference transformer configs
(transformer_test.py:355-361: bs=256/seq=256 and bs=64/seq=512).

Round-3 additions (VERDICT r2 #1/#2/#8): each transformer config also
emits its ROOFLINE fields — analytic model FLOPs/step, achieved
TFLOP/s, MFU vs the chip's bf16 peak (device_peak_tflops, overridable
via FDT_PEAK_TFLOPS), compiled peak memory, and XLA's own
bytes-accessed estimate — plus a bs=256/seq=512 capacity pair with and
without --remat (the layer-checkpoint lever), and the long-context
attention ladder (attn_fwdbwd_ms_L{2048,4096,8192,16384}, fwd+bwd flash
kernels, token count held at 16k) so the driver records the kernel
envelope round-over-round instead of trusting hand-run PARITY notes
(default-on since round 4, VERDICT r3 #4; FDT_BENCH_ATTN=0 disables).

Round-5 additions (VERDICT r4 #1/#2/#7): the GEMM-chain ceiling probe
(transformer_gemm_ceiling_* — the step's actual matmul shapes as a bare
jitted chain under grad, the measured MXU ceiling its MFU is judged
against), absolute per-step times beside the NGD-overhead % (the % alone
is ambiguous across denominator re-bases), explicit raw-step vs
full-pipeline tricks-speedup keys, a `baseline_note`, and the
`regressions` field: every tracked numeric metric is compared against
the previous round's BENCH_r*.json and >5% moves in the harmful
direction are flagged in-record.

Round-6 additions (VERDICT r5 #1/#2/#5/#7): the EVIDENCE CHAIN — the
full record is persisted to the committed BENCH_LATEST.json every run
and a compact <=1.5 KB essentials line prints LAST so the driver's 2 KB
stdout tail always parses (the r5 record was lost to tail truncation);
`_prev_bench_record` now skips unparseable driver wrappers and prefers
the newest parseable record.  The flagged bs64/seq512 and
tricks-transformer metrics are measured N>=5 times INTERLEAVED with
medians published plus *_noise_band_pct fields that feed the guard's
thresholds.  New arms: the 2D dense/flash crossover cells
(ATTN_ROUTE_BENCH_CELLS -> attn_route_*_step_ms), eval throughput
through the real pad-and-mask eval step (resnet_eval_img_per_sec_*,
transformer_eval_ex_per_sec_*), per-arm transformer_*_step_ms, and the
tentpole A/B attribution arms (transformer_bs256_seq256_ln_autodiff_
step_ms, transformer_bs64_seq512_flash_recompute_step_ms).

Round-7 addition (resilience PR): the checkpoint-overhead arms —
the ResNet NGD step under the resilience manager's save cadence,
per-step fenced, async vs blocking vs no checkpointing.  Two overhead
definitions per arm: ckpt_*_overhead_pct compares MEDIANS (steady-state
non-save step; the tracked <1% claim for async) and
ckpt_*_amortized_overhead_pct compares MEANS (save ticks included — the
honest total cost; a median alone would exclude every save-bearing step
and read 0% even for a fully blocking saver).  Opt out with
FDT_BENCH_CKPT=0.

Round-8 additions (host-free inner loop PR): the fused-dispatch ladder —
transformer_bs256_seq256_k{1,4,16}_step_ms and resnet_bs512_k{1,4,16}_
step_ms, the full train program on DEVICE-RESIDENT synthetic data with
K steps per dispatch (steps.make_fused_train_step), K=1 being the
dispatch-per-step floor on the same path — plus the input-pipeline A/B
data_path_{host,resident}_step_ms (BatchLoader+prefetch+H2D vs resident
in-graph gather, both at K=1, the only arms that INCLUDE steady-state
data work).  All measured N-interleaved with *_noise_band_pct per the
r6 protocol.  Opt out with FDT_BENCH_KDIS=0.

Round-19 additions (shard_map kernel layer): the tp-mesh kernel A/B —
transformer_tp2_{flash,ffn,quant}_{kernel,fallback}_step_ms, the
bs256/seq256 NGD step on a dp x tp=2 mesh per recovered kernel,
kernel-via-shard_map (parallel/kernel_shard.py) vs the forced pre-r19
fallback (FDT_KERNEL_SHARD=0), N>=3 interleaved (FDT_BENCH_TPK=0 opts
out; the ffn cell is TPU-only — interpret mode would measure the
interpreter) — and transformer_bs256_seq256_fp8_e5m2_grad_step_ms, the
FP8-LM completion (fp8 forward + E5M2 JIT-scaled gradient quantization
+ quantized dW/dx), interleaved with the r13 quant set so its A/B twin
is the plain fp8 arm.

Round-18 additions (streaming data plane): data_path_stream_step_ms
joins the input-pipeline A/B — the same ResNet NGD program fed from a
DISK-sharded split through the double-buffered device window
(data/stream/) — and stream_stall_pct records the steady-state fraction
of step time blocked on the window refill (<1% target, absolute-pp
guard like telemetry_overhead_pct).  Same FDT_BENCH_KDIS=0 opt-out.

Round-9 additions (pod-scale hot path PR): the ckpt_async_sharded arm —
the per-host shard-streaming checkpoint path (addressable-shard
snapshot + background shard write + two-phase COMMIT) forced on over
the same ResNet NGD program, tracked as ckpt_async_sharded_overhead_pct
beside the r7 async/sync arms — and the live-record guard: `*_step_ms`
A/B comparisons only run when the baseline is a live bench record
(_is_live_record), never against the r5 record_note reconstruction,
with a warning naming the PARITY flip procedure otherwise.

Round-12 addition (observability PR): the telemetry-overhead arm —
telem_{on,off}_median_step_ms, the ResNet NGD step with a live
per-dispatch TelemetryRecorder vs none (the FDT_TELEMETRY=0 path),
N>=5 interleaved, tracked as telemetry_overhead_pct with a <1%
absolute guard (_ABS_PP_WORSE_IF_UP) — the run-scoped telemetry
subsystem can never silently tax the hot path.  Opt out with
FDT_BENCH_TELEM=0.

Baseline: the reference publishes no absolute throughput (BASELINE.md).
`vs_baseline` is value / FDT_BENCH_BASELINE (img/s/chip) when that env
var is set; otherwise the constant 1.0 with "baseline_configured": false
— the absolute `value` is the tracked metric.  Synthetic device-resident
data, so the numbers measure the compiled train step, not disk IO.

Process model: the parent process builds exactly ONE donating train
program (the ResNet NGD run); every other timed run executes in a
subprocess (FDT_BENCH_CHILD) — each process again builds one program.
Multiple donating programs in one process can corrupt later H2D
transfers on the axon backend, which is why this is not a loop.
Set FDT_BENCH_FAST=1 to emit only the primary metric.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

# Reference proxy: 4xA100 aggregate throughput for ResNet-50/CIFAR-10 @
# bs=1024 with AMP+fusion is not published (BASELINE.md); the driver tracks
# our absolute number round-over-round. Overridable bookkeeping constant:
BASELINE_REF_IPS = float(os.environ.get("FDT_BENCH_BASELINE", "0") or 0)

# 2D dense/flash crossover arms (VERDICT r5 #5): (bs, seq, impls) cells
# measured per round as attn_route_bs{bs}_seq{seq}_{impl}_step_ms.
# cli._ATTN_ROUTE_SURFACE cites these cells per routed region;
# tests/test_substrate.py pins the correspondence.  bs1024/seq256 runs
# flash only — its dense arm is excluded by the routing memory bound
# (see the note emitted beside it).
ATTN_ROUTE_BENCH_CELLS = ((512, 128, ("dense", "flash")),
                          (1024, 128, ("dense", "flash")),
                          (512, 256, ("dense", "flash")),
                          (1024, 256, ("flash",)),
                          (256, 384, ("dense", "flash")))

# r11 sequence-parallel route cells: full NGD train steps at the long-
# context cells the 4-impl surface serves — flash on the 1D mesh (the
# single-chip-replicated alternative) vs ring/ulysses over a
# (dp=1, sp=all-chips) mesh.  Measured N>=5 interleaved with
# *_noise_band_pct (FDT_BENCH_ATTN2D gate in main()); the matching
# kernel-level ladder arms are attn_fwdbwd_ms_L*_{ring,ulysses}.
ATTN_ROUTE_SP_BENCH_CELLS = ((8, 2048, ("flash", "ring", "ulysses")),
                             (4, 4096, ("flash", "ring", "ulysses")))


def _fence(metrics) -> None:
    # fence with a device->host readback — on some PJRT backends
    # block_until_ready returns at dispatch, not completion
    float(metrics["loss"])


def _resnet_train_program(use_ngd: bool, bs: int, steps: int,
                          sentinel: str = "none"):
    """Build + AOT-compile + warm ONE donating ResNet train program (the
    Trainer's exact configuration, honoring FDT_BENCH_REMAT /
    FDT_BENCH_TRICKS).  Shared by timed_resnet and the ckpt_* overhead
    arms so both measure the SAME program.  Returns
    (mesh, compiled, state, batch, compiled_peak_mem_bytes_or_None) with
    the 12-step warmup already run (past NGD's always-update phase — the
    Fisher refresh runs EVERY step while t < 10, then every 4th —
    optim/ngd.py NUM_INITIAL_ITERS) so the caller times steady state."""
    import jax
    import jax.numpy as jnp

    from faster_distributed_training_tpu.cli import (build_model,
                                                     enable_compilation_cache)
    from faster_distributed_training_tpu.config import (TrainConfig,
                                                        resolve_tricks)
    from faster_distributed_training_tpu.optim import build_optimizer
    from faster_distributed_training_tpu.parallel import make_mesh
    from faster_distributed_training_tpu.parallel.placement import (
        make_put_batch, shard_train_state)
    from faster_distributed_training_tpu.train import (create_train_state,
                                                       make_train_step)
    from faster_distributed_training_tpu.utils.profiling import (
        compiled_memory_bytes)

    enable_compilation_cache()
    mesh = make_mesh(("dp",))  # batch sharded over every visible chip
    remat = os.environ.get("FDT_BENCH_REMAT") == "1"
    cfg = resolve_tricks(TrainConfig(
        model="resnet50", batch_size=bs, alpha=0.2, use_ngd=use_ngd,
        optimizer="ngd" if use_ngd else "sgd",
        precision="bf16", epochs=1, remat=remat, sentinel=sentinel,
        tricks=os.environ.get("FDT_BENCH_TRICKS", "") or "on"))
    # build_model so dtype/conv_remat follow cfg (the CLI's real path)
    model = build_model(cfg)
    rng = jax.random.PRNGKey(cfg.seed)
    sample = jnp.zeros((bs, 32, 32, 3), jnp.float32)
    tx, _ = build_optimizer(cfg, steps_per_epoch=steps)
    state = create_train_state(model, tx, sample, rng,
                               init_kwargs={"train": True})
    with mesh:
        state = shard_train_state(state, mesh, cfg)
        put = make_put_batch(mesh)
        rr = np.random.default_rng(0)
        batch = put({
            "image": rr.normal(size=(bs, 32, 32, 3)).astype(np.float32),
            "label": rr.integers(0, 10, size=(bs,)).astype(np.int32),
        })
        # AOT-compile so the executable's memory analysis is available
        # (the axon backend exposes no runtime memory_stats), then run the
        # compiled object directly.
        step = jax.jit(make_train_step(cfg), donate_argnums=0)
        compiled = step.lower(state, batch).compile()
        mem = compiled_memory_bytes(compiled)
        for _ in range(12):
            state, metrics = compiled(state, batch)
        _fence(metrics)
    return mesh, compiled, state, batch, mem


def timed_resnet(use_ngd: bool, bs: int, steps: int):
    """Time `steps` executions of the shared ResNet train program.
    Returns (elapsed_seconds, compiled_peak_mem_bytes_or_None,
    state_bytes_table) — the table's ``opt_state_bytes_per_chip`` /
    ``params_bytes_per_chip`` are the committed HBM-attribution baseline
    ROADMAP's ZeRO item sizes its win against (today the optimizer state
    is replicated across any model axis; ZeRO should drop it ~tp×)."""
    from faster_distributed_training_tpu.telemetry.programs import (
        state_bytes_table)

    mesh, compiled, state, batch, mem = _resnet_train_program(
        use_ngd, bs, steps)
    with mesh:
        state_bytes = state_bytes_table(state)
        t0 = time.monotonic()
        for _ in range(steps):
            state, metrics = compiled(state, batch)
        _fence(metrics)
        return time.monotonic() - t0, mem, state_bytes


def transformer_model_flops(bs: int, seq: int, n_layers: int = 6,
                            d: int = 512, dff: int = 1024,
                            d_hidden: int = 1024, n_class: int = 4) -> float:
    """Analytic matmul FLOPs for one train step (fwd + bwd ≈ 3× fwd), the
    standard MFU numerator.  Per token per layer fwd: QKV 2·d·3d, out
    proj 2·d², FFN 2·2·d·dff, attention 2·2·L·d (QKᵀ + PV); per sentence:
    pooler 2·d² + classifier 2·d·dh + 2·dh·ncls.  Embedding gathers do
    no matmul FLOPs and are excluded (convention)."""
    per_tok = n_layers * (6 * d * d + 2 * d * d + 4 * d * dff
                          + 4 * seq * d)
    per_sent = 2 * d * d + 2 * d * d_hidden + 2 * d_hidden * n_class
    return 3.0 * (bs * seq * per_tok + bs * per_sent)


def device_peak_tflops() -> tuple:
    """(peak bf16 TFLOP/s for MFU, source). FDT_PEAK_TFLOPS overrides; else
    a device_kind table; else a conservative v5e default."""
    import jax
    env = os.environ.get("FDT_PEAK_TFLOPS")
    if env:
        return float(env), "env"
    kind = jax.devices()[0].device_kind.lower()
    for pat, peak in (("v6e", 918.0), ("v6 lite", 918.0), ("v5p", 459.0),
                      ("v5e", 197.0), ("v5 lite", 197.0), ("v4", 275.0),
                      ("v3", 123.0)):
        if pat in kind:
            return peak, kind
    return 197.0, f"default (device_kind={kind!r})"


def timed_transformer(bs: int, seq: int, steps: int,
                      remat: bool = False) -> dict:
    """One donating transformer train program (reference architecture:
    6L d512 h8 ff1024, bert vocab — transformer.py:12-35) on synthetic
    tokens; NGD like the flagship AG News run.  Returns a dict with
    elapsed seconds plus the roofline fields: compiled peak memory and
    XLA's own cost analysis (flops / bytes accessed) when exposed."""
    import jax
    import jax.numpy as jnp

    from faster_distributed_training_tpu.cli import (build_model,
                                                     enable_compilation_cache)
    from faster_distributed_training_tpu.config import TrainConfig
    from faster_distributed_training_tpu.optim import build_optimizer
    from faster_distributed_training_tpu.parallel import make_mesh
    from faster_distributed_training_tpu.parallel.placement import (
        make_put_batch, shard_train_state)
    from faster_distributed_training_tpu.train import (create_train_state,
                                                       make_train_step)
    from faster_distributed_training_tpu.utils.profiling import (
        compiled_memory_bytes)

    enable_compilation_cache()
    mesh_spec = os.environ.get("FDT_BENCH_TF_MESH", "")
    if mesh_spec:
        # 2D arms (route2d_* children): e.g. "dp=1,sp=8" for the
        # sequence-parallel route cells — axis aliases canonicalized
        from faster_distributed_training_tpu.config import parse_mesh
        maxes, mshape = parse_mesh(mesh_spec)
        mesh = make_mesh(maxes, mshape)
    else:
        mesh = make_mesh(("dp",))
    opt = os.environ.get("FDT_BENCH_TF_OPT", "ngd")
    from faster_distributed_training_tpu.config import resolve_tricks
    cfg = resolve_tricks(TrainConfig(
        model="transformer", dataset="agnews", num_classes=4,
        batch_size=bs, seq_len=seq, use_ngd=(opt == "ngd"),
        optimizer=opt, precision="bf16", epochs=1,
        quant=os.environ.get("FDT_BENCH_TF_QUANT", "") or "none",
        quant_grad=os.environ.get("FDT_BENCH_TF_QUANT_GRAD", "") or "none",
        remat=remat,
        remat_policy=os.environ.get("FDT_BENCH_TF_REMAT_POLICY",
                                    "") or "attn_out",
        attention=os.environ.get("FDT_BENCH_TF_ATTN", ""),
        mlp_impl=os.environ.get("FDT_BENCH_TF_MLP", ""),
        ffn_impl=os.environ.get("FDT_BENCH_TF_FFN", "") or "flax",
        dropout_impl=os.environ.get("FDT_BENCH_TF_DROPOUT", "") or "hash",
        tricks=os.environ.get("FDT_BENCH_TRICKS", "") or "on"))
    model = build_model(cfg, vocab_size=30522, mesh=mesh)
    rng = jax.random.PRNGKey(cfg.seed)
    sample = jnp.zeros((bs, seq), jnp.int32)
    tx, _ = build_optimizer(cfg, steps_per_epoch=steps)
    state = create_train_state(model, tx, sample, rng,
                               init_kwargs={"train": True})
    # model-axis meshes (the 2D route arms) pin the step's output state
    # to the placement policy, mirroring run_training — without it XLA
    # drifts params across the model axis between donated steps
    from faster_distributed_training_tpu.parallel.mesh import (sp_size,
                                                               tp_size)
    from faster_distributed_training_tpu.parallel.placement import (
        train_state_shardings)
    shardings = (train_state_shardings(state, mesh, cfg)
                 if tp_size(mesh) > 1 or sp_size(mesh) > 1 else None)
    with mesh:
        state = shard_train_state(state, mesh, cfg, shardings=shardings)
        put = make_put_batch(mesh)
        rr = np.random.default_rng(1)
        lens = rr.integers(seq // 2, seq + 1, size=(bs,))
        batch = put({
            "tokens": rr.integers(0, 30522, size=(bs, seq)).astype(np.int32),
            "token_types": np.zeros((bs, seq), np.int32),
            "mask": (np.arange(seq)[None, :] < lens[:, None]).astype(np.int32),
            "label": rr.integers(0, 4, size=(bs,)).astype(np.int32),
        })
        step = jax.jit(make_train_step(cfg, shardings), donate_argnums=0)
        compiled = step.lower(state, batch).compile()
        out = {"bs": bs, "seq": seq, "remat": remat}
        if remat:
            out["remat_policy"] = cfg.remat_policy
        mem = compiled_memory_bytes(compiled)
        if mem:
            out["compiled_peak_mem_bytes"] = int(mem)
        try:
            ca = compiled.cost_analysis()
            ca = ca[0] if isinstance(ca, (list, tuple)) else ca
            if ca:
                if ca.get("flops"):
                    out["xla_flops_per_step"] = float(ca["flops"])
                ba = ca.get("bytes accessed") or ca.get("bytes_accessed")
                if ba:
                    out["xla_bytes_accessed_per_step"] = float(ba)
        except Exception:
            pass
        for _ in range(12):
            state, metrics = compiled(state, batch)
        _fence(metrics)
        t0 = time.monotonic()
        for _ in range(steps):
            state, metrics = compiled(state, batch)
        _fence(metrics)
        out["elapsed"] = time.monotonic() - t0
        return out


def timed_gemm_ceiling(bs: int, seq: int, steps: int = 30) -> dict:
    """Bare GEMM-chain ceiling probe (VERDICT r4 #1).

    Runs the transformer train step's ACTUAL matmul shapes — fused QKV
    (B·L,512)×(512,1536), the batched attention matmuls QKᵀ and PV at
    (B·H,L,64), out-proj (B·L,512)×(512,512), FFN
    (B·L,512)×(512,1024)×(1024,512), pooler + classifier — as one
    jitted chain under jax.grad (so the backward's dW/dx GEMMs run too,
    FLOPs = 3× forward exactly like the analytic MFU numerator), with
    NOTHING else: no softmax, LN, dropout, residuals, embedding, or
    optimizer.  The achieved TFLOP/s of this chain IS the measured MXU
    ceiling for the step's GEMM structure at these shapes; the train
    step's MFU divided by this ceiling separates "structure-bound"
    (d_model=512 tiles) from recoverable overhead."""
    import jax
    import jax.numpy as jnp

    d, dff, H, n_layers, dh, ncls = 512, 1024, 8, 6, 1024, 4
    dk = d // H
    rr = np.random.default_rng(0)

    def mk(*s):
        return jnp.asarray(rr.normal(size=s) * 0.02, jnp.bfloat16)

    params = [{"qkv": mk(d, 3 * d), "out": mk(d, d),
               "f1": mk(d, dff), "f2": mk(dff, d)} for _ in range(n_layers)]
    head = {"pool": mk(d, d), "w1": mk(d, dh), "w2": mk(dh, ncls)}
    x0 = mk(bs * seq, d)

    def chain(x, params, head):
        for p in params:
            qkv = x @ p["qkv"]
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(bs, seq, H, dk).transpose(0, 2, 1, 3)
            k = k.reshape(bs, seq, H, dk).transpose(0, 2, 1, 3)
            v = v.reshape(bs, seq, H, dk).transpose(0, 2, 1, 3)
            s = q @ k.transpose(0, 1, 3, 2)          # scores GEMM
            c = s @ v                                # context GEMM
            c = c.transpose(0, 2, 1, 3).reshape(bs * seq, d)
            x = c @ p["out"]
            h = x @ p["f1"]
            x = h @ p["f2"]
        cls = x.reshape(bs, seq, d)[:, 0]
        return (cls @ head["pool"]) @ head["w1"] @ head["w2"]

    def loss(x, params, head):
        return jnp.sum(chain(x, params, head).astype(jnp.float32) ** 2)

    def fence(g):
        # device->host readback — on axon block_until_ready returns at
        # dispatch (same hazard _fence guards elsewhere in this file)
        float(jnp.sum(g[0].astype(jnp.float32)))

    step = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    g = step(x0, params, head)
    fence(g)
    t0 = time.monotonic()
    for _ in range(steps):
        g = step(x0, params, head)
    fence(g)
    elapsed = time.monotonic() - t0
    mf = transformer_model_flops(bs, seq)
    return {"bs": bs, "seq": seq, "elapsed": elapsed,
            "gemm_ceiling_tflops": round(mf * steps / elapsed / 1e12, 1)}


def timed_attention_ladder(steps: int = 30, impl: str = "flash") -> dict:
    """Long-context ladder (VERDICT r2 #8: promoted from PARITY prose
    into the bench JSON).  fwd+bwd attention, bf16, D=64, H=8, token
    count held at 16k (B·L = 16384), padding mask — the exact hand-run
    configuration behind PARITY.md's envelope row.

    impl "flash" (default) is the single-chip kernel; "ring"/"ulysses"
    (r11) run the sequence-parallel strategies over a (dp=1, sp=all-
    chips) mesh at the SAME global shapes — the multi-chip side of the
    4-impl routing surface.  Returns {"attn_fwdbwd_ms_L{L}": ms, ...}
    (suffix "_ring"/"_ulysses" for the sp variants); cells the chip
    count cannot serve (L or H not divisible) are omitted, not faked."""
    import jax
    import jax.numpy as jnp

    from faster_distributed_training_tpu.ops.flash_attention import (
        flash_attention)

    H, D, tokens = 8, 64, 16384
    sp_fn, mesh, n = None, None, 1
    if impl != "flash":
        from faster_distributed_training_tpu.ops.ring_attention import (
            ring_self_attention)
        from faster_distributed_training_tpu.ops.ulysses_attention import (
            ulysses_self_attention)
        from faster_distributed_training_tpu.parallel import make_mesh
        n = jax.device_count()
        if n < 2:
            return {}
        mesh = make_mesh(("dp", "sp"), (1, n))
        sp_fn = (ring_self_attention if impl == "ring"
                 else ulysses_self_attention)
    out = {}
    suffix = "" if impl == "flash" else f"_{impl}"
    for L in (2048, 4096, 8192, 16384):
        if impl != "flash" and (L % n or (impl == "ulysses" and H % n)):
            continue
        B = max(tokens // L, 1)
        rr = np.random.default_rng(L)
        q, k, v = (jnp.asarray(rr.normal(size=(B, H, L, D)), jnp.bfloat16)
                   for _ in range(3))
        lens = rr.integers(L // 2, L + 1, size=(B,))
        mask = jnp.asarray(
            (np.arange(L)[None, :] < lens[:, None]).astype(np.int32))

        if impl == "flash":
            def loss(q_, k_, v_):
                return jnp.sum(
                    flash_attention(q_, k_, v_,
                                    mask=mask).astype(jnp.float32) ** 2)
        else:
            def loss(q_, k_, v_):
                return jnp.sum(
                    sp_fn(q_, k_, v_, mask, mesh).astype(jnp.float32) ** 2)

        step = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
        g = step(q, k, v)
        jax.block_until_ready(g)
        t0 = time.monotonic()
        for _ in range(steps):
            g = step(q, k, v)
        jax.block_until_ready(g)
        out[f"attn_fwdbwd_ms_L{L}{suffix}"] = round(
            (time.monotonic() - t0) / steps * 1e3, 2)
    return out


def timed_checkpoint_overhead(mode: str, bs: int, steps: int) -> dict:
    """Checkpoint-save overhead per train step (r7 resilience arm): the
    ResNet-50 NGD train program stepped `steps` times with the resilience
    manager saving every FDT_BENCH_CKPT_EVERY (default 10) steps, each
    step individually fenced and timed.  mode: "off" = no checkpointing
    (the floor), "async" = off-critical-path manager (snapshot on the
    step thread, serialize+commit in the background), "sync" = blocking
    saves, "async_sharded" = the pod-scale per-host shard-streaming
    path forced on (addressable-shard snapshot + background shard write
    + two-phase commit — what a multi-host run takes per host).  The
    tracked claim (ISSUE r7 acceptance): async median step time within
    1% of off — the save cost leaves the critical path; r9 extends the
    same claim to the sharded path (ckpt_async_sharded_overhead_pct).
    The mean (save ticks included) is published beside it as the
    amortized total cost; see the record-building note in main()."""
    import shutil
    import tempfile

    from faster_distributed_training_tpu.resilience import (
        AsyncCheckpointManager, GoodputTracker)

    mesh, compiled, state, batch, _mem = _resnet_train_program(
        True, bs, steps)
    every = int(os.environ.get("FDT_BENCH_CKPT_EVERY", "10"))
    goodput = GoodputTracker()
    manager, ckpt_dir = None, None
    if mode != "off":
        ckpt_dir = tempfile.mkdtemp(prefix="fdt_bench_ckpt_")
        manager = AsyncCheckpointManager(
            ckpt_dir, every_steps=every, keep=2,
            async_save=mode in ("async", "async_sharded"),
            force_sharded=(mode == "async_sharded"),
            goodput=goodput, log=lambda *_: None)
    try:
        with mesh:
            per_step = []
            for i in range(1, steps + 1):
                t0 = time.monotonic()
                state, metrics = compiled(state, batch)
                _fence(metrics)   # per-step fence: each step timed alone
                if manager is not None:
                    manager.maybe_save(state, i)
                per_step.append(time.monotonic() - t0)
            if manager is not None:
                manager.close()
    finally:
        if ckpt_dir is not None:
            # keep=2 full ResNet+NGD states — do not let repeated bench
            # runs accumulate gigabytes under /tmp
            shutil.rmtree(ckpt_dir, ignore_errors=True)
    per_step.sort()
    g = goodput.summary()
    # median = the steady-state (non-save-tick) step; mean = AMORTIZED
    # cost including the save ticks — with saves on 10% of steps the
    # median alone would exclude every save-bearing step and report a
    # vacuous 0% for even a fully blocking saver, so both are tracked.
    out = {"mode": mode, "bs": bs, "steps": steps, "save_every": every,
           "median_step_ms": round(per_step[len(per_step) // 2] * 1e3, 3),
           "mean_step_ms": round(sum(per_step) / len(per_step) * 1e3, 3),
           "max_step_ms": round(per_step[-1] * 1e3, 3),
           "saves": int(g.get("saves", 0))}
    if g.get("saves"):
        out["blocking_ms_per_save"] = round(
            g["checkpoint_blocking_s"] * 1e3 / g["saves"], 2)
    return out


def timed_telemetry_overhead(mode: str, bs: int, steps: int) -> dict:
    """telemetry_overhead_pct arm (r12 observability tentpole): the
    ResNet-50 NGD train program stepped `steps` times with a live
    TelemetryRecorder taking one per-dispatch record ("on") vs no
    recorder at all ("off" — the FDT_TELEMETRY=0 kill-switch path),
    each step individually fenced so the recorder's hot-path cost (a
    few clock reads + dict build + lock-guarded append; JSON/IO on the
    background thread) lands inside the timed region.  Tracked claim:
    on-vs-off median step delta <1% — observability must never silently
    tax the hot path, and the regression guard
    (_ABS_PP_WORSE_IF_UP['telemetry_overhead_pct']) holds it there
    round over round."""
    import shutil
    import tempfile

    from faster_distributed_training_tpu.telemetry import TelemetryRecorder

    mesh, compiled, state, batch, _mem = _resnet_train_program(
        True, bs, steps)
    rec, tdir = None, None
    if mode == "on":
        tdir = tempfile.mkdtemp(prefix="fdt_bench_telem_")
        rec = TelemetryRecorder(tdir, process_index=0, process_count=1,
                                log=lambda *_: None)
    try:
        with mesh:
            per_step = []
            for i in range(1, steps + 1):
                t0 = time.monotonic()
                state, metrics = compiled(state, batch)
                _fence(metrics)   # per-step fence: each step timed alone
                if rec is not None:
                    t1 = time.monotonic()
                    rec.record_step(i, 0, i, 1, (t1 - t0) * 1e3,
                                    (t1 - t0) * 1e3, bs)
                per_step.append(time.monotonic() - t0)
            if rec is not None:
                rec.close()
    finally:
        if tdir is not None:
            shutil.rmtree(tdir, ignore_errors=True)
    per_step.sort()
    return {"mode": mode, "bs": bs, "steps": steps,
            "median_step_ms": round(per_step[len(per_step) // 2] * 1e3, 3),
            "mean_step_ms": round(sum(per_step) / len(per_step) * 1e3, 3)}


def timed_sentinel_overhead(mode: str, bs: int, steps: int) -> dict:
    """sentinel_overhead_pct arm (r24 robustness tentpole): the
    ResNet-50 NGD train program stepped `steps` times with the in-graph
    bad-step guard compiled in plus a live host-side SpikeDetector
    observing every fenced loss ("on" — exactly what --sentinel full
    buys per dispatch) vs the stock program ("off" — --sentinel none,
    byte-identical HLO to pre-sentinel, pinned by
    tests/test_sentinel.py).  BOTH arms fence every step through
    float(metrics["loss"]) — the sentinel's documented per-dispatch
    sync IS that readback, which the bench already pays — so the delta
    isolates the guard's in-graph cost (one fused finiteness reduction
    riding the grad-norm pass + a select on the update) plus the
    detector's host arithmetic.  Tracked claim: <1% median step delta,
    held by _ABS_PP_WORSE_IF_UP['sentinel_overhead_pct']."""
    from faster_distributed_training_tpu.resilience.sentinel import (
        SpikeDetector)

    mesh, compiled, state, batch, _mem = _resnet_train_program(
        True, bs, steps, sentinel="guard" if mode == "on" else "none")
    det = SpikeDetector() if mode == "on" else None
    with mesh:
        per_step = []
        for _ in range(steps):
            t0 = time.monotonic()
            state, metrics = compiled(state, batch)
            loss = float(metrics["loss"])   # fence: BOTH arms pay this
            if det is not None:
                det.observe(loss)
            per_step.append(time.monotonic() - t0)
    per_step.sort()
    return {"mode": mode, "bs": bs, "steps": steps,
            "median_step_ms": round(per_step[len(per_step) // 2] * 1e3, 3),
            "mean_step_ms": round(sum(per_step) / len(per_step) * 1e3, 3)}


# inline child for the relaunch-MTTR arms: one tiny supervised-config
# training run against a shared checkpoint dir; the crash phase dies on
# an injected fault AFTER a committed cadence save, the relaunch phase
# auto-resumes and prints its recovery decomposition (restore seconds
# from goodput, program-acquisition seconds from the observatory feed).
_RELAUNCH_CHILD = r"""
import json, os, sys
sys.path.insert(0, os.environ["FDT_BENCH_REPO"])
from faster_distributed_training_tpu.config import TrainConfig
from faster_distributed_training_tpu.cli import run_training
cfg = TrainConfig(model="transformer", dataset="synthetic", num_classes=4,
                  batch_size=8, seq_len=16, n_layers=1, d_model=16, d_ff=32,
                  n_heads=2, epochs=2, subset_stride=64, optimizer="sgd",
                  precision="fp32", plot=False, workers=0, log_every=0,
                  donate=False, checkpoint_dir=os.environ["FDT_BENCH_DIR"],
                  checkpoint_every=4,
                  executable_cache=os.environ.get("FDT_BENCH_EXEC_CACHE", ""))
out = run_training(cfg, log=lambda *a: print(*a, file=sys.stderr))
print(json.dumps({"step": int(out["state"].step),
                  "restore_s": float(out.get("goodput_restore_s", 0.0)),
                  "compile_s": float(out.get("goodput_compile_s", 0.0)),
                  "restores": int(out.get("goodput_restores", 0))}))
"""


def timed_restart_mttr(cache: bool = False) -> dict:
    """Restart-MTTR arm, r17 definition: the recovery cost of a
    RELAUNCHED process — crash phase (injected fault after a committed
    cadence save) then a fresh process that auto-resumes — which is the
    scenario a restarted/rejoining slice actually pays.  MTTR = the
    relaunch's checkpoint-restore seconds + its program-acquisition
    seconds (every compile in a relaunch is recovery recompile; with
    ``cache`` the executable tier deserializes instead —
    restart_cached_mttr_s vs restart_mttr_s is the tentpole A/B).
    detect/backoff are 0 by scenario: a platform relaunch's detection
    is platform-side and the r17 supervisor's first restart is
    immediate.  Pre-r17 this arm measured the IN-process supervised
    cycle, which keeps its compiled programs alive and therefore could
    never see the compile-dominated half of real-hardware MTTR — the
    old number survives in goodput's restart_mttr_s for supervised
    runs.  Both phases run against a HERMETIC XLA compilation-cache
    dir: a developer's warm ~/.cache would otherwise serve the crash
    phase's compiles and (XLA:CPU) cache-served executables don't
    serialize round-trippably, making the arm measure the machine's
    history instead of the cache tier."""
    import shutil
    import subprocess as sp
    import tempfile

    d = tempfile.mkdtemp(prefix="fdt_bench_mttr_")
    die_at = int(os.environ.get("FDT_BENCH_MTTR_DIE_AT", "13"))
    repo = os.path.dirname(os.path.abspath(__file__))
    xla_dirs = []

    def phase(extra, expect_fail=False):
        # one hermetic XLA cache dir PER PHASE: the persistent dir is
        # machine-local and a relaunched slice on a fresh machine only
        # keeps the (durable, StorageBackend-backed) executable cache —
        # the tier this arm A/Bs
        xla_dirs.append(tempfile.mkdtemp(prefix="fdt_bench_mttr_xla_"))
        env = dict(os.environ, FDT_BENCH_DIR=d, FDT_BENCH_REPO=repo,
                   FDT_COMPILATION_CACHE=xla_dirs[-1],
                   FDT_BENCH_EXEC_CACHE="on" if cache else "0", **extra)
        env.pop("FDT_BENCH_CHILD", None)
        p = sp.run([sys.executable, "-c", _RELAUNCH_CHILD], env=env,
                   capture_output=True, text=True, timeout=900)
        if expect_fail:
            if p.returncode == 0:
                # a disarmed fault would silently turn the "relaunch"
                # into resume-from-a-completed-run and commit bogus
                # MTTR numbers — fail the arm loudly instead
                raise RuntimeError(
                    "crash phase was expected to die on the injected "
                    "fault but exited cleanly (fault not armed?)")
            return None
        if p.returncode != 0:
            raise RuntimeError(f"relaunch child rc={p.returncode}: "
                               f"{p.stderr[-1500:]}")
        return json.loads(p.stdout.strip().splitlines()[-1])

    try:
        phase({"FDT_FAULT_DIE_AT_STEP": str(die_at)}, expect_fail=True)
        out = phase({})
        sources = {}
        try:
            with open(os.path.join(d, "telemetry", "manifest.json")) as f:
                man = json.load(f)
            for prog in man.get("compile", {}).get("programs", []):
                sources[prog["name"]] = [v.get("cache_source", "?")
                                         for v in prog["variants"]]
        except (OSError, ValueError, KeyError):
            pass
    finally:
        shutil.rmtree(d, ignore_errors=True)
        for x in xla_dirs:
            shutil.rmtree(x, ignore_errors=True)
    restore = round(out["restore_s"], 3)
    compile_ = round(out["compile_s"], 3)
    return {"mttr_s": round(restore + compile_, 3),
            "restore_s": restore, "compile_s": compile_,
            "detect_s": 0.0, "backoff_s": 0.0,
            "restores": int(out["restores"]), "die_at": die_at,
            "cache": bool(cache), "cache_sources": sources}


def timed_warm_spare() -> dict:
    """Warm-spare swap arm (r17 tentpole): a simulated 2-slice pod (one
    host thread per slice) plus ONE parked spare thread whose step
    program is already built — slice 1 is killed for good (no restart
    budget), the survivor holds, the spare claims the seat, restores,
    catches up, and finishes the run in slice 1's place.  Reports
    warm_spare_swap_s (claim -> release, published by the spare's
    goodput summary beside the badput segments)
    and warm_spare_hold_s (the survivor's parked time) — the numbers
    the cold-rejoin twin pays a process relaunch + full recompile for.
    Training is tiny by design: the arm measures the swap machinery."""
    import shutil
    import tempfile
    import threading

    import jax
    import jax.numpy as jnp
    import numpy as _np

    from faster_distributed_training_tpu.config import TrainConfig
    from faster_distributed_training_tpu.models import Transformer
    from faster_distributed_training_tpu.optim import build_optimizer
    from faster_distributed_training_tpu.resilience import (
        AsyncCheckpointManager, FaultPlan, GoodputTracker, PodCoordinator,
        Supervisor)
    from faster_distributed_training_tpu.train import (create_train_state,
                                                       make_train_step)

    cfg = TrainConfig(model="transformer", dataset="agnews", num_classes=4,
                      batch_size=4, seq_len=8, optimizer="sgd",
                      precision="fp32", epochs=1, donate=False)
    model = Transformer(n_class=4, vocab=32, n_layers=1, h=2, d_model=16,
                        d_ff=32, d_hidden=16, maxlen=8)
    tx, _ = build_optimizer(cfg, steps_per_epoch=2)
    state0 = create_train_state(model, tx, jnp.zeros((4, 8), jnp.int32),
                                jax.random.PRNGKey(0),
                                init_kwargs={"train": True})
    batch = {"tokens": _np.random.default_rng(0).integers(
                 0, 32, size=(4, 8)).astype(_np.int32),
             "label": _np.arange(4, dtype=_np.int32) % 4}
    step_fn = jax.jit(make_train_step(cfg))
    step_fn(state0, batch)          # the spare's programs are warm
    total, every = 12, 4
    die_at = int(os.environ.get("FDT_BENCH_SPARE_DIE_AT", "6"))
    d = tempfile.mkdtemp(prefix="fdt_bench_spare_")
    goodputs = [GoodputTracker().start() for _ in range(3)]
    # loose lockstep between the two MEMBERS until the kill (the r14
    # harness idiom): without it a scheduling hiccup lets the survivor
    # run ahead into a cadence save whose commit barrier can only wait
    # out the dead peer — the hold would measure the commit timeout,
    # not the swap
    barrier = threading.Barrier(2)

    def member(pi, faults, budget):
        coord = PodCoordinator(
            os.path.join(d, "_pod"), process_index=pi, process_count=2,
            sync_every=1, peer_timeout_s=30.0, slice_index=pi,
            slice_count=2, readmit_timeout_s=60.0,
            goodput=goodputs[pi], log=lambda *_: None)
        mgr = AsyncCheckpointManager(
            d, every_steps=every, process_index=pi, process_count=2,
            shard_owner=((lambda sh: sh.replica_id == 0) if pi == 0
                         else (lambda sh: False)),
            commit_timeout_s=15.0,
            step_gather_fn=coord.gather_restored_step,
            goodput=goodputs[pi], log=lambda *_: None)
        coord.drain_fn = mgr.wait
        sup = Supervisor(max_restarts=budget, backoff_base=0.01,
                         goodput=goodputs[pi], log=lambda *_: None,
                         coordinator=coord)
        progress = {"step": 0}

        def attempt(_i):
            try:
                st, start = state0, 0
                got = mgr.restore_latest(st)
                if got is not None:
                    st, meta = got
                    start = int(meta["step"])
                progress["step"] = start
                if coord.rejoining:
                    coord.rejoin_sync(start)
                with coord.watch_steps():
                    for i in range(start + 1, total + 1):
                        try:
                            barrier.wait(timeout=30.0)
                        except threading.BrokenBarrierError:
                            time.sleep(0.01)   # pace the free run
                        st, _m = step_fn(st, batch)
                        progress["step"] = i
                        if faults is not None:
                            faults.on_step(i)
                        coord.check(i)
                        align = coord.consume_cadence_align()
                        if align is not None:
                            mgr.align_cadence(align)
                        if not coord.saves_suspended:
                            mgr.maybe_save(st, i)
                mgr.wait()
                return st
            except BaseException:
                barrier.abort()
                raise
        try:
            # the supervisor records completion on the coordinator
            return sup.run(attempt, lambda: progress["step"])
        finally:
            barrier.abort()      # a finished member frees the other side
            mgr.close()
            coord.close()

    def spare():
        coord = PodCoordinator(
            os.path.join(d, "_pod"), process_index=0, process_count=2,
            sync_every=1, peer_timeout_s=30.0, slice_count=2,
            readmit_timeout_s=60.0, spare_index=0,
            goodput=goodputs[2], log=lambda *_: None)
        claim = coord.spare_wait(poll_s=0.02)
        if claim is None:
            coord.close()
            return None
        mgr = AsyncCheckpointManager(
            d, every_steps=every, process_index=coord.pi, process_count=2,
            shard_owner=(lambda sh: False), commit_timeout_s=15.0,
            step_gather_fn=coord.gather_restored_step,
            goodput=goodputs[2], log=lambda *_: None)
        coord.drain_fn = mgr.wait
        try:
            st, start = state0, 0
            got = mgr.restore_latest(st)
            if got is not None:
                st, meta = got
                start = int(meta["step"])
            coord.rejoin_sync(start)
            with coord.watch_steps():
                for i in range(start + 1, total + 1):
                    st, _m = step_fn(st, batch)
                    coord.check(i)
                    align = coord.consume_cadence_align()
                    if align is not None:
                        mgr.align_cadence(align)
                    if not coord.saves_suspended:
                        mgr.maybe_save(st, i)
            mgr.wait()
            coord.record_completion(step=total)
            return st
        finally:
            mgr.close()
            coord.close()

    errors = {}

    def body(label, fn, *a):
        try:
            fn(*a)
        except BaseException as e:          # pragma: no cover - reported
            if label != "victim":
                errors[label] = repr(e)

    threads = [
        threading.Thread(target=body, args=("survivor", member, 0, None, 3),
                         daemon=True),
        threading.Thread(target=body,
                         args=("victim", member, 1,
                               FaultPlan(die_at=die_at), 0),
                         daemon=True),
        threading.Thread(target=body, args=("spare", spare), daemon=True)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    shutil.rmtree(d, ignore_errors=True)
    s0, s2 = goodputs[0].summary(), goodputs[2].summary()
    return {"warm_spare_swap_s": round(
                float(s2.get("warm_spare_swap_s", 0.0)), 3),
            "warm_spare_hold_s": round(
                float(s0.get("readmission_hold_s", 0.0)), 3),
            "claims": int(s2.get("warm_spare_claims", 0)),
            "swaps": int(s2.get("warm_spare_swaps", 0)),
            "survivor_restarts": int(s0.get("restarts", 0)),
            "errors": errors, "die_at": die_at}


def timed_restart_slice_mttr() -> dict:
    """Slice-recovery MTTR arm (r14 elastic-recovery PR): a simulated
    2-slice pod (two host threads, one slice each, shared directory —
    the tier-1 simulation seam), slice 1 killed by a deterministic
    injected crash.  The survivor HOLDS at its dispatch boundary
    (await_readmission) instead of restarting; the killed slice
    restarts, rejoins the same generation, restores, catches up and is
    re-admitted.  Reports restart_slice_mttr_s = (detect + hold +
    restore) / readmissions with the components beside it — the
    slice-granular sibling of restart_mttr_s (whose backoff+rollback
    the surviving slice no longer pays).  Training is tiny by design:
    the arm measures the recovery machinery, not the workload."""
    import shutil
    import tempfile
    import threading

    import jax
    import jax.numpy as jnp
    import numpy as _np

    from faster_distributed_training_tpu.config import TrainConfig
    from faster_distributed_training_tpu.models import Transformer
    from faster_distributed_training_tpu.optim import build_optimizer
    from faster_distributed_training_tpu.resilience import (
        AsyncCheckpointManager, FaultPlan, GoodputTracker, PodCoordinator,
        Supervisor)
    from faster_distributed_training_tpu.train import (create_train_state,
                                                       make_train_step)

    cfg = TrainConfig(model="transformer", dataset="agnews", num_classes=4,
                      batch_size=4, seq_len=8, optimizer="sgd",
                      precision="fp32", epochs=1, donate=False)
    model = Transformer(n_class=4, vocab=32, n_layers=1, h=2, d_model=16,
                        d_ff=32, d_hidden=16, maxlen=8)
    tx, _ = build_optimizer(cfg, steps_per_epoch=2)
    state0 = create_train_state(model, tx, jnp.zeros((4, 8), jnp.int32),
                                jax.random.PRNGKey(0),
                                init_kwargs={"train": True})
    batch = {"tokens": _np.random.default_rng(0).integers(
                 0, 32, size=(4, 8)).astype(_np.int32),
             "label": _np.arange(4, dtype=_np.int32) % 4}
    step_fn = jax.jit(make_train_step(cfg))
    total, every = 12, 4
    die_at = int(os.environ.get("FDT_BENCH_SLICE_MTTR_DIE_AT", "6"))
    d = tempfile.mkdtemp(prefix="fdt_bench_slice_mttr_")
    goodputs = [GoodputTracker().start() for _ in range(2)]
    # loose lockstep until the kill (then the barrier is aborted and
    # both sides run free), plus a small per-step pace so the
    # survivor's FAIL-marker observation is deterministic-ish
    barrier = threading.Barrier(2)

    def host(pi, faults):
        coord = PodCoordinator(
            os.path.join(d, "_pod"), process_index=pi, process_count=2,
            sync_every=1, peer_timeout_s=30.0, slice_index=pi,
            slice_count=2, readmit_timeout_s=60.0,
            goodput=goodputs[pi], log=lambda *_: None)
        mgr = AsyncCheckpointManager(
            d, every_steps=every, process_index=pi, process_count=2,
            shard_owner=((lambda sh: sh.replica_id == 0) if pi == 0
                         else (lambda sh: False)),
            commit_timeout_s=15.0,
            step_gather_fn=coord.gather_restored_step,
            goodput=goodputs[pi], log=lambda *_: None)
        coord.drain_fn = mgr.wait
        sup = Supervisor(max_restarts=3, backoff_base=0.01,
                         goodput=goodputs[pi], log=lambda *_: None,
                         coordinator=coord)
        progress = {"step": 0}

        def attempt(_i):
            try:
                st, start = state0, 0
                got = mgr.restore_latest(st)
                if got is not None:
                    st, meta = got
                    start = int(meta["step"])
                progress["step"] = start
                if coord.rejoining:
                    coord.rejoin_sync(start)
                with coord.watch_steps():
                    for i in range(start + 1, total + 1):
                        try:
                            barrier.wait(timeout=30.0)
                        except threading.BrokenBarrierError:
                            pass
                        st, _m = step_fn(st, batch)
                        time.sleep(0.01)
                        progress["step"] = i
                        if faults is not None:
                            faults.on_step(i)
                        coord.check(i)
                        align = coord.consume_cadence_align()
                        if align is not None:
                            mgr.align_cadence(align)
                        if not coord.saves_suspended:
                            mgr.maybe_save(st, i)
                mgr.wait()
                return st
            except BaseException:
                barrier.abort()
                raise
        try:
            return sup.run(attempt, lambda: progress["step"])
        finally:
            mgr.close()
            coord.close()

    errors = {}

    def body(pi, faults):
        try:
            host(pi, faults)
        except BaseException as e:          # pragma: no cover - reported
            errors[pi] = repr(e)

    threads = [
        threading.Thread(target=body, args=(0, None), daemon=True),
        threading.Thread(target=body, args=(1, FaultPlan(die_at=die_at)),
                         daemon=True)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    shutil.rmtree(d, ignore_errors=True)
    s0, s1 = goodputs[0].summary(), goodputs[1].summary()
    readmits = int(s0.get("slice_readmissions", 0))
    detect = float(s0.get("detect_s", 0.0))
    hold = float(s0.get("readmission_hold_s", 0.0))
    restore = float(s1.get("restore_s", 0.0))
    return {"restart_slice_mttr_s": round(
                (detect + hold + restore) / max(readmits, 1), 3),
            "detect_s": round(detect, 3), "hold_s": round(hold, 3),
            "restore_s": round(restore, 3),
            "readmissions": readmits,
            "fallbacks": (int(s0.get("pod_fallback_restarts", 0))
                          + int(s1.get("pod_fallback_restarts", 0))),
            "errors": errors, "die_at": die_at}


def timed_pp_pipeline(pp: int) -> dict:
    """Pipeline weak-scaling rung (r22 pp tentpole): a simulated pod of
    ``pp`` slices (virtual host devices — the same tier-1 simulation
    seam as timed_restart_slice_mttr), pp = one pipeline stage per
    slice, model DEPTH grown with the slice count (weak scaling: fixed
    work per slice).  Ideal pipelining holds step time ~flat as depth
    scales; the executed rotation schedule genuinely pays the
    (S-1)/(M+S-1) fill/drain bubble, so the rung reports the schedule
    it actually ran (n_ticks, bubble share, per-stage idle ticks)
    beside the measured step time.  pp=1 is the unstaged baseline rung
    through the SAME child path.  Tiny by design: the arm measures the
    pipeline machinery; real-DCN numbers are a ROADMAP carryover."""
    import jax
    import jax.numpy as jnp
    import optax

    from faster_distributed_training_tpu.config import TrainConfig
    from faster_distributed_training_tpu.parallel.mesh import make_mesh
    from faster_distributed_training_tpu.parallel.pipeline import (
        build_pipeline_spec)
    from faster_distributed_training_tpu.cli import build_model
    from faster_distributed_training_tpu.train.state import (
        create_train_state)
    from faster_distributed_training_tpu.train.steps import make_train_step

    devices = jax.devices()
    if len(devices) < pp:
        return {"skipped": f"pp={pp} rung needs {pp} devices, host "
                           f"exposes {len(devices)}"}
    steps = int(os.environ.get("FDT_BENCH_PP_STEPS", "10"))
    cfg = TrainConfig(model="transformer", dataset="synthetic", task="lm",
                      batch_size=16, seq_len=32, n_layers=2 * pp,
                      d_model=64, d_ff=128, n_heads=4,
                      dropout_impl="none", optimizer="sgd",
                      precision="fp32", donate=False, num_classes=4)
    mesh = make_mesh(("dp", "pp"), (1, pp), devices[:pp])
    spec = build_pipeline_spec(cfg, mesh)   # None at pp=1 (baseline rung)
    model = build_model(cfg, vocab_size=256, mesh=None)
    sample = jnp.zeros((cfg.batch_size, cfg.seq_len), jnp.int32)
    state = create_train_state(model, optax.sgd(0.01), sample,
                               jax.random.PRNGKey(0),
                               init_kwargs={"train": True})
    batch = {"tokens": jax.random.randint(
        jax.random.PRNGKey(1), (cfg.batch_size, cfg.seq_len), 0, 256)}
    step_fn = jax.jit(make_train_step(cfg, pipeline=spec), donate_argnums=0)
    with mesh:
        for _ in range(3):
            state, m = step_fn(state, batch)
        jax.block_until_ready(m)
        t0 = time.monotonic()
        for _ in range(steps):
            state, m = step_fn(state, batch)
        jax.block_until_ready(m)
    out = {"elapsed": time.monotonic() - t0, "steps_timed": steps,
           "n_stages": 1 if spec is None else spec.n_stages,
           "n_layers": cfg.n_layers}
    if spec is not None:
        out.update(n_microbatches=spec.n_microbatches,
                   n_ticks=spec.n_ticks,
                   bubble_pct=round(spec.bubble_pct, 2),
                   stage_idle_ticks=spec.n_stages - 1)
    return out


# Serving-latency mixes (r16 serve/ tentpole): one tiny checkpoint,
# three batch/length request mixes through the REAL serve stack —
# continuous-batching queue, AOT-warmed per-bucket programs, 2
# replicas.  "ragged" (full bucket spread, partial batches occur
# naturally) is the headline mix published as serve_p50_ms /
# serve_p99_ms / serve_qps_per_chip; the short/long mixes bound the
# surface (smallest-bucket latency floor vs top-bucket compute).
SERVE_BENCH_MIXES = (
    ("short", 4, 8),       # lengths U[4, 8]: smallest bucket only
    ("ragged", 4, 32),     # lengths U[4, 32]: every bucket + spill
    ("long", 24, 32),      # lengths U[24, 32]: top bucket only
)


def timed_serve(mix: str) -> dict:
    """Serving arm (r16): train a tiny transformer checkpoint, stand up
    the serve/ stack on it (cli.run_serving: AOT-warmed bucket
    programs, continuous batching, 2 replicas) and push one request
    mix through the queue.  Reports nearest-rank p50/p99 request
    latency and sustained qps/chip — the serving tier's headline
    numbers feeding the regression guard.  The model is tiny by
    design: the arm measures the queue/batching/dispatch machinery
    (and the predict program's fixed cost), not the workload."""
    import shutil
    import tempfile

    import numpy as _np

    from faster_distributed_training_tpu.cli import (run_serving,
                                                     run_training)
    from faster_distributed_training_tpu.config import TrainConfig

    lo, hi = next((l, h) for m, l, h in SERVE_BENCH_MIXES if m == mix)
    n_req = int(os.environ.get("FDT_BENCH_SERVE_REQUESTS", "128"))
    d = tempfile.mkdtemp(prefix="fdt_bench_serve_")
    cfg = TrainConfig(model="transformer", dataset="synthetic",
                      num_classes=4, batch_size=8, seq_len=32,
                      seq_buckets=(8, 16, 32), n_layers=1, d_model=16,
                      d_ff=32, n_heads=2, epochs=1, subset_stride=64,
                      optimizer="sgd", precision="fp32", plot=False,
                      workers=0, log_every=0, donate=False,
                      checkpoint_dir=d, checkpoint_every=8,
                      serve_batch_size=8, serve_replicas=2,
                      serve_max_delay_ms=5.0)
    try:
        run_training(cfg, log=lambda *_: None)
        rng = _np.random.default_rng(0)
        reqs = [rng.integers(1, 1000,
                             size=int(rng.integers(lo, hi + 1))
                             ).astype(_np.int32) for _ in range(n_req)]
        out = run_serving(cfg, requests=reqs, log=lambda *_: None)
    finally:
        shutil.rmtree(d, ignore_errors=True)
    return {"mix": mix, "requests": out["requests"],
            "batches": out["batches"], "padded_rows": out["padded_rows"],
            "p50_ms": out["p50_ms"], "p99_ms": out["p99_ms"],
            "qps": out["qps"], "qps_per_chip": out["qps_per_chip"]}


# Decode-serving arms (r21 serve/decode tentpole): one tiny LM
# checkpoint per child, the REAL autoregressive stack on it — paged KV
# cache, AOT prefill + per-page-count decode-step program families,
# token-granular continuous batching.  Two arms: decode_gen (closed
# loop — submit everything, measure TTFT percentiles + sustained decode
# throughput per chip) and decode_sustained (open loop — submissions
# PACED at a target QPS so queueing delay surfaces as SLO violations;
# a closed loop self-throttles and can never show an under-provisioned
# decode tier failing).
DECODE_BENCH_SEQ = 16


def _decode_bench_cfg(d):
    """The decode arms' tiny-LM config: stream-corpus next-token
    training at seq 16 with (8, 16) buckets, then single-replica greedy
    decoding at 4 slots over 4-token pages.  Tiny by design — the arms
    measure the prefill/step/admission machinery's fixed cost, not the
    model."""
    from faster_distributed_training_tpu.config import TrainConfig
    return TrainConfig(model="transformer", dataset="stream", task="lm",
                       data_path="stream",
                       stream_dir=os.path.join(d, "stream"),
                       batch_size=8, seq_len=DECODE_BENCH_SEQ,
                       n_layers=1, d_model=16, d_ff=32, n_heads=2,
                       epochs=1, steps_per_dispatch=2, stream_window=4,
                       optimizer="sgd", precision="fp32", plot=False,
                       workers=0, log_every=0, donate=False,
                       checkpoint_dir=os.path.join(d, "ckpt"),
                       seq_buckets=(8, 16), decode_batch_size=4,
                       decode_page=4, decode_replicas=1,
                       decode_max_new_tokens=8, telemetry=False)


def _decode_train_ckpt(cfg):
    from faster_distributed_training_tpu.cli import run_training
    from faster_distributed_training_tpu.data.stream import (
        synthetic_corpus, write_lm_corpus)
    texts = synthetic_corpus(40, seed=3, words_per_doc=(25, 50))
    write_lm_corpus(cfg.stream_dir, texts, seq_len=DECODE_BENCH_SEQ,
                    rows_per_shard=16, val_fraction=0.15)
    run_training(cfg, log=lambda *_: None)


def timed_decode_gen() -> dict:
    """Closed-loop decode arm: train the tiny LM, push
    FDT_BENCH_DECODE_REQUESTS ragged prompts through
    cli.run_decode_serving, report TTFT percentiles + generated tokens
    per second per chip — the decode tier's headline throughput."""
    import shutil
    import tempfile

    from faster_distributed_training_tpu.cli import run_decode_serving

    n_req = int(os.environ.get("FDT_BENCH_DECODE_REQUESTS", "24"))
    d = tempfile.mkdtemp(prefix="fdt_bench_decode_")
    try:
        cfg = _decode_bench_cfg(d)
        _decode_train_ckpt(cfg)
        out = run_decode_serving(cfg.replace(decode_requests=n_req),
                                 log=lambda *_: None)
    finally:
        shutil.rmtree(d, ignore_errors=True)
    return {"requests": out["requests"], "tokens": out["tokens"],
            "steps": out["steps"], "prefills": out["prefills"],
            "ttft_p50_ms": out["ttft_p50_ms"],
            "ttft_p99_ms": out["ttft_p99_ms"],
            "tokens_per_sec_per_chip": out["tokens_per_sec_per_chip"]}


def timed_decode_sustained() -> dict:
    """Open-loop decode arm: same tiny LM, single decode replica, but
    submissions arrive PACED at FDT_BENCH_DECODE_QPS regardless of
    completions — arrival-time load, not completion-time load.  A
    request whose total latency exceeds FDT_BENCH_DECODE_SLO_MS counts
    as an SLO violation; the violation percentage is the metric an
    under-provisioned decode tier actually fails on."""
    import shutil
    import tempfile
    import time as _time

    import numpy as _np

    from faster_distributed_training_tpu.models.decode import SamplingCfg
    from faster_distributed_training_tpu.serve import (RequestQueue,
                                                       load_serving_state)
    from faster_distributed_training_tpu.serve.decode import (
        DecodeEngine, DecodeScheduler)

    n_req = int(os.environ.get("FDT_BENCH_DECODE_REQUESTS", "24"))
    qps = float(os.environ.get("FDT_BENCH_DECODE_QPS", "8"))
    slo_ms = float(os.environ.get("FDT_BENCH_DECODE_SLO_MS", "2000"))
    d = tempfile.mkdtemp(prefix="fdt_bench_decode_")
    try:
        cfg = _decode_bench_cfg(d)
        _decode_train_ckpt(cfg)
        model, sstate, meta = load_serving_state(cfg, log=lambda *_: None)
        q = RequestQueue(cfg.seq_buckets, max_len=cfg.seq_len)
        eng = DecodeEngine(model, sstate, q.buckets,
                           batch_size=cfg.decode_batch_size,
                           page=cfg.decode_page,
                           sampling=SamplingCfg(seed=cfg.seed),
                           name="decode0", log=lambda *_: None)
        eng.warmup()
        sched = DecodeScheduler(q, eng,
                                max_new_tokens=cfg.decode_max_new_tokens,
                                name="decode0", log=lambda *_: None)
        sched.start()
        rng = _np.random.default_rng(0)
        vocab = int(meta.get("vocab") or 256)
        prompts = [rng.integers(1, vocab, size=int(rng.integers(3, 13))
                                ).astype(_np.int32) for _ in range(n_req)]
        handles = []
        t0 = _time.monotonic()
        for i, p in enumerate(prompts):
            # open loop: the i-th arrival is scheduled at t0 + i/qps no
            # matter how far behind the decoder is running
            lag = t0 + i / qps - _time.monotonic()
            if lag > 0:
                _time.sleep(lag)
            handles.append(
                q.submit(p, max_new_tokens=cfg.decode_max_new_tokens))
        for h in handles:
            h.wait(timeout=300.0)
        q.close()
        sched.close()
    finally:
        shutil.rmtree(d, ignore_errors=True)
    lat = [h.latency_ms() for h in handles]
    viol = sum(1 for t in lat if t is None or t > slo_ms)
    return {"requests": len(handles), "target_qps": qps,
            "slo_ms": slo_ms,
            "slo_violation_pct": round(
                100.0 * viol / max(len(lat), 1), 1)}


def zero_opt_state_bytes(zero: bool) -> dict:
    """Per-chip state bytes of the ResNet-50/NGD train state on a
    dp x tp=2 mesh with the ZeRO opt-state overlay on or off — the
    post-ZeRO twin of the r15 replicated baseline the tentpole is
    measured against (no stepping: placement is what's being sized)."""
    import jax
    import jax.numpy as jnp

    from faster_distributed_training_tpu.cli import build_model
    from faster_distributed_training_tpu.config import TrainConfig
    from faster_distributed_training_tpu.optim import build_optimizer
    from faster_distributed_training_tpu.parallel import make_mesh
    from faster_distributed_training_tpu.parallel.placement import (
        shard_train_state, train_state_shardings)
    from faster_distributed_training_tpu.telemetry.programs import (
        state_bytes_table)
    from faster_distributed_training_tpu.train import create_train_state

    n_dev = jax.device_count()
    if n_dev < 2:
        return {"skipped": f"tp=2 sizing needs >=2 chips, host exposes "
                           f"{n_dev}"}
    cfg = TrainConfig(model="resnet50", dataset="synthetic",
                      batch_size=64, use_ngd=True, optimizer="ngd",
                      precision="bf16", mesh_axes=("dp", "tp"),
                      mesh_shape=(n_dev // 2, 2), zero_opt=zero)
    mesh = make_mesh(cfg.mesh_axes, cfg.mesh_shape)
    model = build_model(cfg)
    tx, _ = build_optimizer(cfg, steps_per_epoch=10)
    sample = jnp.zeros((8, 32, 32, 3), jnp.float32)
    state = create_train_state(model, tx, sample, jax.random.PRNGKey(0),
                               init_kwargs={"train": True})
    with mesh:
        sh = train_state_shardings(state, mesh, cfg)
        state = shard_train_state(state, mesh, cfg, shardings=sh)
        table = state_bytes_table(state)
    return {"zero_opt": bool(zero),
            "params_bytes_per_chip": int(table["params_bytes_per_chip"]),
            "opt_state_bytes_per_chip": int(
                table["opt_state_bytes_per_chip"]),
            "opt_state_tiers": table.get("opt_state_tiers") or {}}


def pp_residency_bytes(staged: bool) -> dict:
    """Per-chip param + opt-state bytes of a layer-dominated transformer
    train state on a dp x pp=2 mesh with per-stage residency on
    (``staged``) vs the r22 replicated-over-pp layout (``--no_pp_
    residency``) — the zero_opt_state_bytes idiom applied to the r23
    tentpole.  No stepping: placement is what's being sized.  The model
    is sized so the per-layer stack dominates the shared embedding
    tables (the stage-owned fraction is what residency divides by S, so
    a tiny embeddings-heavy config would understate the ratio real
    models see).  zero_opt is OFF in both twins so the pair isolates
    the residency reduction alone; the ZeRO-over-pp composition is
    pinned functionally by tests/test_pp_residency.py."""
    import jax
    import jax.numpy as jnp

    from faster_distributed_training_tpu.cli import build_model
    from faster_distributed_training_tpu.config import TrainConfig
    from faster_distributed_training_tpu.optim import build_optimizer
    from faster_distributed_training_tpu.parallel import make_mesh
    from faster_distributed_training_tpu.parallel.pipeline import (
        build_pipeline_spec)
    from faster_distributed_training_tpu.parallel.placement import (
        shard_train_state, train_state_shardings)
    from faster_distributed_training_tpu.telemetry.programs import (
        state_bytes_table)
    from faster_distributed_training_tpu.train import create_train_state

    n_dev = jax.device_count()
    if n_dev < 4:
        return {"skipped": f"dp x pp=2 sizing needs >=4 chips, host "
                           f"exposes {n_dev}"}
    cfg = TrainConfig(model="transformer", dataset="synthetic", task="lm",
                      batch_size=8, seq_len=64, n_layers=8, d_model=128,
                      d_ff=512, n_heads=4, dropout_impl="none",
                      optimizer="adamw", precision="fp32",
                      mesh_axes=("dp", "pp"), mesh_shape=(2, 2),
                      zero_opt=False, pp_residency=staged)
    mesh = make_mesh(cfg.mesh_axes, cfg.mesh_shape, jax.devices()[:4])
    model = build_model(cfg, vocab_size=256, mesh=None)
    tx, _ = build_optimizer(cfg, steps_per_epoch=10)
    sample = jnp.zeros((8, cfg.seq_len), jnp.int32)
    state = create_train_state(model, tx, sample, jax.random.PRNGKey(0),
                               init_kwargs={"train": True})
    pipeline = build_pipeline_spec(cfg, mesh)
    with mesh:
        sh = train_state_shardings(state, mesh, cfg, pipeline=pipeline)
        state = shard_train_state(state, mesh, cfg, shardings=sh)
        table = state_bytes_table(state)
    return {"pp_residency": bool(staged),
            "params_bytes_per_chip": int(table["params_bytes_per_chip"]),
            "opt_state_bytes_per_chip": int(
                table["opt_state_bytes_per_chip"]),
            "pp_residency_table": table.get("pp_residency") or {}}


def timed_fused(model: str, k: int, bs: int, seq: int, steps: int,
                overlap=None, offload: bool = False) -> dict:
    """K-step fused dispatch arm (r8 tentpole): the full train program on
    DEVICE-RESIDENT synthetic data, K steps per dispatch
    (steps.make_fused_train_step over data/device_resident.py) — the
    configuration whose per-step time the transformer_bs256_seq256_k{K}_
    step_ms / resnet_bs512_k{K}_step_ms arms track.  The K=1 cell is the
    dispatch-per-step floor on the SAME resident path, so the K ladder
    isolates dispatch amortization from data-path effects; uint8 images
    are augmented in-step (the real pipeline), tokens run as-is.

    overlap (ISSUE 16): None = the legacy ladder program.  True/False =
    the overlap A/B pair — BOTH arms route through train_state_shardings
    (the program shape with the ZeRO overlay), differing only in
    cfg.overlap_grad_reduce, so the pair isolates the bucketed
    reduce-scatter reshard.  offload=True adds --offload_opt_state (on a
    backend without pinned_host the step degrades it to off; the arm
    then measures the same program — read the pair on TPU)."""
    import jax
    import jax.numpy as jnp

    from faster_distributed_training_tpu.cli import (build_model,
                                                     enable_compilation_cache)
    from faster_distributed_training_tpu.config import (TrainConfig,
                                                        resolve_tricks)
    from faster_distributed_training_tpu.data import (DeviceResidentData,
                                                      synthetic_agnews,
                                                      synthetic_cifar)
    from faster_distributed_training_tpu.optim import build_optimizer
    from faster_distributed_training_tpu.parallel import make_mesh
    from faster_distributed_training_tpu.parallel.placement import (
        shard_train_state)
    from faster_distributed_training_tpu.train import (
        create_train_state, make_fused_train_step)

    enable_compilation_cache()
    mesh = make_mesh(("dp",))
    is_text = model == "transformer"
    cfg = resolve_tricks(TrainConfig(
        model=model, dataset="synthetic", num_classes=4 if is_text else 10,
        batch_size=bs, seq_len=seq or 512, use_ngd=True, optimizer="ngd",
        precision="bf16", epochs=1, steps_per_dispatch=k,
        data_path="resident", tricks="on",
        overlap_grad_reduce=bool(overlap), offload_opt_state=offload))
    sharded_state = overlap is not None or offload
    # enough resident steps/epoch to cover ONE K-dispatch in-bounds
    # (dynamic_slice would silently CLAMP an out-of-range start to the
    # last window, re-training the final batch instead of wrapping);
    # successive dispatches wrap the order via `span` below
    n = bs * max(8, k)
    if is_text:
        ds = synthetic_agnews(n, max_len=seq)
        resident = DeviceResidentData(ds, bs, seed=cfg.seed, max_len=seq,
                                      mesh=mesh)
        model_obj = build_model(cfg, vocab_size=ds.vocab_size(), mesh=mesh)
        sample = jnp.zeros((bs, resident.seq_len), jnp.int32)
    else:
        ds = synthetic_cifar(n)
        resident = DeviceResidentData(ds, bs, seed=cfg.seed, mesh=mesh)
        model_obj = build_model(cfg)
        sample = jnp.zeros((bs, 32, 32, 3), jnp.float32)
    rng = jax.random.PRNGKey(cfg.seed)
    tx, _ = build_optimizer(cfg, steps_per_epoch=resident.steps_per_epoch)
    state = create_train_state(model_obj, tx, sample, rng,
                               init_kwargs={"train": True})
    with mesh:
        sh = None
        if sharded_state:
            from faster_distributed_training_tpu.parallel.placement import (
                train_state_shardings)
            sh = train_state_shardings(state, mesh, cfg)
            state = shard_train_state(state, mesh, cfg, shardings=sh)
        else:
            state = shard_train_state(state, mesh, cfg)
        fused = jax.jit(make_fused_train_step(cfg, k, state_shardings=sh,
                                              resident=resident,
                                              mesh=mesh), donate_argnums=0)
        order = resident.epoch_order(0)
        span = max(resident.steps_per_epoch - k + 1, 1)
        n_dispatch = max(-(-steps // k), 1)
        # warm past NGD's always-update phase (the Fisher refresh runs
        # EVERY step while t < 10 — same policy as timed_resnet) and the
        # compile, so the timed window is steady state
        for w in range(max(2, -(-12 // k))):
            state, metrics = fused(state, resident.arrays, order,
                                   jnp.asarray(w % span, jnp.int32))
        _fence(metrics)
        t0 = time.monotonic()
        for d in range(n_dispatch):
            state, metrics = fused(state, resident.arrays, order,
                                   jnp.asarray((d * k) % span, jnp.int32))
        _fence(metrics)
        return {"model": model, "k": k, "bs": bs, "seq": seq,
                "elapsed": time.monotonic() - t0,
                "steps_timed": n_dispatch * k}


def timed_data_path(path: str, bs: int, steps: int) -> dict:
    """data_path_{host,resident,stream} A/B arm (r8 tentpole; stream
    r18): the SAME ResNet NGD train program fed by (a) the host
    pipeline — BatchLoader + PrefetchIterator + device_prefetch staging,
    per-batch H2D — or (b) the device-resident path (split uploaded
    once, batches gathered in-graph), or (c) the streamed path (split
    sharded to DISK in the stream format, trained through the
    double-buffered device window — data/stream/), all at
    steps_per_dispatch=1 so the delta is purely the input path, not
    dispatch fusion.  Includes ALL steady-state data work, which the
    synthetic-device-array arms above deliberately exclude.  The stream
    run additionally returns ``stall_s`` — time the consumer blocked on
    the window refill during the timed span — from which main()
    publishes ``stream_stall_pct`` (<1% steady-state target, the input
    pipeline's ``ckpt_async_overhead_pct`` sibling)."""
    import jax
    import jax.numpy as jnp

    from faster_distributed_training_tpu.cli import (build_model,
                                                     enable_compilation_cache)
    from faster_distributed_training_tpu.config import (TrainConfig,
                                                        resolve_tricks)
    from faster_distributed_training_tpu.data import (BatchLoader,
                                                      DeviceResidentData,
                                                      PrefetchIterator,
                                                      synthetic_cifar)
    from faster_distributed_training_tpu.data.loader import device_prefetch
    from faster_distributed_training_tpu.optim import build_optimizer
    from faster_distributed_training_tpu.parallel import make_mesh
    from faster_distributed_training_tpu.parallel.placement import (
        make_put_batch, shard_train_state)
    from faster_distributed_training_tpu.train import (
        create_train_state, make_fused_train_step, make_train_step)

    enable_compilation_cache()
    mesh = make_mesh(("dp",))
    cfg = resolve_tricks(TrainConfig(
        model="resnet50", batch_size=bs, use_ngd=True, optimizer="ngd",
        precision="bf16", epochs=1, data_path=path, tricks="on"))
    # the stream arm wants warmup+timed to fit ONE epoch (so the timed
    # span sees steady double-buffered refills, not epoch-boundary
    # window restarts) — sized from the requested step count so
    # FDT_BENCH_K_STEPS can't run the window off the end of the epoch;
    # host/resident cycle an 8-step split like r8
    data = synthetic_cifar(bs * (12 + steps + 8 if path == "stream" else 8))
    rng = jax.random.PRNGKey(cfg.seed)
    sample = jnp.zeros((bs, 32, 32, 3), jnp.float32)
    tx, _ = build_optimizer(cfg, steps_per_epoch=8)
    model_obj = build_model(cfg)
    state = create_train_state(model_obj, tx, sample, rng,
                               init_kwargs={"train": True})
    with mesh:
        state = shard_train_state(state, mesh, cfg)
        if path == "stream":
            import tempfile

            from faster_distributed_training_tpu.data.stream import (
                DiskStreamSource, ShardedStreamDataset, write_array_dataset)
            import shutil

            sdir = tempfile.mkdtemp(prefix="fdt_bench_stream_")
            win = None
            try:
                x, y = data
                write_array_dataset(sdir, {"image": x, "label": y},
                                    rows_per_shard=bs * 4)
                src = DiskStreamSource(ShardedStreamDataset(sdir), bs,
                                       seed=cfg.seed, mesh=mesh,
                                       window_batches=8)
                fused = jax.jit(make_fused_train_step(cfg, 1, resident=src,
                                                      mesh=mesh),
                                donate_argnums=0)
                win = src.epoch_window(0)

                def run_span(n0, count):
                    nonlocal state
                    m = None
                    for i in range(n0, n0 + count):
                        base, _hi, dev = win.buffer_for(i)
                        state, m = fused(state, dev, src.dummy_order,
                                         jnp.asarray(i - base, jnp.int32))
                    return m

                _fence(run_span(0, 12))      # past NGD's always-update phase
                stall0 = win.stall_s
                t0 = time.monotonic()
                _fence(run_span(12, steps))
                elapsed = time.monotonic() - t0
                stall = win.stall_s - stall0
            finally:
                if win is not None:     # refill thread never outlives
                    win.close()         # the arm, even on a mid-span crash
                # ~75 MB of shards per rep otherwise accumulates in /tmp
                shutil.rmtree(sdir, ignore_errors=True)
            return {"path": path, "bs": bs, "elapsed": elapsed,
                    "steps_timed": steps, "stall_s": stall}
        if path == "resident":
            resident = DeviceResidentData(data, bs, seed=cfg.seed,
                                          mesh=mesh)
            fused = jax.jit(make_fused_train_step(cfg, 1, resident=resident,
                                                  mesh=mesh),
                            donate_argnums=0)
            order = resident.epoch_order(0)
            for w in range(12):      # past NGD's always-update phase
                state, metrics = fused(state, resident.arrays, order,
                                       jnp.asarray(w % 8, jnp.int32))
            _fence(metrics)
            t0 = time.monotonic()
            for i in range(steps):
                state, metrics = fused(state, resident.arrays, order,
                                       jnp.asarray(i % 8, jnp.int32))
            _fence(metrics)
            elapsed = time.monotonic() - t0
        else:
            put = make_put_batch(mesh)
            step = jax.jit(make_train_step(cfg), donate_argnums=0)

            def stream():
                epoch = 0
                while True:
                    loader = PrefetchIterator(
                        BatchLoader(data, bs, epoch=epoch, seed=cfg.seed),
                        depth=cfg.prefetch_depth)
                    yield from device_prefetch(loader, put,
                                               depth=cfg.prefetch_depth)
                    epoch += 1

            it = stream()
            for _ in range(12):
                state, metrics = step(state, next(it))
            _fence(metrics)
            t0 = time.monotonic()
            for _ in range(steps):
                state, metrics = step(state, next(it))
            _fence(metrics)
            elapsed = time.monotonic() - t0
    return {"path": path, "bs": bs, "elapsed": elapsed,
            "steps_timed": steps}


BENCH_LATEST = "BENCH_LATEST.json"


def _bench_dir() -> str:
    return os.path.dirname(os.path.abspath(__file__))


def _load_bench_record(path):
    """One bench artifact -> metric record, or None.  Handles the
    committed full record (BENCH_LATEST.json), the driver wrapper
    {n, cmd, rc, tail, parsed} — using `parsed` when it is a dict, else
    scanning the captured tail for a parseable JSON line — and a bare
    record.  A wrapper whose tail is a truncated mid-record fragment
    (the r5 failure mode, VERDICT r5 #1) yields None instead of the
    metric-less wrapper itself."""
    try:
        with open(path) as fh:
            rec = json.load(fh)
    except Exception:
        return None
    if not isinstance(rec, dict):
        return None
    if "tail" in rec or "parsed" in rec:          # driver wrapper
        parsed = rec.get("parsed")
        if isinstance(parsed, dict):
            return parsed
        for line in reversed(str(rec.get("tail", "")).splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    cand = json.loads(line)
                except Exception:
                    continue
                if isinstance(cand, dict) and ("value" in cand
                                               or "essentials" in cand):
                    return cand
        return None
    if "value" in rec or "metric" in rec or "essentials" in rec:
        return rec
    return None


def _is_live_record(rec) -> bool:
    """True iff `rec` is a LIVE bench-produced full record — not the r5
    `record_note` reconstruction (re-emitted prose/partial numbers, no
    `bench_unix_time`).  The r6/r7 standing note: A/B `*_step_ms` pairs
    drive the PARITY lever-flip procedure, so the guard must never
    compare them against a reconstructed baseline (a fabricated delta
    could flip a default on zero evidence)."""
    return (isinstance(rec, dict)
            and "record_note" not in rec
            and bool(rec.get("bench_unix_time")))


def _prev_bench_record():
    """(record, filename) for the round-over-round regression guard
    (VERDICT r4 #2c, repaired per VERDICT r5 #1): the NEWEST parseable
    record among the driver-captured BENCH_r*.json wrappers and the
    committed BENCH_LATEST.json (written by bench itself every run so a
    truncated driver tail can never orphan a round again).  Unparseable
    wrappers (r5's `parsed: null` mid-record fragment) are skipped, not
    returned.  Newness = (bench_unix_time, full-record-over-essentials,
    round number); when the newest driver tail carries only the compact
    essentials line of the same run, BENCH_LATEST's full record wins the
    tie on bench_unix_time."""
    import glob
    import re as _re

    here = _bench_dir()
    candidates = []   # (time, is_full, round_rank, rec, name)
    for f in glob.glob(os.path.join(here, "BENCH_r*.json")):
        m = _re.search(r"BENCH_r(\d+)\.json$", f)
        if not m:
            continue
        rec = _load_bench_record(f)
        if rec is None:
            continue
        candidates.append((float(rec.get("bench_unix_time", 0) or 0),
                           0 if rec.get("essentials") else 1,
                           int(m.group(1)), rec, os.path.basename(f)))
    latest = _load_bench_record(os.path.join(here, BENCH_LATEST))
    if latest is not None:
        candidates.append((float(latest.get("bench_unix_time", 0) or 0),
                           0 if latest.get("essentials") else 1,
                           1 << 30, latest, BENCH_LATEST))
    if not candidates:
        return None, None
    _, _, _, rec, name = max(candidates, key=lambda c: c[:3])
    return rec, name


# tracked-metric direction rules for the regression guard: a move the
# WRONG way past the metric's noise threshold vs the previous round's
# BENCH_r*.json is flagged in-record.  Thresholds are per-metric-class,
# set ABOVE each metric's documented run-to-run noise so the permanent
# record doesn't accumulate false alarms (PARITY.md: the tunnel shows
# >10% variance on the attention ladder and ±1 percentage point on the
# NGD-overhead ratio; throughputs are stable to well under 5%).
_HIGHER_IS_BETTER = ("value", "tricks_speedup", "ex_per_sec",
                     "img_per_sec", "achieved_tflops", "mfu_pct",
                     "gemm_ceiling", "qps_per_chip",
                     "tokens_per_sec_per_chip")
_LOWER_IS_BETTER = ("attn_fwdbwd_ms", "peak_mem_bytes", "step_ms",
                    "bytes_per_chip", "p50_ms", "p99_ms")
_REL_THRESHOLD = {"attn_fwdbwd_ms": 0.25,   # ladder: >10% tunnel variance
                  "step_ms": 0.10,          # per-step times: modest noise
                  "p50_ms": 0.50,           # serve latency percentiles on
                  "p99_ms": 0.60,           # a shared CPU host: scheduler
                  #                           sleeps + thread timing noise
                  #                           dominate; the qps arm is the
                  #                           tighter serving signal
                  "qps_per_chip": 0.35,
                  # decode throughput shares the serving class: thread
                  # scheduling + per-step dispatch noise on a shared CPU
                  # host, tightened further by its measured noise band
                  "tokens_per_sec_per_chip": 0.35,
                  "peak_mem_bytes": 0.02,   # compiled memory: deterministic
                  "bytes_per_chip": 0.02}   # state-byte attribution:
#                                             deterministic (a move means
#                                             the state tree itself moved)
_DEFAULT_REL_THRESHOLD = 0.05
# percentage-POINT metrics get an absolute tolerance instead (a relative
# threshold on a small ratio amplifies noise: 5.2% -> 6.0% is +15%
# "relative" but within the documented ±1 pp tunnel noise)
_ABS_PP_WORSE_IF_UP = {"ngd_overhead_pct": 1.5,
                       # r12 observability claim: the per-dispatch
                       # recorder costs <1% of median step — a round
                       # that moves the measured overhead up by a full
                       # percentage point has put real work on the hot
                       # path and gets flagged
                       "telemetry_overhead_pct": 1.0,
                       # r18 streaming claim: <1% of streamed step time
                       # blocked on the window refill at steady state —
                       # a +1pp move means the double-buffered H2D
                       # stopped hiding under compute
                       "stream_stall_pct": 1.0,
                       # r21 decode tier: open-loop sustained load at
                       # the target QPS must stay inside the SLO; a
                       # +5pp move in the violation rate means the
                       # decode loop lost real headroom (the wide
                       # tolerance absorbs CPU-host scheduler jitter
                       # on a ~24-request sample: one request = ~4pp)
                       "decode_slo_violation_pct": 5.0,
                       # r22 pp tentpole: the executed schedule's
                       # fill/drain bubble share, (S-1)/(M+S-1), at the
                       # headline rung — analytic from the schedule the
                       # program actually ran, so a move means the
                       # stage/microbatch resolution itself changed
                       # (e.g. auto-microbatching picked a smaller M);
                       # 5pp absorbs one step of the M ladder
                       "pipeline_bubble_pct": 5.0,
                       # r24 robustness claim: the anomaly sentinel's
                       # in-graph guard + host spike detector cost <1%
                       # of median step — a +1pp move means the guard
                       # stopped fusing into the grad-norm pass (or the
                       # detector grew real host work)
                       "sentinel_overhead_pct": 1.0}
# -- guard-drift registry (r13 satellite; scripts/check_bench_arms.py) --
# Every record key a bench arm can emit, as fnmatch patterns.  The lint
# cross-checks this registry against (a) the *_step_ms string literals
# actually present in this file's source (AST scan — a new arm whose key
# matches no pattern fails the lint, so arms can't silently fall out of
# the regression gate) and (b) _EXPECTED_MOVES/_ABS_PP_WORSE_IF_UP
# (every guard-named metric must be producible).  *_step_ms patterns
# additionally must either appear in NOISE_BANDED_STEP_MS (the r6
# N-interleaved protocol publishes a *_noise_band_pct beside them) or be
# consciously allowlisted in SINGLE_RUN_STEP_MS with the reason class
# documented here: single-run arms predate the noise protocol and their
# guard threshold is the 10% step_ms class default instead of a measured
# band.
PRODUCED_METRIC_PATTERNS = (
    "value", "vs_baseline", "ngd_overhead_pct",
    "resnet_ngd_step_ms", "resnet_sgd_step_ms",
    "compiled_peak_mem_bytes",
    # r15 HBM attribution (the ZeRO-item baseline): per-chip bytes of
    # the primary program's train state, params vs optimizer state
    "params_bytes_per_chip", "opt_state_bytes_per_chip",
    # ISSUE 16 ZeRO tentpole: the dp x tp=2 sizing twins (post-ZeRO vs
    # forced-replicated opt state; the "resnet_bs512_k*_step_ms" pattern
    # above also covers the resnet_bs512_k{1,4}_overlap_{on,off}_step_ms
    # A/B pair), plus the single-run host-offload attribution probe
    "opt_state_bytes_per_chip_tp2_*", "params_bytes_per_chip_tp2",
    "opt_state_zero_reduction_x", "opt_offload_step_ms",
    "transformer_agnews_ex_per_sec_*", "transformer_ex_per_sec_*",
    # per-config train arms: EXACT keys, not a transformer_bs*_seq*
    # wildcard — a wildcard here would swallow every future
    # transformer_*_step_ms arm at lint rule 1 and the single-run
    # allowlist below, making the noise-protocol check vacuous
    "transformer_bs256_seq256_step_ms",
    "transformer_bs64_seq512_step_ms",
    "transformer_bs256_seq512_step_ms",
    "transformer_bs256_seq512_remat_step_ms",
    "transformer_bs*_seq*_model_tflops_per_step",
    "transformer_bs*_seq*_achieved_tflops_per_chip",
    "transformer_bs*_seq*_mfu_pct",
    "transformer_bs*_seq*_peak_mem_bytes",
    "transformer_bs*_seq*_xla_gb_per_step",
    "transformer_bs*_seq*_policy",
    "transformer_gemm_ceiling_*",
    "tricks_speedup_*",
    "attn_route_bs512_seq*_*_step_ms",         # 1D route cells (1 run)
    "attn_route_bs1024_seq*_*_step_ms",
    "attn_route_bs256_seq384_*_step_ms",
    "attn_route_bs8_seq2048_*_step_ms",        # route2d (interleaved)
    "attn_route_bs4_seq4096_*_step_ms",
    "attn_fwdbwd_ms_L*",
    "transformer_bs256_seq256_ln_autodiff_step_ms",
    "transformer_bs64_seq512_flash_recompute_step_ms",
    "ckpt_*_median_step_ms", "ckpt_*_mean_step_ms",
    "ckpt_*_blocking_ms_per_save", "ckpt_*_overhead_pct",
    "restart_mttr_s", "restart_mttr_*_s",
    "restart_slice_mttr_s", "restart_slice_mttr_*_s",
    # r17 instant restart: cached-relaunch twin + warm-spare swap
    "restart_cached_mttr_s", "restart_cached_mttr_*_s",
    "restart_cached_deserialized_programs",
    "warm_spare_swap_s", "warm_spare_hold_s",
    "telem_on_median_step_ms", "telem_off_median_step_ms",
    "telemetry_overhead_pct",
    # r24 robustness arm: in-graph bad-step guard + host spike detector
    # on vs off (interleaved), overhead held <1% by the guard above
    "sentinel_on_median_step_ms", "sentinel_off_median_step_ms",
    "sentinel_overhead_pct",
    "transformer_bs256_seq256_quant_off_step_ms",   # r13 quant A/B
    "transformer_bs256_seq256_int8_step_ms",
    "transformer_bs256_seq256_fp8_step_ms",
    # r19 FP8-LM completion: fp8 forward + E5M2 JIT-scaled gradient
    # quantization (its A/B twin is the fp8 arm above)
    "transformer_bs256_seq256_fp8_e5m2_grad_step_ms",
    # r19 shard_map kernel layer: per recovered kernel on a dp x tp=2
    # mesh, kernel-via-shard_map vs forced fallback (FDT_KERNEL_SHARD=0)
    "transformer_tp2_*_step_ms",
    "quant_peak_tflops_assumed",
    "transformer_bs256_seq256_k*_step_ms",     # r8 K ladder
    "resnet_bs512_k*_step_ms",
    "data_path_host_step_ms", "data_path_resident_step_ms",
    # r18 streaming tier: the disk-windowed input path's step time +
    # steady-state stall fraction (<1% target, guard below)
    "data_path_stream_step_ms", "stream_stall_pct",
    "resnet_eval_img_per_sec_*", "transformer_eval_ex_per_sec_*",
    # r16 serving arms (serve/ tentpole): nearest-rank request-latency
    # percentiles + sustained throughput per mix, ragged = headline
    "serve_*_p50_ms", "serve_*_p99_ms", "serve_*_qps_per_chip",
    "serve_p50_ms", "serve_p99_ms", "serve_qps_per_chip",
    # r21 decode arms (serve/decode tentpole): closed-loop generation
    # throughput + TTFT percentiles, and the open-loop sustained arm's
    # SLO-violation rate at the target QPS (guard above)
    "decode_tokens_per_sec_per_chip",
    "decode_ttft_p50_ms", "decode_ttft_p99_ms",
    "decode_slo_violation_pct",
    # r22 pipeline arms (pp tentpole): weak-scaling ladder over
    # simulated pods of {1,2,4} slices (pp = one stage per slice, depth
    # grown with the slice count) + the executed schedule's bubble
    # share and per-stage idle time from the headline (largest) rung.
    # EXACT rung keys, not a weak_scaling_* wildcard — same reasoning
    # as the per-config transformer arms above.
    "weak_scaling_slice1_step_ms",
    "weak_scaling_slice2_step_ms",
    "weak_scaling_slice4_step_ms",
    "pipeline_bubble_pct", "pp_stage_idle_ms",
    # r23 per-stage residency (ISSUE 19 tentpole): dp x pp=2 sizing
    # twins — per-chip param/opt-state bytes with stage-owned leaves
    # sharded over pp vs the r22 replicated-over-pp layout, plus the
    # reduction ratios the headline quotes (~S x at pp=S for the
    # layer-dominated fraction)
    "pp_param_bytes_per_chip_pp2_*",
    "pp_opt_state_bytes_per_chip_pp2_*",
    "pp_param_residency_reduction_x",
    "pp_opt_state_residency_reduction_x",
)
# *_step_ms arms measured N-interleaved with a published noise band:
NOISE_BANDED_STEP_MS = (
    "telem_on_median_step_ms", "telem_off_median_step_ms",
    "sentinel_on_median_step_ms", "sentinel_off_median_step_ms",
    "transformer_bs256_seq256_quant_off_step_ms",
    "transformer_bs256_seq256_int8_step_ms",
    "transformer_bs256_seq256_fp8_step_ms",
    "transformer_bs256_seq256_fp8_e5m2_grad_step_ms",
    "transformer_tp2_*_step_ms",
    "transformer_bs256_seq256_k*_step_ms",
    "resnet_bs512_k*_step_ms",
    "data_path_host_step_ms", "data_path_resident_step_ms",
    "data_path_stream_step_ms",
    "attn_route_bs8_seq2048_*_step_ms",        # route2d (interleaved)
    "attn_route_bs4_seq4096_*_step_ms",
)
# single-run *_step_ms arms, consciously exempt from the band protocol
# (pre-r6 arms and one-shot attribution probes; class threshold 10%):
SINGLE_RUN_STEP_MS = (
    "resnet_ngd_step_ms", "resnet_sgd_step_ms",
    # the per-config train arms — exact keys (see the PRODUCED note)
    "transformer_bs256_seq256_step_ms",
    "transformer_bs64_seq512_step_ms",
    "transformer_bs256_seq512_step_ms",
    "transformer_bs256_seq512_remat_step_ms",
    "attn_route_bs512_seq*_*_step_ms",         # 1D route cells (1 run)
    "attn_route_bs1024_seq*_*_step_ms",
    "attn_route_bs256_seq384_*_step_ms",
    "transformer_bs256_seq256_ln_autodiff_step_ms",
    "transformer_bs64_seq512_flash_recompute_step_ms",
    "ckpt_*_median_step_ms", "ckpt_*_mean_step_ms",
    # ISSUE 16 offload probe: one-shot attribution arm; its baseline is
    # resnet_bs512_k1_step_ms published beside it (banding the pair
    # would re-measure the ladder cell a third time for no information)
    "opt_offload_step_ms",
    # r22 weak-scaling rungs: single-run simulated-pod arms (like
    # restart_slice_mttr — each rung spins up a virtual multi-slice
    # pod; interleaving the ladder N times would triple a machinery
    # measurement whose real-DCN twin is a ROADMAP carryover anyway)
    "weak_scaling_slice1_step_ms",
    "weak_scaling_slice2_step_ms",
    "weak_scaling_slice4_step_ms",
)

# documented intentional trades: still FLAGGED (honesty first) but
# annotated so a flagged record self-explains instead of reading as an
# unexplained regression
_EXPECTED_MOVES = {
    "transformer_bs256_seq256_peak_mem_bytes": (
        "intentional r5 trade: auto-routed dense attention materializes "
        "the [B,H,L,L] probs (~+1.6 GB) for +13-15% throughput at this "
        "config (PARITY.md, resolve_attention)"),
    "transformer_bs64_seq512_peak_mem_bytes": (
        "intentional r6 trade: the monolithic flash forward now emits "
        "the row lse as a backward residual (saved-stats backward skips "
        "the in-kernel softmax recompute, ops/flash_attention.py); the "
        "128-lane lse buffer costs ~130 MB transient at this shape — "
        "FDT_FLASH_SAVE_STATS=0 restores the recompute backward"),
    "ngd_overhead_pct": (
        "tunnel-noise-sensitive ratio; diagnose with the absolute "
        "resnet_{ngd,sgd}_step_ms arms published beside it"),
}


def _find_regressions(record: dict, prev: dict, check_missing: bool = True,
                      compare_step_ms: bool = True):
    """[{metric, prev, now, change_pct}] for tracked numeric metrics that
    moved past their noise threshold in the harmful direction since the
    previous round.  A tracked metric PRESENT last round but MISSING now
    (e.g. its _run_child subprocess died) is flagged too — a silently
    vanished metric must not read as a clean round.  check_missing=False
    suppresses that (an INTENTIONAL opt-out like FDT_BENCH_FAST=1 must
    not flood the record with missing:true noise); the primary `value`/
    memory comparison is skipped when the two records' `metric` names
    differ (e.g. a different FDT_BENCH_BS configuration).
    compare_step_ms=False excludes every `*_step_ms` key — main() passes
    it when the baseline is not a live record (_is_live_record), because
    the A/B step-ms pairs feed the PARITY lever-flip procedure and must
    only ever be judged against measured numbers."""
    out = []
    tracked = (_HIGHER_IS_BETTER + _LOWER_IS_BETTER
               + tuple(_ABS_PP_WORSE_IF_UP))
    if check_missing:
        for key, was in prev.items():
            if (isinstance(was, (int, float)) and not isinstance(was, bool)
                    and key not in record
                    and not key.endswith("_noise_band_pct")
                    and (compare_step_ms or "step_ms" not in key)
                    and any(p in key for p in tracked)):
                out.append({"metric": key, "prev": was, "now": None,
                            "missing": True})
    same_config = record.get("metric") == prev.get("metric")
    for key, now in record.items():
        if key in ("value", "compiled_peak_mem_bytes") and not same_config:
            continue
        if key.endswith("_noise_band_pct"):   # metadata, not a metric
            continue
        if not compare_step_ms and "step_ms" in key:
            continue
        if not isinstance(now, (int, float)) or isinstance(now, bool):
            continue
        was = prev.get(key)
        if not isinstance(was, (int, float)):
            continue
        if key in _ABS_PP_WORSE_IF_UP:
            if now - was > _ABS_PP_WORSE_IF_UP[key]:
                out.append(_regression_entry(
                    key, was, now, round(now - was, 1),
                    f"+{_ABS_PP_WORSE_IF_UP[key]}pp"))
            continue
        if was == 0:
            continue
        worse_if_down = any(p in key for p in _HIGHER_IS_BETTER)
        worse_if_up = any(p in key for p in _LOWER_IS_BETTER)
        if worse_if_down == worse_if_up:   # untracked or ambiguous key
            continue
        thr = next((t for p, t in _REL_THRESHOLD.items() if p in key),
                   _DEFAULT_REL_THRESHOLD)
        # VERDICT r5 #2: metrics with a MEASURED noise band (N interleaved
        # re-runs, *_noise_band_pct published beside them) set their
        # threshold from the data — the larger of the class threshold and
        # either round's observed band
        band = max(float(prev.get(f"{key}_noise_band_pct") or 0.0),
                   float(record.get(f"{key}_noise_band_pct") or 0.0)) / 100.0
        thr = max(thr, band)
        change = (now - was) / abs(was)
        if (worse_if_down and change < -thr) or (worse_if_up and change > thr):
            out.append(_regression_entry(key, was, now,
                                         round(change * 100.0, 1),
                                         f"{thr:.0%}",
                                         band_pct=round(band * 100.0, 1)
                                         if band else None))
    return out


def _regression_entry(key, prev, now, change_pct, threshold, band_pct=None):
    entry = {"metric": key, "prev": prev, "now": now,
             "change_pct": change_pct, "threshold": threshold}
    notes = []
    if band_pct:
        notes.append(f"threshold includes the measured interleaved-re-run "
                     f"noise band ({band_pct}% of median) — the move is "
                     f"outside it")
    if key in _EXPECTED_MOVES:
        notes.append(_EXPECTED_MOVES[key])
    if notes:
        entry["note"] = "; ".join(notes)
    return entry


def timed_eval(kind: str, bs: int, seq: int, steps: int) -> dict:
    """Eval throughput through the REAL pad-and-mask eval path (VERDICT
    r5 #7): make_eval_step's masked reduction with a padded final batch
    (`valid` carrying zeros exactly as BatchLoader pad_last emits), so a
    routing change at eval shapes — this round makes several — cannot
    regress inference invisibly.  Tracked fields:
    resnet_eval_img_per_sec_bs* and transformer_eval_ex_per_sec_*."""
    import jax
    import jax.numpy as jnp

    from faster_distributed_training_tpu.cli import (build_model,
                                                     enable_compilation_cache)
    from faster_distributed_training_tpu.config import (TrainConfig,
                                                        resolve_tricks)
    from faster_distributed_training_tpu.optim import build_optimizer
    from faster_distributed_training_tpu.parallel import make_mesh
    from faster_distributed_training_tpu.parallel.placement import (
        make_put_batch, shard_train_state)
    from faster_distributed_training_tpu.train import create_train_state
    from faster_distributed_training_tpu.train.steps import make_eval_step

    enable_compilation_cache()
    mesh = make_mesh(("dp",))
    rr = np.random.default_rng(2)
    if kind == "transformer":
        cfg = resolve_tricks(TrainConfig(
            model="transformer", dataset="agnews", num_classes=4,
            batch_size=bs, seq_len=seq, optimizer="sgd", precision="bf16",
            epochs=1, attention=os.environ.get("FDT_BENCH_TF_ATTN", ""),
            tricks="on"))
        model = build_model(cfg, vocab_size=30522, mesh=mesh)
        sample = jnp.zeros((bs, seq), jnp.int32)
        lens = rr.integers(seq // 2, seq + 1, size=(bs,))
        batch_np = {
            "tokens": rr.integers(0, 30522, size=(bs, seq)).astype(np.int32),
            "token_types": np.zeros((bs, seq), np.int32),
            "mask": (np.arange(seq)[None, :] < lens[:, None]
                     ).astype(np.int32),
            "label": rr.integers(0, 4, size=(bs,)).astype(np.int32),
        }
    else:
        cfg = resolve_tricks(TrainConfig(
            model="resnet50", batch_size=bs, precision="bf16", epochs=1,
            tricks="on"))
        model = build_model(cfg)
        sample = jnp.zeros((bs, 32, 32, 3), jnp.float32)
        batch_np = {
            "image": rr.normal(size=(bs, 32, 32, 3)).astype(np.float32),
            "label": rr.integers(0, 10, size=(bs,)).astype(np.int32),
        }
    # the padded-final-batch contract: valid=0 rows count toward nothing
    valid = np.ones((bs,), np.float32)
    valid[-max(bs // 8, 1):] = 0.0
    batch_np["valid"] = valid
    tx, _ = build_optimizer(cfg, steps_per_epoch=1)
    state = create_train_state(model, tx, sample, jax.random.PRNGKey(0),
                               init_kwargs={"train": True})
    with mesh:
        state = shard_train_state(state, mesh, cfg)
        batch = make_put_batch(mesh)(batch_np)
        step = jax.jit(make_eval_step(cfg))
        compiled = step.lower(state, batch).compile()
        for _ in range(5):
            m = compiled(state, batch)
        _fence(m)
        t0 = time.monotonic()
        for _ in range(steps):
            m = compiled(state, batch)
        _fence(m)
        return {"bs": bs, "seq": seq, "elapsed": time.monotonic() - t0}


def _run_child(mode: str, timeout: int = 1800):
    """Run one timed workload in a subprocess; returns its parsed JSON
    (last stdout line) or None on failure — a broken secondary metric
    must not sink the primary one."""
    env = dict(os.environ, FDT_BENCH_CHILD=mode)
    try:
        out = subprocess.run([sys.executable, os.path.abspath(__file__)],
                             env=env, capture_output=True, text=True,
                             timeout=timeout)
        return json.loads(out.stdout.strip().splitlines()[-1])
    except Exception as e:
        print(f"[bench] child {mode} failed: {e!r}", file=sys.stderr)
        return None


def main() -> None:
    import jax

    bs = int(os.environ.get("FDT_BENCH_BS", "1024"))
    steps = int(os.environ.get("FDT_BENCH_STEPS", "20"))
    tf_steps = int(os.environ.get("FDT_BENCH_TF_STEPS", "20"))

    child = os.environ.get("FDT_BENCH_CHILD", "")
    if child == "resnet_sgd":
        print(json.dumps({"elapsed": timed_resnet(False, bs, steps)[0]}))
        return
    if child == "tricks_resnet":
        # bag-of-tricks OFF arm: same workload/optimizer, every speed
        # lever disabled (fp32, autodiff conv+BN, no fusion)
        os.environ["FDT_BENCH_TRICKS"] = "off"
        print(json.dumps({"elapsed": timed_resnet(True, bs, steps)[0]}))
        return
    if child == "tricks_tf":
        # the reference's figures/time.png workload is maxlen=512 at 64
        # per device (global 256 over 4 GPUs); bs=64 also FITS the OFF
        # arm's O(L^2) fp32 dense-attention memory on one 16 GB chip
        os.environ["FDT_BENCH_TRICKS"] = "off"
        print(json.dumps(timed_transformer(64, 512, tf_steps)))
        return
    if child.startswith(("tf_", "tfr_")):
        tag, cbs, cseq = child.split("_")
        print(json.dumps(timed_transformer(int(cbs), int(cseq), tf_steps,
                                           remat=(tag == "tfr"))))
        return
    if child == "attn_ladder":
        print(json.dumps(timed_attention_ladder()))
        return
    if child.startswith("attn_ladder_"):
        # r11: sequence-parallel ladder variant (ring | ulysses)
        print(json.dumps(timed_attention_ladder(
            impl=child[len("attn_ladder_"):])))
        return
    if child.startswith("route2d_"):
        # r11 sequence-parallel route cell: one impl at one long-context
        # cell; ring/ulysses run over a (dp=1, sp=all-chips) mesh, the
        # flash baseline over a dp mesh capped so the small batch still
        # divides it.  Cells this host's chip count cannot serve (seq or
        # heads not divisible — same guards as the ladder) report
        # {"skipped": ...} instead of crashing the child.
        import math as _math

        import jax as _jax
        _, cbs, cseq, impl = child.split("_")
        cbs, cseq = int(cbs), int(cseq)
        n_dev = _jax.device_count()
        os.environ["FDT_BENCH_TF_ATTN"] = impl
        if impl in ("ring", "ulysses"):
            if (n_dev < 2 or cseq % n_dev
                    or (impl == "ulysses" and 8 % n_dev)):
                print(json.dumps(
                    {"skipped": f"{impl} at bs{cbs}/seq{cseq}: "
                                f"{n_dev} chips can't serve the cell "
                                f"(seq/heads divisibility)"}))
                return
            os.environ["FDT_BENCH_TF_MESH"] = f"dp=1,sp={n_dev}"
        else:
            os.environ["FDT_BENCH_TF_MESH"] = f"dp={_math.gcd(cbs, n_dev)}"
        rsteps = int(os.environ.get("FDT_BENCH_ROUTE_STEPS", "10"))
        print(json.dumps(timed_transformer(cbs, cseq, rsteps)))
        return
    if child.startswith("gemm_"):
        _, cbs, cseq = child.split("_")
        print(json.dumps(timed_gemm_ceiling(int(cbs), int(cseq))))
        return
    if child.startswith("route_"):
        # 2D dense/flash crossover arm: one explicit impl at one cell
        _, cbs, cseq, impl = child.split("_")
        os.environ["FDT_BENCH_TF_ATTN"] = impl
        rsteps = int(os.environ.get("FDT_BENCH_ROUTE_STEPS", "10"))
        print(json.dumps(timed_transformer(int(cbs), int(cseq), rsteps)))
        return
    if child.startswith("ckpt_"):
        # resilience arm: checkpoint-save overhead per step, one mode
        # (off|async|sync) per child process
        cbs = int(os.environ.get("FDT_BENCH_CKPT_BS", "256"))
        csteps = int(os.environ.get("FDT_BENCH_CKPT_STEPS", "40"))
        print(json.dumps(timed_checkpoint_overhead(
            child[len("ckpt_"):], cbs, csteps)))
        return
    if child == "restart_mttr":
        # r17 resilience arm: crash + COLD process relaunch — the
        # restore + full-recompile recovery a restarted slice pays
        print(json.dumps(timed_restart_mttr(cache=False)))
        return
    if child == "restart_cached_mttr":
        # r17 tentpole A/B twin: the same relaunch with the persistent
        # executable cache armed — programs deserialize, not recompile
        print(json.dumps(timed_restart_mttr(cache=True)))
        return
    if child == "warm_spare":
        # r17 tentpole arm: parked spare claims a killed slice's seat
        print(json.dumps(timed_warm_spare()))
        return
    if child == "restart_slice_mttr":
        # r14 elastic-recovery arm: simulated 2-slice pod, one slice
        # killed and re-admitted; detect + hold + restore decomposition
        print(json.dumps(timed_restart_slice_mttr()))
        return
    if child.startswith("pp_"):
        # r22 pipeline weak-scaling rung: simulated pod of N slices,
        # pp = one stage per slice, depth grown with the slice count.
        # The parent cannot widen its own device view, so each rung's
        # child forces virtual host devices BEFORE the backend
        # initializes (harmless off-CPU: the flag only shapes the host
        # platform; a real multi-chip backend serves the rung as-is).
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8")
        print(json.dumps(timed_pp_pipeline(int(child[len("pp_"):]))))
        return
    if child.startswith("serve_"):
        # r16 serving arm: one batch/length request mix through the
        # serve/ stack (continuous batching + 2 AOT-warmed replicas)
        print(json.dumps(timed_serve(child[len("serve_"):])))
        return
    if child == "decode_gen":
        # r21 decode arm: closed-loop generation through the paged-KV
        # decode stack (TTFT percentiles + tokens/sec/chip)
        print(json.dumps(timed_decode_gen()))
        return
    if child == "decode_sustained":
        # r21 decode arm: open-loop sustained load at a target QPS —
        # SLO-violation percentage under arrival-time pacing
        print(json.dumps(timed_decode_sustained()))
        return
    if child.startswith("telem_"):
        # r12 observability arm: per-dispatch recorder on vs off, one
        # mode per child process (interleaved by the parent)
        tbs = int(os.environ.get("FDT_BENCH_TELEM_BS", "256"))
        tsteps = int(os.environ.get("FDT_BENCH_TELEM_STEPS", "40"))
        print(json.dumps(timed_telemetry_overhead(
            child[len("telem_"):], tbs, tsteps)))
        return
    if child.startswith("sentinel_"):
        # r24 robustness arm: in-graph bad-step guard + host spike
        # detector on vs off, one mode per child process (interleaved
        # by the parent)
        sbs = int(os.environ.get("FDT_BENCH_SENTINEL_BS", "256"))
        ssteps = int(os.environ.get("FDT_BENCH_SENTINEL_STEPS", "40"))
        print(json.dumps(timed_sentinel_overhead(
            child[len("sentinel_"):], sbs, ssteps)))
        return
    if child.startswith("kdis_"):
        # r8 fused-dispatch ladder: one (model, K) cell per child
        _, m, kk = child.split("_")
        ksteps = int(os.environ.get("FDT_BENCH_K_STEPS", "32"))
        if m == "tf":
            print(json.dumps(timed_fused("transformer", int(kk), 256, 256,
                                         ksteps)))
        else:
            print(json.dumps(timed_fused("resnet50", int(kk), 512, 0,
                                         ksteps)))
        return
    if child.startswith("datapath_"):
        dsteps = int(os.environ.get("FDT_BENCH_K_STEPS", "32"))
        print(json.dumps(timed_data_path(child[len("datapath_"):], 512,
                                         dsteps)))
        return
    if child.startswith("kov_"):
        # ISSUE 16 overlap A/B: resnet K-dispatch with the bucketed
        # gradient reduce-scatter reshard on|off, one (mode, K) cell per
        # child — both arms run the state_shardings program, only
        # cfg.overlap_grad_reduce differs
        _, mode, kk = child.split("_")
        ksteps = int(os.environ.get("FDT_BENCH_K_STEPS", "32"))
        print(json.dumps(timed_fused("resnet50", int(kk), 512, 0, ksteps,
                                     overlap=(mode == "on"))))
        return
    if child == "optoffload":
        # ISSUE 16 host-offload arm: the K=1 resnet program with
        # --offload_opt_state (pinned_host tiers engage on TPU; on a
        # host-only backend the step degrades the flag to off and the
        # arm measures the undegraded twin of resnet_bs512_k1_step_ms)
        ksteps = int(os.environ.get("FDT_BENCH_K_STEPS", "32"))
        print(json.dumps(timed_fused("resnet50", 1, 512, 0, ksteps,
                                     overlap=False, offload=True)))
        return
    if child.startswith("zerobytes_"):
        # ISSUE 16 sizing twins: per-chip opt-state bytes on dp x tp=2
        # with the ZeRO overlay on ("zero") vs forced replicated ("repl")
        print(json.dumps(zero_opt_state_bytes(child.endswith("_zero"))))
        return
    if child.startswith("ppbytes_"):
        # r23 residency sizing twins: per-chip param/opt-state bytes on
        # dp x pp=2 with per-stage residency on ("staged") vs the r22
        # replicated-over-pp layout ("repl").  Same virtual-device seam
        # as the pp_ rungs: the sizing needs a 4-chip mesh.
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8")
        print(json.dumps(pp_residency_bytes(child.endswith("_staged"))))
        return
    if child == "eval_tf":
        print(json.dumps(timed_eval("transformer", 256, 256, tf_steps)))
        return
    if child == "eval_resnet":
        print(json.dumps(timed_eval("resnet", bs, 0, steps)))
        return
    if child.startswith("quant_"):
        # r13 quantized-training A/B arm: one precision (off|int8|fp8)
        # at one cell per child process, interleaved by the parent.
        # "off" is the bf16 baseline measured through the SAME child
        # path so the pair shares every other variable.
        _, fmt, cbs, cseq = child.split("_")
        if fmt == "e5m2grad":
            # r19 FP8-LM completion arm: fp8-E4M3 forward + fp8-E5M2
            # JIT-scaled gradient quantization with the quantized
            # dW/dx GEMMs — the A/B twin of the plain fp8 arm
            os.environ["FDT_BENCH_TF_QUANT"] = "fp8"
            os.environ["FDT_BENCH_TF_QUANT_GRAD"] = "fp8_e5m2"
        elif fmt != "off":
            os.environ["FDT_BENCH_TF_QUANT"] = fmt
        print(json.dumps(timed_transformer(int(cbs), int(cseq), tf_steps)))
        return
    if child.startswith("tpk_"):
        # r19 shard_map kernel-layer A/B: one (kernel, mode) cell per
        # child on a dp x tp=2 mesh — mode "kernel" runs the recovered
        # per-shard kernel through parallel/kernel_shard.py, mode
        # "fallback" forces the pre-r19 warned reroute with
        # FDT_KERNEL_SHARD=0 (the layer's kill switch IS the A/B arm).
        import warnings as _w

        import jax as _jax
        _, kern, mode = child.split("_")
        n_dev = _jax.device_count()
        if n_dev < 2:
            print(json.dumps({"skipped": f"tp=2 arm needs >=2 chips, "
                                         f"host exposes {n_dev}"}))
            return
        if kern == "ffn" and _jax.default_backend() != "tpu":
            # off-TPU the fused-FFN kernel runs in Pallas INTERPRET mode
            # (orders of magnitude slower) — the cell would measure the
            # interpreter, not the kernel; read this pair on TPU
            print(json.dumps({"skipped": "ffn kernel cell is TPU-only "
                                         "(interpret mode off-TPU)"}))
            return
        dp = max(1, min(n_dev // 2, 256))
        while 256 % dp:
            dp -= 1
        os.environ["FDT_BENCH_TF_MESH"] = f"dp={dp},tp=2"
        if mode == "fallback":
            os.environ["FDT_KERNEL_SHARD"] = "0"
        if kern == "flash":
            os.environ["FDT_BENCH_TF_ATTN"] = "flash"
        elif kern == "ffn":
            os.environ["FDT_BENCH_TF_FFN"] = "pallas"
        elif kern == "quant":
            os.environ["FDT_BENCH_TF_QUANT"] = "int8"
        rsteps = int(os.environ.get("FDT_BENCH_ROUTE_STEPS", "10"))
        with _w.catch_warnings():
            _w.simplefilter("ignore")   # the fallback arm warns by design
            print(json.dumps(timed_transformer(256, 256, rsteps)))
        return
    if child == "ab_ln_256_256":
        # tentpole A/B arm: LayerNorm saved-stats VJP OFF (r5 behavior)
        os.environ["FDT_LN_SAVED_STATS"] = "0"
        print(json.dumps(timed_transformer(256, 256, tf_steps)))
        return
    if child == "ab_flashstats_64_512":
        # tentpole A/B arm: flash saved-(out,lse) backward OFF (r5
        # in-kernel-recompute backward)
        os.environ["FDT_FLASH_SAVE_STATS"] = "0"
        print(json.dumps(timed_transformer(64, 512, tf_steps)))
        return

    n_chips = max(jax.device_count(), 1)
    elapsed, mem, state_bytes = timed_resnet(True, bs, steps)
    ips_per_chip = bs * steps / elapsed / n_chips
    # vs_baseline: ratio against FDT_BENCH_BASELINE (img/s/chip) when set;
    # 1.0 otherwise = "no external baseline configured" — the absolute value
    # is the tracked metric (the reference publishes no absolute throughput).
    vs = (ips_per_chip / BASELINE_REF_IPS) if BASELINE_REF_IPS else 1.0
    record = {
        "metric": "resnet50_cifar10_train_images_per_sec_per_chip_bs%d" % bs,
        "value": round(ips_per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(vs, 3),
        "baseline_configured": bool(BASELINE_REF_IPS),
        # VERDICT r4 #7: make the missing denominator self-explanatory
        # where readers look, instead of leaving `false` as an apparent
        # omission.
        "baseline_note": (
            "the reference publishes no absolute throughput (its README "
            "reports unitless relative-time figures only, "
            "/root/reference/README.md:56-73); set FDT_BENCH_BASELINE "
            "(img/s/chip) to wire an external denominator in — until "
            "then the absolute value above is the tracked metric and "
            "the `regressions` field guards it round-over-round"),
    }
    if mem:
        record["compiled_peak_mem_bytes"] = int(mem)
    # HBM attribution of the primary program's train state (ISSUE 11
    # satellite seeding ROADMAP's ZeRO item): opt_state_bytes_per_chip is
    # the number the optimizer-state sharding win will be measured
    # against — today's record IS the replicated baseline (the TP overlay
    # covers params only, so opt state holds full size on every chip of a
    # model axis).  params_bytes_per_chip beside it gives the ratio.
    record["params_bytes_per_chip"] = int(
        state_bytes["params_bytes_per_chip"])
    record["opt_state_bytes_per_chip"] = int(
        state_bytes["opt_state_bytes_per_chip"])
    record["bench_unix_time"] = round(time.time(), 1)

    if os.environ.get("FDT_BENCH_FAST") != "1":
        # VERDICT r4 #2a: the % alone is ambiguous across rounds
        # (re-basing the denominator moves it) — always publish the
        # absolute per-step times of BOTH arms beside it.  The NGD arm's
        # time is local; it must not vanish if the SGD child dies.
        record["resnet_ngd_step_ms"] = round(elapsed / steps * 1e3, 2)
        sgd = _run_child("resnet_sgd")
        if sgd:
            record["resnet_sgd_step_ms"] = round(
                sgd["elapsed"] / steps * 1e3, 2)
            record["ngd_overhead_pct"] = round(
                (elapsed - sgd["elapsed"]) / sgd["elapsed"] * 100.0, 1)
        peak, peak_src = device_peak_tflops()
        record["peak_tflops_assumed"] = peak
        record["peak_tflops_source"] = peak_src
        # Roofline fields (VERDICT r2 #1): model FLOPs per step (analytic
        # matmul count), achieved TFLOP/s, MFU vs the chip's bf16 peak,
        # plus XLA's own cost analysis and the compiled peak memory.
        # tfr_256_512 is the remat capacity point (VERDICT r2 #2): the
        # same config with layer checkpointing, showing the memory delta.
        # VERDICT r5 #2: the four flagged bs64/seq512 + tricks-transformer
        # moves get resolved by MEASUREMENT, not prose — N interleaved
        # re-runs of both arms on the same chip (alternating children so
        # drift decorrelates), median published as the tracked value, the
        # observed range published beside it as *_noise_band_pct, and the
        # guard threshold for these metrics derived from that band
        # (_find_regressions).  FDT_BENCH_REPEATS overrides N.
        def _median_run(runs):
            runs = sorted(runs, key=lambda r: r["elapsed"])
            return runs[len(runs) // 2]

        def _band_pct(runs):
            es = sorted(r["elapsed"] for r in runs)
            med = es[len(es) // 2]
            if len(es) < 2 or not med:
                return 0.0
            return round((es[-1] - es[0]) / med * 100.0, 1)

        reps = max(1, int(os.environ.get("FDT_BENCH_REPEATS", "5")))
        tf64_runs, tricks_tf_runs = [], []
        for _ in range(reps):
            r = _run_child("tf_64_512")
            if r:
                tf64_runs.append(r)
            t = _run_child("tricks_tf")
            if t:
                tricks_tf_runs.append(t)

        tf64_elapsed = None
        for tag, cbs, cseq in (("tf", 256, 256), ("tf", 64, 512),
                               ("tf", 256, 512), ("tfr", 256, 512)):
            if (tag, cbs, cseq) == ("tf", 64, 512):
                if not tf64_runs:
                    continue
                res = _median_run(tf64_runs)
                tf64_elapsed = res["elapsed"]
            else:
                res = _run_child(f"{tag}_{cbs}_{cseq}")
                if not res:
                    continue
            name = f"bs{cbs}_seq{cseq}" + ("_remat" if tag == "tfr" else "")
            exs = cbs * tf_steps / res["elapsed"] / n_chips
            if tag == "tf" and (cbs, cseq) in ((256, 256), (64, 512)):
                # round-over-round tracked keys, unchanged names
                record[f"transformer_agnews_ex_per_sec_{name}"] = round(exs, 1)
            else:
                record[f"transformer_ex_per_sec_{name}"] = round(exs, 1)
            mf = transformer_model_flops(cbs, cseq)
            step_s = res["elapsed"] / tf_steps
            record[f"transformer_{name}_step_ms"] = round(step_s * 1e3, 2)
            # per-chip: the step is sharded over all visible chips, so
            # achieved TFLOP/s and MFU are divided by the chip count to
            # compare against ONE chip's peak
            tflops = mf / step_s / 1e12 / n_chips
            record[f"transformer_{name}_model_tflops_per_step"] = round(
                mf / 1e12, 3)
            record[f"transformer_{name}_achieved_tflops_per_chip"] = round(
                tflops, 1)
            record[f"transformer_{name}_mfu_pct"] = round(
                100.0 * tflops / peak, 1)
            if "compiled_peak_mem_bytes" in res:
                record[f"transformer_{name}_peak_mem_bytes"] = (
                    res["compiled_peak_mem_bytes"])
            if "xla_bytes_accessed_per_step" in res:
                record[f"transformer_{name}_xla_gb_per_step"] = round(
                    res["xla_bytes_accessed_per_step"] / 1e9, 2)
            if "remat_policy" in res:
                record[f"transformer_{name}_policy"] = res["remat_policy"]
            if (tag, cbs, cseq) == ("tf", 64, 512) and len(tf64_runs) > 1:
                band64 = _band_pct(tf64_runs)
                record["transformer_bs64_seq512_repeats"] = len(tf64_runs)
                for kk in (f"transformer_agnews_ex_per_sec_{name}",
                           f"transformer_{name}_achieved_tflops_per_chip",
                           f"transformer_{name}_mfu_pct"):
                    record[kk + "_noise_band_pct"] = band64
        # GEMM-chain ceiling (VERDICT r4 #1): the step's matmul shapes as
        # a bare jitted chain — the measured MXU ceiling the step MFU is
        # judged against (see timed_gemm_ceiling).
        for cbs, cseq in ((256, 256), (64, 512)):
            res = _run_child(f"gemm_{cbs}_{cseq}")
            if res:
                # single-chip by construction (no mesh — the chain runs
                # on device 0), so NOT divided by n_chips
                ceiling = res["gemm_ceiling_tflops"]
                record[f"transformer_gemm_ceiling_tflops_bs{cbs}_seq{cseq}"] \
                    = round(ceiling, 1)
                record[f"transformer_gemm_ceiling_mfu_pct_bs{cbs}_seq{cseq}"] \
                    = round(100.0 * ceiling / peak, 1)
        # Bag-of-tricks end-to-end ablation (VERDICT r3 #1/#2): the same
        # train step with EVERY speed lever disabled (resolve_tricks:
        # fp32, dense attention, naive MLP, unfused QKV, autodiff
        # conv+BN, threefry nn.Dropout) vs the default stack — the
        # analog of the reference's headline ~2.5x figure
        # (/root/reference/README.md:63, figures/time.png).
        off_r = _run_child("tricks_resnet")
        if off_r:
            record["tricks_speedup_resnet50"] = round(
                off_r["elapsed"] / elapsed, 2)
        if tricks_tf_runs and tf64_elapsed:
            # both arms already measured N times interleaved above; the
            # ratio uses the medians, and the published band is the sum
            # of both arms' observed ranges (conservative)
            off_med = _median_run(tricks_tf_runs)["elapsed"]
            record["tricks_speedup_transformer"] = round(
                off_med / tf64_elapsed, 2)
            # the headline analog: the reference's time.png measures the
            # transformer workload at maxlen 512, 64 examples per device
            record["tricks_speedup_x"] = record["tricks_speedup_transformer"]
            if len(tricks_tf_runs) > 1 and len(tf64_runs) > 1:
                band = round(_band_pct(tricks_tf_runs)
                             + _band_pct(tf64_runs), 1)
                record["tricks_speedup_transformer_noise_band_pct"] = band
                record["tricks_speedup_x_noise_band_pct"] = band
        # VERDICT r4 #2b: two DEFINITIONS circulate — the bench keys above
        # are RAW COMPILED STEP ratios (loader/H2D excluded); the
        # figures/tricks_times.json epoch runs are FULL PIPELINE.  Say so
        # in-record, and surface the full-pipeline numbers beside them.
        record["tricks_speedup_definition"] = (
            "tricks_speedup_{resnet50,transformer,x}: raw compiled "
            "train-step time ratio (synthetic device-resident data); "
            "*_fullpipeline: steady-state epoch-time ratio incl. loader/"
            "augmentation/H2D (scripts/bag_of_tricks.py, "
            "figures/tricks_times.json)")
        try:
            with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                   "figures", "tricks_times.json")) as fh:
                tt = json.load(fh)
            for arm in ("resnet50", "transformer"):
                on = tt.get(f"{arm}_on", [])[1:]
                off = tt.get(f"{arm}_off", [])[1:]
                if on and off:
                    record[f"tricks_speedup_{arm}_fullpipeline"] = round(
                        (sum(off) / len(off)) / (sum(on) / len(on)), 2)
        except Exception:
            pass
        # 2D dense/flash crossover arms (VERDICT r5 #5): both impls at
        # every cell the routing surface newly serves, as full NGD train
        # steps — resolve_attention's surface comment cites these fields
        # per cell (cli._ATTN_ROUTE_SURFACE).  bs1024/seq256's dense arm
        # is deliberately NOT run: the materialized probs (6.4 GB) exceed
        # the routing memory budget, which is exactly why that cell
        # routes flash.  Opt out with FDT_BENCH_ROUTE=0.
        if os.environ.get("FDT_BENCH_ROUTE", "1") != "0":
            rsteps = int(os.environ.get("FDT_BENCH_ROUTE_STEPS", "10"))
            for cbs, cseq, impls in ATTN_ROUTE_BENCH_CELLS:
                for impl in impls:
                    res = _run_child(f"route_{cbs}_{cseq}_{impl}")
                    if res:
                        record[f"attn_route_bs{cbs}_seq{cseq}_{impl}"
                               f"_step_ms"] = round(
                            res["elapsed"] / rsteps * 1e3, 2)
            record["attn_route_bs1024_seq256_dense_note"] = (
                "dense arm deliberately not run: 3*4*B*H*L^2 = 6.4 GB of "
                "materialized probs exceeds the routing memory budget "
                "(cli._dense_attn_fits, default FDT_DENSE_ATTN_BUDGET_MB="
                "4096) — the cell routes flash by the headroom bound")
        # Tentpole attribution arms (VERDICT r5 #3/#4): the same train
        # program with ONE lever restored to its r5 behavior, so the
        # committed record carries each change's measured step-time
        # delta in-record (the per-arm transformer_*_step_ms fields
        # above are the ON side of each pair):
        #   ln_autodiff — LayerNorm under default XLA autodiff instead
        #     of the saved-(mean, rstd) custom_vjp (FDT_LN_SAVED_STATS=0)
        #     at bs256/seq256, the 13-site LN-cost shape;
        #   flash_recompute — the r5 in-kernel-recompute flash backward
        #     instead of the saved-stats pair (FDT_FLASH_SAVE_STATS=0)
        #     at bs64/seq512, the flash-routed shape.
        ab = _run_child("ab_ln_256_256")
        if ab:
            record["transformer_bs256_seq256_ln_autodiff_step_ms"] = round(
                ab["elapsed"] / tf_steps * 1e3, 2)
        ab = _run_child("ab_flashstats_64_512")
        if ab:
            record["transformer_bs64_seq512_flash_recompute_step_ms"] = \
                round(ab["elapsed"] / tf_steps * 1e3, 2)
        # Checkpoint-save overhead (r7 resilience arm): the async manager
        # must leave the step critical path — tracked claim: async median
        # step time within 1% of checkpointing-off (the sync arm shows
        # what the background write saves).  Opt out: FDT_BENCH_CKPT=0.
        if os.environ.get("FDT_BENCH_CKPT", "1") != "0":
            ck = {m: _run_child(f"ckpt_{m}") for m in ("off", "async",
                                                       "sync",
                                                       "async_sharded")}
            for m, r in ck.items():
                if r:
                    record[f"ckpt_{m}_median_step_ms"] = r["median_step_ms"]
                    record[f"ckpt_{m}_mean_step_ms"] = r["mean_step_ms"]
                    if "blocking_ms_per_save" in r:
                        record[f"ckpt_{m}_blocking_ms_per_save"] = (
                            r["blocking_ms_per_save"])
            # overhead published under BOTH definitions: *_overhead_pct
            # compares medians (steady-state step; the ISSUE's tracked
            # <1% claim) and *_amortized_overhead_pct compares means
            # (includes the save ticks — the honest total-cost number;
            # the sync arm's amortized value shows what the background
            # write saves)
            # ckpt_async_sharded_overhead_pct (r9 tentpole arm): the
            # per-host shard-streaming save — the path every host of a
            # pod takes now that the sync-collective fallback is gone —
            # must leave the critical path like the single-host async
            # one; its blocking part is the addressable-shard fetch.
            for m in ("async", "sync", "async_sharded"):
                if ck.get("off") and ck.get(m):
                    record[f"ckpt_{m}_overhead_pct"] = round(
                        (ck[m]["median_step_ms"]
                         - ck["off"]["median_step_ms"])
                        / ck["off"]["median_step_ms"] * 100.0, 2)
                    record[f"ckpt_{m}_amortized_overhead_pct"] = round(
                        (ck[m]["mean_step_ms"] - ck["off"]["mean_step_ms"])
                        / ck["off"]["mean_step_ms"] * 100.0, 2)
            # Restart MTTR (redefined r17 — see timed_restart_mttr):
            # crash + COLD process relaunch, MTTR = restore + full
            # program recompile, split into its components.  The old
            # in-process supervised number (which keeps compiled
            # programs alive and, post backoff-fix, reduces to
            # restore_s) lives on in every supervised run's goodput
            # summary; detect/backoff publish 0.0 here by scenario
            # (platform relaunch + immediate first restart).
            mt = _run_child("restart_mttr")
            if mt and mt.get("restores"):
                record["restart_mttr_s"] = mt["mttr_s"]
                record["restart_mttr_restore_s"] = mt["restore_s"]
                record["restart_mttr_compile_s"] = mt["compile_s"]
                record["restart_mttr_backoff_s"] = mt["backoff_s"]
                record["restart_mttr_detect_s"] = mt["detect_s"]
            # ...and the executable-cache twin (r17 tentpole A/B): the
            # SAME relaunch with --executable_cache on — programs
            # deserialize (cache_source=deserialized) instead of
            # recompiling; restart_cached_mttr_s < restart_mttr_s is
            # the committed win.
            cmt = _run_child("restart_cached_mttr")
            if cmt and cmt.get("restores"):
                record["restart_cached_mttr_s"] = cmt["mttr_s"]
                record["restart_cached_mttr_restore_s"] = cmt["restore_s"]
                record["restart_cached_mttr_compile_s"] = cmt["compile_s"]
                srcs = [s for v in cmt.get("cache_sources", {}).values()
                        for s in v]
                record["restart_cached_deserialized_programs"] = sum(
                    1 for s in srcs if s == "deserialized")
            # Warm-spare swap (r17 tentpole arm): a parked spare claims
            # a killed slice's seat — swap wall time (claim->release)
            # and the survivor's hold; the headline awaits real TPU
            # hardware, but the arm commits the machinery's number.
            ws = _run_child("warm_spare")
            if ws and ws.get("swaps"):
                record["warm_spare_swap_s"] = ws["warm_spare_swap_s"]
                record["warm_spare_hold_s"] = ws["warm_spare_hold_s"]
            # Slice-recovery MTTR (r14 elastic-recovery arm): one
            # slice killed and RE-ADMITTED while the other holds —
            # detect + hold + restore per readmission (see
            # timed_restart_slice_mttr); the whole-pod backoff and the
            # survivor's rollback replay are exactly the costs this
            # path removes, so the two headlines are directly
            # comparable.
            smt = _run_child("restart_slice_mttr")
            if smt and smt.get("readmissions"):
                record["restart_slice_mttr_s"] = smt["restart_slice_mttr_s"]
                record["restart_slice_mttr_detect_s"] = smt["detect_s"]
                record["restart_slice_mttr_hold_s"] = smt["hold_s"]
                record["restart_slice_mttr_restore_s"] = smt["restore_s"]
        # Serving arm family (r16 serve/ tentpole): p50/p99 request
        # latency + sustained qps/chip through the REAL serving stack
        # (continuous-batching queue, AOT-warmed per-bucket programs, 2
        # replicas) at three batch/length mixes; the ragged mix is the
        # headline (serve_p50_ms / serve_p99_ms / serve_qps_per_chip in
        # essentials).  CPU-container numbers measure the batching/
        # dispatch machinery — real-TPU latency lands when the driver's
        # TPU bench does.  Opt out: FDT_BENCH_SERVE=0.
        if os.environ.get("FDT_BENCH_SERVE", "1") != "0":
            for mix, _lo, _hi in SERVE_BENCH_MIXES:
                r = _run_child(f"serve_{mix}")
                if r and r.get("requests"):
                    record[f"serve_{mix}_p50_ms"] = r["p50_ms"]
                    record[f"serve_{mix}_p99_ms"] = r["p99_ms"]
                    record[f"serve_{mix}_qps_per_chip"] = r["qps_per_chip"]
            if "serve_ragged_p50_ms" in record:
                record["serve_p50_ms"] = record["serve_ragged_p50_ms"]
                record["serve_p99_ms"] = record["serve_ragged_p99_ms"]
                record["serve_qps_per_chip"] = \
                    record["serve_ragged_qps_per_chip"]
        # Decode-serving arm family (r21 serve/decode tentpole):
        # autoregressive generation through the REAL decode stack —
        # paged KV cache, AOT prefill + decode-step program families,
        # token-granular continuous batching.  The closed-loop child
        # publishes TTFT percentiles + decode_tokens_per_sec_per_chip,
        # measured N INTERLEAVED with the open-loop sustained child (r6
        # noise protocol: alternating children so drift decorrelates)
        # so the throughput headline carries a measured band; the
        # sustained child paces submissions at FDT_BENCH_DECODE_QPS and
        # publishes decode_slo_violation_pct — a closed loop
        # self-throttles, so queueing failure only ever shows open
        # loop.  Opt out: FDT_BENCH_DECODE=0.
        if os.environ.get("FDT_BENCH_DECODE", "1") != "0":
            dreps = max(1, int(os.environ.get("FDT_BENCH_DECODE_REPEATS",
                                              "3")))
            dg_runs, ds_runs = [], []
            for _ in range(dreps):
                r = _run_child("decode_gen")
                if r and r.get("requests"):
                    dg_runs.append(r)
                r = _run_child("decode_sustained")
                if r and r.get("requests"):
                    ds_runs.append(r)

            def _decode_med(key, rs):
                vs = sorted(r[key] for r in rs if key in r)
                return vs[len(vs) // 2] if vs else None

            if dg_runs:
                tps = sorted(r["tokens_per_sec_per_chip"]
                             for r in dg_runs)
                med = tps[len(tps) // 2]
                record["decode_tokens_per_sec_per_chip"] = med
                if len(tps) > 1 and med:
                    record["decode_tokens_per_sec_per_chip"
                           "_noise_band_pct"] = round(
                        (tps[-1] - tps[0]) / med * 100.0, 1)
                record["decode_ttft_p50_ms"] = _decode_med("ttft_p50_ms",
                                                           dg_runs)
                record["decode_ttft_p99_ms"] = _decode_med("ttft_p99_ms",
                                                           dg_runs)
            if ds_runs:
                record["decode_slo_violation_pct"] = _decode_med(
                    "slo_violation_pct", ds_runs)
                record["decode_target_qps"] = ds_runs[0]["target_qps"]
                record["decode_slo_ms"] = ds_runs[0]["slo_ms"]
        # Telemetry-overhead arm (r12 observability tentpole): the
        # per-dispatch recorder must be free — on-vs-off measured N>=5
        # times INTERLEAVED (the r6 noise protocol: alternating children
        # so drift decorrelates), medians published with their observed
        # noise bands, and telemetry_overhead_pct held <1% by the guard
        # (_ABS_PP_WORSE_IF_UP).  The off arm is exactly what
        # FDT_TELEMETRY=0 / --no_telemetry buys.  Opt out:
        # FDT_BENCH_TELEM=0.
        if os.environ.get("FDT_BENCH_TELEM", "1") != "0":
            treps = max(1, int(os.environ.get("FDT_BENCH_TELEM_REPEATS",
                                              "5")))
            t_runs = {"on": [], "off": []}
            for _ in range(treps):
                for m in ("on", "off"):
                    r = _run_child(f"telem_{m}")
                    if r:
                        t_runs[m].append(r)

            def _telem_med_band(name, rs):
                if not rs:
                    return None
                ms = sorted(r["median_step_ms"] for r in rs)
                med = ms[len(ms) // 2]
                record[name] = med
                if len(ms) > 1 and med:
                    record[name + "_noise_band_pct"] = round(
                        (ms[-1] - ms[0]) / med * 100.0, 1)
                return med

            t_on = _telem_med_band("telem_on_median_step_ms",
                                   t_runs["on"])
            t_off = _telem_med_band("telem_off_median_step_ms",
                                    t_runs["off"])
            if t_on and t_off:
                record["telemetry_overhead_pct"] = round(
                    (t_on - t_off) / t_off * 100.0, 2)
        # Sentinel-overhead arm (r24 robustness tentpole): the in-graph
        # bad-step guard + host spike detector must be near-free — on
        # (--sentinel full's per-dispatch cost: fused finiteness
        # reduction + update select in-graph, median/MAD arithmetic on
        # host) vs off (--sentinel none, byte-identical HLO to
        # pre-sentinel) measured N>=5 times INTERLEAVED per the r6
        # noise protocol, sentinel_overhead_pct held <1% by the guard
        # (_ABS_PP_WORSE_IF_UP).  Opt out: FDT_BENCH_SENTINEL=0.
        if os.environ.get("FDT_BENCH_SENTINEL", "1") != "0":
            sreps = max(1, int(os.environ.get(
                "FDT_BENCH_SENTINEL_REPEATS", "5")))
            s_runs = {"on": [], "off": []}
            for _ in range(sreps):
                for m in ("on", "off"):
                    r = _run_child(f"sentinel_{m}")
                    if r:
                        s_runs[m].append(r)

            def _sent_med_band(name, rs):
                if not rs:
                    return None
                ms = sorted(r["median_step_ms"] for r in rs)
                med = ms[len(ms) // 2]
                record[name] = med
                if len(ms) > 1 and med:
                    record[name + "_noise_band_pct"] = round(
                        (ms[-1] - ms[0]) / med * 100.0, 1)
                return med

            s_on = _sent_med_band("sentinel_on_median_step_ms",
                                  s_runs["on"])
            s_off = _sent_med_band("sentinel_off_median_step_ms",
                                   s_runs["off"])
            if s_on and s_off:
                record["sentinel_overhead_pct"] = round(
                    (s_on - s_off) / s_off * 100.0, 2)
        # Quantized-training A/B arms (r13 tentpole): the bs256/seq256
        # NGD train step with the attention-projection + FFN forward
        # GEMMs at int8 / fp8-E4M3 delayed scaling vs the bf16 baseline
        # measured through the SAME child path, N>=5 INTERLEAVED per
        # the r6 noise protocol (medians + *_noise_band_pct feeding the
        # guard thresholds).  Roofline variants judge the quantized
        # arms against the LOW-PRECISION MXU peak (~2x bf16 on TPU;
        # FDT_QUANT_PEAK_TFLOPS overrides) — the ceiling the ROADMAP
        # MFU item says quantization raises.  Opt out: FDT_BENCH_QUANT=0.
        if os.environ.get("FDT_BENCH_QUANT", "1") != "0":
            qreps = max(1, int(os.environ.get("FDT_BENCH_QUANT_REPEATS",
                                              "5")))
            # e5m2grad (r19): the fp8 arm + --quant_grad fp8_e5m2 — its
            # A/B twin is the plain fp8 arm in the same interleaved set
            q_runs = {m: [] for m in ("off", "int8", "fp8", "e5m2grad")}
            for _ in range(qreps):
                for m in q_runs:
                    r = _run_child(f"quant_{m}_256_256")
                    if r:
                        q_runs[m].append(r)
            qpeak = float(os.environ.get("FDT_QUANT_PEAK_TFLOPS", "0")
                          or 0) or 2.0 * peak
            record["quant_peak_tflops_assumed"] = round(qpeak, 1)
            mf_q = transformer_model_flops(256, 256)
            for m, rs in q_runs.items():
                if not rs:
                    continue
                ms = sorted(r["elapsed"] / tf_steps * 1e3 for r in rs)
                med = ms[len(ms) // 2]
                tag = {"off": "quant_off",
                       "e5m2grad": "fp8_e5m2_grad"}.get(m, m)
                key = f"transformer_bs256_seq256_{tag}_step_ms"
                record[key] = round(med, 2)
                if len(ms) > 1 and med:
                    record[key + "_noise_band_pct"] = round(
                        (ms[-1] - ms[0]) / med * 100.0, 1)
                if m in ("int8", "fp8"):
                    # quantized roofline: achieved TFLOP/s at the SAME
                    # analytic FLOP count, MFU vs the low-precision peak
                    # (the e5m2grad arm reads against its fp8 twin's
                    # step_ms instead — same forward, quantized backward)
                    tflops = mf_q / (med / 1e3) / 1e12 / n_chips
                    record[f"transformer_bs256_seq256_{m}"
                           f"_achieved_tflops_per_chip"] = round(tflops, 1)
                    record[f"transformer_bs256_seq256_{m}_mfu_pct"] = \
                        round(100.0 * tflops / qpeak, 1)
        # tp-mesh kernel A/B arms (r19 tentpole): the bs256/seq256 NGD
        # train step on a dp x tp=2 mesh, each recovered kernel measured
        # kernel-via-shard_map vs forced fallback (FDT_KERNEL_SHARD=0 —
        # the layer's kill switch IS the off arm), N>=3 INTERLEAVED per
        # the r6 noise protocol.  On this CPU container the pairs
        # measure the routing/collective machinery (flash runs its
        # blockwise twin per shard, quant the reference GEMMs); the
        # kernel-side wins land with the first live TPU bench — the ffn
        # cell is TPU-only (interpret mode would measure the
        # interpreter).  Opt out: FDT_BENCH_TPK=0.
        if os.environ.get("FDT_BENCH_TPK", "1") != "0":
            treps = max(1, int(os.environ.get("FDT_BENCH_TPK_REPEATS",
                                              "3")))
            rsteps = int(os.environ.get("FDT_BENCH_ROUTE_STEPS", "10"))
            tpk_runs = {(kern, mode): []
                        for kern in ("flash", "ffn", "quant")
                        for mode in ("kernel", "fallback")}
            for _ in range(treps):
                for (kern, mode) in tpk_runs:
                    r = _run_child(f"tpk_{kern}_{mode}")
                    if r and "elapsed" in r:
                        tpk_runs[(kern, mode)].append(r)
            for (kern, mode), rs in tpk_runs.items():
                if not rs:
                    continue
                ms = sorted(r["elapsed"] / rsteps * 1e3 for r in rs)
                med = ms[len(ms) // 2]
                key = f"transformer_tp2_{kern}_{mode}_step_ms"
                record[key] = round(med, 2)
                if len(ms) > 1 and med:
                    record[key + "_noise_band_pct"] = round(
                        (ms[-1] - ms[0]) / med * 100.0, 1)
        # K-step fused dispatch ladder + data-path A/B (r8 tentpole):
        # per-step time at K in {1, 4, 16} on the device-resident path
        # for both workloads, and the host-vs-resident input-pipeline
        # A/B at K=1.  Measured N times INTERLEAVED (r6 noise protocol):
        # medians published, observed range beside them as
        # *_noise_band_pct feeding the regression guard's thresholds.
        # Opt out with FDT_BENCH_KDIS=0.
        if os.environ.get("FDT_BENCH_KDIS", "1") != "0":
            def _k_name(m, kk):
                return (f"transformer_bs256_seq256_k{kk}_step_ms"
                        if m == "tf" else f"resnet_bs512_k{kk}_step_ms")

            reps = max(1, int(os.environ.get("FDT_BENCH_K_REPEATS", "3")))
            arms = [("tf", kk) for kk in (1, 4, 16)] \
                + [("rn", kk) for kk in (1, 4, 16)]
            k_runs = {a: [] for a in arms}
            dp_runs = {p: [] for p in ("host", "resident", "stream")}
            for _ in range(reps):
                for m, kk in arms:
                    r = _run_child(f"kdis_{m}_{kk}")
                    if r:
                        k_runs[(m, kk)].append(r)
                for p in dp_runs:
                    r = _run_child(f"datapath_{p}")
                    if r:
                        dp_runs[p].append(r)

            def _publish(name, rs):
                if not rs:
                    return
                ms = sorted(r["elapsed"] / r["steps_timed"] * 1e3
                            for r in rs)
                med = ms[len(ms) // 2]
                record[name] = round(med, 3)
                if len(ms) > 1 and med:
                    record[name + "_noise_band_pct"] = round(
                        (ms[-1] - ms[0]) / med * 100.0, 1)

            for (m, kk), rs in k_runs.items():
                _publish(_k_name(m, kk), rs)
            for p, rs in dp_runs.items():
                _publish(f"data_path_{p}_step_ms", rs)
            # r18 streaming tier: steady-state stall fraction (median
            # over the interleaved reps) — the <1% acceptance number
            pcts = sorted(100.0 * r["stall_s"] / r["elapsed"]
                          for r in dp_runs["stream"]
                          if r.get("elapsed") and "stall_s" in r)
            if pcts:
                record["stream_stall_pct"] = round(pcts[len(pcts) // 2], 2)
            # ISSUE 16 ZeRO arms (opt out: FDT_BENCH_ZERO=0) — three
            # pieces: (a) dp x tp=2 sizing twins for the tentpole's
            # headline (post-ZeRO opt_state_bytes_per_chip vs the forced-
            # replicated twin, guard class bytes_per_chip); (b) the
            # overlap reduce-scatter A/B at K in {1,4}, N interleaved
            # with noise bands like every other *_step_ms pair; (c) the
            # single-run --offload_opt_state attribution probe.
            if os.environ.get("FDT_BENCH_ZERO", "1") != "0":
                zb = {m: _run_child(f"zerobytes_{m}")
                      for m in ("zero", "repl")}
                z, rp = zb["zero"], zb["repl"]
                if z and "opt_state_bytes_per_chip" in z:
                    record["opt_state_bytes_per_chip_tp2_zero"] = int(
                        z["opt_state_bytes_per_chip"])
                    record["params_bytes_per_chip_tp2"] = int(
                        z["params_bytes_per_chip"])
                elif z and "skipped" in z:
                    record["zero_bytes_note"] = z["skipped"]
                if rp and "opt_state_bytes_per_chip" in rp:
                    record["opt_state_bytes_per_chip_tp2_replicated"] = \
                        int(rp["opt_state_bytes_per_chip"])
                    if z and z.get("opt_state_bytes_per_chip"):
                        record["opt_state_zero_reduction_x"] = round(
                            rp["opt_state_bytes_per_chip"]
                            / z["opt_state_bytes_per_chip"], 2)
                ov_runs = {(mode, kk): [] for mode in ("on", "off")
                           for kk in (1, 4)}
                for _ in range(reps):
                    for (mode, kk) in ov_runs:
                        r = _run_child(f"kov_{mode}_{kk}")
                        if r and "elapsed" in r:
                            ov_runs[(mode, kk)].append(r)
                for (mode, kk), rs in ov_runs.items():
                    _publish(f"resnet_bs512_k{kk}_overlap_{mode}"
                             f"_step_ms", rs)
                r = _run_child("optoffload")
                if r and "elapsed" in r:
                    record["opt_offload_step_ms"] = round(
                        r["elapsed"] / r["steps_timed"] * 1e3, 3)
        # Pipeline weak-scaling ladder (r22 pp tentpole): simulated
        # pods of {1, 2, 4} slices (virtual host devices — the same
        # tier-1 simulation seam as restart_slice_mttr), pp = one
        # stage per slice, model depth grown with the slice count.
        # Ideal pipelining holds step time ~flat across the rungs;
        # the headline (largest) rung also publishes the executed
        # schedule's fill/drain bubble share (pipeline_bubble_pct,
        # guarded above) and the per-stage idle time it implies
        # (pp_stage_idle_ms = idle ticks x measured tick time).  CPU-
        # container rungs measure the rotation/collective machinery —
        # real-DCN numbers land with the first live multi-slice bench
        # (ROADMAP carryover).  Opt out: FDT_BENCH_PP=0.
        if os.environ.get("FDT_BENCH_PP", "1") != "0":
            for npp in (1, 2, 4):
                r = _run_child(f"pp_{npp}")
                if r and "elapsed" in r:
                    pp_ms = round(r["elapsed"] / r["steps_timed"] * 1e3, 3)
                    record[f"weak_scaling_slice{npp}_step_ms"] = pp_ms
                    if r.get("n_stages", 1) > 1:
                        record["pipeline_bubble_pct"] = r["bubble_pct"]
                        record["pp_n_stages"] = r["n_stages"]
                        record["pp_n_microbatches"] = r["n_microbatches"]
                        record["pp_stage_idle_ms"] = round(
                            pp_ms / r["n_ticks"] * r["stage_idle_ticks"],
                            3)
                elif r and r.get("skipped"):
                    # no silent caps: an unservable rung is recorded
                    record[f"pp_slice{npp}_note"] = r["skipped"]
            # r23 per-stage residency sizing twins (ISSUE 19 tentpole
            # headline): per-chip param + opt-state bytes on dp x pp=2
            # with stage-owned leaves sharded over pp vs the r22
            # replicated-over-pp layout — the zerobytes_ twin pattern.
            # Guard class bytes_per_chip (lower is better, 2% band).
            pb = {m: _run_child(f"ppbytes_{m}")
                  for m in ("staged", "repl")}
            st, rp = pb["staged"], pb["repl"]
            if st and "params_bytes_per_chip" in st:
                record["pp_param_bytes_per_chip_pp2_staged"] = int(
                    st["params_bytes_per_chip"])
                record["pp_opt_state_bytes_per_chip_pp2_staged"] = int(
                    st["opt_state_bytes_per_chip"])
            elif st and "skipped" in st:
                record["pp_residency_bytes_note"] = st["skipped"]
            if rp and "params_bytes_per_chip" in rp:
                record["pp_param_bytes_per_chip_pp2_replicated"] = int(
                    rp["params_bytes_per_chip"])
                record["pp_opt_state_bytes_per_chip_pp2_replicated"] = \
                    int(rp["opt_state_bytes_per_chip"])
                if st and st.get("params_bytes_per_chip"):
                    record["pp_param_residency_reduction_x"] = round(
                        rp["params_bytes_per_chip"]
                        / st["params_bytes_per_chip"], 2)
                if st and st.get("opt_state_bytes_per_chip"):
                    record["pp_opt_state_residency_reduction_x"] = round(
                        rp["opt_state_bytes_per_chip"]
                        / st["opt_state_bytes_per_chip"], 2)
        # Eval throughput under the guard (VERDICT r5 #7): the real
        # pad-and-mask eval step at each workload's headline shape.
        ev = _run_child("eval_resnet")
        if ev:
            record[f"resnet_eval_img_per_sec_bs{bs}"] = round(
                bs * steps / ev["elapsed"] / n_chips, 1)
        ev = _run_child("eval_tf")
        if ev:
            record["transformer_eval_ex_per_sec_bs256_seq256"] = round(
                256 * tf_steps / ev["elapsed"] / n_chips, 1)
        # Long-context attention ladder: DEFAULT-ON (VERDICT r3 #4 — the
        # driver runs plain `python bench.py`, so the envelope numbers
        # must land in BENCH_r*.json without hand-running).  Opt out with
        # FDT_BENCH_ATTN=0.
        if os.environ.get("FDT_BENCH_ATTN", "1") != "0":
            ladder = _run_child("attn_ladder")
            if ladder:
                record.update(ladder)
        # r11 2D-mesh attention arms: the ring/ulysses ladder variants
        # plus the sequence-parallel route cells (flash vs ring vs
        # ulysses as full NGD train steps), N>=5 INTERLEAVED re-runs —
        # medians published, observed range beside them as
        # *_noise_band_pct feeding the guard thresholds (the r6 noise
        # protocol).  These arms are what lets `_ATTN_ROUTE_SURFACE`'s
        # sp rows claim their cells with a measurement.  Opt out with
        # FDT_BENCH_ATTN2D=0; single-device hosts skip (nothing to
        # shard over) and say so in-record.
        if os.environ.get("FDT_BENCH_ATTN2D", "1") != "0":
            if jax.device_count() < 2:
                record["attn2d_note"] = (
                    "ring/ulysses ladder + route cells skipped: single-"
                    "device host (the sp strategies need >=2 chips)")
            else:
                reps2 = max(1, int(os.environ.get(
                    "FDT_BENCH_ATTN2D_REPEATS", "5")))
                rsteps2 = int(os.environ.get("FDT_BENCH_ROUTE_STEPS",
                                             "10"))
                lad_runs = {"ring": [], "ulysses": []}
                route2d_runs = {}
                for _ in range(reps2):
                    for impl in ("ring", "ulysses"):
                        r = _run_child(f"attn_ladder_{impl}")
                        if r:
                            lad_runs[impl].append(r)
                    for cbs, cseq, impls in ATTN_ROUTE_SP_BENCH_CELLS:
                        for impl in impls:
                            r = _run_child(f"route2d_{cbs}_{cseq}_{impl}")
                            if r and "elapsed" in r:
                                route2d_runs.setdefault(
                                    (cbs, cseq, impl), []).append(
                                    r["elapsed"] / rsteps2 * 1e3)
                            elif r and r.get("skipped"):
                                # no silent caps: an unservable cell is
                                # recorded, not just absent
                                record[f"attn_route_bs{cbs}_seq{cseq}"
                                       f"_{impl}_note"] = r["skipped"]

                def _med_band(name, ms):
                    ms = sorted(ms)
                    med = ms[len(ms) // 2]
                    record[name] = round(med, 2)
                    if len(ms) > 1 and med:
                        record[name + "_noise_band_pct"] = round(
                            (ms[-1] - ms[0]) / med * 100.0, 1)

                for impl, runs in lad_runs.items():
                    for k2 in sorted(set().union(
                            *(r.keys() for r in runs)) if runs else ()):
                        _med_band(k2, [r[k2] for r in runs if k2 in r])
                for (cbs, cseq, impl), ms in sorted(route2d_runs.items()):
                    _med_band(f"attn_route_bs{cbs}_seq{cseq}_{impl}"
                              f"_step_ms", ms)

    # Round-over-round regression guard (VERDICT r4 #2c): compare every
    # tracked numeric metric against the previous round's record and flag
    # wrong-way moves past each metric's noise threshold — no more
    # hand-diffing rounds.
    prev, prev_file = _prev_bench_record()
    if prev:
        record["regression_baseline_file"] = prev_file
        # missing-metric detection only when the full metric set ran —
        # intentional opt-outs (FDT_BENCH_FAST / FDT_BENCH_ATTN=0) must
        # not read as vanished metrics
        full_run = (os.environ.get("FDT_BENCH_FAST") != "1"
                    and os.environ.get("FDT_BENCH_ATTN", "1") != "0"
                    and os.environ.get("FDT_BENCH_ATTN2D", "1") != "0"
                    and os.environ.get("FDT_BENCH_ROUTE", "1") != "0"
                    and os.environ.get("FDT_BENCH_CKPT", "1") != "0"
                    and os.environ.get("FDT_BENCH_TELEM", "1") != "0"
                    and os.environ.get("FDT_BENCH_QUANT", "1") != "0"
                    and os.environ.get("FDT_BENCH_KDIS", "1") != "0"
                    and os.environ.get("FDT_BENCH_SERVE", "1") != "0"
                    and os.environ.get("FDT_BENCH_DECODE", "1") != "0"
                    and os.environ.get("FDT_BENCH_PP", "1") != "0")
        # r6/r7 standing-note follow-through: the A/B `*_step_ms` pairs
        # are only comparable against a LIVE record — the committed
        # baseline may still be the r5 `record_note` reconstruction,
        # which carries NO measured step-ms pairs worth judging against.
        live = _is_live_record(prev)
        if not live:
            msg = (f"[bench] baseline {prev_file} is the r5 record_note "
                   f"reconstruction, not a live record: *_step_ms A/B "
                   f"guard comparisons skipped — when a live TPU record "
                   f"lands, apply PARITY.md 'r6 A/B follow-up decision' "
                   f"(steps a-d: LN/flash-stats kill switches, route-"
                   f"cell flips, ckpt overhead) to its measured pairs")
            print(msg, file=sys.stderr)
            record["regression_baseline_note"] = msg[len("[bench] "):]
        record["regressions"] = _find_regressions(record, prev,
                                                  check_missing=full_run,
                                                  compare_step_ms=live)
    # Evidence chain (VERDICT r5 #1): persist the FULL record to a
    # committed file beside this script — the driver's 2 KB stdout tail
    # can never orphan a round's numbers again — and print a compact
    # essentials line LAST so that tail always carries the headline even
    # as the record grows.  FDT_BENCH_FAST smoke runs must NOT clobber
    # the committed full record (a near-empty fast record would become
    # the newest baseline and the guard would silently compare nothing).
    if os.environ.get("FDT_BENCH_FAST") != "1":
        try:
            with open(os.path.join(_bench_dir(), BENCH_LATEST), "w") as fh:
                json.dump(record, fh, indent=1)
                fh.write("\n")
        except OSError as e:
            print(f"[bench] could not write {BENCH_LATEST}: {e!r}",
                  file=sys.stderr)
    print(json.dumps(record))
    print(json.dumps(_essentials(record)))


def _essentials(record: dict) -> dict:
    """<=1.5 KB headline subset printed as the LAST stdout line: the
    driver's tail capture parses this even when the full record outgrows
    it; bench_unix_time ties it back to the full BENCH_LATEST.json."""
    keys = ("metric", "value", "unit", "ngd_overhead_pct",
            "transformer_agnews_ex_per_sec_bs256_seq256",
            "transformer_bs256_seq256_mfu_pct",
            "transformer_agnews_ex_per_sec_bs64_seq512",
            "transformer_bs64_seq512_mfu_pct",
            "transformer_bs64_seq512_mfu_pct_noise_band_pct",
            "transformer_eval_ex_per_sec_bs256_seq256",
            "params_bytes_per_chip", "opt_state_bytes_per_chip",
            "tricks_speedup_x", "ckpt_async_overhead_pct",
            "ckpt_async_amortized_overhead_pct",
            "ckpt_async_sharded_overhead_pct", "restart_mttr_s",
            "restart_mttr_compile_s", "restart_mttr_restore_s",
            "restart_cached_mttr_s", "restart_slice_mttr_s",
            "warm_spare_swap_s",
            "serve_p50_ms", "serve_p99_ms", "serve_qps_per_chip",
            "decode_tokens_per_sec_per_chip", "decode_ttft_p50_ms",
            "decode_ttft_p99_ms", "decode_slo_violation_pct",
            "telemetry_overhead_pct",
            "transformer_bs256_seq256_quant_off_step_ms",
            "transformer_bs256_seq256_int8_step_ms",
            "transformer_bs256_seq256_int8_step_ms_noise_band_pct",
            "transformer_bs256_seq256_fp8_step_ms",
            "transformer_bs256_seq256_int8_mfu_pct",
            "transformer_bs256_seq256_fp8_mfu_pct",
            "transformer_bs256_seq256_k1_step_ms",
            "transformer_bs256_seq256_k4_step_ms",
            "transformer_bs256_seq256_k16_step_ms",
            "transformer_bs256_seq256_k4_step_ms_noise_band_pct",
            "resnet_bs512_k1_step_ms", "resnet_bs512_k4_step_ms",
            "resnet_bs512_k16_step_ms",
            "opt_state_bytes_per_chip_tp2_zero",
            "opt_state_bytes_per_chip_tp2_replicated",
            "opt_state_zero_reduction_x",
            "resnet_bs512_k4_overlap_on_step_ms",
            "resnet_bs512_k4_overlap_off_step_ms",
            "opt_offload_step_ms",
            "data_path_host_step_ms", "data_path_resident_step_ms",
            "data_path_stream_step_ms", "stream_stall_pct",
            "weak_scaling_slice1_step_ms", "weak_scaling_slice2_step_ms",
            "weak_scaling_slice4_step_ms",
            "pipeline_bubble_pct", "pp_stage_idle_ms",
            "pp_param_bytes_per_chip_pp2_staged",
            "pp_param_bytes_per_chip_pp2_replicated",
            "pp_opt_state_bytes_per_chip_pp2_staged",
            "pp_opt_state_bytes_per_chip_pp2_replicated",
            "pp_param_residency_reduction_x",
            "pp_opt_state_residency_reduction_x",
            "bench_unix_time", "regression_baseline_file")
    ess = {"essentials": True, "full_record": BENCH_LATEST}
    for k in keys:
        if k in record:
            ess[k] = record[k]
    for k in record:
        if k.startswith("resnet_eval_img_per_sec"):
            ess[k] = record[k]
    regs = record.get("regressions")
    if regs is not None:
        ess["regressions_count"] = len(regs)
        ess["regressed_metrics"] = [r["metric"] for r in regs][:8]
    return ess


if __name__ == "__main__":
    main()
