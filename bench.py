"""Benchmark: ResNet-50/CIFAR-10 training throughput @ bs=1024 (BASELINE.json).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N}

Baseline: the reference publishes no absolute throughput (BASELINE.md).
`vs_baseline` is value / FDT_BENCH_BASELINE (img/s/chip) when that env var
is set; otherwise it is emitted as the constant 1.0 with
"baseline_configured": false — the absolute `value` is the tracked metric.
Synthetic data (device-resident) so the number measures the compiled train
step, not disk IO.  The batch is sharded over a dp mesh spanning every
visible chip, so value is genuine per-chip throughput on multi-chip hosts.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

# Reference proxy: 4xA100 aggregate throughput for ResNet-50/CIFAR-10 @
# bs=1024 with AMP+fusion is not published (BASELINE.md); the driver tracks
# our absolute number round-over-round. Overridable bookkeeping constant:
BASELINE_REF_IPS = float(os.environ.get("FDT_BENCH_BASELINE", "0") or 0)


def main() -> None:
    import jax
    import jax.numpy as jnp

    from faster_distributed_training_tpu.config import TrainConfig
    from faster_distributed_training_tpu.models import resnet50
    from faster_distributed_training_tpu.optim import build_optimizer
    from faster_distributed_training_tpu.parallel import make_mesh
    from faster_distributed_training_tpu.parallel.placement import (
        make_put_batch, shard_train_state)
    from faster_distributed_training_tpu.train import (create_train_state,
                                                       make_train_step)

    n_chips = jax.device_count()
    mesh = make_mesh(("dp",))  # batch sharded over every visible chip
    bs = int(os.environ.get("FDT_BENCH_BS", "1024"))
    steps = int(os.environ.get("FDT_BENCH_STEPS", "20"))

    cfg = TrainConfig(model="resnet50", batch_size=bs, alpha=0.2,
                      use_ngd=True, precision="bf16", epochs=1)
    model = resnet50(num_classes=10)
    tx, _ = build_optimizer(cfg, steps_per_epoch=steps)
    rng = jax.random.PRNGKey(cfg.seed)
    sample = jnp.zeros((bs, 32, 32, 3), jnp.float32)
    state = create_train_state(model, tx, sample, rng,
                               init_kwargs={"train": True})

    rr = np.random.default_rng(0)
    with mesh:
        state = shard_train_state(state, mesh, cfg)
        put = make_put_batch(mesh)
        batch = put({
            "image": rr.normal(size=(bs, 32, 32, 3)).astype(np.float32),
            "label": rr.integers(0, 10, size=(bs,)).astype(np.int32),
        })
        step = jax.jit(make_train_step(cfg), donate_argnums=0)

        # warmup / compile; fence with a device->host readback — on some
        # PJRT backends block_until_ready returns at dispatch, not
        # completion.
        state, metrics = step(state, batch)
        float(metrics["loss"])

        t0 = time.monotonic()
        for _ in range(steps):
            state, metrics = step(state, batch)
        float(metrics["loss"])
        elapsed = time.monotonic() - t0

    ips = bs * steps / elapsed
    ips_per_chip = ips / max(n_chips, 1)
    # vs_baseline: ratio against FDT_BENCH_BASELINE (img/s/chip) when set;
    # 1.0 otherwise = "no external baseline configured" — the absolute value
    # is the tracked metric (the reference publishes no absolute throughput).
    vs = (ips_per_chip / BASELINE_REF_IPS) if BASELINE_REF_IPS else 1.0
    print(json.dumps({
        "metric": "resnet50_cifar10_train_images_per_sec_per_chip_bs%d" % bs,
        "value": round(ips_per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(vs, 3),
        "baseline_configured": bool(BASELINE_REF_IPS),
    }))


if __name__ == "__main__":
    main()
