"""Benchmark: ResNet-50/CIFAR-10 training throughput @ bs=1024 (BASELINE.json).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N}

Baseline: the reference publishes no absolute throughput (BASELINE.md).
`vs_baseline` is value / FDT_BENCH_BASELINE (img/s/chip) when that env var
is set; otherwise it is emitted as the constant 1.0 with
"baseline_configured": false — the absolute `value` is the tracked metric.
Synthetic data (device-resident) so the number measures the compiled train
step, not disk IO.  The batch is sharded over a dp mesh spanning every
visible chip, so value is genuine per-chip throughput on multi-chip hosts.

FDT_BENCH_NGD_OVERHEAD=1 additionally reports NGD's step-time overhead vs
plain SGD (BASELINE.md's second tracked metric).  The SGD run executes in
a SUBPROCESS: each process builds exactly one donating train program —
the same program shape the Trainer runs — which also sidesteps the axon
backend's donated-buffer deallocation bug (.claude/skills/verify/SKILL.md).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

# Reference proxy: 4xA100 aggregate throughput for ResNet-50/CIFAR-10 @
# bs=1024 with AMP+fusion is not published (BASELINE.md); the driver tracks
# our absolute number round-over-round. Overridable bookkeeping constant:
BASELINE_REF_IPS = float(os.environ.get("FDT_BENCH_BASELINE", "0") or 0)


def timed_run(use_ngd: bool, bs: int, steps: int):
    """Build ONE donating train program (the Trainer's exact configuration)
    and time `steps` executions, fenced by a device->host readback.
    Returns (elapsed_seconds, compiled_peak_mem_bytes_or_None)."""
    import jax
    import jax.numpy as jnp

    from faster_distributed_training_tpu.cli import enable_compilation_cache
    from faster_distributed_training_tpu.config import TrainConfig
    from faster_distributed_training_tpu.models import resnet50
    from faster_distributed_training_tpu.optim import build_optimizer
    from faster_distributed_training_tpu.parallel import make_mesh
    from faster_distributed_training_tpu.parallel.placement import (
        make_put_batch, shard_train_state)
    from faster_distributed_training_tpu.train import (create_train_state,
                                                       make_train_step)

    enable_compilation_cache()
    mesh = make_mesh(("dp",))  # batch sharded over every visible chip
    cfg = TrainConfig(model="resnet50", batch_size=bs, alpha=0.2,
                      use_ngd=use_ngd,
                      optimizer="ngd" if use_ngd else "sgd",
                      precision="bf16", epochs=1)
    model = resnet50(num_classes=10)
    rng = jax.random.PRNGKey(cfg.seed)
    sample = jnp.zeros((bs, 32, 32, 3), jnp.float32)
    tx, _ = build_optimizer(cfg, steps_per_epoch=steps)
    state = create_train_state(model, tx, sample, rng,
                               init_kwargs={"train": True})
    with mesh:
        state = shard_train_state(state, mesh, cfg)
        put = make_put_batch(mesh)
        rr = np.random.default_rng(0)
        batch = put({
            "image": rr.normal(size=(bs, 32, 32, 3)).astype(np.float32),
            "label": rr.integers(0, 10, size=(bs,)).astype(np.int32),
        })
        from faster_distributed_training_tpu.utils.profiling import (
            compiled_memory_bytes)

        # AOT-compile so the executable's memory analysis is available
        # (the axon backend exposes no runtime memory_stats), then run the
        # compiled object directly.
        step = jax.jit(make_train_step(cfg), donate_argnums=0)
        compiled = step.lower(state, batch).compile()
        mem = compiled_memory_bytes(compiled)
        # Warmup: advance past NGD's always-update phase (the Fisher
        # refresh runs EVERY step while t < 10, then every 4th —
        # optim/ngd.py NUM_INITIAL_ITERS), so the timed window measures the
        # steady-state step, not the init transient.  Fence with a
        # device->host readback — on some PJRT backends block_until_ready
        # returns at dispatch, not completion.
        for _ in range(12):
            state, metrics = compiled(state, batch)
        float(metrics["loss"])
        t0 = time.monotonic()
        for _ in range(steps):
            state, metrics = compiled(state, batch)
        float(metrics["loss"])
        return time.monotonic() - t0, mem


def main() -> None:
    import jax

    bs = int(os.environ.get("FDT_BENCH_BS", "1024"))
    steps = int(os.environ.get("FDT_BENCH_STEPS", "20"))

    if os.environ.get("FDT_BENCH_INTERNAL_SGD") == "1":
        # child process: print the SGD elapsed time and exit
        print(json.dumps({"sgd_elapsed": timed_run(False, bs, steps)[0]}))
        return

    n_chips = jax.device_count()
    elapsed, mem = timed_run(True, bs, steps)
    ips_per_chip = bs * steps / elapsed / max(n_chips, 1)
    # vs_baseline: ratio against FDT_BENCH_BASELINE (img/s/chip) when set;
    # 1.0 otherwise = "no external baseline configured" — the absolute value
    # is the tracked metric (the reference publishes no absolute throughput).
    vs = (ips_per_chip / BASELINE_REF_IPS) if BASELINE_REF_IPS else 1.0
    record = {
        "metric": "resnet50_cifar10_train_images_per_sec_per_chip_bs%d" % bs,
        "value": round(ips_per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(vs, 3),
        "baseline_configured": bool(BASELINE_REF_IPS),
    }
    if mem:
        record["compiled_peak_mem_bytes"] = int(mem)
    if os.environ.get("FDT_BENCH_NGD_OVERHEAD") == "1":
        env = dict(os.environ, FDT_BENCH_INTERNAL_SGD="1")
        out = subprocess.run([sys.executable, os.path.abspath(__file__)],
                             env=env, capture_output=True, text=True,
                             timeout=1200)
        sgd_elapsed = json.loads(out.stdout.strip().splitlines()[-1]
                                 )["sgd_elapsed"]
        record["ngd_overhead_pct"] = round(
            (elapsed - sgd_elapsed) / sgd_elapsed * 100.0, 1)
    print(json.dumps(record))


if __name__ == "__main__":
    main()
