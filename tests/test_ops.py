"""Gradcheck-style tests for the fused kernels — the TPU analog of the
reference's fp64 ``torch.autograd.gradcheck`` self-test (resnet.py:316-319)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from faster_distributed_training_tpu.ops import (
    conv_bn_reference, fused_conv_bn, fused_mlp, mlp_reference)


def _rand(key, *shape):
    return jax.random.normal(key, shape, dtype=jnp.float64)


class TestFusedConvBN:
    @pytest.mark.parametrize("stride,padding,hw,cin,cout,k", [
        (1, 1, 8, 3, 5, 3),
        (1, 0, 6, 4, 4, 1),
        (2, 1, 8, 3, 6, 3),   # reference only supports stride 1; we support any
    ])
    def test_forward_matches_unfused(self, stride, padding, hw, cin, cout, k):
        kx, kw = jax.random.split(jax.random.PRNGKey(0))
        x = _rand(kx, 2, hw, hw, cin)
        w = _rand(kw, k, k, cin, cout)
        out, mean, var = fused_conv_bn(x, w, stride, padding)
        ref = conv_bn_reference(x, w, stride, padding)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-10)
        # stats are the conv output's batch stats
        from faster_distributed_training_tpu.ops.conv_bn import conv2d
        y = conv2d(x, w, stride, padding)
        np.testing.assert_allclose(np.asarray(mean), np.asarray(y.mean((0, 1, 2))),
                                   rtol=1e-10)
        assert np.all(np.asarray(var) > 0)

    @pytest.mark.parametrize("stride", [1, 2])
    def test_backward_matches_autodiff(self, stride):
        kx, kw, kg = jax.random.split(jax.random.PRNGKey(1), 3)
        x = _rand(kx, 2, 8, 8, 3)
        w = _rand(kw, 3, 3, 3, 5)

        def loss_fused(x, w):
            out, _, _ = fused_conv_bn(x, w, stride, 1)
            return jnp.sum(out * cot)

        def loss_ref(x, w):
            return jnp.sum(conv_bn_reference(x, w, stride, 1) * cot)

        out_shape = conv_bn_reference(x, w, stride, 1).shape
        cot = _rand(kg, *out_shape)
        gx_f, gw_f = jax.grad(loss_fused, argnums=(0, 1))(x, w)
        gx_r, gw_r = jax.grad(loss_ref, argnums=(0, 1))(x, w)
        np.testing.assert_allclose(np.asarray(gx_f), np.asarray(gx_r), rtol=1e-8,
                                   atol=1e-10)
        np.testing.assert_allclose(np.asarray(gw_f), np.asarray(gw_r), rtol=1e-8,
                                   atol=1e-10)

    def test_jit_and_remat_compile(self):
        # the fused op must be jittable and differentiable under jit
        kx, kw = jax.random.split(jax.random.PRNGKey(2))
        x = jax.random.normal(kx, (4, 8, 8, 3), dtype=jnp.float32)
        w = jax.random.normal(kw, (3, 3, 3, 8), dtype=jnp.float32) * 0.1

        @jax.jit
        def step(x, w):
            return jax.grad(lambda w: fused_conv_bn(x, w, 1, 1)[0].sum())(w)

        g = step(x, w)
        assert g.shape == w.shape and np.isfinite(np.asarray(g)).all()


class TestFusedMLP:
    def test_forward_and_backward_match(self):
        ks = jax.random.split(jax.random.PRNGKey(3), 6)
        x = _rand(ks[0], 4, 7, 20)      # leading batch dims like the reference's 3-D input
        w1 = _rand(ks[1], 30, 20) * 0.3
        b1 = _rand(ks[2], 1, 30) * 0.1
        w2 = _rand(ks[3], 10, 30) * 0.3
        b2 = _rand(ks[4], 1, 10) * 0.1
        cot = _rand(ks[5], 4, 7, 10)

        out = fused_mlp(x, w1, b1, w2, b2)
        ref = mlp_reference(x, w1, b1, w2, b2)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-12)

        gf = jax.grad(lambda *a: jnp.sum(fused_mlp(*a) * cot), argnums=(0, 1, 2, 3, 4))(
            x, w1, b1, w2, b2)
        gr = jax.grad(lambda *a: jnp.sum(mlp_reference(*a) * cot), argnums=(0, 1, 2, 3, 4))(
            x, w1, b1, w2, b2)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-9,
                                       atol=1e-12)

    def test_no_bias(self):
        ks = jax.random.split(jax.random.PRNGKey(4), 3)
        x = _rand(ks[0], 5, 8)
        w1 = _rand(ks[1], 16, 8)
        w2 = _rand(ks[2], 3, 16)
        out = fused_mlp(x, w1, None, w2, None)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(mlp_reference(x, w1, None, w2, None)),
                                   rtol=1e-12)
        g = jax.grad(lambda w: fused_mlp(x, w, None, w2, None).sum())(w1)
        assert g.shape == w1.shape

    def test_pallas_kernel_matches_reference(self):
        # interpret-mode run of the Pallas forward (non-aligned shapes
        # exercise the row-padding path); backward shares _mlp_bwd.
        from faster_distributed_training_tpu.ops import fused_mlp_pallas
        ks = jax.random.split(jax.random.PRNGKey(6), 6)
        x = _rand(ks[0], 3, 11, 20)
        w1 = _rand(ks[1], 30, 20) * 0.3
        b1 = _rand(ks[2], 1, 30) * 0.1
        w2 = _rand(ks[3], 10, 30) * 0.3
        b2 = _rand(ks[4], 1, 10) * 0.1
        cot = _rand(ks[5], 3, 11, 10)
        np.testing.assert_allclose(
            np.asarray(fused_mlp_pallas(x, w1, b1, w2, b2)),
            np.asarray(mlp_reference(x, w1, b1, w2, b2)), rtol=1e-5, atol=1e-6)
        gp = jax.grad(lambda *a: jnp.sum(fused_mlp_pallas(*a) * cot),
                      argnums=(0, 1, 2, 3, 4))(x, w1, b1, w2, b2)
        gr = jax.grad(lambda *a: jnp.sum(mlp_reference(*a) * cot),
                      argnums=(0, 1, 2, 3, 4))(x, w1, b1, w2, b2)
        for a, b in zip(gp, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)

    def test_pallas_in_transformer_model(self):
        # the mlp_impl='pallas' classifier path compiles and runs
        from faster_distributed_training_tpu.models import Transformer
        model = Transformer(n_class=4, vocab=50, n_layers=1, h=2, d_model=16,
                            d_ff=32, d_hidden=32, maxlen=12, alpha=0.0,
                            mlp_impl="pallas")
        tokens = jnp.ones((2, 10), jnp.int32)
        variables = model.init({"params": jax.random.PRNGKey(0)}, tokens,
                               train=False)
        logits = model.apply(variables, tokens, train=False)
        assert logits.shape == (2, 4)
        assert np.isfinite(np.asarray(logits)).all()

    def test_mean_bias_grad_parity_mode(self):
        # reference reduces bias grads with mean (transformer.py:311,327)
        ks = jax.random.split(jax.random.PRNGKey(5), 5)
        x = _rand(ks[0], 6, 20)
        w1, b1 = _rand(ks[1], 30, 20), _rand(ks[2], 1, 30)
        w2, b2 = _rand(ks[3], 10, 30), _rand(ks[4], 1, 10)
        g_sum = jax.grad(lambda b: fused_mlp(x, w1, b, w2, b2, False).sum())(b1)
        g_mean = jax.grad(lambda b: fused_mlp(x, w1, b, w2, b2, True).sum())(b1)
        np.testing.assert_allclose(np.asarray(g_mean) * x.shape[0],
                                   np.asarray(g_sum), rtol=1e-9)


class TestConvBNTrain:
    """conv_bn_train: remat and autodiff paths agree with the oracle."""

    def _xw(self, key, dtype=jnp.float64):
        x = jax.random.normal(jax.random.fold_in(key, 0), (4, 8, 8, 3), dtype)
        w = jax.random.normal(jax.random.fold_in(key, 1), (3, 3, 3, 16),
                              dtype)
        return x, w

    @pytest.mark.parametrize("remat", [True, False])
    def test_forward_matches_reference(self, remat):
        from faster_distributed_training_tpu.ops.conv_bn import (
            conv_bn_reference, conv_bn_train)
        x, w = self._xw(jax.random.PRNGKey(5))
        out, mean, var = conv_bn_train(x, w, 1, 1, 1e-3, remat=remat)
        ref = conv_bn_reference(x, w, 1, 1, 1e-3)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-10)
        assert mean.shape == (16,) and var.shape == (16,)

    @pytest.mark.parametrize("remat", [True, False])
    def test_gradients_match_reference(self, remat):
        from faster_distributed_training_tpu.ops.conv_bn import (
            conv_bn_reference, conv_bn_train)
        x, w = self._xw(jax.random.PRNGKey(6))

        def loss_train(x_, w_):
            return jnp.sum(conv_bn_train(x_, w_, 1, 1, 1e-3,
                                         remat=remat)[0] ** 2)

        def loss_ref(x_, w_):
            return jnp.sum(conv_bn_reference(x_, w_, 1, 1, 1e-3) ** 2)

        g1 = jax.grad(loss_train, argnums=(0, 1))(x, w)
        g2 = jax.grad(loss_ref, argnums=(0, 1))(x, w)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-8, atol=1e-10)

    def test_degenerate_constant_channel_finite(self):
        """var==0 (constant conv output) must not produce NaN/inf grads in
        the hand-written backward (the clamp-edge guard)."""
        from faster_distributed_training_tpu.ops.conv_bn import fused_conv_bn
        x = jnp.ones((2, 4, 4, 1), jnp.float32)      # constant input
        w = jnp.ones((1, 1, 1, 4), jnp.float32)      # 1x1 conv -> constant y

        g = jax.grad(lambda x_: jnp.sum(
            fused_conv_bn(x_, w, 1, 0, 1e-3)[0] ** 2))(x)
        assert np.isfinite(np.asarray(g)).all()
