"""Gradcheck-style tests for the fused kernels — the TPU analog of the
reference's fp64 ``torch.autograd.gradcheck`` self-test (resnet.py:316-319)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from faster_distributed_training_tpu.ops import (
    conv_bn_reference, fused_conv_bn, fused_mlp, mlp_reference)


def _rand(key, *shape):
    return jax.random.normal(key, shape, dtype=jnp.float64)


class TestFusedConvBN:
    @pytest.mark.parametrize("stride,padding,hw,cin,cout,k", [
        (1, 1, 8, 3, 5, 3),
        (1, 0, 6, 4, 4, 1),
        (2, 1, 8, 3, 6, 3),   # reference only supports stride 1; we support any
    ])
    def test_forward_matches_unfused(self, stride, padding, hw, cin, cout, k):
        kx, kw = jax.random.split(jax.random.PRNGKey(0))
        x = _rand(kx, 2, hw, hw, cin)
        w = _rand(kw, k, k, cin, cout)
        out, mean, var = fused_conv_bn(x, w, stride, padding)
        ref = conv_bn_reference(x, w, stride, padding)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-10)
        # stats are the conv output's batch stats
        from faster_distributed_training_tpu.ops.conv_bn import conv2d
        y = conv2d(x, w, stride, padding)
        np.testing.assert_allclose(np.asarray(mean), np.asarray(y.mean((0, 1, 2))),
                                   rtol=1e-10)
        assert np.all(np.asarray(var) > 0)

    @pytest.mark.parametrize("stride", [1, 2])
    def test_backward_matches_autodiff(self, stride):
        kx, kw, kg = jax.random.split(jax.random.PRNGKey(1), 3)
        x = _rand(kx, 2, 8, 8, 3)
        w = _rand(kw, 3, 3, 3, 5)

        def loss_fused(x, w):
            out, _, _ = fused_conv_bn(x, w, stride, 1)
            return jnp.sum(out * cot)

        def loss_ref(x, w):
            return jnp.sum(conv_bn_reference(x, w, stride, 1) * cot)

        out_shape = conv_bn_reference(x, w, stride, 1).shape
        cot = _rand(kg, *out_shape)
        gx_f, gw_f = jax.grad(loss_fused, argnums=(0, 1))(x, w)
        gx_r, gw_r = jax.grad(loss_ref, argnums=(0, 1))(x, w)
        np.testing.assert_allclose(np.asarray(gx_f), np.asarray(gx_r), rtol=1e-8,
                                   atol=1e-10)
        np.testing.assert_allclose(np.asarray(gw_f), np.asarray(gw_r), rtol=1e-8,
                                   atol=1e-10)

    def test_jit_and_remat_compile(self):
        # the fused op must be jittable and differentiable under jit
        kx, kw = jax.random.split(jax.random.PRNGKey(2))
        x = jax.random.normal(kx, (4, 8, 8, 3), dtype=jnp.float32)
        w = jax.random.normal(kw, (3, 3, 3, 8), dtype=jnp.float32) * 0.1

        @jax.jit
        def step(x, w):
            return jax.grad(lambda w: fused_conv_bn(x, w, 1, 1)[0].sum())(w)

        g = step(x, w)
        assert g.shape == w.shape and np.isfinite(np.asarray(g)).all()


class TestFusedMLP:
    def test_forward_and_backward_match(self):
        ks = jax.random.split(jax.random.PRNGKey(3), 6)
        x = _rand(ks[0], 4, 7, 20)      # leading batch dims like the reference's 3-D input
        w1 = _rand(ks[1], 30, 20) * 0.3
        b1 = _rand(ks[2], 1, 30) * 0.1
        w2 = _rand(ks[3], 10, 30) * 0.3
        b2 = _rand(ks[4], 1, 10) * 0.1
        cot = _rand(ks[5], 4, 7, 10)

        out = fused_mlp(x, w1, b1, w2, b2)
        ref = mlp_reference(x, w1, b1, w2, b2)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-12)

        gf = jax.grad(lambda *a: jnp.sum(fused_mlp(*a) * cot), argnums=(0, 1, 2, 3, 4))(
            x, w1, b1, w2, b2)
        gr = jax.grad(lambda *a: jnp.sum(mlp_reference(*a) * cot), argnums=(0, 1, 2, 3, 4))(
            x, w1, b1, w2, b2)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-9,
                                       atol=1e-12)

    def test_no_bias(self):
        ks = jax.random.split(jax.random.PRNGKey(4), 3)
        x = _rand(ks[0], 5, 8)
        w1 = _rand(ks[1], 16, 8)
        w2 = _rand(ks[2], 3, 16)
        out = fused_mlp(x, w1, None, w2, None)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(mlp_reference(x, w1, None, w2, None)),
                                   rtol=1e-12)
        g = jax.grad(lambda w: fused_mlp(x, w, None, w2, None).sum())(w1)
        assert g.shape == w1.shape

    def test_pallas_kernel_matches_reference(self):
        # interpret-mode run of the Pallas forward (non-aligned shapes
        # exercise the row-padding path); backward shares _mlp_bwd.
        from faster_distributed_training_tpu.ops import fused_mlp_pallas
        ks = jax.random.split(jax.random.PRNGKey(6), 6)
        x = _rand(ks[0], 3, 11, 20)
        w1 = _rand(ks[1], 30, 20) * 0.3
        b1 = _rand(ks[2], 1, 30) * 0.1
        w2 = _rand(ks[3], 10, 30) * 0.3
        b2 = _rand(ks[4], 1, 10) * 0.1
        cot = _rand(ks[5], 3, 11, 10)
        np.testing.assert_allclose(
            np.asarray(fused_mlp_pallas(x, w1, b1, w2, b2)),
            np.asarray(mlp_reference(x, w1, b1, w2, b2)), rtol=1e-5, atol=1e-6)
        gp = jax.grad(lambda *a: jnp.sum(fused_mlp_pallas(*a) * cot),
                      argnums=(0, 1, 2, 3, 4))(x, w1, b1, w2, b2)
        gr = jax.grad(lambda *a: jnp.sum(mlp_reference(*a) * cot),
                      argnums=(0, 1, 2, 3, 4))(x, w1, b1, w2, b2)
        for a, b in zip(gp, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)

    def test_pallas_in_transformer_model(self):
        # the mlp_impl='pallas' classifier path compiles and runs
        from faster_distributed_training_tpu.models import Transformer
        model = Transformer(n_class=4, vocab=50, n_layers=1, h=2, d_model=16,
                            d_ff=32, d_hidden=32, maxlen=12, alpha=0.0,
                            mlp_impl="pallas")
        tokens = jnp.ones((2, 10), jnp.int32)
        variables = model.init({"params": jax.random.PRNGKey(0)}, tokens,
                               train=False)
        logits = model.apply(variables, tokens, train=False)
        assert logits.shape == (2, 4)
        assert np.isfinite(np.asarray(logits)).all()

    def test_mean_bias_grad_parity_mode(self):
        # reference reduces bias grads with mean (transformer.py:311,327)
        ks = jax.random.split(jax.random.PRNGKey(5), 5)
        x = _rand(ks[0], 6, 20)
        w1, b1 = _rand(ks[1], 30, 20), _rand(ks[2], 1, 30)
        w2, b2 = _rand(ks[3], 10, 30), _rand(ks[4], 1, 10)
        g_sum = jax.grad(lambda b: fused_mlp(x, w1, b, w2, b2, False).sum())(b1)
        g_mean = jax.grad(lambda b: fused_mlp(x, w1, b, w2, b2, True).sum())(b1)
        np.testing.assert_allclose(np.asarray(g_mean) * x.shape[0],
                                   np.asarray(g_sum), rtol=1e-9)


class TestConvBNTrain:
    """conv_bn_train: remat and autodiff paths agree with the oracle."""

    def _xw(self, key, dtype=jnp.float64):
        x = jax.random.normal(jax.random.fold_in(key, 0), (4, 8, 8, 3), dtype)
        w = jax.random.normal(jax.random.fold_in(key, 1), (3, 3, 3, 16),
                              dtype)
        return x, w

    @pytest.mark.parametrize("remat", [True, False])
    def test_forward_matches_reference(self, remat):
        from faster_distributed_training_tpu.ops.conv_bn import (
            conv_bn_reference, conv_bn_train)
        x, w = self._xw(jax.random.PRNGKey(5))
        out, mean, var = conv_bn_train(x, w, 1, 1, 1e-3, remat=remat)
        ref = conv_bn_reference(x, w, 1, 1, 1e-3)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-10)
        assert mean.shape == (16,) and var.shape == (16,)

    @pytest.mark.parametrize("remat", [True, False])
    def test_gradients_match_reference(self, remat):
        from faster_distributed_training_tpu.ops.conv_bn import (
            conv_bn_reference, conv_bn_train)
        x, w = self._xw(jax.random.PRNGKey(6))

        def loss_train(x_, w_):
            return jnp.sum(conv_bn_train(x_, w_, 1, 1, 1e-3,
                                         remat=remat)[0] ** 2)

        def loss_ref(x_, w_):
            return jnp.sum(conv_bn_reference(x_, w_, 1, 1, 1e-3) ** 2)

        g1 = jax.grad(loss_train, argnums=(0, 1))(x, w)
        g2 = jax.grad(loss_ref, argnums=(0, 1))(x, w)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-8, atol=1e-10)

    def test_degenerate_constant_channel_finite(self):
        """var==0 (constant conv output) must not produce NaN/inf grads in
        the hand-written backward (the clamp-edge guard)."""
        from faster_distributed_training_tpu.ops.conv_bn import fused_conv_bn
        x = jnp.ones((2, 4, 4, 1), jnp.float32)      # constant input
        w = jnp.ones((1, 1, 1, 4), jnp.float32)      # 1x1 conv -> constant y

        g = jax.grad(lambda x_: jnp.sum(
            fused_conv_bn(x_, w, 1, 0, 1e-3)[0] ** 2))(x)
        assert np.isfinite(np.asarray(g)).all()


class TestFusedFFNSublayer:
    """ops/fused_ffn.py — the whole pre-LN FFN sublayer (LN -> Dense ->
    GELU -> dropout -> Dense -> dropout -> +residual) as ONE Pallas
    kernel with a vjp-of-reference recompute backward.  Measured role
    (PARITY): an intermediate capacity rung (-11% peak memory for +8%
    step time at bs256/seq512), NOT a throughput win — XLA's
    saved-intermediate autodiff beats recompute on time."""

    def _inputs(self, dtype=jnp.float32, B=4, L=8, d=32, dff=64):
        rr = np.random.default_rng(0)
        h = jnp.asarray(rr.normal(size=(B, L, d)), dtype)
        lns = jnp.asarray(rr.normal(size=(d,)) * 0.1 + 1.0, jnp.float32)
        lnb = jnp.asarray(rr.normal(size=(d,)) * 0.1, jnp.float32)
        w1 = jnp.asarray(rr.normal(size=(d, dff)) * 0.1, dtype)
        b1 = jnp.asarray(rr.normal(size=(dff,)) * 0.1, dtype)
        w2 = jnp.asarray(rr.normal(size=(dff, d)) * 0.1, dtype)
        b2 = jnp.asarray(rr.normal(size=(d,)) * 0.1, dtype)
        return h, lns, lnb, w1, b1, w2, b2

    @pytest.mark.parametrize("rates", [(0.0, 0.0), (0.1, 0.1)])
    def test_kernel_matches_reference_fwd_and_grads(self, rates):
        from faster_distributed_training_tpu.ops.fused_ffn import (
            ffn_sublayer_reference, fused_ffn_sublayer)

        args = self._inputs()
        s1, s2 = jnp.uint32(11), jnp.uint32(22)
        rh, rc = rates
        out = fused_ffn_sublayer(*args, s1, s2, rh, rc)
        ref = ffn_sublayer_reference(*args, s1, s2, rh, rc)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)
        gk = jax.grad(lambda *a: jnp.sum(
            fused_ffn_sublayer(*a, s1, s2, rh, rc) ** 2),
            argnums=tuple(range(7)))(*args)
        gr = jax.grad(lambda *a: jnp.sum(
            ffn_sublayer_reference(*a, s1, s2, rh, rc) ** 2),
            argnums=tuple(range(7)))(*args)
        for a, b in zip(gk, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_no_ffn_shaped_backward_residuals(self):
        """The custom_vjp must save INPUTS only: no residual leaf may
        carry the (rows, d_ff) hidden shape — that is the whole point
        of the fusion (capacity)."""
        from faster_distributed_training_tpu.ops.fused_ffn import (
            fused_ffn_sublayer)

        h, lns, lnb, w1, b1, w2, b2 = self._inputs(B=16)
        n_hidden = h.shape[0] * h.shape[1] * w1.shape[1]
        _, vjp = jax.vjp(
            lambda h_: fused_ffn_sublayer(h_, lns, lnb, w1, b1, w2, b2,
                                          jnp.uint32(1), jnp.uint32(2),
                                          0.1, 0.1), h)
        for leaf in jax.tree.leaves(vjp):
            assert np.size(leaf) < n_hidden, np.shape(leaf)

    def test_dropout_stream_matches_hash_dropout(self):
        """The in-kernel masks must equal ops.dropout.hash_dropout on the
        full tensor (same (seed, global-index) stream), so backward
        regeneration and the module-level engine agree — including at a
        NONZERO row offset and through the sharded _global_rows mapping."""
        from faster_distributed_training_tpu.ops.dropout import (
            hash_dropout, keep_factor_rows, keep_factor_tile)
        from faster_distributed_training_tpu.ops.fused_ffn import (
            _global_rows)

        seed = jnp.uint32(77)
        rows, cols = 16, 32
        ones = jnp.ones((rows, cols), jnp.float32)
        via_tile = np.asarray(
            ones * keep_factor_tile(seed, jnp.uint32(0), rows, cols, 0.3))
        via_module = np.asarray(hash_dropout(ones, seed, 0.3))
        np.testing.assert_array_equal(via_tile, via_module)
        # row0=6: the tile must reproduce rows 6.. of the full stream
        tail = np.asarray(jnp.ones((rows - 6, cols), jnp.float32)
                          * keep_factor_tile(seed, jnp.uint32(6), rows - 6,
                                             cols, 0.3))
        np.testing.assert_array_equal(tail, via_module[6:])
        # the sharded global-rows mapping: a (B=4, L=4) shard at batch
        # offset 2, seq offset 0 of an L_glob=8 tensor addresses rows
        # {(2+b)*8 + s} of the global stream
        g = _global_rows(jnp.arange(8, dtype=jnp.uint32), b0=2, s0=0,
                         l_loc=4, l_glob=8)
        expect = [(2 + r // 4) * 8 + r % 4 for r in range(8)]
        np.testing.assert_array_equal(np.asarray(g), expect)
        shard = np.asarray(keep_factor_rows(seed, g, cols, 0.3))
        full = np.asarray(keep_factor_tile(seed, jnp.uint32(0), 40, cols,
                                           0.3))
        np.testing.assert_array_equal(shard, full[np.asarray(expect)])
        # rate ~1 drops everything instead of dividing by zero
        assert float(np.abs(keep_factor_tile(
            seed, jnp.uint32(0), 4, 8, 1.0 - 1e-9)).max()) == 0.0

    def test_multi_block_grid_and_padding(self):
        """Rows > block_rows exercise the grid>1 path (per-block row0
        dropout offsets) and a non-multiple row count exercises the
        pad-and-slice path — both must still match the reference."""
        from faster_distributed_training_tpu.ops.fused_ffn import (
            ffn_sublayer_reference, fused_ffn_sublayer)

        # 300 rows with block_rows=256 -> 2 blocks, 212 rows of padding
        args = self._inputs(B=30, L=10)
        s1, s2 = jnp.uint32(5), jnp.uint32(6)
        out = fused_ffn_sublayer(*args, s1, s2, 0.3, 0.2)
        ref = ffn_sublayer_reference(*args, s1, s2, 0.3, 0.2)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)
        gk = jax.grad(lambda h: jnp.sum(
            fused_ffn_sublayer(h, *args[1:], s1, s2, 0.3, 0.2) ** 2))(args[0])
        gr = jax.grad(lambda h: jnp.sum(
            ffn_sublayer_reference(h, *args[1:], s1, s2, 0.3, 0.2) ** 2))(
            args[0])
        np.testing.assert_allclose(np.asarray(gk), np.asarray(gr),
                                   rtol=1e-4, atol=1e-5)

    def test_erf_polynomial_accuracy(self):
        """Mosaic has no erf; the A&S 7.1.26 polynomial must stay within
        ~5e-7 of lax.erf in fp32 (measured 4.2e-7 — far below bf16's
        ~8e-3 resolution)."""
        from faster_distributed_training_tpu.ops.fused_ffn import _erf_f32

        x = jnp.linspace(-6.0, 6.0, 4001, dtype=jnp.float32)
        err = np.abs(np.asarray(_erf_f32(x))
                     - np.asarray(jax.lax.erf(x)))
        assert float(err.max()) < 1e-6

    @pytest.mark.slow  # r20 budget diet: 28 s — sharded-vs-unsharded
    # kernel parity incl. dropout placement-invariance is tier-1 in
    # tests/test_kernel_shard.py (the r19 layer this wrapper predates)
    def test_sharded_wrapper_matches_unsharded(self, devices8):
        """fused_ffn_sublayer_sharded is PLACEMENT-INVARIANT (the
        codebase's sharded-dropout convention, ops/attention.py
        dropout_keep): per-shard kernels address the GLOBAL dropout
        index space through their (batch, seq) offsets, so the same
        global batch reproduces the unsharded output and gradients
        exactly — WITH dropout active, on batch-sharded and
        sequence-sharded meshes alike."""
        from faster_distributed_training_tpu.ops.fused_ffn import (
            fused_ffn_sublayer, fused_ffn_sublayer_sharded)
        from faster_distributed_training_tpu.parallel import make_mesh

        args = self._inputs(B=16)
        s1, s2 = jnp.uint32(3), jnp.uint32(4)
        plain = fused_ffn_sublayer(*args, s1, s2, 0.0, 0.0)
        plain_d = np.asarray(fused_ffn_sublayer(*args, s1, s2, 0.4, 0.3))
        gp = jax.grad(lambda h: jnp.sum(
            fused_ffn_sublayer(h, *args[1:], s1, s2, 0.4, 0.3) ** 2))(args[0])

        for axes, shape in ((("dp",), (8,)), (("dp", "sp"), (2, 4))):
            mesh = make_mesh(axes, shape, devices8)
            with mesh:
                sh = fused_ffn_sublayer_sharded(*args, s1, s2, mesh=mesh)
                sh_d = np.asarray(fused_ffn_sublayer_sharded(
                    *args, s1, s2, mesh=mesh, rate_hidden=0.4,
                    rate_conn=0.3))
                gs = jax.grad(lambda h: jnp.sum(
                    fused_ffn_sublayer_sharded(h, *args[1:], s1, s2,
                                               mesh=mesh, rate_hidden=0.4,
                                               rate_conn=0.3) ** 2))(args[0])
            np.testing.assert_allclose(np.asarray(sh), np.asarray(plain),
                                       rtol=1e-5, atol=1e-6,
                                       err_msg=str(axes))
            np.testing.assert_array_equal(
                sh_d == 0.0, plain_d == 0.0)   # identical drop pattern
            np.testing.assert_allclose(sh_d, plain_d, rtol=1e-5, atol=1e-6,
                                       err_msg=str(axes))
            np.testing.assert_allclose(np.asarray(gs), np.asarray(gp),
                                       rtol=1e-4, atol=1e-5,
                                       err_msg=str(axes))

    def test_model_param_tree_identical_and_eval_equal(self):
        """ffn_impl='pallas' must keep the EXACT param tree of the flax
        path (checkpoints interchange) and agree at eval."""
        from faster_distributed_training_tpu.models import Transformer

        x = jnp.asarray(np.random.default_rng(0).integers(0, 64, size=(4, 8)),
                        jnp.int32)
        rng = jax.random.PRNGKey(0)
        models, trees = {}, {}
        for impl in ("flax", "pallas"):
            m = Transformer(n_class=4, vocab=64, n_layers=2, h=2, d_model=16,
                            d_ff=32, d_hidden=16, maxlen=8, ffn_impl=impl)
            v = m.init({"params": rng, "dropout": rng, "mixup": rng},
                       x, train=True)
            models[impl] = m
            trees[impl] = (jax.tree_util.tree_structure(v["params"]), v)
        assert trees["flax"][0] == trees["pallas"][0]
        params = trees["flax"][1]["params"]
        ef = models["flax"].apply({"params": params}, x, train=False)
        ep = models["pallas"].apply({"params": params}, x, train=False)
        np.testing.assert_allclose(np.asarray(ef), np.asarray(ep),
                                   rtol=1e-5, atol=1e-6)

    @pytest.mark.slow  # r21 budget diet: 12 s — kernel fwd/grad parity
    # vs the reference stays tier-1 above; full-model training through
    # the pallas FFN stays tier-1 in test_train (8dev-mesh fused FFN)
    def test_model_trains_through_kernel(self):
        from faster_distributed_training_tpu.models import Transformer

        m = Transformer(n_class=4, vocab=64, n_layers=2, h=2, d_model=16,
                        d_ff=32, d_hidden=16, maxlen=8, ffn_impl="pallas")
        x = jnp.asarray(np.random.default_rng(1).integers(0, 64, size=(4, 8)),
                        jnp.int32)
        rng = jax.random.PRNGKey(0)
        v = m.init({"params": rng, "dropout": rng, "mixup": rng},
                   x, train=True)

        def loss(p):
            lg, idx, lam = m.apply({"params": p}, x, train=True,
                                   rngs={"dropout": jax.random.PRNGKey(1),
                                         "mixup": jax.random.PRNGKey(2)})
            return jnp.mean(lg ** 2)

        l, g = jax.value_and_grad(loss)(v["params"])
        assert np.isfinite(float(l))
        assert all(np.all(np.isfinite(np.asarray(t)))
                   for t in jax.tree.leaves(g))
        # FFN weights actually receive gradient through the kernel path
        gffn = g["layer_0"]["ffn"]["Dense_0"]["kernel"]
        assert float(jnp.max(jnp.abs(gffn))) > 0.0


class TestSavedStatsLayerNorm:
    """ops/layernorm.py torch_layernorm (VERDICT r5 #4): the saved-
    (mean, rstd) custom_vjp must be forward-BIT-IDENTICAL to the pure
    fp32 math at the reference's NONSTANDARD semantics (UNBIASED n-1
    variance, eps added to the STD, not the variance) and gradient-equal
    to XLA autodiff of that math — the 13 LN sites all route through it,
    so a backward-math slip would corrupt every transformer gradient."""

    def _xsb(self, key, shape=(3, 5, 16), dtype=jnp.float32):
        ks = jax.random.split(key, 3)
        return (jax.random.normal(ks[0], shape, dtype),
                jax.random.normal(ks[1], shape[-1:], dtype),
                jax.random.normal(ks[2], shape[-1:], dtype))

    def test_forward_bit_identical_and_unbiased_semantics(self):
        from faster_distributed_training_tpu.ops.layernorm import (
            _ln_saved_stats, torch_layernorm, torch_layernorm_f32)
        x, s, b = self._xsb(jax.random.PRNGKey(0))
        eps = 1e-6
        got = torch_layernorm(x, s, b, eps)
        pure = torch_layernorm_f32(x, s, b, eps)
        assert np.array_equal(np.asarray(got), np.asarray(pure))
        assert np.array_equal(np.asarray(_ln_saved_stats(x, s, b, eps)),
                              np.asarray(pure))
        # explicit reference of the nonstandard semantics
        xn = np.asarray(x, np.float64)
        mean = xn.mean(-1, keepdims=True)
        var = ((xn - mean) ** 2).sum(-1, keepdims=True) / (xn.shape[-1] - 1)
        ref = (np.asarray(s, np.float64) * (xn - mean)
               / (np.sqrt(var) + eps) + np.asarray(b, np.float64))
        np.testing.assert_allclose(np.asarray(got), ref, rtol=2e-5,
                                   atol=2e-6)

    @pytest.mark.parametrize("dtype,rtol", [(jnp.float32, 2e-5),
                                            (jnp.float64, 1e-10)])
    def test_backward_matches_autodiff(self, dtype, rtol):
        from faster_distributed_training_tpu.ops.layernorm import (
            _ln_saved_stats, torch_layernorm_f32)
        x, s, b = self._xsb(jax.random.PRNGKey(1), dtype=dtype)
        eps = 1e-6

        def loss_vjp(x_, s_, b_):
            return jnp.sum(jnp.sin(_ln_saved_stats(x_, s_, b_, eps)))

        def loss_ref(x_, s_, b_):
            return jnp.sum(jnp.sin(torch_layernorm_f32(x_, s_, b_, eps)))

        g_vjp = jax.grad(loss_vjp, argnums=(0, 1, 2))(x, s, b)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(x, s, b)
        for name, a, c in zip(("x", "scale", "bias"), g_vjp, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                       rtol=rtol, atol=rtol,
                                       err_msg=f"d{name} mismatch")

    def test_residuals_are_input_plus_two_scalars_per_row(self):
        # the point of the VJP: residual tensors are x, scale, and ONE
        # (mean, rstd) scalar pair per row — nothing normalized-shaped
        from faster_distributed_training_tpu.ops.layernorm import _ln_fwd
        x, s, b = self._xsb(jax.random.PRNGKey(2))
        out, res = _ln_fwd(x, s, b, 1e-6)
        x_r, s_r, mean, rstd = res
        assert x_r.shape == x.shape and s_r.shape == s.shape
        assert mean.shape == x.shape[:-1] + (1,)
        assert rstd.shape == x.shape[:-1] + (1,)

    def test_kill_switch_restores_default_autodiff(self, monkeypatch):
        from faster_distributed_training_tpu.ops import layernorm as ln
        x, s, b = self._xsb(jax.random.PRNGKey(3))
        monkeypatch.setenv("FDT_LN_SAVED_STATS", "0")
        off = ln.torch_layernorm(x, s, b, 1e-6)
        monkeypatch.delenv("FDT_LN_SAVED_STATS")
        on = ln.torch_layernorm(x, s, b, 1e-6)
        assert np.array_equal(np.asarray(off), np.asarray(on))

    def test_transformer_layernorm_module_routes_through_vjp(self):
        # TorchLayerNorm (models/transformer.py) delegates here; its
        # grads must equal the pure-math autodiff at model shapes
        from faster_distributed_training_tpu.models.transformer import (
            TorchLayerNorm)
        from faster_distributed_training_tpu.ops.layernorm import (
            torch_layernorm_f32)
        m = TorchLayerNorm()
        x = jax.random.normal(jax.random.PRNGKey(4), (2, 6, 32),
                              jnp.float32)
        v = m.init(jax.random.PRNGKey(5), x)

        def loss(p, x_):
            return jnp.sum(m.apply(p, x_) ** 2)

        gx = jax.grad(loss, argnums=1)(v, x)

        def loss_ref(x_):
            return jnp.sum(torch_layernorm_f32(
                x_, v["params"]["scale"], v["params"]["bias"], m.eps) ** 2)

        gx_ref = jax.grad(loss_ref)(x)
        np.testing.assert_allclose(np.asarray(gx), np.asarray(gx_ref),
                                   rtol=2e-5, atol=2e-6)


class TestFfnVmemDtypeBytes:
    """r13 satellite: ffn_kernel_fits_vmem's weight-byte parameter must
    follow the ACTUAL compute dtype at the build_model call site — an
    fp32 run must not falsely pass the budget sized for bf16, and
    1-byte (quantized) weights must not be falsely rejected.  The
    (1280, 1280) cell is chosen to straddle the 12 MiB budget: weights
    alone are 6.25 MiB at bf16, 12.5 MiB at fp32, 3.13 MiB at int8."""

    def test_w_bytes_drive_the_verdict(self):
        from faster_distributed_training_tpu.ops.fused_ffn import (
            ffn_kernel_fits_vmem)
        assert ffn_kernel_fits_vmem(1280, 1280, w_bytes=2)       # bf16
        assert not ffn_kernel_fits_vmem(1280, 1280, w_bytes=4)   # fp32
        assert ffn_kernel_fits_vmem(1280, 1280, w_bytes=1)       # int8

    def test_build_model_passes_compute_dtype_itemsize(self):
        import warnings as _w
        from faster_distributed_training_tpu.cli import build_model
        from faster_distributed_training_tpu.config import TrainConfig

        def mk(precision):
            return TrainConfig(model="transformer", dataset="synthetic",
                               num_classes=4, batch_size=4, seq_len=16,
                               n_layers=1, d_model=1280, d_ff=1280,
                               n_heads=4, precision=precision,
                               attention="dense", ffn_impl="pallas")

        with pytest.warns(UserWarning, match="VMEM budget"):
            m32 = build_model(mk("fp32"), vocab_size=100)
        assert m32.ffn_impl == "flax"      # fp32 weights bust the budget
        with _w.catch_warnings(record=True) as caught:
            _w.simplefilter("always")
            m16 = build_model(mk("bf16"), vocab_size=100)
        assert m16.ffn_impl == "pallas"    # bf16 weights fit
        assert not any("VMEM budget" in str(c.message) for c in caught)
