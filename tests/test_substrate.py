"""Core substrate tests: config parsing, PRNG streams, mesh + sharding."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import faster_distributed_training_tpu as fdt
from faster_distributed_training_tpu.config import (
    build_parser, config_from_args, parse_mesh)
from faster_distributed_training_tpu.parallel import (
    batch_spec, fsdp_partition_params, make_mesh, shard_pytree)


def test_config_reference_flags():
    # The reference CLI surface (resnet50_test.py:46-59) must parse unchanged.
    args = build_parser().parse_args(
        ["--bs", "256", "--lr", "0.01", "--ngd", "--meta_learning",
         "--epoch", "30", "--alpha", "0.4", "--distributed"])
    cfg = config_from_args(args)
    assert cfg.batch_size == 256 and cfg.lr == 0.01
    assert cfg.use_ngd and cfg.meta_learning and cfg.distributed
    assert cfg.epochs == 30 and cfg.alpha == 0.4


def test_cache_dir_isa_keyed_unless_tpu(monkeypatch):
    """ADVICE r4 #1 / VERDICT r4 #5: the persistent-cache directory must
    be ISA-keyed on EVERY path that isn't a known TPU platform —
    including the default where no platform is configured at all (the
    --device auto / early-bench hazard) — and version-bumped so stale
    round-4 entries can't load."""
    from faster_distributed_training_tpu import cli

    fp = cli._host_isa_fingerprint()
    for plat, keyed in (("", True), ("cpu", True), ("cuda", True),
                        ("tpu", False), ("axon", False)):
        monkeypatch.setattr(cli, "_configured_platform", lambda p=plat: p)
        d = cli._default_cache_dir()
        assert "fdt_xla_v2" in d, d
        assert d.endswith(f"-{fp}") == keyed, (plat, d)


def test_bench_regression_guard():
    """VERDICT r4 #2c: bench flags >5% wrong-way moves per metric
    direction (throughput/speedup/MFU up=good; ms/overhead/mem up=bad)."""
    import importlib.util
    import os as _os
    spec = importlib.util.spec_from_file_location(
        "bench", _os.path.join(_os.path.dirname(__file__), "..", "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    prev = {"value": 100.0, "ngd_overhead_pct": 5.0,
            "attn_fwdbwd_ms_L2048": 8.0, "attn_fwdbwd_ms_L4096": 14.0,
            "tricks_speedup_x": 2.7,
            "transformer_bs256_seq256_mfu_pct": 25.0,
            "resnet_ngd_step_ms": 130.0}
    rec = {"value": 90.0,                 # -10% throughput: regression
           "ngd_overhead_pct": 7.0,       # +2 pp: past the 1.5pp tolerance
           "attn_fwdbwd_ms_L2048": 9.0,   # +12.5%: tunnel noise, NOT flagged
           "attn_fwdbwd_ms_L4096": 20.0,  # +43%: past the 25% ladder band
           "tricks_speedup_x": 2.9,       # up = good
           "transformer_bs256_seq256_mfu_pct": 26.0,  # up = good
           "resnet_ngd_step_ms": 125.0,   # down = good
           "baseline_note": "strings are skipped"}
    regs = bench._find_regressions(rec, prev)
    assert {r["metric"] for r in regs} == {
        "value", "ngd_overhead_pct", "attn_fwdbwd_ms_L4096"}
    by = {r["metric"]: r for r in regs}
    assert by["value"]["change_pct"] == -10.0
    assert by["ngd_overhead_pct"]["change_pct"] == 2.0  # pp, not relative
    assert by["attn_fwdbwd_ms_L4096"]["prev"] == 14.0
    # a pp metric IMPROVING is never flagged
    assert not bench._find_regressions({"ngd_overhead_pct": 3.0},
                                       {"ngd_overhead_pct": 5.0})
    # a tracked metric VANISHING (child subprocess death) is flagged
    gone = bench._find_regressions({"value": 100.0},
                                   {"value": 100.0,
                                    "attn_fwdbwd_ms_L2048": 8.0,
                                    "untracked_thing": 3.0})
    assert gone == [{"metric": "attn_fwdbwd_ms_L2048", "prev": 8.0,
                     "now": None, "missing": True}]
    # VERDICT r5 #2: a published measured noise band raises the metric's
    # threshold — a move inside the band is NOT flagged, outside IS, and
    # the band itself is metadata, never a compared metric
    key = "transformer_agnews_ex_per_sec_bs64_seq512"
    inside = bench._find_regressions(
        {key: 1030.0, f"{key}_noise_band_pct": 7.0}, {key: 1098.0})
    assert inside == []
    outside = bench._find_regressions(
        {key: 950.0, f"{key}_noise_band_pct": 7.0}, {key: 1098.0})
    assert [r["metric"] for r in outside] == [key]
    assert "noise band" in outside[0]["note"]
    assert not bench._find_regressions(
        {"value": 100.0}, {"value": 100.0, f"{key}_noise_band_pct": 7.0})
    # VERDICT r5 #1: the repo's real previous record parses — driver
    # wrappers whose `parsed` is null and whose tail is a truncated
    # mid-record fragment (BENCH_r05.json) are SKIPPED, never returned,
    # and the committed BENCH_LATEST.json full record backstops them
    import os as _os2
    assert bench._load_bench_record(
        _os2.path.join(_os2.path.dirname(bench.__file__),
                       "BENCH_r05.json")) is None
    prev_rec, prev_file = bench._prev_bench_record()
    assert prev_rec and (prev_file.startswith("BENCH_r")
                         or prev_file == bench.BENCH_LATEST)
    assert "value" in prev_rec and "attn_fwdbwd_ms_L8192" in prev_rec


def test_config_mixup_mode_flag():
    # every mixup variant is reachable from the CLI (VERDICT r1 weak #2)
    from faster_distributed_training_tpu.train.steps import resolve_mixup_mode
    for mode in ("static", "intra", "meta", "attn", "none"):
        cfg = config_from_args(
            build_parser().parse_args(["--mixup_mode", mode]))
        assert cfg.mixup_mode == mode
        assert resolve_mixup_mode(cfg) == mode
    # '' auto-resolves per the reference pairing
    assert resolve_mixup_mode(config_from_args(
        build_parser().parse_args(["--meta_learning"]))) == "meta"
    assert resolve_mixup_mode(config_from_args(
        build_parser().parse_args(["--alpha", "0"]))) == "none"
    assert resolve_mixup_mode(config_from_args(
        build_parser().parse_args([]))) == "static"


def test_config_tricks_off_rewrites_every_speed_lever():
    # the bag-of-tricks ablation switch (VERDICT r3 #2): --tricks off
    # must flip EVERY lever at once via resolve_tricks (applied inside
    # config_from_args)
    cfg = config_from_args(build_parser().parse_args(["--tricks", "off"]))
    assert cfg.tricks == "off"
    assert cfg.precision == "fp32"
    assert cfg.attention == "dense"
    assert cfg.mlp_impl == "naive"
    assert cfg.dropout_impl == "xla"
    assert cfg.dropout_rng_impl == "threefry"
    assert cfg.prefetch_depth == 0 and cfg.workers == 0
    # default: every lever stays on
    on = config_from_args(build_parser().parse_args([]))
    assert on.tricks == "on" and on.precision == "bf16"
    assert on.dropout_impl == "hash" and on.prefetch_depth > 0


def test_tricks_off_builds_unfused_reference_layout():
    # the OFF arm reproduces the reference's three separate QKV Linears
    # (transformer.py:196-227) and the naive stored-activation MLP
    import jax
    import jax.numpy as jnp

    from faster_distributed_training_tpu.cli import build_model
    from faster_distributed_training_tpu.config import (TrainConfig,
                                                        resolve_tricks)

    cfg = resolve_tricks(TrainConfig(
        model="transformer", num_classes=4, seq_len=8, n_layers=1,
        d_model=16, d_ff=32, n_heads=2, tricks="off"))
    model = build_model(cfg, vocab_size=32)
    assert model.fused_qkv is False and model.mlp_impl == "naive"
    variables = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1),
         "mixup": jax.random.PRNGKey(2)},
        jnp.zeros((2, 8), jnp.int32), train=False)
    attn = variables["params"]["layer_0"]["attn"]
    assert {"query", "key", "value", "out"} <= set(attn)
    assert "qkv" not in attn
    # resnet OFF arm: autodiff conv+BN, fp32
    rcfg = resolve_tricks(TrainConfig(model="resnet18", tricks="off"))
    rmodel = build_model(rcfg)
    assert rmodel.conv_remat is False and rmodel.dtype == jnp.float32


def test_resolve_attention_seq_length_routing(monkeypatch, devices8):
    """'' auto-resolution (r6, measured 2D crossover surface): dense at
    seq<=256 on TPU while the materialized probs fit the routing memory
    budget, flash beyond either bound, ring under an sp axis, dense
    off-TPU; explicit --attention always wins."""
    from faster_distributed_training_tpu.cli import resolve_attention
    from faster_distributed_training_tpu.config import TrainConfig
    from faster_distributed_training_tpu.parallel import make_mesh

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert resolve_attention(
        TrainConfig(seq_len=256, batch_size=256)) == "dense"
    assert resolve_attention(
        TrainConfig(seq_len=512, batch_size=256)) == "flash"
    # r6 2D surface: large batches stay dense at short seq while the
    # probs fit (attn_route_* bench arms), flash past the memory bound
    assert resolve_attention(
        TrainConfig(seq_len=128, batch_size=512)) == "dense"
    assert resolve_attention(
        TrainConfig(seq_len=128, batch_size=1024)) == "dense"
    assert resolve_attention(
        TrainConfig(seq_len=256, batch_size=512)) == "dense"
    # bs1024/seq256: 3*4*B*H*L^2 = 6.4 GB probs > the 4 GB budget
    assert resolve_attention(
        TrainConfig(seq_len=256, batch_size=1024)) == "flash"
    # seq=384 sits past the L-crossover (flash from seq>=384 up)
    assert resolve_attention(
        TrainConfig(seq_len=384, batch_size=256)) == "flash"
    # the memory-headroom env override flips the bound, not the code
    monkeypatch.setenv("FDT_DENSE_ATTN_BUDGET_MB", "8192")
    assert resolve_attention(
        TrainConfig(seq_len=256, batch_size=1024)) == "dense"
    monkeypatch.setenv("FDT_DENSE_ATTN_BUDGET_MB", "0")
    assert resolve_attention(
        TrainConfig(seq_len=128, batch_size=64)) == "flash"
    monkeypatch.delenv("FDT_DENSE_ATTN_BUDGET_MB")
    assert resolve_attention(TrainConfig(seq_len=512,
                                         attention="dense")) == "dense"
    # r11 4-impl surface: a dedicated sp axis routes sequence-parallel —
    # ulysses when the axis divides heads AND seq (lower interconnect
    # volume, the measured-arm-backed preference), ring otherwise
    sp_mesh = make_mesh(("dp", "sp"), (1, 8), devices8)
    assert resolve_attention(TrainConfig(seq_len=2048), sp_mesh) == "ulysses"
    assert resolve_attention(
        TrainConfig(seq_len=2048, n_heads=6), sp_mesh) == "ring"
    # seq % sp != 0: NEITHER sp strategy can serve it (shard_map needs
    # the sequence to divide the axis) — falls through to the 1D
    # surface instead of routing an impl that would fail at trace time
    assert resolve_attention(
        TrainConfig(seq_len=2050), sp_mesh) == "flash"
    # a (data, model) tp mesh goes sequence-parallel only from the first
    # measured long-context cell up; below it the 1D surface rules
    tp_mesh = make_mesh(("dp", "tp"), (4, 2), devices8)
    assert resolve_attention(TrainConfig(seq_len=2048), tp_mesh) == "ulysses"
    assert resolve_attention(
        TrainConfig(seq_len=2048, n_heads=7), tp_mesh) == "ring"
    assert resolve_attention(
        TrainConfig(seq_len=2049), tp_mesh) == "flash"   # seq % tp != 0
    assert resolve_attention(
        TrainConfig(seq_len=256, batch_size=256), tp_mesh) == "dense"
    assert resolve_attention(
        TrainConfig(seq_len=512, batch_size=256), tp_mesh) == "flash"
    # mixed sp+tp mesh: divisibility must be validated against the axis
    # the model will EXECUTE over (seq_parallel_axis prefers sp) — seq
    # 2050 divides tp=2 but not sp=4, and routing it by the tp check
    # would crash shard_map at trace time over the sp axis
    mix_mesh = make_mesh(("dp", "sp", "tp"), (1, 4, 2), devices8)
    assert resolve_attention(TrainConfig(seq_len=2050), mix_mesh) == "flash"
    assert resolve_attention(TrainConfig(seq_len=2048),
                             mix_mesh) == "ulysses"
    # axis ALIAS unification (r11 satellite): '--mesh dp=4,model=2'
    # builds a canonical tp axis, so routing can't miss it by name
    alias_mesh = make_mesh(("dp", "model"), (4, 2), devices8)
    assert "tp" in alias_mesh.axis_names
    assert resolve_attention(TrainConfig(seq_len=2048),
                             alias_mesh) == "ulysses"
    monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
    assert resolve_attention(TrainConfig(seq_len=512)) == "dense"
    assert resolve_attention(TrainConfig(seq_len=512), tp_mesh) == "dense"


def test_attn_route_surface_cells_cite_measured_arms():
    """VERDICT r5 #5 acceptance: every cell the 2D routing surface
    serves cites a bench arm that bench.py actually measures — either an
    attn_route_* cell in bench.ATTN_ROUTE_BENCH_CELLS or a tracked
    transformer arm present in the committed BENCH_LATEST.json."""
    import importlib.util
    import json as _json
    import os as _os
    import re as _re

    from faster_distributed_training_tpu.cli import _ATTN_ROUTE_SURFACE

    here = _os.path.join(_os.path.dirname(__file__), "..")
    spec = importlib.util.spec_from_file_location(
        "bench", _os.path.join(here, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    with open(_os.path.join(here, "BENCH_LATEST.json")) as fh:
        latest = _json.load(fh)

    assert _ATTN_ROUTE_SURFACE, "routing surface must not be empty"
    cell = {c[:2]: c[2] for c in (bench.ATTN_ROUTE_BENCH_CELLS
                                  + bench.ATTN_ROUTE_SP_BENCH_CELLS)}
    for bs, seq, impl, arm, cond in _ATTN_ROUTE_SURFACE:
        if arm.startswith("attn_route_"):
            m = _re.match(r"attn_route_bs(\d+)_seq(\d+)_(\w+?)_step_ms$",
                          arm)
            assert m, arm
            abs_, aseq, aimpl = int(m.group(1)), int(m.group(2)), m.group(3)
            assert (abs_, aseq) == (bs, seq), (arm, bs, seq)
            assert (bs, seq) in cell, f"{arm}: no bench arm for cell"
            assert aimpl in cell[(bs, seq)], f"{arm}: impl not measured"
        else:
            # r5-measured cells ride the round-tracked transformer arms
            assert arm in latest, f"{arm} not in BENCH_LATEST.json"
        # the surface's impl must agree with what resolve_attention's
        # rule actually returns for the cell (table and code in sync)
        assert impl == expect_route(bs, seq, cond), (bs, seq, impl, cond)


def expect_route(bs, seq, cond):
    """What resolve_attention's code actually returns for a surface row
    — evaluated through the REAL function with a mesh matching the
    row's condition, so the table cannot drift from the rule."""
    import jax

    from faster_distributed_training_tpu.cli import resolve_attention
    from faster_distributed_training_tpu.config import TrainConfig
    from faster_distributed_training_tpu.parallel import make_mesh

    if cond == "":
        # mesh-independent rows are the r6 TPU dense/flash crossover
        from unittest import mock
        with mock.patch.object(jax, "default_backend", lambda: "tpu"):
            return resolve_attention(
                TrainConfig(seq_len=seq, batch_size=bs))
    # sp rows: an 8-way sequence-capable axis; "sp" = divisible heads
    # (default h=8), "sp_ragged" = heads the axis doesn't divide
    if len(jax.devices()) < 8:
        import pytest
        pytest.skip("sp surface rows need an 8-device mesh, host "
                    f"exposes {len(jax.devices())}")
    mesh = make_mesh(("dp", "sp"), (1, 8), jax.devices()[:8])
    heads = 8 if cond == "sp" else 6
    return resolve_attention(
        TrainConfig(seq_len=seq, batch_size=bs, n_heads=heads), mesh)


def test_ffn_impl_pallas_mesh_routing(devices8, monkeypatch):
    """--ffn_impl pallas: data-sharded meshes (dp/fsdp/sp) keep the
    kernel (shard_map per-shard path, mesh handed to the model); since
    r19 tp meshes ALSO keep it (Megatron column/row tiles through
    parallel/kernel_shard.py) when d_ff/seq divide — the flax
    composition survives only as the registered warned fallback
    (non-dividing shapes, or FDT_KERNEL_SHARD=0)."""
    import warnings as _w

    from faster_distributed_training_tpu.cli import build_model
    from faster_distributed_training_tpu.config import TrainConfig
    from faster_distributed_training_tpu.parallel import make_mesh

    cfg = TrainConfig(model="transformer", num_classes=4, seq_len=8,
                      n_layers=1, d_model=16, d_ff=32, n_heads=2,
                      ffn_impl="pallas")
    for axes, shape, expect in ((("dp",), (8,), "pallas"),
                                (("dp", "sp"), (1, 8), "pallas"),
                                (("dp", "tp"), (1, 8), "pallas"),
                                (("dp",), (1,), "pallas")):
        mesh = make_mesh(axes, shape, devices8[:int(np.prod(shape))])
        with _w.catch_warnings(record=True) as rec:
            _w.simplefilter("always")
            model = build_model(cfg, vocab_size=32, mesh=mesh)
        assert model.ffn_impl == expect, (axes, shape)
        assert not any("falling back to the flax" in str(r.message)
                       for r in rec), (axes, shape)
        if any(s > 1 for s in shape):
            assert model.mesh is mesh   # the sharded path needs the mesh
    # non-dividing seq (seq=12 doesn't divide tp=8): warned fallback
    mesh = make_mesh(("dp", "tp"), (1, 8), devices8)
    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter("always")
        model = build_model(cfg.replace(seq_len=12), vocab_size=32,
                           mesh=mesh)
    assert model.ffn_impl == "flax"
    assert any("cannot run the Megatron" in str(r.message) for r in rec)
    # kill switch: the pre-r19 reroute comes back (the bench A/B arm)
    monkeypatch.setenv("FDT_KERNEL_SHARD", "0")
    mesh = make_mesh(("dp", "tp"), (1, 8), devices8)
    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter("always")
        model = build_model(cfg, vocab_size=32, mesh=mesh)
    assert model.ffn_impl == "flax"
    assert any("FDT_KERNEL_SHARD=0" in str(r.message) for r in rec)


def test_config_mesh_and_fsdp():
    args = build_parser().parse_args(["--mesh", "dp=2,tp=4"])
    cfg = config_from_args(args)
    assert cfg.mesh_axes == ("dp", "tp") and cfg.mesh_shape == (2, 4)
    assert parse_mesh("") == ((), ())
    with pytest.raises(ValueError):
        parse_mesh("dp")
    # bare --fsdp defaults the whole mesh onto the fsdp axis
    cfg2 = config_from_args(build_parser().parse_args(["--fsdp"]))
    assert cfg2.mesh_axes == ("fsdp",)
    # --fsdp with an explicit mesh lacking an fsdp axis is an error, not a no-op
    with pytest.raises(ValueError):
        config_from_args(build_parser().parse_args(["--fsdp", "--mesh", "dp=8"]))
    # overrides kwarg applies last
    cfg3 = config_from_args(build_parser().parse_args([]), epochs=5)
    assert cfg3.epochs == 5


def test_prng_streams_distinct_and_deterministic():
    k = fdt.prng.root_key(0)
    a = fdt.prng.stream(k, "mixup")
    b = fdt.prng.stream(k, "dropout")
    assert not np.array_equal(np.asarray(a), np.asarray(b))
    assert np.array_equal(np.asarray(a), np.asarray(fdt.prng.stream(k, "mixup")))
    # step folding works under jit (traced step)
    f = jax.jit(lambda s: fdt.prng.at_step(fdt.prng.stream(k, "mixup"), s))
    assert not np.array_equal(np.asarray(f(0)), np.asarray(f(1)))


def test_make_mesh_auto(devices8):
    m = make_mesh(("dp",), devices=devices8)
    assert m.shape["dp"] == 8
    m2 = make_mesh(("dp", "tp"), (4, 2), devices8)
    assert m2.shape["dp"] == 4 and m2.shape["tp"] == 2
    # smaller than available -> first prod(shape) devices (device narrowing)
    m3 = make_mesh(("dp",), (3,), devices8)
    assert m3.size == 3 and list(np.ravel(m3.devices)) == devices8[:3]
    with pytest.raises(ValueError):
        make_mesh(("dp",), (16,), devices8)


def test_batch_sharding_runs_collective(mesh8):
    x = jnp.arange(16.0).reshape(16, 1)
    xs = jax.device_put(x, NamedSharding(mesh8, batch_spec(mesh8)))
    # a jit'd mean over a sharded batch must compile in a psum and match
    got = jax.jit(lambda a: a.mean())(xs)
    assert np.isclose(float(got), float(x.mean()))


def test_zero1_shards_only_opt_state(mesh8):
    # ZeRO-1 (ZeroRedundancyOptimizer analog, transformer_test.py:4,221-222):
    # params replicated, optimizer state sharded over the data axis.
    from faster_distributed_training_tpu.config import TrainConfig
    from faster_distributed_training_tpu.models import resnet18
    from faster_distributed_training_tpu.optim import build_optimizer
    from faster_distributed_training_tpu.parallel.placement import (
        make_put_batch, shard_train_state, train_state_shardings)
    from faster_distributed_training_tpu.train import (create_train_state,
                                                       make_train_step)

    bs = 16
    cfg = TrainConfig(model="resnet18", batch_size=bs, zero1=True,
                      optimizer="sgd", precision="fp32", mixup_mode="none",
                      epochs=1)
    model = resnet18(num_classes=10)
    tx, _ = build_optimizer(cfg, steps_per_epoch=2)
    state = create_train_state(model, tx, jnp.zeros((bs, 32, 32, 3)),
                               jax.random.PRNGKey(0),
                               init_kwargs={"train": True})
    shardings = train_state_shardings(state, mesh8, cfg)
    # every param leaf replicated
    assert all(s.spec == P()
               for s in jax.tree.leaves(shardings.params))
    # at least one big optimizer-state leaf sharded over dp
    opt_specs = [s.spec for s in jax.tree.leaves(shardings.opt_state)]
    assert any("dp" in tuple(sp) for sp in opt_specs), opt_specs
    with mesh8:
        state = shard_train_state(state, mesh8, cfg)
        batch = make_put_batch(mesh8)({
            "image": np.zeros((bs, 32, 32, 3), np.float32),
            "label": np.arange(bs, dtype=np.int32) % 10})
        step = jax.jit(make_train_step(cfg), donate_argnums=0)
        state, m = step(state, batch)
    assert np.isfinite(float(m["loss"]))


def test_fsdp_partition_params(devices8):
    mesh = make_mesh(("fsdp",), (8,), devices8)
    params = {
        "w_big": jnp.zeros((256, 64)),      # shard dim 0 (256 % 8 == 0, largest)
        "w_odd": jnp.zeros((255, 7)),       # nothing divisible -> replicated
        "bias": jnp.zeros((64,)),           # too small -> replicated
    }
    specs = fsdp_partition_params(params, mesh, min_size=1024)
    assert specs["w_big"] == P("fsdp", None)
    assert specs["w_odd"] == P()
    assert specs["bias"] == P()
    sharded = shard_pytree(params, specs, mesh)
    assert sharded["w_big"].sharding.spec == P("fsdp", None)
    # sharded compute still correct
    s = jax.jit(jnp.sum)(sharded["w_big"])
    assert float(s) == 0.0


def test_compiled_memory_bytes():
    """Static peak-memory estimate from an AOT-compiled executable — the
    fallback for backends without runtime memory_stats (utils/profiling)."""
    import jax
    import jax.numpy as jnp

    from faster_distributed_training_tpu.utils.profiling import (
        compiled_memory_bytes)

    compiled = jax.jit(lambda x: (x @ x).sum()).lower(
        jnp.ones((64, 64))).compile()
    mem = compiled_memory_bytes(compiled)
    assert mem is None or mem >= 64 * 64 * 4  # at least the argument buffer
