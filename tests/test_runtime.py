"""Native runtime core tests: the C++ library must reproduce the Python
reference implementations byte-for-byte (clean_text, HashTokenizer
encode, crc32, gather)."""

import zlib

import numpy as np
import pytest

from faster_distributed_training_tpu.data.agnews import (HashTokenizer,
                                                         bucket_length,
                                                         clean_text_py)
from faster_distributed_training_tpu.runtime import native_lib

pytestmark = pytest.mark.skipif(not native_lib.available(),
                                reason="native toolchain unavailable")

SAMPLES = [
    "World's largest oil company &amp; partners <b>announce</b> merger!",
    "Visit https://example.com/x?a=1 or www.example.org for more info",
    "  <div class='x'>Reuters &mdash; Stocks fell 3.5% on Monday...</div>",
    "AT&amp;T to buy T-Mobile&#39;s assets; shares don't move",
    "plain lowercase text with stopwords the a an of to in",
    "&lt;not a tag&gt; but &unknown; entity stays",
    "Tabs\tand\nnewlines  and   MIXED Case WORDS",
    "", "   ", "a", "'''", "100% numbers 42 and ids a1b2",
    # full-HTML5-table entities the 20-entry table era got wrong
    "caf&eacute; prices rise", "3&times;4 grid", "&copy;2024 &hearts; news",
    # bare scheme / trailing www. must NOT match the URL regex
    "http:// broken", "see www. for details", "end with www.",
    "HTTP://CAPS.example not a match", "wwww.notaurl.com ok",
]


class TestCleanText:
    def test_matches_python_reference(self):
        from faster_distributed_training_tpu.data.agnews import clean_text
        for s in SAMPLES:
            assert clean_text(s) == clean_text_py(s), repr(s)

    def test_long_text(self):
        from faster_distributed_training_tpu.data.agnews import clean_text
        s = " ".join(SAMPLES) * 50
        assert clean_text(s) == clean_text_py(s)


class TestStopwords:
    def test_native_list_equals_python_list(self):
        """kStopwords (fdt_native.cc) must be the SAME SET as
        data/agnews.py STOPWORDS — asserted directly via the
        fdt_stopwords export, not inferred from cleaner behavior."""
        from faster_distributed_training_tpu.data.agnews import STOPWORDS
        native = native_lib.stopwords()
        assert native is not None
        assert native == STOPWORDS


class TestCrc32:
    def test_matches_zlib(self):
        for data in [b"", b"a", b"hello world", bytes(range(256)) * 7]:
            assert native_lib.crc32(data) == zlib.crc32(data)


class TestEncodeBatch:
    def test_matches_hash_tokenizer(self):
        tk = HashTokenizer()
        texts = [clean_text_py(s) for s in SAMPLES]
        max_len = 16
        out = native_lib.encode_batch(texts, max_len, tk.vocab_size,
                                      tk.pad_id, tk.cls_id, tk.sep_id,
                                      tk._reserved)
        assert out is not None
        tokens, lens = out
        for i, t in enumerate(texts):
            ref = tk.encode(t, max_len)
            assert lens[i] == len(ref)
            np.testing.assert_array_equal(tokens[i, :len(ref)], ref)
            assert (tokens[i, len(ref):] == tk.pad_id).all()

    def test_truncation(self):
        tk = HashTokenizer()
        text = " ".join(f"word{i}" for i in range(100))
        out = native_lib.encode_batch([text], 8, tk.vocab_size, tk.pad_id,
                                      tk.cls_id, tk.sep_id, tk._reserved)
        tokens, lens = out
        ref = tk.encode(text, 8)
        assert len(ref) == 8 and lens[0] == 8
        np.testing.assert_array_equal(tokens[0], ref)


class TestGather:
    def test_matches_fancy_indexing(self):
        rng = np.random.default_rng(0)
        src = rng.integers(0, 256, size=(50, 8, 8, 3)).astype(np.uint8)
        idx = rng.permutation(50)[:16]
        out = native_lib.gather_u8(src, idx)
        np.testing.assert_array_equal(out, src[idx])


class TestPipelineIntegration:
    def test_agnews_encode_batch_native_vs_python(self, tmp_path,
                                                  monkeypatch):
        """AGNewsDataset.encode_batch: the native branch and the Python
        fallback return identical batch dicts (tokens, mask, labels)."""
        import csv

        from faster_distributed_training_tpu.data.agnews import AGNewsDataset

        d = tmp_path / "ag_news"
        d.mkdir()
        with open(d / "train.csv", "w", newline="") as f:
            w = csv.writer(f)
            for i, s in enumerate(t for t in SAMPLES if t.strip()):
                w.writerow([1 + i % 4, f"Title {i}", s])

        ds = AGNewsDataset(str(tmp_path), train=True, buckets=(8, 16, 32),
                           tokenizer=HashTokenizer())
        idx = list(range(len(ds)))
        native_out = ds.encode_batch(idx, max_len=32)

        monkeypatch.setattr(native_lib, "encode_batch",
                            lambda *a, **k: None)     # force Python path
        py_out = ds.encode_batch(idx, max_len=32)

        assert set(native_out) == set(py_out)
        for k in native_out:
            np.testing.assert_array_equal(native_out[k], py_out[k], err_msg=k)
