"""2D (data, model) mesh parallelism tests (r11 tentpole).

The ISSUE acceptance pins, all tier-1 on the 8-virtual-device CPU mesh
(conftest) with clean `requires_devices` degradation elsewhere:

  * `--mesh dp=4,tp=2` trains the transformer with FFN/attention/
    embedding params ACTUALLY sharded on tp (asserted via sharding
    specs + per-shard bytes, not just no-crash), loss curve allclose to
    the 1D run;
  * 2D-vs-1D forward parity: bitwise where the math is replicated,
    allclose at fp64 for the tp-sharded (psum-reordered) path;
  * the r9 sharded two-phase-commit checkpoints stay correct when
    params carry a tp dimension, and r10-style kill-at-N on a dp=2,tp=2
    mesh resumes bitwise-equal to uninterrupted;
  * the r8 K-fused dispatch twins bitwise on the 2D mesh;
  * `ShardedDeviceResidentData` computes row shards from the dp submesh
    (replicated across tp) with a bitwise host-loader batch stream, and
    falls back to replicated rows loudly only when dp genuinely doesn't
    divide the process count;
  * one canonical axis-alias table: `--mesh dp=4,model=2` and the ring/
    ulysses shard_map fallbacks agree the model axis is "tp".
"""

import math
import os
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from faster_distributed_training_tpu.config import TrainConfig, parse_mesh
from faster_distributed_training_tpu.parallel import make_mesh
from faster_distributed_training_tpu.parallel.mesh import (canonical_axes,
                                                           seq_parallel_axis,
                                                           sp_size, tp_size)
from faster_distributed_training_tpu.parallel.placement import (
    train_state_shardings)
from faster_distributed_training_tpu.parallel.sharding import (
    shard_activation)
from faster_distributed_training_tpu.resilience import faults as faults_mod


def _tiny_tf_cfg(tmp, **kw):
    """The resilience-suite tiny transformer (8 steps/epoch x 2 epochs),
    reconfigurable onto 2D meshes: h=2 and d_ff=32 divide tp=2."""
    base = dict(model="transformer", dataset="synthetic", num_classes=4,
                batch_size=8, seq_len=16, n_layers=1, d_model=16, d_ff=32,
                n_heads=2, epochs=2, subset_stride=64, optimizer="sgd",
                precision="fp32", plot=False, workers=0, log_every=0,
                donate=False, checkpoint_dir=str(tmp))
    base.update(kw)
    return TrainConfig(**base)


def _distinct_shard_indices(arr):
    """Hashable view of an array's distinct addressable shard indices
    (slice objects are unhashable on this jaxlib)."""
    return {tuple((s.start, s.stop) for s in sh.index)
            for sh in arr.addressable_shards}


def _tree_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _tree_allclose(a, b, rtol, atol=0.0):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=rtol, atol=atol)


class TestMeshConstruction:
    def test_canonical_aliases(self):
        assert canonical_axes(("dp", "model")) == ("dp", "tp")
        assert canonical_axes(("data", "mp", "seq")) == ("dp", "tp", "sp")
        assert parse_mesh("dp=4,model=2") == (("dp", "tp"), (4, 2))
        with pytest.raises(ValueError, match="duplicate canonical"):
            canonical_axes(("tp", "model"))

    def test_make_mesh_2d(self, requires_devices):
        requires_devices(8)
        mesh = make_mesh(("dp", "model"), (4, 2))
        assert mesh.axis_names == ("dp", "tp")
        assert dict(mesh.shape) == {"dp": 4, "tp": 2}
        # row-major reshape: the model axis is the fastest-varying, so a
        # tp pair sits on adjacent devices (the ICI-nearest analog the
        # TPU path gets from create_device_mesh)
        ids = np.vectorize(lambda d: d.id)(mesh.devices)
        assert ids[0, 1] - ids[0, 0] == 1

    def test_axis_helpers(self, requires_devices):
        requires_devices(8)
        m2 = make_mesh(("dp", "tp"), (4, 2))
        assert tp_size(m2) == 2 and sp_size(m2) == 1
        assert seq_parallel_axis(m2) == ("tp", 2)
        msp = make_mesh(("dp", "sp"), (2, 4))
        assert seq_parallel_axis(msp) == ("sp", 4)
        assert seq_parallel_axis(None) == (None, 1)
        m1 = make_mesh(("dp",), (8,))
        assert tp_size(m1) == 1 and seq_parallel_axis(m1) == (None, 1)


class TestShardActivation:
    def test_filters_and_identity(self, requires_devices):
        requires_devices(8)
        mesh = make_mesh(("dp", "tp"), (4, 2))
        x = jnp.arange(8 * 6 * 4, dtype=jnp.float32).reshape(8, 6, 4)
        y = shard_activation(x, mesh, (("dp",), "tp", None))
        np.testing.assert_array_equal(np.asarray(y), np.asarray(x))
        assert y.sharding.spec[1] == "tp", y.sharding.spec
        # non-divisible dim annotations are dropped, absent axes ignored
        z = shard_activation(x, mesh, (None, ("sp",), "tp"))
        np.testing.assert_array_equal(np.asarray(z), np.asarray(x))
        assert shard_activation(x, None, (None, None, None)) is x


class TestForwardParity:
    """2D-vs-1D forward/backward parity: replicated math bitwise,
    tp-sharded FFN/attention allclose at fp64."""

    def _model_and_batch(self, dtype, mesh=None):
        from faster_distributed_training_tpu.models import Transformer
        model = Transformer(n_class=4, vocab=64, n_layers=1, h=2,
                            d_model=16, d_ff=32, d_hidden=16, maxlen=16,
                            dtype=dtype, param_dtype=dtype, mesh=mesh)
        rr = np.random.default_rng(0)
        tokens = rr.integers(0, 64, size=(8, 16)).astype(np.int32)
        mask = np.ones((8, 16), np.int32)
        params = model.init({"params": jax.random.PRNGKey(0)},
                            jnp.asarray(tokens), mask=jnp.asarray(mask),
                            train=False)
        return model, params, tokens, mask

    def test_replicated_math_bitwise(self, requires_devices, devices8):
        requires_devices(8)
        model, params, tokens, mask = self._model_and_batch(jnp.float32)
        logits = {}
        for name, axes, shape in (("1d", ("dp",), (8,)),
                                  ("2d", ("dp", "tp"), (4, 2))):
            mesh = make_mesh(axes, shape, devices8)
            from jax.sharding import NamedSharding, PartitionSpec as P
            batch = jax.device_put(jnp.asarray(tokens),
                                   NamedSharding(mesh, P("dp")))
            m = jax.device_put(jnp.asarray(mask),
                               NamedSharding(mesh, P("dp")))
            p = jax.device_put(params, NamedSharding(mesh, P()))
            logits[name] = np.asarray(jax.jit(
                lambda pp, t, mm: model.apply(pp, t, mask=mm, train=False)
            )(p, batch, m))
        np.testing.assert_array_equal(logits["1d"], logits["2d"])

    @pytest.mark.slow
    def test_tp_sharded_allclose_fp64(self, requires_devices, devices8):
        """Whole-model tp-sharded parity.  `-m slow`: the coverage is
        the union of test_encoder_layer_tp_fp64 (the tp math at fp64)
        and TestTrain2D's e2e loss pin, and the tier-1 budget is tight
        — run with `pytest -m slow` for the full-model check."""
        requires_devices(8)
        mesh = make_mesh(("dp", "tp"), (4, 2), devices8)
        model, params, tokens, mask = self._model_and_batch(jnp.float64)
        sharded_model, _, _, _ = self._model_and_batch(jnp.float64,
                                                       mesh=mesh)
        from jax.sharding import NamedSharding, PartitionSpec as P
        from faster_distributed_training_tpu.parallel.sharding import (
            apply_tp_rules)
        specs = apply_tp_rules(params["params"], mesh)
        sharded_params = {"params": jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            params["params"], specs,
            is_leaf=lambda x: isinstance(x, P))}
        # the rules actually hit: qkv head dim + both FFN kernels
        qkv = sharded_params["params"]["layer_0"]["attn"]["qkv"]["kernel"]
        assert "tp" in (qkv.sharding.spec[2],), qkv.sharding.spec
        assert len(_distinct_shard_indices(qkv)) == 2

        def make_loss(mdl, t, mm):
            def f(p):
                out = mdl.apply(p, t, mask=mm, train=False)
                return jnp.sum(out ** 2), out
            return f

        t64 = jnp.asarray(tokens)
        m64 = jnp.asarray(mask)
        (l_ref, o_ref), g_ref = jax.jit(jax.value_and_grad(
            make_loss(model, t64, m64), has_aux=True))(params)
        bt = jax.device_put(t64, NamedSharding(mesh, P("dp")))
        bm = jax.device_put(m64, NamedSharding(mesh, P("dp")))
        (l_tp, o_tp), g_tp = jax.jit(jax.value_and_grad(
            make_loss(sharded_model, bt, bm),
            has_aux=True))(sharded_params)
        # the classifier's deliberate fp32 logits island (reference
        # parity) caps whole-model agreement at fp32 epsilon; the fp64
        # tier lives in test_encoder_layer_tp_fp64 below
        np.testing.assert_allclose(np.asarray(o_ref), np.asarray(o_tp),
                                   rtol=5e-6, atol=5e-6)
        assert math.isclose(float(l_ref), float(l_tp), rel_tol=1e-5)
        _tree_allclose(g_ref, g_tp, rtol=2e-5, atol=2e-6)

    def test_encoder_layer_tp_fp64(self, requires_devices, devices8):
        """The tp-sharded FFN/attention math itself (no fp32 logits
        island): one EncoderLayer at fp64, tp-sharded params + the
        activation annotations, vs the unsharded single-program run.

        Measured bound (this PR): the model's deliberate reference-
        parity fp32 islands — the TorchLayerNorm core and the softmax —
        compile with different fusion inside an SPMD-partitioned
        program, so ANY sharding annotation shifts those islands'
        rounding by ~fp32 eps (~3.6e-7 absolute here; verified the
        islands are placement-invariant in isolation and the no-
        constraint program is bitwise).  The fp64 claim is therefore
        fp32-island-bounded: everything OUTSIDE the islands — the
        tp-sharded matmuls and their psums — agrees to fp32-eps-class
        tolerance at fp64, and a genuine tp math bug (wrong shard, a
        dropped psum) shows up orders of magnitude above it."""
        requires_devices(8)
        from jax.sharding import NamedSharding, PartitionSpec as P
        from faster_distributed_training_tpu.models.transformer import (
            EncoderLayer)
        from faster_distributed_training_tpu.parallel.sharding import (
            apply_tp_rules)
        mesh = make_mesh(("dp", "tp"), (4, 2), devices8)
        rr = np.random.default_rng(1)
        h = jnp.asarray(rr.normal(size=(8, 16, 16)), jnp.float64)
        mask = jnp.ones((8, 1, 1, 16), jnp.int32)
        ref_layer = EncoderLayer(h=2, d_model=16, d_ff=32,
                                 dtype=jnp.float64,
                                 param_dtype=jnp.float64)
        params = ref_layer.init({"params": jax.random.PRNGKey(7)}, h,
                                mask, False)
        tp_layer = EncoderLayer(h=2, d_model=16, d_ff=32,
                                dtype=jnp.float64,
                                param_dtype=jnp.float64, mesh=mesh)
        specs = apply_tp_rules(params["params"], mesh)
        tp_params = {"params": jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            params["params"], specs,
            is_leaf=lambda x: isinstance(x, P))}
        hs = jax.device_put(h, NamedSharding(mesh, P("dp")))

        def make_loss(mdl, hh):
            def f(p):
                out = mdl.apply(p, hh, mask, False)
                return jnp.sum(out ** 2), out
            return f

        (l_ref, o_ref), g_ref = jax.jit(jax.value_and_grad(
            make_loss(ref_layer, h), has_aux=True))(params)
        (l_tp, o_tp), g_tp = jax.jit(jax.value_and_grad(
            make_loss(tp_layer, hs), has_aux=True))(tp_params)
        np.testing.assert_allclose(np.asarray(o_ref), np.asarray(o_tp),
                                   rtol=1e-5, atol=2e-6)
        assert math.isclose(float(l_ref), float(l_tp), rel_tol=1e-6)
        # grads are O(10-100) here: atol tracks fp32 eps at that scale
        _tree_allclose(g_ref, g_tp, rtol=2e-5, atol=1e-5)


class TestRingUlyssesOverTpAxis:
    """The axis-unification satellite at the ops layer: ring/ulysses run
    over a mesh whose ONLY model axis is named tp (sp_axis='tp'), and
    match the dense reference — previously they required an axis
    literally named 'sp'."""

    def _qkvm(self, B=4, H=4, L=16, D=8):
        rr = np.random.default_rng(5)
        q, k, v = (jnp.asarray(rr.normal(size=(B, H, L, D)), jnp.float32)
                   for _ in range(3))
        lens = rr.integers(L // 2, L + 1, size=(B,))
        mask = jnp.asarray((np.arange(L)[None, :] < lens[:, None])
                           .astype(np.int32))
        return q, k, v, mask

    def _dense_ref(self, q, k, v, mask):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(q.shape[-1])
        s = jnp.where(mask[:, None, None, :] == 0, -1e9, s)
        p = jax.nn.softmax(s.astype(jnp.float32), axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v).astype(q.dtype)

    @pytest.mark.parametrize("impl", ["ring", "ulysses"])
    def test_matches_dense_over_tp(self, impl, requires_devices, devices8):
        requires_devices(8)
        from faster_distributed_training_tpu.ops.ring_attention import (
            ring_self_attention)
        from faster_distributed_training_tpu.ops.ulysses_attention import (
            ulysses_self_attention)
        mesh = make_mesh(("dp", "tp"), (4, 2), devices8)
        q, k, v, mask = self._qkvm()
        fn = (ring_self_attention if impl == "ring"
              else ulysses_self_attention)
        out = fn(q, k, v, mask, mesh, sp_axis="tp")
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(self._dense_ref(q, k, v,
                                                              mask)),
                                   rtol=2e-5, atol=2e-5)

    def test_build_model_flash_tp_routing(self, requires_devices,
                                          devices8, monkeypatch):
        """r19: flash on a serviceable tp mesh (heads divide tp) KEEPS
        the kernel — routed head-sharded through parallel/kernel_shard
        — with no capability warning; the warned sequence-parallel
        fallback survives for non-dividing heads and under the
        FDT_KERNEL_SHARD=0 kill switch."""
        requires_devices(8)
        from faster_distributed_training_tpu.cli import build_model
        mesh = make_mesh(("dp", "tp"), (4, 2), devices8)
        cfg = TrainConfig(model="transformer", num_classes=4, seq_len=16,
                          n_layers=1, d_model=16, d_ff=32, n_heads=2,
                          attention="flash")
        with warnings.catch_warnings(record=True) as rec:
            warnings.simplefilter("always")
            model = build_model(cfg, vocab_size=64, mesh=mesh)
        assert model.attention_impl == "flash"    # h=2 divides tp=2
        assert not any("flash" in str(w.message).lower() for w in rec)
        # non-dividing heads: the REGISTERED warned fallback remains
        cfg1 = cfg.replace(n_heads=1)
        with warnings.catch_warnings(record=True) as rec:
            warnings.simplefilter("always")
            model1 = build_model(cfg1, vocab_size=64, mesh=mesh)
        assert model1.attention_impl in ("ring", "ulysses", "dense")
        assert any("cannot run head-sharded" in str(w.message)
                   for w in rec)
        # kill switch restores the pre-r19 reroute (the bench A/B arm)
        monkeypatch.setenv("FDT_KERNEL_SHARD", "0")
        with warnings.catch_warnings(record=True) as rec:
            warnings.simplefilter("always")
            model0 = build_model(cfg, vocab_size=64, mesh=mesh)
        assert model0.attention_impl == "ulysses"  # h=2, seq=16 divide tp
        assert model0.sp_axis == "tp"
        assert any("FDT_KERNEL_SHARD=0" in str(w.message) for w in rec)


class TestTrain2D:
    """The headline acceptance: --mesh dp=4,tp=2 trains with params
    actually sharded on tp, loss allclose to the 1D (same-dp) run.

    The 1D/2D/K=4 runs are class-scoped fixtures: the K=4 twin's K=1
    reference IS the 2D acceptance run (same config), so the class
    costs three run_training compiles, not five — the tier-1 budget
    guardrail (conftest) is why."""

    def _run(self, tmp, **kw):
        from faster_distributed_training_tpu.cli import run_training
        return run_training(_tiny_tf_cfg(tmp, **kw), log=lambda *_: None)

    @pytest.fixture(scope="class")
    def run_1d(self, tmp_path_factory, requires_devices):
        requires_devices(8)
        return self._run(tmp_path_factory.mktemp("m1d"), epochs=1,
                         subset_stride=128,
                         mesh_axes=("dp",), mesh_shape=(4,))

    @pytest.fixture(scope="class")
    def run_2d(self, tmp_path_factory, requires_devices):
        requires_devices(8)
        return self._run(tmp_path_factory.mktemp("m2d"), epochs=1,
                         subset_stride=128,
                         mesh_axes=("dp", "tp"), mesh_shape=(4, 2))

    def test_dp4_tp2_trains_sharded_and_allclose(self, run_1d, run_2d):
        ref, got = run_1d, run_2d
        model_params = got["state"].params["model"]
        # sharding specs assert the tp placement (not just no-crash):
        ruled = {
            "attn/qkv/kernel":
                model_params["layer_0"]["attn"]["qkv"]["kernel"],
            "ffn/Dense_0/kernel":
                model_params["layer_0"]["ffn"]["Dense_0"]["kernel"],
            "ffn/Dense_1/kernel":
                model_params["layer_0"]["ffn"]["Dense_1"]["kernel"],
            "token_embedding":
                model_params["Embeddings_0"]["token_embedding"],
        }
        for name, leaf in ruled.items():
            spec = leaf.sharding.spec
            assert "tp" in tuple(spec), (name, spec)
            # per-param footprint ~1/tp: each distinct shard holds half
            idx = _distinct_shard_indices(leaf)
            assert len(idx) == 2, (name, idx)
            shard = leaf.addressable_shards[0]
            assert shard.data.nbytes * 2 == leaf.nbytes, name
        unruled = model_params["layer_0"]["ln_attn"]["scale"]
        assert tuple(unruled.sharding.spec) in ((), (None,)), \
            unruled.sharding.spec
        # the loss curve stays the 1D run's (tp only reorders psums)
        np.testing.assert_allclose(got["history"]["train_loss"],
                                   ref["history"]["train_loss"],
                                   rtol=2e-4)
        np.testing.assert_allclose(got["history"]["test_loss"],
                                   ref["history"]["test_loss"],
                                   rtol=2e-4)
        _tree_allclose(ref["state"].params, got["state"].params,
                       rtol=5e-4, atol=1e-6)

    def test_fused_dispatch_k4_twin_2d(self, tmp_path, run_2d):
        """r8's K-fused dispatch on the 2D mesh.  On 1D meshes the
        transformer twins bitwise; on the tp mesh the scan and unfused
        programs are DIFFERENT SPMD partitionings, and XLA:CPU compiles
        the fp32 LN/softmax islands with different fusion per program
        (~1 ULP/step — the same measured class as r8's ResNet
        scan-rounding caveat and this file's fp64 parity bound), so the
        cross-program pin is tight-allclose; the within-program
        determinism that resume correctness needs is pinned bitwise by
        test_kill_at_n_resumes_bitwise_2d below."""
        k1 = run_2d
        k4 = self._run(tmp_path / "k4", epochs=1, subset_stride=128,
                       steps_per_dispatch=4,
                       mesh_axes=("dp", "tp"), mesh_shape=(4, 2))
        assert int(k1["state"].step) == int(k4["state"].step) == 4
        _tree_allclose(k1["state"].params, k4["state"].params,
                       rtol=1e-3, atol=1e-6)
        np.testing.assert_allclose(k1["history"]["train_loss"],
                                   k4["history"]["train_loss"],
                                   rtol=1e-4)

    def test_kill_at_n_resumes_bitwise_2d(self, tmp_path, monkeypatch,
                                          requires_devices):
        requires_devices(8)
        import faster_distributed_training_tpu.train.checkpoint as ckpt
        from faster_distributed_training_tpu.cli import run_training
        mesh_kw = dict(mesh_axes=("dp", "tp"), mesh_shape=(2, 2),
                       epochs=1)
        ref = self._run(tmp_path / "ref", **mesh_kw)
        monkeypatch.setenv(faults_mod.ENV_DIE, "4")
        got = run_training(
            _tiny_tf_cfg(tmp_path / "killed", checkpoint_every=2,
                         supervise=True, **mesh_kw),
            log=lambda *_: None)
        assert int(got["state"].step) == int(ref["state"].step) == 8
        assert got["goodput_restarts"] == 1
        _tree_equal(ckpt._state_pytree(ref["state"]),
                    ckpt._state_pytree(got["state"]))


class TestShardedCheckpointTp:
    """r9 acceptance carried to 2D: replica-0-owned shard snapshots stay
    a disjoint exact cover when params carry a tp dimension, and the
    two-phase sharded save/restore roundtrips bitwise."""

    def _sharded_state(self, devices8):
        from faster_distributed_training_tpu.models import Transformer
        from faster_distributed_training_tpu.optim import build_optimizer
        from faster_distributed_training_tpu.train import create_train_state
        mesh = make_mesh(("dp", "tp"), (2, 2), devices8[:4])
        cfg = TrainConfig(model="transformer", num_classes=4, batch_size=4,
                          seq_len=8, optimizer="sgd", precision="fp32",
                          donate=False)
        model = Transformer(n_class=4, vocab=32, n_layers=1, h=2,
                            d_model=16, d_ff=32, d_hidden=16, maxlen=8)
        tx, _ = build_optimizer(cfg, steps_per_epoch=2)
        state = create_train_state(model, tx,
                                   jnp.zeros((4, 8), jnp.int32),
                                   jax.random.PRNGKey(3),
                                   init_kwargs={"train": True})
        shardings = train_state_shardings(state, mesh, cfg)
        return jax.tree.map(jax.device_put, state, shardings), mesh

    def test_tp_shard_snapshot_roundtrip(self, tmp_path, devices8,
                                         requires_devices):
        requires_devices(8)
        import faster_distributed_training_tpu.train.checkpoint as ckpt
        state, mesh = self._sharded_state(devices8)
        blocks = ckpt.host_shard_snapshot(state)
        # the MODEL param only: the optimizer-state mirror of qkv stays
        # replicated (the TP overlay covers params; ZeRO-style tp
        # sharding of opt state is a documented ROADMAP follow-on)
        qkv_blocks = [(idx, arr) for key, idx, arr in blocks
                      if "['params']" in key
                      and key.endswith("['qkv']['kernel']")]
        # tp=2: the replica-0 cover emits one block PER tp shard (half
        # the head dim each), disjoint — not one replicated whole
        assert len(qkv_blocks) == 2
        got = sorted((i[2].start, i[2].stop) for i, _ in qkv_blocks)
        assert got == [(0, 1), (1, 2)], got
        path = os.path.join(str(tmp_path), "ck_step_000000004")
        ckpt.write_host_shards(path, 0, blocks)
        ckpt.commit_sharded_checkpoint(
            path, {"step": 4, "epoch": 1, "best_acc": 0.25}, n_hosts=1,
            timeout_s=5.0)
        restored, epoch, best = ckpt.restore_sharded_checkpoint(
            str(tmp_path), "ck_step_000000004", state)
        assert epoch == 1 and best == 0.25
        _tree_equal(ckpt._state_pytree(restored),
                    ckpt._state_pytree(state))


class TestResident2D:
    """Satellite: ShardedDeviceResidentData on a tp-carrying mesh —
    rows shard over the dp submesh only (replicated across tp), the
    batch stream stays bitwise the host loader's, and a dp that
    genuinely doesn't divide the process count falls back to replicated
    rows with a warning instead of the r9 hard reject."""

    def test_dp4_tp2_stream_bitwise_host_loader(self, requires_devices):
        requires_devices(8)
        from faster_distributed_training_tpu.data import (
            BatchLoader, ShardedDeviceResidentData, synthetic_cifar)
        x, y = synthetic_cifar(70, seed=3)
        bs, seed = 16, 42
        mesh = make_mesh(("dp", "tp"), (4, 2))
        res = ShardedDeviceResidentData((x, y), bs, seed=seed, mesh=mesh)
        # rows shard over dp only: each of the 4 dp groups holds 1/4 of
        # the (padded) rows; the 2 tp devices of a group replicate them
        for arr in res.arrays.values():
            idx = _distinct_shard_indices(arr)
            assert len(idx) == 4, idx
            rows = {sh.data.shape[0] for sh in arr.addressable_shards}
            assert rows == {res._n_pad // 4}, rows
        for epoch in (0, 2):
            view = res.epoch_arrays(epoch)
            imgs = np.asarray(view["image"])
            labs = np.asarray(view["label"])
            loader = BatchLoader((x, y), bs, epoch=epoch, seed=seed)
            for b, (want, got_i, got_l) in enumerate(
                    zip(loader, imgs, labs)):
                if b >= res.steps_per_epoch:
                    break
                np.testing.assert_array_equal(got_i, want["image"])
                np.testing.assert_array_equal(got_l, want["label"])

    def test_tp_heavy_mesh_falls_back_replicated(self, monkeypatch,
                                                 requires_devices):
        requires_devices(8)
        from faster_distributed_training_tpu.data import (
            BatchLoader, ShardedDeviceResidentData, synthetic_cifar)
        x, y = synthetic_cifar(64, seed=3)
        mesh = make_mesh(("dp", "tp"), (1, 8))
        # simulate a 2-process pod: dp_size=1 % 2 != 0 — the r9 check
        # hard-raised here; now rows replicate with a warning and the
        # stream machinery keeps working
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        with warnings.catch_warnings(record=True) as rec:
            warnings.simplefilter("always")
            res = ShardedDeviceResidentData((x, y), 16, seed=1, mesh=mesh,
                                            process_count=2)
        assert res._rows_replicated
        assert any("REPLICATED" in str(w.message) for w in rec)
        monkeypatch.undo()
        view = res.epoch_arrays(0)
        imgs = np.asarray(view["image"])
        loaders = [BatchLoader((x, y), 8, epoch=0, seed=1,
                               process_index=pi, process_count=2)
                   for pi in range(2)]
        plans = [ld.plan() for ld in loaders]
        for b in range(res.steps_per_epoch):
            want = np.concatenate(
                [loaders[pi].materialize(plans[pi][b])["image"]
                 for pi in range(2)])
            np.testing.assert_array_equal(imgs[b], want)
