"""Attention stack tests: blockwise == dense, flash (interpret) == dense,
ring == dense under an sp-sharded mesh, gradients included — the coverage
the reference lacks entirely (SURVEY.md §4)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from faster_distributed_training_tpu.ops.attention import (
    blockwise_attention, dense_attention_reference)
from faster_distributed_training_tpu.ops.flash_attention import flash_attention
from faster_distributed_training_tpu.ops.ring_attention import (
    ring_self_attention)
from faster_distributed_training_tpu.parallel import make_mesh


def _qkv(key, B=2, H=2, L=32, D=16, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    shape = (B, H, L, D)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


def _padding_mask(key, B=2, L=32):
    lens = jax.random.randint(key, (B,), L // 2, L + 1)
    return (jnp.arange(L)[None, :] < lens[:, None]).astype(jnp.int32)


class TestBlockwise:
    def test_matches_dense_no_mask(self):
        q, k, v = _qkv(jax.random.PRNGKey(0))
        out = blockwise_attention(q, k, v, block_k=8)
        ref = dense_attention_reference(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_analytic_causal_matches_dense(self):
        # causal=True builds per-key-block bias analytically — never an
        # [Lq, Lk] mask tensor; must equal a dense lower-triangular mask,
        # incl. with a block_k that does not divide L (padding interplay)
        q, k, v = _qkv(jax.random.PRNGKey(40), L=24)
        tri = jnp.tril(jnp.ones((24, 24), jnp.int32))[None, None]
        ref = dense_attention_reference(q, k, v, tri)
        for bk in (8, 7, 24):
            out = blockwise_attention(q, k, v, block_k=bk, causal=True)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       rtol=1e-5, atol=1e-5,
                                       err_msg=f"block_k={bk}")

    def test_matches_dense_with_padding_mask(self):
        q, k, v = _qkv(jax.random.PRNGKey(1))
        mask = _padding_mask(jax.random.PRNGKey(2))[:, None, None, :]
        out = blockwise_attention(q, k, v, mask, block_k=8)
        ref = dense_attention_reference(q, k, v, mask)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_ragged_block_size(self):
        # Lk=32 with block_k=10 -> padded final block must not change result
        q, k, v = _qkv(jax.random.PRNGKey(3))
        out = blockwise_attention(q, k, v, block_k=10)
        ref = dense_attention_reference(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_gradients_match_dense(self):
        q, k, v = _qkv(jax.random.PRNGKey(4), B=1, H=1, L=16, D=8)
        mask = _padding_mask(jax.random.PRNGKey(5), B=1, L=16)[:, None, None]

        def loss_block(q, k, v):
            return jnp.sum(blockwise_attention(q, k, v, mask, block_k=4) ** 2)

        def loss_dense(q, k, v):
            return jnp.sum(dense_attention_reference(q, k, v, mask) ** 2)

        g1 = jax.grad(loss_block, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)


class TestFlash:
    def test_fallback_matches_dense(self):
        q, k, v = _qkv(jax.random.PRNGKey(6))
        mask = _padding_mask(jax.random.PRNGKey(7))[:, None, None, :]
        out = flash_attention(q, k, v, mask)
        ref = dense_attention_reference(q, k, v, mask)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_pallas_interpret_matches_dense(self):
        os.environ["FDT_FORCE_PALLAS_INTERPRET"] = "1"
        try:
            q, k, v = _qkv(jax.random.PRNGKey(8), L=16, D=8)
            mask = _padding_mask(jax.random.PRNGKey(9), L=16)[:, None, None, :]
            out = flash_attention(q, k, v, mask, block_q=8)
            ref = dense_attention_reference(q, k, v, mask)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       rtol=1e-5, atol=1e-5)
        finally:
            del os.environ["FDT_FORCE_PALLAS_INTERPRET"]

    def test_backward_runs(self):
        q, k, v = _qkv(jax.random.PRNGKey(10), B=1, H=1, L=16, D=8)
        g = jax.grad(lambda q_: jnp.sum(flash_attention(q_, k, v) ** 2))(q)
        ref = jax.grad(lambda q_: jnp.sum(
            dense_attention_reference(q_, k, v) ** 2))(q)
        np.testing.assert_allclose(np.asarray(g), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)


class TestRing:
    @pytest.fixture()
    def sp_mesh(self, devices8):
        return make_mesh(("dp", "sp"), (2, 4), devices8)

    def test_matches_dense(self, sp_mesh):
        q, k, v = _qkv(jax.random.PRNGKey(11), B=4, H=2, L=32, D=16)
        mask = _padding_mask(jax.random.PRNGKey(12), B=4, L=32)
        out = ring_self_attention(q, k, v, mask, sp_mesh)
        ref = dense_attention_reference(q, k, v, mask[:, None, None, :])
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_no_mask(self, sp_mesh):
        q, k, v = _qkv(jax.random.PRNGKey(13), B=4, H=2, L=32, D=16)
        out = ring_self_attention(q, k, v, None, sp_mesh)
        ref = dense_attention_reference(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_causal(self, sp_mesh):
        q, k, v = _qkv(jax.random.PRNGKey(14), B=4, H=1, L=16, D=8)
        causal = jnp.tril(jnp.ones((16, 16), jnp.int32))[None, None]
        out = ring_self_attention(q, k, v, None, sp_mesh, causal=True)
        ref = dense_attention_reference(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_gradients_match_dense(self, sp_mesh):
        q, k, v = _qkv(jax.random.PRNGKey(15), B=4, H=1, L=16, D=8)
        mask = _padding_mask(jax.random.PRNGKey(16), B=4, L=16)

        def loss_ring(q, k, v):
            return jnp.sum(ring_self_attention(q, k, v, mask, sp_mesh) ** 2)

        def loss_dense(q, k, v):
            return jnp.sum(dense_attention_reference(
                q, k, v, mask[:, None, None, :]) ** 2)

        g1 = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_transformer_ring_forward(self, sp_mesh):
        """Transformer with attention_impl='ring' runs under jit."""
        from faster_distributed_training_tpu.models import Transformer

        model = Transformer(n_class=4, vocab=64, n_layers=1, h=2, d_model=16,
                            d_ff=32, maxlen=16, attention_impl="ring",
                            mesh=sp_mesh)
        x = jax.random.randint(jax.random.PRNGKey(17), (4, 16), 0, 64)
        variables = model.init({"params": jax.random.PRNGKey(0),
                                "dropout": jax.random.PRNGKey(1),
                                "mixup": jax.random.PRNGKey(2)},
                               x, train=False)
        dense = Transformer(n_class=4, vocab=64, n_layers=1, h=2, d_model=16,
                            d_ff=32, maxlen=16, attention_impl="dense")
        out_ring = jax.jit(
            lambda v, x: model.apply(v, x, train=False))(variables, x)
        out_dense = jax.jit(
            lambda v, x: dense.apply(v, x, train=False))(variables, x)
        np.testing.assert_allclose(np.asarray(out_ring),
                                   np.asarray(out_dense),
                                   rtol=1e-4, atol=1e-4)


class TestUlysses:
    """ops/ulysses_attention: all-to-all sequence parallelism must be
    numerically the same attention as dense — same contract as the ring,
    different collective structure (H must divide by sp)."""

    @pytest.fixture()
    def sp_mesh(self, devices8):
        return make_mesh(("dp", "sp"), (2, 4), devices8)

    def test_matches_dense(self, sp_mesh):
        from faster_distributed_training_tpu.ops.ulysses_attention import (
            ulysses_self_attention)
        q, k, v = _qkv(jax.random.PRNGKey(21), B=4, H=4, L=32, D=16)
        mask = _padding_mask(jax.random.PRNGKey(22), B=4, L=32)
        out = ulysses_self_attention(q, k, v, mask, sp_mesh)
        ref = dense_attention_reference(q, k, v, mask[:, None, None, :])
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_causal_no_mask(self, sp_mesh):
        from faster_distributed_training_tpu.ops.ulysses_attention import (
            ulysses_self_attention)
        q, k, v = _qkv(jax.random.PRNGKey(23), B=4, H=4, L=16, D=8)
        causal = jnp.tril(jnp.ones((16, 16), jnp.int32))[None, None]
        out = ulysses_self_attention(q, k, v, None, sp_mesh, causal=True)
        ref = dense_attention_reference(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_gradients_match_dense(self, sp_mesh):
        from faster_distributed_training_tpu.ops.ulysses_attention import (
            ulysses_self_attention)
        q, k, v = _qkv(jax.random.PRNGKey(24), B=4, H=4, L=16, D=8)
        mask = _padding_mask(jax.random.PRNGKey(25), B=4, L=16)

        def loss_u(q, k, v):
            return jnp.sum(ulysses_self_attention(q, k, v, mask,
                                                  sp_mesh) ** 2)

        def loss_dense(q, k, v):
            return jnp.sum(dense_attention_reference(
                q, k, v, mask[:, None, None, :]) ** 2)

        g1 = jax.grad(loss_u, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_rejects_indivisible_heads(self, sp_mesh):
        from faster_distributed_training_tpu.ops.ulysses_attention import (
            ulysses_self_attention)
        q, k, v = _qkv(jax.random.PRNGKey(26), B=4, H=2, L=16, D=8)  # 2 % 4
        with pytest.raises(ValueError, match="divisible"):
            ulysses_self_attention(q, k, v, None, sp_mesh)

    def test_transformer_ulysses_matches_dense(self, sp_mesh):
        """Transformer with attention_impl='ulysses' == dense forward."""
        from faster_distributed_training_tpu.models import Transformer

        kw = dict(n_class=4, vocab=64, n_layers=1, h=4, d_model=16,
                  d_ff=32, maxlen=16)
        model = Transformer(attention_impl="ulysses", mesh=sp_mesh, **kw)
        x = jax.random.randint(jax.random.PRNGKey(27), (4, 16), 0, 64)
        variables = model.init({"params": jax.random.PRNGKey(0),
                                "dropout": jax.random.PRNGKey(1),
                                "mixup": jax.random.PRNGKey(2)},
                               x, train=False)
        dense = Transformer(attention_impl="dense", **kw)
        out_u = jax.jit(
            lambda v, x: model.apply(v, x, train=False))(variables, x)
        out_d = jax.jit(
            lambda v, x: dense.apply(v, x, train=False))(variables, x)
        np.testing.assert_allclose(np.asarray(out_u), np.asarray(out_d),
                                   rtol=1e-4, atol=1e-4)

    def test_tp_plus_sp_matches_dense(self, devices8):
        """dp=2,tp=2,sp=2: heads split over tp AND again over sp inside
        the body — the head-parallel-inside-sequence-parallel compose."""
        from faster_distributed_training_tpu.ops.ulysses_attention import (
            ulysses_self_attention)
        mesh = make_mesh(("dp", "tp", "sp"), (2, 2, 2), devices8)
        q, k, v = _qkv(jax.random.PRNGKey(28), B=4, H=4, L=16, D=8)
        mask = _padding_mask(jax.random.PRNGKey(29), B=4, L=16)
        out = ulysses_self_attention(q, k, v, mask, mesh)
        ref = dense_attention_reference(q, k, v, mask[:, None, None, :])
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_ring_tp_plus_sp_matches_dense(self, devices8):
        from faster_distributed_training_tpu.ops.ring_attention import (
            ring_self_attention)
        mesh = make_mesh(("dp", "tp", "sp"), (2, 2, 2), devices8)
        q, k, v = _qkv(jax.random.PRNGKey(30), B=4, H=4, L=16, D=8)
        mask = _padding_mask(jax.random.PRNGKey(31), B=4, L=16)
        out = ring_self_attention(q, k, v, mask, mesh)
        ref = dense_attention_reference(q, k, v, mask[:, None, None, :])
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)


class TestFlashBackwardPolicy:
    """_flash_bwd picks dense VJP under the score-memory budget and the
    blockwise VJP above it (measured policy, ops/flash_attention.py);
    both branches must produce dense-equal gradients."""

    def _grads(self, budget, monkeypatch):
        import importlib
        # ops/__init__ re-exports the flash_attention FUNCTION under the
        # submodule's name; fetch the module itself to patch the budget
        fa = importlib.import_module(
            "faster_distributed_training_tpu.ops.flash_attention")
        monkeypatch.setattr(fa, "_DENSE_BWD_BUDGET_BYTES", budget)
        q, k, v = _qkv(jax.random.PRNGKey(50), B=2, H=2, L=32, D=16)
        mask = _padding_mask(jax.random.PRNGKey(51), B=2, L=32)

        def loss(q, k, v):
            return jnp.sum(fa.flash_attention(q, k, v, mask=mask) ** 2)

        return (q, k, v, mask), jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    def test_both_branches_match_dense(self, monkeypatch):
        (q, k, v, mask), g_dense_branch = self._grads(1 << 40, monkeypatch)
        _, g_block_branch = self._grads(0, monkeypatch)

        def loss_ref(q, k, v):
            return jnp.sum(dense_attention_reference(
                q, k, v, mask[:, None, None, :]) ** 2)

        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", g_dense_branch, g_block_branch):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5,
                                       err_msg=f"branches differ on {name}")
        for name, a, b in zip("qkv", g_dense_branch, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5,
                                       err_msg=f"vs dense ref on {name}")


class TestAttentionDropout:
    """Attention-prob dropout on the never-materialized paths
    (VERDICT r1 missing #3): the index-hash mask (ops.attention.
    dropout_keep) must (a) actually drop ~rate of the probability mass,
    (b) produce IDENTICAL outputs across dense-hash / blockwise / Pallas
    / ring / ulysses for the same seed — including under sp sharding —
    and (c) regenerate exactly in both flash backward branches."""

    RATE = 0.3

    def _seed(self):
        return jnp.uint32(20240730)

    def test_keep_fraction_and_scaling(self):
        from faster_distributed_training_tpu.ops.attention import dropout_keep
        bh = jnp.arange(8, dtype=jnp.int32)[:, None, None].reshape(8, 1, 1, 1)
        qi = jnp.arange(64, dtype=jnp.int32)[None, None, :, None]
        ki = jnp.arange(64, dtype=jnp.int32)[None, None, None, :]
        keep = dropout_keep(self._seed(), bh, qi, ki, self.RATE)
        vals = np.asarray(keep).ravel()
        frac_dropped = float((vals == 0.0).mean())
        assert abs(frac_dropped - self.RATE) < 0.02
        kept = vals[vals > 0]
        np.testing.assert_allclose(kept, 1.0 / (1.0 - self.RATE), rtol=1e-6)
        # E[keep] == 1 (unbiased)
        assert abs(float(vals.mean()) - 1.0) < 0.02
        # seed changes the pattern
        keep2 = dropout_keep(jnp.uint32(7), bh, qi, ki, self.RATE)
        assert not np.array_equal(np.asarray(keep), np.asarray(keep2))

    def test_blockwise_matches_dense_hash(self):
        q, k, v = _qkv(jax.random.PRNGKey(60), B=2, H=2, L=32, D=16)
        mask = _padding_mask(jax.random.PRNGKey(61), B=2, L=32)[:, None,
                                                                None, :]
        out = blockwise_attention(q, k, v, mask, block_k=8,
                                  dropout_rate=self.RATE,
                                  dropout_seed=self._seed())
        ref = dense_attention_reference(q, k, v, mask,
                                        dropout_rate=self.RATE,
                                        dropout_seed=self._seed())
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)
        # and differs from the undropped output
        clean = dense_attention_reference(q, k, v, mask)
        assert not np.allclose(np.asarray(out), np.asarray(clean),
                               atol=1e-3)

    def test_pallas_interpret_matches_dense_hash(self):
        os.environ["FDT_FORCE_PALLAS_INTERPRET"] = "1"
        try:
            q, k, v = _qkv(jax.random.PRNGKey(62), L=16, D=8)
            mask = _padding_mask(jax.random.PRNGKey(63),
                                 L=16)[:, None, None, :]
            out = flash_attention(q, k, v, mask, block_q=8,
                                  dropout_rate=self.RATE,
                                  dropout_seed=self._seed())
            ref = dense_attention_reference(q, k, v, mask,
                                            dropout_rate=self.RATE,
                                            dropout_seed=self._seed())
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       rtol=1e-5, atol=1e-5)
        finally:
            del os.environ["FDT_FORCE_PALLAS_INTERPRET"]

    def test_flash_backward_branches_regenerate_mask(self, monkeypatch):
        import importlib
        fa = importlib.import_module(
            "faster_distributed_training_tpu.ops.flash_attention")
        q, k, v = _qkv(jax.random.PRNGKey(64), B=2, H=2, L=32, D=16)

        def grads(budget):
            monkeypatch.setattr(fa, "_DENSE_BWD_BUDGET_BYTES", budget)

            def loss(q_, k_, v_):
                return jnp.sum(fa.flash_attention(
                    q_, k_, v_, dropout_rate=self.RATE,
                    dropout_seed=self._seed()) ** 2)

            return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

        g_dense = grads(1 << 40)
        g_block = grads(0)

        def loss_ref(q_, k_, v_):
            return jnp.sum(dense_attention_reference(
                q_, k_, v_, dropout_rate=self.RATE,
                dropout_seed=self._seed()) ** 2)

        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", g_dense, g_block):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5,
                                       err_msg=f"branches differ on {name}")
        for name, a, b in zip("qkv", g_dense, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5,
                                       err_msg=f"vs hash-dense ref on {name}")

    def test_ring_matches_dense_hash_under_sharding(self, devices8):
        mesh = make_mesh(("dp", "sp"), (2, 4), devices8)
        q, k, v = _qkv(jax.random.PRNGKey(65), B=4, H=2, L=32, D=16)
        mask = _padding_mask(jax.random.PRNGKey(66), B=4, L=32)
        out = ring_self_attention(q, k, v, mask, mesh,
                                  dropout_rate=self.RATE,
                                  dropout_seed=self._seed())
        ref = dense_attention_reference(q, k, v, mask[:, None, None, :],
                                        dropout_rate=self.RATE,
                                        dropout_seed=self._seed())
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_ulysses_matches_dense_hash_under_sharding(self, devices8):
        from faster_distributed_training_tpu.ops.ulysses_attention import (
            ulysses_self_attention)
        mesh = make_mesh(("dp", "sp"), (2, 4), devices8)
        q, k, v = _qkv(jax.random.PRNGKey(67), B=4, H=4, L=32, D=16)
        mask = _padding_mask(jax.random.PRNGKey(68), B=4, L=32)
        out = ulysses_self_attention(q, k, v, mask, mesh,
                                     dropout_rate=self.RATE,
                                     dropout_seed=self._seed())
        ref = dense_attention_reference(q, k, v, mask[:, None, None, :],
                                        dropout_rate=self.RATE,
                                        dropout_seed=self._seed())
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_transformer_flash_train_path_uses_dropout(self):
        """The auto-selected TPU path must regularize in training: same
        params + same rngs, dropout_attention on vs off must differ in
        the train forward (eval stays deterministic and equal)."""
        from faster_distributed_training_tpu.models import Transformer

        def build(rate):
            return Transformer(n_class=4, vocab=64, n_layers=1, h=2,
                               d_model=16, d_ff=32, d_hidden=32, maxlen=16,
                               dropout_attention=rate,
                               dropout_encodings=0.0,
                               dropout_connection_attention=0.0,
                               dropout_connection_ffn=0.0, dropout_ffn=0.0,
                               attention_impl="flash", alpha=0.0)

        x = jnp.ones((4, 16), jnp.int32)
        rngs = {"params": jax.random.PRNGKey(0),
                "dropout": jax.random.PRNGKey(1),
                "mixup": jax.random.PRNGKey(2)}
        m_on, m_off = build(0.5), build(0.0)
        params = m_off.init(rngs, x, train=False)
        run = lambda m, train: m.apply(  # noqa: E731
            params, x, train=train,
            rngs={"dropout": jax.random.PRNGKey(3),
                  "mixup": jax.random.PRNGKey(4)})
        on_logits = run(m_on, True)[0]
        off_logits = run(m_off, True)[0]
        assert not np.allclose(np.asarray(on_logits),
                               np.asarray(off_logits), atol=1e-4)
        ev_on = m_on.apply(params, x, train=False)
        ev_off = m_off.apply(params, x, train=False)
        np.testing.assert_allclose(np.asarray(ev_on), np.asarray(ev_off),
                                   rtol=1e-6)


class TestPallasBackwardKernel:
    """The Pallas flash backward (dq/dk/dv recomputed in-kernel) must be
    gradient-equal to the dense reference, with and without dropout,
    including ragged q (pad rows) and padding masks — interpret mode."""

    def _grads_kernel(self, q, k, v, mask=None, rate=0.0, seed=None,
                      monkeypatch=None):
        import importlib
        fa = importlib.import_module(
            "faster_distributed_training_tpu.ops.flash_attention")
        # force the long-context branch so the kernel path is taken
        monkeypatch.setattr(fa, "_DENSE_BWD_BUDGET_BYTES", 0)
        os.environ["FDT_FORCE_PALLAS_INTERPRET"] = "1"
        try:
            def loss(q_, k_, v_):
                return jnp.sum(fa.flash_attention(
                    q_, k_, v_, mask=mask, block_q=8, dropout_rate=rate,
                    dropout_seed=seed) ** 2)

            return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        finally:
            del os.environ["FDT_FORCE_PALLAS_INTERPRET"]

    def _grads_ref(self, q, k, v, mask=None, rate=0.0, seed=None):
        def loss(q_, k_, v_):
            return jnp.sum(dense_attention_reference(
                q_, k_, v_, mask, dropout_rate=rate,
                dropout_seed=seed) ** 2)

        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    def _check(self, got, want):
        for name, a, b in zip("qkv", got, want):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5,
                                       err_msg=f"d{name} mismatch")

    def test_matches_dense_no_mask(self, monkeypatch):
        q, k, v = _qkv(jax.random.PRNGKey(70), B=2, H=2, L=16, D=8)
        self._check(self._grads_kernel(q, k, v, monkeypatch=monkeypatch),
                    self._grads_ref(q, k, v))

    def test_matches_dense_with_mask_and_ragged_q(self, monkeypatch):
        # L=12 with block_q=8 -> one ragged (padded) q block
        q, k, v = _qkv(jax.random.PRNGKey(71), B=2, H=2, L=12, D=8)
        mask = _padding_mask(jax.random.PRNGKey(72), B=2,
                             L=12)[:, None, None, :]
        self._check(
            self._grads_kernel(q, k, v, mask, monkeypatch=monkeypatch),
            self._grads_ref(q, k, v, mask))

    def test_matches_dense_with_dropout(self, monkeypatch):
        q, k, v = _qkv(jax.random.PRNGKey(73), B=2, H=2, L=16, D=8)
        seed = jnp.uint32(99)
        self._check(
            self._grads_kernel(q, k, v, rate=0.3, seed=seed,
                               monkeypatch=monkeypatch),
            self._grads_ref(q, k, v, rate=0.3, seed=seed))

    def test_saved_stats_and_recompute_backwards_agree(self, monkeypatch):
        """r6 saved-(out, lse) monolithic backward (the L=512 retune)
        vs the r5 in-kernel-recompute kernel (FDT_FLASH_SAVE_STATS=0):
        both must match the dense reference — with a padding mask,
        ragged q (pad rows) AND dropout, the full hard-mode combo."""
        q, k, v = _qkv(jax.random.PRNGKey(74), B=2, H=2, L=12, D=8)
        mask = _padding_mask(jax.random.PRNGKey(75), B=2,
                             L=12)[:, None, None, :]
        seed = jnp.uint32(123)
        assert os.environ.get("FDT_FLASH_SAVE_STATS") is None
        g_stats = self._grads_kernel(q, k, v, mask, rate=0.3, seed=seed,
                                     monkeypatch=monkeypatch)
        monkeypatch.setenv("FDT_FLASH_SAVE_STATS", "0")
        g_rec = self._grads_kernel(q, k, v, mask, rate=0.3, seed=seed,
                                   monkeypatch=monkeypatch)
        monkeypatch.delenv("FDT_FLASH_SAVE_STATS")
        g_ref = self._grads_ref(q, k, v, mask, rate=0.3, seed=seed)
        self._check(g_stats, g_ref)
        self._check(g_rec, g_ref)
        for name, a, b in zip("qkv", g_stats, g_rec):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5,
                                       err_msg=f"stats-vs-recompute d{name}")

    def test_forward_emits_exact_lse(self, monkeypatch):
        """The emit_lse forward's row lse must equal the dense
        log-sum-exp of the biased scores (it becomes a residual the
        backward trusts verbatim)."""
        import importlib
        import math as _math
        fa = importlib.import_module(
            "faster_distributed_training_tpu.ops.flash_attention")
        os.environ["FDT_FORCE_PALLAS_INTERPRET"] = "1"
        try:
            B, H, L, D = 2, 2, 12, 8
            q, k, v = _qkv(jax.random.PRNGKey(76), B=B, H=H, L=L, D=D)
            mask2d = _padding_mask(jax.random.PRNGKey(77), B=B, L=L)
            from faster_distributed_training_tpu.ops.attention import (
                mask_to_bias)
            key_bias = mask_to_bias(mask2d)
            n3 = lambda x: x.reshape(B * H, L, D)  # noqa: E731
            out, lse = fa._flash_fwd_pallas(
                n3(q), n3(k), n3(v), key_bias, H, block_q=8, emit_lse=True)
            s = (jnp.einsum("bhqd,bhkd->bhqk", q, k) / _math.sqrt(D)
                 + key_bias[:, None, None, :])
            lse_ref = jax.nn.logsumexp(s, axis=-1).reshape(B * H, L)
            np.testing.assert_allclose(np.asarray(lse),
                                       np.asarray(lse_ref),
                                       rtol=1e-5, atol=1e-5)
            ref = fa.flash_attention(q, k, v, mask=mask2d, block_q=8)
            np.testing.assert_allclose(
                np.asarray(out.reshape(B, H, L, D)), np.asarray(ref),
                rtol=1e-5, atol=1e-5)
        finally:
            del os.environ["FDT_FORCE_PALLAS_INTERPRET"]


class TestKernelEnvelopeRouting:
    """Beyond the monolithic Pallas kernels' empirical VMEM caps the
    policy must route to the K-BLOCKED (FA-2-style) kernels — and, with
    the Pallas backward disabled, to the blockwise XLA VJP — and stay
    gradient-correct on every route.  Exercised at small sizes by
    shrinking the caps."""

    def _grads(self, q, k, v):
        def loss(q_, k_, v_):
            import importlib
            fa = importlib.import_module(
                "faster_distributed_training_tpu.ops.flash_attention")
            return jnp.sum(fa.flash_attention(
                q_, k_, v_, dropout_rate=0.3,
                dropout_seed=jnp.uint32(5)) ** 2)

        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    def _grads_ref(self, q, k, v):
        def loss_ref(q_, k_, v_):
            return jnp.sum(dense_attention_reference(
                q_, k_, v_, dropout_rate=0.3,
                dropout_seed=jnp.uint32(5)) ** 2)

        return jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)

    def test_beyond_envelope_routes_to_kblocked_and_matches_dense(
            self, monkeypatch):
        import importlib
        fa = importlib.import_module(
            "faster_distributed_training_tpu.ops.flash_attention")
        monkeypatch.setattr(fa, "_FWD_KERNEL_MAX_LK", 0)
        monkeypatch.setattr(fa, "_BWD_KERNEL_MAX_LK", 0)
        os.environ["FDT_FORCE_PALLAS_INTERPRET"] = "1"
        try:
            q, k, v = _qkv(jax.random.PRNGKey(80), B=2, H=2, L=32, D=8)
            g = self._grads(q, k, v)
            g_ref = self._grads_ref(q, k, v)
            for name, a, b in zip("qkv", g, g_ref):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=1e-4, atol=1e-5,
                                           err_msg=f"d{name} mismatch "
                                                   f"on k-blocked path")
        finally:
            del os.environ["FDT_FORCE_PALLAS_INTERPRET"]

    def test_bwd_disabled_beyond_envelope_falls_back_to_blockwise_vjp(
            self, monkeypatch):
        import importlib
        fa = importlib.import_module(
            "faster_distributed_training_tpu.ops.flash_attention")
        monkeypatch.setattr(fa, "_FWD_KERNEL_MAX_LK", 0)
        monkeypatch.setattr(fa, "_BWD_KERNEL_MAX_LK", 0)
        monkeypatch.setattr(fa, "_DENSE_BWD_BUDGET_BYTES", 0)
        os.environ["FDT_FORCE_PALLAS_INTERPRET"] = "1"
        os.environ["FDT_DISABLE_PALLAS_BWD"] = "1"
        try:
            q, k, v = _qkv(jax.random.PRNGKey(81), B=2, H=2, L=32, D=8)
            g = self._grads(q, k, v)
            g_ref = self._grads_ref(q, k, v)
            for name, a, b in zip("qkv", g, g_ref):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=1e-4, atol=1e-5,
                                           err_msg=f"d{name} mismatch "
                                                   f"on blockwise fallback")
        finally:
            del os.environ["FDT_FORCE_PALLAS_INTERPRET"]
            del os.environ["FDT_DISABLE_PALLAS_BWD"]

    def test_unsupported_head_dim_routes_to_blockwise(self, monkeypatch):
        """VERDICT r3 #7: a head dim outside the K-blocked support set
        (D > 128 and D % 128 != 0, e.g. D=192) that is ALSO beyond the
        monolithic envelope must silently route to the XLA blockwise
        path — no error, dense-equal values and gradients."""
        import importlib
        fa = importlib.import_module(
            "faster_distributed_training_tpu.ops.flash_attention")
        assert not fa._kblocked_supported(192)
        assert fa._kblocked_supported(128) and fa._kblocked_supported(256)
        monkeypatch.setattr(fa, "_FWD_KERNEL_MAX_LK", 0)
        monkeypatch.setattr(fa, "_BWD_KERNEL_MAX_LK", 0)
        os.environ["FDT_FORCE_PALLAS_INTERPRET"] = "1"
        try:
            q, k, v = _qkv(jax.random.PRNGKey(82), B=1, H=2, L=16, D=192)
            g = self._grads(q, k, v)
            g_ref = self._grads_ref(q, k, v)
            for name, a, b in zip("qkv", g, g_ref):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=1e-4, atol=1e-5,
                                           err_msg=f"d{name} mismatch on "
                                                   f"D=192 blockwise route")
        finally:
            del os.environ["FDT_FORCE_PALLAS_INTERPRET"]

    def test_envelope_caps_scale_with_head_dim(self):
        """ADVICE r2 (medium): the empirical Lk caps were validated at
        D=64; K/V residency scales with D, so the fit checks must scale
        the cap by 64/D — a D=128 model at the D=64 cap must NOT claim
        to fit."""
        import importlib
        fa = importlib.import_module(
            "faster_distributed_training_tpu.ops.flash_attention")
        assert fa._bwd_kernel_fits(128, fa._BWD_KERNEL_MAX_LK, d=64)
        assert not fa._bwd_kernel_fits(128, fa._BWD_KERNEL_MAX_LK, d=128)
        assert fa._bwd_kernel_fits(128, fa._BWD_KERNEL_MAX_LK // 2, d=128)
        # q-tile 32: small enough that only the Lk·D envelope decides
        assert fa._fwd_kernel_fits(32, fa._FWD_KERNEL_MAX_LK, d=64)
        assert not fa._fwd_kernel_fits(32, fa._FWD_KERNEL_MAX_LK, d=128)

    def test_bwd_block_q_is_sublane_aligned(self):
        """ADVICE r2 (low): odd Lq must not yield an odd q-tile —
        Mosaic sublane tiling wants multiples of 8 (padding handles
        Lq % bq)."""
        import importlib
        fa = importlib.import_module(
            "faster_distributed_training_tpu.ops.flash_attention")
        for lq in (100, 33, 7, 512):
            assert fa._bwd_block_q(lq, 4096) % 8 == 0, lq


class TestKBlockedKernels:
    """The k-blocked (FA-2-style) kernels must match the dense reference
    in forward, lse, and gradients — including padding masks, ragged
    tiles, and dropout — in interpret mode (hardware-checked separately
    on the real chip)."""

    def _setup(self, key, B=2, H=2, L=48, D=16, masked=True):
        q, k, v = _qkv(key, B=B, H=H, L=L, D=D)
        mask = (_padding_mask(jax.random.PRNGKey(7), B=B, L=L)
                if masked else None)
        return q, k, v, mask

    def _force_kblocked(self, monkeypatch):
        import importlib
        fa = importlib.import_module(
            "faster_distributed_training_tpu.ops.flash_attention")
        monkeypatch.setattr(fa, "_FWD_KERNEL_MAX_LK", 0)
        monkeypatch.setattr(fa, "_BWD_KERNEL_MAX_LK", 0)
        return fa

    def test_forward_and_lse_match_dense(self, monkeypatch):
        fa = self._force_kblocked(monkeypatch)
        os.environ["FDT_FORCE_PALLAS_INTERPRET"] = "1"
        try:
            q, k, v, mask = self._setup(jax.random.PRNGKey(90))
            B, H, L, D = q.shape
            out = fa.flash_attention(q, k, v, mask=mask)
            ref = dense_attention_reference(q, k, v,
                                            mask[:, None, None, :])
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       rtol=1e-5, atol=1e-5)
            # direct kernel call: lse must equal logsumexp of the
            # masked scaled scores
            from faster_distributed_training_tpu.ops.attention import (
                mask_to_bias)
            n3 = lambda x: x.reshape(B * H, L, D)  # noqa: E731
            kb = jnp.repeat(mask_to_bias(mask.astype(jnp.float32)), H,
                            axis=0)
            o2, lse = fa._flash_fwd_kblocked(n3(q), n3(k), n3(v), kb)
            s = (jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
                 + jnp.where(mask[:, None, None, :] == 0, -1e9, 0.0))
            lse_ref = jax.nn.logsumexp(s, axis=-1).reshape(B * H, L)
            np.testing.assert_allclose(np.asarray(lse),
                                       np.asarray(lse_ref),
                                       rtol=1e-5, atol=1e-5)
        finally:
            del os.environ["FDT_FORCE_PALLAS_INTERPRET"]

    def test_grads_match_dense_with_mask_ragged_and_dropout(
            self, monkeypatch):
        fa = self._force_kblocked(monkeypatch)
        os.environ["FDT_FORCE_PALLAS_INTERPRET"] = "1"
        try:
            # L=44 -> ragged q and k tiles after 8/128-multiple padding
            q, k, v, mask = self._setup(jax.random.PRNGKey(91), L=44)
            seed = jnp.uint32(17)

            def loss(q_, k_, v_):
                return jnp.sum(fa.flash_attention(
                    q_, k_, v_, mask=mask, dropout_rate=0.3,
                    dropout_seed=seed) ** 2)

            def loss_ref(q_, k_, v_):
                return jnp.sum(dense_attention_reference(
                    q_, k_, v_, mask[:, None, None, :], dropout_rate=0.3,
                    dropout_seed=seed) ** 2)

            g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
            g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
            for name, a, b in zip("qkv", g, g_ref):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=1e-4, atol=1e-5,
                                           err_msg=f"d{name} mismatch")
        finally:
            del os.environ["FDT_FORCE_PALLAS_INTERPRET"]
