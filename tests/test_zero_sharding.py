"""ZeRO optimizer-state sharding (ISSUE 16 tentpole) pins.

What is pinned here:
  * the shape-aware rule classes (sharding.OPT_STATE_RULES /
    REPLICATED_OPT_STATE) and the coverage lint
    (scripts/check_sharding_rules.py) that keeps them honest;
  * the dp4xtp2 ZeRO twin: losses allclose to the replicated-opt-state
    run AND the >= 1.8x opt_state_bytes_per_chip drop the ISSUE's
    acceptance criterion names;
  * checkpoint INTERCHANGE: a ZeRO-sharded run's checkpoint restores
    bitwise into a replicated-opt-state config and vice versa, through
    BOTH the single-file orbax path and the r9 two-phase sharded path;
  * the sharding-drift guard fires when a sharded opt-state leaf is
    deliberately re-replicated;
  * --offload_opt_state degrades cleanly (no pinned_host on CPU) and
    the step stream stays bitwise vs the non-offload run;
  * --overlap_grad_reduce is value-preserving (allclose twin).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from faster_distributed_training_tpu.config import TrainConfig
from faster_distributed_training_tpu.optim.builder import build_optimizer
from faster_distributed_training_tpu.parallel.placement import (
    make_put_batch, shard_train_state, train_state_shardings)
from faster_distributed_training_tpu.parallel.sharding import (
    OPT_STATE_RULES, REPLICATED_OPT_STATE, bucketed_grad_reduce,
    classify_opt_state_leaf, _param_suffix_table)
from faster_distributed_training_tpu.train import checkpoint as ckpt
from faster_distributed_training_tpu.train.state import create_train_state
from faster_distributed_training_tpu.train.steps import make_train_step


def _tree_equal(a, b) -> bool:
    a = jax.device_get(a)
    b = jax.device_get(b)
    return all(jax.tree.leaves(
        jax.tree.map(lambda x, y: bool(np.array_equal(np.asarray(x),
                                                      np.asarray(y))),
                     a, b)))


def _cfg(**kw) -> TrainConfig:
    base = dict(model="transformer", dataset="synthetic", batch_size=8,
                seq_len=16, n_layers=1, d_model=16, d_ff=32, n_heads=2,
                optimizer="sgd", use_ngd=False, precision="fp32",
                donate=False, alpha=0.0, telemetry=False, plot=False)
    base.update(kw)
    return TrainConfig(**base)


def _build(devices, mesh_shape, axes, cfg, n_steps=3):
    """(state, losses, shardings, cfg) after n_steps on a fixed batch."""
    from faster_distributed_training_tpu.cli import build_model

    devs = np.array(devices[:int(np.prod(mesh_shape))]).reshape(mesh_shape)
    mesh = Mesh(devs, axes)
    cfg = cfg.replace(mesh_axes=axes)
    model = build_model(cfg, vocab_size=128, mesh=mesh)
    tx, _ = build_optimizer(cfg, steps_per_epoch=10)
    sample = jnp.zeros((cfg.batch_size, cfg.seq_len), jnp.int32)
    state = create_train_state(model, tx, sample, jax.random.PRNGKey(0),
                               init_kwargs={"train": True})
    shardings = (train_state_shardings(state, mesh, cfg)
                 if len(axes) > 1 or cfg.offload_opt_state
                 or cfg.overlap_grad_reduce else None)
    state = shard_train_state(state, mesh, cfg, shardings=shardings)
    step = jax.jit(make_train_step(cfg, shardings))
    tok = np.random.RandomState(1).randint(
        0, 100, (cfg.batch_size, cfg.seq_len)).astype(np.int32)
    y = np.random.RandomState(2).randint(
        0, 4, (cfg.batch_size,)).astype(np.int32)
    batch = make_put_batch(mesh)({"tokens": tok, "label": y})
    losses = []
    for _ in range(n_steps):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    return state, losses, shardings, cfg


@pytest.fixture(scope="module")
def zero_twin(devices8):
    """One replicated 1D run and one dp4xtp2 ZeRO run, same model/data —
    shared by the twin, byte-drop, and interchange tests."""
    st1, l1, _, cfg1 = _build(devices8, (8,), ("dp",), _cfg())
    st2, l2, sh2, cfg2 = _build(devices8, (4, 2), ("dp", "tp"), _cfg())
    return {"repl": (st1, l1, cfg1), "zero": (st2, l2, sh2, cfg2)}


class TestRules:
    def test_registries_disjoint_and_documented(self):
        assert not set(OPT_STATE_RULES) & set(REPLICATED_OPT_STATE)
        for reason in list(OPT_STATE_RULES.values()) + \
                list(REPLICATED_OPT_STATE.values()):
            assert len(reason) > 20     # a story, not a stub

    def test_classify_by_role_and_shape(self):
        params = {"model": {"fc": {"kernel": jnp.zeros((512, 100)),
                                   "bias": jnp.zeros((100,))}}}
        suf = _param_suffix_table(params, jax.tree.map(lambda _: P(),
                                                       params))
        # mirror: endswith + shape
        name, spec = classify_opt_state_leaf(
            "[1].trace['model']['fc']['kernel']", (512, 100), suf, 2)
        assert name == "param_mirror" and spec == P("tp", None)
        # mirror inherits the param's tp spec when it has one
        suf2 = {"['model']['fc']['kernel']": ((512, 100), P(None, "tp"))}
        name, spec = classify_opt_state_leaf(
            "[1].trace['model']['fc']['kernel']", (512, 100), suf2, 2)
        assert name == "param_mirror" and spec == P(None, "tp")
        # NGD grouped factor: leading G axis when divisible
        name, spec = classify_opt_state_leaf(
            "[1].groups['r2:n576:d64:k32'].w", (2, 32, 64), suf, 2)
        assert name == "ngd_group_factor" and spec == P("tp", None, None)
        # ... falls back to any divisible axis when G is not
        name, spec = classify_opt_state_leaf(
            "[1].groups['r0:n100:d512:k80'].w", (1, 80, 512), suf, 2)
        assert name == "ngd_group_factor" and spec == P(None, None, "tp")
        # scalars / small / indivisible replicate with a reason
        assert classify_opt_state_leaf("[1].t", (), suf, 2) == \
            ("scalar", P())
        assert classify_opt_state_leaf(
            "[1].trace['model']['fc']['bias']", (100,), suf, 2) == \
            ("small", P())
        name, spec = classify_opt_state_leaf(
            "[0].mu['model']['odd']", (1025, 7),
            {"['model']['odd']": ((1025, 7), P())}, 2)
        assert (name, spec) == ("indivisible", P())
        # an unknown role stays replicated but is named 'unmatched'
        # (the lint turns that into a failure)
        name, spec = classify_opt_state_leaf(
            "[0].mystery_slot", (4096, 4096), {}, 2)
        assert (name, spec) == ("unmatched", P())

    def test_coverage_lint_clean_and_catches_unmatched(self):
        from scripts import check_sharding_rules as lint
        assert lint.check() == []
        # a foreign optimizer slot must FAIL the lint, not silently
        # replicate: simulate by classifying a leaf no rule knows
        rows = [("fake_opt", ".exotic_slot['model']", (2048, 2048),
                 "unmatched")]
        orig = lint.classify_all
        lint.classify_all = lambda n=2: rows
        try:
            problems = lint.check()
        finally:
            lint.classify_all = orig
        assert any("unmatched" in p for p in problems)
        # and rule 2 fires too (no probe hit the real registries)
        assert any("rule 2" in p for p in problems)


class TestZeroTwin:
    def test_losses_allclose_to_replicated(self, zero_twin):
        _, l1, _ = zero_twin["repl"]
        _, l2, _, _ = zero_twin["zero"]
        assert np.allclose(l1, l2, rtol=2e-4), (l1, l2)

    def test_opt_state_bytes_drop_and_tiers(self, zero_twin):
        from faster_distributed_training_tpu.telemetry.programs import (
            state_bytes_table)
        st1, _, _ = zero_twin["repl"]
        st2, _, _, _ = zero_twin["zero"]
        t1 = state_bytes_table(st1)
        t2 = state_bytes_table(st2)
        ratio = t1["opt_state_bytes_per_chip"] / t2["opt_state_bytes_per_chip"]
        # the ISSUE acceptance: >= 1.8x drop on a tp=2 mesh
        assert ratio >= 1.8, (t1["opt_state_bytes_per_chip"],
                              t2["opt_state_bytes_per_chip"])
        tiers = t2["opt_state_tiers"]
        assert tiers["sharded"]["bytes_per_chip"] > \
            tiers["replicated"]["bytes_per_chip"]
        # per-leaf attribution reaches top_leaves too
        assert all("tier" in leaf for leaf in t2["top_leaves"])

    def test_momentum_actually_sharded(self, zero_twin):
        st2, _, _, _ = zero_twin["zero"]
        flat = jax.tree_util.tree_flatten_with_path(st2.opt_state)[0]
        sharded = {jax.tree_util.keystr(p): v.sharding.spec
                   for p, v in flat
                   if not v.sharding.is_fully_replicated}
        # the qkv momentum follows its param's tp spec
        assert any("qkv" in k and "kernel" in k for k in sharded), sharded
        for key, spec in sharded.items():
            assert "tp" in jax.tree.leaves(tuple(spec)), (key, spec)

    def test_no_zero_opt_restores_replicated_layout(self, devices8):
        st, _, _, _ = _build(devices8, (4, 2), ("dp", "tp"),
                             _cfg(zero_opt=False), n_steps=1)
        for leaf in jax.tree.leaves(st.opt_state):
            assert leaf.sharding.is_fully_replicated


class TestCheckpointInterchange:
    """A checkpoint is layout-free: ZeRO-sharded <-> replicated configs
    restore each other bitwise through both checkpoint formats."""

    def _roundtrip_single_file(self, tmp_path, src_state, dst_state):
        ckpt.save_checkpoint(str(tmp_path), "x", src_state, epoch=1,
                             best_acc=0.5)
        restored, epoch, acc = ckpt.restore_checkpoint(
            str(tmp_path), "x", dst_state)
        assert (epoch, acc) == (1, 0.5)
        return restored

    def _roundtrip_sharded(self, tmp_path, src_state, dst_state):
        blocks = ckpt.host_shard_snapshot(src_state)
        ckpt.write_host_shards(str(tmp_path / "s"), 0, blocks)
        ckpt.commit_sharded_checkpoint(str(tmp_path / "s"),
                                       {"epoch": 1, "best_acc": 0.5},
                                       n_hosts=1)
        restored, epoch, acc = ckpt.restore_sharded_checkpoint(
            str(tmp_path), "s", dst_state)
        assert (epoch, acc) == (1, 0.5)
        return restored

    @pytest.mark.parametrize("path", ["single", "sharded"])
    def test_zero_to_replicated_bitwise(self, tmp_path, zero_twin, path):
        st_zero = zero_twin["zero"][0]
        # fresh replicated-config template (same arch, same abstract tree)
        dst, _, _, _ = _build(jax.devices()[:8], (8,), ("dp",), _cfg(),
                              n_steps=0)
        rt = (self._roundtrip_single_file if path == "single"
              else self._roundtrip_sharded)
        restored = rt(tmp_path, st_zero, dst)
        assert _tree_equal(ckpt._state_pytree(restored),
                           ckpt._state_pytree(st_zero))

    @pytest.mark.parametrize("path", ["single", "sharded"])
    def test_replicated_to_zero_bitwise(self, tmp_path, zero_twin, path):
        st_repl = zero_twin["repl"][0]
        dst, _, sh, _ = _build(jax.devices()[:8], (4, 2), ("dp", "tp"),
                               _cfg(), n_steps=0)
        rt = (self._roundtrip_single_file if path == "single"
              else self._roundtrip_sharded)
        restored = rt(tmp_path, st_repl, dst)
        assert _tree_equal(ckpt._state_pytree(restored),
                           ckpt._state_pytree(st_repl))
        # re-placing onto the ZeRO shardings preserves values exactly
        from faster_distributed_training_tpu.parallel.placement import (
            place_on_shardings)
        placed = place_on_shardings(restored, sh)
        assert _tree_equal(ckpt._state_pytree(placed),
                           ckpt._state_pytree(st_repl))

    def test_meta_records_opt_state_layout(self, tmp_path, zero_twin):
        # the save meta pins which ZeRO layout wrote the checkpoint:
        # sharded leaves present under ZeRO, absent on the 1D replicated
        # twin's layout summary
        st_zero = zero_twin["zero"][0]
        ckpt.save_checkpoint(str(tmp_path), "z", st_zero, epoch=0,
                             best_acc=0.0)
        meta = ckpt.read_checkpoint_meta(str(tmp_path), "z")
        layout = meta.get("opt_state_layout")
        assert layout and layout.get("sharded", 0) > 0
        st_repl = zero_twin["repl"][0]
        assert ckpt.opt_state_layout(st_repl).get("sharded", 0) == 0


class TestDriftGuard:
    def test_rereplicating_sharded_opt_leaf_warns(self, zero_twin):
        from faster_distributed_training_tpu.train.loop import Trainer
        st, _, sh, cfg = zero_twin["zero"]
        tr = Trainer.__new__(Trainer)
        tr.cfg = cfg.replace(debug=True)
        tr.telemetry = None
        tr.log = lambda *_: None
        tr._sharding_expect = None
        tr._sharding_detail = None
        tr._observe_state_placement(st)
        assert tr._sharding_expect is not None

        # deliberately re-replicate every sharded opt-state leaf (the
        # r11 drift class applied to the ZeRO layout)
        mesh = jax.tree.leaves(
            sh, is_leaf=lambda x: hasattr(x, "mesh"))[0].mesh
        repl = NamedSharding(mesh, P())
        drifted = st.replace(opt_state=jax.tree.map(
            lambda x: jax.device_put(x, repl), st.opt_state))
        with pytest.warns(UserWarning, match="sharding DRIFT"):
            tr._check_sharding_drift(drifted, epoch=1)
        # the guard re-anchors: a second check on the same state is quiet
        import warnings as _w
        with _w.catch_warnings():
            _w.simplefilter("error")
            tr._check_sharding_drift(drifted, epoch=2)


class TestOffloadAndOverlap:
    def test_offload_selection_by_size(self):
        from faster_distributed_training_tpu.parallel.sharding import (
            OFFLOAD_MIN_ELEMENTS, offload_opt_leaf)
        assert offload_opt_leaf((OFFLOAD_MIN_ELEMENTS,))
        assert offload_opt_leaf((512, 512))
        assert not offload_opt_leaf((100,))
        assert not offload_opt_leaf(())

    def test_leaf_tier_attribution(self):
        from faster_distributed_training_tpu.telemetry.programs import (
            leaf_tier)

        class FakeSharding:
            memory_kind = "pinned_host"
            is_fully_replicated = False

        class FakeLeaf:
            sharding = FakeSharding()

        assert leaf_tier(FakeLeaf()) == "offloaded"
        assert leaf_tier(np.zeros(3)) == "host"
        x = jnp.zeros((4,))
        assert leaf_tier(x) == "replicated"

    def test_offload_opt_state_degrades_bitwise_on_cpu(self, devices8):
        # no pinned_host on the CPU backend: the tier degrades to plain
        # device pins — the step stream must be bitwise vs offload-off
        st_off, l_off, _, _ = _build(devices8, (4, 2), ("dp", "tp"),
                                     _cfg(offload_opt_state=True))
        st_ref, l_ref, _, _ = _build(devices8, (4, 2), ("dp", "tp"),
                                     _cfg())
        assert l_off == l_ref
        assert _tree_equal(ckpt._state_pytree(st_off),
                           ckpt._state_pytree(st_ref))

    def test_offload_requires_shardings(self):
        with pytest.raises(ValueError, match="offload_opt_state"):
            make_train_step(_cfg(offload_opt_state=True), None)

    def test_bucketed_grad_reduce_identity(self, devices8):
        devs = np.array(devices8).reshape(4, 2)
        mesh = Mesh(devs, ("dp", "tp"))
        grads = {"a": jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
                 "b": jnp.ones((7,), jnp.float32) * 3,   # pad path
                 "c": jnp.asarray(2.5),                  # scalar
                 "d": jnp.arange(10, dtype=jnp.int32)}   # second dtype
        out = jax.jit(lambda g: bucketed_grad_reduce(
            g, mesh, bucket_bytes=64))(grads)
        for k in grads:
            np.testing.assert_array_equal(np.asarray(out[k]),
                                          np.asarray(grads[k]))

    def test_overlap_twin_allclose(self, devices8, zero_twin):
        _, l_ref, _, _ = zero_twin["zero"]
        _, l_on, _, _ = _build(devices8, (4, 2), ("dp", "tp"),
                               _cfg(overlap_grad_reduce=True))
        assert np.allclose(l_ref, l_on, rtol=1e-4), (l_ref, l_on)
