"""Anomaly-sentinel tests (r24 tentpole): the deterministic bad-step
guard, loss-spike rollback-and-quarantine, and stream CRC integrity.

The ISSUE acceptance pins, all tier-1 on the 8-virtual-device CPU mesh
(conftest):

  * sentinel OFF adds NOTHING: the lowered HLO of a --sentinel none
    fp32 program is byte-identical to the unguarded build (trace-time
    Python gating, no is-finite residue);
  * sentinel ON skip-at-N is BITWISE equal to never dispatching the
    poisoned step: params/opt_state/rng untouched, step advanced,
    metrics masked, bad_steps counted — on the host program, a (dp, tp)
    mesh, a (dp, pp) pipeline program, and inside the K=4 fused scan;
  * spike -> rollback -> quarantined replay is DETERMINISTIC (two
    spiked runs land bitwise-equal) and survives a kill mid-replay;
  * the chaos matrix composes: NaN guard + spike rollback in one run;
  * a corrupt stream shard is quarantined-and-continued (rows remapped,
    counter + durable ledger entry), never a crash.

donate=False throughout — several train programs share this pytest
process (the test_resilience.py precedent)."""

import json
import os
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from faster_distributed_training_tpu.config import TrainConfig
from faster_distributed_training_tpu.models import Transformer
from faster_distributed_training_tpu.parallel import make_mesh
from faster_distributed_training_tpu.resilience import (GoodputTracker,
                                                        build_resilience)
from faster_distributed_training_tpu.resilience import faults as faults_mod
from faster_distributed_training_tpu.resilience.sentinel import (
    LossSpike, QuarantineLedger, Sentinel, SpikeDetector, host_finite)
from faster_distributed_training_tpu.resilience.storage import build_backend
from faster_distributed_training_tpu.train import (create_train_state,
                                                   make_train_step)
from faster_distributed_training_tpu.train.steps import make_fused_train_step

_SILENT = lambda *_: None                                 # noqa: E731


def _assert_tree_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _tiny(sentinel="none", seed=0):
    """The resilience-suite tiny transformer (d16 cls), with the
    sentinel mode as the only degree of freedom: guard/none programs
    share state+batch bitwise so program-level diffs are the sentinel's
    alone.  Plain (unscheduled) sgd: the sentinel verdicts below need a
    state that stays FINITE on healthy steps."""
    cfg = TrainConfig(model="transformer", dataset="agnews", num_classes=4,
                      batch_size=4, seq_len=8, optimizer="sgd",
                      precision="fp32", epochs=1, donate=False,
                      sentinel=sentinel)
    import optax
    model = Transformer(n_class=4, vocab=32, n_layers=1, h=2, d_model=16,
                        d_ff=32, d_hidden=16, maxlen=8)
    state = create_train_state(model, optax.sgd(0.1),
                               jnp.zeros((4, 8), jnp.int32),
                               jax.random.PRNGKey(seed),
                               init_kwargs={"train": True})
    batch = {"tokens": np.random.default_rng(0).integers(
                 0, 32, size=(4, 8)).astype(np.int32),
             "label": np.arange(4, dtype=np.int32) % 4}
    return cfg, state, batch


# -- host-side units ------------------------------------------------------

class TestHostFinite:
    def test_finite_and_not(self):
        assert host_finite(1.5) and host_finite(0.0)
        assert not host_finite(float("nan"))
        assert not host_finite(float("inf"))
        assert not host_finite(None)
        assert not host_finite("n/a")
        assert host_finite(jnp.float32(2.0))
        assert not host_finite(jnp.float32(np.nan))


class TestSpikeDetector:
    def test_min_history_gates_detection(self):
        det = SpikeDetector(window=16, threshold=8.0, min_history=8)
        # an early outlier passes: not enough history to judge it
        for i in range(7):
            assert not det.observe(1.0 + 0.01 * i)
        assert not det.observe(1e6)      # 8th observation, history is 7
        det.reset()
        for i in range(8):
            det.observe(1.0 + 0.01 * i)
        assert det.observe(1e6)          # now the window can vote

    def test_spiking_loss_not_absorbed_into_window(self):
        det = SpikeDetector(window=16, threshold=8.0, min_history=8)
        for i in range(8):
            det.observe(1.0 + 0.01 * i)
        assert det.observe(1e6)
        # the spike was NOT appended: the very next spike still fires
        # against the healthy window instead of a poisoned median
        assert det.observe(1e6)

    def test_nonfinite_ignored(self):
        det = SpikeDetector(window=16, threshold=8.0, min_history=2)
        det.observe(1.0)
        det.observe(1.0)
        assert not det.observe(float("nan"))
        assert not det.observe(float("inf"))
        # and neither entered the window (median still 1.0)
        assert det.observe(1e6)

    def test_mad_floor_on_flat_window(self):
        # identical losses: MAD == 0, floored at 1e-3*|median| so any
        # numeric jitter does not become a rollback storm
        det = SpikeDetector(window=16, threshold=8.0, min_history=8)
        for _ in range(8):
            det.observe(1.0)
        assert not det.observe(1.005)    # inside the floored band
        assert det.observe(1.01)         # > 1.0 + 8 * 1e-3

    def test_reset_clears_history(self):
        det = SpikeDetector(window=16, threshold=8.0, min_history=4)
        for _ in range(4):
            det.observe(1.0)
        det.reset()
        assert not det.observe(1e6)      # history gone, gate re-armed


class TestQuarantineLedger:
    def test_in_memory_accumulates(self):
        led = QuarantineLedger()
        led.add_batches(1, [3, 5])
        led.add_batches(1, [5, 7])
        led.add_shard(2)
        assert led.batches_for(1) == {3, 5, 7}
        assert led.batches_for(0) == set()
        assert led.shards() == {2}

    def test_durable_roundtrip(self, tmp_path):
        backend = build_backend("posix", str(tmp_path), log=_SILENT)
        key = backend.join(str(tmp_path), "quarantine/ledger.json")
        led = QuarantineLedger(backend=backend, key=key)
        led.add_batches(1, [1])
        led.add_shard(3)
        # the flush is durable JSON a fresh process can reload
        path = tmp_path / "quarantine" / "ledger.json"
        assert path.exists()
        doc = json.loads(path.read_text())
        assert doc["version"] == 1
        assert doc["batches"] == {"1": [1]} and doc["shards"] == [3]
        # a fresh process (fresh backend object) reloads the identical
        # quarantine set before its first dispatch
        led2 = QuarantineLedger(backend=build_backend(
            "posix", str(tmp_path), log=_SILENT), key=key)
        assert led2.batches_for(1) == {1} and led2.shards() == {3}


class TestSentinelHost:
    def test_mode_validation(self):
        with pytest.raises(ValueError, match="guard/full"):
            Sentinel("none", log=_SILENT)
        with pytest.raises(ValueError):
            Sentinel("bogus", log=_SILENT)

    def test_plan_fast_path_and_splits(self):
        s = Sentinel("guard", log=_SILENT)
        assert s.plan(0, 8, 4) == [(8, 4)]          # empty-ledger hot path
        s.ledger.add_batches(0, [10])
        assert s.plan(0, 8, 4) == [(8, 2), (11, 1)]
        s.ledger.add_batches(0, [8, 9, 11])
        assert s.plan(0, 8, 4) == []                # fully quarantined
        assert s.plan(1, 8, 4) == [(8, 4)]          # other epochs untouched
        assert s.quarantined(0, 10) and not s.quarantined(1, 10)

    def test_observe_quarantines_counts_and_raises(self):
        g = GoodputTracker().start()
        s = Sentinel("full", goodput=g, log=_SILENT)
        for i in range(9):
            s.observe(0, i, 1, 1.0 + 0.01 * i, step=i + 1)
        with pytest.raises(LossSpike) as ei:
            s.observe(1, 1, 2, 1e6, step=10)
        assert ei.value.epoch == 1 and ei.value.positions == (1, 2)
        assert s.quarantined(1, 1) and s.quarantined(1, 2)
        summ = g.summary()
        assert summ["rollbacks"] == 1
        assert summ["quarantined_batches"] == 2
        # detector reset on spike: the replay's stream re-trains the
        # window before it may vote again
        assert not s.detector.observe(1e6)

    def test_guard_mode_observe_is_noop(self):
        s = Sentinel("guard", log=_SILENT)
        assert s.detector is None
        for i in range(20):
            s.observe(0, i, 1, 1e6, step=i + 1)     # never raises

    def test_quarantine_shard_warns_counts_never_raises(self):
        g = GoodputTracker().start()
        s = Sentinel("guard", goodput=g, log=_SILENT)
        with pytest.warns(UserWarning, match="CRC"):
            s.quarantine_shard(2, path="shard_00002/tokens.npy")
        assert s.ledger.shards() == {2}
        assert g.summary()["quarantined_shards"] == 1

    def test_build_resilience_wires_sentinel(self, tmp_path):
        cfg = TrainConfig(model="transformer", dataset="synthetic",
                          num_classes=4, batch_size=8, seq_len=16,
                          epochs=1, donate=False, sentinel="guard",
                          checkpoint_dir=str(tmp_path))
        res = build_resilience(cfg, log=_SILENT)
        assert res is not None and res.sentinel is not None
        assert res.sentinel.mode == "guard"
        # the ledger key is rooted under checkpoint_dir — NOT the bare
        # CWD-relative LEDGER_KEY (PosixBackend keys are paths verbatim;
        # a restart from another directory must still find the ledger)
        assert res.sentinel.ledger._key.startswith(str(tmp_path))


# -- the in-graph guard ---------------------------------------------------

class TestSentinelGraph:
    """Program-level pins: OFF is byte-identical, ON skips bitwise."""

    def test_sentinel_off_trace_is_byte_identical(self):
        # fp32 --sentinel none must lower to the same text as the
        # pre-sentinel build: no is-finite residue anywhere (the fp32
        # unscale path returns a constant-True verdict)
        cfg_none, state, batch = _tiny("none")
        cfg_guard, _s, _b = _tiny("guard")
        plain = jax.jit(make_train_step(cfg_none)).lower(
            state, batch).as_text()
        guard = jax.jit(make_train_step(cfg_guard)).lower(
            state, batch).as_text()
        assert "is_finite" not in plain
        assert "is_finite" in guard
        assert plain != guard

    def _skip_parity(self, cfg_guard, cfg_none, state, batch, steps=4,
                     nan_at=2, mesh=None, pipeline=None):
        """Guarded run with NaN poison at state.step == nan_at vs the
        unguarded program that simply never dispatches that step
        (manual step bump) — bitwise equality is the skip contract."""
        import contextlib
        ctx = mesh if mesh is not None else contextlib.nullcontext()
        with ctx:
            step_g = jax.jit(make_train_step(cfg_guard, pipeline=pipeline))
            s = state
            bad, losses = 0.0, []
            for _ in range(steps):
                s, m = step_g(s, batch)
                bad += float(m["bad_steps"])
                losses.append(float(m["loss"]))
        # reference: the sentinel-none program.  The NaN arm may still
        # be baked into this trace (env armed) — harmless: the poisoned
        # step counter is exactly the one this loop never dispatches
        with ctx:
            step_p = jax.jit(make_train_step(cfg_none, pipeline=pipeline))
            r = state
            for i in range(steps):
                if i == nan_at:
                    r = r.replace(step=r.step + 1)
                    continue
                r, _m = step_p(r, batch)
        assert bad == 1.0
        assert losses[nan_at] == 0.0            # masked, not NaN
        assert all(np.isfinite(losses))
        assert int(s.step) == int(r.step) == steps
        _assert_tree_equal(s.params, r.params)
        _assert_tree_equal(s.opt_state, r.opt_state)
        np.testing.assert_array_equal(np.asarray(s.rng), np.asarray(r.rng))

    def test_skip_at_n_bitwise_host(self, monkeypatch):
        cfg_guard, state, batch = _tiny("guard")
        monkeypatch.setenv(faults_mod.ENV_NAN, "2")   # read at TRACE time
        cfg_none, _s, _b = _tiny("none")
        self._skip_parity(cfg_guard, cfg_none, state, batch)

    def test_skip_at_n_bitwise_dp_tp_mesh(self, monkeypatch,
                                          requires_devices):
        requires_devices(8)
        cfg_guard, state, batch = _tiny("guard")
        cfg_none, _s, _b = _tiny("none")
        monkeypatch.setenv(faults_mod.ENV_NAN, "2")
        mesh = make_mesh(("dp", "tp"), (4, 2), jax.devices()[:8])
        self._skip_parity(cfg_guard, cfg_none, state, batch, mesh=mesh)

    def test_skip_at_n_bitwise_dp_pp_mesh(self, monkeypatch,
                                          requires_devices):
        requires_devices(4)
        import optax

        from faster_distributed_training_tpu.cli import build_model
        from faster_distributed_training_tpu.parallel.pipeline import (
            build_pipeline_spec)
        base = dict(model="transformer", dataset="synthetic", task="lm",
                    batch_size=8, seq_len=16, n_layers=2, d_model=32,
                    d_ff=64, n_heads=4, dropout_impl="none",
                    optimizer="sgd", precision="fp32", donate=False,
                    num_classes=4)
        cfg_guard = TrainConfig(sentinel="guard", **base)
        cfg_none = TrainConfig(**base)
        mesh = make_mesh(("dp", "pp"), (2, 2), jax.devices()[:4])
        spec = build_pipeline_spec(cfg_guard, mesh)
        model = build_model(cfg_guard, vocab_size=100, mesh=None)
        state = create_train_state(model, optax.sgd(0.1),
                                   jnp.zeros((8, 16), jnp.int32),
                                   jax.random.PRNGKey(0),
                                   init_kwargs={"train": True})
        batch = {"tokens": jax.random.randint(jax.random.PRNGKey(1),
                                              (8, 16), 0, 100)}
        monkeypatch.setenv(faults_mod.ENV_NAN, "2")
        self._skip_parity(cfg_guard, cfg_none, state, batch,
                          mesh=mesh, pipeline=spec)

    def test_skip_inside_fused_k4_scan(self, monkeypatch):
        """The poisoned step skips INSIDE the K-dispatch scan: the fused
        K=4 dispatch with a NaN at scan step 2 lands bitwise on four
        guarded K=1 steps, and its reduced metrics count bad_steps=1."""
        cfg_guard, state, batch = _tiny("guard")
        monkeypatch.setenv(faults_mod.ENV_NAN, "2")
        batches = {k: np.stack([v] * 4) for k, v in batch.items()}
        s4, m4 = jax.jit(make_fused_train_step(cfg_guard, 4))(state, batches)
        step1 = jax.jit(make_train_step(cfg_guard))
        s1, bad = state, 0.0
        for _ in range(4):
            s1, m1 = step1(s1, batch)
            bad += float(m1["bad_steps"])
        assert float(m4["bad_steps"]) == bad == 1.0
        assert int(s4.step) == int(s1.step) == 4
        _assert_tree_equal(s4.params, s1.params)
        _assert_tree_equal(s4.opt_state, s1.opt_state)


# -- e2e: spike -> rollback -> quarantined replay -------------------------

def _e2e_cfg(tmp, **kw):
    """Tiny REAL run_training config (the test_resilience.py twin):
    synthetic AG News, 8 steps/epoch x 2 epochs = 16 global steps."""
    base = dict(model="transformer", dataset="synthetic", num_classes=4,
                batch_size=8, seq_len=16, n_layers=1, d_model=16, d_ff=32,
                n_heads=2, epochs=2, subset_stride=64, optimizer="sgd",
                precision="fp32", plot=False, workers=2, log_every=0,
                donate=False, checkpoint_dir=str(tmp))
    base.update(kw)
    return TrainConfig(**base)


def _spiked_run(tmp, extra_env=()):
    """One full-sentinel run with the spike arm at step 10: epoch 1
    position 1 spikes (9 healthy observations >= min_history), the
    supervisor rolls back to the step-8 checkpoint and replays epoch 1
    with that position quarantined — 7 replay dispatches, final step 15.

    checkpoint_async=False: the rollback target is the newest COMMITTED
    checkpoint, and an async commit frontier is a race against the step
    loop — sync saves make the restore point (and with it the whole
    replay trajectory) a pure function of the step sequence."""
    from faster_distributed_training_tpu.cli import run_training
    env = dict(extra_env)
    env[faults_mod.ENV_SPIKE] = "10"
    try:
        for k, v in env.items():
            os.environ[k] = v
        return run_training(
            # lr=0.01: the default-lr schedule genuinely diverges on
            # this tiny run (loss ~47 at the epoch turn) and trips the
            # detector on its own — the test wants the INJECTED spike
            # to be the only anomaly in an otherwise-healthy stream
            _e2e_cfg(tmp, sentinel="full", supervise=True,
                     checkpoint_every=2, checkpoint_async=False,
                     lr=0.01),
            log=_SILENT)
    finally:
        for k in env:
            os.environ.pop(k, None)


class TestSpikeRollbackE2E:
    @pytest.fixture(scope="class")
    def spiked(self, tmp_path_factory):
        tmp = tmp_path_factory.mktemp("spiked")
        return _spiked_run(tmp), tmp

    def test_spike_rolls_back_and_quarantines(self, spiked):
        out, tmp = spiked
        # one spike -> one rollback, one batch position quarantined, one
        # restore through the supervisor's newest-VALID ladder; the run
        # finishes one step short of 16 (the batch is gone, not retried)
        assert out["goodput_rollbacks"] == 1
        assert out["goodput_quarantined_batches"] == 1
        assert out["goodput_restores"] == 1
        assert int(out["state"].step) == 15

    def test_ledger_is_durable_json(self, spiked):
        _out, tmp = spiked
        doc = json.loads(
            (tmp / "quarantine" / "ledger.json").read_text())
        assert doc["version"] == 1
        assert doc["batches"] == {"1": [1]}      # epoch 1, position 1
        assert doc["shards"] == []

    def test_replay_is_deterministic(self, spiked, tmp_path):
        # the whole ladder is pure (pod_epoch_order algebra + bitwise
        # restore): a second spiked run reproduces the first bitwise
        out1, _tmp = spiked
        out2 = _spiked_run(tmp_path)
        assert int(out2["state"].step) == 15
        _assert_tree_equal(out1["state"].params, out2["state"].params)
        _assert_tree_equal(out1["state"].opt_state, out2["state"].opt_state)
        np.testing.assert_array_equal(np.asarray(out1["state"].rng),
                                      np.asarray(out2["state"].rng))

    def test_kill_mid_replay_resumes_bitwise(self, spiked, tmp_path):
        """A crash DURING the quarantined replay (die at step 12; the
        first pass ends at the step-10 spike, so only the replay reaches
        12) restores by stored (epoch, position) and still lands bitwise
        on the uninterrupted spiked run."""
        out1, _tmp = spiked
        out2 = _spiked_run(tmp_path,
                           extra_env={faults_mod.ENV_DIE: "12"})
        assert out2["goodput_rollbacks"] == 1
        assert out2["goodput_restarts"] >= 1     # the injected crash
        assert int(out2["state"].step) == 15
        _assert_tree_equal(out1["state"].params, out2["state"].params)
        _assert_tree_equal(out1["state"].opt_state, out2["state"].opt_state)
        np.testing.assert_array_equal(np.asarray(out1["state"].rng),
                                      np.asarray(out2["state"].rng))

    def test_chaos_matrix_nan_plus_spike(self, tmp_path):
        # both arms in one run: the in-graph guard eats the NaN step
        # (skipped, counted), the spike ladder rolls back and replays —
        # the run completes with both verdicts on the goodput surface
        out = _spiked_run(tmp_path,
                          extra_env={faults_mod.ENV_NAN: "4"})
        assert int(out["state"].step) == 15
        assert out["goodput_skipped_steps"] == 1
        assert out["goodput_rollbacks"] == 1
        assert out["goodput_quarantined_batches"] == 1

    def test_nan_guard_only_no_supervisor(self, tmp_path, monkeypatch):
        # --sentinel guard alone (no supervise, no checkpoints): the
        # poisoned step is skipped in-graph and the run just finishes
        from faster_distributed_training_tpu.cli import run_training
        monkeypatch.setenv(faults_mod.ENV_NAN, "4")
        out = run_training(_e2e_cfg(tmp_path, sentinel="guard"),
                           log=_SILENT)
        assert int(out["state"].step) == 16      # skip advances the step
        assert out["goodput_skipped_steps"] == 1
        assert out["goodput_rollbacks"] == 0


# -- e2e: stream CRC quarantine ------------------------------------------

class TestCorruptShardE2E:
    def test_corrupt_shard_quarantined_run_completes(self, tmp_path,
                                                     monkeypatch):
        from faster_distributed_training_tpu.cli import run_training
        from faster_distributed_training_tpu.data.stream import (
            ShardedStreamDataset, synthetic_corpus, write_lm_corpus)
        d = str(tmp_path / "corpus")
        write_lm_corpus(d, synthetic_corpus(40, seed=3,
                                            words_per_doc=(25, 50)),
                        seq_len=16, rows_per_shard=16, val_fraction=0.15)
        train = ShardedStreamDataset(os.path.join(d, "train"))
        assert len(train.manifest["shards"]) > 1
        cfg = TrainConfig(model="transformer", dataset="stream", task="lm",
                          data_path="stream", stream_dir=d, batch_size=8,
                          seq_len=16, n_layers=1, d_model=16, d_ff=32,
                          n_heads=2, epochs=1, steps_per_dispatch=2,
                          stream_window=4, optimizer="sgd",
                          precision="fp32", plot=False, workers=0,
                          log_every=0, donate=False, sentinel="guard",
                          checkpoint_dir=str(tmp_path / "ckpt"))
        monkeypatch.setenv(faults_mod.ENV_CORRUPT, "1")
        with pytest.warns(UserWarning, match="CRC"):
            out = run_training(cfg, log=_SILENT)
        # the corruption was detected, quarantined, counted — and the
        # run did FULL work: quarantined rows remap to a healthy shard
        # (position-preserving), they are not dropped
        assert out["goodput_quarantined_shards"] == 1
        assert int(out["state"].step) == train.n // 8
        doc = json.loads((tmp_path / "ckpt" / "quarantine" /
                          "ledger.json").read_text())
        assert doc["shards"] == [1]
