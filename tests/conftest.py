"""Test harness: force an 8-device CPU platform so every sharding/collective
path (dp, fsdp, tp, sp/ring) is exercised without TPU hardware — the strategy
SURVEY.md §4 prescribes (the reference has no test suite at all)."""

import os

os.environ["JAX_PLATFORMS"] = os.environ.get("FDT_TEST_PLATFORM", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    flags = flags + " --xla_force_host_platform_device_count=8"


# one shared subprocess probe (compat.xla_accepts_flags): XLA hard-aborts
# on unknown flags, and older jaxlibs predate the collective-timeout
# flags below — probing keeps the suite alive on both generations.
# (compat imports jax, which is fine before the flags settle: XLA parses
# XLA_FLAGS at first backend use, not at import — the same reason the
# sitecustomize pre-import is tolerated below.)
import sys as _sys  # noqa: E402

_sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from faster_distributed_training_tpu.compat import (  # noqa: E402
    xla_accepts_flags as _xla_accepts)

if "xla_cpu_collective_call_terminate_timeout_seconds" not in flags:
    # 8 virtual device threads can share ONE physical core here; XLA's CPU
    # collective rendezvous aborts the process if a participant is >40s late
    # (rendezvous.cc), which a starved thread legitimately can be.  Raise the
    # warn/terminate timeouts so slow scheduling is slow, not fatal —
    # on jaxlibs new enough to know the flags (probed above).
    candidate = flags + (
        " --xla_cpu_collective_call_warn_stuck_timeout_seconds=300"
        " --xla_cpu_collective_call_terminate_timeout_seconds=1800"
        " --xla_cpu_collective_timeout_seconds=1800")
    if _xla_accepts(candidate.strip()):
        flags = candidate
os.environ["XLA_FLAGS"] = flags.strip()

# AVX2 cap (x86 only): AVX-512 targeting bakes +prefer-no-* pseudo-features
# into cached CPU AOT executables, which warn on every replay (VERDICT r4
# #5; the helper holds the measurement and the arch guard).
from faster_distributed_training_tpu.cli import quiet_cpu_aot_flags  # noqa: E402

quiet_cpu_aot_flags()

import jax  # noqa: E402
import pytest  # noqa: E402

# sitecustomize may import jax before this file runs, freezing the platform
# choice from the outer environment — override through the config API too.
jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
jax.config.update("jax_threefry_partitionable", True)
# fp64 available for gradcheck-style kernel tests (explicit dtypes elsewhere).
jax.config.update("jax_enable_x64", True)


def pytest_configure(config):
    # the tier-1 gate runs `-m 'not slow'` (ROADMAP): register the marker
    # so opting heavy e2e twins out of the budget is not an unknown-mark
    # warning
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 budgeted run "
        "(ROADMAP's `-m 'not slow'`); run with `pytest -m slow`")


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs[:8]


@pytest.fixture()
def mesh8(devices8):
    from faster_distributed_training_tpu.parallel import make_mesh
    return make_mesh(("dp",), (8,), devices8)
