"""Test harness: force an 8-device CPU platform so every sharding/collective
path (dp, fsdp, tp, sp/ring) is exercised without TPU hardware — the strategy
SURVEY.md §4 prescribes (the reference has no test suite at all)."""

import os

os.environ["JAX_PLATFORMS"] = os.environ.get("FDT_TEST_PLATFORM", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    flags = flags + " --xla_force_host_platform_device_count=8"


# one shared subprocess probe (compat.xla_accepts_flags): XLA hard-aborts
# on unknown flags, and older jaxlibs predate the collective-timeout
# flags below — probing keeps the suite alive on both generations.
# (compat imports jax, which is fine before the flags settle: XLA parses
# XLA_FLAGS at first backend use, not at import — the same reason the
# sitecustomize pre-import is tolerated below.)
import sys as _sys  # noqa: E402

_sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from faster_distributed_training_tpu.compat import (  # noqa: E402
    xla_accepts_flags as _xla_accepts)

if "xla_cpu_collective_call_terminate_timeout_seconds" not in flags:
    # 8 virtual device threads can share ONE physical core here; XLA's CPU
    # collective rendezvous aborts the process if a participant is >40s late
    # (rendezvous.cc), which a starved thread legitimately can be.  Raise the
    # warn/terminate timeouts so slow scheduling is slow, not fatal —
    # on jaxlibs new enough to know the flags (probed above).
    candidate = flags + (
        " --xla_cpu_collective_call_warn_stuck_timeout_seconds=300"
        " --xla_cpu_collective_call_terminate_timeout_seconds=1800"
        " --xla_cpu_collective_timeout_seconds=1800")
    if _xla_accepts(candidate.strip()):
        flags = candidate
os.environ["XLA_FLAGS"] = flags.strip()

# AVX2 cap (x86 only): AVX-512 targeting bakes +prefer-no-* pseudo-features
# into cached CPU AOT executables, which warn on every replay (VERDICT r4
# #5; the helper holds the measurement and the arch guard).
from faster_distributed_training_tpu.cli import (  # noqa: E402
    enable_compilation_cache, quiet_cpu_aot_flags)

quiet_cpu_aot_flags()
# The suite is COMPILE-bound (r9 budget audit: the slowest tier-1 tests
# are all multi-second XLA:CPU compiles of jitted train programs).  The
# run_training-based e2e tests already flip the ISA-keyed persistent
# cache on mid-process (cli.setup_platform), which silently left every
# directly-jitted test paying a cold compile per run; enabling it here
# covers the whole suite, so repeat runs (including the driver's budget
# gate in the same container) replay instead of recompiling.
enable_compilation_cache()

import jax  # noqa: E402
import pytest  # noqa: E402

# sitecustomize may import jax before this file runs, freezing the platform
# choice from the outer environment — override through the config API too.
jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
jax.config.update("jax_threefry_partitionable", True)
# fp64 available for gradcheck-style kernel tests (explicit dtypes elsewhere).
jax.config.update("jax_enable_x64", True)


def pytest_configure(config):
    # the tier-1 gate runs `-m 'not slow'` (ROADMAP): register the marker
    # so opting heavy e2e twins out of the budget is not an unknown-mark
    # warning
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 budgeted run "
        "(ROADMAP's `-m 'not slow'`); run with `pytest -m slow`")


# ROADMAP tier-1 wall-clock budget the suite must stay under; printed
# with the slowest-10 summary so a budget-eating test is visible in
# every run instead of being discovered at the gate.
TIER1_BUDGET_S = 870


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    """Test-budget guardrail: the suite runs against a hard 870 s
    ROADMAP budget (and sat at ~790 s after r8) — every run prints its
    10 slowest tests so the next session sees exactly where the budget
    goes before adding more.  New heavyweight e2e twins belong behind
    `-m slow`; new tier-1 tests should use the pure-function /
    simulated-process_index seams (tests/test_pod_scale.py is the
    pattern), not real multi-process runs."""
    reps = []
    for key in ("passed", "failed", "error"):
        for r in terminalreporter.stats.get(key, []):
            if getattr(r, "when", None) == "call":
                reps.append(r)
    if not reps:
        return
    total = sum(r.duration for r in reps)
    slowest = sorted(reps, key=lambda r: r.duration, reverse=True)[:10]
    terminalreporter.write_sep(
        "-", f"10 slowest tests (tier-1 budget {TIER1_BUDGET_S} s, "
             f"call-time total {total:.0f} s / {len(reps)} tests)")
    for r in slowest:
        terminalreporter.write_line(f"{r.duration:8.2f}s  {r.nodeid}")


def _requires_devices(n: int):
    """Skip (not error) when the host exposes fewer than `n` devices —
    2D-mesh tests degrade cleanly on hosts where the 8-virtual-device
    CPU flag didn't take (r11 satellite) instead of dying inside
    make_mesh."""
    have = len(jax.devices())
    if have < n:
        pytest.skip(f"needs {n} devices, host exposes {have}")


@pytest.fixture(scope="session")
def requires_devices():
    return _requires_devices


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs[:8]


@pytest.fixture()
def mesh8(devices8):
    from faster_distributed_training_tpu.parallel import make_mesh
    return make_mesh(("dp",), (8,), devices8)
