"""Test harness: force an 8-device CPU platform so every sharding/collective
path (dp, fsdp, tp, sp/ring) is exercised without TPU hardware — the strategy
SURVEY.md §4 prescribes (the reference has no test suite at all)."""

import os

os.environ["JAX_PLATFORMS"] = os.environ.get("FDT_TEST_PLATFORM", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import pytest  # noqa: E402

# sitecustomize may import jax before this file runs, freezing the platform
# choice from the outer environment — override through the config API too.
jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
jax.config.update("jax_threefry_partitionable", True)
# fp64 available for gradcheck-style kernel tests (explicit dtypes elsewhere).
jax.config.update("jax_enable_x64", True)


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs[:8]


@pytest.fixture()
def mesh8(devices8):
    from faster_distributed_training_tpu.parallel import make_mesh
    return make_mesh(("dp",), (8,), devices8)
