"""Pod-coordinated restart + cluster health watchdog tests (r10,
resilience/coordinator.py) — all CPU, ONE pytest process, tier-1.

The simulation seam is the r9 one, extended: two PodCoordinators /
AsyncCheckpointManagers / Supervisors with complementary
``process_index`` against ONE shared directory ARE a simulated two-host
pod — each "host" runs in its own thread (jax stays single-process, so
every host computes the identical full state), coordination happens
purely through the shared-fs marker files, and the manager's restore
step-agreement rides the coordinator's marker-file allgather
(``step_gather_fn``) instead of a real jax collective.  The ISSUE
acceptance tests at the bottom drive REAL train steps through real
supervisors end-to-end: kill one host → both converge on the next
generation, restore the SAME step, and finish bitwise-equal to the
uninterrupted reference; injected hang → the watchdog (the only thing
able to act while the main thread is blocked) escalates and the pod
restarts without deadlock."""

import json
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from faster_distributed_training_tpu.config import TrainConfig
from faster_distributed_training_tpu.models import Transformer
from faster_distributed_training_tpu.optim import build_optimizer
from faster_distributed_training_tpu.resilience import (
    AsyncCheckpointManager, FakeObjectStoreBackend, FaultPlan,
    GoodputTracker, PeerFailure, PodCoordinator, StepTimeout, Supervisor,
    build_resilience, pod_identity, slice_identity)
from faster_distributed_training_tpu.resilience import coordinator as coord_mod
from faster_distributed_training_tpu.resilience import faults as faults_mod
from faster_distributed_training_tpu.train import (checkpoint as ckpt,
                                                   create_train_state,
                                                   make_train_step)


def _tiny_state(seed=0):
    """Small but real TrainState (transformer d16) + one batch — the
    test_resilience.py fixture, duplicated so this file imports nothing
    from another test module."""
    cfg = TrainConfig(model="transformer", dataset="agnews", num_classes=4,
                      batch_size=4, seq_len=8, optimizer="sgd",
                      precision="fp32", epochs=1, donate=False)
    model = Transformer(n_class=4, vocab=32, n_layers=1, h=2, d_model=16,
                        d_ff=32, d_hidden=16, maxlen=8)
    tx, _ = build_optimizer(cfg, steps_per_epoch=2)
    state = create_train_state(model, tx, jnp.zeros((4, 8), jnp.int32),
                               jax.random.PRNGKey(seed),
                               init_kwargs={"train": True})
    batch = {"tokens": np.random.default_rng(0).integers(
                 0, 32, size=(4, 8)).astype(np.int32),
             "label": np.arange(4, dtype=np.int32) % 4}
    return cfg, state, batch


def _assert_tree_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


class TestPodIdentity:
    def test_env_seam_overrides_runtime(self):
        assert pod_identity({"FDT_POD_COUNT": "2",
                             "FDT_POD_INDEX": "1"}) == (1, 2, True)
        assert pod_identity({"FDT_POD_COUNT": "4"}) == (0, 4, True)

    def test_without_env_reads_jax_runtime(self):
        pi, pc, sim = pod_identity({})
        assert (pi, pc) == (jax.process_index(), jax.process_count())
        assert not sim


class TestGenerationProtocol:
    def _pair(self, d, **kw):
        kw.setdefault("sync_every", 1)
        kw.setdefault("peer_timeout_s", 0.0)   # staleness off: these
        # tests pin the FAIL-marker protocol alone
        c0 = PodCoordinator(str(d), process_index=0, process_count=2,
                            log=lambda *_: None, **kw)
        c1 = PodCoordinator(str(d), process_index=1, process_count=2,
                            log=lambda *_: None, **kw)
        return c0, c1

    def test_failure_converges_both_hosts_on_next_generation(self, tmp_path):
        c0, c1 = self._pair(tmp_path)
        try:
            assert c0.begin_attempt() == 0
            assert c1.begin_attempt() == 0
            c0.check(1)                      # clean generation: no raise
            c1.record_failure(RuntimeError("boom"), step=6)
            with pytest.raises(PeerFailure, match=r"host\(s\) \[1\]"):
                c0.check(2)
            # BOTH re-enter at 1 + the newest failed generation — however
            # each got there (own crash vs observed peer failure)
            assert c1.begin_attempt() == 1
            assert c0.begin_attempt() == 1
            c0.check(1)                      # new generation is clean
        finally:
            c0.close(), c1.close()

    def test_fail_marker_payload_and_kinds(self, tmp_path):
        c0, c1 = self._pair(tmp_path)
        try:
            c1.begin_attempt()
            c1.record_failure(StepTimeout("wedged"), step=7)
            marker = os.path.join(str(tmp_path), "gen_000000", "FAIL_00001")
            with open(marker) as f:
                got = json.load(f)
            assert got["kind"] == "hang" and got["step"] == 7
            assert "wedged" in got["reason"]
            c1.record_failure(PeerFailure("peer died"))
            with open(marker) as f:
                assert json.load(f)["kind"] == "peer"
        finally:
            c0.close(), c1.close()

    def test_fresh_process_joins_incident_generation(self, tmp_path):
        c0, c1 = self._pair(tmp_path)
        try:
            c1.begin_attempt()
            c1.record_failure(RuntimeError("x"), step=3)
        finally:
            c0.close(), c1.close()
        # a re-LAUNCHED process (nothing in memory) joins at the
        # incident's next generation instead of rewinding to 0
        fresh = PodCoordinator(str(tmp_path), process_index=0,
                               process_count=2, peer_timeout_s=0.0,
                               log=lambda *_: None)
        try:
            assert fresh.begin_attempt() == 1
        finally:
            fresh.close()

    def test_check_cadence_gating(self, tmp_path):
        c0, c1 = self._pair(tmp_path, sync_every=4)
        try:
            c0.begin_attempt(), c1.begin_attempt()
            c0.check(1)                       # first poll of the attempt
            c1.record_failure(RuntimeError("late"), step=1)
            c0.check(2)                       # same sync window: no poll
            c0.check(3)
            with pytest.raises(PeerFailure):
                c0.check(4)                   # crossed the boundary
        finally:
            c0.close(), c1.close()

    def test_generation_pruning_keeps_recent(self, tmp_path):
        c0 = PodCoordinator(str(tmp_path), process_index=0, process_count=1,
                            peer_timeout_s=0.0, log=lambda *_: None)
        try:
            for g in range(6):
                d = os.path.join(str(tmp_path), f"gen_{g:06d}")
                os.makedirs(d)
                coord_mod._write_json_atomic(
                    os.path.join(d, "FAIL_00000"), {"kind": "crash"})
            assert c0.begin_attempt() == 6
            kept = sorted(n for n in os.listdir(str(tmp_path))
                          if n.startswith("gen_"))
            assert kept == ["gen_000004", "gen_000005", "gen_000006"]
        finally:
            c0.close()


class TestHealthWatchdog:
    def test_missing_peer_heartbeat_goes_stale(self, tmp_path):
        g = GoodputTracker().start()
        c0 = PodCoordinator(str(tmp_path), process_index=0, process_count=2,
                            sync_every=1, peer_timeout_s=0.15, goodput=g,
                            log=lambda *_: None)
        try:
            c0.begin_attempt()
            c0.check(1)             # within the attempt-start grace
            time.sleep(0.25)
            with pytest.raises(PeerFailure, match="heartbeat-stale"):
                c0.check(2)
            assert g.summary()["peer_failures"] == 1
        finally:
            c0.close()

    def test_exited_peer_not_stale_and_stale_detect_latency(self, tmp_path):
        """r10 review fixes: (1) heartbeat-staleness detect_s is the full
        silence age — necessarily >= peer_timeout_s, a silent death
        cannot be observed faster than the threshold (the previous
        max(age - timeout, 0) under-reported MTTR by ~timeout for
        exactly the SIGKILL/machine-loss class the watchdog exists
        for); (2) an EXITED peer's quiet heartbeat is success, not
        death — stragglers keep running instead of restart-looping."""
        g = GoodputTracker().start()
        c0 = PodCoordinator(str(tmp_path), process_index=0, process_count=2,
                            sync_every=1, peer_timeout_s=5.0, goodput=g,
                            log=lambda *_: None)
        c1 = PodCoordinator(str(tmp_path), process_index=1, process_count=2,
                            sync_every=1, peer_timeout_s=5.0,
                            log=lambda *_: None)
        try:
            c1.begin_attempt()          # one heartbeat, then silence
            c1.close()
            c0.begin_attempt()
            c0.check(1)                 # fresh heartbeat: healthy
            # silence is SIMULATED by backdating the heartbeat mtime
            # (no sleeps — load-robust), 10 s > the 5 s timeout
            hb1 = os.path.join(c0._require_gen(), "HB_00001")
            past = time.time() - 10.0
            os.utime(hb1, (past, past))
            with pytest.raises(PeerFailure, match="heartbeat-stale"):
                c0.check(2)
            assert g.summary()["detect_s"] >= 5.0     # full silence age
            # peer 1 actually FINISHED: its EXIT marker retro-explains
            # the silence and host 0 keeps running
            c1.record_completion(step=8)
            c0.check(3)                 # no raise
        finally:
            c0.close(), c1.close()

    def test_live_peer_heartbeat_keeps_pod_healthy(self, tmp_path):
        c0 = PodCoordinator(str(tmp_path), process_index=0, process_count=2,
                            sync_every=1, peer_timeout_s=0.4,
                            hb_interval_s=0.05, log=lambda *_: None)
        c1 = PodCoordinator(str(tmp_path), process_index=1, process_count=2,
                            sync_every=1, peer_timeout_s=0.4,
                            hb_interval_s=0.05, log=lambda *_: None)
        try:
            c0.begin_attempt(), c1.begin_attempt()
            for i in range(1, 4):
                time.sleep(0.15)    # > several hb intervals, < timeout
                c0.check(i)         # peer 1's thread keeps HB fresh
        finally:
            c0.close(), c1.close()
        # AFTER close (heartbeats stopped) staleness accrues again
        time.sleep(0.5)
        c2 = PodCoordinator(str(tmp_path), process_index=0, process_count=2,
                            sync_every=1, peer_timeout_s=0.4,
                            log=lambda *_: None)
        try:
            c2._attempt_wall_t = time.time() - 10.0   # no fresh-start grace
            with pytest.raises(PeerFailure, match="heartbeat-stale"):
                c2.check(1)
        finally:
            c2.close()

    def test_step_watchdog_escalates_writes_fail_then_aborts(self, tmp_path):
        aborted = threading.Event()
        g = GoodputTracker().start()
        c0 = PodCoordinator(str(tmp_path), process_index=0, process_count=1,
                            step_timeout_s=0.15, hb_interval_s=0.03,
                            peer_timeout_s=0.0, goodput=g,
                            abort_fn=lambda reason: aborted.set(),
                            log=lambda *_: None)
        try:
            c0.begin_attempt()
            with c0.watch_steps():
                c0.check(1)
                # the "main thread" stops making progress; only the
                # watchdog thread can act
                assert aborted.wait(5.0), "watchdog never escalated"
            fails = c0._failures(c0._gen_dir)
            assert fails[0]["kind"] == "hang"       # durably published
            assert g.summary()["step_timeouts"] == 1
            # the intercepted abort surfaces as a RESTARTABLE fault on
            # the very next poll (cadence bypassed after escalation)
            with pytest.raises(StepTimeout, match="watchdog"):
                c0.check(2)
        finally:
            c0.close()

    def test_watchdog_only_armed_inside_watch_steps(self, tmp_path):
        aborted = threading.Event()
        c0 = PodCoordinator(str(tmp_path), process_index=0, process_count=1,
                            step_timeout_s=0.1, hb_interval_s=0.02,
                            peer_timeout_s=0.0,
                            abort_fn=lambda reason: aborted.set(),
                            log=lambda *_: None)
        try:
            c0.begin_attempt()
            time.sleep(0.3)      # eval/restore phase: no step progress,
            assert not aborted.is_set()   # no escalation
        finally:
            c0.close()

    def test_pause_watch_suspends_escalation_during_blocking_saves(
            self, tmp_path):
        """r10 review fix: blocking checkpoint work on the step thread
        (a cadence save draining a prior write's commit barrier, the
        preemption emergency save) is legitimate stalling — inside
        pause_watch the watchdog must NOT SIGKILL the healthy host,
        and it re-arms with a fresh step clock on exit."""
        aborted = threading.Event()
        c0 = PodCoordinator(str(tmp_path), process_index=0, process_count=1,
                            step_timeout_s=0.5, hb_interval_s=0.02,
                            peer_timeout_s=0.0,
                            abort_fn=lambda reason: aborted.set(),
                            log=lambda *_: None)
        try:
            c0.begin_attempt()
            with c0.watch_steps():
                with c0.pause_watch():
                    time.sleep(1.5)       # "saving": way past the timeout
                assert not aborted.is_set()
                # re-armed: a REAL stall after resume still escalates
                assert aborted.wait(timeout=10.0)
        finally:
            c0.close()


class TestRestoreStepGather:
    """The fs allgather that replaces the jax restore-agreement
    collective on fs-simulated pods (manager ``step_gather_fn``)."""

    def _pair(self, d, **kw):
        kw.setdefault("peer_timeout_s", 0.0)
        return (PodCoordinator(str(d), process_index=0, process_count=2,
                               log=lambda *_: None, **kw),
                PodCoordinator(str(d), process_index=1, process_count=2,
                               log=lambda *_: None, **kw))

    def test_rendezvous_returns_every_hosts_step(self, tmp_path):
        c0, c1 = self._pair(tmp_path)
        out = {}
        try:
            c0.begin_attempt(), c1.begin_attempt()
            t = threading.Thread(
                target=lambda: out.update(r1=c1.gather_restored_step(-1)))
            t.start()
            out["r0"] = c0.gather_restored_step(4)
            t.join(timeout=30)
            np.testing.assert_array_equal(out["r0"], [4, -1])
            np.testing.assert_array_equal(out["r1"], [4, -1])
        finally:
            c0.close(), c1.close()

    def test_barrier_timeout_raises_instead_of_deadlocking(self, tmp_path):
        c0, _c1 = self._pair(tmp_path, gather_timeout_s=0.2)
        try:
            c0.begin_attempt()
            with pytest.raises(PeerFailure, match="timed out"):
                c0.gather_restored_step(4)
        finally:
            c0.close(), _c1.close()

    def test_peer_failure_during_barrier_raises(self, tmp_path):
        c0, c1 = self._pair(tmp_path)
        try:
            c0.begin_attempt(), c1.begin_attempt()
            c1.record_failure(RuntimeError("died mid-restore"))
            with pytest.raises(PeerFailure, match="restore-agreement"):
                c0.gather_restored_step(4)
        finally:
            c0.close(), c1.close()

    def test_stale_exit_from_previous_run_ignored(self, tmp_path):
        """r10 review fix: EXIT markers are time-scoped to THIS run — a
        previous completed run's markers in a reused checkpoint_dir
        must neither fail fresh restore barriers ("pod already
        finished") nor disable peer-staleness detection, and a
        relaunching host clears its own."""
        c1a = PodCoordinator(str(tmp_path), process_index=1,
                             process_count=2, log=lambda *_: None)
        try:
            c1a.begin_attempt()
            c1a.record_completion(step=16)     # run 1 finished
        finally:
            c1a.close()
        time.sleep(0.05)
        # run 2 relaunches host 0 in the same directory
        c0 = PodCoordinator(str(tmp_path), process_index=0, process_count=2,
                            sync_every=1, peer_timeout_s=5.0,
                            gather_timeout_s=0.3, log=lambda *_: None)
        try:
            c0.begin_attempt()
            with pytest.raises(PeerFailure, match="timed out"):
                c0.gather_restored_step(4)     # waits — no stale fail-fast
            # ...and staleness detection still works against the peer
            hb1 = os.path.join(c0._require_gen(), "HB_00001")
            past = time.time() - 10.0
            os.utime(hb1, (past, past))
            with pytest.raises(PeerFailure, match="heartbeat-stale"):
                c0.check(1)
        finally:
            c0.close()
        # host 1's relaunch clears its own stale completion marker
        c1b = PodCoordinator(str(tmp_path), process_index=1,
                             process_count=2, log=lambda *_: None)
        try:
            c1b.begin_attempt()
            assert not os.path.exists(
                os.path.join(str(tmp_path), "EXIT_00001"))
        finally:
            c1b.close()

    def test_completed_peer_fails_barrier_fast_not_timeout(self, tmp_path):
        """r10 review fix: a peer that already COMPLETED the run (EXIT
        marker) can never join the barrier — a host restarting after
        its peer finished must learn that in milliseconds, not wait
        out gather_timeout_s per supervisor attempt."""
        c0, c1 = self._pair(tmp_path, gather_timeout_s=30.0)
        try:
            c0.begin_attempt(), c1.begin_attempt()
            c1.record_completion(step=16)
            t0 = time.monotonic()
            with pytest.raises(PeerFailure, match="already completed"):
                c0.gather_restored_step(4)
            assert time.monotonic() - t0 < 5.0    # fast, not the timeout
        finally:
            c0.close(), c1.close()


class TestBuildResilienceWiring:
    """config -> bundle: the env pod seam grows a coordinator, the
    manager rides the coordinator's step gather, and the plain
    single-host default stays coordinator-free."""

    def _cfg(self, tmp, **kw):
        return TrainConfig(model="transformer", dataset="synthetic",
                           checkpoint_dir=str(tmp), checkpoint_every=2,
                           donate=False, **kw)

    def test_simulated_pod_gets_coordinator_and_gather(self, tmp_path,
                                                       monkeypatch):
        monkeypatch.setenv(coord_mod.ENV_POD_INDEX, "1")
        monkeypatch.setenv(coord_mod.ENV_POD_COUNT, "2")
        res = build_resilience(self._cfg(tmp_path, supervise=True),
                               log=lambda *_: None)
        try:
            assert res.pod_simulated and (res.pod_index,
                                          res.pod_count) == (1, 2)
            assert res.coordinator is not None
            assert res.coordinator.directory == os.path.join(
                str(tmp_path), "_pod")
            assert res.manager is not None
            assert res.manager._step_gather_fn == \
                res.coordinator.gather_restored_step
            assert res.manager._sharded and res.manager._pi == 1
            # non-zero simulated host owns no shards (host 0 writes the
            # full replica-0 cover of the identical state)
            assert not res.manager._shard_owner(object())
        finally:
            res.close()

    def test_single_host_default_has_no_coordinator(self, tmp_path):
        res = build_resilience(self._cfg(tmp_path, supervise=True),
                               log=lambda *_: None)
        try:
            assert res.coordinator is None and res.pod_count == 1
        finally:
            res.close()

    def test_step_timeout_arms_watchdog_even_single_host(self, tmp_path):
        res = build_resilience(
            self._cfg(tmp_path, supervise=True, step_timeout_s=120.0),
            log=lambda *_: None)
        try:
            assert res.coordinator is not None
            assert res.coordinator.step_timeout_s == 120.0
        finally:
            res.close()

    def test_commit_timeout_tied_to_peer_timeout_when_armed(
            self, tmp_path, monkeypatch):
        """r17 satellite (the r14 follow-on): whenever a pod coordinator
        is armed, the manager's commit-barrier timeout defaults to
        O(peer_timeout_s) instead of the historic 600s — a barrier that
        outlives peer detection turns every re-admission hold into a
        pod_fallback_restart."""
        monkeypatch.setenv(coord_mod.ENV_POD_INDEX, "0")
        monkeypatch.setenv(coord_mod.ENV_POD_COUNT, "2")
        res = build_resilience(
            self._cfg(tmp_path, supervise=True, peer_timeout_s=20.0),
            log=lambda *_: None)
        try:
            assert res.manager._commit_timeout_s == 40.0   # max(2x, 10)
        finally:
            res.close()
        # a tiny peer timeout still gets the 10s floor
        res = build_resilience(
            self._cfg(tmp_path, supervise=True, peer_timeout_s=1.0),
            log=lambda *_: None)
        try:
            assert res.manager._commit_timeout_s == 10.0
        finally:
            res.close()

    def test_commit_timeout_unarmed_keeps_600_and_user_value_warns(
            self, tmp_path, monkeypatch):
        # no coordinator (single host, no supervise): historic default
        res = build_resilience(self._cfg(tmp_path), log=lambda *_: None)
        try:
            assert res.manager._commit_timeout_s == 600.0
        finally:
            res.close()
        # a user value that INVERTS the detection ordering warns
        monkeypatch.setenv(coord_mod.ENV_POD_INDEX, "0")
        monkeypatch.setenv(coord_mod.ENV_POD_COUNT, "2")
        logs = []
        res = build_resilience(
            self._cfg(tmp_path, supervise=True, peer_timeout_s=60.0,
                      commit_timeout_s=5.0),
            log=logs.append)
        try:
            assert res.manager._commit_timeout_s == 5.0   # honored...
            assert any("commit_timeout_s" in m and "WARNING" in m
                       for m in logs)                     # ...but warned
        finally:
            res.close()
        # ...and one that outlives the re-admission hold window warns too
        monkeypatch.setenv(coord_mod.ENV_SLICE_COUNT, "2")
        logs.clear()
        res = build_resilience(
            self._cfg(tmp_path, supervise=True, peer_timeout_s=10.0,
                      readmit_timeout_s=30.0, commit_timeout_s=120.0),
            log=logs.append)
        try:
            assert any("readmit_timeout_s" in m and "WARNING" in m
                       for m in logs)
        finally:
            res.close()

    def test_spare_env_builds_out_of_pod_identity(self, tmp_path,
                                                  monkeypatch):
        """r17 warm spares: FDT_SLICE_SPARE parks the bundle under a
        synthetic out-of-pod index (pc + spare id) — its markers, shard
        files and commit-barrier role can never collide with a
        member's — and the coordinator carries the spare identity."""
        monkeypatch.setenv(coord_mod.ENV_POD_COUNT, "2")
        monkeypatch.setenv(coord_mod.ENV_SLICE_COUNT, "2")
        monkeypatch.setenv(coord_mod.ENV_SLICE_SPARE, "0")
        res = build_resilience(self._cfg(tmp_path, supervise=True),
                               log=lambda *_: None)
        try:
            assert res.spare_index == 0
            assert res.pod_index == 2           # pc + spare id
            assert res.coordinator is not None
            assert res.coordinator.spare_index == 0
            assert res.coordinator.pi == 2
            assert res.manager._pi == 2         # never commits/prunes
        finally:
            res.close()

    def test_step_timeout_without_supervise_warns(self, tmp_path):
        """r10 review fix: the hang watchdog lives on the coordinator,
        which only the supervised path builds — --step_timeout_s
        without --supervise must WARN rather than silently no-op, even
        when it is the only resilience flag (bundle not built at
        all)."""
        logs = []
        cfg = TrainConfig(model="transformer", dataset="synthetic",
                          checkpoint_dir=str(tmp_path), donate=False,
                          step_timeout_s=60.0)
        assert build_resilience(cfg, log=logs.append) is None
        assert any("step_timeout_s" in m and "WARNING" in m for m in logs)
        # with cadence on, the bundle builds but still warns + no watchdog
        logs.clear()
        res = build_resilience(self._cfg(tmp_path, step_timeout_s=60.0),
                               log=logs.append)
        try:
            assert res.coordinator is None
            assert any("WARNING" in m for m in logs)
        finally:
            res.close()


class TestBatchOrderReagreement:
    """The restart protocol ASSUMES nothing about data position: the
    batch order is a pure function of (seed, epoch), so hosts that
    restart re-derive the identical stream and a mid-epoch resume is a
    skip into the same permutation.  The ISSUE says assert this, not
    assume it — a stateful/shuffled-in-place loader would silently
    diverge the pod after a coordinated restart."""

    def test_order_is_pure_in_seed_epoch_across_restarts(self):
        from faster_distributed_training_tpu.data.loader import (
            pod_epoch_order, shard_for_host)
        for epoch in (0, 1, 5):
            a = shard_for_host(257, epoch, seed=3)
            b = shard_for_host(257, epoch, seed=3)   # "restarted" host
            np.testing.assert_array_equal(a, b)
            pa = pod_epoch_order(64, epoch, seed=3, process_count=2,
                                 local_batch_size=4)
            pb = pod_epoch_order(64, epoch, seed=3, process_count=2,
                                 local_batch_size=4)
            np.testing.assert_array_equal(pa, pb)
        # different epochs genuinely reshuffle (the purity is in (seed,
        # epoch), not a frozen order)
        assert not np.array_equal(shard_for_host(257, 0, seed=3),
                                  shard_for_host(257, 1, seed=3))

    def test_mid_epoch_resume_position_reagrees(self):
        """Skipping start_step batches of a freshly rebuilt loader
        replays exactly the remainder of the original stream — the
        property the coordinated restart's mid-epoch resume rides."""
        from faster_distributed_training_tpu.data import (BatchLoader,
                                                          synthetic_agnews)
        ds = synthetic_agnews(n=64, max_len=16)
        mk = lambda: BatchLoader(ds, batch_size=8, epoch=1, seed=5,  # noqa: E731,E501
                                 max_len=16, process_index=0,
                                 process_count=1)
        full = [b["tokens"] for b in mk()]
        resumed = [b["tokens"] for b in mk()][3:]     # skip-replay
        assert len(full) == 8
        for a, b in zip(full[3:], resumed):
            np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# ISSUE acceptance: simulated 2-host pod, end-to-end through REAL train
# steps, managers, supervisors and the shared-fs coordination protocol.
# ---------------------------------------------------------------------------

_TOTAL = 12      # global steps per host
_EVERY = 4       # checkpoint cadence


class TestSliceIdentity:
    """r14 multi-slice seam: FDT_SLICE_INDEX/FDT_SLICE_COUNT beside
    pod_identity, contiguous-block membership, per-slice fault
    scoping (FDT_FAULT_SLICE)."""

    def test_env_seam(self):
        assert slice_identity({}) == (0, 1, False)
        assert slice_identity({"FDT_SLICE_COUNT": "1"}) == (0, 1, False)
        env = {"FDT_SLICE_COUNT": "2", "FDT_POD_COUNT": "4",
               "FDT_POD_INDEX": "3"}
        assert slice_identity(env) == (1, 2, True)
        env["FDT_SLICE_INDEX"] = "0"          # explicit override wins
        assert slice_identity(env) == (0, 2, True)

    def test_contiguous_blocks(self, tmp_path):
        c = PodCoordinator(str(tmp_path), process_index=0, process_count=8,
                           slice_index=0, slice_count=4,
                           log=lambda *_: None)
        assert [c.slice_of(p) for p in range(8)] == [0, 0, 1, 1, 2, 2, 3, 3]
        assert c._slice_members(2) == [4, 5]
        c.close()

    def test_slice_qualified_marker_names(self, tmp_path):
        c = PodCoordinator(str(tmp_path), process_index=2, process_count=4,
                           slice_index=1, slice_count=2,
                           log=lambda *_: None)
        assert c._marker_name("FAIL", 2) == "FAIL_s001_00002"
        assert c._marker_name("HB", 0) == "HB_s000_00000"
        m = coord_mod._FAIL.match(c._marker_name("FAIL", 2))
        assert m and int(m.group("pi")) == 2 and int(m.group("si")) == 1
        c.close()

    def test_fault_slice_scoping(self):
        env = {"FDT_FAULT_DIE_AT_STEP": "6", "FDT_FAULT_SLICE": "1",
               "FDT_SLICE_COUNT": "2", "FDT_POD_COUNT": "4"}
        # slice 1 = processes {2, 3}: they get the plan, slice 0 doesn't
        assert FaultPlan.from_env(env, process_index=0) is None
        assert FaultPlan.from_env(env, process_index=1) is None
        assert FaultPlan.from_env(env, process_index=2).die_at == 6
        assert FaultPlan.from_env(env, process_index=3).die_at == 6
        # composes with FDT_FAULT_HOST: both must match
        env["FDT_FAULT_HOST"] = "2"
        assert FaultPlan.from_env(env, process_index=3) is None
        assert FaultPlan.from_env(env, process_index=2).die_at == 6
        assert faults_mod.ENV_SLICE == "FDT_FAULT_SLICE"


def _slice_pair(d, readmit=10.0, backend=None, **kw):
    """Minimal 2-slice pod: one host per slice, shared directory."""
    kw.setdefault("sync_every", 1)
    kw.setdefault("peer_timeout_s", 30.0)
    out = []
    for pi in (0, 1):
        out.append(PodCoordinator(
            os.path.join(d, "_pod"), process_index=pi, process_count=2,
            slice_index=pi, slice_count=2, readmit_timeout_s=readmit,
            backend=backend, goodput=GoodputTracker(),
            log=lambda *_: None, **kw))
    return out


class TestReadmissionProtocol:
    """Unit-level drive of the r14 hold/rejoin handshake: two
    coordinators, one host per slice, no train loop."""

    def test_survivor_holds_until_rejoiner_ready_then_releases(
            self, tmp_path):
        c0, c1 = _slice_pair(str(tmp_path))
        c0.begin_attempt(), c1.begin_attempt()
        c1.record_failure(RuntimeError("boom"), step=6)
        c1.close()
        outcome = {}

        def survivor():
            try:
                c0.check(6)          # foreign-slice FAIL -> parks
                outcome["released"] = True
            except BaseException as e:   # pragma: no cover - surfaced
                outcome["error"] = e

        t = threading.Thread(target=survivor, daemon=True)
        t.start()
        hold = os.path.join(c0._gen_path(0), "HOLD_s000_00000")
        deadline = time.monotonic() + 5.0
        while not os.path.exists(hold) and time.monotonic() < deadline:
            time.sleep(0.01)
        assert os.path.exists(hold), "survivor never published its HOLD"
        assert json.load(open(hold))["step"] == 6
        # the restarted slice-1 process: fresh coordinator, same dir —
        # begin_attempt must REJOIN generation 0, not advance to 1
        c1b = PodCoordinator(
            os.path.join(str(tmp_path), "_pod"), process_index=1,
            process_count=2, sync_every=1, peer_timeout_s=30.0,
            slice_index=1, slice_count=2, readmit_timeout_s=10.0,
            goodput=GoodputTracker(), log=lambda *_: None)
        g = c1b.begin_attempt()
        assert g == 0 and c1b.rejoining
        c1b.rejoin_sync(6)           # restored step == target: completes
        t.join(timeout=10.0)
        assert outcome.get("released") is True, outcome
        # both advanced to generation 1 IN PLACE, cadence realigns at 6
        assert c0._gen == 1 and c1b._gen == 1
        assert not c1b.rejoining
        assert c0.consume_cadence_align() == 6
        assert c1b.consume_cadence_align() == 6
        assert c0.consume_cadence_align() is None      # one-shot
        s0 = c0._goodput.summary()
        s1 = c1b._goodput.summary()
        assert s0["slice_readmissions"] == 1
        assert s0["readmission_hold_s"] > 0
        assert s0["restarts"] == 0
        assert s1["slice_readmissions"] == 1
        assert s0["pod_fallback_restarts"] == 0
        c0.close(), c1b.close()

    def test_hold_timeout_falls_back_to_whole_pod(self, tmp_path):
        c0, c1 = _slice_pair(str(tmp_path), readmit=0.3)
        c0.begin_attempt(), c1.begin_attempt()
        c1.record_failure(RuntimeError("boom"), step=6)
        c1.close()
        with pytest.raises(PeerFailure, match="falling back"):
            c0.check(6)
        s0 = c0._goodput.summary()
        assert s0["pod_fallback_restarts"] == 1
        assert s0["peer_failures"] == 1
        assert s0["readmission_hold_s"] > 0.2     # the hold was real
        c0.close()

    def test_readmit_disabled_raises_immediately_like_r10(self, tmp_path):
        c0, c1 = _slice_pair(str(tmp_path), readmit=0.0)
        c0.begin_attempt(), c1.begin_attempt()
        c1.record_failure(RuntimeError("boom"), step=6)
        c1.close()
        t0 = time.monotonic()
        with pytest.raises(PeerFailure):
            c0.check(6)
        assert time.monotonic() - t0 < 1.0        # no hold happened
        assert not os.path.exists(
            os.path.join(c0._gen_path(0), "HOLD_s000_00000"))
        assert c0._goodput.summary()["pod_fallback_restarts"] == 0
        c0.close()

    def test_multi_slice_incident_goes_whole_pod(self, tmp_path):
        """Failures spanning TWO foreign slices: no hold — the r10
        whole-pod PeerFailure (re-admission only handles one slice)."""
        cs = []
        for pi in range(3):
            cs.append(PodCoordinator(
                os.path.join(str(tmp_path), "_pod"), process_index=pi,
                process_count=3, sync_every=1, slice_index=pi,
                slice_count=3, readmit_timeout_s=10.0,
                goodput=GoodputTracker(), log=lambda *_: None))
        for c in cs:
            c.begin_attempt()
        cs[1].record_failure(RuntimeError("b1"), step=6)
        cs[2].record_failure(RuntimeError("b2"), step=6)
        t0 = time.monotonic()
        with pytest.raises(PeerFailure):
            cs[0].check(6)
        assert time.monotonic() - t0 < 1.0
        for c in cs:
            c.close()

    def test_rejoin_retry_aborts_to_whole_pod(self, tmp_path):
        """Own rejoin residue in the incident generation (a previous
        rejoin attempt died mid-handshake): begin_attempt publishes
        RJ_ABORT and takes the whole-pod path — retry ambiguity always
        degrades to the proven r10 protocol."""
        c0, c1 = _slice_pair(str(tmp_path))
        c0.begin_attempt(), c1.begin_attempt()
        c1.record_failure(RuntimeError("boom"), step=6)
        # residue of a first rejoin attempt by host 1
        coord_mod._write_json_atomic(
            os.path.join(c1._gen_path(0), "RJRENTER_s001_00001"),
            {"step": 4})
        c1.close()
        c1b = PodCoordinator(
            os.path.join(str(tmp_path), "_pod"), process_index=1,
            process_count=2, sync_every=1, slice_index=1, slice_count=2,
            readmit_timeout_s=10.0, goodput=GoodputTracker(),
            log=lambda *_: None)
        g = c1b.begin_attempt()
        assert g == 1 and not c1b.rejoining       # whole-pod path
        assert os.path.exists(os.path.join(c1b._gen_path(0), "RJ_ABORT"))
        c1b.close()

    def test_stale_foreign_slice_gets_proxied_fail(self, tmp_path):
        """A silently-SIGKILLed foreign slice (no FAIL marker): the
        survivor writes a proxied FAIL on its behalf — the durable
        incident record the relaunched slice keys its rejoin on — then
        holds (here: times out into the fallback)."""
        c0, c1 = _slice_pair(str(tmp_path), readmit=0.3,
                             peer_timeout_s=0.2)
        c0.begin_attempt(), c1.begin_attempt()
        c1.close()                     # slice 1 goes silent
        time.sleep(0.4)                # heartbeat goes stale
        with pytest.raises(PeerFailure, match="falling back"):
            c0.check(6)
        fail = os.path.join(c0._gen_path(0), "FAIL_s001_00001")
        got = json.load(open(fail))
        assert got["kind"] == "stale" and got["proxied_by"] == 0
        # ...and a fresh slice-1 relaunch keys its rejoin on it
        c1b = PodCoordinator(
            os.path.join(str(tmp_path), "_pod"), process_index=1,
            process_count=2, sync_every=1, slice_index=1, slice_count=2,
            readmit_timeout_s=10.0, goodput=GoodputTracker(),
            log=lambda *_: None)
        c1b.begin_attempt()
        assert c1b.rejoining
        c0.close(), c1b.close()


class TestWarmSpareProtocol:
    """Unit drive of the r17 SPARE/CLAIM marker exchange (no train
    loop): a parked spare claims a failed seat only once the survivors
    are provably holding, arbitration is first-writer-wins, a
    relaunched original finds the claim and stands down, and a
    completed pod sends the spare home."""

    def _spare(self, d, idx=0, pi=None):
        c = PodCoordinator(
            os.path.join(d, "_pod"), process_index=0, process_count=2,
            sync_every=1, peer_timeout_s=30.0, slice_count=2,
            readmit_timeout_s=10.0, spare_index=idx,
            goodput=GoodputTracker(), log=lambda *_: None)
        if pi is not None:
            c.pi = pi
        return c

    def test_claim_waits_for_holds_then_swaps(self, tmp_path):
        c0, c1 = _slice_pair(str(tmp_path))
        c0.begin_attempt(), c1.begin_attempt()
        c1.record_failure(RuntimeError("boom"), step=6)
        c1.close()
        sp = self._spare(str(tmp_path))
        assert sp.pi == 2                  # synthetic out-of-pod index
        # survivors not parked yet: no claim (racing the whole-pod path)
        assert sp._spare_try_claim() is None
        outcome = {}

        def survivor():
            try:
                c0.check(6)                # foreign-slice FAIL -> parks
                outcome["released"] = True
            except BaseException as e:     # pragma: no cover - surfaced
                outcome["error"] = e

        t = threading.Thread(target=survivor, daemon=True)
        t.start()
        hold = os.path.join(c0._gen_path(0), "HOLD_s000_00000")
        deadline = time.monotonic() + 5.0
        while not os.path.exists(hold) and time.monotonic() < deadline:
            time.sleep(0.01)
        assert os.path.exists(hold)
        claim = sp._spare_try_claim()
        assert claim == {"seat": 1, "slice": 1, "generation": 0}
        assert sp.pi == 1 and sp.si == 1 and sp.rejoining
        # first writer won: a second spare finds every seat claimed
        sp2 = self._spare(str(tmp_path), idx=1, pi=3)
        assert sp2._spare_try_claim() is None
        # the spare completes the swap (restored step == target here)
        sp.rejoin_sync(6)
        t.join(timeout=10.0)
        assert outcome.get("released") is True, outcome
        s = sp._goodput.summary()
        assert s["warm_spare_claims"] == 1
        assert s["warm_spare_swaps"] == 1
        assert s["warm_spare_swap_s"] > 0
        assert c0._goodput.summary()["slice_readmissions"] == 1
        sp.close(), sp2.close(), c0.close()

    def test_relaunched_original_raises_seat_taken(self, tmp_path):
        """The original host coming back after a spare claimed its seat
        must stand down — two processes under one pod identity would
        corrupt every barrier — and SeatTaken is not restartable (the
        supervisor pass-through is pinned in test_resilience)."""
        from faster_distributed_training_tpu.resilience import SeatTaken
        c0, c1 = _slice_pair(str(tmp_path))
        c0.begin_attempt(), c1.begin_attempt()
        c1.record_failure(RuntimeError("boom"), step=6)
        c1.close()
        coord_mod._write_json_atomic(
            os.path.join(c0._gen_path(0), "CLAIM_s001_00001"),
            {"spare": 0})
        c1b = PodCoordinator(
            os.path.join(str(tmp_path), "_pod"), process_index=1,
            process_count=2, sync_every=1, slice_index=1, slice_count=2,
            readmit_timeout_s=10.0, goodput=GoodputTracker(),
            log=lambda *_: None)
        with pytest.raises(SeatTaken, match="warm spare"):
            c1b.begin_attempt()
        c1b.close(), c0.close()

    def test_spare_stands_down_when_pod_completes(self, tmp_path):
        c0, c1 = _slice_pair(str(tmp_path))
        c0.begin_attempt(), c1.begin_attempt()
        sp = self._spare(str(tmp_path))      # created BEFORE the EXITs
        time.sleep(0.02)   # EXIT times are ms-rounded; step past the
        #                    spare's creation stamp deterministically
        c0.record_completion(step=16)
        c1.record_completion(step=16)
        refreshes = []
        got = sp.spare_wait(refresh_fn=lambda: refreshes.append(1),
                            poll_s=0.01)
        assert got is None                   # stood down, nothing claimed
        assert refreshes                     # the park loop did refresh
        sp.close(), c0.close(), c1.close()

    def test_original_rejoin_claims_seat_atomically(self, tmp_path):
        """Review fix (TOCTOU): the relaunched ORIGINAL arbitrates its
        seat through the same first-writer-wins CLAIM create_if_absent
        a spare uses — a check-then-proceed would race a spare's claim
        in the begin_attempt-to-first-rejoin-marker gap and put two
        processes under one pod identity.  Winning blocks every spare;
        a rejoin RETRY (our own earlier claim) still proceeds."""
        c0, c1 = _slice_pair(str(tmp_path))
        c0.begin_attempt(), c1.begin_attempt()
        c1.record_failure(RuntimeError("boom"), step=6)
        c1.close()
        # survivors hold (so a spare WOULD otherwise claim)
        coord_mod._write_json_atomic(
            os.path.join(c0._gen_path(0), "HOLD_s000_00000"), {"step": 6})
        c1b = PodCoordinator(
            os.path.join(str(tmp_path), "_pod"), process_index=1,
            process_count=2, sync_every=1, slice_index=1, slice_count=2,
            readmit_timeout_s=10.0, goodput=GoodputTracker(),
            log=lambda *_: None)
        g = c1b.begin_attempt()
        assert g == 0 and c1b.rejoining       # the original won its seat
        claim = json.load(open(os.path.join(
            c1b._gen_path(0), "CLAIM_s001_00001")))
        assert claim["spare"] is None and claim["pi"] == 1
        sp = self._spare(str(tmp_path))
        assert sp._spare_try_claim() is None  # spare lost arbitration
        # a retry by the SAME original (fresh process, same seat) finds
        # its own claim and keeps the seat; the RJRENTER-residue rule
        # then decides retry-vs-abort exactly as before
        c1c = PodCoordinator(
            os.path.join(str(tmp_path), "_pod"), process_index=1,
            process_count=2, sync_every=1, slice_index=1, slice_count=2,
            readmit_timeout_s=10.0, goodput=GoodputTracker(),
            log=lambda *_: None)
        assert c1c.begin_attempt() == 0 and c1c.rejoining
        sp.close(), c0.close(), c1b.close(), c1c.close()

    def test_malformed_spare_id_fails_fast(self):
        """Review fix: two spares whose malformed ids both silently
        mapped to 0 would collide on the synthetic pod index — a typo'd
        launcher config must raise, not alias."""
        with pytest.raises(ValueError, match="FDT_SLICE_SPARE"):
            coord_mod.spare_identity(env={"FDT_SLICE_SPARE": "yes"})
        assert coord_mod.spare_identity(env={}) is None
        assert coord_mod.spare_identity(env={"FDT_SLICE_SPARE": "2"}) == 2

    def test_spare_ignores_incident_already_rejoining(self, tmp_path):
        """The real slice beat the spare to its own seat (RJRENTER in
        the generation): the spare stands aside instead of racing it."""
        c0, c1 = _slice_pair(str(tmp_path))
        c0.begin_attempt(), c1.begin_attempt()
        c1.record_failure(RuntimeError("boom"), step=6)
        coord_mod._write_json_atomic(
            os.path.join(c0._gen_path(0), "HOLD_s000_00000"), {"step": 6})
        coord_mod._write_json_atomic(
            os.path.join(c0._gen_path(0), "RJRENTER_s001_00001"),
            {"step": 4})
        sp = self._spare(str(tmp_path))
        assert sp._spare_try_claim() is None
        sp.close(), c0.close(), c1.close()


def _run_spare(d, step_fn, state0, gp, total=_TOTAL):
    """The spare side of the warm-spare e2e: park (programs already
    warm — step_fn is the shared compiled program), claim, restore
    through the slice-scoped barrier, catch up, release, finish the
    run in the dead member's place."""
    coord = PodCoordinator(
        os.path.join(d, "_pod"), process_index=0, process_count=2,
        sync_every=1, peer_timeout_s=30.0, slice_count=2,
        readmit_timeout_s=30.0, spare_index=0, goodput=gp,
        log=lambda *_: None)
    claim = coord.spare_wait(poll_s=0.02)
    if claim is None:
        coord.close()
        return None
    mgr = AsyncCheckpointManager(
        d, every_steps=_EVERY, process_index=coord.pi, process_count=2,
        shard_owner=(lambda sh: False), commit_timeout_s=15.0,
        step_gather_fn=coord.gather_restored_step, goodput=gp,
        log=lambda *_: None)
    coord.drain_fn = mgr.wait
    try:
        st, start = state0, 0
        got = mgr.restore_latest(st)
        if got is not None:
            st, meta = got
            start = int(meta["step"])
        coord.rejoin_sync(start)
        with coord.watch_steps():
            for i in range(start + 1, total + 1):
                st, _m = step_fn(st)
                coord.check(i)
                align = coord.consume_cadence_align()
                if align is not None:
                    mgr.align_cadence(align)
                if not coord.saves_suspended:
                    mgr.maybe_save(st, i)
        mgr.wait()
        coord.record_completion(step=total)
        return st
    finally:
        mgr.close()
        coord.close()


class TestWarmSpareEndToEnd:
    """ISSUE acceptance (r17): kill slice 1 for good -> the spare
    claims its seat -> the survivor's HOLD is shorter than the
    cold-rejoin twin's (which pays a fresh program build, the process-
    relaunch reality) -> final states bitwise-equal to the
    uninterrupted reference."""

    @pytest.fixture(scope="class")
    def program(self):
        cfg, state, batch = _tiny_state()
        step = jax.jit(make_train_step(cfg))
        reference = state
        for _ in range(_TOTAL):
            reference, _m = step(reference, batch)
        return cfg, state, batch, (lambda st: step(st, batch)), reference

    def test_spare_swap_bitwise_and_faster_than_cold_rejoin(
            self, program, tmp_path):
        cfg, state, batch, step_fn, reference = program

        # -- scenario A: warm spare; the victim has NO restart budget
        # (dead for good — the platform never relaunches it)
        d = str(tmp_path / "spare")
        barrier = threading.Barrier(2)
        kw = dict(pc=2, readmit_timeout_s=30.0, step_delay=0.02,
                  slice_count=2)
        h0 = _SimHost(0, d, barrier, slice_index=0, **kw)
        h1 = _SimHost(1, d, barrier, faults=FaultPlan(die_at=6),
                      slice_index=1, max_restarts=0, **kw)
        gp_spare = GoodputTracker().start()
        results, errors = {}, {}

        def run_host(h):
            try:
                results[h.pi] = h.run(step_fn, state)
            except BaseException as e:
                errors[h.pi] = e

        def run_sp():
            try:
                results["spare"] = _run_spare(d, step_fn, state, gp_spare)
            except BaseException as e:     # pragma: no cover - surfaced
                errors["spare"] = e

        threads = [threading.Thread(target=run_host, args=(h,),
                                    daemon=True) for h in (h0, h1)]
        threads.append(threading.Thread(target=run_sp, daemon=True))
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        assert not any(t.is_alive() for t in threads), "spare pod hung"
        # the victim died for good, by design; nothing else may fail
        assert isinstance(errors.pop(1, None), faults_mod.InjectedFault)
        assert not errors, f"unexpected failures: {errors!r}"
        # survivor: held once, never restarted, never rolled back
        s0 = h0.goodput.summary()
        assert s0["restarts"] == 0 and s0["restores"] == 0
        assert s0["slice_readmissions"] == 1
        spare_hold = s0["readmission_hold_s"]
        assert spare_hold > 0
        # spare: claimed + swapped, finished bitwise-correct
        ssp = gp_spare.summary()
        assert ssp["warm_spare_claims"] == 1
        assert ssp["warm_spare_swaps"] == 1
        _assert_tree_equal(ckpt._state_pytree(results["spare"]),
                           ckpt._state_pytree(reference))
        _assert_tree_equal(ckpt._state_pytree(results[0]),
                           ckpt._state_pytree(reference))

        # -- scenario B: cold-rejoin twin — no spare; the killed slice
        # restarts and rejoins through a FRESHLY BUILT program (a new
        # jax.jit recompiles: the relaunch reality a restarted slice
        # pays), so the survivor's hold covers that compile
        d2 = str(tmp_path / "cold")
        barrier2 = threading.Barrier(2)

        def fresh_program():
            fresh = jax.jit(make_train_step(cfg))
            return lambda st: fresh(st, batch)

        c0 = _SimHost(0, d2, barrier2, slice_index=0, **kw)
        c1 = _SimHost(1, d2, barrier2, faults=FaultPlan(die_at=6),
                      slice_index=1, fresh_program_fn=fresh_program, **kw)
        results2 = _run_pod([c0, c1], step_fn, state)
        s0c = c0.goodput.summary()
        assert s0c["slice_readmissions"] == 1
        cold_hold = s0c["readmission_hold_s"]
        for pi in (0, 1):
            _assert_tree_equal(ckpt._state_pytree(results2[pi]),
                               ckpt._state_pytree(reference))
        # the tentpole claim, measured: the warm spare's swap keeps the
        # survivors parked for LESS time than a cold rejoin that must
        # rebuild its programs
        assert spare_hold < cold_hold, \
            f"spare hold {spare_hold:.3f}s !< cold hold {cold_hold:.3f}s"


class TestSimulatedSlicePodEndToEnd:
    """ISSUE acceptance (r14): simulated 2-slice pod, 4 hosts, slice 1
    killed whole mid-run — the surviving slice parks (never exits its
    dispatch loop, never restarts, never rolls back), the killed slice
    restarts, rejoins the SAME generation and catches up, and every
    host finishes bitwise-equal to the uninterrupted reference.  Run on
    the shared POSIX directory AND on the fake object store (shared
    MemoryMedium across the host threads) with the rename primitives
    trapped on the checkpoint namespace."""

    @pytest.fixture(scope="class")
    def program(self):
        cfg, state, batch = _tiny_state()
        step = jax.jit(make_train_step(cfg))
        reference = state
        for _ in range(_TOTAL):
            reference, _m = step(reference, batch)
        return state, (lambda st: step(st, batch)), reference

    @pytest.mark.parametrize("store", ["posix", "fake_object_store"])
    def test_slice_kill_survivors_hold_rejoin_bitwise(
            self, program, tmp_path, store, monkeypatch):
        state, step_fn, reference = program
        d = str(tmp_path)
        be = None
        if store == "fake_object_store":
            be = FakeObjectStoreBackend()
            # zero-rename proof: any rename primitive touching the
            # checkpoint namespace while the object store serves it is
            # a routing bug
            real = os.replace

            def guarded(src, dst, *a, **k):
                if str(dst).startswith(d):
                    raise AssertionError(
                        f"os.replace on object-store path {dst}")
                return real(src, dst, *a, **k)
            monkeypatch.setattr(os, "replace", guarded)
        barrier = threading.Barrier(4)
        kw = dict(pc=4, backend=be, slice_count=2, readmit_timeout_s=30.0,
                  step_delay=0.02)
        hosts = [
            _SimHost(0, d, barrier, slice_index=0, **kw),
            _SimHost(1, d, barrier, slice_index=0, **kw),
            _SimHost(2, d, barrier, faults=FaultPlan(die_at=6),
                     slice_index=1, **kw),
            _SimHost(3, d, barrier, faults=FaultPlan(die_at=6),
                     slice_index=1, **kw),
        ]
        results = _run_pod(hosts, step_fn, state)
        for pi in range(4):
            _assert_tree_equal(ckpt._state_pytree(results[pi]),
                               ckpt._state_pytree(reference))
        s = [h.goodput.summary() for h in hosts]
        for i in (0, 1):     # the surviving slice: held, nothing else
            assert s[i]["restarts"] == 0 and s[i]["restores"] == 0, s[i]
            assert s[i]["slice_readmissions"] == 1
            assert s[i]["readmission_hold_s"] > 0
            assert hosts[i].generations == [0]
        for i in (2, 3):     # the killed slice: restarted + re-admitted
            assert s[i]["restarts"] == 1
            assert s[i]["slice_readmissions"] == 1
            # the second attempt REJOINED generation 0, no advance
            assert hosts[i].generations == [0, 0]
            assert hosts[i].restored_steps[1] >= 0
        assert all(x["pod_fallback_restarts"] == 0 for x in s), s


class _SimHost:
    """One simulated pod host running in its own thread: its own
    coordinator + sharded manager (complementary owners) + supervisor +
    fault plan against the SHARED directory (or shared object-store
    backend, r14).  ``barrier`` keeps the hosts in loose lockstep so
    the failure injection interleaves deterministically enough to
    assert on; it is aborted (not just broken) the moment any attempt
    dies, so the survivors never wait out the full barrier timeout.
    ``step_delay`` paces the free-running phase after an abort (slice
    tests: a survivor must observe the FAIL marker before it can finish
    the run).  The attempt body mirrors Trainer._resilience_hooks'
    hazard order INCLUDING the r14 hooks: rejoin_sync after restore,
    cadence re-align after check, saves gated on saves_suspended."""

    def __init__(self, pi, d, barrier, faults=None, total=_TOTAL,
                 pc=2, backend=None, step_delay=0.0, max_restarts=3,
                 fresh_program_fn=None, **coord_kw):
        self.pi, self.total, self.barrier = pi, total, barrier
        self.step_delay = step_delay
        # r17 cold-rejoin twin: when set, every RESTART attempt steps
        # through fresh_program_fn() instead of the shared warm step_fn
        # — a fresh jax.jit recompiles, modeling the process relaunch a
        # real restarted slice pays (the warm-spare e2e measures the
        # survivor hold against exactly this)
        self.fresh_program_fn = fresh_program_fn
        self.goodput = GoodputTracker()
        coord_kw.setdefault("sync_every", 1)
        coord_kw.setdefault("peer_timeout_s", 30.0)
        self.coord = PodCoordinator(
            os.path.join(d, "_pod"), process_index=pi, process_count=pc,
            backend=backend,
            goodput=self.goodput, log=lambda *_: None, **coord_kw)
        self.mgr = AsyncCheckpointManager(
            d, every_steps=_EVERY, process_index=pi, process_count=pc,
            shard_owner=((lambda sh: sh.replica_id == 0) if pi == 0
                         else (lambda sh: False)),
            commit_timeout_s=15.0, backend=backend,
            step_gather_fn=self.coord.gather_restored_step,
            goodput=self.goodput, log=lambda *_: None)
        self.coord.drain_fn = self.mgr.wait
        self.faults = faults
        self.sup = Supervisor(max_restarts=max_restarts, backoff_base=0.01,
                              goodput=self.goodput, log=lambda *_: None,
                              coordinator=self.coord)
        self.progress = 0
        self.generations = []        # generation entered per attempt
        self.restored_steps = []     # restore_latest outcome per attempt

    def _lockstep(self):
        try:
            self.barrier.wait(timeout=30.0)
        except threading.BrokenBarrierError:
            if self.step_delay:
                time.sleep(self.step_delay)   # pace the free run

    def run(self, step_fn, state0):
        def attempt(_i):
            try:
                fn = step_fn
                if self.fresh_program_fn is not None and _i > 0:
                    fn = self.fresh_program_fn()
                self.generations.append(self.coord._gen)
                st, start = state0, 0
                got = self.mgr.restore_latest(st)
                if got is not None:
                    st, meta = got
                    start = int(meta["step"])
                self.restored_steps.append(start if got is not None else -1)
                self.progress = start
                if self.coord.rejoining:
                    # r14: agree the catch-up target with the parked
                    # survivors (completes here when start == target)
                    self.coord.rejoin_sync(start)
                # mirror Trainer._resilience_hooks' hazard order: faults
                # (the crash), then the coordinator poll, then the save
                with self.coord.watch_steps():
                    for i in range(start + 1, self.total + 1):
                        self._lockstep()
                        st, _m = fn(st)
                        self.progress = i
                        if self.faults is not None:
                            self.faults.on_step(i)
                        self.coord.check(i)
                        align = self.coord.consume_cadence_align()
                        if align is not None:
                            self.mgr.align_cadence(align)
                        if not self.coord.saves_suspended:
                            self.mgr.maybe_save(st, i)
                self.mgr.wait()
                return st
            except BaseException:
                self.barrier.abort()
                raise
        try:
            return self.sup.run(attempt, lambda: self.progress)
        finally:
            self.mgr.close()
            self.coord.close()


def _run_pod(hosts, step_fn, state0):
    results, errors = {}, {}

    def body(h):
        try:
            results[h.pi] = h.run(step_fn, state0)
        except BaseException as e:          # pragma: no cover - surfaced
            errors[h.pi] = e

    threads = [threading.Thread(target=body, args=(h,), daemon=True)
               for h in hosts]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180)
    assert not any(t.is_alive() for t in threads), \
        "pod deadlocked: a host thread never finished"
    assert not errors, f"host(s) died unrecovered: {errors!r}"
    return results


class TestSimulatedPodEndToEnd:
    @pytest.fixture(scope="class")
    def program(self):
        cfg, state, batch = _tiny_state()
        step = jax.jit(make_train_step(cfg))
        reference = state
        for _ in range(_TOTAL):
            reference, _m = step(reference, batch)
        return state, (lambda st: step(st, batch)), reference

    @pytest.mark.slow  # r24 budget diet: 15 s — the FAIL-marker /
    # generation-agreement protocol keeps tier-1 coverage via
    # TestSimulatedSlicePodEndToEnd::test_slice_kill_survivors_hold_rejoin_bitwise
    # (same markers + restore-step agreement on the richer slice path)
    # and kill-at-N bitwise resume stays pinned by test_mesh2d,
    # test_pipeline, and test_sentinel's kill-mid-replay twin
    def test_killed_host_pod_restarts_same_generation_bitwise(
            self, program, tmp_path):
        """Kill host 1 at step 6: host 0 observes the FAIL marker, both
        supervisors re-enter generation 1, restore_latest agrees step 4
        on both, and both finish bitwise-equal to uninterrupted."""
        state, step_fn, reference = program
        barrier = threading.Barrier(2)
        h0 = _SimHost(0, str(tmp_path), barrier)
        h1 = _SimHost(1, str(tmp_path), barrier, faults=FaultPlan(die_at=6))
        results = _run_pod([h0, h1], step_fn, state)
        # same generation sequence on both hosts
        assert h0.generations == [0, 1]
        assert h1.generations == [0, 1]
        # restore step-agreement: both restored the SAME step (the last
        # committed cadence save before the kill)
        assert h0.restored_steps == [-1, _EVERY]
        assert h1.restored_steps == [-1, _EVERY]
        # resumed runs are bitwise-equal to the uninterrupted reference
        for pi in (0, 1):
            _assert_tree_equal(ckpt._state_pytree(results[pi]),
                               ckpt._state_pytree(reference))
        # MTTR accounting: the survivor observed a peer failure and its
        # recovery latency decomposes into detect + backoff + restore
        s0, s1 = h0.goodput.summary(), h1.goodput.summary()
        assert s0["peer_failures"] == 1 and s0["restarts"] == 1
        assert s0["restart_mttr_s"] > 0 and s0["restore_s"] > 0
        assert s1["restarts"] == 1 and s1["restart_mttr_s"] > 0

    def test_hung_host_watchdog_escalates_pod_recovers(self, program,
                                                      tmp_path):
        """FDT_FAULT_HANG_AT_STEP semantics: host 1's main thread blocks
        forever at step 6 — nothing raises, nothing exits.  Its watchdog
        escalates within step_timeout_s (FAIL marker first, then the
        abort, which the test intercepts to release the hang in place of
        SIGKILL), host 0 observes the marker, and the pod restarts
        without deadlock."""
        state, step_fn, reference = program
        barrier = threading.Barrier(2)
        plan = FaultPlan(hang_at=6)
        h0 = _SimHost(0, str(tmp_path), barrier)
        h1 = _SimHost(1, str(tmp_path), barrier, faults=plan,
                      step_timeout_s=0.4, hb_interval_s=0.05,
                      abort_fn=lambda reason: plan.hang_release.set())
        t0 = time.monotonic()
        results = _run_pod([h0, h1], step_fn, state)
        elapsed = time.monotonic() - t0
        assert h0.generations == [0, 1] and h1.generations == [0, 1]
        assert h0.restored_steps == [-1, _EVERY]
        assert h1.restored_steps == [-1, _EVERY]
        for pi in (0, 1):
            _assert_tree_equal(ckpt._state_pytree(results[pi]),
                               ckpt._state_pytree(reference))
        s0, s1 = h0.goodput.summary(), h1.goodput.summary()
        assert s1["step_timeouts"] == 1      # the watchdog fired
        assert s0["peer_failures"] == 1      # ...and the peer saw it
        assert s0["restart_mttr_s"] > 0 and s1["restart_mttr_s"] > 0
        # detection was watchdog-fast, not peer-timeout-slow: the whole
        # recovered run is far inside the 30s staleness window
        assert elapsed < 30.0


def _load_smoke_module(monkeypatch):
    """The smoke script, plus env so its subprocess children inherit
    conftest's numeric config (x64, partitionable threefry: set here
    in-process via jax.config, invisible to subprocesses) — or the
    byte-equality checks would compare across float semantics."""
    import importlib.util

    monkeypatch.setenv("JAX_ENABLE_X64", str(int(jax.config.jax_enable_x64)))
    monkeypatch.setenv("JAX_THREEFRY_PARTITIONABLE",
                       str(int(jax.config.jax_threefry_partitionable)))
    spec = importlib.util.spec_from_file_location(
        "pod_restart_smoke",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "pod_restart_smoke.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_SMOKE_REF = {}


def _smoke_reference_digest(mod):
    """The uninterrupted in-process reference, computed ONCE per pytest
    process and shared by every smoke wrapper (same math regardless of
    the pod scenario/backend under test — recomputing it per wrapper
    would triple the tier-1 cost for zero coverage)."""
    if "digest" not in _SMOKE_REF:
        import tempfile

        from faster_distributed_training_tpu.cli import run_training
        ref = run_training(mod.reference_cfg(tempfile.mkdtemp()),
                           log=lambda *_: None)
        assert int(ref["state"].step) == mod.TOTAL_STEPS
        _SMOKE_REF["digest"] = mod.state_digest(ref["state"])
    return _SMOKE_REF["digest"]


@pytest.mark.slow  # r21 budget diet: 35 s (includes the in-process
# reference training the other smoke variants share) — process-level
# kill/respawn keeps a tier-1 representative in the decode smoke
# wrapper (tests/test_decode.py::test_decode_smoke_in_process: real
# SIGKILL of a spawned worker + respawn/readmit), and bitwise
# kill-at-N resume stays tier-1 in test_mesh2d/test_resilience
def test_pod_restart_smoke(monkeypatch):
    """scripts/pod_restart_smoke.py end-to-end: a REAL two-process
    simulated pod (coordination genuinely cross-process through the
    shared fs), host 1 killed via FDT_FAULT_HOST+FDT_FAULT_DIE_AT_STEP,
    coordinated restart + final-state equality asserted by the script
    itself.  The uninterrupted reference digest is computed IN-process
    (warm jax) so the smoke only spawns the two pod children."""
    mod = _load_smoke_module(monkeypatch)
    assert mod.main(ref_digest=_smoke_reference_digest(mod)) == 0


@pytest.mark.slow  # r20 budget diet: 29 s — the SAME smoke as
# test_pod_restart_smoke (which stays tier-1) on the fake-object-store
# backend; the backend's rename-free semantics are unit-tested in
# test_resilience.py
def test_pod_restart_smoke_fake_object_store(monkeypatch):
    """r14 satellite: the SAME two-process kill/recover scenario with
    every resilience-critical durable write on the rename-free
    fake-object-store backend (framed generation files under
    <dir>/_objects, cross-PROCESS) — digest equality must hold with no
    rename primitive, and the script asserts no marker/step-checkpoint
    state leaked onto the plain filesystem."""
    mod = _load_smoke_module(monkeypatch)
    assert mod.main(ref_digest=_smoke_reference_digest(mod),
                    backend="fake_object_store") == 0


@pytest.mark.slow  # r21 budget diet: 32 s — the plain
# test_pod_restart_smoke stays tier-1 for the restart flow; the r17
# cache_source=deserialized contract keeps tier-1 coverage via the
# manifest compile-table tests and the decode program-pin test (which
# round-trips the executable cache), and the MTTR A/B stays with the
# bench restart_mttr_s vs restart_cached_mttr_s arms
def test_pod_restart_smoke_cache(monkeypatch):
    """r17 acceptance: scripts/pod_restart_smoke.py --cache — crash +
    process relaunch with the executable cache armed: the relaunched
    process records cache_source=deserialized for EVERY steady-state
    program, zero retraces, bitwise-equal final state.  Budget mode
    (cache_cold_twin=False): the digest compares against the
    UNINTERRUPTED reference, which the resilience e2e suite already
    pins bitwise-equal to a cold restart (kill-at-N resume, r7), and
    the cold-acquisition A/B stays with the bench restart_mttr_s vs
    restart_cached_mttr_s arms — the manual script run keeps the full
    cold twin (~25 s of extra compile this wrapper spares tier-1)."""
    mod = _load_smoke_module(monkeypatch)
    assert mod.main(ref_digest=_smoke_reference_digest(mod),
                    cache=True, cache_cold_twin=False) == 0


@pytest.mark.slow
def test_pod_restart_smoke_two_slices(monkeypatch):
    """r14 acceptance at PROCESS level (the threaded twin runs tier-1;
    this one is `-m slow`): 2-slice pod, 4 processes, slice 1 killed
    whole via FDT_FAULT_SLICE — survivors hold (zero restarts / zero
    restores), the slice rejoins, all digests equal the reference."""
    mod = _load_smoke_module(monkeypatch)
    assert mod.main(ref_digest=_smoke_reference_digest(mod),
                    slices=2) == 0
