"""Run-scoped telemetry subsystem (r12): recorder/JSONL schema, span
API, pod aggregation + straggler detection, the windowed profiler, the
live-throughput fix, and the report script against the recorded
fixture.

Pod scope uses the established simulation seams (two recorders with
explicit process_index sharing one directory = a simulated two-host
pod — the r9/r10 pattern), never real multi-process runs."""

import glob
import importlib.util
import json
import os
import time

import numpy as np
import pytest

from faster_distributed_training_tpu.config import TrainConfig
from faster_distributed_training_tpu.telemetry import (
    TelemetryRecorder, aggregate_run, build_telemetry, pod_epoch_aggregate,
    publish_epoch_marker, read_host_records, span_breakdown, spans,
    write_manifest)
from faster_distributed_training_tpu.train.metrics import percentiles
from faster_distributed_training_tpu.utils.profiling import (
    StepWindowProfiler, parse_profile_steps)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(ROOT, "tests", "fixtures", "telemetry")


def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


class TestRecorder:
    def test_jsonl_stream_and_manifest(self, tmp_path):
        d = str(tmp_path)
        rec = TelemetryRecorder(d, process_index=0, process_count=1,
                                log=lambda *_: None)
        rec.record_step(1, 0, 1, 1, 12.0, 10.0, 64, data_ms=1.5,
                        block_ms=0.5, compile_=True)
        rec.record_step(2, 0, 2, 1, 10.0, 9.5, 64)
        rec.record_span("eval", 123.4, step=2)
        rec.record_event("epoch", epoch=0, steps=2, loss=1.25)
        rec.close()
        recs = _read_jsonl(os.path.join(d, "host_00000.jsonl"))
        kinds = [r["kind"] for r in recs]
        assert kinds == ["run_start", "step", "step", "span", "epoch"]
        s1, s2 = recs[1], recs[2]
        assert s1["compile"] is True and "compile" not in s2
        assert s1["wall_ms"] == 12.0 and s1["data_ms"] == 1.5
        assert s2["ex_s"] == round(64 / (10.0 / 1e3), 1)
        assert recs[3]["name"] == "eval" and recs[3]["step"] == 2
        # manifest is self-describing: versions + device + config + mesh
        write_manifest(d, cfg=TrainConfig(), extra={"workload": "t"})
        man = json.load(open(os.path.join(d, "manifest.json")))
        for key in ("schema", "jax_version", "jaxlib_version", "backend",
                    "device_kind", "config", "workload"):
            assert key in man, key
        assert man["config"]["batch_size"] == TrainConfig().batch_size

    def test_capacity_triggers_background_flush(self, tmp_path):
        rec = TelemetryRecorder(str(tmp_path), process_index=0,
                                process_count=1, capacity=8,
                                log=lambda *_: None)
        for i in range(20):
            rec.record_step(i + 1, 0, i + 1, 1, 1.0, 1.0, 4)
        deadline = time.monotonic() + 10
        path = os.path.join(str(tmp_path), "host_00000.jsonl")
        while time.monotonic() < deadline:
            if os.path.exists(path) and len(_read_jsonl(path)) >= 16:
                break
            time.sleep(0.02)
        # >= two capacity batches hit disk WITHOUT any explicit flush
        assert len(_read_jsonl(path)) >= 16
        rec.close()
        assert len([r for r in _read_jsonl(path)
                    if r["kind"] == "step"]) == 20
        assert rec.dropped_records == 0

    def test_kill_switch_and_flag(self, tmp_path, monkeypatch):
        cfg = TrainConfig(checkpoint_dir=str(tmp_path))
        monkeypatch.setenv("FDT_TELEMETRY", "0")
        assert build_telemetry(cfg) is None
        monkeypatch.delenv("FDT_TELEMETRY")
        assert build_telemetry(cfg.replace(telemetry=False)) is None
        tel = build_telemetry(cfg, log=lambda *_: None)
        assert tel is not None
        assert tel.directory == os.path.abspath(
            os.path.join(str(tmp_path), "telemetry"))
        tel.close()


class TestSpans:
    def test_span_records_to_active_recorder(self, tmp_path):
        rec = TelemetryRecorder(str(tmp_path), process_index=0,
                                process_count=1, log=lambda *_: None)
        prev = spans.set_recorder(rec)
        try:
            with spans.span("restore", step=7):
                time.sleep(0.01)
            with pytest.raises(RuntimeError):
                with spans.span("rendezvous"):
                    raise RuntimeError("mid-span failure")
        finally:
            spans.set_recorder(prev)
        rec.close()
        recs = [r for r in _read_jsonl(rec.path) if r["kind"] == "span"]
        names = [r["name"] for r in recs]
        assert names == ["restore", "rendezvous"]
        assert recs[0]["dur_ms"] >= 10.0 and recs[0]["step"] == 7
        # the failed span still recorded its cost (that time IS the
        # MTTR restore component)
        assert recs[1]["dur_ms"] >= 0.0

    def test_span_without_recorder_is_noop(self):
        assert spans.get_recorder() is None
        with spans.span("eval"):
            pass  # no recorder installed: must not raise or record


class TestPercentiles:
    def test_nearest_rank(self):
        vals = list(range(1, 101))
        assert percentiles(vals) == {50: 50.0, 95: 95.0, 99: 99.0}
        assert percentiles([7.0], qs=(50, 99)) == {50: 7.0, 99: 7.0}
        assert percentiles([]) == {}


class TestPodAggregation:
    def _simulated_pod(self, d, slow_host=1, factor=3.0, steps=20):
        """Two recorders sharing one directory = a simulated 2-host
        pod (the r9/r10 seam); host `slow_host` dispatches `factor`x
        slower.  Records carry injected times — the aggregation math is
        the unit under test, not the clock."""
        for pi in (0, 1):
            rec = TelemetryRecorder(d, process_index=pi, process_count=2,
                                    log=lambda *_: None)
            base = 10.0 * (factor if pi == slow_host else 1.0)
            rec.record_step(1, 0, 1, 1, 500.0, 500.0, 64, compile_=True)
            for i in range(2, steps + 2):
                rec.record_step(i, 0, i, 1, base + 1.0, base, 64)
            rec.flush(wait=True)
            publish_epoch_marker(d, 0, pi)
            rec.close()

    def test_straggler_flagged_and_compile_excluded(self, tmp_path):
        d = str(tmp_path)
        self._simulated_pod(d)
        summary = aggregate_run(d, straggler_ratio=2.0)
        assert summary["host_count"] == 2
        # compile records never pollute the percentiles: host 0's p99
        # would be 500 if they did
        assert summary["hosts"]["0"]["step_ms_p99"] == 10.0
        assert summary["hosts"]["1"]["step_ms_p95"] == 30.0
        # 2-host pods use the LOW median so the slow half is flaggable
        assert summary["pod_median_host_p95_ms"] == 10.0
        assert [s["host"] for s in summary["stragglers"]] == [1]
        assert summary["stragglers"][0]["ratio"] == 3.0

    def test_epoch_fold_logs_and_writes_summary(self, tmp_path):
        d = str(tmp_path)
        self._simulated_pod(d)
        lines = []
        out = pod_epoch_aggregate(d, 0, pi=0, pc=2, straggler_ratio=2.0,
                                  log=lines.append, wait_s=0.0)
        assert out["epoch"] == 0 and out["hosts_reported"] == [0, 1]
        text = "\n".join(lines)
        assert "[telemetry] epoch 0: pod step p50=" in text
        assert "straggler: host 1" in text
        disk = json.load(open(os.path.join(d, "pod_summary.json")))
        assert disk["stragglers"][0]["host"] == 1
        # non-zero hosts never aggregate (their job was flush + marker)
        assert pod_epoch_aggregate(d, 0, pi=1, pc=2) is None

    def test_fold_proceeds_without_missing_host(self, tmp_path):
        d = str(tmp_path)
        rec = TelemetryRecorder(d, process_index=0, process_count=2,
                                log=lambda *_: None)
        rec.record_step(1, 0, 1, 1, 10.0, 10.0, 64)
        rec.flush(wait=True)
        publish_epoch_marker(d, 0, 0)
        rec.close()
        lines = []
        out = pod_epoch_aggregate(d, 0, pi=0, pc=2, log=lines.append,
                                  wait_s=0.1)
        # a host that never flushed is reported, not waited on forever
        assert out["hosts_reported"] == [0]
        assert any("had not flushed" in ln for ln in lines)

    def test_no_straggler_on_uniform_pod(self, tmp_path):
        d = str(tmp_path)
        self._simulated_pod(d, factor=1.1)
        assert aggregate_run(d, straggler_ratio=2.0)["stragglers"] == []

    def test_runfold_incremental_matches_stateless(self, tmp_path):
        """RunFold (per-epoch tail parsing) and aggregate_run (whole
        directory) share one step-time definition and must produce the
        same summary — incrementality can't change the math."""
        from faster_distributed_training_tpu.telemetry import RunFold

        d = str(tmp_path)
        rec = TelemetryRecorder(d, process_index=0, process_count=1,
                                log=lambda *_: None)
        fold = RunFold(d)
        for i in range(1, 11):
            rec.record_step(i, 0, i, 2, 20.0 + i, 20.0 + i, 64)
        rec.flush(wait=True)
        first = fold.summary()           # consumes the first tail
        for i in range(11, 21):
            rec.record_step(i, 1, i, 2, 40.0 + i, 40.0 + i, 64)
        rec.flush(wait=True)
        second = fold.summary()          # parses ONLY the new tail
        rec.close()
        assert first["pod"]["steps"] == 20      # 10 records x k=2
        assert second == aggregate_run(d)
        assert second["pod"]["steps"] == 40

    def test_runfold_resets_on_truncated_file(self, tmp_path):
        """A host file that SHRANK (a relaunch replaced it) resets that
        host's fold instead of seeking past the end forever."""
        from faster_distributed_training_tpu.telemetry import RunFold

        d = str(tmp_path)
        rec = TelemetryRecorder(d, process_index=0, process_count=1,
                                log=lambda *_: None)
        for i in range(1, 6):
            rec.record_step(i, 0, i, 1, 10.0, 10.0, 8)
        rec.flush(wait=True)
        fold = RunFold(d)
        assert fold.summary()["pod"]["steps"] == 5
        rec.close()
        os.remove(rec.path)
        rec2 = TelemetryRecorder(d, process_index=0, process_count=1,
                                 log=lambda *_: None)
        rec2.record_step(1, 0, 1, 1, 30.0, 30.0, 8)
        rec2.flush(wait=True)
        rec2.close()
        s = fold.summary()
        assert s["pod"]["steps"] == 1
        assert s["hosts"]["0"]["step_ms_p50"] == 30.0

    def test_stale_markers_from_previous_run_ignored(self, tmp_path):
        """Time-scoping (the r10 EXIT-marker idiom): an epoch marker
        older than this run's telemetry is a reused directory's residue
        and must not satisfy the aggregation barrier."""
        d = str(tmp_path)
        self._simulated_pod(d)            # both hosts' epoch-0 markers
        lines = []
        out = pod_epoch_aggregate(d, 0, pi=0, pc=2, log=lines.append,
                                  wait_s=0.1,
                                  newer_than=time.time() + 60.0)
        assert out["hosts_reported"] == []
        assert any("had not flushed" in ln for ln in lines)
        # markers newer than the scope are honored
        out = pod_epoch_aggregate(d, 0, pi=0, pc=2, wait_s=0.1,
                                  log=lambda *_: None,
                                  newer_than=time.time() - 60.0)
        assert out["hosts_reported"] == [0, 1]


class TestStepWindowProfiler:
    def _fake(self):
        calls = []
        return (calls, lambda d: calls.append(("start", d)),
                lambda: calls.append(("stop",)))

    def test_window_covers_requested_steps_k1(self):
        calls, start, stop = self._fake()
        p = StepWindowProfiler("/tmp/t", 3, 5, start_fn=start,
                               stop_fn=stop, log=lambda *_: None)
        for s in range(8):           # dispatches run step s+1
            p.before_dispatch(s, 1)
            p.after_dispatch(s + 1)
        assert calls == [("start", "/tmp/t"), ("stop",)]
        # started before step 3 ran, stopped once step 5 completed
        assert p.started_at == 2 and p.stopped_at == 5

    def test_window_quantizes_to_dispatch_boundaries(self):
        calls, start, stop = self._fake()
        p = StepWindowProfiler("/tmp/t", 3, 5, start_fn=start,
                               stop_fn=stop, log=lambda *_: None)
        fenced = []
        for s in range(0, 8, 2):     # K=2 dispatches
            p.before_dispatch(s, 2)
            p.after_dispatch(s + 2, fence=lambda: fenced.append(True))
        # the dispatch covering step 3 is steps 3-4 (starts at 2);
        # the stop lands after the dispatch that completes step 5 (6)
        assert p.started_at == 2 and p.stopped_at == 6
        assert fenced == [True]      # fence ran exactly at the stop
        assert calls == [("start", "/tmp/t"), ("stop",)]

    def test_resume_past_window_never_starts(self):
        calls, start, stop = self._fake()
        p = StepWindowProfiler("/tmp/t", 3, 5, start_fn=start,
                               stop_fn=stop, log=lambda *_: None)
        p.before_dispatch(10, 1)     # resumed past B
        p.after_dispatch(11)
        p.close()
        assert calls == [] and p.done

    def test_run_ending_early_still_captures(self):
        calls, start, stop = self._fake()
        p = StepWindowProfiler("/tmp/t", 2, 100, start_fn=start,
                               stop_fn=stop, log=lambda *_: None)
        p.before_dispatch(1, 1)
        p.after_dispatch(2)
        p.close()                    # run ended before step 100
        assert calls == [("start", "/tmp/t"), ("stop",)]

    def test_parse_profile_steps(self):
        assert parse_profile_steps("") is None
        assert parse_profile_steps("3:5") == (3, 5)
        assert parse_profile_steps("7:7") == (7, 7)
        for bad in ("5", "0:3", "5:3", "a:b", "3:"):
            with pytest.raises(ValueError):
                parse_profile_steps(bad)


def _tiny_cfg(tmp_path, epochs=2, **kw):
    return TrainConfig(model="transformer", dataset="synthetic",
                       num_classes=4, batch_size=8, seq_len=16, n_layers=1,
                       d_model=16, d_ff=32, n_heads=2, epochs=epochs,
                       subset_stride=64, optimizer="sgd", precision="fp32",
                       plot=False, workers=0, log_every=0, donate=False,
                       checkpoint_dir=str(tmp_path), **kw)


class TestEndToEnd:
    def test_run_emits_valid_stream_matching_summary(self, tmp_path):
        """The r12 acceptance pin: a CPU run with telemetry enabled
        emits a valid manifest + per-dispatch JSONL whose step count and
        loss match the epoch summary, with the checkpoint/eval/compile
        seams visible as spans."""
        from faster_distributed_training_tpu.cli import run_training

        cfg = _tiny_cfg(tmp_path, checkpoint_every=4)
        out = run_training(cfg, log=lambda *_: None)
        td = out["telemetry_dir"]
        man = json.load(open(os.path.join(td, "manifest.json")))
        assert man["workload"] == "transformer"
        assert man["config"]["batch_size"] == 8
        assert man["steps_per_epoch"] == 8
        recs = _read_jsonl(os.path.join(td, "host_00000.jsonl"))
        epochs = [r for r in recs if r["kind"] == "epoch"]
        assert [e["epoch"] for e in epochs] == [0, 1]
        for e in epochs:
            step_recs = [r for r in recs if r["kind"] == "step"
                         and r["epoch"] == e["epoch"]]
            # step count matches the epoch summary exactly
            assert sum(r["k"] for r in step_recs) == e["trained_steps"] == 8
            # the epoch event's loss IS the epoch summary's loss
            assert e["loss"] == out["history"]["train_loss"][e["epoch"]]
            assert e["eval_accuracy"] == out["history"]["test_acc"][
                e["epoch"]]
        names = {r["name"] for r in recs if r["kind"] == "span"}
        # instrumented seams: compile, eval, checkpoint snapshot+commit
        # (checkpoint_every=4 fired mid-epoch on the async path)
        assert {"first_dispatch_compile", "eval", "ckpt_snapshot",
                "ckpt_commit"} <= names, names
        # goodput rides the same stream (one snapshot per epoch)
        goodputs = [r for r in recs if r["kind"] == "goodput"]
        assert len(goodputs) == 2 and goodputs[-1]["saves"] >= 1
        # compile marked exactly once for the single (host, 1) program
        assert sum(1 for r in recs
                   if r["kind"] == "step" and r.get("compile")) == 1

    @pytest.mark.slow  # r20 budget diet: 38 s — operator tooling, not
    # a correctness contract; the window boundary arithmetic stays
    # tier-1 via the profile-window unit tests above
    def test_profile_steps_window_produces_trace(self, tmp_path):
        """--profile_steps A:B produces a trace directory covering only
        the requested window (start/stop observed via the log; the real
        jax.profiler runs and leaves trace files behind)."""
        from faster_distributed_training_tpu.cli import run_training

        lines = []
        cfg = _tiny_cfg(tmp_path, epochs=1, profile_steps="3:5")
        out = run_training(cfg, log=lines.append)
        trace_dir = os.path.join(out["telemetry_dir"], "trace_steps_3_5")
        assert os.path.isdir(trace_dir)
        assert glob.glob(os.path.join(trace_dir, "**", "*"),
                         recursive=True), "trace directory is empty"
        text = "\n".join(lines)
        assert "trace started before step 3" in text
        assert "trace stopped after step 5" in text

    def test_no_telemetry_runs_clean(self, tmp_path, monkeypatch):
        from faster_distributed_training_tpu.cli import run_training

        monkeypatch.setenv("FDT_TELEMETRY", "0")
        out = run_training(_tiny_cfg(tmp_path, epochs=1),
                           log=lambda *_: None)
        assert "telemetry_dir" not in out
        assert not os.path.exists(os.path.join(str(tmp_path), "telemetry"))


class TestLiveThroughputFix:
    def test_log_dispatch_subtracts_blocked_time(self):
        """The r12 satellite pin: the live ex/s line reports STEP
        throughput — checkpoint-blocking/hook seconds measured since the
        last line are subtracted from the wall window (a save landing
        mid-window used to read as a throughput dip)."""
        from faster_distributed_training_tpu.train.loop import Trainer

        lines = []
        cfg = TrainConfig(model="transformer", batch_size=100,
                          log_every=10, donate=False)
        tr = Trainer(cfg, log=lines.append)
        metrics = {"loss": np.float32(1.0)}
        t_now = time.monotonic()
        # a 2 s window, 1 s of which was a blocking checkpoint
        tr._blocked_since_log = 1.0
        tr._log_dispatch(0, 10, 1, metrics, (t_now - 2.0, 0))
        assert len(lines) == 1, lines
        exs = float(lines[0].split(" ex/s")[0].split()[-1])
        # 10 steps x 100 ex over (2.0 - 1.0) s ~= 1000 ex/s; the raw
        # wall number (the old bug) would be ~500
        assert 900 <= exs <= 1100, lines[0]
        assert "(+1.00s blocked)" in lines[0]
        assert tr._blocked_since_log == 0.0   # window accounting reset
        # K=1 lines carry no fused suffix (unchanged r8 format)
        assert "fused" not in lines[0]

    def test_log_dispatch_without_blocking_unchanged(self):
        from faster_distributed_training_tpu.train.loop import Trainer

        lines = []
        cfg = TrainConfig(model="transformer", batch_size=64,
                          log_every=4, donate=False)
        tr = Trainer(cfg, log=lines.append)
        metrics = {"loss": np.float32(2.0)}
        tr._log_dispatch(1, 8, 4, metrics, (time.monotonic() - 1.0, 4))
        assert len(lines) == 1
        assert "blocked" not in lines[0]
        assert "(K=4 fused)" in lines[0]
        # no emission when the dispatch didn't cross a boundary:
        # `last` is returned untouched
        last = (time.monotonic(), 8)
        assert tr._log_dispatch(1, 10, 2, metrics, last) == last
        assert len(lines) == 1


class TestReportScript:
    def _mod(self):
        spec = importlib.util.spec_from_file_location(
            "telemetry_report",
            os.path.join(ROOT, "scripts", "telemetry_report.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_report_against_recorded_fixture(self):
        """Tier-1 smoke against the committed fixture: percentiles,
        straggler table, span breakdown, throughput curve — pinned
        values, so a schema change that breaks consumers fails here."""
        mod = self._mod()
        rep = mod.run(FIXTURE)
        s = rep["summary"]
        assert s["hosts"]["0"]["step_ms_p50"] == 102.0
        assert s["hosts"]["1"]["step_ms_p95"] == 304.0
        assert s["pod"]["steps"] == 46          # compile records excluded
        assert [x["host"] for x in s["stragglers"]] == [1]
        assert rep["manifest"]["workload"] == "resnet"
        assert {"eval", "ckpt_snapshot", "ckpt_commit"} <= set(rep["spans"])
        assert [e["epoch"] for e in rep["throughput_curve"]] == [0, 1]
        assert rep["throughput_curve"][1]["eval_accuracy"] == 0.65
        assert rep["goodput"]["goodput_pct"] == 96.0
        text = mod.render(rep)
        assert "straggler" in text and "host 1" in text
        assert "span breakdown" in text

    def test_report_cli_main(self, capsys):
        mod = self._mod()
        rep = mod.main([FIXTURE, "--straggler_ratio", "2.0"])
        assert rep["summary"]["stragglers"]
        assert "stragglers" in capsys.readouterr().out

    def test_fixture_helpers_roundtrip(self):
        hosts = read_host_records(FIXTURE)
        assert set(hosts) == {0, 1}
        bd = span_breakdown(hosts[0] + hosts[1])
        assert bd["eval"]["count"] == 4
        assert bd["ckpt_commit"]["total_ms"] == 360.0

    def test_render_orders_hosts_numerically(self):
        """Host rows sort by host INDEX, not by the stringified key —
        host 10 must render after host 2 on big pods."""
        mod = self._mod()
        summary = {"hosts": {str(pi): {"step_ms_p50": 1.0,
                                       "step_ms_p95": 1.0,
                                       "step_ms_p99": 1.0, "steps": 4}
                             for pi in (0, 2, 10)},
                   "host_count": 3, "straggler_ratio": 2.0,
                   "stragglers": [],
                   "pod": {"step_ms_p50": 1.0, "step_ms_p95": 1.0,
                           "step_ms_p99": 1.0, "steps": 12}}
        text = mod.render({"directory": "/tmp/x", "summary": summary})
        rows = [ln for ln in text.splitlines() if "host " in ln]
        assert [r.split()[1] for r in rows] == ["0", "2", "10"]


class TestStepSampling:
    """--telemetry_every N (r13 satellite): the r12 note names
    per-dispatch time.monotonic pressure under async dispatch as the
    first suspect if telemetry_overhead_pct ever fails on live TPU —
    sampling every Nth dispatch is the landed mitigation.  Sampling
    drops whole records (surviving ones keep their TRUE step numbers);
    compile-marked first dispatches are always kept."""

    def test_every_n_keeps_true_step_numbers(self, tmp_path):
        rec = TelemetryRecorder(str(tmp_path), process_index=0,
                                process_count=1, step_every=3,
                                log=lambda *_: None)
        rec.record_step(1, 0, 1, 1, 1.0, 1.0, 4, compile_=True)
        for i in range(2, 13):
            rec.record_step(i, 0, i, 1, 1.0, 1.0, 4)
        rec.record_event("epoch", epoch=0)   # events are never sampled
        rec.close()
        recs = _read_jsonl(os.path.join(str(tmp_path),
                                        "host_00000.jsonl"))
        steps = [r for r in recs if r["kind"] == "step"]
        assert steps[0]["step"] == 1 and steps[0].get("compile")
        # every 3rd dispatch thereafter, true global steps preserved
        assert [r["step"] for r in steps[1:]] == [3, 6, 9, 12]
        assert any(r["kind"] == "epoch" for r in recs)

    def test_compile_records_survive_sampling(self, tmp_path):
        rec = TelemetryRecorder(str(tmp_path), process_index=0,
                                process_count=1, step_every=100,
                                log=lambda *_: None)
        for i in range(1, 6):
            rec.record_step(i, 0, i, 1, 1.0, 1.0, 4, compile_=(i == 2))
        rec.close()
        steps = [r for r in _read_jsonl(os.path.join(
            str(tmp_path), "host_00000.jsonl")) if r["kind"] == "step"]
        # only the compile-marked dispatch survives a 1-in-100 rate
        assert [r["step"] for r in steps] == [2]
        assert steps[0]["compile"] is True

    def test_build_telemetry_wires_the_flag(self, tmp_path):
        cfg = TrainConfig(checkpoint_dir=str(tmp_path),
                          telemetry_every=4)
        tel = build_telemetry(cfg, log=lambda *_: None)
        assert tel.recorder.step_every == 4
        tel.close()

    def test_default_records_every_dispatch(self, tmp_path):
        rec = TelemetryRecorder(str(tmp_path), process_index=0,
                                process_count=1, log=lambda *_: None)
        for i in range(1, 6):
            rec.record_step(i, 0, i, 1, 1.0, 1.0, 4)
        rec.close()
        steps = [r for r in _read_jsonl(os.path.join(
            str(tmp_path), "host_00000.jsonl")) if r["kind"] == "step"]
        assert [r["step"] for r in steps] == [1, 2, 3, 4, 5]

    def test_next_step_kept_predicts_record_decisions(self, tmp_path):
        """The Trainer consults next_step_kept BEFORE a dispatch to
        skip the telemetry-only clock reads (review pass: sampling at
        the recorder layer alone would keep 100% of the monotonic
        pressure) — the prediction must agree exactly with what
        record_step then keeps."""
        rec = TelemetryRecorder(str(tmp_path), process_index=0,
                                process_count=1, step_every=3,
                                log=lambda *_: None)
        preds = []
        for i in range(1, 10):
            preds.append(rec.next_step_kept())
            rec.record_step(i, 0, i, 1, 1.0, 1.0, 4)
        rec.close()
        steps = [r["step"] for r in _read_jsonl(os.path.join(
            str(tmp_path), "host_00000.jsonl")) if r["kind"] == "step"]
        assert steps == [i for i, p in zip(range(1, 10), preds) if p]
